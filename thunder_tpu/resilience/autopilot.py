"""Fleet autopilot: the policy engine that picks WHICH recovery to apply.

PRs 6-10 built every fault-tolerance actuator — executor demotion, the
compile de-opt ladder, the collective watchdog, elastic resharded resume,
SDC quarantine+rerun, checkpoint-and-halt — but each fires in isolation
under a hand-written test. In production the faults arrive mixed and
concurrent: a host flaps, a collective hangs during the elastic resume the
flap triggered, an SDC rerun is interrupted by a preemption. This module is
the control plane that sits between the *signal streams* and the
*actuators* (ISSUE 11):

Signals (normalized into :class:`Signal`):

- ``CollectiveTimeoutError`` verdicts with suspect-host naming (watchdog);
- ``sdc_suspect`` divergences and persistent :class:`SDCDetectedError`;
- ``HostLost`` / ``Preempted`` step-boundary faults (preemption);
- OOM / compile-failure escalations (the de-opt ladder consults the
  installed autopilot before climbing);
- ``analysis/events.host_health`` spread-ratio summaries
  (:meth:`Autopilot.note_host_health`) — a host the observatory already
  flagged as a straggler skips the gentle same-mesh retry when it later
  hangs a collective.

Actuators (the four policy classes; ``DECISION_RECOVERY_KINDS`` in
``analysis/events.py`` names each one's recovery event):

===================  ========================================================
``elastic_resume``   checkpoint restore via :func:`~.elastic.elastic_resume`
                     — ``mode`` is ``same_mesh`` (re-dispatch from the last
                     checkpoint), ``shrink`` (halve an axis, continue on the
                     survivors), or ``regrow`` (replacement capacity came
                     back: reshard up to the full mesh)
``quarantine_rerun`` the SDC guard's quarantine + re-run of a divergent step
``deopt_escalate``   the compile de-opt ladder climbs/jumps a level
``checkpoint_halt``  save a durable checkpoint and stop — the next process
                     (scheduler allocation) resumes
``shrink_dp``        fleet-level (ISSUE 18): a slice died — shrink the
                     data-parallel group to the survivors and rescale
                     gradient accumulation loss-equivalently
                     (``resilience/federation.py`` applies it)
``regrow_dp``        fleet-level: a cooled-down slice cleared the rejoin
                     hysteresis — reshard back to full DP width
===================  ========================================================

Every decision is emitted as a typed ``autopilot_decision`` event carrying
the triggering evidence (signal kind, step, suspect host, hysteresis rung,
fires-in-window) and must be followed by its actuator's recovery event —
the replay correlation rule ``events.unactuated-decision`` enforces it,
exactly like ``events.unrecovered-fault`` does for injections.

**Hysteresis.** Each signal kind has a policy ladder: repeated signals of
the same kind (keyed by suspect host, so two different flapping hosts don't
share a strike count) within ``window_s`` climb the ladder — e.g. a first
collective hang retries on the same mesh, a second within the window
shrinks the mesh away from the suspect, a third halts. Outside the window
the count decays back to the first rung. ``backoff_s`` spaces actuator
applications so a flapping host cannot thrash resume loops.

**Serialization.** Recoveries apply one at a time: actuator applications
run inside :meth:`Autopilot.recovery`, a reentrant-per-thread critical
section (a recovery that *causes* another fault handles it as one nested
chain; a concurrent thread's recovery waits). The recorded
``recovery_intervals`` let tests assert no two actuators overlapped.

Driver: :func:`run_autopiloted_training` wraps
:func:`~.preemption.run_training` in the decide→apply loop; the soak
harness (``scripts/soak_fleet.py``) runs it for hundreds of steps under a
seeded mixed-fault schedule and commits the resulting **goodput** number.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from thunder_tpu.observability import events as obs_events
from thunder_tpu.observability import metrics as obsm

ACTUATORS = (
    "elastic_resume", "quarantine_rerun", "deopt_escalate", "checkpoint_halt",
    # Fleet actuators (ISSUE 18): shrink the data-parallel group away from a
    # lost slice / regrow it when the slice rejoins after hysteresis. Both
    # actuate as the elastic resume that re-enters training at the new DP
    # width (DECISION_RECOVERY_KINDS), applied by the federation driver.
    "shrink_dp", "regrow_dp",
)

# Signal kinds the default policy table covers. Unknown kinds fall through
# to checkpoint_halt: an unclassified fault must degrade to the safest
# actuator (durable state, loud stop), never be silently retried.
SIGNAL_KINDS = (
    "host_loss", "collective_hang", "sdc_suspect", "sdc_persistent",
    "oom", "compile_fail", "preempt", "host_unhealthy",
    "slice_loss", "slice_recovered",
)


class AutopilotHalt(RuntimeError):
    """The autopilot chose ``checkpoint_halt``: a durable checkpoint exists
    and this process should exit; the next allocation resumes from it."""

    def __init__(self, step: int, reason: str, decision=None):
        self.step = step
        self.reason = reason
        self.decision = decision
        self.report: Optional["AutopilotReport"] = None  # attached by the driver
        super().__init__(
            f"autopilot halt at step {step}: {reason} — checkpoint is "
            f"durable; resume in a fresh process"
        )


@dataclass
class Signal:
    """One normalized fault/health observation the policy engine decides on.
    ``suspect_host`` keys the hysteresis history (per-host strike counts);
    ``evidence`` is free-form and lands verbatim in the decision event."""

    kind: str
    step: Optional[int] = None
    suspect_host: Optional[Any] = None
    evidence: dict = field(default_factory=dict)


@dataclass
class Policy:
    """Hysteresis ladder for one signal kind: the Nth signal within
    ``window_s`` (keyed by suspect host) applies ``ladder[min(N-1, last)]``.
    ``backoff_s`` is the base anti-thrash delay before applying the
    actuator, doubled per rung."""

    signal: str
    ladder: tuple  # of (actuator, mode-or-None)
    window_s: float = 300.0
    backoff_s: float = 0.0


def default_policies() -> dict[str, Policy]:
    """The committed policy table (docs/robustness.md "fleet autopilot")."""
    return {p.signal: p for p in (
        # A dead host never comes back by retrying: shrink immediately;
        # two losses inside the window and the third halts (the mesh is
        # evaporating faster than it can reshard).
        Policy("host_loss",
               (("elastic_resume", "shrink"), ("elastic_resume", "shrink"),
                ("checkpoint_halt", None)),
               window_s=600.0),
        # A hang may be transient (ICI hiccup): first retry the same mesh
        # from the last checkpoint; a repeat within the window means the
        # suspect is flapping — shrink away from it; a third halts.
        Policy("collective_hang",
               (("elastic_resume", "same_mesh"), ("elastic_resume", "shrink"),
                ("checkpoint_halt", None)),
               window_s=120.0),
        # Transient bit-flips are the SDC guard's job (it bounds its own
        # reruns); the decision records that the quarantine path was chosen.
        Policy("sdc_suspect", (("quarantine_rerun", None),), window_s=60.0),
        # Corruption that survived the rerun budget is a bad device, not a
        # cosmic ray: shrink away from it, halt if it persists.
        Policy("sdc_persistent",
               (("elastic_resume", "shrink"), ("checkpoint_halt", None)),
               window_s=600.0),
        # Memory/compile pressure de-opts in place — the ladder itself is
        # bounded (THUNDER_TPU_MAX_RECOVERY_ATTEMPTS), so no escalation
        # rung is needed here.
        Policy("oom", (("deopt_escalate", None),), window_s=60.0),
        Policy("compile_fail", (("deopt_escalate", None),), window_s=60.0),
        # Preemption is an order, not a fault: save and stop.
        Policy("preempt", (("checkpoint_halt", None),), window_s=60.0),
        # A dead SLICE (ISSUE 18) shrinks the DP group and keeps training on
        # the survivors; two losses inside the window still shrink (the
        # fleet has width to give), the third halts — slices are evaporating
        # faster than the fleet can rescale. Keyed on the slice id (the
        # signal's suspect_host), so two different flapping slices don't
        # share a strike count.
        Policy("slice_loss",
               (("shrink_dp", None), ("shrink_dp", None),
                ("checkpoint_halt", None)),
               window_s=600.0),
    )}


@dataclass
class Decision:
    """One policy-engine verdict, mirrored into an ``autopilot_decision``
    event. ``rung``/``fires_in_window`` expose the hysteresis state that
    produced it; the correlation rule pairs it with the actuator's recovery
    event (``DECISION_RECOVERY_KINDS``)."""

    id: int
    signal: Signal
    actuator: str
    mode: Optional[str] = None
    rung: int = 0
    fires_in_window: int = 0
    window_s: float = 0.0
    backoff_s: float = 0.0


class Autopilot:
    """The policy engine. One instance drives one training job; install it
    (:meth:`installed` / :func:`install`) so the seams that cannot take a
    parameter — the de-opt ladder inside the dispatcher, the SDC guard
    inside ``run_training`` — find it via :func:`current`.

    ``clock`` is injectable for deterministic hysteresis tests;
    ``spread_threshold``/``health_strikes`` govern when host-health
    summaries mark a host as a known straggler (which skips the gentle
    same-mesh rung on its next collective hang)."""

    def __init__(self, policies: Optional[dict] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 spread_threshold: float = 1.5, health_strikes: int = 2):
        self.policies = dict(policies) if policies is not None else default_policies()
        self._clock = clock
        self.spread_threshold = float(spread_threshold)
        self.health_strikes = int(health_strikes)
        self.decisions: list[Decision] = []
        self.recovery_intervals: list[tuple[float, float, int]] = []
        self._fires: dict = {}           # (kind, suspect) -> [ts, ...]
        self._health_strikes: dict = {}  # host -> consecutive flags
        self._flagged: set = set()       # hosts past the strike budget
        # Streaming-detector anomalies (observability/detect.py), newest
        # last; decide() cites the relevant one in its evidence so the soak
        # can measure detection lead time (anomaly ts -> decision ts).
        # Anomaly-earned straggler strikes live in their OWN time-windowed
        # ledger (timestamps, pruned on read) — unlike the health ledger,
        # no host_health summary ever runs to clear them, so they must
        # decay on their own or a transient slowdown would flag a host for
        # the rest of a week-long run.
        self._anomalies: deque = deque(maxlen=64)
        self._anomaly_strikes: dict = {}  # host -> [anomaly ts, ...]
        self.anomaly_cite_window_s = 300.0
        self.anomaly_strike_window_s = 600.0
        self._state_lock = threading.Lock()
        self._serial = threading.RLock()
        self._owner: Optional[int] = None
        self._depth = 0
        self._serialized_waits = 0
        self._active_decision_id: Optional[int] = None

    # -- signal intake --------------------------------------------------------

    def note_host_health(self, summary: Optional[dict]) -> None:
        """Consume a ``host_health`` summary (spread ratio + stragglers).
        A host flagged in ``health_strikes`` consecutive summaries becomes a
        known straggler: its next ``collective_hang`` decision starts one
        rung up the ladder (no same-mesh retry for a host the observatory
        already measured slow)."""
        if not summary:
            return
        with self._state_lock:
            stragglers = set(summary.get("stragglers") or ())
            for host in stragglers:
                n = self._health_strikes.get(host, 0) + 1
                self._health_strikes[host] = n
                if n >= self.health_strikes:
                    self._flagged.add(host)
            for host in list(self._health_strikes):
                if host not in stragglers:
                    self._health_strikes.pop(host, None)
                    self._flagged.discard(host)

    def _anomaly_flagged(self, now: Optional[float] = None) -> set:
        """Hosts with >= health_strikes warn+ anomalies inside the strike
        window. Pruned on read: anomaly flags DECAY — a host that stopped
        drifting earns its gentle same-mesh rung back. Called under
        _state_lock."""
        now = time.time() if now is None else now
        flagged = set()
        for host, ts in list(self._anomaly_strikes.items()):
            ts[:] = [t for t in ts if now - t <= self.anomaly_strike_window_s]
            if not ts:
                del self._anomaly_strikes[host]
            elif len(ts) >= self.health_strikes:
                flagged.add(host)
        return flagged

    def flagged_stragglers(self) -> set:
        with self._state_lock:
            return set(self._flagged) | self._anomaly_flagged()

    def note_anomaly(self, anomaly: Optional[dict]) -> None:
        """Consume one streaming-detector anomaly (ISSUE 15;
        ``observability/detect.DetectorBank`` routes every verdict here
        when an autopilot is installed). The anomaly joins the evidence
        ring that :meth:`decide` cites, and a warn+ anomaly naming a
        suspect host is a straggler strike: ``health_strikes`` of them
        inside ``anomaly_strike_window_s`` flag the host exactly like
        consecutive host_health summaries would — it loses the gentle
        same-mesh rung on its next hang BEFORE a watchdog timeout ever
        names it. The anomaly ledger is separate from the health one
        (health summaries clear on recovery; anomaly strikes decay by
        time) so the two feeders cannot erase each other's evidence."""
        if not anomaly:
            return
        rec = dict(anomaly)
        rec.setdefault("ts", time.time())
        with self._state_lock:
            self._anomalies.append(rec)
            host = rec.get("suspect_host")
            if host is not None and rec.get("severity") in ("warn", "critical"):
                self._anomaly_strikes.setdefault(host, []).append(
                    float(rec["ts"]))

    # Which anomaly kinds are evidence for which signal kinds: a slow/
    # drifting step backs the hang/loss ladders; a recompile storm backs
    # the compile-pressure ladder.
    _ANOMALY_RELEVANCE = {
        "collective_hang": ("step_time_drift", "goodput_drop", "host_spread",
                            "bottleneck_shift"),
        "host_loss": ("step_time_drift", "goodput_drop", "host_spread",
                      "bottleneck_shift"),
        "host_unhealthy": ("step_time_drift", "goodput_drop", "host_spread",
                           "bottleneck_shift"),
        "oom": ("recompile_storm",),
        "compile_fail": ("recompile_storm",),
        # A DCN-tier spread verdict is evidence for the slice ladder: the
        # slow slice was already a named suspect before it died (ISSUE 18);
        # so is the fleet timeline's bottleneck_shift — the critical path
        # had already moved onto straggler-wait / exposed DCN (ISSUE 20).
        "slice_loss": ("slice_spread", "goodput_drop", "bottleneck_shift"),
    }

    def _cite_anomaly(self, signal: Signal) -> Optional[dict]:
        """The newest relevant anomaly within the citation window (wall
        clock — anomaly timestamps come from the detectors' ``time.time``),
        host-matched when both sides name one. Called under _state_lock."""
        kinds = self._ANOMALY_RELEVANCE.get(signal.kind)
        if not kinds:
            return None
        now = time.time()
        for rec in reversed(self._anomalies):
            if rec.get("anomaly") not in kinds:
                continue
            if now - float(rec.get("ts") or 0.0) > self.anomaly_cite_window_s:
                continue
            a_host = rec.get("suspect_host")
            if (signal.suspect_host is not None and a_host is not None
                    and signal.suspect_host != a_host):
                continue
            return {
                "anomaly": rec.get("anomaly"),
                "severity": rec.get("severity"),
                "ts": rec.get("ts"),
                "value": rec.get("value"),
                "baseline": rec.get("baseline"),
                "suspect_host": a_host,
            }
        return None

    def signal_from_exception(self, exc: BaseException) -> Signal:
        """Normalize a fault exception raised out of the training loop."""
        from thunder_tpu.resilience.preemption import HostLost, Preempted
        from thunder_tpu.resilience.watchdog import (
            CollectiveTimeoutError,
            SDCDetectedError,
        )

        if isinstance(exc, HostLost):
            return Signal("host_loss", step=exc.step,
                          evidence={"path": exc.path})
        if isinstance(exc, Preempted):
            return Signal("preempt", step=exc.step,
                          evidence={"path": exc.path})
        if isinstance(exc, CollectiveTimeoutError):
            return Signal("collective_hang", suspect_host=exc.suspected_host,
                          evidence={"fn": exc.fn_name,
                                    "timeout_s": exc.timeout_s,
                                    "lines": list(exc.trace_lines)})
        if isinstance(exc, SDCDetectedError):
            return Signal("sdc_persistent", step=exc.step,
                          evidence={"leaves": list(exc.leaves)})
        return Signal(type(exc).__name__, evidence={"error": str(exc)})

    # -- the decision ---------------------------------------------------------

    def decide(self, signal: Signal) -> Decision:
        """Pick the actuator for ``signal`` per the policy table and the
        hysteresis state, record the firing, and emit the
        ``autopilot_decision`` event. Pure bookkeeping — the caller applies
        the actuator (inside :meth:`recovery`)."""
        with self._state_lock:
            policy = self.policies.get(signal.kind)
            if policy is None:
                # Unknown signal: the safe actuator, single-rung.
                policy = Policy(signal.kind, (("checkpoint_halt", None),))
            now = self._clock()
            key = (signal.kind, signal.suspect_host)
            hist = self._fires.setdefault(key, [])
            hist[:] = [t for t in hist if now - t <= policy.window_s]
            rung = min(len(hist), len(policy.ladder) - 1)
            if (signal.kind == "collective_hang"
                    and (signal.suspect_host in self._flagged
                         or signal.suspect_host in self._anomaly_flagged())
                    and rung == 0 and len(policy.ladder) > 1):
                # The observatory already measured this host slow: skip the
                # same-mesh retry rung, go straight to shrinking away.
                rung = 1
            hist.append(now)
            actuator, mode = policy.ladder[rung]
            # Cite the streaming-detector evidence (ISSUE 15): a decision
            # whose fault the detectors saw coming carries the anomaly in
            # its evidence — the soak's detection-lead-time join keys on
            # exactly this (decision ts − cited anomaly ts).
            cited = self._cite_anomaly(signal)
            if cited is not None:
                signal.evidence = dict(signal.evidence or {})
                signal.evidence["anomaly"] = cited
            decision = Decision(
                id=0, signal=signal, actuator=actuator,
                mode=mode, rung=rung, fires_in_window=len(hist),
                window_s=policy.window_s,
                backoff_s=policy.backoff_s * (2 ** rung) if policy.backoff_s else 0.0,
            )
        return self._record(decision)

    def _record(self, decision: Decision) -> Decision:
        """The one writer of decision records: id assignment, the
        ``autopilot_decision`` event, and the actuator metric — shared by
        :meth:`decide` and the non-fault regrow path so the event shape
        cannot diverge between producers."""
        with self._state_lock:
            decision.id = len(self.decisions) + 1
            self.decisions.append(decision)
        if obsm.enabled():
            obsm.AUTOPILOT_DECISIONS.inc(actuator=decision.actuator)
        extra = {"mode": decision.mode} if decision.mode else {}
        obs_events.emit_event(
            "autopilot_decision",
            decision_id=decision.id,
            signal=decision.signal.kind,
            actuator=decision.actuator,
            step=decision.signal.step,
            suspect_host=decision.signal.suspect_host,
            rung=decision.rung,
            fires_in_window=decision.fires_in_window,
            window_s=decision.window_s,
            evidence=decision.signal.evidence or None,
            **extra,
        )
        return decision

    # -- serialized application -----------------------------------------------

    @contextlib.contextmanager
    def recovery(self, decision: Decision):
        """Critical section for applying ``decision``'s actuator: one
        recovery at a time across threads (reentrant within one thread, so
        a recovery that triggers a nested fault handles it as one chain).
        Sleeps the decision's hysteresis backoff before yielding and records
        the (start, end, decision_id) interval for the serialization
        assertions."""
        me = threading.get_ident()
        if self._owner is not None and self._owner != me:
            with self._state_lock:
                self._serialized_waits += 1
        self._serial.acquire()
        try:
            self._owner = me
            self._depth += 1
            self._active_decision_id = decision.id
            if decision.backoff_s:
                time.sleep(decision.backoff_s)
            t0 = self._clock()
            try:
                yield
            finally:
                self.recovery_intervals.append((t0, self._clock(), decision.id))
        finally:
            self._depth -= 1
            if self._depth == 0:
                self._owner = None
                self._active_decision_id = None
            self._serial.release()

    def debug_state(self, last: int = 16) -> dict:
        """The ops-plane ``/debug/state`` view: live strike ladders, flagged
        stragglers, recent anomalies, and the last ``last`` decisions."""
        with self._state_lock:
            return {
                "strikes": {
                    f"{kind}@{host}": len(ts)
                    for (kind, host), ts in sorted(
                        self._fires.items(), key=lambda kv: str(kv[0]))
                    if ts
                },
                "flagged_stragglers": sorted(
                    set(self._flagged) | self._anomaly_flagged(), key=str),
                "anomalies": list(self._anomalies)[-last:],
                "decisions": [
                    {"id": d.id, "signal": d.signal.kind,
                     "actuator": d.actuator, "mode": d.mode, "rung": d.rung,
                     "suspect_host": d.signal.suspect_host}
                    for d in self.decisions[-last:]
                ],
                "serialized_waits": self._serialized_waits,
            }

    def stats(self) -> dict:
        """Decision/recovery accounting for reports and tests."""
        by_actuator: dict[str, int] = {}
        for d in self.decisions:
            by_actuator[d.actuator] = by_actuator.get(d.actuator, 0) + 1
        return {
            "decisions": len(self.decisions),
            "by_actuator": by_actuator,
            "recoveries": len(self.recovery_intervals),
            "serialized_waits": self._serialized_waits,
            "flagged_stragglers": sorted(self.flagged_stragglers(), key=str),
        }

    # -- installation ---------------------------------------------------------

    @contextlib.contextmanager
    def installed(self):
        """Make this the process's active autopilot within the scope — the
        de-opt ladder and the SDC guard consult :func:`current`."""
        tok = _current.set(self)
        try:
            yield self
        finally:
            _current.reset(tok)


_current: contextvars.ContextVar[Optional[Autopilot]] = contextvars.ContextVar(
    "thunder_tpu_autopilot", default=None
)


def current() -> Optional[Autopilot]:
    """The installed autopilot, or None — seams that cannot take a
    parameter (deopt.escalate, the SDC guard) ask here before deciding."""
    return _current.get()


def install(ap: Optional[Autopilot]):
    """Process-wide installation (None uninstalls); prefer the scoped
    :meth:`Autopilot.installed` where a ``with`` block fits."""
    _current.set(ap)
    return ap


# =============================================================================
# Mesh reshaping helpers
# =============================================================================


def shrink_shape(shape: dict, order=("fsdp", "tp", "dp")) -> Optional[dict]:
    """Halve the first axis in ``order`` (then any axis) still > 1 —
    "half the machines survived" as a shape transform. None when the mesh
    is already a single device (nothing left to shrink onto)."""
    axes = [a for a in order if shape.get(a, 1) > 1]
    axes += [a for a in shape if a not in order and shape[a] > 1]
    if not axes:
        return None
    out = dict(shape)
    out[axes[0]] = out[axes[0]] // 2
    return out


def _make_mesh(shape: dict):
    from thunder_tpu.parallel import make_mesh

    return make_mesh(**{k: int(v) for k, v in shape.items()})


# =============================================================================
# The autopiloted training driver
# =============================================================================


@dataclass
class AutopilotReport:
    """What :func:`run_autopiloted_training` hands back besides the state."""

    losses: list = field(default_factory=list)
    decisions: list = field(default_factory=list)
    final_mesh_shape: Optional[dict] = None
    recoveries: int = 0
    halted: Optional[AutopilotHalt] = None
    steps_executed: int = 0  # includes re-executed (wasted) steps


def run_autopiloted_training(
    autopilot: Autopilot,
    build_for_mesh: Callable,
    init_state: Any,
    n_steps: int,
    *,
    manager,
    mesh,
    specs_for_mesh: Callable,
    sdc_guard=True,
    watchdog_timeout_s: Optional[float] = None,
    save_every: int = 0,
    snapshot_every: int = 0,
    on_step: Optional[Callable] = None,
    regrow_after: Optional[int] = None,
    max_recoveries: int = 32,
    warm_start: bool = True,
) -> tuple[Any, AutopilotReport]:
    """Drive training to ``n_steps`` under the autopilot: faults raised out
    of :func:`~.preemption.run_training` are normalized into signals, the
    policy engine picks the actuator, and this loop applies it — elastic
    resume (same mesh / shrunk mesh / regrow), or checkpoint-and-halt
    (:class:`AutopilotHalt`). The quarantine-rerun and de-opt actuators fire
    *inside* the step via the installed-autopilot hooks and need no action
    here.

    ``build_for_mesh(mesh) -> step_fn`` (``step_fn(state) -> (state, loss)``,
    non-donating when ``sdc_guard`` is on) and ``specs_for_mesh(mesh) ->
    PartitionSpec pytree`` rebuild the workload for whatever mesh survives.
    ``regrow_after`` N healthy post-shrink steps reshard back up to the
    original mesh ("the replacement host arrived"). An anchor checkpoint is
    written up front so the first recovery always has something to resume
    from. ``snapshot_every`` forwards to
    :func:`~.preemption.run_training`'s RAM-snapshot cadence (ISSUE 14):
    with a :class:`~.snapshot.SnapshotStore` attached to ``manager``,
    every ``elastic_resume`` here restores from the newest valid tier
    (local RAM → peer RAM → disk) and its event names the tier. Returns
    ``(state, AutopilotReport)``; losses are indexed by step
    (re-executed steps overwrite, so each step counts once)."""
    from thunder_tpu.resilience import elastic
    from thunder_tpu.resilience.preemption import (
        HostLost,
        Preempted,
        run_training,
    )
    from thunder_tpu.resilience.watchdog import (
        CollectiveTimeoutError,
        SDCDetectedError,
    )
    from thunder_tpu import api

    full_shape = elastic.mesh_shape(mesh)
    cur_mesh = mesh
    cur_shape = dict(full_shape or {})
    state = init_state
    report = AutopilotReport(losses=[None] * n_steps, final_mesh_shape=cur_shape)
    shrunk_at: Optional[int] = None  # step the mesh last shrank at

    if manager.latest_complete_step() is None:
        # Recovery anchor: elastic_resume (the recovery event every
        # elastic decision must be followed by) needs a checkpoint on disk.
        manager.save(state, 0, rng_seed=api._global_rng["seed"], mesh=cur_mesh)
    # The driver owns every restore: elastic_resume reshards the restored
    # leaves onto the current mesh (a checkpoint restore hands back
    # single-device arrays that a mesh-sharded step must not be fed), so
    # run_training always gets start_step and never resumes on its own.
    state, start = elastic.elastic_resume(
        manager, state, mesh=cur_mesh, specs=specs_for_mesh(cur_mesh)
    )

    def _elastic(decision: Decision, target_mesh, target_shape):
        nonlocal state, start, cur_mesh, cur_shape
        with autopilot.recovery(decision):
            state, start = elastic.elastic_resume(
                manager, state, mesh=target_mesh,
                specs=specs_for_mesh(target_mesh),
            )
            cur_mesh, cur_shape = target_mesh, dict(target_shape)
            report.recoveries += 1
            report.final_mesh_shape = cur_shape

    def _on_loss(step, loss):
        report.losses[step] = loss
        report.steps_executed += 1
        if on_step is not None:
            on_step(step, loss)

    warmed: set = set()

    with autopilot.installed():
        while True:
            step_fn = build_for_mesh(cur_mesh)
            shape_key = tuple(sorted(cur_shape.items()))
            if warm_start and shape_key not in warmed:
                # One discarded step OUTSIDE the watchdog: the first call on
                # a freshly-built mesh step pays the XLA compile, and a cold
                # compile inside the guarded dispatch reads as a hang —
                # which would climb the collective_hang ladder on a
                # perfectly healthy mesh.
                step_fn(state)
                warmed.add(shape_key)
            # After a shrink, run only up to the regrow boundary so the
            # driver gets the state back at a step edge and can reshard up.
            target = n_steps
            if regrow_after and shrunk_at is not None and cur_shape != full_shape:
                target = min(n_steps, (start or 0) + regrow_after)
            try:
                state, _ = run_training(
                    step_fn, state, target,
                    manager=manager, mesh=cur_mesh, sdc_guard=sdc_guard,
                    watchdog_timeout_s=watchdog_timeout_s,
                    save_every=save_every, snapshot_every=snapshot_every,
                    on_loss=_on_loss,
                    start_step=start,
                )
                if target >= n_steps:
                    report.decisions = list(autopilot.decisions)
                    return state, report
                # Healthy through the regrow window: checkpoint at the
                # boundary and reshard back up to the full mesh.
                manager.save(state, target, rng_seed=api._global_rng["seed"],
                             mesh=cur_mesh)
                decision = _decide_regrow(autopilot, target, regrow_after)
                _elastic(decision, _make_mesh(full_shape), full_shape)
                shrunk_at = None
                continue
            except Preempted as e:
                # The checkpoint_halt decision was emitted inside
                # run_training before the save; this process stops here.
                report.decisions = list(autopilot.decisions)
                report.halted = AutopilotHalt(e.step, "preemption", None)
                report.halted.report = report
                # Black-box dump (ISSUE 15): every halt leaves the ring's
                # preceding context on disk next to the durable checkpoint.
                obs_events.flight_dump("autopilot_halt")
                raise report.halted from e
            except (HostLost, CollectiveTimeoutError, SDCDetectedError) as e:
                if report.recoveries >= max_recoveries:
                    raise
                signal = autopilot.signal_from_exception(e)
                decision = autopilot.decide(signal)
                if decision.actuator == "checkpoint_halt":
                    with autopilot.recovery(decision):
                        path = manager.save(
                            state, start if start is not None else 0,
                            rng_seed=api._global_rng["seed"], mesh=cur_mesh,
                        )
                    report.decisions = list(autopilot.decisions)
                    report.halted = AutopilotHalt(
                        signal.step or 0, f"policy ladder exhausted for "
                        f"{signal.kind}", decision)
                    report.halted.report = report
                    obs_events.flight_dump("autopilot_halt")
                    raise report.halted from e
                if decision.mode == "shrink":
                    new_shape = shrink_shape(cur_shape)
                    if new_shape is None:
                        # Nothing left to shrink onto: halt instead.
                        with autopilot.recovery(decision):
                            manager.save(state, start or 0,
                                         rng_seed=api._global_rng["seed"],
                                         mesh=cur_mesh)
                        report.decisions = list(autopilot.decisions)
                        report.halted = AutopilotHalt(
                            signal.step or 0, "mesh exhausted", decision)
                        report.halted.report = report
                        obs_events.flight_dump("autopilot_halt")
                        raise report.halted from e
                    _elastic(decision, _make_mesh(new_shape), new_shape)
                    shrunk_at = start
                else:  # same_mesh
                    _elastic(decision, cur_mesh, cur_shape)
                continue


def _decide_regrow(autopilot: Autopilot, step: int, healthy: Optional[int]) -> Decision:
    """The regrow decision: not fault-triggered, so it bypasses the policy
    ladder — a healthy window elapsed and replacement capacity is assumed
    back (the soak's stand-in for a scheduler granting a new host)."""
    return autopilot._record(Decision(
        id=0,
        signal=Signal("host_recovered", step=step,
                      evidence={"healthy_steps": healthy}),
        actuator="elastic_resume", mode="regrow",
    ))


def decide_regrow_dp(autopilot: Autopilot, slice_: int, step: Optional[int],
                     evidence: Optional[dict] = None) -> Decision:
    """The fleet regrow decision (ISSUE 18): emitted when the federation
    ledger promotes a cooled-down slice back to active — a recovery, not a
    fault, so like :func:`_decide_regrow` it bypasses the policy ladder but
    still flows through :meth:`Autopilot._record` so the decision is a
    replay-required event like every other actuator's."""
    return autopilot._record(Decision(
        id=0,
        signal=Signal("slice_recovered", step=step,
                      suspect_host=f"slice{slice_}",
                      evidence=dict(evidence or {})),
        actuator="regrow_dp",
    ))
