"""Tiered snapshot store: RAM ring + buddy-replicated restore tiers.

The disk checkpoint (``resilience/preemption.CheckpointManager``) is
durable but expensive: a synchronous save gathers the state to host AND
pays the serialize/write/rename protocol on the hot path, so PR 11's soak
could only afford sparse anchors — and every fault lost up to
``save_every`` steps of progress plus a disk read on restore
(``SOAK_r01``: 3.61 s charged per fault). ISSUE 14 splits the cost:

- the **step-boundary stall** is only the device→host copy (plus a crc32
  over the host bytes) — a :class:`Snapshot`, measured and emitted as the
  ``snapshot`` event's ``stall_ms``;
- durability moves to a background writer thread inside
  ``CheckpointManager`` (the existing tmp→rename→META protocol, off the
  hot path);
- availability comes from RAM: each host keeps a small ring of recent
  snapshots (:class:`SnapshotStore`) and replicates every snapshot to a
  **buddy** host, so the tiered restore
  (``resilience/elastic.elastic_resume``) can try local RAM → peer RAM →
  disk, checksum-validating each tier and falling through on
  mismatch/absence.

Integrity reuses the SDC guard's checksum
(:func:`~thunder_tpu.resilience.watchdog.array_crc32`): every snapshot
records per-leaf crc32s at capture time and :meth:`Snapshot.verify`
recomputes them before a restore trusts the bytes — a corrupted replica
(chaos seam ``snap_corrupt``) degrades to the next tier instead of
resuming from poison.

On a real multi-host fleet ``replicate`` would ship shard bytes to the
buddy over the network; on the virtual 8-device mesh the buddy is another
in-process :class:`SnapshotStore` (the soak wires a pair), which keeps the
tier ladder — and every chaos seam along it — exercisable in CI.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

TIERS = ("local", "peer", "disk")


def to_host(state: Any) -> Any:
    """Device→host copy of a pytree of (possibly sharded) arrays — the ONLY
    work on the training hot path (the ``checkpoint_stall_ms`` the
    ``snapshot`` event measures). Multi-process sharded leaves allgather
    (``distributed/checkpoint.gather_full``); everything else is a
    ``device_get``."""
    from thunder_tpu.distributed.checkpoint import gather_full

    return gather_full(state)


def pytree_crc32(host_state: Any) -> tuple:
    """Per-array-leaf crc32s of a host pytree, in flatten order — the SDC
    guard's integrity code (``watchdog.array_crc32``) applied to a
    snapshot. Non-array leaves (step counters, python scalars) are skipped:
    they travel in the snapshot but are not checksummed."""
    import numpy as np

    from thunder_tpu.core.pytree import tree_flatten
    from thunder_tpu.resilience.watchdog import array_crc32

    flat, _ = tree_flatten(host_state)
    out = []
    for leaf in flat:
        if isinstance(leaf, np.ndarray) and leaf.size:
            out.append(array_crc32(leaf))
    return tuple(out)


@dataclass
class Snapshot:
    """One step-boundary capture: the host-side state plus everything a
    restore needs (rng stream, writing mesh shape) and the capture-time
    crc32s that let a later restore verify the bytes are still the bytes."""

    step: int
    state: Any
    rng_seed: Optional[int] = None
    mesh: Optional[dict] = None
    crcs: tuple = ()
    ts: float = field(default_factory=time.time)

    def verify(self) -> bool:
        """True iff the state's array bytes still match the capture-time
        checksums — the gate every RAM-tier restore passes through."""
        return pytree_crc32(self.state) == self.crcs

    def share(self) -> "Snapshot":
        """A new Snapshot sharing the underlying arrays — what replication
        hands the buddy. Sharing is safe because corruption (the chaos
        seam) is copy-on-write: :meth:`SnapshotStore.corrupt_newest`
        replaces the flipped leaf instead of mutating it in place, so one
        tier's corruption never bleeds into the other's copy."""
        return Snapshot(step=self.step, state=self.state,
                        rng_seed=self.rng_seed, mesh=self.mesh,
                        crcs=self.crcs, ts=self.ts)


class SnapshotStore:
    """Per-host ring of recent snapshots plus replicas held for buddies.

    ``put`` appends to the local ring (bounded: ``ring`` newest kept) and
    forwards a shared-array copy to the paired buddy, which files it under
    this host's id. The tiered restore reads ``local_snapshots()`` (own
    ring) and ``peer_snapshots()`` (this host's replicas as held BY the
    buddy — where a replacement process would fetch them from after losing
    its RAM), both newest-first."""

    def __init__(self, host: int = 0, *, ring: int = 4):
        self.host = int(host)
        self.ring = int(ring)
        self._ring: deque = deque(maxlen=self.ring)
        self._replicas: dict[int, deque] = {}  # origin host -> ring of copies
        self.buddy: Optional["SnapshotStore"] = None
        # DCN-partition switch (chaos seam ``dcn_partition``): while True,
        # cross-boundary replication AND peer reads are severed — the buddy
        # is unreachable, not just write-blocked.
        self.partitioned = False
        self._lock = threading.Lock()

    @staticmethod
    def pair(a: "SnapshotStore", b: "SnapshotStore") -> None:
        """Mutual buddies — the 2-host wiring the soak uses."""
        a.buddy, b.buddy = b, a

    @classmethod
    def make_ring(cls, stores: list) -> None:
        """Ring-wire a fleet: buddy of store i = store (i+1) % n — how a
        federated pod assigns each slice's replication target ACROSS the
        DCN boundary, so a whole-slice loss always leaves a surviving buddy
        holding the victim's replicas. Two stores degenerate to
        :meth:`pair`."""
        n = len(stores)
        if n < 2:
            raise ValueError(f"a buddy ring needs >= 2 stores, got {n}")
        for i, s in enumerate(stores):
            s.buddy = stores[(i + 1) % n]

    # -- writes ---------------------------------------------------------------

    def put(self, snap: Snapshot) -> bool:
        """File ``snap`` in the local ring and replicate it to the buddy.
        Returns True when a buddy held a replica (the ``snapshot`` event's
        ``replicated`` field). A DCN partition (``partitioned`` on either
        end) severs replication: the local ring still fills, the buddy
        holds nothing new — honest degraded durability, reported as
        ``replicated=False``."""
        with self._lock:
            self._ring.append(snap)
        if (self.buddy is not None and not self.partitioned
                and not self.buddy.partitioned):
            self.buddy.receive(self.host, snap.share())
            return True
        return False

    def receive(self, origin: int, snap: Snapshot) -> None:
        """Buddy side of :meth:`put`: hold ``origin``'s replica in a ring
        of the same bound."""
        with self._lock:
            ring = self._replicas.get(origin)
            if ring is None:
                ring = self._replicas[origin] = deque(maxlen=self.ring)
            ring.append(snap)

    def drop_local(self) -> None:
        """Forget the local ring — what a host loss does to RAM. The chaos
        and test harnesses call this to force the peer/disk tiers."""
        with self._lock:
            self._ring.clear()

    # -- reads ----------------------------------------------------------------

    def local_snapshots(self) -> list:
        """Own ring, newest first."""
        with self._lock:
            return list(self._ring)[::-1]

    def peer_snapshots(self) -> list:
        """This host's replicas as held by the buddy, newest first — the
        peer RAM tier of the restore ladder. Unreachable (empty) while
        either end is DCN-partitioned."""
        if self.buddy is None or self.partitioned or self.buddy.partitioned:
            return []
        with self.buddy._lock:
            ring = self.buddy._replicas.get(self.host)
            return list(ring)[::-1] if ring else []

    def has_snapshots(self) -> bool:
        return bool(self.local_snapshots() or self.peer_snapshots())

    def newest_step(self) -> Optional[int]:
        steps = [s.step for s in self.local_snapshots()]
        steps += [s.step for s in self.peer_snapshots()]
        return max(steps) if steps else None

    # -- chaos hook -----------------------------------------------------------

    def corrupt_newest(self, tier: str) -> bool:
        """Flip one bit in the newest snapshot of ``tier`` (``local`` /
        ``peer``) — the ``snap_corrupt`` chaos seam's actuator. The flip is
        copy-on-write (the leaf is copied, flipped, and swapped into THIS
        tier's Snapshot only), so the share()'d twin in the other tier
        keeps the honest bytes. Returns False when the tier is empty or
        holds no array leaf (the rule stays armed — firing on nothing would
        record an injection that never happened)."""
        import numpy as np

        from thunder_tpu.core.pytree import tree_flatten, tree_unflatten

        snaps = (self.local_snapshots() if tier == "local"
                 else self.peer_snapshots())
        # Corrupt the newest snapshot that is still VALID: the bit flip is
        # an XOR, so "corrupting" an already-corrupted snapshot would undo
        # the damage and silently re-validate the tier.
        for snap in snaps:
            if not snap.verify():
                continue
            flat, spec = tree_flatten(snap.state)
            for i, leaf in enumerate(flat):
                if isinstance(leaf, np.ndarray) and leaf.size:
                    bad = leaf.copy()
                    bad.view(np.uint8).reshape(-1)[0] ^= 1
                    flat = list(flat)
                    flat[i] = bad
                    snap.state = tree_unflatten(spec, flat)
                    return True
            return False
        return False
