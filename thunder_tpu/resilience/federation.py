"""Slice-granular failure domains: the fleet controller (ISSUE 18).

Every resilience layer below this one — autopilot, elastic resume, tiered
checkpointing — treats a HOST as the unit of failure. On a DCN-federated
fleet the real unit is a **slice**: an ICI-connected block of devices that
lives or dies together (a maintenance drain, a power domain, a network
partition takes out the whole slice, not one chip). This module owns that
failure domain:

- :class:`FederationLedger` — slice membership as a TYPED ledger: every
  slice is ``active`` / ``lost`` / ``cooldown`` and every transition is a
  ``slice_state`` event (replay-required, like every autopilot decision),
  so the fleet's membership history is reconstructable from the log alone;
- :class:`FleetController` — the shrink/regrow state machine. On slice
  loss, shrink the data-parallel group and keep training on the survivors
  (the ``shrink_dp`` actuator; gradient accumulation rescales
  loss-equivalently so the effective global batch is unchanged). On
  recovery, the slice enters **cooldown** behind a rejoin backoff with
  hysteresis: it rejoins (``regrow_dp``) only after it has stayed healthy
  for the full window — a flapping slice (fail/recover faster than the
  window) degrades the fleet ONCE (one shrink, one deferred regrow)
  instead of thrashing resume loops;
- :func:`run_federated_training` — the federated driver: an emulated
  multi-slice fleet in one process (the same emulation discipline as the
  virtual 8-device mesh), wiring the chaos slice seams (``slice_loss``,
  ``dcn_partition``, ``slice_slow``, ``slice_flap``) through the
  controller and the tiered restore.

Cross-slice checkpoint replication rides the peer-snapshot tier
(``resilience/snapshot.SnapshotStore.make_ring``): each slice's snapshots
replicate to a buddy slice ACROSS the DCN boundary, so a whole-slice loss
restores from the buddy's RAM (``restore`` event ``tier="peer"``) — the
disk tier is never touched in a slice-loss recovery, which is the
acceptance invariant the pod soak (``scripts/soak_pod.py``) proves with
tier-hit counters.

Loss equivalence of the shrink (degraded-mode semantics,
docs/robustness.md "failure domains"): at full width ``W`` slices the
global batch is ``W x per_slice_batch x grad_accum``. After shrinking to
``w`` survivors, :meth:`FleetController.grad_accum_for` returns
``ceil(grad_accum x W / w)`` — the survivors run more accumulation
micro-steps so each optimizer step still sees (at least) the same global
batch, and the loss trajectory stays comparable at reduced THROUGHPUT,
not reduced statistical quality. The headline goodput at reduced width is
reported honestly: fewer tokens/s, same tokens/step.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from thunder_tpu.observability import events as obs_events
from thunder_tpu.resilience import chaos
from thunder_tpu.resilience.autopilot import (
    Autopilot,
    AutopilotHalt,
    Decision,
    Signal,
    decide_regrow_dp,
)

SLICE_STATES = ("active", "lost", "cooldown")

# The process-wide membership ledger the ops plane reads (/healthz rolls
# up per-slice health; /debug/state exposes the full ledger). Installed by
# FleetController construction — one fleet controller per process, like
# the autopilot's `current()`.
_state: dict = {"ledger": None}


def install_ledger(ledger: Optional["FederationLedger"]) -> None:
    """Install (or, with None, clear) the ledger ``current_ledger`` serves."""
    _state["ledger"] = ledger


def current_ledger() -> Optional["FederationLedger"]:
    return _state["ledger"]


@dataclass
class SliceEntry:
    """One slice's row in the membership ledger. ``recovered_at`` is the
    controller-clock time the slice last came back (cooldown entry) — the
    stability window the rejoin hysteresis measures from; a re-failure
    inside cooldown clears it, restarting the window."""

    slice_id: int
    state: str = "active"
    lost_at: Optional[float] = None
    recovered_at: Optional[float] = None
    losses: int = 0

    def as_dict(self) -> dict:
        return {
            "slice": self.slice_id, "state": self.state,
            "losses": self.losses, "lost_at": self.lost_at,
            "recovered_at": self.recovered_at,
        }


class FederationLedger:
    """Typed slice-membership ledger — the single writer of ``slice_state``
    events, so the transition history in the log IS the membership history
    (no second bookkeeping path can diverge from it). Transitions are the
    edges of the state machine only: active→lost, lost→cooldown,
    cooldown→lost (re-failure), cooldown→active (promotion); anything else
    raises — a fleet controller that tries an illegal edge has a logic
    bug, and silently absorbing it would corrupt the replay."""

    def __init__(self, n_slices: int, *,
                 clock: Callable[[], float] = time.monotonic):
        if n_slices < 1:
            raise ValueError(f"a fleet needs >= 1 slice, got {n_slices}")
        self.n_slices = int(n_slices)
        self._clock = clock
        self.entries = {i: SliceEntry(i) for i in range(self.n_slices)}
        self.transitions: list[tuple[int, str, str, str]] = []

    _EDGES = {
        ("active", "lost"), ("lost", "cooldown"),
        ("cooldown", "lost"), ("cooldown", "active"),
    }

    def _transition(self, slice_: int, to: str, reason: str) -> SliceEntry:
        e = self.entries[int(slice_)]
        frm = e.state
        if (frm, to) not in self._EDGES:
            raise ValueError(
                f"illegal slice state transition {frm!r} -> {to!r} for "
                f"slice {slice_} ({reason})"
            )
        e.state = to
        now = self._clock()
        if to == "lost":
            e.lost_at = now
            e.recovered_at = None
            e.losses += 1
        elif to == "cooldown":
            e.recovered_at = now
        self.transitions.append((e.slice_id, frm, to, reason))
        obs_events.emit_event(
            "slice_state", slice=e.slice_id, to=to, reason=reason,
            **{"from": frm},
        )
        return e

    def mark_lost(self, slice_: int, reason: str = "slice_loss") -> SliceEntry:
        return self._transition(slice_, "lost", reason)

    def mark_cooldown(self, slice_: int,
                      reason: str = "slice_recovered") -> SliceEntry:
        return self._transition(slice_, "cooldown", reason)

    def promote(self, slice_: int, reason: str = "rejoin") -> SliceEntry:
        return self._transition(slice_, "active", reason)

    def active_slices(self) -> list[int]:
        return [i for i, e in sorted(self.entries.items())
                if e.state == "active"]

    def width(self) -> int:
        """Current data-parallel width in slices."""
        return len(self.active_slices())

    def state_of(self, slice_: int) -> str:
        return self.entries[int(slice_)].state

    def debug_state(self) -> dict:
        """The ops-plane ``/debug/state`` view of fleet membership."""
        return {
            "n_slices": self.n_slices,
            "width": self.width(),
            "slices": [self.entries[i].as_dict()
                       for i in range(self.n_slices)],
            "transitions": [
                {"slice": s, "from": f, "to": t, "reason": r}
                for s, f, t, r in self.transitions[-32:]
            ],
        }


class FleetController:
    """The shrink/regrow state machine over a :class:`FederationLedger`.

    ``rejoin_backoff_s`` is the minimum hold-out after a slice recovers;
    ``hysteresis_s`` is the stability window it must survive WITHOUT
    re-failing. Both are measured on the injectable ``clock`` from the
    cooldown entry (``recovered_at``), and a re-failure restarts the
    window — so a slice flapping faster than the window never rejoins
    until it genuinely stabilizes, and the fleet pays exactly one shrink
    for the whole episode.

    Decisions flow through the autopilot: a loss is a ``slice_loss``
    signal decided on the policy ladder (``shrink_dp`` — or
    ``checkpoint_halt`` when slices evaporate faster than the window), a
    promotion is a ladder-bypassing ``regrow_dp`` record
    (:func:`~.autopilot.decide_regrow_dp`). Both are replay-required
    events; the driver applies each inside ``autopilot.recovery`` so no
    two fleet actuations overlap."""

    def __init__(self, ledger: FederationLedger, autopilot: Autopilot, *,
                 rejoin_backoff_s: float = 1.0, hysteresis_s: float = 1.0,
                 clock: Optional[Callable[[], float]] = None):
        self.ledger = ledger
        self.autopilot = autopilot
        self.rejoin_backoff_s = float(rejoin_backoff_s)
        self.hysteresis_s = float(hysteresis_s)
        self._clock = clock if clock is not None else ledger._clock
        install_ledger(ledger)

    # -- loss / recovery intake -----------------------------------------------

    def on_slice_loss(self, slice_: int, step: Optional[int] = None,
                      reason: str = "slice_loss") -> Optional[Decision]:
        """A slice died. Returns the shrink (or halt) decision when the
        fleet must actually degrade, None when it already has:

        - ``active`` → ``lost``: decide on the ``slice_loss`` ladder —
          normally ``shrink_dp``;
        - ``cooldown`` → ``lost``: a re-failure inside the rejoin window.
          The fleet never regrew, so there is nothing to shrink — the
          ledger records the flap and the hold-out restarts. This is the
          edge that makes a flapping slice degrade once;
        - ``lost``: duplicate report, no-op."""
        state = self.ledger.state_of(slice_)
        if state == "lost":
            return None
        if state == "cooldown":
            self.ledger.mark_lost(slice_, reason="flap_refailure")
            return None
        self.ledger.mark_lost(slice_, reason=reason)
        return self.autopilot.decide(Signal(
            "slice_loss", step=step, suspect_host=f"slice{int(slice_)}",
            evidence={"slice": int(slice_), "reason": reason},
        ))

    def on_slice_recovered(self, slice_: int,
                           step: Optional[int] = None) -> None:
        """A lost slice reports healthy again. It does NOT rejoin: it
        enters cooldown, and :meth:`poll` promotes it only after the
        backoff + hysteresis window passes without another failure."""
        if self.ledger.state_of(slice_) == "lost":
            self.ledger.mark_cooldown(slice_)

    def poll(self, step: Optional[int] = None) -> Optional[Decision]:
        """Promote at most ONE cooled-down slice whose stability window has
        cleared (oldest recovery first), returning its ``regrow_dp``
        decision — the driver applies one regrow at a time so each
        resharding lands at a step boundary."""
        now = self._clock()
        hold = max(self.rejoin_backoff_s, self.hysteresis_s)
        ready = [
            e for e in self.ledger.entries.values()
            if e.state == "cooldown" and e.recovered_at is not None
            and now - e.recovered_at >= hold
        ]
        if not ready:
            return None
        e = min(ready, key=lambda e: e.recovered_at)
        stable_s = now - e.recovered_at
        self.ledger.promote(e.slice_id)
        return decide_regrow_dp(
            self.autopilot, e.slice_id, step,
            evidence={"stable_s": round(stable_s, 3),
                      "rejoin_backoff_s": self.rejoin_backoff_s,
                      "hysteresis_s": self.hysteresis_s},
        )

    # -- degraded-mode arithmetic ----------------------------------------------

    def grad_accum_for(self, base: int = 1) -> int:
        """Gradient-accumulation factor at the CURRENT width that keeps the
        global batch loss-equivalent to full width (see module docstring).
        Ceil, so a non-divisible shrink errs on the side of a slightly
        larger batch rather than a smaller one."""
        width = self.ledger.width()
        if width < 1:
            raise AutopilotHalt(0, "fleet exhausted: no active slices")
        return math.ceil(int(base) * self.ledger.n_slices / width)


# =============================================================================
# The federated training driver
# =============================================================================


@dataclass
class FleetReport:
    """What :func:`run_federated_training` hands back besides the state."""

    losses: list = field(default_factory=list)
    decisions: list = field(default_factory=list)
    shrinks: int = 0
    regrows: int = 0
    full_width: int = 0
    final_width: int = 0
    steps_executed: int = 0       # includes re-executed (wasted) steps
    degraded_steps: int = 0       # steps run at reduced width
    partitioned_steps: int = 0    # steps with the DCN tier severed
    halted: Optional[AutopilotHalt] = None


def run_federated_training(
    controller: FleetController,
    build_for_width: Callable,
    init_state: Any,
    n_steps: int,
    *,
    manager,
    mesh_for_width: Callable,
    stores: Optional[list] = None,
    grad_accum: int = 1,
    snapshot_every: int = 0,
    save_every: int = 0,
    recover_after: Optional[int] = None,
    on_step: Optional[Callable] = None,
    slice_step_time: Optional[Callable] = None,
    timeline=None,
    warm_start: bool = True,
    max_recoveries: int = 32,
) -> tuple[Any, FleetReport]:
    """Drive an emulated federated fleet to ``n_steps`` under the
    controller: the chaos slice seams fire at step boundaries, losses
    shrink the DP width through the autopilot's ``shrink_dp`` decisions,
    and cooled-down slices regrow through ``regrow_dp`` — all without a
    process restart.

    - ``build_for_width(mesh, width, accum) -> step_fn`` rebuilds the
      workload for the surviving width (``step_fn(state) -> (state,
      loss)``); ``accum`` is the loss-equivalent gradient-accumulation
      factor (:meth:`FleetController.grad_accum_for`).
    - ``mesh_for_width(width) -> (mesh, specs)`` builds the emulated mesh
      spanning ``width`` slices and the matching PartitionSpec pytree.
    - ``stores`` is one ring-wired :class:`~.snapshot.SnapshotStore` per
      slice (``SnapshotStore.make_ring``); the driver keeps the manager's
      store on the lowest ACTIVE slice, fans each snapshot out to every
      other active slice, and — on a slice loss — restores through the
      VICTIM's store so the read lands on the cross-slice buddy's peer-RAM
      tier (never disk).
    - ``recover_after`` N steps after a ``slice_loss``, the victim reports
      healthy again (the stand-in for the scheduler re-granting the
      slice); rejoin then waits out the controller's backoff + hysteresis.
    - ``slice_step_time(slice_id, seconds)`` receives each active slice's
      per-step duration (base step + its ``slice_slow`` inflation) — the
      feed for the cross-slice spread detector
      (``observability/detect.py``).
    - ``timeline`` is an armed
      :class:`~thunder_tpu.observability.timeline.TimelineRecorder`: each
      step the driver feeds it per-slice spans (work + snapshot stall +
      measured dispatch gap, wire legs from the recorder's static split)
      and one lockstep-barrier ``collective`` rendezvous record per slice
      — the fleet critical-path ledger's entire input (ISSUE 20).

    A ``slice_flap`` injection scripts the victim through a fail/recover/
    fail/recover loop on consecutive steps — faster than any sane
    hysteresis window — which the controller must absorb as ONE shrink
    and ONE deferred regrow (the acceptance invariant the replayed event
    ledger proves)."""
    from thunder_tpu import api
    from thunder_tpu.resilience import elastic

    ledger = controller.ledger
    autopilot = controller.autopilot
    full_width = ledger.n_slices
    report = FleetReport(losses=[None] * n_steps, full_width=full_width,
                         final_width=ledger.width())

    def _attach_primary_store() -> None:
        if stores:
            active = ledger.active_slices()
            manager.store = stores[active[0]] if active else None

    _attach_primary_store()

    def _mesh(width: int):
        return mesh_for_width(width)

    def _build(mesh, width: int):
        fn = build_for_width(mesh, width, controller.grad_accum_for(grad_accum))
        if warm_start:
            key = (width,)
            if key not in warmed:
                fn(state)  # one discarded step: pay the compile off-ledger
                warmed.add(key)
        return fn

    def _fan_out(snap) -> None:
        # DP state is replicated: every active slice holds (and buddy-
        # replicates) the snapshot, so ANY slice's loss leaves a surviving
        # peer copy across the DCN boundary.
        if not stores or snap is None:
            return
        for sid in ledger.active_slices():
            st = stores[sid]
            if st is not manager.store:
                st.put(snap.share())

    def _halt(step: int, reason: str, decision, exc=None):
        manager.save(state, step, rng_seed=api._global_rng["seed"], mesh=mesh)
        report.decisions = list(autopilot.decisions)
        report.final_width = ledger.width()
        halted = AutopilotHalt(step, reason, decision)
        report.halted = halted
        obs_events.flight_dump("autopilot_halt")
        raise halted from exc

    warmed: set = set()
    state = init_state
    width = ledger.width()
    mesh, specs = _mesh(width)
    if manager.latest_complete_step() is None:
        # Durability anchor (written once, BEFORE any slice-loss recovery:
        # the restores themselves must land on RAM tiers).
        manager.save(state, 0, rng_seed=api._global_rng["seed"], mesh=mesh)
    state, step = elastic.elastic_resume(manager, state, mesh=mesh,
                                         specs=specs)
    step_fn = _build(mesh, width)
    pending_recover: dict[int, int] = {}   # slice -> step it reports healthy
    flap_script: dict[int, list[tuple[str, int]]] = {}  # step -> actions
    partition_heal_at: Optional[int] = None

    def _apply_shrink(decision, victim: int, at_step: int):
        nonlocal state, step, width, mesh, specs, step_fn
        if ledger.width() < 1:
            _halt(at_step, "fleet exhausted: no active slices", decision)
        if decision.actuator == "checkpoint_halt":
            _halt(at_step, "slice_loss policy ladder exhausted", decision)
        if stores:
            # The victim's RAM died with it; its state survives ONLY as the
            # cross-slice buddy's replica. Restoring through the victim's
            # store makes the tier ladder prove exactly that (tier="peer").
            stores[victim].drop_local()
            manager.store = stores[victim]
        with autopilot.recovery(decision):
            width = ledger.width()
            mesh, specs = _mesh(width)
            state, step = elastic.elastic_resume(manager, state, mesh=mesh,
                                                 specs=specs)
        _attach_primary_store()
        step_fn = _build(mesh, width)
        report.shrinks += 1

    def _apply_regrow(decision, at_step: int):
        nonlocal state, step, width, mesh, specs, step_fn
        # Snapshot at the boundary so the regrow resume restores THIS step
        # from RAM (local tier) instead of replaying from an old anchor.
        snap = manager.snapshot(state, at_step,
                                rng_seed=api._global_rng["seed"], mesh=mesh)
        _fan_out(snap)
        with autopilot.recovery(decision):
            width = ledger.width()
            mesh, specs = _mesh(width)
            state, step = elastic.elastic_resume(manager, state, mesh=mesh,
                                                 specs=specs)
        _attach_primary_store()
        step_fn = _build(mesh, width)
        report.regrows += 1

    def _feed_timeline(at_step: int, base_s: float, delays: dict,
                       stall_s: float, gap_s: float) -> None:
        # Per-slice spans for the critical-path ledger: each slice's own
        # work is the base step plus its chaos inflation; the snapshot
        # stall and the driver's dispatch gap are uniform (every host
        # snapshots its own shard / waits on the same loop). Wire legs come
        # from the recorder's static split of the compute work — measured
        # per-leg timing is a hardware-fleet capability; the emulated fleet
        # prices it statically, which is exactly what keeps the recorder's
        # static-vs-measured cross-check falsifiable.
        spans: dict = {}
        wall = base_s + (max(delays.values()) if delays else 0.0) + stall_s
        for sid, d in delays.items():
            sp = dict(timeline.static_spans(base_s))
            sp["total_s"] = base_s + d + stall_s + gap_s
            sp["stall_s"] = stall_s
            spans[sid] = sp
            # The lockstep barrier ending the step is the rendezvous
            # anchor every slice leaves together — one collective record
            # per slice, `s` = the wire time this slice spent in it.
            timeline.note_collective(
                sid, at_step, fn="fleet_step",
                s=max(0.0, wall - (base_s + d + stall_s)),
                in_slice_s=sp.get("ici_s", 0.0),
                cross_slice_s=sp.get("dcn_s", 0.0),
                step=at_step,
            )
        timeline.record_step(at_step, spans)

    # Installed for the loop's duration (the run_training pattern): the
    # DetectorBank publishes every anomaly to autopilot.current(), and
    # the controller's decisions must cite that evidence ring -- an
    # uninstalled autopilot would decide blind.
    with autopilot.installed():
        while step < n_steps:
            iter_t0 = time.perf_counter()
            # ---- chaos seams + scripted recoveries at the step boundary ----
            for kind, sid in flap_script.pop(step, []):
                if kind == "lose":
                    d = controller.on_slice_loss(sid, step, reason="slice_flap")
                    if d is not None:
                        _apply_shrink(d, sid, step)
                else:
                    controller.on_slice_recovered(sid, step)
            for sid, at in list(pending_recover.items()):
                if step >= at:
                    del pending_recover[sid]
                    controller.on_slice_recovered(sid, step)
            if partition_heal_at is not None:
                if step >= partition_heal_at:
                    partition_heal_at = None
                    if stores:
                        for st in stores:
                            st.partitioned = False
                else:
                    report.partitioned_steps += 1

            victim = chaos.slice_loss_at_step(step)
            if victim is not None:
                if report.shrinks + report.regrows >= max_recoveries:
                    _halt(step, "max recoveries exceeded", None)
                d = controller.on_slice_loss(victim, step)
                if d is not None:
                    _apply_shrink(d, victim, step)
                if recover_after:
                    pending_recover[victim] = step + int(recover_after)

            flapper = chaos.slice_flap_at_step(step)
            if flapper is not None:
                # Scripted flap: lose now, recover next step, re-fail the one
                # after, recover again — two cycles inside any hysteresis
                # window long enough to matter.
                d = controller.on_slice_loss(flapper, step, reason="slice_flap")
                if d is not None:
                    _apply_shrink(d, flapper, step)
                flap_script.setdefault(step + 1, []).append(("recover", flapper))
                flap_script.setdefault(step + 2, []).append(("lose", flapper))
                flap_script.setdefault(step + 3, []).append(("recover", flapper))

            rule = chaos.dcn_partition_at_step(step)
            if rule is not None and stores:
                for st in stores:
                    st.partitioned = True
                partition_heal_at = step + max(1, int(round(rule.delay_s)))

            regrow = controller.poll(step)
            if regrow is not None:
                _apply_regrow(regrow, step)

            # ---- the training step ----
            t0 = time.perf_counter()
            state, loss = step_fn(state)
            base_s = time.perf_counter() - t0
            slow = 0.0
            delays: dict = {}
            for sid in ledger.active_slices():
                d = chaos.slice_slow_delay(sid)
                delays[sid] = d
                if slice_step_time is not None:
                    slice_step_time(sid, base_s + d)
                slow = max(slow, d)
            if slow:
                # The fleet steps in lockstep: the slowest slice gates the step.
                time.sleep(slow)
            report.losses[step] = loss
            report.steps_executed += 1
            if width < full_width:
                report.degraded_steps += 1
            if on_step is not None:
                on_step(step, loss, width)

            done = step + 1
            snap_stall_s = 0.0
            if done < n_steps:
                want_disk = bool(save_every and done % save_every == 0)
                want_snap = bool(snapshot_every and done % snapshot_every == 0)
                if want_snap or want_disk:
                    t_snap = time.perf_counter()
                    async_flush = bool(getattr(manager, "async_flush", False))
                    snap = manager.snapshot(
                        state, done, rng_seed=api._global_rng["seed"], mesh=mesh,
                        flush=want_disk and async_flush,
                    )
                    _fan_out(snap)
                    if want_disk and not async_flush:
                        manager.save(state, done,
                                     rng_seed=api._global_rng["seed"], mesh=mesh)
                    snap_stall_s = time.perf_counter() - t_snap
            if timeline is not None and delays:
                # The dispatch gap: loop wall time not accounted to work,
                # lockstep wait, or the snapshot stall — the step's idle class.
                gap_s = max(0.0, (time.perf_counter() - iter_t0)
                            - base_s - slow - snap_stall_s)
                _feed_timeline(step, base_s, delays, snap_stall_s, gap_s)
            step = done

    # Drain any still-cooling slice the caller wants resolved via poll()
    # after the run; the report captures where the fleet ended up.
    report.decisions = list(autopilot.decisions)
    report.final_width = ledger.width()
    return state, report
