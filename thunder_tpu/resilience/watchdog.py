"""Mesh-level step guards: the collective watchdog and the SDC guard.

Two detectors for faults a single process cannot see from its own stack
traces (ISSUE 9): a peer that stopped participating in a collective (the
job hangs forever at an all-gather with no error), and silent data
corruption (a flipped bit in one replica's memory poisons the run with no
signal at all). Both close the loop from PR 8's *detection* (per-host
telemetry, straggler suspects) to *action*.

**Collective watchdog** — :func:`guard_call` runs a dispatch that contains
collectives on a worker thread and joins with a configurable timeout
(``THUNDER_TPU_COLLECTIVE_TIMEOUT_S`` / :func:`configure`). A hung
collective cannot be cancelled (on real hardware the ICI transfer is in
flight; the process restarts), so on timeout the watchdog abandons the
worker and raises a typed :class:`CollectiveTimeoutError` naming the
collective trace lines of the guarded program and the suspected host —
joined against the last :func:`~thunder_tpu.analysis.events.host_health`
summary (:func:`note_host_health`), so the straggler the observatory
flagged is the first name in the error. Dispatch sites that opt in:
``api._run_entry`` (traces with collectives), ``distributed/runtime``'s
shard_map callables, and ``resilience.preemption.run_training`` steps on a
mesh. The watchdog is off unless a timeout is configured — steady-state
overhead is one dict probe per call.

**SDC guard** — :class:`SDCGuard`, armed via
``run_training(sdc_guard=...)``: after each guarded step it cross-checks a
cheap rolling checksum (crc32 of each addressable shard's bytes) across
data-parallel replicas of the training state. Replicas hold bitwise-equal
copies by construction, so any divergence is a corrupted device; the guard
emits ``sdc_suspect`` naming the leaf and devices, quarantines the step
(discards the poisoned state), and re-runs it from the previous state —
``sdc_rerun`` records the outcome; a divergence that survives the re-run
raises :class:`SDCDetectedError`. Requires a non-donating step function
(the previous state must stay alive for the re-run).
"""

from __future__ import annotations

import os
import threading
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from thunder_tpu.observability import events as obs_events
from thunder_tpu.observability import metrics as obsm
from thunder_tpu.resilience import chaos


class CollectiveTimeoutError(RuntimeError):
    """A guarded dispatch containing collectives did not complete within the
    watchdog timeout — a peer stopped participating (host hang/loss) or the
    interconnect stalled. Carries the collective trace lines of the guarded
    program and the suspected host from the last host-health summary."""

    seam = "collective_hang"

    def __init__(self, fn_name: str, timeout_s: float,
                 trace_lines: Optional[Sequence[str]] = None,
                 suspected_host: Optional[Any] = None,
                 schedule: Optional[dict] = None):
        self.fn_name = fn_name
        self.timeout_s = timeout_s
        self.trace_lines = list(trace_lines or [])
        self.suspected_host = suspected_host
        # Certified per-axis collective order of the guarded program
        # ({axis: ["L<i>.<sym>", ...]} — analysis/schedule.ScheduleCertificate
        # .axis_labels()): everything left of a pending collective must have
        # completed on every healthy host, which is what narrows a hang to
        # the first line a dead peer never reached.
        self.schedule = dict(schedule or {})
        lines = ", ".join(self.trace_lines) if self.trace_lines else \
            "collectives inserted by the SPMD partitioner (no trace lines)"
        suspect = (
            f"suspected host {suspected_host} (straggler per host_health)"
            if suspected_host is not None
            else "no straggler data (run monitor.host_health over per-host logs)"
        )
        sched = ""
        if self.schedule:
            sched = "; certified order " + "; ".join(
                f"{axis}: " + " -> ".join(labels)
                for axis, labels in sorted(self.schedule.items())
            )
        super().__init__(
            f"collective watchdog: {fn_name!r} exceeded {timeout_s:g}s — "
            f"a peer stopped participating; pending collectives: {lines}; "
            f"{suspect}{sched}"
        )


class SDCDetectedError(RuntimeError):
    """Replica checksums diverged and the quarantine re-run did not clear
    it — persistent corruption (bad device memory), not a transient flip."""

    seam = "sdc"

    def __init__(self, step: int, leaves: Sequence[str]):
        self.step = step
        self.leaves = list(leaves)
        super().__init__(
            f"SDC guard: replica checksum divergence at step {step} survived "
            f"the quarantine re-run (leaves: {', '.join(self.leaves)}) — "
            f"suspect persistent device corruption"
        )


# -- watchdog configuration ----------------------------------------------------

_config: dict = {"timeout_s": None, "resolved": False}
_last_health: dict = {"summary": None}
# Workers abandoned after a timeout (a hung collective cannot be cancelled,
# so the thread leaks until the hang clears). A soak full of injected hangs
# would otherwise grow live threads without bound (ISSUE 11 satellite):
# past THUNDER_TPU_WATCHDOG_MAX_ABANDONED live abandoned workers, guard
# arming is refused — the dispatch runs unguarded with a warning — until
# some of them die. The registry lock keeps a concurrent timeout's append
# from being lost under another thread's prune (guard_call is explicitly
# multi-thread safe).
_abandoned: list = []
_abandoned_lock = threading.Lock()


def max_abandoned_workers() -> int:
    try:
        return int(os.environ.get("THUNDER_TPU_WATCHDOG_MAX_ABANDONED", "16"))
    except ValueError:
        return 16


def abandoned_worker_count() -> int:
    """Live abandoned watchdog workers (dead ones are pruned on each call)."""
    with _abandoned_lock:
        _abandoned[:] = [t for t in _abandoned if t.is_alive()]
        return len(_abandoned)


def configure(timeout_s: Optional[float]) -> None:
    """Arm (or disarm with ``None``) the collective watchdog process-wide —
    the programmatic spelling of ``THUNDER_TPU_COLLECTIVE_TIMEOUT_S``."""
    _config["timeout_s"] = float(timeout_s) if timeout_s else None
    _config["resolved"] = True


def active_timeout() -> Optional[float]:
    if not _config["resolved"]:
        env = os.environ.get("THUNDER_TPU_COLLECTIVE_TIMEOUT_S", "").strip()
        _config["timeout_s"] = float(env) if env else None
        _config["resolved"] = True
    return _config["timeout_s"]


def enabled() -> bool:
    return active_timeout() is not None


def note_host_health(summary: Optional[dict]) -> None:
    """Record the latest cross-host health summary
    (``analysis/events.host_health`` calls this) so a later timeout can name
    the suspected straggler instead of just "somewhere in the mesh"."""
    _last_health["summary"] = summary


def last_host_health() -> Optional[dict]:
    return _last_health["summary"]


def _suspected_host() -> Optional[Any]:
    summary = _last_health["summary"]
    if summary and summary.get("stragglers"):
        return summary["stragglers"][0]
    return None


# -- the guarded call ----------------------------------------------------------


def guard_call(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    *,
    fn_name: str = "?",
    trace_lines: Optional[Sequence[str]] = None,
    timeout_s: Optional[float] = None,
    schedule: Optional[dict] = None,
):
    """Run ``fn(*args, **kwargs)`` under the collective watchdog.

    With no timeout configured this is a direct call. Otherwise the call
    runs on a daemon worker thread and the caller joins with the timeout:
    on expiry the worker is abandoned (a hung collective cannot be
    cancelled — production recovery is checkpoint + elastic resume in a
    fresh process) and :class:`CollectiveTimeoutError` raises, after
    emitting a ``collective_timeout`` event and bumping
    ``thunder_tpu_collective_watchdog_timeouts_total``. The chaos
    ``collective_hang`` seam fires inside the guarded region, so injected
    hangs exercise exactly this path."""
    timeout = timeout_s if timeout_s is not None else active_timeout()
    if timeout is None:
        return fn(*args, **(kwargs or {}))
    cap = max_abandoned_workers()
    if abandoned_worker_count() >= cap:
        # Refusing to arm bounds the leak: each timeout strands one worker
        # thread forever (the hung collective cannot be cancelled), and a
        # soak full of hangs must not grow threads without limit. The
        # dispatch still runs — unguarded, loudly.
        import warnings

        if obsm.enabled():
            obsm.WATCHDOG_UNGUARDED.inc()
        warnings.warn(
            f"thunder_tpu collective watchdog: {cap} abandoned worker(s) "
            f"still alive (THUNDER_TPU_WATCHDOG_MAX_ABANDONED={cap}); "
            f"running {fn_name!r} UNguarded until they exit",
            RuntimeWarning, stacklevel=2,
        )
        return fn(*args, **(kwargs or {}))

    import contextvars

    # The worker must see the caller's context: chaos scopes and per-function
    # event-log routing are contextvars, and a fresh thread starts from an
    # empty context.
    ctx = contextvars.copy_context()
    box: dict = {}

    def worker():
        try:
            def body():
                chaos.collective_hang_seam()
                # Sub-timeout slowdown (straggler@step): the streaming
                # detectors (observability/detect.py) must see a drifting
                # step BEFORE it becomes a hang — this is the seam the soak
                # uses to prove detection lead time (ISSUE 15).
                chaos.straggler_seam("step")
                return fn(*args, **(kwargs or {}))

            box["out"] = ctx.run(body)
        except BaseException as e:  # propagated to the caller below
            box["exc"] = e

    t = threading.Thread(
        target=worker, name=f"thunder-tpu-watchdog:{fn_name}", daemon=True
    )
    t.start()
    t.join(timeout)
    if t.is_alive():
        with _abandoned_lock:
            _abandoned.append(t)
        lines = list(trace_lines or [])
        suspect = _suspected_host()
        if obsm.enabled():
            obsm.WATCHDOG_TIMEOUTS.inc(fn=fn_name)
        # schedule appears only when the trace carried a certificate —
        # consumers detect certification by field presence, not null.
        extra = {"schedule": dict(schedule)} if schedule else {}
        obs_events.emit_event(
            "collective_timeout", fn=fn_name, timeout_s=timeout,
            lines=lines, suspected_host=suspect, **extra,
        )
        # Black-box dump (ISSUE 15): the ring already holds the fault's
        # preceding context (step timings, injections, the timeout record
        # above) — capture it before the raise unwinds the stack.
        obs_events.flight_dump("collective_timeout")
        raise CollectiveTimeoutError(fn_name, timeout, lines, suspect, schedule)
    if "exc" in box:
        raise box["exc"]
    return box.get("out")


class _GuardedCallable:
    """The :func:`wrap` result: calls route through :func:`guard_call` when
    the watchdog is armed at call time (plain passthrough otherwise), and
    every other attribute access delegates to the wrapped callable — so a
    wrapped ``jax.jit`` object keeps its ``lower``/``as_text``/... API and
    consumers probing ``hasattr(jfn, "lower")`` don't silently degrade."""

    def __init__(self, fn: Callable, name: str,
                 trace_lines: Optional[Sequence[str]],
                 schedule: Optional[dict] = None):
        self.__wrapped__ = fn
        self._name = name
        self._trace_lines = trace_lines
        self._schedule = schedule
        self.__name__ = f"watchdog[{name}]"

    def __call__(self, *args, **kwargs):
        if active_timeout() is None:
            return self.__wrapped__(*args, **kwargs)
        return guard_call(self.__wrapped__, args, kwargs, fn_name=self._name,
                          trace_lines=self._trace_lines,
                          schedule=self._schedule)

    def __getattr__(self, item):
        return getattr(self.__wrapped__, item)

    def __repr__(self):
        return f"<watchdog-guarded {self.__wrapped__!r}>"


def wrap(fn: Callable, *, fn_name: Optional[str] = None,
         trace_lines: Optional[Sequence[str]] = None,
         schedule: Optional[dict] = None) -> Callable:
    """A callable that routes through :func:`guard_call` when the watchdog
    is armed at call time and is a plain passthrough otherwise — dispatch
    sites wrap once at build time and pay one probe per call. Non-call
    attribute access (``lower``, ``as_text``, ...) passes through to
    ``fn``. ``schedule`` is the certified per-axis collective order
    (``analysis.schedule.ScheduleCertificate.axis_labels()``) attached to
    any timeout diagnosis."""
    return _GuardedCallable(fn, fn_name or getattr(fn, "__name__", "?"),
                            trace_lines, schedule)


# =============================================================================
# SDC guard: cross-replica checksums
# =============================================================================


def array_crc32(arr) -> int:
    """crc32 over a host array's buffer (contiguity-normalized) — the one
    integrity checksum shared by the SDC replica guard and the tiered
    snapshot store (``resilience/snapshot.py``): both answer "are these the
    bytes we wrote?" with the same cheap C-speed code."""
    import numpy as np

    arr = np.asarray(arr)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    return zlib.crc32(arr)


def replica_checksums(state) -> dict:
    """Per-leaf, per-replica-group crc32 checksums of a pytree of (possibly
    sharded) jax Arrays.

    Shards with the same global index tuple on different devices are
    replicas of the same data and must agree bitwise; the checksum is crc32
    over each addressable shard's bytes (host-side, C-speed — the "cheap
    rolling checksum" of ISSUE 9). Returns
    ``{leaf_name: {group_index: {device_ordinal: crc}}}`` covering only
    leaves that actually have replicas."""
    import jax

    from thunder_tpu.core.pytree import tree_flatten

    flat, _ = tree_flatten(state)
    out: dict = {}
    for i, leaf in enumerate(flat):
        if not isinstance(leaf, jax.Array) or leaf.size == 0:
            continue
        try:
            shards = list(leaf.addressable_shards)
        except Exception:
            continue
        if len(shards) < 2:
            continue
        # Group by global index FIRST and checksum only groups with >1
        # device: a fully-sharded leaf (every device holds a distinct
        # shard) has no replicas to cross-check, and skipping it skips the
        # device→host readback entirely — on an fsdp×tp mesh that is most
        # of the parameter bytes.
        groups: dict = {}
        for sh in shards:
            groups.setdefault(str(sh.index), []).append(sh)
        replicated = {}
        for idx, members in groups.items():
            if len(members) < 2:
                continue
            per_dev = {}
            for sh in members:
                # crc32 reads the array's buffer directly — no tobytes copy.
                per_dev[sh.device.id] = array_crc32(sh.data)
            replicated[idx] = per_dev
        if replicated:
            out[f"leaf{i}"] = replicated
    return out


def divergent_leaves(checksums: dict) -> dict:
    """``{leaf: {group_index: {device: crc}}}`` restricted to groups whose
    replicas disagree — empty means the state is replica-consistent."""
    bad: dict = {}
    for leaf, groups in checksums.items():
        for idx, per_dev in groups.items():
            if len(set(per_dev.values())) > 1:
                bad.setdefault(leaf, {})[idx] = dict(per_dev)
    return bad


def suspect_devices(divergence: dict) -> list:
    """Minority devices per divergent group — the corrupted replicas (ties
    report every device in the group)."""
    suspects: list = []
    for groups in divergence.values():
        for per_dev in groups.values():
            counts: dict = {}
            for crc in per_dev.values():
                counts[crc] = counts.get(crc, 0) + 1
            majority = max(counts.values())
            if majority == min(counts.values()):
                suspects.extend(per_dev)  # even split: all suspect
            else:
                suspects.extend(
                    d for d, crc in per_dev.items() if counts[crc] < majority
                )
    return sorted(set(suspects))


@dataclass
class SDCGuard:
    """Opt-in per-step silent-data-corruption guard for
    :func:`~thunder_tpu.resilience.preemption.run_training`.

    ``check_every`` thins the checksum to every Nth step (the check costs a
    host readback of every replicated shard); ``max_reruns`` bounds the
    quarantine re-runs per divergent step; ``loss_spike_factor`` arms the
    gradient-norm heuristic — a finite loss larger than ``factor`` × the
    rolling median of the last ``history`` losses is treated as an SDC
    suspect too (catches corruption in non-replicated shards the checksum
    cannot cross-check)."""

    check_every: int = 1
    max_reruns: int = 1
    loss_spike_factor: Optional[float] = None
    history: int = 8
    _losses: list = field(default_factory=list, repr=False)

    def due(self, step: int) -> bool:
        return self.check_every > 0 and step % self.check_every == 0

    def check_state(self, state) -> dict:
        """Divergence report for ``state`` (empty dict = consistent)."""
        return divergent_leaves(replica_checksums(state))

    def loss_suspect(self, loss) -> bool:
        """Rolling-median spike heuristic over scalar losses (see class
        docstring); also trips on non-finite losses. Feeds the same
        quarantine + re-run path as a checksum divergence."""
        if self.loss_spike_factor is None:
            return False
        import math

        try:
            v = float(loss)
        except (TypeError, ValueError):
            return False
        if not math.isfinite(v):
            return True
        prior = sorted(abs(x) for x in self._losses[-self.history:])
        median = prior[len(prior) // 2] if len(prior) >= 3 else 0.0
        spike = median > 0 and abs(v) > self.loss_spike_factor * median
        if not spike:
            self._losses.append(v)  # a suspect loss must not skew the median
        return spike


def resolve_sdc_guard(value) -> Optional[SDCGuard]:
    """Normalize a ``run_training(sdc_guard=...)`` value: None/False off,
    True → default :class:`SDCGuard`, or a configured instance."""
    if not value:
        return None
    if value is True:
        return SDCGuard()
    if isinstance(value, SDCGuard):
        return value
    raise TypeError(f"sdc_guard must be bool or SDCGuard, got {type(value).__name__}")
