"""Elastic resharded resume: continue a training run on a DIFFERENT mesh.

Losing a host on a preemptible TPU fleet shrinks the device set; the run
must continue on what survives instead of waiting for a replacement
(Gemma-on-Cloud-TPU operational comparison, PAPERS.md). The ingredients:

- :class:`~thunder_tpu.resilience.preemption.CheckpointManager` records the
  **mesh shape** (``parallel.mesh.axis_sizes``) in each step's META commit
  marker (``save(mesh=...)``);
- :func:`elastic_resume` restores the newest complete checkpoint and, when
  the target mesh's shape differs from the saved one, **reshards** the
  params/optimizer pytree through its PartitionSpec pytree
  (``parallel.sharding.reshard_pytree`` host path here; the Orbax restore
  path in ``distributed/checkpoint.load(mesh=..., specs=...)`` reads only
  the byte ranges each surviving device needs at scale);
- the caller rebuilds its step function for the surviving mesh
  (``parallel.build_train_step``) and continues from the restored step.

Numerics caveat (documented, asserted in tests): resharding is bitwise —
gather + device_put never touches values — but the *continued run* on a
different mesh shape reduces grads/loss in a different order (XLA reduction
trees follow the partitioning), so the post-resume loss trajectory matches
the uninterrupted one to float tolerance, not bitwise. Resuming onto the
SAME mesh shape stays bitwise (that path is PR 6's
``tests/test_resilience.py::TestPreemption``).
"""

from __future__ import annotations

from typing import Any, Optional

from thunder_tpu.observability import events as obs_events
from thunder_tpu.observability import metrics as obsm
from thunder_tpu.resilience.preemption import CheckpointManager


def mesh_shape(mesh) -> Optional[dict]:
    """``{axis: size}`` of a mesh, or None — the shape record checkpoints
    carry and the resume path compares."""
    if mesh is None:
        return None
    from thunder_tpu.parallel.mesh import axis_sizes

    return axis_sizes(mesh)


def reshard_state(state: Any, mesh, specs) -> Any:
    """Re-lay-out a state pytree onto ``mesh`` per its PartitionSpec pytree
    (bitwise: only the layout changes)."""
    from thunder_tpu.parallel.sharding import reshard_pytree

    return reshard_pytree(state, mesh, specs)


def elastic_resume(
    manager: CheckpointManager,
    init_state: Any,
    *,
    mesh=None,
    specs=None,
) -> tuple[Any, int]:
    """(state, start_step) like
    :func:`~thunder_tpu.resilience.preemption.resume`, but landing the
    restored state on ``mesh`` (per ``specs``, a PartitionSpec pytree
    matching the state structure) even when the checkpoint was written by a
    different mesh shape — the surviving-devices path after a host loss.

    Emits an ``elastic_resume`` event recording the saved → target shape
    and bumps ``thunder_tpu_elastic_resumes_total`` when an actual reshard
    happened. With no checkpoint on disk, returns ``(init_state, 0)``
    (``init_state`` is resharded too when it isn't already laid out on
    ``mesh`` — a fresh elastic start is just a reshard from nothing)."""
    if manager.latest_complete_step() is None:
        if mesh is not None and specs is not None:
            init_state = reshard_state(init_state, mesh, specs)
        return init_state, 0

    state, meta = manager.restore()
    saved_shape = meta.get("mesh")
    target_shape = mesh_shape(mesh)
    resharded = False
    if mesh is not None and specs is not None:
        # Restored leaves are host arrays (pickle fallback) or arrays on the
        # saving mesh (Orbax) — either way, land them on the target layout.
        state = reshard_state(state, mesh, specs)
        resharded = saved_shape is not None and saved_shape != target_shape
    obs_events.emit_event(
        "elastic_resume",
        step=int(meta["step"]),
        from_mesh=saved_shape,
        to_mesh=target_shape,
        resharded=resharded,
    )
    if resharded and obsm.enabled():
        obsm.ELASTIC_RESUMES.inc()
    if meta.get("rng_seed") is not None:
        from thunder_tpu import api

        api._global_rng["seed"] = int(meta["rng_seed"])
    return state, int(meta["step"])
