"""Elastic resharded resume: continue a training run on a DIFFERENT mesh.

Losing a host on a preemptible TPU fleet shrinks the device set; the run
must continue on what survives instead of waiting for a replacement
(Gemma-on-Cloud-TPU operational comparison, PAPERS.md). The ingredients:

- :class:`~thunder_tpu.resilience.preemption.CheckpointManager` records the
  **mesh shape** (``parallel.mesh.axis_sizes``) in each step's META commit
  marker (``save(mesh=...)``);
- :func:`elastic_resume` restores the newest complete checkpoint and, when
  the target mesh's shape differs from the saved one, **reshards** the
  params/optimizer pytree through its PartitionSpec pytree
  (``parallel.sharding.reshard_pytree`` host path here; the Orbax restore
  path in ``distributed/checkpoint.load(mesh=..., specs=...)`` reads only
  the byte ranges each surviving device needs at scale);
- the caller rebuilds its step function for the surviving mesh
  (``parallel.build_train_step``) and continues from the restored step;
- restores are TIERED (ISSUE 14): :func:`tiered_restore` picks the newest
  valid state across local RAM → buddy-replicated peer RAM → disk
  (``resilience/snapshot.SnapshotStore`` attached to the manager),
  checksum-validating each tier and falling through on mismatch — the
  common recovery is a host-memory read, not a disk round-trip, and every
  ``elastic_resume`` event names its winning ``tier``.

Numerics caveat (documented, asserted in tests): resharding is bitwise —
gather + device_put never touches values — but the *continued run* on a
different mesh shape reduces grads/loss in a different order (XLA reduction
trees follow the partitioning), so the post-resume loss trajectory matches
the uninterrupted one to float tolerance, not bitwise. Resuming onto the
SAME mesh shape stays bitwise (that path is PR 6's
``tests/test_resilience.py::TestPreemption``).
"""

from __future__ import annotations

from typing import Any, Optional

from thunder_tpu.observability import events as obs_events
from thunder_tpu.observability import metrics as obsm
from thunder_tpu.resilience import chaos
from thunder_tpu.resilience.preemption import (
    CheckpointManager,
    CheckpointRestoreError,
)


def mesh_shape(mesh) -> Optional[dict]:
    """``{axis: size}`` of a mesh, or None — the shape record checkpoints
    carry and the resume path compares."""
    if mesh is None:
        return None
    from thunder_tpu.parallel.mesh import axis_sizes

    return axis_sizes(mesh)


def reshard_state(state: Any, mesh, specs) -> Any:
    """Re-lay-out a state pytree onto ``mesh`` per its PartitionSpec pytree
    (bitwise: only the layout changes)."""
    from thunder_tpu.parallel.sharding import reshard_pytree

    return reshard_pytree(state, mesh, specs)


def tiered_restore(manager: CheckpointManager) -> tuple[Any, dict, str, list]:
    """The tier ladder (ISSUE 14): pick the NEWEST valid state across
    local RAM → peer RAM → disk, checksum-validating each RAM candidate and
    falling through on mismatch/absence. Returns
    ``(state, meta, tier, tried)`` where ``tier`` names the winning tier
    and ``tried`` lists the ``tier@step`` candidates that failed
    validation before it.

    Candidates are ordered newest-step-first with the cheaper tier winning
    ties (a local snapshot and its buddy replica carry the same step; the
    local copy needs no fetch). Disk joins the ladder at its newest
    complete step and uses :meth:`CheckpointManager.restore`'s own
    incomplete/corrupt fall-through below that. The chaos ``snap_corrupt``
    seam fires here — before validation — so a corrupted replica is
    exactly what the checksum gate must catch. Every outcome is a
    ``restore`` event (``tier``, ``ok``, ``tried``); raises
    :class:`~thunder_tpu.resilience.preemption.CheckpointRestoreError` when
    every tier is exhausted."""
    store = getattr(manager, "store", None)
    if hasattr(manager, "drain"):
        # Quiesce the background writer before reading the directory: a
        # restore racing an in-flight flush's rmtree/rename/GC could see a
        # "complete" step vanish mid-scan. (The queued snapshot, if any,
        # stays in RAM — it is one of the candidates below anyway.)
        manager.drain()
    chaos.snapshot_corrupt_seam(store)
    candidates: list = []
    if store is not None:
        for snap in store.local_snapshots():
            candidates.append((snap.step, 0, "local", snap))
        for snap in store.peer_snapshots():
            candidates.append((snap.step, 1, "peer", snap))
    disk_step = manager.latest_complete_step()
    if disk_step is not None:
        candidates.append((disk_step, 2, "disk", None))
    candidates.sort(key=lambda c: (-c[0], c[1]))
    tried: list = []
    for step, _, tier, snap in candidates:
        if tier == "disk":
            try:
                state, meta = manager.restore()
            except CheckpointRestoreError as e:
                obs_events.emit_event(
                    "restore", step=int(step), tier="disk", ok=False,
                    tried=list(tried), reason=str(e),
                )
                tried.append(f"disk@{step}")
                continue
        else:
            if not snap.verify():
                # The SDC-guard crc caught a rotted/corrupted snapshot:
                # fall through to the next tier instead of resuming from
                # poison (the snap_corrupt chaos seam's recovery).
                obs_events.emit_event(
                    "restore", step=int(step), tier=tier, ok=False,
                    reason="checksum mismatch",
                )
                tried.append(f"{tier}@{step}")
                continue
            state = snap.state
            meta = {"step": snap.step, "rng_seed": snap.rng_seed,
                    "mesh": snap.mesh}
        obs_events.emit_event(
            "restore", step=int(meta["step"]), tier=tier, ok=True,
            tried=list(tried),
        )
        if obsm.enabled():
            obsm.RESTORES.inc(tier=tier)
        return state, meta, tier, tried
    raise CheckpointRestoreError(
        f"no valid state in any tier under {manager.directory!r} "
        f"(tried {tried or 'nothing'})"
    )


def elastic_resume(
    manager: CheckpointManager,
    init_state: Any,
    *,
    mesh=None,
    specs=None,
) -> tuple[Any, int]:
    """(state, start_step) like
    :func:`~thunder_tpu.resilience.preemption.resume`, but landing the
    restored state on ``mesh`` (per ``specs``, a PartitionSpec pytree
    matching the state structure) even when the checkpoint was written by a
    different mesh shape — the surviving-devices path after a host loss.

    The restore is TIERED (:func:`tiered_restore`): the newest valid state
    wins across local RAM → peer RAM → disk, so an in-process recovery is
    a host-memory read instead of a disk round-trip and loses at most the
    snapshot cadence of steps. The ``elastic_resume`` event names the
    winning ``tier`` (the ISSUE 14 acceptance invariant) alongside the
    saved → target shape; ``thunder_tpu_elastic_resumes_total`` bumps when
    an actual reshard happened. Fresh-start semantics match the pre-tier
    behavior: with no COMPLETE disk step and nothing VALID in RAM, returns
    ``(init_state, 0)`` (``init_state`` is resharded too when it isn't
    already laid out on ``mesh`` — a fresh elastic start is just a reshard
    from nothing; invalid RAM snapshots count as absent here), while a
    disk step that exists but fails to load still raises — corruption of a
    real checkpoint must stay loud."""
    def _fresh_start():
        nonlocal init_state
        if mesh is not None and specs is not None:
            init_state = reshard_state(init_state, mesh, specs)
        return init_state, 0

    store = getattr(manager, "store", None)
    # Captured BEFORE the restore attempt: a failing disk restore
    # quarantines the steps it rejects, so asking afterwards would make
    # corrupted-durable-state indistinguishable from never-had-any.
    had_disk = manager.latest_complete_step() is not None
    if not had_disk and not (store is not None and store.has_snapshots()):
        return _fresh_start()

    try:
        state, meta, tier, _tried = tiered_restore(manager)
    except CheckpointRestoreError:
        if not had_disk:
            # Every RAM candidate failed its checksum and disk never had a
            # complete step: a run that has not yet committed anything
            # durable starts over cleanly instead of dying mid-recovery.
            return _fresh_start()
        raise
    saved_shape = meta.get("mesh")
    target_shape = mesh_shape(mesh)
    resharded = False
    if mesh is not None and specs is not None:
        # Restored leaves are host arrays (RAM snapshots, pickle fallback)
        # or arrays on the saving mesh (Orbax) — either way, land them on
        # the target layout.
        state = reshard_state(state, mesh, specs)
        resharded = saved_shape is not None and saved_shape != target_shape
    obs_events.emit_event(
        "elastic_resume",
        step=int(meta["step"]),
        from_mesh=saved_shape,
        to_mesh=target_shape,
        resharded=resharded,
        tier=tier,
    )
    if resharded and obsm.enabled():
        obsm.ELASTIC_RESUMES.inc()
    if meta.get("rng_seed") is not None:
        from thunder_tpu import api

        api._global_rng["seed"] = int(meta["rng_seed"])
    return state, int(meta["step"])
