"""Deterministic fault-injection harness (the chaos half of ISSUE 6).

Faults are injected at **named seams** — fixed points in the runtime where
production failures actually occur — so every recovery path (executor
demotion, the compile de-opt ladder, checkpoint retry, preemption sync) can
be exercised deterministically in CI instead of waiting for a TPU pod to
misbehave.

Seams and their typed errors:

=================  =====================================================
``kernel_raise``   claimed executor kernel raises at compile/first run
                   (:class:`InjectedKernelError`; recovery: demotion)
``compile_fail``   XLA compile failure (:class:`InjectedCompileError`;
                   recovery: de-opt ladder)
``compile_timeout`` XLA compile timeout (:class:`InjectedCompileTimeout`)
``oom``            device OOM at run (:class:`InjectedOOMError`, message
                   mimics ``RESOURCE_EXHAUSTED``; recovery: de-opt ladder)
``nan``            NaN-poisons a chosen BoundSymbol's output (a trace
                   pass; recovery: post-step isfinite guard + attribution)
``straggler``      collective straggler — sleeps ``~<delay>`` seconds at
                   the dispatch seam (recovery: none needed, run completes)
``ckpt_io``        checkpoint-write I/O error
                   (:class:`InjectedCheckpointError`; recovery: retry/
                   backoff in :class:`~.preemption.CheckpointManager`)
``preempt``        preemption signal at a chosen training step (recovery:
                   step-boundary checkpoint + resume)
``cache_corrupt``  truncates a persistent compile-cache entry (recovery:
                   :mod:`~.compile_cache` sweep)
``collective_hang`` a peer stops participating in a collective — sleeps
                   ``~<delay>`` seconds inside the watchdog-guarded
                   dispatch (recovery: :mod:`~.watchdog` raises a typed
                   :class:`~.watchdog.CollectiveTimeoutError`)
``host_loss``      a host dies at a chosen training step (recovery:
                   step-boundary checkpoint agreement + elastic resume on
                   the surviving mesh, :mod:`~.elastic`)
``sdc``            silent data corruption — flips one mantissa bit in one
                   data-parallel replica's shard of the training state
                   (recovery: the SDC replica-checksum guard quarantines
                   and re-runs the step, :class:`~.watchdog.SDCGuard`)
``snap_torn``      torn write on the background checkpoint flush — the
                   step directory lands WITHOUT its META commit marker
                   (recovery: restore skips the incomplete step; the
                   writer keeps flushing later steps)
``snap_corrupt``   flips one bit in the newest RAM-tier snapshot
                   (``@local`` / ``@peer`` / ``@local,peer``; recovery:
                   the tiered restore's checksum gate falls through to
                   the next tier, :mod:`~.snapshot`)
``snap_slow``      slow background flush — sleeps ``~<delay>`` seconds
                   inside the writer thread (recovery: the flush still
                   commits; backpressure coalesces queued snapshots)
``slice_loss``     a whole ICI slice dies at a chosen training step
                   (``slice=N`` clause picks the victim; recovery: the
                   fleet controller shrinks the DP group and restores the
                   lost replica's state from the cross-slice buddy
                   peer-RAM tier, :mod:`~.federation`)
``dcn_partition``  the DCN tier partitions at a chosen step — cross-slice
                   snapshot replication is severed until healed (recovery:
                   training continues in-slice; replication resumes when
                   the partition heals)
``slice_slow``     one slice's step time inflates by ``~<delay>`` seconds
                   (``slice=N`` picks it; recovery: none required — the
                   cross-slice spread detector must flag the outlier
                   before any watchdog would)
``slice_flap``     a slice enters a fail/recover loop faster than the
                   rejoin hysteresis window (recovery: the fleet
                   controller degrades ONCE — one shrink, one deferred
                   regrow after the backoff clears — instead of thrashing)
=================  =====================================================

Spec grammar (``THUNDER_TPU_CHAOS=<spec>`` or ``jit(chaos=<spec>)``)::

    spec      := component (";" component)*
    component := "seed=" INT
               | seam ["@" target] ["*" count] ["%" prob] ["~" delay_s]
    target    := clause ("," clause)*
    clause    := "host=" INT | "slice=" INT | <seam-specific target>
    count     := INT | "inf"          (default 1: fire once, then disarm)
    prob      := FLOAT in (0, 1]      (default 1.0; drawn from the seeded RNG)
    delay_s   := FLOAT                (straggler sleep seconds, default 0.01)

``target`` is seam-specific: for ``kernel_raise`` an executor name or
``executor:op`` substring; for ``nan`` a BoundSymbol-name substring or
``L<index>``; for ``preempt``/``host_loss`` the step number; for ``sdc``
the replica ordinal to corrupt; for ``oom`` an optional ``<LEVEL`` clause
(``oom@<3*inf``) that keeps firing while the entry's de-opt ladder level
is below LEVEL — a deterministic memory ceiling for exercising the
planner-guided ladder (resilience/deopt.py). A ``host=N`` clause restricts any seam to
the process with ``jax.process_index() == N`` (multi-host targeting; the
``THUNDER_TPU_CHAOS_PROCESS_INDEX`` env var overrides the index for
single-process simulation and tests). Examples::

    THUNDER_TPU_CHAOS="kernel_raise@flash*1"
    THUNDER_TPU_CHAOS="oom*2;seed=7"
    THUNDER_TPU_CHAOS="nan@tanh;preempt@3"
    THUNDER_TPU_CHAOS="collective_hang@host=2~30;seed=5"
    THUNDER_TPU_CHAOS="host_loss@3,host=1"

Every injection emits a ``fault_injected`` JSONL event and increments
``thunder_tpu_faults_injected_total{seam=...}``. Injection decisions are
deterministic given the spec (counts + seeded RNG): the same spec replays
the same fault schedule. The probability RNG is seeded from the full
``(seed, slice_id, host_id)`` coordinate via a stable hash, so every host
of a federated multi-process job draws an independent — but individually
replayable — stream. Hashing the coordinate (rather than summing into the
seed) keeps schedules collision-free as the fleet shrinks and regrows:
``seed + process_index()`` made host 3 of a 4-host fleet replay host 2's
schedule after a shrink renumbered it, which is exactly the
non-reproducibility a federated chaos soak cannot tolerate.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import time
from dataclasses import dataclass, field
from typing import Optional

from thunder_tpu.observability import events as obs_events
from thunder_tpu.observability import metrics as obsm

SEAMS = (
    "kernel_raise", "compile_fail", "compile_timeout", "oom", "nan",
    "straggler", "ckpt_io", "preempt", "cache_corrupt",
    "collective_hang", "host_loss", "sdc", "sched_bad",
    "snap_torn", "snap_corrupt", "snap_slow",
    "slice_loss", "dcn_partition", "slice_slow", "slice_flap",
)


def process_index() -> int:
    """This process's mesh-wide index: ``THUNDER_TPU_CHAOS_PROCESS_INDEX``
    when set (single-process multi-host simulation, tests), else
    ``jax.process_index()`` from an already-initialized backend, else 0.
    Chaos must never be the thing that initializes the jax backend."""
    env = os.environ.get("THUNDER_TPU_CHAOS_PROCESS_INDEX", "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    import sys

    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            if jax_mod._src.xla_bridge._backends:  # type: ignore[attr-defined]
                return int(jax_mod.process_index())
        except Exception:
            pass
    return 0


def slice_id() -> int:
    """This process's slice in a federated fleet: ``THUNDER_TPU_SLICE_ID``
    when set (the federation driver and single-process emulation set it),
    else 0 — a plain single-slice job is slice 0 of a one-slice fleet."""
    env = os.environ.get("THUNDER_TPU_SLICE_ID", "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    return 0


def _derive_seed(seed: int, slice_: int, host: int) -> int:
    """Stable per-process RNG seed from the ``(seed, slice, host)``
    coordinate. A keyed hash, not arithmetic: ``seed + host`` collides when
    the fleet renumbers hosts after a shrink (host 3's old schedule becomes
    host 2's new one), and Python's ``hash()`` is per-process randomized
    for strings — neither replays."""
    import hashlib

    h = hashlib.blake2s(f"{seed}:{slice_}:{host}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class ChaosError(RuntimeError):
    """Base of every chaos-injected error. ``seam`` names the injection
    point so an unrecovered fault fails loudly with its origin."""

    seam = "unknown"

    def __init__(self, msg: str, *, target: Optional[str] = None):
        self.target = target
        super().__init__(msg)


class InjectedKernelError(ChaosError):
    """A claimed executor kernel raised (chaos seam ``kernel_raise``)."""

    seam = "kernel_raise"

    def __init__(self, executor: str, op: str):
        self.executor = executor
        self.op = op
        super().__init__(
            f"chaos[kernel_raise]: injected kernel failure in executor "
            f"{executor!r} op {op!r}",
            target=f"{executor}:{op}",
        )


class InjectedCompileError(ChaosError):
    seam = "compile_fail"

    def __init__(self, fn_name: str = "?"):
        super().__init__(
            f"chaos[compile_fail]: injected XLA compile failure for {fn_name!r}",
            target=fn_name,
        )


class InjectedCompileTimeout(InjectedCompileError):
    seam = "compile_timeout"

    def __init__(self, fn_name: str = "?"):
        ChaosError.__init__(
            self,
            f"chaos[compile_timeout]: injected XLA compile timeout for {fn_name!r}",
            target=fn_name,
        )


class InjectedOOMError(ChaosError):
    seam = "oom"

    def __init__(self):
        super().__init__(
            "chaos[oom]: RESOURCE_EXHAUSTED: injected device out-of-memory"
        )


class InjectedCheckpointError(OSError):
    """Transient checkpoint-write I/O failure (chaos seam ``ckpt_io``).
    An OSError so the checkpoint retry path treats it like a real disk/
    network write error."""

    seam = "ckpt_io"

    def __init__(self):
        super().__init__("chaos[ckpt_io]: injected checkpoint write I/O error")


@dataclass
class FaultRule:
    """One armed fault: fires up to ``count`` times with probability
    ``prob`` per opportunity (drawn from the config's seeded RNG)."""

    seam: str
    target: Optional[str] = None
    count: float = 1  # float so "inf" parses; compared against fired
    prob: float = 1.0
    delay_s: float = 0.01
    host: Optional[int] = None  # host=N clause: only this process fires
    slice: Optional[int] = None  # slice=N clause: the victim/targeted slice
    fired: int = 0

    def exhausted(self) -> bool:
        return self.fired >= self.count

    def matches(self, target: Optional[str]) -> bool:
        if self.target is None:
            return True
        if target is None:
            return False
        return self.target in str(target)

    def host_matches(self) -> bool:
        return self.host is None or self.host == process_index()


@dataclass
class ChaosConfig:
    """Parsed chaos spec: rules + the seeded RNG driving probability draws.

    The RNG is created lazily on first draw and seeded from the hashed
    ``(seed, slice_id(), process_index())`` coordinate: each host of a
    federated multi-process job gets its own replayable stream that stays
    collision-free across fleet shrink/regrow renumbering (laziness
    matters — specs parse before the jax backend knows the process
    index)."""

    rules: list = field(default_factory=list)
    seed: int = 0

    def __post_init__(self):
        self._rng: Optional[random.Random] = None

    @property
    def rng(self) -> random.Random:
        if self._rng is None:
            self._rng = random.Random(
                _derive_seed(self.seed, slice_id(), process_index())
            )
        return self._rng

    def rules_for(self, seam: str):
        return [r for r in self.rules if r.seam == seam]


def parse_spec(spec: str) -> ChaosConfig:
    """Parse the chaos spec grammar (module docstring) into a
    :class:`ChaosConfig`. Raises ``ValueError`` on unknown seams or
    malformed components — a chaos run with a typo'd spec must fail loudly,
    not silently inject nothing."""
    rules: list[FaultRule] = []
    seed = 0
    for comp in str(spec).split(";"):
        comp = comp.strip()
        if not comp:
            continue
        if comp.startswith("seed="):
            seed = int(comp[len("seed="):])
            continue
        rule = FaultRule(seam="")
        rest = comp
        # Peel *count / %prob / ~delay suffixes from the right, in whatever
        # order they were written.
        _attr = {"*": "count", "%": "prob", "~": "delay_s"}
        while True:
            pos = max(rest.rfind(sep) for sep in _attr)
            if pos <= 0:
                break
            sep = rest[pos]
            rest, val = rest[:pos], rest[pos + 1:].strip()
            if sep == "*":
                rule.count = float("inf") if val == "inf" else int(val)
            else:
                setattr(rule, _attr[sep], float(val))
        if "@" in rest:
            rest, _, target = rest.partition("@")
            # A target is a comma-list of clauses; "host=N" clauses restrict
            # the rule to that process, the remainder is the seam target.
            plain = []
            for clause in target.split(","):
                clause = clause.strip()
                if clause.startswith("host=") or clause.startswith("slice="):
                    attr, _, val = clause.partition("=")
                    try:
                        setattr(rule, attr, int(val))
                    except ValueError:
                        raise ValueError(
                            f"chaos spec: malformed {attr} clause {clause!r} "
                            f"in component {comp!r}"
                        ) from None
                elif clause:
                    plain.append(clause)
            rule.target = ",".join(plain) or None
        rule.seam = rest.strip()
        if rule.seam not in SEAMS:
            raise ValueError(
                f"chaos spec: unknown seam {rule.seam!r} in component {comp!r} "
                f"(known: {', '.join(SEAMS)})"
            )
        if not (0.0 < rule.prob <= 1.0):
            raise ValueError(f"chaos spec: prob must be in (0, 1], got {rule.prob}")
        rules.append(rule)
    return ChaosConfig(rules=rules, seed=seed)


# -- activation ----------------------------------------------------------------

_scope: contextvars.ContextVar[Optional[ChaosConfig]] = contextvars.ContextVar(
    "thunder_tpu_chaos", default=None
)
_env = {"resolved": False, "config": None}


def _env_config() -> Optional[ChaosConfig]:
    if not _env["resolved"]:
        spec = os.environ.get("THUNDER_TPU_CHAOS", "").strip()
        _env["config"] = parse_spec(spec) if spec else None
        _env["resolved"] = True
    return _env["config"]


def reset_env_config() -> None:
    """Re-read ``THUNDER_TPU_CHAOS`` on next use (tests)."""
    _env["resolved"] = False
    _env["config"] = None


def active() -> Optional[ChaosConfig]:
    cfg = _scope.get()
    if cfg is not None:
        return cfg
    return _env_config()


def enabled() -> bool:
    return active() is not None


@contextlib.contextmanager
def chaos_scope(config):
    """Activate a chaos config (spec string or :class:`ChaosConfig`) within
    the scope; ``None`` leaves the ambient config in place."""
    if config is None:
        yield None
        return
    if isinstance(config, str):
        config = parse_spec(config)
    tok = _scope.set(config)
    try:
        yield config
    finally:
        _scope.reset(tok)


def resolve(config) -> Optional[ChaosConfig]:
    """Normalize a ``jit(chaos=...)`` value (None | spec str | config)."""
    if config is None or isinstance(config, ChaosConfig):
        return config
    return parse_spec(str(config))


# -- injection core ------------------------------------------------------------


def _should_fire(seam: str, target: Optional[str] = None,
                 matcher=None) -> Optional[FaultRule]:
    """One copy of the fire-decision protocol (exhausted → match → host →
    prob draw → fired/record). ``matcher(rule) -> bool`` replaces the
    default substring ``rule.matches(target)`` for seams whose target
    grammar is not a substring (the oom ``<LEVEL`` ceiling)."""
    cfg = active()
    if cfg is None:
        return None
    for rule in cfg.rules_for(seam):
        if rule.exhausted() or not rule.host_matches():
            continue
        if not (matcher(rule) if matcher is not None else rule.matches(target)):
            continue
        if rule.prob < 1.0 and cfg.rng.random() >= rule.prob:
            continue
        rule.fired += 1
        _record(rule, target)
        return rule
    return None


def _record(rule: FaultRule, target: Optional[str]) -> None:
    if obsm.enabled():
        obsm.FAULTS_INJECTED.inc(seam=rule.seam)
    obs_events.emit_event(
        "fault_injected",
        seam=rule.seam,
        target=target if target is not None else rule.target,
        n=rule.fired,
    )


# -- seams ---------------------------------------------------------------------


def kernel_seam(executor: str, op: str) -> None:
    """Called at the top of kernel-executor impls (pallasex/flashex/
    quantex): raise :class:`InjectedKernelError` when an armed
    ``kernel_raise`` rule matches ``executor`` or ``executor:op``."""
    if active() is None:  # one-None-check fast path: chaos off costs nothing
        return
    if _should_fire("kernel_raise", f"{executor}:{op}") is not None:
        raise InjectedKernelError(executor, op)


def compile_seam(fn_name: str) -> None:
    """Compile-pipeline seam (api._compile_entry_checked): injected compile
    failure or timeout."""
    if active() is None:
        return
    if _should_fire("compile_timeout", fn_name) is not None:
        raise InjectedCompileTimeout(fn_name)
    if _should_fire("compile_fail", fn_name) is not None:
        raise InjectedCompileError(fn_name)


def run_seam(has_collectives: bool = False, deopt_level: int = 0) -> None:
    """Dispatch-time seam (api._run_entry): device OOM, and the collective
    straggler delay (fires on any entry when the rule's target is ``any``,
    else only on traces containing collectives).

    The ``oom`` seam's target grammar: ``oom`` (fire per its count, as
    before) or ``oom@<L`` — keep firing while the dispatched entry's de-opt
    ladder level is **below** L. The latter is a deterministic memory
    ceiling: exactly what a chip whose HBM only fits ladder level L looks
    like, which is how ``lint_traces.py --static`` proves the planner jump
    pays fewer failed compiles than blind climbing."""
    if active() is None:
        return

    def _oom_matches(rule: FaultRule) -> bool:
        t = rule.target
        if not t:
            return True
        if not t.startswith("<"):
            return False  # oom has no other target form
        try:
            return deopt_level < int(t[1:])
        except ValueError:
            return False

    if _should_fire("oom", f"level{deopt_level}", matcher=_oom_matches) is not None:
        raise InjectedOOMError()
    cfg = active()
    for rule in cfg.rules_for("straggler"):
        if rule.exhausted() or not rule.host_matches():
            continue
        if rule.target == "step":
            continue  # guarded-step-only rules fire in straggler_seam()
        if rule.target != "any" and not has_collectives:
            continue
        if rule.prob < 1.0 and cfg.rng.random() >= rule.prob:
            continue
        rule.fired += 1
        _record(rule, rule.target)
        time.sleep(rule.delay_s)


def straggler_seam(site: str = "step") -> None:
    """Step-path straggler delay (watchdog.guard_call's worker body): an
    armed ``straggler@step`` rule sleeps ``~<delay>`` seconds inside the
    guarded step — a host slowing down WITHOUT hanging, the drift the
    streaming detectors (observability/detect.py) must flag before the
    watchdog's timeout would. Rules targeting ``any`` (or untargeted) fire
    here too; the dispatch-path straggler in :func:`run_seam` ignores the
    ``step`` target, so the two sites never double-fire a targeted rule."""
    cfg = active()
    if cfg is None:
        return
    for rule in cfg.rules_for("straggler"):
        if rule.exhausted() or not rule.host_matches():
            continue
        if rule.target not in (None, "any", site):
            continue
        if rule.prob < 1.0 and cfg.rng.random() >= rule.prob:
            continue
        rule.fired += 1
        _record(rule, site)
        time.sleep(rule.delay_s)


def sched_seam(site_key: str, placement: int, latest: int) -> int:
    """Comm-scheduler seam (transforms/comm_schedule.py): when an armed
    ``sched_bad`` rule matches the collective site, corrupt the computed
    placement to one past the site's certified ``latest`` — the scheduler's
    own interval validation must catch it and fall back to the unscheduled
    trace (a bad schedule demotes cleanly instead of compiling a potential
    cross-host deadlock). Returns ``placement`` unchanged when not armed."""
    if active() is None:
        return placement
    if _should_fire("sched_bad", site_key) is not None:
        return latest + 8
    return placement


def checkpoint_seam() -> None:
    """Checkpoint-write seam (resilience.preemption.CheckpointManager)."""
    if active() is None:
        return
    if _should_fire("ckpt_io") is not None:
        raise InjectedCheckpointError()


def flush_slow_seam() -> None:
    """Background-flush seam (CheckpointManager's writer thread): an armed
    ``snap_slow`` rule sleeps ``~<delay>`` seconds inside the flush — a
    slow disk or contended network FS. The training loop must not stall
    (the flush is off the hot path) and the single-in-flight backpressure
    must coalesce snapshots queued behind the slow write instead of growing
    an unbounded backlog."""
    if active() is None:
        return
    rule = _should_fire("snap_slow")
    if rule is not None:
        time.sleep(rule.delay_s)


def flush_torn_seam() -> bool:
    """Background-flush seam: True when an armed ``snap_torn`` rule fires —
    the flush must simulate a writer crash between the state write and the
    META commit marker (a step directory in place WITHOUT its marker, the
    torn write the commit protocol exists to catch). The restore path must
    skip the incomplete step and fall through to the next tier/step."""
    if active() is None:
        return False
    return _should_fire("snap_torn") is not None


def snapshot_corrupt_seam(store) -> None:
    """Restore-time seam (the tiered restore in ``resilience/elastic``):
    an armed ``snap_corrupt`` rule flips one bit in the newest snapshot of
    the targeted RAM tier — ``@local``, ``@peer`` (default), or
    ``@local,peer`` for both — before the tiers are validated, so the
    checksum gate must catch it and fall through. A rule that finds
    nothing to corrupt (empty tier) stays armed rather than recording an
    injection that never happened (the cache_corrupt discipline)."""
    cfg = active()
    if cfg is None or store is None:
        return
    for rule in cfg.rules_for("snap_corrupt"):
        if rule.exhausted() or not rule.host_matches():
            continue
        tiers = [t.strip() for t in (rule.target or "peer").split(",")
                 if t.strip() in ("local", "peer")] or ["peer"]
        if rule.prob < 1.0 and cfg.rng.random() >= rule.prob:
            continue
        corrupted = [t for t in tiers if store.corrupt_newest(t)]
        if not corrupted:
            continue
        rule.fired += 1
        _record(rule, ",".join(corrupted))


def preempt_at_step(step: int) -> bool:
    """Training-loop seam: True when an armed ``preempt`` rule targets this
    step (exact match — ``preempt@3`` must not also fire at step 13) or has
    no target. The caller treats it exactly like a SIGTERM."""
    return _step_seam_fires("preempt", step)


def host_loss_at_step(step: int) -> bool:
    """Training-loop seam: True when an armed ``host_loss`` rule targets
    this step (or has no step target). The caller checkpoints at the step
    boundary and raises :class:`~.preemption.HostLost` — the surviving
    processes' elastic-resume path (``resilience/elastic.py``) continues on
    a shrunk mesh from that agreed checkpoint."""
    return _step_seam_fires("host_loss", step)


def _step_seam_fires(seam: str, step: int) -> bool:
    cfg = active()
    if cfg is None:
        return False
    for rule in cfg.rules_for(seam):
        if rule.exhausted() or not rule.host_matches():
            continue
        if rule.target is not None and rule.target != str(step):
            continue
        if rule.prob < 1.0 and cfg.rng.random() >= rule.prob:
            continue
        rule.fired += 1
        _record(rule, str(step))
        return True
    return False


# -- slice-granular seams (federated fleets, resilience/federation.py) ---------


def _slice_step_seam(seam: str, step: int) -> Optional[int]:
    """Exact-step slice seam: the victim slice id when an armed rule fires
    at ``step`` (``slice=N`` clause, default slice 0), else None."""
    cfg = active()
    if cfg is None:
        return None
    for rule in cfg.rules_for(seam):
        if rule.exhausted() or not rule.host_matches():
            continue
        if rule.target is not None and rule.target != str(step):
            continue
        if rule.prob < 1.0 and cfg.rng.random() >= rule.prob:
            continue
        rule.fired += 1
        victim = rule.slice if rule.slice is not None else 0
        _record(rule, f"step{step}:slice{victim}")
        return victim
    return None


def slice_loss_at_step(step: int) -> Optional[int]:
    """Federated training-loop seam: the slice id an armed ``slice_loss``
    rule kills at this step (``slice_loss@3,slice=1``), or None. The fleet
    controller (``resilience/federation.py``) shrinks the DP group, rescales
    gradient accumulation, and restores the lost replica's contribution
    from the victim's cross-slice buddy peer-RAM snapshot."""
    return _slice_step_seam("slice_loss", step)


def slice_flap_at_step(step: int) -> Optional[int]:
    """Federated training-loop seam: the slice id an armed ``slice_flap``
    rule starts flapping at this step — the driver runs it through a
    fail/recover loop faster than the rejoin hysteresis window, and the
    fleet controller must degrade ONCE (one shrink, one deferred regrow)."""
    return _slice_step_seam("slice_flap", step)


def dcn_partition_at_step(step: int) -> Optional[FaultRule]:
    """Federated training-loop seam: the armed ``dcn_partition`` rule firing
    at this step (exact-step target), else None. The caller severs
    cross-slice snapshot replication (``SnapshotStore.partitioned``) and
    heals it after the rule's ``~<delay>`` seconds — or at its own healing
    boundary — while training continues in-slice."""
    cfg = active()
    if cfg is None:
        return None
    for rule in cfg.rules_for("dcn_partition"):
        if rule.exhausted() or not rule.host_matches():
            continue
        if rule.target is not None and rule.target != str(step):
            continue
        if rule.prob < 1.0 and cfg.rng.random() >= rule.prob:
            continue
        rule.fired += 1
        _record(rule, str(step))
        return rule
    return None


def slice_slow_delay(slice_: int) -> float:
    """Federated step-path seam: seconds slice ``slice_``'s step inflates by
    when an armed ``slice_slow`` rule targets it (``slice=N`` clause;
    untargeted rules slow every slice they're asked about). The cross-slice
    step-time spread detector (observability/detect.py) must flag the
    outlier slice from exactly this drift."""
    cfg = active()
    if cfg is None:
        return 0.0
    total = 0.0
    for rule in cfg.rules_for("slice_slow"):
        if rule.exhausted() or not rule.host_matches():
            continue
        if rule.slice is not None and rule.slice != slice_:
            continue
        if rule.prob < 1.0 and cfg.rng.random() >= rule.prob:
            continue
        rule.fired += 1
        _record(rule, f"slice{slice_}")
        total += rule.delay_s
    return total


def collective_hang_seam() -> None:
    """Collective-dispatch seam, called INSIDE the watchdog-guarded call
    (``resilience/watchdog.guard_call``): an armed ``collective_hang`` rule
    sleeps ``~<delay>`` seconds — a peer that stopped participating, from
    this process's point of view — so a delay longer than the watchdog
    timeout exercises the typed-timeout path end to end."""
    if active() is None:
        return
    rule = _should_fire("collective_hang")
    if rule is not None:
        time.sleep(rule.delay_s)


def corrupt_cache_seam(cache_dir: str) -> Optional[str]:
    """Truncate one persistent-cache entry to zero bytes (the crash/disk-full
    corruption mode the sweep repairs). Returns the corrupted path."""
    if active() is None:
        return None
    from thunder_tpu.resilience.compile_cache import _entry_files

    # Check there IS something to corrupt before consuming the rule:
    # firing (and recording fault_injected) on an empty cache dir would
    # disarm the rule with no injection and leave an unrecoverable-looking
    # fault event in the log.
    entries = _entry_files(cache_dir)
    if not entries:
        return None
    if _should_fire("cache_corrupt", cache_dir) is None:
        return None
    victim = entries[0]
    with open(victim, "w"):
        pass  # truncate
    return victim


# -- silent-data-corruption seam -----------------------------------------------


def maybe_corrupt_replica(state):
    """When an armed ``sdc`` rule fires, flip one mantissa bit in ONE
    data-parallel replica's shard of the first replicated leaf of ``state``
    (a pytree of jax Arrays) and rebuild the array from its per-device
    buffers — the replicas now disagree bitwise while the "official" value
    XLA would read is unchanged, which is exactly what a silent hardware
    corruption looks like. Returns the (possibly corrupted) state.

    The rule's target selects the replica ordinal to corrupt (default 1 —
    a non-primary copy, so at least one honest peer disagrees). Leaves with
    no replication (every device holds a distinct shard) cannot host a
    replica divergence and are skipped."""
    cfg = active()
    if cfg is None or not any(
        not r.exhausted() and r.host_matches() for r in cfg.rules_for("sdc")
    ):
        return state

    import jax
    import numpy as np

    from thunder_tpu.core.pytree import tree_flatten, tree_unflatten

    flat, spec = tree_flatten(state)
    for i, leaf in enumerate(flat):
        if not isinstance(leaf, jax.Array) or not leaf.shape or leaf.size == 0:
            continue
        if not np.issubdtype(np.dtype(leaf.dtype), np.floating):
            continue
        try:
            shards = list(leaf.addressable_shards)
        except Exception:
            continue
        groups: dict = {}
        for sh in shards:
            groups.setdefault(str(sh.index), []).append(sh)
        replicas = next((g for g in groups.values() if len(g) > 1), None)
        if replicas is None:
            continue
        # The sdc target is the replica ordinal, not a match filter, so rule
        # selection bypasses the generic substring matching.
        rule = None
        for r in cfg.rules_for("sdc"):
            if r.exhausted() or not r.host_matches():
                continue
            if r.prob < 1.0 and cfg.rng.random() >= r.prob:
                continue
            rule = r
            break
        if rule is None:
            return state
        rule.fired += 1
        _record(rule, f"leaf{i}")
        ordinal = int(rule.target) if rule.target and rule.target.isdigit() else 1
        victim = replicas[min(ordinal, len(replicas) - 1)]
        data = np.array(victim.data)  # host copy of the victim shard
        data.view(np.uint8).reshape(-1)[0] ^= 1  # mantissa LSB of element 0
        bufs = [
            jax.device_put(data if sh is victim else np.asarray(sh.data), sh.device)
            for sh in shards
        ]
        flat = list(flat)
        flat[i] = jax.make_array_from_single_device_arrays(
            leaf.shape, leaf.sharding, bufs
        )
        return tree_unflatten(spec, flat)
    return state


# -- NaN poisoning pass --------------------------------------------------------


def _poison_value(x):
    # Pure function of the tensor: stages fine under jax.jit and runs
    # eagerly under the instrumented re-run, so attribution lands here.
    return x * float("nan")


def maybe_poison_nan(extrace):
    """When an armed ``nan`` rule matches a BoundSymbol of ``extrace``
    (by name substring or ``L<index>``), insert a ``chaos_nan_poison`` op
    after it and rewrite downstream uses to consume the poisoned value.
    Runs after claiming so the poison survives into both the staged entry
    and the instrumented attribution re-run."""
    cfg = active()
    if cfg is None or not cfg.rules_for("nan"):
        return extrace

    from thunder_tpu.core import dtypes
    from thunder_tpu.core.proxies import TensorProxy, variableify
    from thunder_tpu.core.pytree import tree_flatten, tree_unflatten
    from thunder_tpu.core.symbol import Symbol
    from thunder_tpu.core.trace import from_trace, tracectx, wrap_in_trace_provenance

    target_idx = None
    target_out = None
    for i, bsym in enumerate(extrace.bound_symbols):
        outs = [
            o for o in bsym.flat_proxy_outs
            if isinstance(o, TensorProxy) and dtypes.is_inexact_dtype(o.dtype)
        ]
        if not outs:
            continue
        name_key = f"L{i}"
        rule = None
        for r in cfg.rules_for("nan"):
            if r.exhausted():
                continue
            if r.target is None or r.target == name_key or r.target in bsym.sym.name:
                rule = r
                break
        if rule is None:
            continue
        rule.fired += 1
        _record(rule, f"L{i}.{bsym.sym.name}")
        target_idx, target_out = i, outs[0]
        break
    if target_idx is None:
        return extrace

    start = time.perf_counter_ns()
    ntrace = from_trace(extrace)
    with tracectx(ntrace):
        poisoned = TensorProxy(like=target_out)
    poison_sym = Symbol(
        "chaos_nan_poison", meta=None, id="resilience.chaos_nan_poison",
        is_prim=True, python_impl=_poison_value,
    )
    swap = {variableify(target_out): poisoned}
    new_bsyms = []
    for i, bsym in enumerate(extrace.bound_symbols):
        if i <= target_idx:
            new_bsyms.append(bsym)
            if i == target_idx:
                new_bsyms.append(poison_sym.bind(target_out, output=poisoned))
        else:
            new_bsyms.append(bsym.from_bsym_swap_proxies(swap, skip_output=True))
    ntrace.bound_symbols = new_bsyms
    flat_out, spec = tree_flatten(ntrace.output)
    ntrace.output = tree_unflatten(
        spec, [swap.get(variableify(p), p) if isinstance(p, TensorProxy) else p
               for p in flat_out]
    )
    return wrap_in_trace_provenance(ntrace, "Chaos NaN poisoning", start)
