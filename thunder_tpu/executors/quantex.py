"""Quantized linear executor: int8 matmuls on the MXU.

Reference parity: the TransformerEngine FP8 executor seat
(thunder/executors/transformer_engineex.py:185 — `TELinear` with
amax/scale management via a stateful `Context:110`, `_linear_checker:376`,
fwd/bwd rules `:398,423`). TPU v5e/v5p have native int8 MXU throughput
(2× bf16), so the quantized dtype here is int8 with per-tensor activation
scales and per-output-channel weight scales; the backward runs in the
original dtype (straight-through), matching TE's "fp8 fwd,
higher-precision bwd" recipe.

**Why dynamic scales instead of TE's delayed amax history.** TE keeps a
rolling amax history because on GPU the exact amax reduction is a separate
kernel launch on the critical path; the history lets it reuse a stale scale
for free. On TPU the amax reduction fuses into the surrounding XLA program:
measured on v5e at (4096×3200)·(3200×3200), int8 matmul with in-graph
dynamic amax = 4.94 ms vs 4.96 ms with precomputed fixed scales — the
history's entire motivation costs nothing here, and the current-step exact
scale is strictly better numerically than a delayed one. (A host-fed
history is additionally impossible on this runtime: the axon PJRT backend
rejects io_callback/host send-recv.) The recipe below still exposes TE-style
knobs (margin, per-channel toggle).

Opt-in (not a default executor — it changes numerics):
    thunder_tpu.jit(fn, executors=["quant", "flash", "pallas", "jax"])
"""

from __future__ import annotations

from dataclasses import dataclass

from thunder_tpu.core.proxies import TensorProxy
from thunder_tpu.extend import OperatorExecutor, register_executor
from thunder_tpu.resilience import chaos

ex = OperatorExecutor("quant")
register_executor(ex)

_MIN_K = 64  # too-small contractions are not worth quantizing


@dataclass
class QuantRecipe:
    """TE-recipe analogue (reference: transformer_engineex.py `Context:110`
    + TE's DelayedScaling recipe): ``margin`` backs the scale off by
    2**margin (headroom against step-to-step amax growth — the role TE's
    history window plays), ``per_channel_weights`` selects row-wise weight
    scales vs one per-tensor scale.

    ``skip_out_features`` is the seat of TE's ``skip_modules`` / exclusion
    list: linears whose OUT dimension is listed stay in the original dtype.
    In a functional trace there are no module names at claim time, but the
    standard exclusion — the lm_head, whose out dim is the (padded) vocab
    size and whose logits feed the loss directly — is exactly a shape
    predicate. E.g. ``QuantRecipe(skip_out_features=(50304,))`` keeps
    pythia's lm_head in bf16."""

    margin: int = 0
    per_channel_weights: bool = True
    skip_out_features: tuple = ()

    @property
    def qmax(self) -> float:
        return 127.0 / (2.0 ** self.margin)


_recipe = QuantRecipe()


def set_recipe(recipe: QuantRecipe) -> None:
    """Install the quantization recipe. Takes effect at the next trace
    (compiled entries bake the recipe in — clear caches / re-jit to apply
    to an existing module)."""
    global _recipe
    _recipe = recipe


def get_recipe() -> QuantRecipe:
    return _recipe


from thunder_tpu.core import dtypes  # noqa: E402

_QUANTIZABLE = (dtypes.float32, dtypes.bfloat16, dtypes.float16)


def _linear_checker(a, w, bias=None) -> bool:
    if not (hasattr(a, "shape") and hasattr(w, "shape")):
        return False
    if len(w.shape) != 2 or w.shape[1] < _MIN_K:
        return False
    if int(w.shape[0]) in _recipe.skip_out_features:
        return False  # excluded layer class (e.g. lm_head) stays full-precision
    # Quantization only replaces standard float matmuls; f64 (precision
    # contract) and integer linears stay with the default executor.
    if getattr(a, "dtype", None) not in _QUANTIZABLE or getattr(w, "dtype", None) not in _QUANTIZABLE:
        return False
    return True


def _quantize_per_tensor(x, qmax):
    import jax.numpy as jnp

    amax = jnp.maximum(jnp.max(jnp.abs(x)), 1e-6)
    scale = amax / qmax
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _quantize_per_channel(w, qmax, per_channel=True):
    """Per-output-channel (row) scales for a (out, in) weight."""
    import jax.numpy as jnp

    if not per_channel:
        q, s = _quantize_per_tensor(w, qmax)
        return q, jnp.broadcast_to(s, (w.shape[0], 1))
    amax = jnp.maximum(jnp.max(jnp.abs(w), axis=1, keepdims=True), 1e-6)
    scale = amax / qmax
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale  # scale: (out, 1)


def _quant_linear_impl(a, w, bias=None):
    chaos.kernel_seam("quant", "linear")
    import jax.numpy as jnp
    from jax import lax

    r = _recipe
    orig_dtype = a.dtype
    af = a.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    qa, sa = _quantize_per_tensor(af, r.qmax)
    qw, sw = _quantize_per_channel(wf, r.qmax, r.per_channel_weights)

    # int8 × int8 → int32 on the MXU, then one rescale.
    acc = lax.dot_general(
        qa, qw, (((a.ndim - 1,), (1,)), ((), ())), preferred_element_type=jnp.int32
    )
    out = acc.astype(jnp.float32) * (sa * sw[:, 0])
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(orig_dtype)


# Backward note: autodiff decomposes `linear` before claiming, so the grad
# trace's matmuls fall to the default executor in the original dtype — TE's
# "int8/fp8 forward, higher-precision backward" recipe without a bespoke rule
# (reference: transformer_engineex.py:423).

from thunder_tpu.core.prims import PrimIDs  # noqa: E402

ex.register_implementation("torch.linear", fn=_quant_linear_impl, checker=_linear_checker)
# The autodiff pass flattens composites to prims, so the forward of a grad
# trace carries prims.linear — claim that too (backward matmuls stay bf16).
ex.register_implementation(PrimIDs.LINEAR, fn=_quant_linear_impl, checker=_linear_checker)
