"""First-party Pallas TPU kernels: fused cross-entropy.

Reference parity: the reference's only in-repo kernel-DSL code is its
Triton cross-entropy (thunder/executors/triton_crossentropy.py:53-343, four
@triton.jit kernels) plus the apex seat (apex_entropyex.py:38). This module
is the TPU equivalent: Pallas/Mosaic kernels fusing max/logsumexp/pick into
one HBM pass over the logits — the (N, V≈32-50k) logits matrix is the
largest activation in LM training, so one fused read (fwd) and one fused
write (bwd) replaces the ~5 passes of the decomposed path.

Claims ``torch.cross_entropy`` and the ``torch.cross_entropy_bwd``
composite emitted by the autodiff rule. Falls back to the decomposition
when shapes don't block-align (checker), exactly like the reference's
executor checkers.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

from thunder_tpu.core.proxies import TensorProxy, pyval
from thunder_tpu.executors.jaxex import enable_x64 as jaxex_enable_x64
from thunder_tpu.extend import OperatorExecutor, add_default_executor, register_executor
from thunder_tpu.resilience import chaos

ex = OperatorExecutor("pallas")
register_executor(ex)
add_default_executor(ex, front=True)

_BLOCK_N = 16
_LANE = 128


def _interpret() -> bool:
    import jax

    return jax.default_backend() == "cpu"


def _ce_block_n(N: int, V: int):
    """Row-block size for the CE kernels, or None when unclaimable.

    The bwd kernel live-holds ~6 f32 (block, V) temporaries (x, e, p, iota,
    onehot, out) in scoped VMEM; budget them under the 16 MB scoped limit
    with headroom (r5: pythia's V=50304 at the old fixed block of 16
    overflowed by 724 KB on the real chip — 'Ran out of memory in memory
    space vmem')."""
    for bt in (32, 16, 8):
        if N % bt == 0 and 6 * bt * V * 4 <= 12 * 1024 * 1024:
            return bt
    return None


def _ce_shapes_ok(input, target) -> bool:
    if len(getattr(input, "shape", ())) != 2:
        return False
    N, V = input.shape
    return V % _LANE == 0 and _ce_block_n(int(N), int(V)) is not None


def _ce_checker(input, target, weight=None, ignore_index=-100, reduction="mean", label_smoothing=0.0):
    return (
        weight is None
        and float(pyval(label_smoothing)) == 0.0
        and reduction in ("mean", "sum")
        and _ce_shapes_ok(input, target)
    )


def _ce_bwd_checker(g, input, target, ignore_index=-100, reduction="mean"):
    return reduction in ("mean", "sum") and _ce_shapes_ok(input, target)


# =============================================================================
# Kernels
# =============================================================================


# Lane-width padding: Mosaic requires the last (lane) dim of every VMEM
# block to be 128-aligned, so per-row scalars (targets, loss, row scales)
# travel as (N, 128) with only lane 0 meaningful.


def _ce_fwd_kernel(logits_ref, tgt_ref, loss_ref, *, ignore_index: int):
    import jax
    import jax.numpy as jnp

    x = logits_ref[:].astype(jnp.float32)  # (BLOCK_N, V)
    n, v = x.shape
    m = jnp.max(x, axis=1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=1, keepdims=True)) + m  # (BLOCK_N, 1)

    tgt = tgt_ref[:, 0:1]  # (BLOCK_N, 1) int32
    cols = jax.lax.broadcasted_iota(jnp.int32, (n, v), dimension=1)
    picked = jnp.sum(jnp.where(cols == tgt, x, 0.0), axis=1, keepdims=True)

    valid = (tgt != ignore_index).astype(jnp.float32)
    loss_ref[:] = jnp.broadcast_to((lse - picked) * valid, loss_ref.shape)


def _ce_bwd_kernel(logits_ref, tgt_ref, scale_ref, dlogits_ref, *, ignore_index: int):
    import jax
    import jax.numpy as jnp

    x = logits_ref[:].astype(jnp.float32)
    n, v = x.shape
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    p = e / jnp.sum(e, axis=1, keepdims=True)

    tgt = tgt_ref[:, 0:1]
    cols = jax.lax.broadcasted_iota(jnp.int32, (n, v), dimension=1)
    onehot = (cols == tgt).astype(jnp.float32)

    dlogits_ref[:] = ((p - onehot) * scale_ref[:, 0:1]).astype(dlogits_ref.dtype)


# =============================================================================
# Host-side wrappers
# =============================================================================


def _ce_call(kernel, out_lanes, out_dtype, logits, *extra):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, V = logits.shape
    bn = _ce_block_n(int(N), int(V))
    assert bn is not None, (
        f"CE kernel called with unclaimable shape ({N}, {V}) — the checker "
        "must gate this (a floored grid would leave tail rows unwritten)"
    )
    grid = (N // bn,)
    in_specs = [pl.BlockSpec((bn, V), lambda i: (i, 0), memory_space=pltpu.VMEM)]
    for _ in extra:
        in_specs.append(pl.BlockSpec((bn, _LANE), lambda i: (i, 0), memory_space=pltpu.VMEM))
    # Mosaic's index maths is 32-bit; scope out the runtime's x64 mode so the
    # grid index maps don't trace to i64 (which fails to legalize).
    with jaxex_enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((bn, out_lanes), lambda i: (i, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N, out_lanes), out_dtype),
            interpret=_interpret(),
        )(logits, *extra)


def _lanes(col):
    """(N,) per-row values → (N, 128) lane-padded array."""
    import jax.numpy as jnp

    return jnp.broadcast_to(col.reshape(-1, 1), (col.shape[0], _LANE))


def _ce_impl(input, target, weight=None, ignore_index=-100, reduction="mean", label_smoothing=0.0):
    chaos.kernel_seam("pallas", "cross_entropy")
    import jax.numpy as jnp

    N, V = input.shape
    tgt = _lanes(target.astype(jnp.int32))
    loss = _ce_call(
        partial(_ce_fwd_kernel, ignore_index=int(ignore_index)), _LANE, jnp.float32, input, tgt
    )[:, 0]
    total = jnp.sum(loss)
    if reduction == "sum":
        return total
    count = jnp.maximum(jnp.sum((target != ignore_index).astype(jnp.float32)), 1.0)
    return total / count


def _ce_bwd_impl(g, input, target, ignore_index=-100, reduction="mean"):
    chaos.kernel_seam("pallas", "cross_entropy_bwd")
    import jax.numpy as jnp

    N, V = input.shape
    tgt = _lanes(target.astype(jnp.int32))
    valid = (target != ignore_index).astype(jnp.float32)
    if reduction == "mean":
        count = jnp.maximum(jnp.sum(valid), 1.0)
        row_scale = _lanes(g.astype(jnp.float32) * valid / count)
    else:
        row_scale = _lanes(g.astype(jnp.float32) * valid)
    return _ce_call(
        partial(_ce_bwd_kernel, ignore_index=int(ignore_index)), V, input.dtype, input, tgt, row_scale
    )


ex.register_implementation("torch.cross_entropy", fn=_ce_impl, checker=_ce_checker)
ex.register_implementation("torch.cross_entropy_bwd", fn=_ce_bwd_impl, checker=_ce_bwd_checker)


# =============================================================================
# Fused rotary embedding (rotate-half ROPE)
# =============================================================================
#
# The decomposed rotate-half at head sizes like 100 produces 50-lane slices
# and a lane-dim concat — badly misaligned VPU work (r4 profile: ~14 ms/iter
# of (.., 50)-shaped fusions plus associated relayouts on the 3B bench). The
# kernel does the whole thing in one HBM pass per tensor; the backward is
# the same kernel with -sin (see the torch.apply_rope VJP rule).


_ROPE_BT = 2048  # sequence rows per block


def _rope_checker(x, cos, sin):
    if len(getattr(x, "shape", ())) != 4 or len(getattr(cos, "shape", ())) != 2:
        return False
    T, n = cos.shape
    if not (x.dtype == cos.dtype == sin.dtype):
        return False  # mixed dtypes promote in the decomposition; don't alter semantics
    # full-rotary only (partial decomposes); bt shrinks to a divisor of T
    return x.shape[-2] == T and x.shape[-1] == n and n % 2 == 0 and T % 8 == 0


def _rope_kernel(x_ref, cos_ref, sin_ref, out_ref, *, half: int):
    import jax.numpy as jnp

    x = x_ref[0]
    x1 = x[..., :half]
    x2 = x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    out_ref[0] = (x * cos_ref[...] + rotated * sin_ref[...]).astype(out_ref.dtype)


def _rope_impl(x, cos, sin):
    chaos.kernel_seam("pallas", "apply_rope")
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = x.shape
    bt = _ROPE_BT
    while T % bt:
        bt //= 2
    xf = x.reshape(B * H, T, D)
    cosx = cos.astype(x.dtype)
    sinx = sin.astype(x.dtype)
    with jaxex_enable_x64(False):
        out = pl.pallas_call(
            partial(_rope_kernel, half=D // 2),
            grid=(B * H, T // bt),
            in_specs=[
                pl.BlockSpec((1, bt, D), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((bt, D), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((bt, D), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, bt, D), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((B * H, T, D), x.dtype),
            interpret=_interpret(),
        )(xf, cosx, sinx)
    return out.reshape(B, H, T, D)


ex.register_implementation("torch.apply_rope", fn=_rope_impl, checker=_rope_checker)


# =============================================================================
# Fused RMSNorm (fwd + bwd) — OPT-IN executor "norm"
# =============================================================================
#
# Reference seat: the cudnn fused-norm executor (cudnn_layernormex.py:134).
# MEASURED (r4, open_llama_3b on v5e): claiming these by default REGRESSES
# the bench — fwd 1.1197→1.1398 s, train 0.6808→0.6900 s/iter — because XLA
# fuses the decomposed norm into its matmul neighbors, which a pallas_call
# boundary forbids. The seat therefore exists as an opt-in executor
# (``executors=["norm", ...]``), mirroring quantex's registered-not-default
# posture, with this measurement as the justification.


_NORM_BT = 256


def _rms_shapes_ok(a, weight) -> bool:
    if len(getattr(a, "shape", ())) < 2:
        return False
    D = a.shape[-1]
    if D % _LANE != 0:
        return False
    n_rows = 1
    for s in a.shape[:-1]:
        n_rows *= int(s)
    return n_rows % 8 == 0 and weight is not None and tuple(weight.shape) == (D,)


def _rms_fwd_checker(a, normalized_shape, weight=None, eps=None):
    return len(tuple(normalized_shape)) == 1 and _rms_shapes_ok(a, weight)


def _rms_bwd_checker(g, a, weight, eps):
    return _rms_shapes_ok(a, weight)


def _rms_fwd_kernel(x_ref, w_ref, out_ref, *, eps: float):
    import jax
    import jax.numpy as jnp

    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps) * w_ref[...].astype(jnp.float32)
    out_ref[...] = y.astype(out_ref.dtype)


def _rms_bwd_kernel(g_ref, x_ref, w_ref, dx_ref, dwp_ref, *, eps: float):
    import jax
    import jax.numpy as jnp

    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = x * rstd
    wg = g * w
    dot = jnp.mean(wg * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (wg - xhat * dot)).astype(dx_ref.dtype)
    # dw partial: (8, D) block (TPU sublane tiling); the sum lands in row 0
    part = jnp.sum(g * xhat, axis=0, keepdims=True)
    rows = jax.lax.broadcasted_iota(jnp.int32, dwp_ref.shape, dimension=0)
    dwp_ref[...] = jnp.where(rows == 0, part, 0.0)


def _norm_bt(n_rows: int, d: int) -> int:
    bt = _NORM_BT
    # VMEM budget: ~3 row-blocks live in f32 plus outputs; stay well under
    # the 16 MB scoped limit (measured OOM at bt=256, D=3200).
    while bt > 8 and bt * d * 4 * 5 > 10_000_000:
        bt //= 2
    while n_rows % bt:
        bt //= 2
    return max(bt, 1)


def _rms_impl(a, normalized_shape, weight=None, eps=None):
    chaos.kernel_seam("norm", "rms_norm")
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e = 1e-6 if eps is None else float(eps)
    D = a.shape[-1]
    xf = a.reshape(-1, D)
    N = xf.shape[0]
    bt = _norm_bt(N, D)
    w2 = weight.reshape(1, D)
    with jaxex_enable_x64(False):
        out = pl.pallas_call(
            partial(_rms_fwd_kernel, eps=e),
            grid=(N // bt,),
            in_specs=[
                pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, D), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N, D), a.dtype),
            interpret=_interpret(),
        )(xf, w2)
    return out.reshape(a.shape)


def _rms_bwd_impl(g, a, weight, eps):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e = float(eps)
    D = a.shape[-1]
    xf = a.reshape(-1, D)
    gf = g.reshape(-1, D)
    N = xf.shape[0]
    bt = _norm_bt(N, D)
    w2 = weight.reshape(1, D)
    with jaxex_enable_x64(False):
        dx, dwp = pl.pallas_call(
            partial(_rms_bwd_kernel, eps=e),
            grid=(N // bt,),
            in_specs=[
                pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, D), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((8, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((N, D), a.dtype),
                jax.ShapeDtypeStruct((8 * (N // bt), D), jnp.float32),
            ],
            interpret=_interpret(),
        )(gf, xf, w2)
    dw = jnp.sum(dwp, axis=0).astype(weight.dtype)
    return dx.reshape(a.shape), dw


def _ln_fwd_checker(a, normalized_shape, weight=None, bias=None, eps=1e-5):
    return len(tuple(normalized_shape)) == 1 and _rms_shapes_ok(a, weight)


def _ln_bwd_checker(g, a, weight, bias, eps):
    return _rms_shapes_ok(a, weight)


def _ln_fwd_kernel(x_ref, w_ref, b_ref, out_ref, *, eps: float, has_bias: bool):
    import jax
    import jax.numpy as jnp

    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps) * w_ref[...].astype(jnp.float32)
    if has_bias:
        y = y + b_ref[...].astype(jnp.float32)
    out_ref[...] = y.astype(out_ref.dtype)


def _ln_bwd_kernel(g_ref, x_ref, w_ref, dx_ref, dwp_ref, dbp_ref, *, eps: float):
    import jax
    import jax.numpy as jnp

    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    wg = g * w
    m1 = jnp.mean(wg, axis=-1, keepdims=True)
    m2 = jnp.mean(wg * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (wg - m1 - xhat * m2)).astype(dx_ref.dtype)
    rows = jax.lax.broadcasted_iota(jnp.int32, dwp_ref.shape, dimension=0)
    dwp_ref[...] = jnp.where(rows == 0, jnp.sum(g * xhat, axis=0, keepdims=True), 0.0)
    dbp_ref[...] = jnp.where(rows == 0, jnp.sum(g, axis=0, keepdims=True), 0.0)


def _ln_impl(a, normalized_shape, weight=None, bias=None, eps=1e-5):
    chaos.kernel_seam("norm", "layer_norm")
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e = float(eps)
    D = a.shape[-1]
    xf = a.reshape(-1, D)
    N = xf.shape[0]
    bt = _norm_bt(N, D)
    w2 = weight.reshape(1, D)
    has_bias = bias is not None
    b2 = bias.reshape(1, D) if has_bias else jnp.zeros((1, D), dtype=a.dtype)
    with jaxex_enable_x64(False):
        out = pl.pallas_call(
            partial(_ln_fwd_kernel, eps=e, has_bias=has_bias),
            grid=(N // bt,),
            in_specs=[
                pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, D), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, D), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N, D), a.dtype),
            interpret=_interpret(),
        )(xf, w2, b2)
    return out.reshape(a.shape)


def _ln_bwd_impl(g, a, weight, bias, eps):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e = float(eps)
    D = a.shape[-1]
    xf = a.reshape(-1, D)
    gf = g.reshape(-1, D)
    N = xf.shape[0]
    bt = _norm_bt(N, D)
    w2 = weight.reshape(1, D)
    with jaxex_enable_x64(False):
        dx, dwp, dbp = pl.pallas_call(
            partial(_ln_bwd_kernel, eps=e),
            grid=(N // bt,),
            in_specs=[
                pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, D), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((bt, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((8, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((8, D), lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((N, D), a.dtype),
                jax.ShapeDtypeStruct((8 * (N // bt), D), jnp.float32),
                jax.ShapeDtypeStruct((8 * (N // bt), D), jnp.float32),
            ],
            interpret=_interpret(),
        )(gf, xf, w2)
    dw = jnp.sum(dwp, axis=0).astype(weight.dtype)
    db = jnp.sum(dbp, axis=0).astype(weight.dtype) if bias is not None else None
    return dx.reshape(a.shape), dw, db


norm_ex = OperatorExecutor("norm")
register_executor(norm_ex)
norm_ex.register_implementation("torch.rms_norm", fn=_rms_impl, checker=_rms_fwd_checker)
norm_ex.register_implementation("torch.rms_norm_bwd", fn=_rms_bwd_impl, checker=_rms_bwd_checker)
norm_ex.register_implementation("torch.layer_norm", fn=_ln_impl, checker=_ln_fwd_checker)
norm_ex.register_implementation("torch.layer_norm_bwd", fn=_ln_bwd_impl, checker=_ln_bwd_checker)
