"""Executors: concrete backends for the trace IR.

Reference parity: thunder/executors/ — here the backend zoo is TPU-native:
``jaxex`` (JAX/XLA operator executor, the torchex+nvFuser seat), ``pythonex``
(guards/prologues), and the Pallas executors (flash attention, fused
cross-entropy — the cuDNN/Triton/Apex/TE seats).
"""
