"""The Python executor: host-side guards, unpacking, and utility prims.

Reference parity: thunder/executors/pythonex.py (`ex:28`) — the always-on
executor that runs prologue traces (metadata guards) and utility statements.
Everything here executes on the host in plain Python; no device work.
"""

from __future__ import annotations

from thunder_tpu.core import prims
from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.extend import OperatorExecutor, add_always_executor, register_executor

ex = OperatorExecutor("python")
register_executor(ex)
add_always_executor(ex)

_guard_ids = (
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA,
    PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    PrimIDs.CHECK_STRING_VALUE,
    PrimIDs.CHECK_LEN,
    PrimIDs.CHECK_KEYS,
    PrimIDs.CHECK_NONE,
    PrimIDs.CHECK_DIM_BUCKET,
)

for pid in _guard_ids:
    ex.register_implementation(pid, fn=prims.get_prim(pid).python_impl)

ex.register_implementation(PrimIDs.PRINT, fn=print)
