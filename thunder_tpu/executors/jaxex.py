"""The JAX/XLA operator executor: every prim lowered to jax.numpy / lax.

Reference parity: this executor occupies the seats of both ``torchex``
(thunder/executors/torchex.py:40 — the default operator executor covering
all prims) and ``nvfuserex`` (thunder/executors/nvfuserex_impl.py — fusion):
on TPU the claimed trace is staged whole under ``jax.jit``, so XLA performs
the fusion, layout assignment, and scheduling that nvFuser did for CUDA, and
the compiled-executable cache takes the seat of descriptor-keyed nvFuser
caching and CUDA graphs.

Numeric notes:
- ``jax_enable_x64`` is turned on by the runtime so the torch-facing dtype
  semantics (int64 indices, float64 when requested) hold exactly; all hot
  compute is explicitly bf16/f32 in the traces, so this costs nothing on TPU.
- ``prims.div`` is true division for floats and *floor* division for
  integers (clang routes int true-division through a float convert).
"""

from __future__ import annotations

import math
from numbers import Number
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from thunder_tpu.core import dtypes
from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.extend import OperatorExecutor, add_default_executor, register_executor
from thunder_tpu.observability import metrics as obsm

ex = OperatorExecutor("jax")
register_executor(ex)
add_default_executor(ex, front=False)


def _jd(d: dtypes.dtype):
    return dtypes.to_jax_dtype(d)


def enable_x64(enabled: bool = True):
    """Compat shim for the ``jax.enable_x64`` context manager: newer jax
    releases moved it to ``jax.experimental.enable_x64`` and removed the
    top-level alias. The Pallas kernels (pallasex/flashex) scope x64 OFF
    around pallas_call (Mosaic rejects x64 iota), and the max-pool adjoint
    scopes it ON for int64 index packing — both must work on every jax in
    the support window."""
    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx(enabled)


def _reg(prim_id: PrimIDs, fn, checker=None):
    ex.register_implementation(prim_id, fn=fn, checker=checker)


# -- data movement ------------------------------------------------------------


def _convert_element_type(a, dtype):
    if isinstance(a, Number):
        return dtypes.dtype_to_numbertype(dtype)(a)
    return lax.convert_element_type(a, _jd(dtype))


_reg(PrimIDs.CONVERT_ELEMENT_TYPE, _convert_element_type)
_reg(PrimIDs.DEVICE_PUT, lambda a, device: a)
_reg(PrimIDs.ITEM, lambda a: a.item())
_reg(PrimIDs.SHALLOW_COPY, lambda a: a)
_reg(PrimIDs.STOP_GRADIENT, lax.stop_gradient)
_reg(PrimIDs.COPY_, lambda src, dst: jnp.broadcast_to(src, dst.shape).astype(dst.dtype))


# -- creation -----------------------------------------------------------------

_reg(PrimIDs.FULL, lambda shape, v, *, device, dtype: jnp.full(tuple(shape), v, dtype=_jd(dtype)))
_reg(
    PrimIDs.IOTA,
    lambda length, *, start, step, device, dtype: (jnp.arange(int(length), dtype=_jd(dtype)) * step + start).astype(
        _jd(dtype)
    ),
)
_reg(PrimIDs.TENSOR_FROM_SEQUENCE, lambda seq, *, device, dtype: jnp.asarray(seq, dtype=_jd(dtype) if dtype else None))


def _uniform_keyed(shape, minval, maxval, key, salt, *, device, dtype):
    k = jax.random.fold_in(key, salt)
    return jax.random.uniform(k, tuple(shape), dtype=_jd(dtype), minval=minval, maxval=maxval)


def _randn_keyed(shape, key, salt, *, device, dtype):
    k = jax.random.fold_in(key, salt)
    return jax.random.normal(k, tuple(shape), dtype=_jd(dtype))


_reg(PrimIDs.UNIFORM_KEYED, _uniform_keyed)
_reg(PrimIDs.RANDN_KEYED, _randn_keyed)

# Unkeyed RNG only executes eagerly (outside jit); the rng functionalization
# pass rewrites these away before staging.
_host_rng = {"seed": 0}


def _eager_key():
    _host_rng["seed"] += 1
    return jax.random.PRNGKey(_host_rng["seed"])


_reg(
    PrimIDs.UNIFORM,
    lambda shape, minval, maxval, *, device, dtype: jax.random.uniform(
        _eager_key(), tuple(shape), dtype=_jd(dtype), minval=minval, maxval=maxval
    ),
)
_reg(PrimIDs.RANDN, lambda shape, *, device, dtype: jax.random.normal(_eager_key(), tuple(shape), dtype=_jd(dtype)))


# -- shape --------------------------------------------------------------------

_reg(PrimIDs.BROADCAST_IN_DIM, lambda a, shape, bdims: lax.broadcast_in_dim(a, tuple(int(s) for s in shape), tuple(bdims)))
_reg(PrimIDs.CAT, lambda tensors, dim: jnp.concatenate(tensors, axis=dim))
_reg(PrimIDs.FLIP, lambda a, dims: jnp.flip(a, axis=tuple(dims)))


def _pad(a, padding_value, padding_config):
    pv = jnp.asarray(padding_value, dtype=a.dtype)
    return lax.pad(a, pv, [(int(lo), int(hi), int(d)) for lo, hi, d in padding_config])


_reg(PrimIDs.PAD, _pad)
_reg(PrimIDs.RESHAPE, lambda a, shape: jnp.reshape(a, tuple(int(s) for s in shape)))
_reg(
    PrimIDs.SLICE,
    lambda a, starts, ends, strides=None: lax.slice(
        a, tuple(int(s) for s in starts), tuple(int(e) for e in ends), tuple(int(s) for s in strides) if strides else None
    ),
)
def _setitem(a, key, value):
    # Explicit cast to the target dtype: torch setitem truncates (7.5 into
    # an int32 tensor stores 7); jax's implicit unsafe-scatter cast is
    # deprecated and will become an error.
    return a.at[key].set(jnp.asarray(value, a.dtype))


_reg(PrimIDs.SETITEM, _setitem)


_reg(PrimIDs.SQUEEZE, lambda a, dims: lax.squeeze(a, tuple(dims)))
_reg(PrimIDs.TRANSPOSE, lambda a, perm: lax.transpose(a, tuple(perm)))
_reg(PrimIDs.TAKE, lambda a, idx, dim: jnp.take(a, idx, axis=dim))
_reg(PrimIDs.TAKE_ALONG_AXIS, lambda a, idx, dim: jnp.take_along_axis(a, idx, axis=dim))
_reg(PrimIDs.GATHER, lambda a, idx, dim: jnp.take_along_axis(a, idx, axis=dim))


def _scatter_add(a, idx, val, dim):
    grids = jnp.indices(idx.shape, sparse=True)
    index_tuple = tuple(idx if d == dim else grids[d] for d in range(a.ndim))
    return a.at[index_tuple].add(val)


_reg(PrimIDs.SCATTER_ADD, _scatter_add)


def _index_put(a, indices, values, accumulate):
    idx = tuple(indices)
    if accumulate:
        return a.at[idx].add(values)
    return a.at[idx].set(values)


_reg(PrimIDs.INDEX_PUT, _index_put)
_reg(PrimIDs.ARGSORT, lambda a, dim, descending: jnp.argsort(a, axis=dim, descending=descending))


def _sort(a, dim, descending):
    v = jnp.sort(a, axis=dim, descending=descending)
    i = jnp.argsort(a, axis=dim, descending=descending)
    return v, i


_reg(PrimIDs.SORT, _sort)


def _cumsum(a, dim):
    if jnp.issubdtype(a.dtype, jnp.bool_) or jnp.issubdtype(a.dtype, jnp.integer):
        return jnp.cumsum(a, axis=dim, dtype=jnp.int64)
    return jnp.cumsum(a, axis=dim)


_reg(PrimIDs.CUMSUM, _cumsum)


def _cumprod(a, dim):
    if jnp.issubdtype(a.dtype, jnp.bool_) or jnp.issubdtype(a.dtype, jnp.integer):
        return jnp.cumprod(a, axis=dim, dtype=jnp.int64)
    return jnp.cumprod(a, axis=dim)


_reg(PrimIDs.CUMPROD, _cumprod)


def _topk(a, k, dim, largest, sorted):
    a_m = jnp.moveaxis(a, dim, -1)
    if largest:
        v, i = lax.top_k(a_m, k)
    else:
        v, i = lax.top_k(-a_m, k)
        v = -v
    return jnp.moveaxis(v, -1, dim), jnp.moveaxis(i, -1, dim).astype(jnp.int64)


_reg(PrimIDs.TOPK, _topk)


# -- elementwise unary --------------------------------------------------------

from jax.scipy import special as jsp  # noqa: E402

_unary_table = {
    PrimIDs.ABS: jnp.abs,
    PrimIDs.ACOS: jnp.arccos,
    PrimIDs.ACOSH: jnp.arccosh,
    PrimIDs.ASIN: jnp.arcsin,
    PrimIDs.ASINH: jnp.arcsinh,
    PrimIDs.ATAN: jnp.arctan,
    PrimIDs.ATANH: jnp.arctanh,
    PrimIDs.BITWISE_NOT: lambda a: jnp.logical_not(a) if a.dtype == jnp.bool_ else jnp.invert(a),
    PrimIDs.CEIL: jnp.ceil,
    PrimIDs.COS: jnp.cos,
    PrimIDs.COSH: jnp.cosh,
    PrimIDs.DIGAMMA: jsp.digamma,
    PrimIDs.ERF: jsp.erf,
    PrimIDs.ERFC: jsp.erfc,
    PrimIDs.ERFINV: jsp.erfinv,
    PrimIDs.EXP: jnp.exp,
    PrimIDs.EXP2: jnp.exp2,
    PrimIDs.EXPM1: jnp.expm1,
    PrimIDs.FLOOR: jnp.floor,
    PrimIDs.ISFINITE: jnp.isfinite,
    PrimIDs.ISINF: jnp.isinf,
    PrimIDs.ISNAN: jnp.isnan,
    PrimIDs.LGAMMA: jsp.gammaln,
    PrimIDs.LOG: jnp.log,
    PrimIDs.LOG10: jnp.log10,
    PrimIDs.LOG1P: jnp.log1p,
    PrimIDs.LOG2: jnp.log2,
    PrimIDs.NEG: jnp.negative,
    PrimIDs.RECIPROCAL: jnp.reciprocal,
    PrimIDs.ROUND: jnp.round,
    PrimIDs.RSQRT: lax.rsqrt,
    PrimIDs.SIGN: jnp.sign,
    PrimIDs.SIGNBIT: jnp.signbit,
    PrimIDs.SIN: jnp.sin,
    PrimIDs.SINH: jnp.sinh,
    PrimIDs.SQRT: jnp.sqrt,
    PrimIDs.TAN: jnp.tan,
    PrimIDs.TANH: jnp.tanh,
    PrimIDs.TRUNC: jnp.trunc,
    PrimIDs.REAL: jnp.real,
    PrimIDs.IMAG: jnp.imag,
}
for pid, fn in _unary_table.items():
    _reg(pid, fn)


# -- elementwise binary -------------------------------------------------------


def _div(a, b):
    if jnp.issubdtype(jnp.result_type(a), jnp.integer) and jnp.issubdtype(jnp.result_type(b), jnp.integer):
        return jnp.floor_divide(a, b)
    return jnp.true_divide(a, b)


def _bool_aware(int_fn, bool_fn):
    def fn(a, b):
        if jnp.result_type(a) == jnp.bool_:
            return bool_fn(a, b)
        return int_fn(a, b)

    return fn


_binary_table = {
    PrimIDs.ADD: jnp.add,
    PrimIDs.ATAN2: jnp.arctan2,
    PrimIDs.BITWISE_AND: _bool_aware(jnp.bitwise_and, jnp.logical_and),
    PrimIDs.BITWISE_OR: _bool_aware(jnp.bitwise_or, jnp.logical_or),
    PrimIDs.BITWISE_XOR: _bool_aware(jnp.bitwise_xor, jnp.logical_xor),
    PrimIDs.BITWISE_LEFT_SHIFT: jnp.left_shift,
    PrimIDs.BITWISE_RIGHT_SHIFT: jnp.right_shift,
    PrimIDs.DIV: _div,
    PrimIDs.EQ: jnp.equal,
    PrimIDs.FMOD: jnp.fmod,
    PrimIDs.GE: jnp.greater_equal,
    PrimIDs.GT: jnp.greater,
    PrimIDs.LE: jnp.less_equal,
    PrimIDs.LT: jnp.less,
    PrimIDs.MAXIMUM: jnp.maximum,
    PrimIDs.MINIMUM: jnp.minimum,
    PrimIDs.MUL: jnp.multiply,
    PrimIDs.NE: jnp.not_equal,
    PrimIDs.NEXTAFTER: jnp.nextafter,
    PrimIDs.POW: jnp.power,
    PrimIDs.REMAINDER: jnp.remainder,
    PrimIDs.SUB: jnp.subtract,
    PrimIDs.COPYSIGN: jnp.copysign,
    PrimIDs.ZETA: lambda a, b: jsp.zeta(a, b),
}
for pid, fn in _binary_table.items():
    _reg(pid, fn)

_reg(PrimIDs.WHERE, jnp.where)


# -- reductions ---------------------------------------------------------------


def _sum(a, dims):
    if jnp.issubdtype(a.dtype, jnp.bool_) or jnp.issubdtype(a.dtype, jnp.integer):
        return jnp.sum(a, axis=tuple(dims), dtype=jnp.int64)
    return jnp.sum(a, axis=tuple(dims))


def _prod(a, dims):
    if jnp.issubdtype(a.dtype, jnp.bool_) or jnp.issubdtype(a.dtype, jnp.integer):
        return jnp.prod(a, axis=tuple(dims), dtype=jnp.int64)
    return jnp.prod(a, axis=tuple(dims))


_reg(PrimIDs.AMAX, lambda a, dims: jnp.max(a, axis=tuple(dims)))
_reg(PrimIDs.AMIN, lambda a, dims: jnp.min(a, axis=tuple(dims)))
_reg(PrimIDs.SUM, _sum)
_reg(PrimIDs.PROD, _prod)
_reg(PrimIDs.VAR, lambda a, dims, *, correction: jnp.var(a, axis=tuple(dims), ddof=int(correction)))
_reg(
    PrimIDs.VAR_MEAN,
    lambda a, dims, *, correction: (
        jnp.var(a, axis=tuple(dims), ddof=int(correction)),
        jnp.mean(a, axis=tuple(dims)),
    ),
)
_reg(PrimIDs.ARGMAX, lambda a, dim: jnp.argmax(a, axis=dim).astype(jnp.int64))
_reg(PrimIDs.ARGMIN, lambda a, dim: jnp.argmin(a, axis=dim).astype(jnp.int64))


# -- linear algebra / NN ------------------------------------------------------


# Float32 matmul precision, mirroring torch.set_float32_matmul_precision:
# "highest" = true f32 (6-pass bf16 on the MXU), "high" ≈ tf32 (3-pass),
# "medium" = 1-pass bf16. bf16/f16 inputs are unaffected — that is the hot
# path for training and runs the MXU natively.
_f32_matmul_precision = {"value": lax.Precision.HIGHEST}
_PRECISION_MAP = {
    "highest": lax.Precision.HIGHEST,
    "high": lax.Precision.HIGH,
    "medium": lax.Precision.DEFAULT,
}


def set_float32_matmul_precision(mode: str) -> None:
    _f32_matmul_precision["value"] = _PRECISION_MAP[mode]


def _dot_precision(*operands):
    if any(o.dtype in (jnp.float32, jnp.float64) for o in operands):
        return _f32_matmul_precision["value"]
    return None


def _matmul(a, b):
    return jnp.matmul(a, b, precision=_dot_precision(a, b))


_reg(PrimIDs.MATMUL, _matmul)


def _linear(a, w, bias):
    # x @ w.T via dot_general: contract a's last dim with w's dim 1 —
    # a single MXU-friendly contraction, no materialized transpose.
    out = lax.dot_general(a, w, (((a.ndim - 1,), (1,)), ((), ())), precision=_dot_precision(a, w))
    if bias is not None:
        out = out + bias
    return out


_reg(PrimIDs.LINEAR, _linear)


def _convolution(a, weight, bias, stride, padding, dilation, groups):
    spatial = a.ndim - 2
    stride = tuple(stride[i] if i < len(stride) else stride[-1] for i in range(spatial))
    padding_seq = tuple(
        (padding[i] if i < len(padding) else padding[-1],) * 2 for i in range(spatial)
    )
    dilation = tuple(dilation[i] if i < len(dilation) else dilation[-1] for i in range(spatial))
    spec = "NC" + "DHW"[3 - spatial :]
    wspec = "OI" + "DHW"[3 - spatial :]
    dn = lax.conv_dimension_numbers(a.shape, weight.shape, (spec, wspec, spec))
    out = lax.conv_general_dilated(
        a,
        weight,
        window_strides=stride,
        padding=padding_seq,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
        precision=_dot_precision(a, weight),
    )
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * spatial)
    return out


_reg(PrimIDs.CONVOLUTION, _convolution)


def _convolution_bwd(g, a, weight, stride, padding, dilation, groups):
    _, vjp = jax.vjp(lambda x, w: _convolution(x, w, None, stride, padding, dilation, groups), a, weight)
    return vjp(g)


_reg(PrimIDs.CONVOLUTION_BWD, _convolution_bwd)
_reg(PrimIDs.EMBEDDING, lambda idx, w: jnp.take(w, idx, axis=0))


def _embedding_backward(grad, idx, num_weights, embed_dim):
    out = jnp.zeros((num_weights, embed_dim), dtype=grad.dtype)
    return out.at[idx.reshape(-1)].add(grad.reshape(-1, embed_dim))


_reg(PrimIDs.EMBEDDING_BACKWARD, _embedding_backward)
_reg(PrimIDs.POLYGAMMA, lambda n, a: jsp.polygamma(n, a))


def _pool_fwd_fn(a, kind, window, strides, padding):
    """reduce_window over the trailing len(window) dims — XLA's native
    pooling; avg divides by the full window size (count_include_pad=True,
    torch's default)."""
    k = len(window)
    full_window = (1,) * (a.ndim - k) + tuple(window)
    full_strides = (1,) * (a.ndim - k) + tuple(strides)
    full_pad = ((0, 0),) * (a.ndim - k) + tuple((int(lo), int(hi)) for lo, hi in padding)
    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
        return lax.reduce_window(a, jnp.asarray(init, a.dtype), lax.max, full_window, full_strides, full_pad)
    s = lax.reduce_window(a, jnp.asarray(0, a.dtype), lax.add, full_window, full_strides, full_pad)
    return s / math.prod(window)


def _pool_bwd_fn(g, a, kind, window, strides, padding):
    """Direct pooling adjoints (this jax build cannot differentiate
    reduce_window under jit at all — Linearization failure — so jax.vjp is
    not an option here).

    avg: the transpose of a strided window-sum is a stride-1 window-sum over
    the base-dilated cotangent (XLA's own transpose rule), divided by the
    window size. max: torch semantics (grad to the FIRST max element of each
    window) via a single int64 reduce_window over (monotonic-value, reversed-
    index) packed keys, then scatter-add of g at each window's argmax."""
    k = len(window)
    lead = a.shape[: a.ndim - k]
    spatial = a.shape[a.ndim - k:]

    if kind == "avg":
        full_window = (1,) * (a.ndim - k) + tuple(window)
        pads = []
        for s_in, kk, tt, (lo, hi) in zip(spatial, window, strides, padding):
            d = (g.shape[g.ndim - k + len(pads)] - 1) * tt + 1
            pl = kk - 1 - lo
            ph = s_in + lo - d
            pads.append((pl, ph))
        full_pads = ((0, 0),) * (a.ndim - k) + tuple(pads)
        base_dil = (1,) * (a.ndim - k) + tuple(strides)
        adj = lax.reduce_window(
            g, jnp.asarray(0, g.dtype), lax.add, full_window, (1,) * a.ndim,
            full_pads, base_dilation=base_dil,
        )
        return adj / math.prod(window)

    # max: pack (monotonic value bits, reversed linear index) into int64 so a
    # single reduce_window max yields each window's first-argmax index. The
    # packing needs real int64 — enable x64 locally so the adjoint works even
    # when the caller never went through jit()'s _ensure_runtime.
    with enable_x64():
        return _max_pool_bwd_x64(g, a, window, strides, padding, lead, spatial)


def _max_pool_bwd_x64(g, a, window, strides, padding, lead, spatial):
    k = len(window)
    n_spatial = math.prod(spatial)
    b = math.prod(lead) if lead else 1
    if a.dtype == jnp.float64:
        # The packed argmax key holds 32 value bits; two f64 values inside a
        # window that differ only below f32 precision would pick the wrong
        # winner and silently misroute the whole cotangent. Refuse rather
        # than be subtly wrong (torch-parity surface is f32/bf16 pooling).
        raise NotImplementedError(
            "max-pool backward for float64 inputs is not supported (argmax "
            "key packing is exact only to float32); cast to float32"
        )
    af = a.astype(jnp.float32) if a.dtype != jnp.float32 else a
    bits = lax.bitcast_convert_type(af, jnp.int32).astype(jnp.int64)
    mono = jnp.where(bits < 0, ~bits, bits | jnp.int64(0x80000000))
    # Center to [-2^31, 2^31) so the <<32 below cannot overflow int64.
    mono = mono - (jnp.int64(1) << 31)
    idx = jnp.arange(n_spatial, dtype=jnp.int64).reshape((1,) * len(lead) + spatial)
    packed = (mono << 32) | (jnp.int64(n_spatial) - idx)  # larger = earlier index
    full_window = (1,) * (a.ndim - k) + tuple(window)
    full_strides = (1,) * (a.ndim - k) + tuple(strides)
    full_pad = ((0, 0),) * (a.ndim - k) + tuple((int(lo), int(hi)) for lo, hi in padding)
    winner = lax.reduce_window(
        jnp.broadcast_to(packed, a.shape), jnp.iinfo(jnp.int64).min, lax.max,
        full_window, full_strides, full_pad,
    )
    win_idx = jnp.int64(n_spatial) - (winner & jnp.int64(0xFFFFFFFF))
    flat_idx = win_idx.reshape(b, -1)
    flat_g = g.reshape(b, -1)
    grad = jnp.zeros((b, n_spatial), g.dtype).at[
        jnp.arange(b)[:, None], flat_idx
    ].add(flat_g)
    return grad.reshape(a.shape)


_reg(PrimIDs.POOL, _pool_fwd_fn)
_reg(PrimIDs.POOL_BWD, _pool_bwd_fn)


def _uniform_philox(shape, minval, maxval, *, seed, offset, device, dtype):
    key = jax.random.fold_in(jax.random.PRNGKey(seed), offset)
    return jax.random.uniform(key, tuple(shape), dtype=_jd(dtype), minval=minval, maxval=maxval)


_reg(PrimIDs.UNIFORM_PHILOX, _uniform_philox)


# =============================================================================
# Bucketed staging (cache="symbolic values", core/bucketing.py)
#
# One XLA executable serves a whole shape bucket: marked input dims are
# zero-padded up to the bucket ceiling here, at the jax.jit boundary, and
# outputs are cropped back by the dispatcher (api._run_entry). The padded
# buffers are dispatch-time temporaries, so they are DONATED to XLA (off-CPU):
# the executable reuses their memory instead of copying.
# =============================================================================


def _donation_active() -> bool:
    # Narrow catch (ISSUE 6 satellite): jax raises RuntimeError when no
    # backend can initialize — the one legitimate "answer conservatively"
    # case. Anything else (ImportError from a broken install, a TypeError
    # from an API change) is a real bug and must propagate, not be
    # swallowed into silently-disabled donation.
    try:
        return jax.default_backend() != "cpu"
    except RuntimeError as e:
        from thunder_tpu.common import sharp_edge

        sharp_edge(
            f"jax backend unavailable while resolving donation "
            f"({type(e).__name__}: {e}); buffer donation disabled"
        )
        return False


def stage_bucketed(trace_callable, donate_leaves: Sequence[int], *, donate: bool = True):
    """jax.jit a trace callable whose ``donate_leaves`` argument positions
    receive freshly padded (dispatch-owned) buffers. Donation is skipped on
    CPU, where jax does not implement it (and would warn per call), and at
    de-opt ladder level ≥ 1 (``donate=False`` — resilience/deopt.py).

    The actual donation decision is stamped on the staged callable
    (``_thunder_donated_argnums``): api._compile_entry_impl reconciles the
    claimed trace's ``donated_inputs`` tag against it after staging, and
    it is the introspection point for anyone holding only the jitted
    callable. The caller's ``donate`` must already be the full predicate
    (api's ``donate_buckets``); this function only adds the backend checks
    it owns (CPU has no donation)."""
    donating = bool(donate and _donation_active() and donate_leaves)
    jfn = (
        jax.jit(trace_callable, donate_argnums=tuple(donate_leaves))
        if donating
        else jax.jit(trace_callable)
    )
    try:
        jfn._thunder_donated_argnums = tuple(donate_leaves) if donating else ()
    except Exception:  # jit wrapper without attribute support
        pass
    return jfn


def pad_to_bucket(inps: list, sym_spec) -> list:
    """Zero-pad marked dims of the (jax) input leaves up to their bucket
    ceilings. Always returns buffers safe to donate for marked leaves: a leaf
    already at the ceiling is copied, so the caller's array is never donated
    out from under it.

    With metrics enabled, the padded-minus-true element count per call is
    accumulated into ``thunder_tpu_padding_waste_elements_total`` — the
    bucket-policy tuning signal (too-coarse buckets show up as waste, not
    just as fewer compiles)."""
    donating = _donation_active()
    track_waste = obsm.enabled()
    waste = 0
    out = list(inps)
    for li, dims in sym_spec.marks.items():
        x = out[li]
        widths = [(0, 0)] * x.ndim
        padded = False
        for d, (_lo, hi, _cid) in dims.items():
            delta = int(hi) - int(x.shape[d])
            if delta > 0:
                widths[d] = (0, delta)
                padded = True
        if padded:
            if track_waste:
                true_elems = math.prod(int(s) for s in x.shape)
                padded_elems = math.prod(
                    int(s) + w[1] for s, w in zip(x.shape, widths)
                )
                waste += padded_elems - true_elems
            out[li] = jnp.pad(x, widths)
        elif donating:
            out[li] = jnp.array(x, copy=True)
    if track_waste and waste:
        obsm.PADDING_WASTE_ELEMENTS.inc(waste)
    return out


def crop_to_extents(out, sym_spec, true_extents: dict):
    """Slice padded output dims back to the call's true extents, per the
    provenance crop plan (transforms/padmask.py): each listed flat output
    leaf is sliced exactly on its tracked dims. The plan is always derived —
    from the masked trace, or re-analyzed after grad/autocast transforms —
    so no shape-coincidence guessing happens here."""
    from thunder_tpu.core.pytree import tree_flatten, tree_unflatten

    if not sym_spec.crop_plan:
        return out
    flat, spec = tree_flatten(out)

    def slice_dim(x, d, n):
        if int(x.shape[d]) == int(n):
            return x
        ix = [slice(None)] * x.ndim
        ix[d] = slice(0, int(n))
        return x[tuple(ix)]

    for i, dims in sym_spec.crop_plan:
        if i < len(flat) and isinstance(flat[i], jax.Array):
            for d, cid in dims.items():
                flat[i] = slice_dim(flat[i], d, true_extents[cid])
    return tree_unflatten(spec, flat)
