"""The torch/numpy ↔ JAX array boundary.

Reference analogue: the reference executes on torch tensors natively; here
the compute substrate is JAX on TPU, and torch (CPU-only in this build) is a
*frontend* — so the boundary lives in one place. DLPack is used for
zero-copy handoff where possible (BASELINE.json north star: "tensor proxies
round-tripping through DLPack"), with a copying fallback for dtypes numpy
can't express (bf16).
"""

from __future__ import annotations

from numbers import Number
from typing import Any

from thunder_tpu.core import dtypes


def is_torch_tensor(x: Any) -> bool:
    return type(x).__module__.startswith("torch") and hasattr(x, "layout")


def is_jax_array(x: Any) -> bool:
    import jax

    return isinstance(x, jax.Array)


def to_jax(x: Any) -> Any:
    """Concrete tensor/number → jax value on the default device."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if isinstance(x, jax.Array):
        return x
    if isinstance(x, np.ndarray):
        return jnp.asarray(x)
    if is_torch_tensor(x):
        import torch

        t = x.detach().contiguous()
        try:
            # DLPack: zero-copy on CPU, then XLA transfers to device once.
            arr = jnp.from_dlpack(torch.utils.dlpack.to_dlpack(t))
        except Exception:
            if t.dtype == torch.bfloat16:
                arr = jnp.asarray(t.float().numpy()).astype(jnp.bfloat16)
            else:
                arr = jnp.asarray(t.numpy())
        return arr
    if isinstance(x, Number):
        return x
    return x


def to_torch(x: Any) -> Any:
    """jax array → torch tensor (CPU)."""
    import torch
    import numpy as np
    import jax

    if is_torch_tensor(x):
        return x
    if isinstance(x, jax.Array):
        np_dtype = x.dtype
        if str(np_dtype) == "bfloat16":
            return torch.from_numpy(np.array(x.astype("float32"))).to(torch.bfloat16)
        # np.array copies: device→host transfer yields a read-only buffer
        # torch would otherwise warn about.
        return torch.from_numpy(np.array(x))
    return x


def _staging_device() -> str:
    """Host containers (numpy, torch-CPU tensors) are device_put to the
    default accelerator when the staged program runs, so they trace as that
    device — keeping single-program traces on one device instead of
    spuriously mixing cpu/tpu."""
    from thunder_tpu.core import devices

    return str(devices.Device())


def tensor_metadata(x: Any) -> tuple:
    """(shape, device_str, framework dtype, requires_grad) of a concrete tensor."""
    if is_torch_tensor(x):
        dev = _staging_device() if x.device.type == "cpu" else str(x.device)
        return tuple(x.shape), dev, dtypes.from_torch_dtype(x.dtype), bool(x.requires_grad)
    import jax

    if isinstance(x, jax.Array):
        try:
            plat = list(x.devices())[0].platform
        except Exception:
            plat = "cpu"
        return tuple(x.shape), ("cpu" if plat == "cpu" else "tpu"), dtypes.from_jax_dtype(x.dtype), False
    import numpy as np

    if isinstance(x, np.ndarray):
        return tuple(x.shape), _staging_device(), dtypes.from_jax_dtype(x.dtype), False
    raise ValueError(f"Not a tensor: {type(x)}")


def framework_of(x: Any) -> str:
    """Which array framework a concrete tensor belongs to — guarded by the
    prologue so a cache entry compiled for numpy inputs is never reused for
    torch inputs (the output framework follows the input framework)."""
    if is_torch_tensor(x):
        return "torch"
    import jax

    if isinstance(x, jax.Array):
        return "jax"
    return "numpy"


def is_concrete_tensor(x: Any) -> bool:
    import numpy as np
    import jax

    return is_torch_tensor(x) or isinstance(x, (jax.Array, np.ndarray))
