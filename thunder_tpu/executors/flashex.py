"""Flash-attention executor: Pallas TPU kernels claiming SDPA whole.

Reference parity: the cuDNN/sdpa executor seats
(thunder/executors/cudnnex.py:44 — fused SDPA fwd/bwd via cuDNN's graph
API; sdpaex.py:26 — flash/mem-efficient backend selection). Here the fused
kernels are the public JAX Pallas TPU flash-attention kernels (Mosaic), an
external kernel library in exactly the sense cuDNN is to the reference.

Claims:
- ``torch.scaled_dot_product_attention`` (forward) — online-softmax flash
  kernel; no (B, H, S, S) score materialization, the win that moves the
  single-chip memory ceiling (bench.py).
- ``torch.sdpa_bwd`` (backward composite emitted by the autodiff rule) —
  flash backward via the kernel's custom VJP with forward recompute.

Checker gates (fall back to the decomposition otherwise): no mask, no
dropout, q/kv seq lengths equal and divisible by the 128 block, head dim
≤ 256.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

from thunder_tpu.core.proxies import TensorProxy, pyval
from thunder_tpu.extend import OperatorExecutor, add_default_executor, register_executor

ex = OperatorExecutor("flash")
register_executor(ex)
add_default_executor(ex, front=True)

_BLOCK = 128


def _sdpa_bound(args, kwargs) -> dict:
    names = ("query", "key", "value", "attn_mask", "dropout_p", "is_causal", "scale", "enable_gqa")
    defaults = {"attn_mask": None, "dropout_p": 0.0, "is_causal": False, "scale": None, "enable_gqa": False}
    b = dict(zip(names, args))
    b.update(kwargs)
    for k, v in defaults.items():
        b.setdefault(k, v)
    return b


def _shapes_ok(q, k) -> bool:
    if not (isinstance(q, TensorProxy) or hasattr(q, "shape")):
        return False
    if len(q.shape) != 4 or len(k.shape) != 4:
        return False
    S, L, D = q.shape[-2], k.shape[-2], q.shape[-1]
    return S == L and S % _BLOCK == 0 and D <= 256


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() != "cpu"


def _sdpa_checker(*args, **kwargs) -> bool:
    b = _sdpa_bound(args, kwargs)
    return (
        _on_tpu()
        and b["attn_mask"] is None
        and float(pyval(b["dropout_p"])) == 0.0
        and _shapes_ok(b["query"], b["key"])
    )


def _bwd_checker(g, query, key, value, is_causal=False, scale=None, enable_gqa=False) -> bool:
    return _on_tpu() and _shapes_ok(query, key)


def _expand_gqa(k, v, H):
    import jax.numpy as jnp

    G = k.shape[-3]
    if G == H:
        return k, v
    rep = H // G
    return jnp.repeat(k, rep, axis=-3), jnp.repeat(v, rep, axis=-3)


def _flash(q, k, v, *, causal: bool, sm_scale: float):
    import jax
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes, flash_attention

    S = q.shape[-2]
    # Bigger blocks amortize the online-softmax bookkeeping: fwd 512 measured
    # 1.6× faster than 128 at S=2048 on v5e (block sweep in commit history);
    # bwd 512 vs 256 cut the open_llama_3b train step 0.888→0.807 s/iter
    # (train MFU 0.482→0.530, r3 ablations). 1024 measured neutral vs 512.
    def fit(pref):
        b = min(pref, S)
        while S % b:
            b //= 2
        return max(b, 1)

    b, bb = fit(512), fit(512)
    sizes = BlockSizes(
        block_q=b, block_k_major=b, block_k=b, block_b=1,
        block_q_major_dkv=bb, block_k_major_dkv=bb, block_k_dkv=bb, block_q_dkv=bb,
        block_k_major_dq=bb, block_k_dq=bb, block_q_dq=bb,
    )
    # The kernel's internal index math assumes 32-bit Python-int weak types;
    # scope out the runtime's x64 mode while tracing it.
    with jax.enable_x64(False):
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale, block_sizes=sizes)


def _sdpa_impl(*args, **kwargs):
    b = _sdpa_bound(args, kwargs)
    q, k, v = b["query"], b["key"], b["value"]
    H, D = q.shape[-3], q.shape[-1]
    scale = b["scale"] if b["scale"] is not None else 1.0 / math.sqrt(D)
    k, v = _expand_gqa(k, v, H)
    return _flash(q, k, v, causal=bool(b["is_causal"]), sm_scale=float(scale))


def _sdpa_bwd_impl(g, query, key, value, is_causal=False, scale=None, enable_gqa=False):
    import jax
    import jax.numpy as jnp

    H, D = query.shape[-3], query.shape[-1]
    G = key.shape[-3]
    sm_scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    k, v = _expand_gqa(key, value, H)

    f = partial(_flash, causal=bool(is_causal), sm_scale=sm_scale)
    with jax.enable_x64(False):
        _, vjp = jax.vjp(f, query, k, v)
        dq, dk, dv = vjp(g)

    if G != H:
        rep = H // G
        bshape = dk.shape[:-3]
        dk = dk.reshape(bshape + (G, rep) + dk.shape[-2:]).sum(axis=len(bshape) + 1)
        dv = dv.reshape(bshape + (G, rep) + dv.shape[-2:]).sum(axis=len(bshape) + 1)
    return dq.astype(query.dtype), dk.astype(key.dtype), dv.astype(value.dtype)


ex.register_implementation("torch.scaled_dot_product_attention", fn=_sdpa_impl, checker=_sdpa_checker)
ex.register_implementation("torch.sdpa_bwd", fn=_sdpa_bwd_impl, checker=_bwd_checker)
