"""Flash-attention executor: Pallas TPU splash-attention kernels claiming SDPA whole.

Reference parity: the cuDNN/sdpa executor seats
(thunder/executors/cudnnex.py:44 — fused SDPA fwd/bwd via cuDNN's graph
API, including the attn-mask bias input at cudnnex.py:81-92; sdpaex.py:26 —
flash/mem-efficient backend selection, incl. the head-dim padding at
sdpaex.py:49). Here the fused kernels are JAX's production splash-attention
Pallas TPU kernels (block-sparse flash with native causal skipping), an
external kernel library in exactly the sense cuDNN is to the reference.

Claims:
- ``torch.scaled_dot_product_attention`` (forward) — online-softmax flash;
  no (B, H, S, S) score materialization.
- ``torch.sdpa_bwd`` (backward composite emitted by the autodiff rule) —
  splash backward kernels via the kernel's custom VJP.

Mask support (the reference's cudnnex builds its graph with a bias input;
splash is mask-structured instead, so masks are handled by shape class):
- ``attn_mask=None`` (+ optional ``is_causal``): claimed directly.
- Key-padding masks — bool/additive of shape (S,), (B, 1, 1, S),
  (1, 1, 1, S) (the torch-broadcast shapes that are constant over the
  query axis; a 2D (X, S) mask aligns X with the QUERY dim in torch, so it
  is NOT key-padding and takes the decomposition): lowered to splash
  segment-ids. Additive key-padding masks are runtime-verified (entries
  must be 0 or very negative), and any row with no valid key falls back —
  torch's safe-softmax zeros vs kernel-defined output; on mismatch a
  ``lax.cond`` falls back to the exact decomposed SDPA, so claiming is
  always value-correct.
- 4D float/bool masks (B, 1, Sq, Skv) — the shape HF builds for padded
  causal batches: the kv-validity row is extracted at runtime, the mask is
  rebuilt as causal∧padding (and full∧padding), and compared; the flash
  path executes only when the rebuild matches (other masks — e.g. ALiBi
  biases — take the decomposed branch of the same ``lax.cond``).
  Positions whose query is padding are undefined in the flash branch
  (finite garbage, exactly like the reference's flash kernels) — HF-style
  consumers never read them.
- Unequal q/kv lengths and lengths not divisible by 128 are handled by
  in-executor padding with segment-ids (reference bar: sdpaex.py:49 pads
  head dims to stay on the fast path).

Tuning knobs (env): THUNDER_FLASH_IMPL=splash|legacy,
THUNDER_FLASH_BQ/BKV/BQ_DKV/BKV_DKV, THUNDER_FLASH_FUSED_BWD=1|0.
Block-size defaults (1024) were measured end-to-end on v5e: open_llama_3b
train iter 0.6979 (512) -> 0.6950 s (1024); fwd 1.1647 -> 1.1546 s (r4
ablations; 2048 regressed to 0.7080).
"""

from __future__ import annotations

import math
import os
from functools import lru_cache, partial
from typing import Optional

import numpy as np

from thunder_tpu.core.proxies import TensorProxy, pyval
from thunder_tpu.executors.jaxex import enable_x64 as jaxex_enable_x64
from thunder_tpu.extend import OperatorExecutor, add_default_executor, register_executor
from thunder_tpu.resilience import chaos

ex = OperatorExecutor("flash")
register_executor(ex)
add_default_executor(ex, front=True)

_PAD = 128  # sequence alignment quantum (Mosaic lane width)
_NEG_BIG = -1e9  # additive-mask entries at or below this count as "masked"


def _impl_name() -> str:
    return os.environ.get("THUNDER_FLASH_IMPL", "splash")


def _blk(name: str, dflt: int) -> int:
    return int(os.environ.get(name, dflt))


def _fused_bwd() -> bool:
    return os.environ.get("THUNDER_FLASH_FUSED_BWD", "1") == "1"


def _interpret() -> bool:
    import jax

    return jax.default_backend() == "cpu"


def _on_tpu() -> bool:
    import jax

    # THUNDER_FLASH_FORCE=1 lets tests exercise the splash path on the CPU
    # mesh via Pallas interpret mode.
    if os.environ.get("THUNDER_FLASH_FORCE") == "1":
        return True
    return jax.default_backend() != "cpu"


def _sdpa_bound(args, kwargs) -> dict:
    names = ("query", "key", "value", "attn_mask", "dropout_p", "is_causal", "scale", "enable_gqa")
    defaults = {"attn_mask": None, "dropout_p": 0.0, "is_causal": False, "scale": None, "enable_gqa": False}
    b = dict(zip(names, args))
    b.update(kwargs)
    for k, v in defaults.items():
        b.setdefault(k, v)
    return b


# =============================================================================
# Mask classification (shape-level; value checks happen at runtime)
# =============================================================================


def _is_bool(x) -> bool:
    from thunder_tpu.core import dtypes

    return dtypes.is_boolean_dtype(x.dtype)


def _mask_kind(m, q, k) -> str:
    """'none' | 'keypad' | 'keypad_verify' | 'verify4d' | 'no'."""
    if m is None:
        return "none"
    if not (isinstance(m, TensorProxy) or hasattr(m, "shape")):
        return "no"
    if getattr(m, "requires_grad", False):
        return "no"  # no mask cotangent from the fused kernel
    B, Tq = q.shape[0], q.shape[-2]
    Tkv = k.shape[-2]
    shp = tuple(m.shape)
    # torch-legal key-padding shapes: broadcastable to (B, H, Sq, Skv) while
    # constant over the query axis.
    keypad_shapes = {(Tkv,), (B, 1, 1, Tkv), (1, 1, 1, Tkv)}
    if shp in keypad_shapes:
        return "keypad" if _is_bool(m) else "keypad_verify"
    if len(shp) == 4 and shp[0] in (1, B) and shp[1] == 1 and shp[2] == Tq and shp[3] == Tkv:
        return "verify4d"
    return "no"


def _pad_amt(t: int) -> int:
    return (-t) % _PAD


def _dtype_ok(q, k, v) -> bool:
    """Half-precision only, like the reference's fused-SDPA executors
    (cudnnex.py:60 / sdpaex.py checkers reject fp32): the TPU kernel's
    internal MXU passes are bf16, so claiming f32 would silently lose the
    HIGHEST-precision semantics the decomposition provides."""
    from thunder_tpu.core import dtypes

    def half(t):
        dt = dtypes.to_dtype(t.dtype)
        return dt in (dtypes.bfloat16, dtypes.float16)

    return half(q) and half(k) and half(v)


def _shapes_ok(q, k) -> bool:
    if not (isinstance(q, TensorProxy) or hasattr(q, "shape")):
        return False
    if len(q.shape) != 4 or len(k.shape) != 4:
        return False
    S, L, D = q.shape[-2], k.shape[-2], q.shape[-1]
    if D > 256:
        return False
    # Below half a block of real work, padding waste dominates any kernel
    # win — keep the cheap decomposition.
    return S >= _PAD // 2 and L >= _PAD // 2


def _sdpa_checker(*args, **kwargs) -> bool:
    b = _sdpa_bound(args, kwargs)
    q, k = b["query"], b["key"]
    if not (_on_tpu() and float(pyval(b["dropout_p"])) == 0.0 and _shapes_ok(q, k)
            and _dtype_ok(q, k, b["value"])):
        return False
    if _impl_name() == "legacy":
        S, L = q.shape[-2], k.shape[-2]
        return b["attn_mask"] is None and S == L and S % _PAD == 0
    kind = _mask_kind(b["attn_mask"], q, k)
    if kind == "no":
        return False
    if kind != "none" and b["is_causal"]:
        return False  # torch: is_causal and attn_mask are mutually exclusive
    return True


def _bwd_checker(g, query, key, value, attn_mask=None, is_causal=False, scale=None, enable_gqa=False) -> bool:
    if not (_on_tpu() and _shapes_ok(query, key) and _dtype_ok(query, key, value)):
        return False
    if _impl_name() == "legacy":
        S, L = query.shape[-2], key.shape[-2]
        return attn_mask is None and S == L and S % _PAD == 0
    return _mask_kind(attn_mask, query, key) != "no"


# =============================================================================
# splash kernel construction (cached per static configuration)
# =============================================================================


def _fit_block(pref: int, t: int) -> int:
    b = min(pref, t)
    b -= b % _PAD
    b = max(b, _PAD)
    while t % b:
        b -= _PAD
    return max(b, _PAD)


@lru_cache(maxsize=64)
def _splash_kernel(H: int, Tq: int, Tkv: int, causal: bool, offset: int, interpret: bool,
                   bq: int, bkv: int, bqd: int, bkd: int, fused: bool, downcast: bool,
                   save_res: bool = False):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    block_sizes = sk.BlockSizes(
        block_q=bq, block_kv=bkv, block_kv_compute=bkv,
        block_q_dkv=bqd, block_kv_dkv=bkd, block_kv_dkv_compute=bkd,
        block_q_dq=None if fused else bqd,
        block_kv_dq=None if fused else bkd,
        use_fused_bwd_kernel=fused,
    )
    if causal:
        head_mask = sm.CausalMask((Tq, Tkv), offset=offset)
    else:
        head_mask = sm.FullMask((Tq, Tkv))
    mask = sm.MultiHeadMask([head_mask for _ in range(H)])
    import jax

    # The kernel object (mask-info arrays) is cached across jit traces —
    # build it outside the ambient trace so no tracer leaks into the cache.
    with jax.ensure_compile_time_eval():
        return sk.make_splash_mha(
            mask=mask, head_shards=1, q_seq_shards=1, block_sizes=block_sizes,
            interpret=interpret, downcast_smem_data=downcast, save_residuals=save_res,
        )


def _splash_sdpa(q, k, v, *, causal: bool, scale: float, kv_valid=None, q_valid=None):
    """Run splash attention with in-executor sequence padding.

    q: (B, H, Tq, D); k/v: (B, H, Tkv, D) (already GQA-expanded).
    kv_valid/q_valid: optional bool (B, T) — False positions never attend /
    are never attended to (lowered to splash segment-ids). Output positions
    with an invalid query are finite garbage and are expected to be ignored
    by the consumer (their cotangents are zero in the backward, so no
    garbage reaches dq/dk/dv at valid positions).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental.pallas.ops.tpu.splash_attention import splash_attention_kernel as sk

    B, H, Tq, D = q.shape
    Tkv = k.shape[-2]
    off = Tkv - Tq  # bottom-right causal alignment, matching the decomposition
    pq, pkv = _pad_amt(Tq), _pad_amt(Tkv)

    need_seg = kv_valid is not None or q_valid is not None or pq or pkv
    if pq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pq), (0, 0)))
    if pkv:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pkv), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pkv), (0, 0)))

    Tqp, Tkvp = Tq + pq, Tkv + pkv
    kernel = _splash_kernel(
        H, Tqp, Tkvp, causal, off, _interpret(),
        _fit_block(_blk("THUNDER_FLASH_BQ", 1024), Tqp),
        _fit_block(_blk("THUNDER_FLASH_BKV", 1024), Tkvp),
        _fit_block(_blk("THUNDER_FLASH_BQ_DKV", 1024), Tqp),
        _fit_block(_blk("THUNDER_FLASH_BKV_DKV", 1024), Tkvp),
        _fused_bwd(),
        # bf16 data is already narrow; keep f32 inputs at full precision in
        # SMEM (the downcast costs ~1e-3 abs error on f32 workloads).
        q.dtype == jnp.bfloat16,
    )
    qs = (q * jnp.asarray(scale, dtype=q.dtype)).astype(q.dtype)

    with jaxex_enable_x64(False):
        if need_seg:
            qv = jnp.ones((B, Tq), dtype=jnp.bool_) if q_valid is None else q_valid
            kvv = jnp.ones((B, Tkv), dtype=jnp.bool_) if kv_valid is None else kv_valid
            qv = jnp.pad(qv, ((0, 0), (0, pq)))
            kvv = jnp.pad(kvv, ((0, 0), (0, pkv)))
            seg = sk.SegmentIds(q=qv.astype(jnp.int32), kv=kvv.astype(jnp.int32))
            out = jax.vmap(kernel, in_axes=(0, 0, 0, sk.SegmentIds(q=0, kv=0)))(qs, k, v, seg)
        else:
            out = jax.vmap(kernel)(qs, k, v)
    return out[..., :Tq, :] if pq else out


# =============================================================================
# Runtime dispatch: mask → flash path (+ verified cond fallback)
# =============================================================================


def _xla_sdpa(q, k, v, attn_mask, causal: bool, scale: float):
    """Exact decomposed SDPA (the lax.cond fallback branch)."""
    import jax
    import jax.numpy as jnp

    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    Tq, Tkv = q.shape[-2], k.shape[-2]
    if causal:
        i = jnp.arange(Tq)[:, None]
        j = jnp.arange(Tkv)[None, :]
        s = jnp.where(i + (Tkv - Tq) >= j, s, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            s = jnp.where(attn_mask, s, -jnp.inf)
        else:
            s = s + attn_mask.astype(jnp.float32)
    # torch-sdpa safe-softmax: fully-masked rows yield zeros, not NaN
    dead = jnp.max(s, axis=-1, keepdims=True) == -jnp.inf
    p = jnp.where(dead, 0.0, jax.nn.softmax(s, axis=-1)).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _mask_kind_rt(m, q, k) -> str:
    """Runtime twin of _mask_kind (on concrete arrays)."""
    import jax.numpy as jnp

    class _Shim:
        def __init__(self, x):
            self.shape = x.shape
            self.requires_grad = False
            self.dtype = x.dtype

    if m is None:
        return "none"
    B, Tq, Tkv = q.shape[0], q.shape[-2], k.shape[-2]
    shp = tuple(m.shape)
    if shp in {(Tkv,), (B, 1, 1, Tkv), (1, 1, 1, Tkv)}:
        return "keypad" if m.dtype == jnp.bool_ else "keypad_verify"
    return "verify4d"


def _sdpa_runtime(q, k, v, attn_mask, causal: bool, scale: float):
    """Dispatch one SDPA call to splash, with runtime-verified fallbacks."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, H, Tq, D = q.shape
    Tkv = k.shape[-2]
    kind = _mask_kind_rt(attn_mask, q, k)

    if kind == "none":
        return _splash_sdpa(q, k, v, causal=causal, scale=scale)

    if kind in ("keypad", "keypad_verify"):
        m = jnp.reshape(attn_mask, (-1, Tkv))
        m = jnp.broadcast_to(m, (B, Tkv))
        if kind == "keypad":
            kv_valid = m
            ok = jnp.ones((), dtype=jnp.bool_)
        else:
            # additive key-padding: entries must be 0 (keep) or <= _NEG_BIG (drop)
            kv_valid = m == 0
            ok = jnp.all(kv_valid | (m <= _NEG_BIG))
        # A row with NO valid key must take the exact branch: torch's
        # safe-softmax yields zeros there, while splash's output for a query
        # with no matching segment is kernel-defined (ADVICE r4). Softmax
        # shift-invariance also means an all-(-1e9) additive row attends
        # normally in the exact path but masks everything in segment-ids.
        ok = ok & jnp.all(jnp.any(kv_valid, axis=-1))
        return lax.cond(
            ok,
            lambda q, k, v: _splash_sdpa(q, k, v, causal=causal, scale=scale, kv_valid=kv_valid),
            lambda q, k, v: _xla_sdpa(q, k, v, attn_mask, causal, scale),
            q, k, v,
        )

    # verify4d: (1|B, 1, Tq, Tkv) — HF's padded causal (or full) mask.
    m4 = jnp.broadcast_to(attn_mask, (B, 1, Tq, Tkv))[:, 0]  # (B, Tq, Tkv)
    if m4.dtype == jnp.bool_:
        visible = m4
    else:
        visible = m4 == 0
        # additive entries must be 0/very-negative for the rebuild to be valid
        additive_ok = jnp.all(visible | (m4 <= _NEG_BIG))
    kv_valid = visible[:, -1, :]  # last query row sees every valid key (causal)
    # q validity: self-attention ⇒ q tokens are the last Tq of the kv axis
    q_valid = kv_valid[:, Tkv - Tq:]
    i = jnp.arange(Tq)[:, None]
    j = jnp.arange(Tkv)[None, :]
    causal_tri = i + (Tkv - Tq) >= j  # (Tq, Tkv)
    rebuild_causal = causal_tri[None] & kv_valid[:, None, :]
    rebuild_full = jnp.broadcast_to(kv_valid[:, None, :], visible.shape)
    rows_ok = q_valid[:, :, None]  # only rows with a valid query must match
    ok_causal = jnp.all((rebuild_causal == visible) | ~rows_ok)
    ok_full = jnp.all((rebuild_full == visible) | ~rows_ok)
    if m4.dtype != jnp.bool_:
        ok_causal = ok_causal & additive_ok
        ok_full = ok_full & additive_ok

    def flash_causal(q, k, v):
        return _splash_sdpa(q, k, v, causal=True, scale=scale, kv_valid=kv_valid, q_valid=q_valid)

    def flash_full(q, k, v):
        return _splash_sdpa(q, k, v, causal=False, scale=scale, kv_valid=kv_valid, q_valid=q_valid)

    def fallback(q, k, v):
        return lax.cond(
            ok_full, flash_full,
            lambda q, k, v: _xla_sdpa(q, k, v, attn_mask, causal, scale),
            q, k, v,
        )

    return lax.cond(ok_causal, flash_causal, fallback, q, k, v)


# =============================================================================
# Legacy kernel (THUNDER_FLASH_IMPL=legacy; unmasked, aligned shapes only)
# =============================================================================


def _legacy_flash(q, k, v, *, causal: bool, sm_scale: float):
    import jax
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes, flash_attention

    S = q.shape[-2]
    # r3 block sweep: fwd 512 measured 1.6× faster than 128 at S=2048 on
    # v5e; bwd 512 vs 256 cut the open_llama_3b train step 0.888→0.807.
    def fit(pref):
        b = min(pref, S)
        while S % b:
            b //= 2
        return max(b, 1)

    b = fit(512)
    sizes = BlockSizes(
        block_q=b, block_k_major=b, block_k=b, block_b=1,
        block_q_major_dkv=b, block_k_major_dkv=b, block_k_dkv=b, block_q_dkv=b,
        block_k_major_dq=b, block_k_dq=b, block_q_dq=b,
    )
    # The kernel's internal index math assumes 32-bit Python-int weak types;
    # scope out the runtime's x64 mode while tracing it.
    with jaxex_enable_x64(False):
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale, block_sizes=sizes)


# =============================================================================
# Claimed implementations
# =============================================================================


def _expand_gqa(k, v, H):
    import jax.numpy as jnp

    G = k.shape[-3]
    if G == H:
        return k, v
    rep = H // G
    return jnp.repeat(k, rep, axis=-3), jnp.repeat(v, rep, axis=-3)


def _sdpa_impl(*args, **kwargs):
    chaos.kernel_seam("flash", "sdpa")
    b = _sdpa_bound(args, kwargs)
    q, k, v = b["query"], b["key"], b["value"]
    H, D = q.shape[-3], q.shape[-1]
    scale = float(b["scale"]) if b["scale"] is not None else 1.0 / math.sqrt(D)
    k, v = _expand_gqa(k, v, H)
    if _impl_name() == "legacy":
        return _legacy_flash(q, k, v, causal=bool(b["is_causal"]), sm_scale=scale)
    return _sdpa_runtime(q, k, v, b["attn_mask"], bool(b["is_causal"]), scale)


def _sdpa_bwd_impl(g, query, key, value, attn_mask=None, is_causal=False, scale=None, enable_gqa=False):
    chaos.kernel_seam("flash", "sdpa_bwd")
    import jax

    H, D = query.shape[-3], query.shape[-1]
    G = key.shape[-3]
    sm_scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    k, v = _expand_gqa(key, value, H)

    if _impl_name() == "legacy":
        f = partial(_legacy_flash, causal=bool(is_causal), sm_scale=sm_scale)
    else:
        f = lambda q, k, v: _sdpa_runtime(q, k, v, attn_mask, bool(is_causal), sm_scale)
    with jaxex_enable_x64(False):
        _, vjp = jax.vjp(f, query, k, v)
        dq, dk, dv = vjp(g)

    if G != H:
        rep = H // G
        bshape = dk.shape[:-3]
        dk = dk.reshape(bshape + (G, rep) + dk.shape[-2:]).sum(axis=len(bshape) + 1)
        dv = dv.reshape(bshape + (G, rep) + dv.shape[-2:]).sum(axis=len(bshape) + 1)
    return dq.astype(query.dtype), dk.astype(key.dtype), dv.astype(value.dtype)


# =============================================================================
# Residual-saving pair (transforms/attention_residuals.py; reference:
# cudnnex.py:375 — bwd graph consumes the fwd's saved softmax stats)
# =============================================================================


def residual_eligible(q, k, v) -> bool:
    """The attention-residual pass asks before rewriting: both sides must be
    claimable without padding or masks (the no-recompute path keeps the
    simplest geometry; everything else stays on the recompute composite)."""
    if not (_on_tpu() and _impl_name() == "splash" and _dtype_ok(q, k, v)):
        return False
    if len(q.shape) != 4 or len(k.shape) != 4:
        return False
    S, L, D = q.shape[-2], k.shape[-2], q.shape[-1]
    return S == L and S % _PAD == 0 and D <= 256


def _fwd_res_checker(query, key, value, attn_mask=None, is_causal=False, scale=None, enable_gqa=False):
    return attn_mask is None and residual_eligible(query, key, value)


def _bwd_res_checker(g, query, key, value, out, lse, attn_mask=None, is_causal=False,
                     scale=None, enable_gqa=False):
    return attn_mask is None and residual_eligible(query, key, value)


def _splash_fwd_res(q, k, v, *, causal: bool, scale: float):
    import jax
    import jax.numpy as jnp

    B, H, Tq, D = q.shape
    Tkv = k.shape[-2]
    kernel = _splash_kernel(
        H, Tq, Tkv, causal, Tkv - Tq, _interpret(),
        _fit_block(_blk("THUNDER_FLASH_BQ", 1024), Tq),
        _fit_block(_blk("THUNDER_FLASH_BKV", 1024), Tkv),
        _fit_block(_blk("THUNDER_FLASH_BQ_DKV", 1024), Tq),
        _fit_block(_blk("THUNDER_FLASH_BKV_DKV", 1024), Tkv),
        _fused_bwd(),
        q.dtype == jnp.bfloat16,
        True,
    )
    qs = (q * jnp.asarray(scale, dtype=q.dtype)).astype(q.dtype)
    with jaxex_enable_x64(False):
        out, (lse,) = jax.vmap(kernel)(qs, k, v)
    return out, lse[..., :Tq].astype(jnp.float32)


def _sdpa_fwd_res_impl(query, key, value, attn_mask=None, is_causal=False, scale=None, enable_gqa=False):
    H, D = query.shape[-3], query.shape[-1]
    sm_scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    k, v = _expand_gqa(key, value, H)
    return _splash_fwd_res(query, k, v, causal=bool(is_causal), scale=sm_scale)


def _sdpa_bwd_res_impl(g, query, key, value, out, lse, attn_mask=None, is_causal=False,
                       scale=None, enable_gqa=False):
    """Direct splash backward from saved (out, lse) — no forward recompute
    (the jax.vjp route re-runs the forward kernel to rebuild these exact
    residuals; r4 profile: 24.5 ms/iter on the 3B bench)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.pallas.ops.tpu.splash_attention import splash_attention_kernel as sk

    B, H, Tq, D = query.shape
    G = key.shape[-3]
    Tkv = key.shape[-2]
    sm_scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    k, v = _expand_gqa(key, value, H)

    kernel = _splash_kernel(
        H, Tq, Tkv, bool(is_causal), Tkv - Tq, _interpret(),
        _fit_block(_blk("THUNDER_FLASH_BQ", 1024), Tq),
        _fit_block(_blk("THUNDER_FLASH_BKV", 1024), Tkv),
        _fit_block(_blk("THUNDER_FLASH_BQ_DKV", 1024), Tq),
        _fit_block(_blk("THUNDER_FLASH_BKV_DKV", 1024), Tkv),
        _fused_bwd(),
        query.dtype == jnp.bfloat16,
        False,
    )
    kw = dict(kernel.kwargs)
    qs = (query * jnp.asarray(sm_scale, dtype=query.dtype)).astype(query.dtype)

    def one(qb, kb, vb, ob, lseb, gb):
        res = (qb, kb, vb, None, None, ob, lseb, kernel.dq_mask_info, kernel.dkv_mask_info)
        grads = sk._splash_attention_bwd(
            False,
            kw.get("mask_value", -0.7 * float(np.finfo(np.dtype("float32")).max)),
            kw.get("is_mqa", False),
            kw.get("block_sizes"),
            kw.get("residual_checkpoint_name"),
            kw.get("mask_function"),
            kw.get("attn_logits_soft_cap"),
            kw.get("interpret", False),
            res,
            gb,
        )
        return grads[3], grads[4], grads[5]

    with jaxex_enable_x64(False):
        dqs, dk, dv = jax.vmap(one)(qs, k, v, out, lse.astype(jnp.float32), g)
    dq = dqs.astype(jnp.float32) * sm_scale  # fwd consumed q*scale

    if G != H:
        rep = H // G
        bshape = dk.shape[:-3]
        dk = dk.reshape(bshape + (G, rep) + dk.shape[-2:]).sum(axis=len(bshape) + 1)
        dv = dv.reshape(bshape + (G, rep) + dv.shape[-2:]).sum(axis=len(bshape) + 1)
    return dq.astype(query.dtype), dk.astype(key.dtype), dv.astype(value.dtype)


ex.register_implementation("torch.scaled_dot_product_attention", fn=_sdpa_impl, checker=_sdpa_checker)
ex.register_implementation("torch.sdpa_bwd", fn=_sdpa_bwd_impl, checker=_bwd_checker)
ex.register_implementation("torch.sdpa_fwd_res", fn=_sdpa_fwd_res_impl, checker=_fwd_res_checker)
ex.register_implementation("torch.sdpa_bwd_res", fn=_sdpa_bwd_res_impl, checker=_bwd_res_checker)
