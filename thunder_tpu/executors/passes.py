"""The claiming pass and codegen-adjacent passes.

Reference parity: thunder/executors/passes.py (`transform_for_execution:131`
— operator-executor claiming, fusion passes, always-executors —
and `del_last_used:232`).

Claiming walks each top-level bound symbol: the first executor in priority
order whose checker accepts it claims it whole; otherwise the pass descends
into the symbol's decomposition (subsymbols). Terminal prims must be claimed
by someone (the JAX executor covers all of them).
"""

from __future__ import annotations

import copy
import time
from typing import Sequence

from thunder_tpu.core.baseutils import check
from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import Proxy, variableify
from thunder_tpu.core.pytree import tree_flatten
from thunder_tpu.core.symbol import BoundSymbol, Symbol
from thunder_tpu.core.trace import TraceCtx, from_trace, wrap_in_trace_provenance
from thunder_tpu.extend import Executor, FusionExecutor, get_always_executors

_PASSTHROUGH_IDS = {
    PrimIDs.DEL,
    PrimIDs.RETURN,
    PrimIDs.COMMENT,
    PrimIDs.UNPACK_TRIVIAL,
    PrimIDs.UNPACK_SEQUENCE,
    PrimIDs.UNPACK_KEY,
    PrimIDs.UNPACK_ATTR,
    PrimIDs.UNPACK_DIM,  # printer emits `d = t.shape[i]`, any backend
    PrimIDs.TENSOR_CONSTANT,  # printer emits a _call_ctx binding, any backend
}


def _claimed(sym: Symbol, ex: Executor) -> Symbol:
    new = copy.copy(sym)
    new.executor = ex
    return new


def transform_for_execution(
    trace: TraceCtx,
    executors_list: Sequence[Executor],
    *,
    comm_schedule: bool = False,
    comm_schedule_opts: dict | None = None,
) -> TraceCtx:
    """Claim every bound symbol, run fusion passes, and — when
    ``comm_schedule=True`` and ``THUNDER_TPU_COMM_SCHEDULE`` permits — run
    the certificate-driven collective-overlap scheduler
    (``transforms/comm_schedule.py``) over the claimed trace.
    ``comm_schedule_opts`` forwards ``device``/``capacity_bytes``/
    ``arg_divisors`` to the scheduler."""
    start = time.perf_counter_ns()
    executors_list = tuple(executors_list) + get_always_executors()
    new_bsyms: list[BoundSymbol] = []

    # Executor demotion (resilience/demotion.py): a (sym, executor) pair
    # quarantined after a kernel failure is skipped here, so the re-claim
    # walks down the priority list to jaxex/pythonex until the TTL expires.
    from thunder_tpu.resilience.demotion import is_quarantined

    def claim(bsym: BoundSymbol, depth: int = 0) -> None:
        if bsym.sym.id in _PASSTHROUGH_IDS:
            new_bsyms.append(bsym)
            return
        for ex in executors_list:
            if is_quarantined(bsym.sym.id, ex.name):
                continue
            if ex.can_execute(bsym):
                new_bsyms.append(bsym.from_bsym(sym=_claimed(bsym.sym, ex)))
                return
        if bsym.sym.python_impl is not None:
            # Host-side op with an inline implementation (guards etc.)
            new_bsyms.append(bsym)
            return
        if not bsym.subsymbols and not (
            bsym.has_tag(OpTags.SIDE_EFFECT) or bsym.has_tag(OpTags.DONT_DCE)
        ):
            # A composite whose decomposition recorded nothing is an identity
            # (e.g. ``x[...]`` with full slices, dropout(p=0)): its outputs
            # ARE its input proxies, so the op can simply be dropped — unless
            # it is tagged effectful, in which case dropping it would erase an
            # observable action (the verifier/DCE share this tag model).
            arg_vars = {variableify(p) for p in bsym.flat_proxy_args}
            if all(variableify(o) in arg_vars for o in bsym.flat_proxy_outs):
                return
        check(
            len(bsym.subsymbols) > 0,
            lambda: f"No executor for primitive {bsym.sym.qualname} (id {bsym.sym.id})",
        )
        for sub in bsym.subsymbols:
            claim(sub, depth + 1)

    for bsym in trace.bound_symbols:
        claim(bsym)

    extrace = from_trace(trace)
    extrace.bound_symbols = new_bsyms

    # Fusion executors run after claiming (reference: passes.py:145); on TPU
    # XLA is the fusion engine so this is typically a no-op hook.
    for ex in executors_list:
        if isinstance(ex, FusionExecutor):
            extrace = ex.fusion_pass(extrace)

    extrace.tags["claim_breakdown"] = _claim_breakdown(extrace)
    extrace.tags["collective_bytes"] = _collective_bytes(extrace)
    extrace = wrap_in_trace_provenance(extrace, "Transform for execution", start)

    if comm_schedule:
        from thunder_tpu.transforms import comm_schedule as comm_sched

        if comm_sched.enabled():
            extrace, _ = comm_sched.schedule_collectives(
                extrace, **(comm_schedule_opts or {})
            )
    return extrace


def _claim_breakdown(trace: TraceCtx) -> dict[str, int]:
    """{executor name (or "host" for python_impl plumbing): claimed bsyms} —
    the observability subsystem's executor-claim metric/event payload."""
    out: dict[str, int] = {}
    for bsym in trace.bound_symbols:
        ex = bsym.sym.executor
        name = ex.name if ex is not None else "host"
        out[name] = out.get(name, 0) + 1
    return out


def _collective_bytes(trace: TraceCtx) -> int:
    """Static bytes moved by collectives (COMM_OP-tagged symbols), from the
    trace's tensor metadata: each collective is charged its tensor operands'
    sizes. A per-trace constant — the dispatcher multiplies by call counts."""
    from thunder_tpu.core.proxies import TensorProxy

    total = 0
    for bsym in trace.bound_symbols:
        if OpTags.COMM_OP not in bsym.sym.tags:
            continue
        for p in bsym.flat_proxy_args:
            if isinstance(p, TensorProxy):
                total += p.size_bytes
    return total


def del_last_used(trace: TraceCtx, *, clear_mutable_collections: bool = False) -> TraceCtx:
    """Insert ``del`` statements after each proxy's last use
    (reference: passes.py `del_last_used:232`).

    Under whole-trace XLA staging this is cosmetic for device memory (XLA
    buffer liveness governs), but it keeps host references from pinning
    donated arrays and preserves the reference's readable-trace contract.
    """
    from thunder_tpu.core import prims

    start = time.perf_counter_ns()
    flat_out, _ = tree_flatten(trace.output)
    keep = {variableify(p) for p in flat_out if isinstance(p, Proxy)}
    flat_args, _ = tree_flatten((trace.args, trace.kwargs))
    arg_vars = {variableify(p) for p in flat_args if isinstance(p, Proxy)}

    seen: set = set()
    rev: list[BoundSymbol] = []
    for bsym in reversed(trace.bound_symbols):
        if bsym.sym.id in (PrimIDs.DEL,):
            continue
        to_del = []
        for p in list(bsym.flat_proxy_args) + list(bsym.flat_proxy_outs):
            v = variableify(p)
            if v in seen or v in keep:
                continue
            seen.add(v)
            to_del.append(p)
        if to_del and bsym.sym.id not in (PrimIDs.RETURN,):
            rev.append(prims.python_del.bind(*to_del, output=None))
        rev.append(bsym)
    new_bsyms = list(reversed(rev))

    ntrace = from_trace(trace)
    ntrace.bound_symbols = new_bsyms
    return wrap_in_trace_provenance(ntrace, "Delete Last Used", start)
