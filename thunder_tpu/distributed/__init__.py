"""Distributed API: trace-level collectives + DDP/FSDP entry points.

Reference parity: thunder/distributed/__init__.py (`ddp:88`, `fsdp:303`,
`FSDPType:248`, `FSDPBucketingStrategy:261`, `no_sync:27-67`).

TPU-first split of responsibilities:
- This package provides the reference's *capability surface*: collective
  prims in traces (prims.py), DDP/FSDP marking of parameters, the
  grad-sync semantics on the `synchronize` prim's VJP, and a `no_sync`
  context.
- The *performance path* — mesh + PartitionSpec + XLA SPMD partitioning —
  lives in ``thunder_tpu.parallel``; `ddp()`/`fsdp()` here resolve to
  sharding plans on that path. Bucketing and wait-sorting have no seat:
  XLA's collective combiners and latency-hiding scheduler do that job
  (SURVEY.md §7 stage 8: "validate, don't assume" — validated by the
  overlap tests in tests/test_distributed.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import enum
from typing import Any, Optional

from thunder_tpu.core.proxies import DistParallelType


class FSDPType(enum.Enum):
    """Reference parity: thunder/distributed/__init__.py `FSDPType:248`."""

    ZERO2 = enum.auto()
    ZERO3 = enum.auto()


class FSDPBucketingStrategy(enum.Enum):
    """Reference parity: `FSDPBucketingStrategy:261`. Accepted for API
    compatibility; it deliberately has no effect here — collective
    coalescing is XLA's combiner pass (the `sort_waits`/bucketing seat,
    SURVEY §5), tunable globally via
    `--xla_tpu_*_combine_threshold_bytes` XLA flags rather than per-call."""

    NONE = enum.auto()
    LAYER = enum.auto()
    BLOCK = enum.auto()


_initialized = False


def init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
    **kwargs,
) -> dict:
    """Multi-host bootstrap (VERDICT r2 item 8).

    The reference delegates rank bootstrap to torchrun + NCCL process groups
    (thunder/benchmarks/benchmark_litgpt.py:24 `init_process_group`); the TPU
    seat is ``jax.distributed.initialize`` (SURVEY.md §5): on a TPU pod slice
    every argument auto-detects from the TPU metadata, so ``init()`` with no
    arguments is the whole multi-controller bootstrap. Explicit arguments
    cover CPU/GPU clusters (coordinator ip:port, world size, rank).

    Idempotent; returns {"process_id", "num_processes", "devices",
    "local_devices"} for the caller's logging.
    """
    global _initialized
    import jax

    if not _initialized:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
            **kwargs,
        )
        _initialized = True
    else:
        # A repeat call with explicit arguments that CONTRADICT the live
        # runtime is a misconfigured bootstrap, not idempotence (ADVICE r3:
        # silently ignoring the args masks wiring bugs in multi-host launch
        # scripts).
        for name, given, active in (
            ("process_id", process_id, jax.process_index()),
            ("num_processes", num_processes, jax.process_count()),
        ):
            if given is not None and given != active:
                raise RuntimeError(
                    f"distributed.init(): {name}={given} conflicts with the active "
                    f"runtime ({name}={active}); call shutdown() first to rebootstrap"
                )
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }


def shutdown() -> None:
    """Tear down the multi-host runtime (torchrun-exit analogue)."""
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False


def is_initialized() -> bool:
    return _initialized


_skip_data_sync = contextvars.ContextVar("skip_data_sync", default=False)


@contextlib.contextmanager
def no_sync():
    """Skip grad all-reduce inside the context (gradient accumulation).
    Reference parity: thunder/distributed/__init__.py:27-67."""
    tok = _skip_data_sync.set(True)
    try:
        yield
    finally:
        _skip_data_sync.reset(tok)


def skip_data_parallel_grad_sync() -> bool:
    return _skip_data_sync.get()


def _is_torch_module(x) -> bool:
    try:
        import torch

        return isinstance(x, torch.nn.Module)
    except ImportError:
        return False


def _is_thunder_module(x) -> bool:
    from thunder_tpu.frontend.module import ThunderModule

    return isinstance(x, ThunderModule)


def _validate_dist_cfg(cfg: dict) -> None:
    mesh = cfg.get("mesh")
    if mesh is None:
        # Reference parity: `fsdp(model)` / `ddp(model)` with no process
        # group uses the default world. Here the world is all local jax
        # devices — resolve a 1-axis mesh over them rather than silently
        # compiling single-device with no sharding/grad-sync.
        import jax
        from jax.sharding import Mesh
        import numpy as _numpy

        devs = jax.devices()
        cfg["mesh"] = Mesh(_numpy.array(devs), (cfg["axis"],))
        return
    axis = cfg.get("axis")
    if axis not in mesh.axis_names:
        raise ValueError(
            f"{cfg.get('mode')}(axis={axis!r}) but the mesh has axes {tuple(mesh.axis_names)}; "
            f"pass axis=<one of them> (silently compiling single-device would drop the sharding)"
        )


def _attach_dist_config(model, cfg: dict):
    """Tag a torch module / ThunderModule so the jit pipeline inserts
    `dist_prims.synchronize` for its params at trace time and stages the
    compiled traces under shard_map over the mesh (the flagship workflow:
    reference thunder/common.py:521-528 inserts synchronize for tagged
    params during tracing; the VJP at distributed/prims.py:260-298 emits
    grad sync into the backward)."""
    _validate_dist_cfg(cfg)
    if _is_thunder_module(model):
        model.configure_distributed(cfg)
        return model
    model._thunder_dist = cfg
    return model


def ddp(model_or_params, *, mesh=None, axis: str = "dp", broadcast_from: Optional[int] = 0,
        shard_data: bool = True):
    """Mark a model/params replicated for data-parallel training
    (reference: `ddp:88`).

    - torch ``nn.Module`` / ``ThunderModule``: tags the module; at trace time
      every param passes through `synchronize` (identity forward, pre-scaled
      all-reduce backward) and the traces stage under shard_map on ``mesh``.
      ``broadcast_from`` exists for reference API parity (`__init__.py:150-163`);
      in this single-controller runtime every device is initialized from the
      one host copy, so root-rank broadcast is satisfied by construction and
      the value is accepted but has no further effect.
      ``shard_data=False`` disables batch sharding of data inputs (use when
      dim 0 of an input is not the batch dim).
    - params pytree of proxies: marks `dist_parallel_type` (trace-level IR).
    """
    if _is_torch_module(model_or_params) or _is_thunder_module(model_or_params):
        cfg = {"mode": "ddp", "mesh": mesh, "axis": axis, "broadcast_from": broadcast_from,
               "shard_data": shard_data}
        return _attach_dist_config(model_or_params, cfg)

    from thunder_tpu.core.pytree import tree_map
    from thunder_tpu.core.proxies import TensorProxy

    def mark(p):
        if isinstance(p, TensorProxy):
            p.dist_parallel_type = DistParallelType.REPLICATED
        return p

    return tree_map(mark, model_or_params)


def fsdp(
    model_or_params,
    *,
    mesh=None,
    sharding_strategy: FSDPType = FSDPType.ZERO3,
    bucketing_strategy: FSDPBucketingStrategy = FSDPBucketingStrategy.NONE,
    axis: str = "fsdp",
    shard_data: bool = True,
):
    """Mark a model/params fully-sharded (reference: `fsdp:303`,
    dim-0 `_shard_param:406`).

    - torch ``nn.Module`` / ``ThunderModule``: tags the module; params live
      dim-0-sharded on the mesh, `synchronize` (all-gather) is inserted at
      trace time, and the backward carries the grad reduce-scatter — the
      reference's flagship `fsdp(model); thunder.jit(model)` workflow.
    - params pytree: marks proxies / device_puts arrays with dim-0-sharded
      NamedShardings (the GSPMD path).
    """
    if _is_torch_module(model_or_params) or _is_thunder_module(model_or_params):
        cfg = {
            "mode": "fsdp",
            "mesh": mesh,
            "axis": axis,
            "fsdp_type": sharding_strategy,
            "bucketing": bucketing_strategy,
            "shard_data": shard_data,
        }
        return _attach_dist_config(model_or_params, cfg)

    from thunder_tpu.core.pytree import tree_map
    from thunder_tpu.core.proxies import TensorProxy

    def mark(p):
        if isinstance(p, TensorProxy):
            p.dist_parallel_type = DistParallelType.FULLY_SHARDED
        return p

    marked = tree_map(mark, model_or_params)
    if mesh is None:
        return marked

    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n = sizes.get(axis, 1)

    def shard(p):
        if hasattr(p, "shape") and p.ndim >= 1 and p.shape[0] % n == 0 and n > 1:
            spec = PartitionSpec(axis, *([None] * (p.ndim - 1)))
        else:
            spec = PartitionSpec()
        return jax.device_put(p, NamedSharding(mesh, spec))

    return tree_map(shard, marked)


from thunder_tpu.distributed import prims  # noqa: E402,F401
