"""Distributed API: trace-level collectives + DDP/FSDP entry points.

Reference parity: thunder/distributed/__init__.py (`ddp:88`, `fsdp:303`,
`FSDPType:248`, `FSDPBucketingStrategy:261`, `no_sync:27-67`).

TPU-first split of responsibilities:
- This package provides the reference's *capability surface*: collective
  prims in traces (prims.py), DDP/FSDP marking of parameters, the
  grad-sync semantics on the `synchronize` prim's VJP, and a `no_sync`
  context.
- The *performance path* — mesh + PartitionSpec + XLA SPMD partitioning —
  lives in ``thunder_tpu.parallel``; `ddp()`/`fsdp()` here resolve to
  sharding plans on that path. Bucketing and wait-sorting have no seat:
  XLA's collective combiners and latency-hiding scheduler do that job
  (SURVEY.md §7 stage 8: "validate, don't assume" — validated by the
  overlap tests in tests/test_distributed.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import enum
from typing import Any, Optional

from thunder_tpu.core.proxies import DistParallelType


class FSDPType(enum.Enum):
    """Reference parity: thunder/distributed/__init__.py `FSDPType:248`."""

    ZERO2 = enum.auto()
    ZERO3 = enum.auto()


class FSDPBucketingStrategy(enum.Enum):
    """Reference parity: `FSDPBucketingStrategy:261`. On TPU, bucketing is
    XLA's collective-combiner's job; accepted for API compatibility and used
    as a hint for the combiner threshold flag."""

    NONE = enum.auto()
    LAYER = enum.auto()
    BLOCK = enum.auto()


_skip_data_sync = contextvars.ContextVar("skip_data_sync", default=False)


@contextlib.contextmanager
def no_sync():
    """Skip grad all-reduce inside the context (gradient accumulation).
    Reference parity: thunder/distributed/__init__.py:27-67."""
    tok = _skip_data_sync.set(True)
    try:
        yield
    finally:
        _skip_data_sync.reset(tok)


def skip_data_parallel_grad_sync() -> bool:
    return _skip_data_sync.get()


def ddp(model_or_params, *, mesh=None, axis: str = "dp", broadcast_from: int = 0):
    """Mark a params pytree (or ThunderModule) replicated for data-parallel
    training (reference: `ddp:88`). On the mesh path this resolves to
    replicated param specs + batch-sharded data; grad sync is a psum the
    partitioner inserts from the sharding contract."""
    from thunder_tpu.core.pytree import tree_map
    from thunder_tpu.core.proxies import TensorProxy

    def mark(p):
        if isinstance(p, TensorProxy):
            p.dist_parallel_type = DistParallelType.REPLICATED
        return p

    return tree_map(mark, model_or_params)


def fsdp(
    model_or_params,
    *,
    mesh=None,
    sharding_strategy: FSDPType = FSDPType.ZERO3,
    bucketing_strategy: FSDPBucketingStrategy = FSDPBucketingStrategy.NONE,
    axis: str = "fsdp",
):
    """Mark a params pytree fully-sharded (reference: `fsdp:303`,
    dim-0 `_shard_param:406`). With a mesh, returns the pytree device_put
    with dim-0-sharded NamedShardings — the same layout the reference
    shards to, expressed as sharding metadata instead of narrowed tensors."""
    from thunder_tpu.core.pytree import tree_map
    from thunder_tpu.core.proxies import TensorProxy

    def mark(p):
        if isinstance(p, TensorProxy):
            p.dist_parallel_type = DistParallelType.FULLY_SHARDED
        return p

    marked = tree_map(mark, model_or_params)
    if mesh is None:
        return marked

    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n = sizes.get(axis, 1)

    def shard(p):
        if hasattr(p, "shape") and p.ndim >= 1 and p.shape[0] % n == 0 and n > 1:
            spec = PartitionSpec(axis, *([None] * (p.ndim - 1)))
        else:
            spec = PartitionSpec()
        return jax.device_put(p, NamedSharding(mesh, spec))

    return tree_map(shard, marked)


from thunder_tpu.distributed import prims  # noqa: E402,F401
