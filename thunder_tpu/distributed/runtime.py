"""Staging traces with explicit collectives onto a device mesh.

A trace containing ``dist_prims`` collectives references mesh axes by name;
this module stages its compiled callable inside ``shard_map`` over a
``jax.sharding.Mesh`` so those names resolve, then ``jax.jit``s the result —
one SPMD executable per host, collectives riding ICI/DCN.

Reference analogue: the runtime seat of the generated code calling
`torch_all_gather_prim_impl` → NCCL (thunder/executors/torchex.py:1709-1729)
— except the program is compiled once and the comm/compute overlap is XLA's
latency-hiding scheduler rather than stream juggling.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence


def shard_map_callable(fn: Callable, mesh, in_specs, out_specs, *, check_rep: bool = False,
                       trace_lines=None, schedule=None) -> Callable:
    """Wrap a pure callable in shard_map over ``mesh`` and jit it.

    The result routes through the collective watchdog
    (``resilience/watchdog.guard_call``) whenever a timeout is configured
    (``THUNDER_TPU_COLLECTIVE_TIMEOUT_S`` / ``watchdog.configure``): a
    shard_map program IS a collective dispatch site, so a peer that stops
    participating raises a typed ``CollectiveTimeoutError`` (naming
    ``trace_lines`` when the caller has them) instead of hanging the host
    forever. Unconfigured, the wrapper is one dict probe per call."""
    import jax

    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # newer jax
        from jax.shard_map import shard_map  # type: ignore

    from thunder_tpu.resilience import watchdog

    inner = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_rep)
    return watchdog.wrap(
        jax.jit(inner),
        fn_name=getattr(fn, "__name__", "shard_map"),
        trace_lines=trace_lines,
        schedule=schedule,
    )


def compile_with_collectives(
    fn: Callable,
    example_args: tuple,
    mesh,
    in_specs,
    out_specs,
    *,
    grad: bool = False,
    comm_schedule: bool = False,
    comm_schedule_opts: Optional[dict] = None,
):
    """Trace ``fn`` through the framework pipeline (so dist_prims record into
    the trace), then stage the claimed trace under shard_map over ``mesh``.

    ``comm_schedule=True`` runs the certificate-driven collective-overlap
    scheduler over the claimed trace first (transforms/comm_schedule.py):
    fsdp ``synchronize`` gathers hoist to async-prefetch positions, the
    re-certified trace stages in the scheduled order.

    Returns the jitted callable (flat args in trace order).
    """
    from thunder_tpu.api import trace_program
    from thunder_tpu.executors.passes import transform_for_execution
    from thunder_tpu.extend import resolve_executors
    from thunder_tpu.transforms.autodiff import grad_transform
    from thunder_tpu.transforms.common import dce

    _, comp = trace_program(fn, example_args, {})
    comp = dce(comp)
    if grad:
        comp = grad_transform(comp, return_value=True)
    extrace = transform_for_execution(
        comp, resolve_executors(None),
        comm_schedule=comm_schedule, comm_schedule_opts=comm_schedule_opts,
    )
    return stage_collective_trace(extrace, mesh, in_specs, out_specs), extrace


def stage_collective_trace(extrace, mesh, in_specs, out_specs) -> Callable:
    """Stage an already-claimed collective-bearing execution trace under
    shard_map over ``mesh`` (the tail of :func:`compile_with_collectives`,
    split out so callers holding a transformed trace — e.g. one rewritten
    by the comm scheduler — can restage it without re-tracing)."""
    from thunder_tpu.distributed.prims import collective_trace_lines

    inner = extrace.python_callable()
    # Certify the collective schedule (ISSUE 10): stamps the per-axis order
    # baseline on the trace and hands the watchdog the certified order so a
    # timeout names the collectives that must already have completed before
    # the pending one. Advisory — certification failure never blocks staging.
    schedule = None
    try:
        from thunder_tpu.analysis import schedule as sched_mod

        schedule = sched_mod.stamp(extrace).axis_labels()
    except Exception:  # noqa: BLE001
        pass
    return shard_map_callable(
        inner, mesh, in_specs, out_specs,
        trace_lines=collective_trace_lines(extrace),
        schedule=schedule,
    )
