"""Sharded checkpoint save/load.

Reference parity: thunder/distributed/checkpoint.py (`StateDictOptions:35`
— full_state_dict/cpu_offload/rank0_only; `save:184`, `load:197` — sharded
model state over torch.distributed.checkpoint + DTensor;
`_split_state_dict:210`). The TPU equivalent is Orbax/TensorStore: each
host writes its own shards, restore re-shards to the target mesh layout
(the same dim-0 layouts ``fsdp()`` produces) — including a DIFFERENT mesh
shape than the one that saved (prove by the fsdp8→fsdp4 round-trip test).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class StateDictOptions:
    """Reference parity: checkpoint.py `StateDictOptions:35`."""

    full_state_dict: bool = False  # gather to replicated host arrays before save
    cpu_offload: bool = False  # with full_state_dict: materialize on host memory
    # Accepted for reference-API parity; no effect on behavior. With
    # full_state_dict, Orbax writes the (replicated) gathered arrays from
    # the primary host only — the consolidated export is always rank-0.
    rank0_only: bool = True


def _checkpointer(async_save: bool = False):
    import orbax.checkpoint as ocp

    if async_save:
        return ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return ocp.PyTreeCheckpointer()


class AsyncSaveHandle:
    """Returned by ``save(..., async_save=True)``: the write happens on a
    background thread (reference analogue: the async fsspec writer the
    torch.distributed.checkpoint stack offers); call ``wait()`` before
    relying on the files."""

    def __init__(self, ckptr):
        self._ckptr = ckptr

    def wait(self) -> None:
        self._ckptr.wait_until_finished()


def _gather_full(state: Any) -> Any:
    """Gather every (possibly sharded) array to a host numpy array."""
    import jax

    from thunder_tpu.core.pytree import tree_map

    def gather(x):
        if not isinstance(x, jax.Array):
            return x
        if jax.process_count() > 1 and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(x, tiled=True)
        return jax.device_get(x)

    return tree_map(gather, state)


def save(
    state: Any,
    path: str,
    *,
    options: Optional[StateDictOptions] = None,
    async_save: bool = False,
) -> Optional[AsyncSaveHandle]:
    """Save a params/optimizer pytree (reference: checkpoint.py `save:184`).

    Default: sharded save — every host writes its own shards via
    TensorStore. ``options.full_state_dict=True`` gathers to replicated
    host arrays first; Orbax then writes them from the primary host only
    (the reference's rank0-consolidated export — ``rank0_only`` is
    accepted for API parity but the consolidation always happens).
    ``async_save=True`` returns an AsyncSaveHandle and does the IO on a
    background thread.
    """
    options = options or StateDictOptions()
    if options.full_state_dict:
        state = _gather_full(state)
        # rank0_only: every process must still enter ckptr.save — Orbax runs
        # global sync barriers inside save(), so returning early on nonzero
        # ranks deadlocks process 0 (ADVICE r4). After _gather_full the
        # leaves are replicated host arrays, which Orbax writes from the
        # primary host only — that IS the rank0-consolidated export.
    ckptr = _checkpointer(async_save=async_save)
    ckptr.save(os.path.abspath(path), state)
    if async_save:
        return AsyncSaveHandle(ckptr)
    if hasattr(ckptr, "wait_until_finished"):
        ckptr.wait_until_finished()
    return None


def load(path: str, *, template: Any = None, mesh=None, specs=None) -> Any:
    """Restore a pytree; with ``mesh``+``specs`` the arrays are restored
    directly into the target sharding — which may be a different mesh SHAPE
    than the save used (reference: `load:197` resharding via DTensor; here
    TensorStore reads + shard_pytree re-lays-out)."""
    ckptr = _checkpointer()
    if mesh is not None and specs is not None:
        # Restore DIRECTLY into the target sharding: TensorStore reads only
        # the byte ranges each device needs, so an fsdp-8 checkpoint loads
        # onto an fsdp-4 (or any) mesh without materializing full arrays.
        import orbax.checkpoint as ocp
        from jax.sharding import NamedSharding

        from thunder_tpu.core.pytree import tree_map

        def restore_arg(spec):
            return ocp.ArrayRestoreArgs(sharding=NamedSharding(mesh, spec))

        restore_args = tree_map(
            restore_arg, specs, is_leaf=lambda x: type(x).__name__ == "PartitionSpec"
        )
        return ckptr.restore(os.path.abspath(path), restore_args=restore_args)
    return ckptr.restore(os.path.abspath(path))
