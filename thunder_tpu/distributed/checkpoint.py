"""Sharded checkpoint save/load.

Reference parity: thunder/distributed/checkpoint.py (`StateDictOptions:35`
— full_state_dict/cpu_offload/rank0_only; `save:184`, `load:197` — sharded
model state over torch.distributed.checkpoint + DTensor;
`_split_state_dict:210`). The TPU equivalent is Orbax/TensorStore: each
host writes its own shards, restore re-shards to the target mesh layout
(the same dim-0 layouts ``fsdp()`` produces) — including a DIFFERENT mesh
shape than the one that saved (prove by the fsdp8→fsdp4 round-trip test).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class StateDictOptions:
    """Reference parity: checkpoint.py `StateDictOptions:35`."""

    full_state_dict: bool = False  # gather to replicated host arrays before save
    cpu_offload: bool = False  # with full_state_dict: materialize on host memory
    # Accepted for reference-API parity; no effect on behavior. With
    # full_state_dict, Orbax writes the (replicated) gathered arrays from
    # the primary host only — the consolidated export is always rank-0.
    rank0_only: bool = True


def _checkpointer(async_save: bool = False):
    import orbax.checkpoint as ocp

    if async_save:
        return ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return ocp.PyTreeCheckpointer()


class AsyncSaveHandle:
    """Returned by ``save(..., async_save=True)``: the write happens on a
    background thread (reference analogue: the async fsspec writer the
    torch.distributed.checkpoint stack offers); call ``wait()`` before
    relying on the files. ``ckptr=None`` marks an already-durable save
    (the pickle fallback writes synchronously) — ``wait()`` is a no-op."""

    def __init__(self, ckptr):
        self._ckptr = ckptr

    def wait(self) -> None:
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()


def gather_full(state: Any) -> Any:
    """Gather every (possibly sharded) array to a host numpy array —
    the full_state_dict export and the host leg of a reshard
    (``parallel.sharding.reshard_pytree``)."""
    import jax
    import numpy as np

    from thunder_tpu.core.pytree import tree_map

    def gather(x):
        if not isinstance(x, jax.Array):
            return x
        if jax.process_count() > 1 and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(x, tiled=True)
        return np.asarray(jax.device_get(x))

    return tree_map(gather, state)


_gather_full = gather_full  # pre-ISSUE-9 private spelling


def save(
    state: Any,
    path: str,
    *,
    options: Optional[StateDictOptions] = None,
    async_save: bool = False,
) -> Optional[AsyncSaveHandle]:
    """Save a params/optimizer pytree (reference: checkpoint.py `save:184`).

    Default: sharded save — every host writes its own shards via
    TensorStore. ``options.full_state_dict=True`` gathers to replicated
    host arrays first; Orbax then writes them from the primary host only
    (the reference's rank0-consolidated export — ``rank0_only`` is
    accepted for API parity but the consolidation always happens).
    ``async_save=True`` returns an AsyncSaveHandle and does the IO on a
    background thread.
    """
    options = options or StateDictOptions()
    if options.full_state_dict:
        state = gather_full(state)
        # rank0_only: every process must still enter ckptr.save — Orbax runs
        # global sync barriers inside save(), so returning early on nonzero
        # ranks deadlocks process 0 (ADVICE r4). After gather_full the
        # leaves are replicated host arrays, which Orbax writes from the
        # primary host only — that IS the rank0-consolidated export.
    try:
        ckptr = _checkpointer(async_save=async_save)
    except ImportError:
        # No Orbax in this environment (CPU dev, tests): a host-local pickle
        # of the gathered state keeps the single-process story working.
        # Every consumer gets the same fallback instead of reimplementing it
        # (CheckpointManager used to carry its own copy).
        _pickle_save(gather_full(state), path)
        # The pickle write is synchronous, but async_save callers were
        # promised a handle — hand back an already-finished one.
        return AsyncSaveHandle(None) if async_save else None
    ckptr.save(os.path.abspath(path), state)
    if async_save:
        return AsyncSaveHandle(ckptr)
    if hasattr(ckptr, "wait_until_finished"):
        ckptr.wait_until_finished()
    return None


_PICKLE_NAME = "state.pkl"


def _pickle_save(host_state: Any, path: str) -> None:
    import pickle

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, _PICKLE_NAME), "wb") as f:
        pickle.dump(host_state, f)


def _pickle_load(path: str) -> Any:
    import pickle

    with open(os.path.join(os.path.abspath(path), _PICKLE_NAME), "rb") as f:
        return pickle.load(f)


def load(path: str, *, template: Any = None, mesh=None, specs=None) -> Any:
    """Restore a pytree; with ``mesh``+``specs`` the arrays are restored
    directly into the target sharding — which may be a different mesh SHAPE
    than the save used (reference: `load:197` resharding via DTensor; here
    TensorStore reads + shard_pytree re-lays-out). The pickle fallback (no
    Orbax) reshards the host arrays by device_put instead."""
    if os.path.isfile(os.path.join(os.path.abspath(path), _PICKLE_NAME)):
        state = _pickle_load(path)
        if mesh is not None and specs is not None:
            from thunder_tpu.parallel.sharding import shard_pytree

            return shard_pytree(state, mesh, specs)
        return state
    ckptr = _checkpointer()
    if mesh is not None and specs is not None:
        # Restore DIRECTLY into the target sharding: TensorStore reads only
        # the byte ranges each device needs, so an fsdp-8 checkpoint loads
        # onto an fsdp-4 (or any) mesh without materializing full arrays.
        import orbax.checkpoint as ocp
        from jax.sharding import NamedSharding

        from thunder_tpu.core.pytree import tree_map

        def restore_arg(spec):
            return ocp.ArrayRestoreArgs(sharding=NamedSharding(mesh, spec))

        restore_args = tree_map(
            restore_arg, specs, is_leaf=lambda x: type(x).__name__ == "PartitionSpec"
        )
        return ckptr.restore(os.path.abspath(path), restore_args=restore_args)
    return ckptr.restore(os.path.abspath(path))
