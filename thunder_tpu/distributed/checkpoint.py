"""Sharded checkpoint save/load.

Reference parity: thunder/distributed/checkpoint.py (`StateDictOptions:35`,
`save:184`, `load:197` — sharded model state over
torch.distributed.checkpoint + DTensor). The TPU equivalent is
Orbax/TensorStore: each host writes its shards, restore re-shards to the
target mesh layout (the same dim-0 layouts `fsdp()` produces).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class StateDictOptions:
    """Reference parity: checkpoint.py `StateDictOptions:35`."""

    full_state_dict: bool = False  # gather to replicated before save
    cpu_offload: bool = False


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save(state: Any, path: str, *, options: Optional[StateDictOptions] = None) -> None:
    """Save a params/optimizer pytree; sharded arrays write their shards
    (reference: checkpoint.py `save:184`)."""
    import jax

    options = options or StateDictOptions()
    if options.full_state_dict:
        from thunder_tpu.core.pytree import tree_map

        state = tree_map(lambda x: jax.device_get(x) if isinstance(x, jax.Array) else x, state)
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(path), state)
    ckptr.wait_until_finished() if hasattr(ckptr, "wait_until_finished") else None


def load(path: str, *, template: Any = None, mesh=None, specs=None) -> Any:
    """Restore a pytree; with ``mesh``+``specs`` the arrays are restored
    directly into the target sharding (reference: `load:197` resharding via
    DTensor — here TensorStore reads only each host's shards)."""
    import jax

    ckptr = _checkpointer()
    restored = ckptr.restore(os.path.abspath(path))
    if mesh is not None and specs is not None:
        from thunder_tpu.parallel.sharding import shard_pytree

        restored = shard_pytree(restored, mesh, specs)
    return restored
