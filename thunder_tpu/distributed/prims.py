"""Trace-level collective primitives.

Reference parity: thunder/distributed/prims.py (`PrimIDs:13` — ALL_GATHER,
ALL_REDUCE, BROADCAST, REDUCE_SCATTER, SYNCHRONIZE, WAIT; async ops
returning `FutureTensorProxy`; the grad rule of `synchronize` at `:260-298`
is where DDP/FSDP semantics live).

TPU-first lowering: the jax executor maps these to `jax.lax` collectives by
*named mesh axis* (`psum`, `all_gather`, `psum_scatter`) — valid inside a
`shard_map`-staged trace (see thunder_tpu/distributed/runtime.py). Async
start/wait pairs keep the IR structure of the reference, but lower to the
plain collective: XLA's latency-hiding scheduler splits them into
async-start/async-done and overlaps with compute, which is the TPU seat of
`sort_waits` / `limit_in_flight_allgathers`.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence

from thunder_tpu.core import dtypes
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.proxies import FutureTensorProxy, TensorProxy
from thunder_tpu.core.symbol import Symbol, register_module


class DistOpIDs(enum.Enum):
    ALL_GATHER = enum.auto()
    ALL_REDUCE = enum.auto()
    BROADCAST = enum.auto()
    REDUCE_SCATTER = enum.auto()
    SYNCHRONIZE = enum.auto()
    WAIT = enum.auto()
    PPERMUTE = enum.auto()
    ALL_TO_ALL = enum.auto()
    MASK_TO_RANK = enum.auto()
    HIER_ALL_REDUCE = enum.auto()


_dist_syms: dict[DistOpIDs, Symbol] = {}


def _make(id: DistOpIDs, name: str, meta) -> Symbol:
    from thunder_tpu.core.prims import OpTags

    # COMM_OP marks the symbol as a collective for trace analyses (the
    # analysis/ verifier's dist.* rules key on the DistOpIDs themselves, but
    # the tag lets generic passes treat collectives uniformly).
    sym = Symbol(name, meta, id=id, is_prim=True, module="dist_prims", tags=(OpTags.COMM_OP,))
    _dist_syms[id] = sym
    return sym


def _out(like: TensorProxy, shape=None, future: bool = False) -> TensorProxy:
    cls = FutureTensorProxy if future else TensorProxy
    return cls(like=like, shape=tuple(shape) if shape is not None else tuple(like.shape), requires_grad=False)


# -- metas --------------------------------------------------------------------


def _all_gather_meta(a: TensorProxy, axis: str, group_size: int, *, dim: int = 0, async_op: bool = False):
    shape = list(a.shape)
    shape[dim] = shape[dim] * group_size
    return _out(a, shape, future=async_op)


def _all_reduce_meta(a: TensorProxy, axis: str, group_size: int, *, op: str = "sum", async_op: bool = False):
    check(op in ("sum", "avg", "max", "min"), lambda: f"Unsupported reduce op {op}")
    return _out(a, future=async_op)


def _broadcast_meta(a: TensorProxy, axis: str, group_size: int, *, root: int = 0, async_op: bool = False):
    return _out(a, future=async_op)


def _reduce_scatter_meta(a: TensorProxy, axis: str, group_size: int, *, op: str = "sum", dim: int = 0,
                         async_op: bool = False):
    check(a.shape[dim] % group_size == 0, lambda: f"reduce_scatter dim {dim} ({a.shape[dim]}) not divisible by {group_size}")
    shape = list(a.shape)
    shape[dim] = shape[dim] // group_size
    return _out(a, shape, future=async_op)


def _sync_is_sharded(a, parallel_type: Optional[str]) -> bool:
    from thunder_tpu.core.proxies import DistParallelType

    if parallel_type is not None:
        return parallel_type == "fsdp"
    return getattr(a, "dist_parallel_type", None) == DistParallelType.FULLY_SHARDED


def _synchronize_meta(
    a: TensorProxy, axis: str, group_size: int, parallel_type: Optional[str] = None,
    *, grad_scale: Optional[float] = None, grad_sync: bool = True,
):
    """FULLY_SHARDED params enter dim-0-sharded and synchronize to the full
    tensor (all-gather); REPLICATED params pass through. The VJP rule holds
    the grad-sync semantics (see autodiff registration below).

    ``parallel_type`` ("fsdp" | "replicated") records the decision as a
    static arg so the runtime lowering doesn't depend on trace-time proxy
    attributes; None falls back to the proxy's dist_parallel_type.

    ``grad_sync=False`` compiles the `no_sync` variant (reference:
    thunder/distributed/__init__.py:27-67): the VJP emits the scaled LOCAL
    grad with no collective — for fsdp params that grad is full-size
    (unsharded), matching the reference's no_sync-accumulates-unsharded-grads
    behavior; the deferred sync reduces at context exit."""
    from thunder_tpu.core.proxies import DistParallelType

    if _sync_is_sharded(a, parallel_type):
        shape = (a.shape[0] * group_size,) + tuple(a.shape[1:])
        out = TensorProxy(like=a, shape=shape, requires_grad=a.requires_grad)
        out.dist_parallel_type = DistParallelType.NONE
        return out
    return TensorProxy(like=a, requires_grad=a.requires_grad)


def _wait_meta(fut: TensorProxy):
    check(isinstance(fut, FutureTensorProxy), "wait expects a FutureTensorProxy")
    return TensorProxy(like=fut)


def _ppermute_meta(a: TensorProxy, axis: str, perm: Sequence[tuple]):
    return _out(a)


def _mask_to_rank_meta(a: TensorProxy, axis: str, rank: int):
    """Identity on rank ``rank`` along mesh axis ``axis``, zeros elsewhere
    (the transpose of broadcast's replicate-from-root forward)."""
    return _out(a)


def _hier_all_reduce_meta(
    a: TensorProxy, inner_axis: str, outer_axis: str,
    inner_size: int, outer_size: int, *, op: str = "sum",
):
    """Hierarchical all-reduce over a federated mesh (ISSUE 18): in-slice
    reduce-scatter along ``inner_axis`` (ICI), cross-slice all-reduce of the
    1/inner_size shard along ``outer_axis`` (DCN), in-slice all-gather back
    to the full tensor. Numerically an all-reduce over both axes, but only
    ``nbytes/inner_size`` ever crosses the DCN boundary — the wire-cost
    asymmetry the cost model's ``dcn_bw`` class prices.

    The shard walk needs ``a.shape[0] % inner_size == 0``; the lowering
    falls back to a flat two-axis psum otherwise (same result, full bytes
    on the DCN tier)."""
    check(op in ("sum", "avg"), lambda: f"Unsupported hierarchical reduce op {op}")
    return _out(a)


def _all_to_all_meta(a: TensorProxy, axis: str, group_size: int, *, split_dim: int, concat_dim: int):
    check(a.shape[split_dim] % group_size == 0, "all_to_all split dim not divisible by group size")
    shape = list(a.shape)
    shape[split_dim] = shape[split_dim] // group_size
    shape[concat_dim] = shape[concat_dim] * group_size
    return _out(a, shape)


all_gather = _make(DistOpIDs.ALL_GATHER, "all_gather", _all_gather_meta)
all_reduce = _make(DistOpIDs.ALL_REDUCE, "all_reduce", _all_reduce_meta)
broadcast = _make(DistOpIDs.BROADCAST, "broadcast", _broadcast_meta)
reduce_scatter = _make(DistOpIDs.REDUCE_SCATTER, "reduce_scatter", _reduce_scatter_meta)
synchronize = _make(DistOpIDs.SYNCHRONIZE, "synchronize", _synchronize_meta)
wait = _make(DistOpIDs.WAIT, "wait", _wait_meta)
ppermute = _make(DistOpIDs.PPERMUTE, "ppermute", _ppermute_meta)
all_to_all = _make(DistOpIDs.ALL_TO_ALL, "all_to_all", _all_to_all_meta)
mask_to_rank = _make(DistOpIDs.MASK_TO_RANK, "mask_to_rank", _mask_to_rank_meta)
hier_all_reduce = _make(DistOpIDs.HIER_ALL_REDUCE, "hier_all_reduce", _hier_all_reduce_meta)

register_module("dist_prims", __import__("sys").modules[__name__])


def is_collective_bsym(bsym) -> bool:
    """True for a BoundSymbol that dispatches a collective — its sym id is a
    :class:`DistOpIDs` or it carries the COMM_OP tag (generic passes and
    the watchdog treat both uniformly)."""
    from thunder_tpu.core.prims import OpTags

    sym = getattr(bsym, "sym", None)
    if sym is None:
        return False
    if isinstance(sym.id, DistOpIDs):
        return True
    return OpTags.COMM_OP in (getattr(sym, "tags", None) or ())


def collective_trace_lines(trace, limit: int = 8) -> list:
    """``L<idx>.<sym>`` labels of a trace's collective dispatch sites — the
    same spelling the annotated codegen stamps into HLO scopes, so a
    :class:`~thunder_tpu.resilience.watchdog.CollectiveTimeoutError` names
    lines an operator can join against profiles and the cost model's
    per-line wire bounds. ``limit`` caps the list (a deep FSDP trace has
    hundreds of synchronize sites; the first few identify the program)."""
    if trace is None:
        return []
    lines = []
    for i, bsym in enumerate(getattr(trace, "bound_symbols", ()) or ()):
        if is_collective_bsym(bsym):
            lines.append(f"L{i}.{bsym.sym.name}")
            if limit and len(lines) >= limit:
                break
    return lines


# -- jax executor implementations ---------------------------------------------
# Valid inside shard_map over a mesh with the named axis.


def _register_jax_impls():
    import jax
    from jax import lax

    from thunder_tpu.executors.jaxex import ex as jax_ex

    def ag(a, axis, group_size, *, dim=0, async_op=False):
        return lax.all_gather(a, axis, axis=dim, tiled=True)

    def ar(a, axis, group_size, *, op="sum", async_op=False):
        if op == "sum":
            return lax.psum(a, axis)
        if op == "avg":
            return lax.pmean(a, axis)
        if op == "max":
            return lax.pmax(a, axis)
        return lax.pmin(a, axis)

    def bc(a, axis, group_size, *, root=0, async_op=False):
        # Replicate the root's value across the axis.
        idx = lax.axis_index(axis)
        masked = jax.numpy.where(idx == root, a, jax.numpy.zeros_like(a))
        return lax.psum(masked, axis)

    def rs(a, axis, group_size, *, op="sum", dim=0, async_op=False):
        r = lax.psum_scatter(a, axis, scatter_dimension=dim, tiled=True)
        if op == "avg":
            r = r / group_size
        return r

    def sync(a, axis, group_size, parallel_type=None, *, grad_scale=None, grad_sync=True):
        # FSDP shards all-gather to the full param; replicated params pass
        # through (their sync semantics live entirely in the VJP's grad
        # all-reduce). None = legacy call sites that always gather.
        if parallel_type == "replicated":
            return a
        return lax.all_gather(a, axis, axis=0, tiled=True) if group_size > 1 else a

    def pp(a, axis, perm):
        return lax.ppermute(a, axis, [tuple(p) for p in perm])

    def a2a(a, axis, group_size, *, split_dim, concat_dim):
        return lax.all_to_all(a, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True)

    def mask(a, axis, rank):
        idx = lax.axis_index(axis)
        return jax.numpy.where(idx == rank, a, jax.numpy.zeros_like(a))

    def har(a, inner_axis, outer_axis, inner_size, outer_size, *, op="sum"):
        # Hierarchical lowering (ISSUE 18): reduce-scatter in-slice so each
        # ICI rank owns a 1/inner_size shard, all-reduce only the shard
        # across the DCN axis, gather the slice back together. Shapes that
        # don't split along dim 0 fall back to a flat two-axis psum —
        # identical math, no DCN savings.
        if inner_size > 1 and a.ndim and a.shape[0] % inner_size == 0:
            part = lax.psum_scatter(a, inner_axis, scatter_dimension=0, tiled=True)
            if outer_size > 1:
                part = lax.psum(part, outer_axis)
            r = lax.all_gather(part, inner_axis, axis=0, tiled=True)
        else:
            axes = tuple(ax for ax, n in ((inner_axis, inner_size),
                                          (outer_axis, outer_size)) if n > 1)
            r = lax.psum(a, axes) if axes else a
        if op == "avg":
            r = r / (inner_size * outer_size)
        return r

    jax_ex.register_implementation(DistOpIDs.ALL_GATHER, fn=ag)
    jax_ex.register_implementation(DistOpIDs.ALL_REDUCE, fn=ar)
    jax_ex.register_implementation(DistOpIDs.BROADCAST, fn=bc)
    jax_ex.register_implementation(DistOpIDs.REDUCE_SCATTER, fn=rs)
    jax_ex.register_implementation(DistOpIDs.SYNCHRONIZE, fn=sync)
    jax_ex.register_implementation(DistOpIDs.WAIT, fn=lambda fut: fut)
    jax_ex.register_implementation(DistOpIDs.PPERMUTE, fn=pp)
    jax_ex.register_implementation(DistOpIDs.ALL_TO_ALL, fn=a2a)
    jax_ex.register_implementation(DistOpIDs.MASK_TO_RANK, fn=mask)
    jax_ex.register_implementation(DistOpIDs.HIER_ALL_REDUCE, fn=har)


_register_jax_impls()


# -- VJP rules ----------------------------------------------------------------
# Reference parity: distributed/prims.py:260-298 — synchronize's grad rule is
# where DDP/FSDP grad-sync semantics live.


def _register_vjps():
    from thunder_tpu.core.proxies import DistParallelType
    from thunder_tpu.transforms.autodiff import register_vjp

    @register_vjp(DistOpIDs.ALL_GATHER)
    def _ag_vjp(bsym, g):
        a, axis, group_size = bsym.args[:3]
        dim = bsym.kwargs.get("dim", 0)
        return (reduce_scatter(g, axis, group_size, dim=dim), None, None)

    @register_vjp(DistOpIDs.REDUCE_SCATTER)
    def _rs_vjp(bsym, g):
        a, axis, group_size = bsym.args[:3]
        dim = bsym.kwargs.get("dim", 0)
        return (all_gather(g, axis, group_size, dim=dim), None, None)

    @register_vjp(DistOpIDs.ALL_REDUCE)
    def _ar_vjp(bsym, g):
        a, axis, group_size = bsym.args[:3]
        return (all_reduce(g, axis, group_size), None, None)

    @register_vjp(DistOpIDs.BROADCAST)
    def _bc_vjp(bsym, g):
        # Only the root's input affects the output, so the summed cotangent
        # belongs to the root alone; non-root ranks get zero (ADVICE r1).
        a, axis, group_size = bsym.args[:3]
        root = bsym.kwargs.get("root", 0)
        return (mask_to_rank(all_reduce(g, axis, group_size), axis, root), None, None)

    @register_vjp(DistOpIDs.WAIT)
    def _wait_vjp(bsym, g):
        return (g,)

    @register_vjp(DistOpIDs.HIER_ALL_REDUCE)
    def _har_vjp(bsym, g):
        # Sum all-reduce is self-adjoint; the hierarchical decomposition
        # keeps the cotangent's DCN traffic sharded too.
        a, inner_axis, outer_axis, inner_size, outer_size = bsym.args[:5]
        return (hier_all_reduce(g, inner_axis, outer_axis, inner_size, outer_size),
                None, None, None, None)

    @register_vjp(DistOpIDs.SYNCHRONIZE)
    def _sync_vjp(bsym, g):
        import thunder_tpu.clang as clang

        a, axis, group_size = bsym.args[:3]
        ptype = bsym.args[3] if len(bsym.args) > 3 else bsym.kwargs.get("parallel_type")
        # grad_scale: 1/world when every device redundantly computes the
        # full-batch grad (replicated data — averaging the identical copies
        # is the identity); 1.0 when data is batch-sharded and per-device
        # partial grads must SUM to the global grad.
        scale = bsym.kwargs.get("grad_scale")
        if scale is None:
            scale = 1.0 / group_size
        scaled = clang.mul(g, scale) if scale != 1.0 else g
        if bsym.kwargs.get("grad_sync", True) is False:
            # no_sync: keep the scaled local grad, defer the collective to
            # context exit (sum over the device axis there). For fsdp the
            # local grad stays FULL-size — the reduce_scatter that would
            # shard it is exactly the skipped sync.
            return (scaled, None, None)
        if _sync_is_sharded(a, ptype):
            # FSDP: grad of the gathered param reduce-scatters back to shards
            # (reference: prims.py:286-298).
            return (reduce_scatter(scaled, axis, group_size, dim=0), None, None)
        # DDP (replicated): all-reduce.
        return (all_reduce(scaled, axis, group_size), None, None)


_register_vjps()
