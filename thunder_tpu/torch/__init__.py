"""The torch-mirror language layer ("ltorch").

Reference parity: thunder/torch/__init__.py (168 `@torchsymbol`s mirroring the
`torch.*` / `torch.nn.functional.*` API, the `_torch_to_thunder_function_map`
at `:61` consumed by frontend lookasides, and method registration via
`torchsymbol:73`).

Each op here is a :class:`~thunder_tpu.core.symbol.Symbol` whose meta function
*decomposes* into clang ops and prims while tracing — producing the
multi-level IR that lets high-priority executors (e.g. the Pallas
flash-attention executor) claim composite ops whole, while the terminal
JAX/XLA executor claims the prims they decompose into.

The dtype/shape semantics mirror torch (type promotion, integer true-division
producing floats, `keepdim`, negative dims, ...); the decompositions are
written to be XLA-friendly — static shapes, `where` instead of data-dependent
branches, reductions/matmuls the MXU can tile.
"""

from __future__ import annotations

import math
from numbers import Number
from typing import Any, Callable, Optional, Sequence, Union

import thunder_tpu.clang as clang
import thunder_tpu.core.prims as prims
from thunder_tpu.core import dtypes, devices, utils
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.langctxs import LanguageContext, Languages, register_langctx, resolve_language
from thunder_tpu.core.proxies import NumberProxy, TensorProxy, pyval
from thunder_tpu.core.symbol import Symbol, register_module
from thunder_tpu.core.utils import canonicalize_dim, canonicalize_dims

# -- language context ---------------------------------------------------------

_torch_ctx = LanguageContext(Languages.TORCH)
# The torch language is a superset of clang's method surface.
_clang_ctx = resolve_language(Languages.CLANG)
_torch_ctx._methods.update(_clang_ctx._methods)
register_langctx(Languages.TORCH, _torch_ctx)

# torch.foo / torch.Tensor.foo / F.foo → ltorch symbol. Consumed by the
# module frontend's __torch_function__ dispatch (reference: thunder/torch
# `_torch_to_thunder_function_map:61`).
_torch_to_thunder_function_map: dict[Any, Callable] = {}


def _resolve_torch_attr(path: str):
    """'torch.nn.functional.linear' → the live torch object, or None."""
    try:
        import torch
    except ImportError:
        return None
    obj = torch
    for part in path.split(".")[1:]:
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


def torchsymbol(*torch_paths: str, method_name: Optional[str] = None, id: Optional[str] = None):
    """Create an ltorch Symbol from a decomposition fn, registering it under
    the given torch dotted paths and optionally as a tensor method
    (reference: thunder/torch `torchsymbol:73`)."""

    def decorator(fn: Callable) -> Symbol:
        sym = Symbol(fn.__name__, meta=fn, id=id if id is not None else f"torch.{fn.__name__}", module="ltorch")
        for path in torch_paths:
            obj = _resolve_torch_attr(path)
            if obj is not None:
                _torch_to_thunder_function_map[obj] = sym
        if method_name is not None:
            _torch_ctx.register_method(method_name, sym)
        return sym

    return decorator


def to_dtype(x) -> Optional[dtypes.dtype]:
    return dtypes.to_dtype(x) if x is not None else None


def _dim_seq(dim) -> Optional[tuple]:
    if dim is None:
        return None
    if isinstance(dim, (int, NumberProxy)):
        return (int(pyval(dim)),)
    return tuple(int(pyval(d)) for d in dim)


# =============================================================================
# Tensor creation
# =============================================================================


@torchsymbol("torch.zeros")
def zeros(*size, dtype=None, device=None, requires_grad: bool = False):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.full(tuple(shape), 0, device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.ones")
def ones(*size, dtype=None, device=None, requires_grad: bool = False):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.full(tuple(shape), 1, device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.full")
def full(size, fill_value, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.full(tuple(size), fill_value, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.empty")
def empty(*size, dtype=None, device=None, requires_grad: bool = False):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.full(tuple(shape), 0, device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.zeros_like", method_name="new_zeros")
def zeros_like(a, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.zeros_like(a, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.ones_like")
def ones_like(a, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.ones_like(a, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.full_like")
def full_like(a, fill_value, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.full_like(a, fill_value, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.arange")
def arange(start, end=None, step=1, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.arange(start, end, step, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.rand")
def rand(*size, dtype=None, device=None, requires_grad: bool = False, generator=None):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.uniform(tuple(shape), 0.0, 1.0, device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.randn")
def randn(*size, dtype=None, device=None, requires_grad: bool = False, generator=None):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.randn(tuple(shape), device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.tensor")
def tensor(data, *, dtype=None, device=None, requires_grad: bool = False):
    if isinstance(data, TensorProxy):
        return clang.to(data, device=device, dtype=to_dtype(dtype))
    if isinstance(data, (Number, NumberProxy)) and not isinstance(data, (list, tuple)):
        dt = to_dtype(dtype) or dtypes.to_strong(dtypes.numbertype_to_dtype(type(pyval(data))))
        return clang.full((), data, device=device, dtype=dt)
    return clang.tensor_from_sequence(data, device=device, dtype=to_dtype(dtype))


# =============================================================================
# Data movement / dtype casts
# =============================================================================


@torchsymbol("torch.Tensor.to", method_name="to")
def to(a, *args, **kwargs):
    device = kwargs.get("device")
    dtype = kwargs.get("dtype")
    for arg in args:
        if isinstance(arg, str) or type(arg).__name__ == "device" or isinstance(arg, devices.Device):
            device = arg
        elif arg is not None:
            dtype = arg
    return clang.to(a, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.Tensor.type_as", method_name="type_as")
def type_as(a, b):
    return clang.maybe_convert_to_dtype(a, b.dtype)


def _make_cast(name: str, dtype: dtypes.dtype) -> Symbol:
    def cast(a):
        return clang.maybe_convert_to_dtype(a, dtype)

    cast.__name__ = name
    sym = Symbol(name, meta=cast, id=f"torch.Tensor.{name}", module="ltorch")
    _torch_ctx.register_method(name, sym)
    obj = _resolve_torch_attr(f"torch.Tensor.{name}")
    if obj is not None:
        _torch_to_thunder_function_map[obj] = sym
    return sym


float_ = _make_cast("float", dtypes.float32)
double = _make_cast("double", dtypes.float64)
half = _make_cast("half", dtypes.float16)
bfloat16 = _make_cast("bfloat16", dtypes.bfloat16)
long = _make_cast("long", dtypes.int64)
int_ = _make_cast("int", dtypes.int32)
bool_ = _make_cast("bool", dtypes.bool8)


@torchsymbol("torch.Tensor.contiguous", method_name="contiguous")
def contiguous(a, *, memory_format=None):
    # All arrays are logically contiguous under XLA; layout is the compiler's.
    return prims.shallow_copy(a)


@torchsymbol("torch.clone", method_name="clone")
def clone(a, *, memory_format=None):
    return prims.shallow_copy(a)


@torchsymbol("torch.Tensor.detach", method_name="detach")
def detach(a):
    return prims.stop_gradient(a)


@torchsymbol("torch.Tensor.item", method_name="item")
def item(a):
    return prims.item(a)


# =============================================================================
# Shape operations
# =============================================================================


@torchsymbol("torch.Tensor.view", method_name="view")
def view(a, *shape):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    return reshape(a, shape)


@torchsymbol("torch.reshape", method_name="reshape")
def reshape(a, *shape):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    shape = [int(pyval(s)) for s in shape]
    if -1 in shape:
        idx = shape.index(-1)
        known = 1
        for i, s in enumerate(shape):
            if i != idx:
                known *= s
        check(known != 0 and a.numel % known == 0, lambda: f"cannot reshape {a.shape} to {shape}")
        shape[idx] = a.numel // known
    return clang.reshape(a, tuple(shape))


@torchsymbol("torch.permute", method_name="permute")
def permute(a, *dims):
    dims = dims[0] if len(dims) == 1 and isinstance(dims[0], (tuple, list)) else dims
    return clang.permute(a, tuple(int(pyval(d)) for d in dims))


@torchsymbol("torch.transpose", method_name="transpose")
def transpose(a, dim0: int, dim1: int):
    return clang.transpose(a, int(pyval(dim0)), int(pyval(dim1)))


@torchsymbol("torch.Tensor.t", method_name="t")
def t(a):
    check(a.ndim <= 2, "t() requires rank <= 2")
    return clang.matrix_transpose(a) if a.ndim == 2 else a


@torchsymbol("torch.movedim", method_name="movedim")
def movedim(a, source, destination):
    return clang.movedim(a, source, destination)


@torchsymbol("torch.squeeze", method_name="squeeze")
def squeeze(a, dim=None):
    if dim is None:
        dims = tuple(i for i, s in enumerate(a.shape) if s == 1)
    else:
        d = canonicalize_dim(a.ndim, int(pyval(dim)))
        if a.shape[d] != 1:
            return a
        dims = (d,)
    return clang.squeeze(a, dims)


@torchsymbol("torch.unsqueeze", method_name="unsqueeze")
def unsqueeze(a, dim: int):
    return clang.unsqueeze(a, int(pyval(dim)))


@torchsymbol("torch.flatten", method_name="flatten")
def flatten(a, start_dim: int = 0, end_dim: int = -1):
    return clang.flatten(a, int(pyval(start_dim)), int(pyval(end_dim)))


@torchsymbol("torch.cat", "torch.concat")
def cat(tensors, dim: int = 0):
    # torch's legacy allowance: 1-D zero-element tensors are compatible with
    # anything in cat and contribute nothing (HF KV caches rely on this).
    tensors = [t for t in tensors if not (t.ndim == 1 and t.numel == 0)]
    check(len(tensors) > 0, "cat of only empty tensors")
    if len(tensors) == 1:
        return prims.shallow_copy(tensors[0])
    return clang.cat(list(tensors), int(pyval(dim)))


@torchsymbol("torch.stack")
def stack(tensors, dim: int = 0):
    return clang.stack(list(tensors), int(pyval(dim)))


@torchsymbol("torch.chunk", method_name="chunk")
def chunk(a, chunks: int, dim: int = 0):
    return clang.chunk(a, int(pyval(chunks)), int(pyval(dim)))


@torchsymbol("torch.split", method_name="split")
def split(a, split_size_or_sections, dim: int = 0):
    return clang.split(a, split_size_or_sections, int(pyval(dim)))


@torchsymbol("torch.Tensor.expand", method_name="expand")
def expand(a, *shape):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    shape = list(int(pyval(s)) for s in shape)
    offset = len(shape) - a.ndim
    for i, s in enumerate(shape):
        if s == -1:
            check(i >= offset, "cannot use -1 for a new leading dim in expand")
            shape[i] = a.shape[i - offset]
    return clang.expand(a, tuple(shape))


@torchsymbol("torch.Tensor.repeat", method_name="repeat")
def repeat(a, *sizes):
    sizes = sizes[0] if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)) else sizes
    sizes = tuple(int(pyval(s)) for s in sizes)
    check(len(sizes) >= a.ndim, "repeat requires at least a.ndim sizes")
    offset = len(sizes) - a.ndim
    r = a
    for _ in range(offset):
        r = clang.unsqueeze(r, 0)
    # tile by interleaving reshape/broadcast per dim
    for i, n in enumerate(sizes):
        if n != 1:
            r = clang.unsqueeze(r, i)
            target = list(r.shape)
            target[i] = n
            r = clang.expand(r, tuple(target))
            merged = list(r.shape)
            merged[i + 1] = merged[i] * merged[i + 1]
            del merged[i]
            r = clang.reshape(r, tuple(merged))
    return r


@torchsymbol("torch.flip", method_name="flip")
def flip(a, dims):
    return clang.flip(a, dims)


@torchsymbol("torch.Tensor.__getitem__", method_name="getitem")
def getitem(a, key):
    return clang.getitem(a, key)


@torchsymbol("torch.index_select", method_name="index_select")
def index_select(a, dim: int, index):
    return clang.take(a, index, int(pyval(dim)))


@torchsymbol("torch.gather", method_name="gather")
def gather(a, dim: int, index):
    return clang.gather(a, int(pyval(dim)), index)


@torchsymbol("torch.scatter_add", method_name="scatter_add")
def scatter_add(a, dim: int, index, src):
    return clang.scatter_add(a, int(pyval(dim)), index, src)


@torchsymbol("torch.take_along_dim", method_name="take_along_dim")
def take_along_dim(a, indices, dim: int):
    return clang.take_along_axis(a, indices, int(pyval(dim)))


@torchsymbol("torch.index_put", method_name="index_put")
def index_put(a, indices, values, accumulate: bool = False):
    return clang.index_put(a, indices, values, accumulate)


@torchsymbol("torch.tril", method_name="tril")
def tril(a, diagonal: int = 0):
    return clang.tril(a, int(pyval(diagonal)))


@torchsymbol("torch.triu", method_name="triu")
def triu(a, diagonal: int = 0):
    return clang.triu(a, int(pyval(diagonal)))


@torchsymbol("torch.Tensor.masked_fill", method_name="masked_fill")
def masked_fill(a, mask, value):
    return clang.where(mask, value, a)


@torchsymbol("torch.where")
def where(pred, a=None, b=None):
    check(a is not None and b is not None, "where() requires three arguments")
    return clang.where(pred, a, b)


@torchsymbol("torch.topk", method_name="topk")
def topk(a, k: int, dim: int = -1, largest: bool = True, sorted: bool = True):
    return clang.topk(a, k, dim, largest, sorted)


@torchsymbol("torch.sort", method_name="sort")
def sort(a, dim: int = -1, descending: bool = False):
    return clang.sort(a, dim, descending)


@torchsymbol("torch.argsort", method_name="argsort")
def argsort(a, dim: int = -1, descending: bool = False):
    return clang.argsort(a, dim, descending)


@torchsymbol("torch.cumsum", method_name="cumsum")
def cumsum(a, dim: int, *, dtype=None):
    r = clang.cumsum(a, int(pyval(dim)))
    if dtype is not None:
        r = clang.maybe_convert_to_dtype(r, to_dtype(dtype))
    return r


@torchsymbol("torch.repeat_interleave", method_name="repeat_interleave")
def repeat_interleave(a, repeats: int, dim: Optional[int] = None):
    check(isinstance(repeats, (int, NumberProxy)), "only int repeats supported")
    n = int(pyval(repeats))
    if dim is None:
        a = flatten(a)
        dim = 0
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    r = clang.unsqueeze(a, d + 1)
    target = list(r.shape)
    target[d + 1] = n
    r = clang.expand(r, tuple(target))
    merged = list(a.shape)
    merged[d] = merged[d] * n
    return clang.reshape(r, tuple(merged))


# =============================================================================
# Elementwise ops (torch.* functions; methods inherited from clang)
# =============================================================================


def _register_elementwise(name: str, clang_fn: Callable, torch_paths: Sequence[str], method: Optional[str] = None):
    def meta(*args, **kwargs):
        return clang_fn(*args, **kwargs)

    meta.__name__ = name
    sym = Symbol(name, meta=meta, id=f"torch.{name}", module="ltorch")
    for path in torch_paths:
        obj = _resolve_torch_attr(path)
        if obj is not None:
            _torch_to_thunder_function_map[obj] = sym
    if method is not None:
        _torch_ctx.register_method(method, sym)
    return sym


# unary
abs = _register_elementwise("abs", clang.abs, ["torch.abs", "torch.Tensor.abs"])
acos = _register_elementwise("acos", clang.acos, ["torch.acos"])
asin = _register_elementwise("asin", clang.asin, ["torch.asin"])
atan = _register_elementwise("atan", clang.atan, ["torch.atan"])
ceil = _register_elementwise("ceil", clang.ceil, ["torch.ceil"])
cos = _register_elementwise("cos", clang.cos, ["torch.cos", "torch.Tensor.cos"])
cosh = _register_elementwise("cosh", clang.cosh, ["torch.cosh"])
erf = _register_elementwise("erf", clang.erf, ["torch.erf"])
exp = _register_elementwise("exp", clang.exp, ["torch.exp", "torch.Tensor.exp"])
expm1 = _register_elementwise("expm1", clang.expm1, ["torch.expm1"])
floor = _register_elementwise("floor", clang.floor, ["torch.floor"])
isfinite = _register_elementwise("isfinite", clang.isfinite, ["torch.isfinite"])
isinf = _register_elementwise("isinf", clang.isinf, ["torch.isinf"])
isnan = _register_elementwise("isnan", clang.isnan, ["torch.isnan"])
log = _register_elementwise("log", clang.log, ["torch.log", "torch.Tensor.log"])
log1p = _register_elementwise("log1p", clang.log1p, ["torch.log1p"])
log2 = _register_elementwise("log2", clang.log2, ["torch.log2"])
neg = _register_elementwise("neg", clang.neg, ["torch.neg"])
reciprocal = _register_elementwise("reciprocal", clang.reciprocal, ["torch.reciprocal"])
round = _register_elementwise("round", clang.round, ["torch.round"])
rsqrt = _register_elementwise("rsqrt", clang.rsqrt, ["torch.rsqrt"])
sign = _register_elementwise("sign", clang.sign, ["torch.sign"])
sin = _register_elementwise("sin", clang.sin, ["torch.sin", "torch.Tensor.sin"])
sinh = _register_elementwise("sinh", clang.sinh, ["torch.sinh"])
sqrt = _register_elementwise("sqrt", clang.sqrt, ["torch.sqrt", "torch.Tensor.sqrt"])
tan = _register_elementwise("tan", clang.tan, ["torch.tan"])
tanh = _register_elementwise("tanh", clang.tanh, ["torch.tanh", "torch.Tensor.tanh"])
trunc = _register_elementwise("trunc", clang.trunc, ["torch.trunc"])
logical_not = _register_elementwise("logical_not", clang.logical_not, ["torch.logical_not"])

# binary
add_sym = _register_elementwise("add", clang.add, ["torch.add", "torch.Tensor.add"])
atan2 = _register_elementwise("atan2", clang.atan2, ["torch.atan2"])
bitwise_and = _register_elementwise("bitwise_and", clang.bitwise_and, ["torch.bitwise_and"])
bitwise_or = _register_elementwise("bitwise_or", clang.bitwise_or, ["torch.bitwise_or"])
bitwise_xor = _register_elementwise("bitwise_xor", clang.bitwise_xor, ["torch.bitwise_xor"])
div = _register_elementwise("div", clang.true_divide, ["torch.div", "torch.true_divide", "torch.Tensor.div"])
eq = _register_elementwise("eq", clang.eq, ["torch.eq"])
floor_divide = _register_elementwise("floor_divide", clang.floor_divide, ["torch.floor_divide"])
fmod = _register_elementwise("fmod", clang.fmod, ["torch.fmod"])
ge = _register_elementwise("ge", clang.ge, ["torch.ge"])
gt = _register_elementwise("gt", clang.gt, ["torch.gt"])
le = _register_elementwise("le", clang.le, ["torch.le"])
lt = _register_elementwise("lt", clang.lt, ["torch.lt"])
maximum = _register_elementwise("maximum", clang.maximum, ["torch.maximum"])
minimum = _register_elementwise("minimum", clang.minimum, ["torch.minimum"])
mul = _register_elementwise("mul", clang.mul, ["torch.mul", "torch.Tensor.mul"])
ne = _register_elementwise("ne", clang.ne, ["torch.ne"])
pow = _register_elementwise("pow", clang.pow, ["torch.pow", "torch.Tensor.pow"])
remainder = _register_elementwise("remainder", clang.remainder, ["torch.remainder"])
sub = _register_elementwise("sub", clang.sub, ["torch.sub", "torch.Tensor.sub"])
clamp = _register_elementwise("clamp", clang.clamp, ["torch.clamp", "torch.Tensor.clamp"])


@torchsymbol("torch.sigmoid", "torch.nn.functional.sigmoid", method_name="sigmoid")
def sigmoid(a):
    # 1 / (1 + exp(-x)) — stable via where on sign, but XLA's logistic is
    # what this lowers to after fusion; keep the simple composition.
    return clang.true_divide(1.0, clang.add(1.0, clang.exp(clang.neg(a))))


@torchsymbol("torch.nn.functional.softplus")
def softplus(a, beta: float = 1.0, threshold: float = 20.0):
    scaled = clang.mul(a, beta)
    soft = clang.true_divide(clang.log1p(clang.exp(scaled)), beta)
    return clang.where(clang.gt(scaled, threshold), a, soft)


# =============================================================================
# Activations
# =============================================================================


@torchsymbol("torch.nn.functional.relu", method_name="relu")
def relu(a, inplace: bool = False):
    return clang.maximum(a, 0)


@torchsymbol("torch.nn.functional.leaky_relu")
def leaky_relu(a, negative_slope: float = 0.01, inplace: bool = False):
    return clang.where(clang.gt(a, 0), a, clang.mul(a, negative_slope))


@torchsymbol("torch.nn.functional.elu")
def elu(a, alpha: float = 1.0, inplace: bool = False):
    return clang.where(clang.gt(a, 0), a, clang.mul(alpha, clang.expm1(a)))


@torchsymbol("torch.nn.functional.gelu")
def gelu(a, approximate: str = "none"):
    if approximate == "tanh":
        inner = clang.mul(math.sqrt(2.0 / math.pi), clang.add(a, clang.mul(0.044715, clang.mul(a, clang.mul(a, a)))))
        return clang.mul(clang.mul(0.5, a), clang.add(1.0, clang.tanh(inner)))
    return clang.mul(clang.mul(0.5, a), clang.add(1.0, clang.erf(clang.mul(a, 1.0 / math.sqrt(2.0)))))


@torchsymbol("torch.nn.functional.silu")
def silu(a, inplace: bool = False):
    return clang.mul(a, sigmoid(a))


@torchsymbol("torch.nn.functional.mish")
def mish(a, inplace: bool = False):
    return clang.mul(a, clang.tanh(softplus(a)))


@torchsymbol("torch.nn.functional.hardswish")
def hardswish(a, inplace: bool = False):
    return clang.mul(a, clang.true_divide(clang.clamp(clang.add(a, 3.0), 0.0, 6.0), 6.0))


@torchsymbol("torch.softmax", "torch.nn.functional.softmax", method_name="softmax")
def softmax(a, dim: int, dtype=None):
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, to_dtype(dtype))
    shifted = clang.sub(a, clang.amax(a, (d,), True))
    e = clang.exp(shifted)
    return clang.true_divide(e, clang.sum(e, (d,), True))


@torchsymbol("torch.log_softmax", "torch.nn.functional.log_softmax", method_name="log_softmax")
def log_softmax(a, dim: int, dtype=None):
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, to_dtype(dtype))
    shifted = clang.sub(a, clang.amax(a, (d,), True))
    return clang.sub(shifted, clang.log(clang.sum(clang.exp(shifted), (d,), True)))


# =============================================================================
# Reductions
# =============================================================================


@torchsymbol("torch.sum", method_name="sum")
def sum(a, dim=None, keepdim: bool = False, *, dtype=None):
    return clang.sum(a, _dim_seq(dim), keepdim, dtype=to_dtype(dtype))


@torchsymbol("torch.mean", method_name="mean")
def mean(a, dim=None, keepdim: bool = False, *, dtype=None):
    return clang.mean(a, _dim_seq(dim), keepdim, dtype=to_dtype(dtype))


@torchsymbol("torch.prod", method_name="prod")
def prod(a, dim=None, keepdim: bool = False, *, dtype=None):
    r = clang.prod(a, _dim_seq(dim), keepdim)
    if dtype is not None:
        r = clang.maybe_convert_to_dtype(r, to_dtype(dtype))
    return r


@torchsymbol("torch.amax", method_name="amax")
def amax(a, dim=None, keepdim: bool = False):
    return clang.amax(a, _dim_seq(dim), keepdim)


@torchsymbol("torch.amin", method_name="amin")
def amin(a, dim=None, keepdim: bool = False):
    return clang.amin(a, _dim_seq(dim), keepdim)


@torchsymbol("torch.max", method_name="max")
def max(a, dim=None, keepdim: bool = False):
    if isinstance(dim, TensorProxy):
        return clang.maximum(a, dim)
    if dim is None:
        return clang.amax(a, None, False)
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    return clang.amax(a, (d,), keepdim), clang.argmax(a, d, keepdim)


@torchsymbol("torch.min", method_name="min")
def min(a, dim=None, keepdim: bool = False):
    if isinstance(dim, TensorProxy):
        return clang.minimum(a, dim)
    if dim is None:
        return clang.amin(a, None, False)
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    return clang.amin(a, (d,), keepdim), clang.argmin(a, d, keepdim)


@torchsymbol("torch.argmax", method_name="argmax")
def argmax(a, dim=None, keepdim: bool = False):
    return clang.argmax(a, dim if dim is None else int(pyval(dim)), keepdim)


@torchsymbol("torch.argmin", method_name="argmin")
def argmin(a, dim=None, keepdim: bool = False):
    return clang.argmin(a, dim if dim is None else int(pyval(dim)), keepdim)


@torchsymbol("torch.var", method_name="var")
def var(a, dim=None, *, correction: Number = 1, keepdim: bool = False):
    return clang.var(a, _dim_seq(dim), correction=correction, keepdim=keepdim)


@torchsymbol("torch.var_mean")
def var_mean(a, dim=None, *, correction: Number = 1, keepdim: bool = False):
    return clang.var_mean(a, _dim_seq(dim), correction=correction, keepdim=keepdim)


@torchsymbol("torch.std", method_name="std")
def std(a, dim=None, *, correction: Number = 1, keepdim: bool = False):
    return clang.std(a, _dim_seq(dim), correction=correction, keepdim=keepdim)


@torchsymbol("torch.all", method_name="all")
def all(a, dim=None, keepdim: bool = False):
    return clang.all_tensor(a, _dim_seq(dim), keepdim)


@torchsymbol("torch.any", method_name="any")
def any(a, dim=None, keepdim: bool = False):
    return clang.any_tensor(a, _dim_seq(dim), keepdim)


# =============================================================================
# Linear algebra / NN ops
# =============================================================================


@torchsymbol("torch.matmul", method_name="matmul")
def matmul(a, b):
    return clang.matmul(a, b)


@torchsymbol("torch.bmm", method_name="bmm")
def bmm(a, b):
    check(a.ndim == 3 and b.ndim == 3, "bmm requires rank-3 tensors")
    return clang.matmul(a, b)


@torchsymbol("torch.nn.functional.linear")
def linear(a, w, bias=None):
    return clang.linear(a, w, bias)


@torchsymbol("torch.outer", method_name="outer")
def outer(a, b):
    check(a.ndim == 1 and b.ndim == 1, "outer requires rank-1 tensors")
    return clang.mul(clang.unsqueeze(a, 1), clang.unsqueeze(b, 0))


@torchsymbol("torch.einsum")
def einsum(equation: str, *operands):
    """Einstein summation decomposed to transpose/reshape/matmul prims (so
    the contraction lands on the MXU). Supports 1-2 operands, no repeated
    indices within an operand; '...' broadcasting is not supported yet."""
    if len(operands) == 1 and isinstance(operands[0], (tuple, list)):
        operands = tuple(operands[0])
    check("..." not in equation, "einsum ellipsis is not supported yet")
    eq = equation.replace(" ", "")
    if "->" in eq:
        lhs, out_spec = eq.split("->")
    else:
        lhs = eq
        # implicit output: non-repeated indices, sorted
        counts: dict[str, int] = {}
        for ch in lhs.replace(",", ""):
            counts[ch] = counts.get(ch, 0) + 1
        out_spec = "".join(sorted(ch for ch, n in counts.items() if n == 1))
    specs = lhs.split(",")
    check(len(specs) == len(operands), "einsum operand count mismatch")
    check(len(operands) in (1, 2), "einsum supports 1 or 2 operands")

    if len(operands) == 1:
        (spec,), (a,) = specs, operands
        check(len(set(spec)) == len(spec), "repeated in-operand indices unsupported")
        # sum out dims absent from output, then permute
        sum_dims = tuple(i for i, ch in enumerate(spec) if ch not in out_spec)
        if sum_dims:
            a = clang.sum(a, sum_dims)
            spec = "".join(ch for ch in spec if ch in out_spec)
        perm = tuple(spec.index(ch) for ch in out_spec)
        return clang.permute(a, perm) if perm != tuple(range(len(perm))) else a

    sa, sb = specs
    a, b = operands
    check(len(set(sa)) == len(sa) and len(set(sb)) == len(sb),
          "repeated in-operand indices unsupported")
    # classify indices
    batch = [ch for ch in sa if ch in sb and ch in out_spec]
    contract = [ch for ch in sa if ch in sb and ch not in out_spec]
    free_a = [ch for ch in sa if ch not in sb]
    free_b = [ch for ch in sb if ch not in sa]
    # sum out indices appearing in only one operand and not the output
    pre_a = tuple(i for i, ch in enumerate(sa) if ch in free_a and ch not in out_spec)
    if pre_a:
        a = clang.sum(a, pre_a)
        sa = "".join(ch for i, ch in enumerate(sa) if i not in pre_a)
        free_a = [ch for ch in free_a if ch in sa]
    pre_b = tuple(i for i, ch in enumerate(sb) if ch in free_b and ch not in out_spec)
    if pre_b:
        b = clang.sum(b, pre_b)
        sb = "".join(ch for i, ch in enumerate(sb) if i not in pre_b)
        free_b = [ch for ch in free_b if ch in sb]

    def dims_of(spec, chs):
        return {ch: spec.index(ch) for ch in chs}

    da, db = dims_of(sa, sa), dims_of(sb, sb)
    size = {}
    for spec, op in ((sa, a), (sb, b)):
        for i, ch in enumerate(spec):
            size[ch] = op.shape[i]

    def prod(chs):
        n = 1
        for ch in chs:
            n *= size[ch]
        return n

    # a → (batch, free_a, contract); b → (batch, contract, free_b)
    a_perm = tuple(da[ch] for ch in batch + free_a + contract)
    b_perm = tuple(db[ch] for ch in batch + contract + free_b)
    a2 = clang.reshape(clang.permute(a, a_perm), (prod(batch), prod(free_a), prod(contract)))
    b2 = clang.reshape(clang.permute(b, b_perm), (prod(batch), prod(contract), prod(free_b)))
    o = clang.matmul(a2, b2)  # (batch, free_a, free_b)
    o = clang.reshape(o, tuple(size[ch] for ch in batch) + tuple(size[ch] for ch in free_a)
                      + tuple(size[ch] for ch in free_b))
    cur = batch + free_a + free_b
    perm = tuple(cur.index(ch) for ch in out_spec)
    return clang.permute(o, perm) if perm != tuple(range(len(perm))) else o


@torchsymbol("torch.nn.functional.embedding")
def embedding(indices, weight, padding_idx=None, max_norm=None, norm_type: float = 2.0,
              scale_grad_by_freq: bool = False, sparse: bool = False):
    check(max_norm is None, "embedding max_norm is not supported")
    return clang.embedding(indices, weight)


@torchsymbol("torch.nn.functional.conv1d")
def conv1d(a, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1):
    return _convnd(a, weight, bias, stride, padding, dilation, groups, 1)


@torchsymbol("torch.nn.functional.conv2d")
def conv2d(a, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1):
    return _convnd(a, weight, bias, stride, padding, dilation, groups, 2)


@torchsymbol("torch.nn.functional.conv3d")
def conv3d(a, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1):
    return _convnd(a, weight, bias, stride, padding, dilation, groups, 3)


def _convnd(a, weight, bias, stride, padding, dilation, groups, spatial):
    def _seq(x):
        return (x,) * spatial if isinstance(x, (int, NumberProxy)) else tuple(x)

    return clang.convolution(a, weight, bias, _seq(stride), _seq(padding), _seq(dilation), groups)


# =============================================================================
# Normalization
# =============================================================================


@torchsymbol("torch.nn.functional.layer_norm")
def layer_norm(a, normalized_shape, weight=None, bias=None, eps: float = 1e-5):
    n = len(tuple(normalized_shape))
    dims = tuple(range(a.ndim - n, a.ndim))
    # Compute statistics in f32 for bf16 inputs (torch's mixed-precision
    # layer_norm semantics; also the numerically right call on TPU).
    compute_dtype = dtypes.float32 if a.dtype in (dtypes.bfloat16, dtypes.float16) else a.dtype
    x = clang.maybe_convert_to_dtype(a, compute_dtype)
    v, m = clang.var_mean(x, dims, correction=0, keepdim=True)
    normed = clang.mul(clang.sub(x, m), clang.rsqrt(clang.add(v, eps)))
    normed = clang.maybe_convert_to_dtype(normed, a.dtype)
    if weight is not None:
        normed = clang.mul(normed, weight)
    if bias is not None:
        normed = clang.add(normed, bias)
    return normed


@torchsymbol("torch.nn.functional.rms_norm")
def rms_norm(a, normalized_shape, weight=None, eps: Optional[float] = None):
    if eps is None:
        eps = 1e-6
    n = len(tuple(normalized_shape))
    dims = tuple(range(a.ndim - n, a.ndim))
    compute_dtype = dtypes.float32 if a.dtype in (dtypes.bfloat16, dtypes.float16) else a.dtype
    x = clang.maybe_convert_to_dtype(a, compute_dtype)
    ms = clang.mean(clang.mul(x, x), dims, True)
    normed = clang.mul(x, clang.rsqrt(clang.add(ms, eps)))
    normed = clang.maybe_convert_to_dtype(normed, a.dtype)
    if weight is not None:
        normed = clang.mul(normed, weight)
    return normed


@torchsymbol("torch.nn.functional.group_norm")
def group_norm(a, num_groups: int, weight=None, bias=None, eps: float = 1e-5):
    check(a.ndim >= 2, "group_norm requires rank >= 2")
    N, C = a.shape[0], a.shape[1]
    check(C % num_groups == 0, "channels must divide num_groups")
    spatial = a.shape[2:]
    x = clang.reshape(a, (N, num_groups, C // num_groups) + tuple(spatial))
    dims = tuple(range(2, x.ndim))
    v, m = clang.var_mean(x, dims, correction=0, keepdim=True)
    normed = clang.mul(clang.sub(x, m), clang.rsqrt(clang.add(v, eps)))
    normed = clang.reshape(normed, tuple(a.shape))
    shape = (1, C) + (1,) * len(spatial)
    if weight is not None:
        normed = clang.mul(normed, clang.reshape(weight, shape))
    if bias is not None:
        normed = clang.add(normed, clang.reshape(bias, shape))
    return normed


# =============================================================================
# Dropout and losses
# =============================================================================


@torchsymbol("torch.nn.functional.dropout")
def dropout(a, p: float = 0.5, training: bool = True, inplace: bool = False):
    p = float(pyval(p))
    if not training or p == 0.0:
        return a
    check(0.0 <= p < 1.0, lambda: f"dropout p must be in [0, 1), got {p}")
    mask = clang.lt(clang.uniform(a.shape, 0.0, 1.0, device=a.device, dtype=a.dtype), 1.0 - p)
    return clang.mul(clang.where(mask, a, clang.zeros_like(a)), 1.0 / (1.0 - p))


@torchsymbol("torch.nn.functional.cross_entropy")
def cross_entropy(input, target, weight=None, ignore_index: int = -100, reduction: str = "mean",
                  label_smoothing: float = 0.0):
    """Fused-friendly cross-entropy: log_softmax + gather. Kept composite so
    the Pallas CE executor can claim it whole (reference: the Triton/Apex
    cross-entropy executor seats, thunder/executors/triton_crossentropy.py)."""
    check(input.ndim == 2, "cross_entropy expects (N, C) logits (flatten upstream)")
    check(target.ndim == 1, "cross_entropy expects (N,) integer targets")
    check(weight is None, "cross_entropy class weights not supported yet")
    N, C = input.shape
    logp = log_softmax(input, 1)
    picked = clang.squeeze(clang.take_along_axis(logp, clang.reshape(clang.maximum(target, 0), (N, 1)), 1), (1,))
    nll = clang.neg(picked)
    if label_smoothing > 0.0:
        smooth = clang.neg(clang.mean(logp, (1,)))
        nll = clang.add(clang.mul(nll, 1.0 - label_smoothing), clang.mul(smooth, label_smoothing))
    valid = clang.ne(target, ignore_index)
    nll = clang.where(valid, nll, clang.zeros_like(nll))
    if reduction == "none":
        return nll
    total = clang.sum(nll, None)
    if reduction == "sum":
        return total
    count = clang.sum(clang.maybe_convert_to_dtype(valid, nll.dtype), None)
    return clang.true_divide(total, clang.maximum(count, 1.0))


@torchsymbol("torch.nn.functional.nll_loss")
def nll_loss(input, target, weight=None, ignore_index: int = -100, reduction: str = "mean"):
    check(input.ndim == 2 and target.ndim == 1, "nll_loss expects (N, C) and (N,)")
    check(weight is None, "nll_loss class weights not supported yet")
    N, C = input.shape
    picked = clang.squeeze(clang.take_along_axis(input, clang.reshape(clang.maximum(target, 0), (N, 1)), 1), (1,))
    nll = clang.neg(picked)
    valid = clang.ne(target, ignore_index)
    nll = clang.where(valid, nll, clang.zeros_like(nll))
    if reduction == "none":
        return nll
    total = clang.sum(nll, None)
    if reduction == "sum":
        return total
    count = clang.sum(clang.maybe_convert_to_dtype(valid, nll.dtype), None)
    return clang.true_divide(total, clang.maximum(count, 1.0))


@torchsymbol("torch.nn.functional.mse_loss")
def mse_loss(input, target, reduction: str = "mean"):
    d = clang.sub(input, target)
    sq = clang.mul(d, d)
    if reduction == "none":
        return sq
    if reduction == "sum":
        return clang.sum(sq, None)
    return clang.mean(sq, None)


# =============================================================================
# Attention
# =============================================================================


@torchsymbol("torch.nn.functional.scaled_dot_product_attention")
def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p: float = 0.0,
                                 is_causal: bool = False, scale: Optional[float] = None,
                                 enable_gqa: bool = False):
    """SDPA over (..., H, S, E) — decomposes to matmul/softmax/matmul; kept
    composite so the Pallas flash-attention executor claims it whole
    (reference: the cudnnex/sdpaex executor seats)."""
    check(dropout_p == 0.0, "sdpa dropout is not supported yet")
    E = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(E)

    if enable_gqa and key.shape[-3] != query.shape[-3]:
        rep = query.shape[-3] // key.shape[-3]
        key = repeat_interleave(key, rep, -3)
        value = repeat_interleave(value, rep, -3)

    # Attention scores in f32 for bf16 inputs: softmax accumulates in f32 on
    # the VPU; the two matmuls stay bf16 on the MXU.
    q = clang.mul(query, scale)
    scores = clang.matmul(q, clang.transpose(key, -2, -1))
    scores = clang.maybe_convert_to_dtype(scores, dtypes.float32)

    S, L = query.shape[-2], key.shape[-2]
    if is_causal:
        check(attn_mask is None, "is_causal and attn_mask are mutually exclusive")
        mask = clang.diagonal_mask(S, L, offset=L - S, upper=False, device=query.device)
        scores = clang.where(clang.expand_to(mask, scores.shape), scores, clang.full_like(scores, -float("inf")))
    elif attn_mask is not None:
        if dtypes.is_boolean_dtype(attn_mask.dtype):
            scores = clang.where(clang.expand_to(attn_mask, scores.shape), scores,
                                 clang.full_like(scores, -float("inf")))
        else:
            scores = clang.add(scores, clang.maybe_convert_to_dtype(attn_mask, dtypes.float32))

    probs = softmax(scores, -1)
    probs = clang.maybe_convert_to_dtype(probs, value.dtype)
    return clang.matmul(probs, value)


# =============================================================================
# Backward composites (claimable by fast executors; decompose for fallback)
# =============================================================================


@torchsymbol(id="torch.sdpa_bwd")
def sdpa_bwd(g, query, key, value, is_causal: bool = False, scale: Optional[float] = None,
             enable_gqa: bool = False):
    """(dq, dk, dv) of causal/plain SDPA by recompute — the flash executor
    replaces this whole op with the Pallas flash-attention backward
    (reference analogue: cudnnex's sdpa backward graph, cudnnex.py:375)."""
    E = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(E)
    H = query.shape[-3]
    G = key.shape[-3]

    k, v = key, value
    if enable_gqa and G != H:
        rep = H // G
        k = repeat_interleave(k, rep, -3)
        v = repeat_interleave(v, rep, -3)

    qf = clang.maybe_convert_to_dtype(query, dtypes.float32)
    kf = clang.maybe_convert_to_dtype(k, dtypes.float32)
    vf = clang.maybe_convert_to_dtype(v, dtypes.float32)
    gf = clang.maybe_convert_to_dtype(g, dtypes.float32)

    s = clang.mul(clang.matmul(qf, clang.transpose(kf, -2, -1)), scale)
    S, L = query.shape[-2], key.shape[-2]
    if is_causal:
        cmask = clang.diagonal_mask(S, L, offset=L - S, upper=False, device=query.device)
        s = clang.where(clang.expand_to(cmask, s.shape), s, clang.full_like(s, -float("inf")))
    p = softmax(s, -1)

    dv = clang.matmul(clang.transpose(p, -2, -1), gf)
    dp = clang.matmul(gf, clang.transpose(vf, -2, -1))
    ds = clang.mul(p, clang.sub(dp, clang.sum(clang.mul(dp, p), (-1,), True)))
    dq = clang.mul(clang.matmul(ds, kf), scale)
    dk = clang.mul(clang.matmul(clang.transpose(ds, -2, -1), qf), scale)

    if enable_gqa and G != H:
        rep = H // G
        bshape = tuple(dk.shape[:-3])
        dk = clang.sum(clang.reshape(dk, bshape + (G, rep) + tuple(dk.shape[-2:])), (len(bshape) + 1,))
        dv = clang.sum(clang.reshape(dv, bshape + (G, rep) + tuple(dv.shape[-2:])), (len(bshape) + 1,))

    dq = clang.maybe_convert_to_dtype(dq, query.dtype)
    dk = clang.maybe_convert_to_dtype(dk, key.dtype)
    dv = clang.maybe_convert_to_dtype(dv, value.dtype)
    return dq, dk, dv


@torchsymbol(id="torch.cross_entropy_bwd")
def cross_entropy_bwd(g, input, target, ignore_index: int = -100, reduction: str = "mean"):
    """dlogits of fused cross-entropy: (softmax − onehot) · g/count. The
    Pallas executor replaces this whole op (reference analogue: the Triton
    CE backward kernels, triton_crossentropy.py:270,343)."""
    N, C = input.shape
    p = softmax(clang.maybe_convert_to_dtype(input, dtypes.float32), 1)
    cols = clang.expand_to(clang.arange(0, C, 1, device=input.device, dtype=dtypes.int64), (N, C))
    onehot = clang.maybe_convert_to_dtype(clang.eq(cols, clang.unsqueeze(clang.maximum(target, 0), 1)),
                                          dtypes.float32)
    valid = clang.ne(target, ignore_index)
    validf = clang.maybe_convert_to_dtype(valid, dtypes.float32)
    if reduction == "mean":
        count = clang.maximum(clang.sum(validf, None), 1.0)
        row_scale = clang.true_divide(clang.mul(g, validf), count)
    else:  # sum
        row_scale = clang.mul(g, validf)
    d = clang.mul(clang.sub(p, onehot), clang.unsqueeze(row_scale, 1))
    return clang.maybe_convert_to_dtype(d, input.dtype)


def _register_composite_vjps():
    from thunder_tpu.transforms.autodiff import register_vjp

    def _sdpa_args(args, kwargs):
        names = ("query", "key", "value", "attn_mask", "dropout_p", "is_causal", "scale", "enable_gqa")
        defaults = {"attn_mask": None, "dropout_p": 0.0, "is_causal": False, "scale": None, "enable_gqa": False}
        bound = dict(zip(names, args))
        bound.update(kwargs)
        for k, dflt in defaults.items():
            bound.setdefault(k, dflt)
        return bound

    def _sdpa_checker(*args, **kwargs):
        b = _sdpa_args(args, kwargs)
        return b["attn_mask"] is None and float(pyval(b["dropout_p"])) == 0.0

    @register_vjp("torch.scaled_dot_product_attention", checker=_sdpa_checker)
    def _sdpa_vjp(bsym, g):
        b = _sdpa_args(bsym.args, bsym.kwargs)
        dq, dk, dv = sdpa_bwd(g, b["query"], b["key"], b["value"], b["is_causal"], b["scale"], b["enable_gqa"])
        grads = [None] * len(bsym.args)
        for i, name in enumerate(("query", "key", "value")):
            if i < len(bsym.args):
                grads[i] = (dq, dk, dv)[i]
        return grads

    def _ce_checker(input, target, weight=None, ignore_index=-100, reduction="mean", label_smoothing=0.0):
        return weight is None and float(pyval(label_smoothing)) == 0.0 and reduction in ("mean", "sum")

    @register_vjp("torch.cross_entropy", checker=_ce_checker)
    def _ce_vjp(bsym, g):
        bound = dict(zip(("input", "target", "weight", "ignore_index", "reduction"), bsym.args))
        bound.update(bsym.kwargs)
        d = cross_entropy_bwd(
            g, bound["input"], bound["target"],
            bound.get("ignore_index", -100), bound.get("reduction", "mean"),
        )
        return (d,) + (None,) * (len(bsym.args) - 1)


_register_composite_vjps()


# =============================================================================
# Misc tensor methods
# =============================================================================


def _size(a, dim: Optional[int] = None):
    if dim is None:
        return tuple(a.shape)
    return a.shape[canonicalize_dim(a.ndim, int(pyval(dim)))]


_torch_ctx.register_method("size", _size)
_torch_ctx.register_method("dim", lambda a: a.ndim)
_torch_ctx.register_method("numel", lambda a: a.numel)
_torch_ctx.register_method("float", lambda a: clang.maybe_convert_to_dtype(a, dtypes.float32))
_torch_ctx.register_method("type", lambda a, dt=None: a.dtype if dt is None else clang.maybe_convert_to_dtype(a, dtypes.to_dtype(dt)))


# Generated code prints ltorch symbols qualified as ``ltorch.<name>``.
register_module("ltorch", __import__("sys").modules[__name__])


def torch_function_map() -> dict:
    return _torch_to_thunder_function_map
