"""The torch-mirror language layer ("ltorch").

Reference parity: thunder/torch/__init__.py (168 `@torchsymbol`s mirroring the
`torch.*` / `torch.nn.functional.*` API, the `_torch_to_thunder_function_map`
at `:61` consumed by frontend lookasides, and method registration via
`torchsymbol:73`).

Each op here is a :class:`~thunder_tpu.core.symbol.Symbol` whose meta function
*decomposes* into clang ops and prims while tracing — producing the
multi-level IR that lets high-priority executors (e.g. the Pallas
flash-attention executor) claim composite ops whole, while the terminal
JAX/XLA executor claims the prims they decompose into.

The dtype/shape semantics mirror torch (type promotion, integer true-division
producing floats, `keepdim`, negative dims, ...); the decompositions are
written to be XLA-friendly — static shapes, `where` instead of data-dependent
branches, reductions/matmuls the MXU can tile.
"""

from __future__ import annotations

import functools
import math
from numbers import Number
from typing import Any, Callable, Optional, Sequence, Union

import thunder_tpu.clang as clang
import thunder_tpu.core.prims as prims
from thunder_tpu.core import dtypes, devices, utils
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.langctxs import LanguageContext, Languages, register_langctx, resolve_language
from thunder_tpu.core.proxies import AnyProxy, NumberProxy, StringProxy, TensorProxy, pyval
from thunder_tpu.core.symbol import Symbol, register_module
from thunder_tpu.core.utils import canonicalize_dim, canonicalize_dims

# -- language context ---------------------------------------------------------

_torch_ctx = LanguageContext(Languages.TORCH)
# The torch language is a superset of clang's method surface.
_clang_ctx = resolve_language(Languages.CLANG)
_torch_ctx._methods.update(_clang_ctx._methods)
register_langctx(Languages.TORCH, _torch_ctx)

# torch.foo / torch.Tensor.foo / F.foo → ltorch symbol. Consumed by the
# module frontend's __torch_function__ dispatch (reference: thunder/torch
# `_torch_to_thunder_function_map:61`).
_torch_to_thunder_function_map: dict[Any, Callable] = {}


def _resolve_torch_attr(path: str):
    """'torch.nn.functional.linear' → the live torch object, or None."""
    try:
        import torch
    except ImportError:
        return None
    obj = torch
    for part in path.split(".")[1:]:
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


def _unproxy_static(x):
    """Replace static-valued scalar/string/opaque input proxies with their
    concrete values, recursively through containers.

    Exact under CONSTANT_VALUES caching: the prologue guards every number/
    string input value, so the computation is already specialized to them —
    recording the value (not the proxy) in the bound symbol keeps dims,
    mode strings, slices etc. out of the generated program's free variables.
    NumberProxies with *unknown* values (e.g. `.item()` outputs — genuinely
    dynamic) are preserved."""
    if isinstance(x, (tuple, list)):
        return type(x)(_unproxy_static(v) for v in x)
    if isinstance(x, dict):
        return {k: _unproxy_static(v) for k, v in x.items()}
    if isinstance(x, NumberProxy):
        return x.value if x.value is not None else x
    if isinstance(x, (StringProxy, AnyProxy)):
        return x.value
    return x


def torchsymbol(*torch_paths: str, method_name: Optional[str] = None, id: Optional[str] = None):
    """Create an ltorch Symbol from a decomposition fn, registering it under
    the given torch dotted paths and optionally as a tensor method
    (reference: thunder/torch `torchsymbol:73`).

    The registered callable unwraps static scalar/string input proxies at
    the op boundary (see ``_unproxy_static``) before recording the symbol."""

    def decorator(fn: Callable) -> Symbol:
        sym = Symbol(fn.__name__, meta=fn, id=id if id is not None else f"torch.{fn.__name__}", module="ltorch")

        @functools.wraps(fn)
        def op(*args, **kwargs):
            return sym(*_unproxy_static(args), **_unproxy_static(kwargs))

        op._symbol = sym
        for path in torch_paths:
            obj = _resolve_torch_attr(path)
            if obj is not None:
                _torch_to_thunder_function_map[obj] = op
        if method_name is not None:
            _torch_ctx.register_method(method_name, op)
        return op

    return decorator


def to_dtype(x) -> Optional[dtypes.dtype]:
    return dtypes.to_dtype(x) if x is not None else None


# The module shadows several builtins with torch-mirror ops below.
builtins_abs, builtins_min, builtins_max, builtins_sum = abs, min, max, sum


def _dim_seq(dim) -> Optional[tuple]:
    if dim is None:
        return None
    if isinstance(dim, (int, NumberProxy)):
        return (int(pyval(dim)),)
    return tuple(int(pyval(d)) for d in dim)


# =============================================================================
# Tensor creation
# =============================================================================


@torchsymbol("torch.zeros")
def zeros(*size, dtype=None, device=None, requires_grad: bool = False):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.full(tuple(shape), 0, device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.ones")
def ones(*size, dtype=None, device=None, requires_grad: bool = False):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.full(tuple(shape), 1, device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.full")
def full(size, fill_value, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.full(tuple(size), fill_value, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.empty")
def empty(*size, dtype=None, device=None, requires_grad: bool = False):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.full(tuple(shape), 0, device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.zeros_like")
def zeros_like(a, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.zeros_like(a, device=device, dtype=to_dtype(dtype))


def _new_factory_shape(size) -> tuple:
    if len(size) == 1 and isinstance(size[0], (tuple, list)):
        return tuple(size[0])
    return tuple(size)


@torchsymbol("torch.Tensor.new_zeros", method_name="new_zeros")
def new_zeros(a, *size, dtype=None, device=None, requires_grad: bool = False):
    return clang.full(_new_factory_shape(size), 0, device=device or a.device,
                      dtype=to_dtype(dtype) or a.dtype)


@torchsymbol("torch.Tensor.new_ones", method_name="new_ones")
def new_ones(a, *size, dtype=None, device=None, requires_grad: bool = False):
    return clang.full(_new_factory_shape(size), 1, device=device or a.device,
                      dtype=to_dtype(dtype) or a.dtype)


@torchsymbol("torch.Tensor.new_full", method_name="new_full")
def new_full(a, size, fill_value, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.full(tuple(size), fill_value, device=device or a.device,
                      dtype=to_dtype(dtype) or a.dtype)


@torchsymbol("torch.Tensor.new_empty", method_name="new_empty")
def new_empty(a, *size, dtype=None, device=None, requires_grad: bool = False):
    return clang.full(_new_factory_shape(size), 0, device=device or a.device,
                      dtype=to_dtype(dtype) or a.dtype)


@torchsymbol("torch.ones_like")
def ones_like(a, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.ones_like(a, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.full_like")
def full_like(a, fill_value, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.full_like(a, fill_value, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.arange")
def arange(start, end=None, step=1, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.arange(start, end, step, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.rand")
def rand(*size, dtype=None, device=None, requires_grad: bool = False, generator=None):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.uniform(tuple(shape), 0.0, 1.0, device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.randn")
def randn(*size, dtype=None, device=None, requires_grad: bool = False, generator=None):
    shape = size[0] if len(size) == 1 and isinstance(size[0], (tuple, list)) else size
    return clang.randn(tuple(shape), device=device, dtype=to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.tensor")
def tensor(data, *, dtype=None, device=None, requires_grad: bool = False):
    if isinstance(data, TensorProxy):
        return clang.to(data, device=device, dtype=to_dtype(dtype))
    if isinstance(data, (Number, NumberProxy)) and not isinstance(data, (list, tuple)):
        dt = to_dtype(dtype) or dtypes.to_strong(dtypes.numbertype_to_dtype(type(pyval(data))))
        return clang.full((), data, device=device, dtype=dt)
    return clang.tensor_from_sequence(data, device=device, dtype=to_dtype(dtype))


# =============================================================================
# Data movement / dtype casts
# =============================================================================


@torchsymbol("torch.Tensor.to", method_name="to")
def to(a, *args, **kwargs):
    device = kwargs.get("device")
    dtype = kwargs.get("dtype")
    for arg in args:
        if isinstance(arg, str) or type(arg).__name__ == "device" or isinstance(arg, devices.Device):
            device = arg
        elif arg is not None:
            dtype = arg
    return clang.to(a, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.Tensor.type_as", method_name="type_as")
def type_as(a, b):
    return clang.maybe_convert_to_dtype(a, b.dtype)


def _make_cast(name: str, dtype: dtypes.dtype) -> Symbol:
    def cast(a):
        return clang.maybe_convert_to_dtype(a, dtype)

    cast.__name__ = name
    sym = Symbol(name, meta=cast, id=f"torch.Tensor.{name}", module="ltorch")
    _torch_ctx.register_method(name, sym)
    obj = _resolve_torch_attr(f"torch.Tensor.{name}")
    if obj is not None:
        _torch_to_thunder_function_map[obj] = sym
    return sym


float_ = _make_cast("float", dtypes.float32)
double = _make_cast("double", dtypes.float64)
half = _make_cast("half", dtypes.float16)
bfloat16 = _make_cast("bfloat16", dtypes.bfloat16)
long = _make_cast("long", dtypes.int64)
int_ = _make_cast("int", dtypes.int32)
bool_ = _make_cast("bool", dtypes.bool8)


@torchsymbol("torch.Tensor.contiguous", method_name="contiguous")
def contiguous(a, *, memory_format=None):
    # All arrays are logically contiguous under XLA; layout is the compiler's.
    return prims.shallow_copy(a)


@torchsymbol("torch.clone", method_name="clone")
def clone(a, *, memory_format=None):
    return prims.shallow_copy(a)


@torchsymbol("torch.Tensor.detach", method_name="detach")
def detach(a):
    return prims.stop_gradient(a)


@torchsymbol("torch.Tensor.item", method_name="item")
def item(a):
    return prims.item(a)


# =============================================================================
# Shape operations
# =============================================================================


@torchsymbol("torch.Tensor.view", method_name="view")
def view(a, *shape):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    return reshape(a, shape)


@torchsymbol("torch.reshape", method_name="reshape")
def reshape(a, *shape):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    shape = [int(pyval(s)) for s in shape]
    if -1 in shape:
        idx = shape.index(-1)
        known = 1
        for i, s in enumerate(shape):
            if i != idx:
                known *= s
        check(known != 0 and a.numel % known == 0, lambda: f"cannot reshape {a.shape} to {shape}")
        shape[idx] = a.numel // known
    return clang.reshape(a, tuple(shape))


@torchsymbol("torch.permute", method_name="permute")
def permute(a, *dims):
    dims = dims[0] if len(dims) == 1 and isinstance(dims[0], (tuple, list)) else dims
    return clang.permute(a, tuple(int(pyval(d)) for d in dims))


@torchsymbol("torch.transpose", method_name="transpose")
def transpose(a, dim0: int, dim1: int):
    return clang.transpose(a, int(pyval(dim0)), int(pyval(dim1)))


@torchsymbol("torch.Tensor.t", method_name="t")
def t(a):
    check(a.ndim <= 2, "t() requires rank <= 2")
    return clang.matrix_transpose(a) if a.ndim == 2 else a


@torchsymbol("torch.movedim", method_name="movedim")
def movedim(a, source, destination):
    return clang.movedim(a, source, destination)


@torchsymbol("torch.squeeze", method_name="squeeze")
def squeeze(a, dim=None):
    if dim is None:
        dims = tuple(i for i, s in enumerate(a.shape) if s == 1)
    else:
        d = canonicalize_dim(a.ndim, int(pyval(dim)))
        if a.shape[d] != 1:
            return a
        dims = (d,)
    return clang.squeeze(a, dims)


@torchsymbol("torch.unsqueeze", method_name="unsqueeze")
def unsqueeze(a, dim: int):
    return clang.unsqueeze(a, int(pyval(dim)))


@torchsymbol("torch.flatten", method_name="flatten")
def flatten(a, start_dim: int = 0, end_dim: int = -1):
    return clang.flatten(a, int(pyval(start_dim)), int(pyval(end_dim)))


@torchsymbol("torch.cat", "torch.concat")
def cat(tensors, dim: int = 0):
    # torch's legacy allowance: 1-D zero-element tensors are compatible with
    # anything in cat and contribute nothing (HF KV caches rely on this).
    tensors = [t for t in tensors if not (t.ndim == 1 and t.numel == 0)]
    check(len(tensors) > 0, "cat of only empty tensors")
    if len(tensors) == 1:
        return prims.shallow_copy(tensors[0])
    return clang.cat(list(tensors), int(pyval(dim)))


@torchsymbol("torch.stack")
def stack(tensors, dim: int = 0):
    return clang.stack(list(tensors), int(pyval(dim)))


@torchsymbol("torch.chunk", method_name="chunk")
def chunk(a, chunks: int, dim: int = 0):
    check(int(pyval(chunks)) > 0, lambda: f"chunk expects `chunks` to be greater than 0, got {chunks}")
    return clang.chunk(a, int(pyval(chunks)), int(pyval(dim)))


@torchsymbol("torch.split", method_name="split")
def split(a, split_size_or_sections, dim: int = 0):
    return clang.split(a, split_size_or_sections, int(pyval(dim)))


@torchsymbol("torch.Tensor.expand", method_name="expand")
def expand(a, *shape):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    shape = list(int(pyval(s)) for s in shape)
    offset = len(shape) - a.ndim
    for i, s in enumerate(shape):
        if s == -1:
            check(i >= offset, "cannot use -1 for a new leading dim in expand")
            shape[i] = a.shape[i - offset]
    return clang.expand(a, tuple(shape))


@torchsymbol("torch.Tensor.repeat", method_name="repeat")
def repeat(a, *sizes):
    sizes = sizes[0] if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)) else sizes
    sizes = tuple(int(pyval(s)) for s in sizes)
    check(len(sizes) >= a.ndim, "repeat requires at least a.ndim sizes")
    offset = len(sizes) - a.ndim
    r = a
    for _ in range(offset):
        r = clang.unsqueeze(r, 0)
    # tile by interleaving reshape/broadcast per dim
    for i, n in enumerate(sizes):
        if n != 1:
            r = clang.unsqueeze(r, i)
            target = list(r.shape)
            target[i] = n
            r = clang.expand(r, tuple(target))
            merged = list(r.shape)
            merged[i + 1] = merged[i] * merged[i + 1]
            del merged[i]
            r = clang.reshape(r, tuple(merged))
    return r


@torchsymbol("torch.flip", method_name="flip")
def flip(a, dims):
    return clang.flip(a, dims)


@torchsymbol("torch.Tensor.__getitem__", method_name="getitem")
def getitem(a, key):
    return clang.getitem(a, key)


@torchsymbol("torch.index_select", method_name="index_select")
def index_select(a, dim: int, index):
    return clang.take(a, index, int(pyval(dim)))


@torchsymbol("torch.gather", method_name="gather")
def gather(a, dim: int, index):
    return clang.gather(a, int(pyval(dim)), index)


@torchsymbol("torch.scatter_add", method_name="scatter_add")
def scatter_add(a, dim: int, index, src):
    return clang.scatter_add(a, int(pyval(dim)), index, src)


@torchsymbol("torch.take_along_dim", method_name="take_along_dim")
def take_along_dim(a, indices, dim: int):
    return clang.take_along_axis(a, indices, int(pyval(dim)))


def _normalize_index_key(key):
    """pyval static ints (incl. inside slices); keep TensorProxy indices."""
    def one(k):
        if isinstance(k, slice):
            return slice(one(k.start), one(k.stop), one(k.step))
        from thunder_tpu.core.proxies import NumberProxy

        if isinstance(k, NumberProxy):
            return pyval(k)
        return k

    if isinstance(key, tuple):
        return tuple(one(k) for k in key)
    return one(key)


@torchsymbol("torch.setitem", method_name="setitem")
def setitem(a, key, value):
    """Out-of-place ``a[key] = value`` (a copy with the update applied);
    the in-place form functionalizes through ``TensorProxy.__setitem__``
    (HF T5's relative-position bucketing writes slices in place).

    Boolean-mask keys: ``a[mask] = scalar`` lowers to ``where`` (static
    shapes — the jax scatter path would need concrete indices); a TENSOR
    value under a boolean mask is data-dependently shaped and rejected
    loudly."""
    from thunder_tpu.core import dtypes as _dt

    keys = key if isinstance(key, tuple) else (key,)
    bool_masks = [
        k for k in keys
        if isinstance(k, TensorProxy) and _dt.is_boolean_dtype(_dt.to_dtype(k.dtype))
    ]
    if bool_masks:
        if len(keys) == 1 and not isinstance(value, TensorProxy):
            mask = bool_masks[0]
            # torch aligns mask dims with a's LEADING dims; expand trailing.
            while mask.ndim < a.ndim:
                mask = unsqueeze(mask, mask.ndim)
            fill = clang.full((), pyval(value), device=a.device, dtype=a.dtype)
            return clang.where(mask, fill, a)
        raise NotImplementedError(
            "setitem with a boolean mask and a tensor value (or a mask "
            "inside a tuple key) is data-dependently shaped; use "
            "masked_fill / torch.where, or index with integer tensors"
        )
    if isinstance(value, TensorProxy):
        value = clang.maybe_convert_to_dtype(value, a.dtype)
    else:
        value = pyval(value)
    return prims.setitem(a, _normalize_index_key(key), value)


@torchsymbol("torch.index_put", method_name="index_put")
def index_put(a, indices, values, accumulate: bool = False):
    return clang.index_put(a, indices, values, accumulate)


@torchsymbol("torch.tril", method_name="tril")
def tril(a, diagonal: int = 0):
    return clang.tril(a, int(pyval(diagonal)))


@torchsymbol("torch.triu", method_name="triu")
def triu(a, diagonal: int = 0):
    return clang.triu(a, int(pyval(diagonal)))


@torchsymbol("torch.Tensor.masked_fill", method_name="masked_fill")
def masked_fill(a, mask, value):
    return clang.where(mask, value, a)


@torchsymbol("torch.where")
def where(pred, a=None, b=None):
    check(a is not None and b is not None, "where() requires three arguments")
    return clang.where(pred, a, b)


@torchsymbol("torch.topk", method_name="topk")
def topk(a, k: int, dim: int = -1, largest: bool = True, sorted: bool = True):
    return clang.topk(a, k, dim, largest, sorted)


@torchsymbol("torch.sort", method_name="sort")
def sort(a, dim: int = -1, descending: bool = False):
    return clang.sort(a, dim, descending)


@torchsymbol("torch.argsort", method_name="argsort")
def argsort(a, dim: int = -1, descending: bool = False):
    return clang.argsort(a, dim, descending)


@torchsymbol("torch.cumsum", method_name="cumsum")
def cumsum(a, dim: int, *, dtype=None):
    r = clang.cumsum(a, int(pyval(dim)))
    if dtype is not None:
        r = clang.maybe_convert_to_dtype(r, to_dtype(dtype))
    return r


@torchsymbol("torch.repeat_interleave", method_name="repeat_interleave")
def repeat_interleave(a, repeats: int, dim: Optional[int] = None):
    check(isinstance(repeats, (int, NumberProxy)), "only int repeats supported")
    n = int(pyval(repeats))
    if dim is None:
        a = flatten(a)
        dim = 0
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    r = clang.unsqueeze(a, d + 1)
    target = list(r.shape)
    target[d + 1] = n
    r = clang.expand(r, tuple(target))
    merged = list(a.shape)
    merged[d] = merged[d] * n
    return clang.reshape(r, tuple(merged))


# =============================================================================
# Elementwise ops (torch.* functions; methods inherited from clang)
# =============================================================================


def _register_elementwise(name: str, clang_fn: Callable, torch_paths: Sequence[str], method: Optional[str] = None):
    def meta(*args, **kwargs):
        return clang_fn(*args, **kwargs)

    meta.__name__ = name
    sym = Symbol(name, meta=meta, id=f"torch.{name}", module="ltorch")
    for path in torch_paths:
        obj = _resolve_torch_attr(path)
        if obj is not None:
            _torch_to_thunder_function_map[obj] = sym
    if method is not None:
        _torch_ctx.register_method(method, sym)
    return sym


# unary
abs = _register_elementwise("abs", clang.abs, ["torch.abs", "torch.Tensor.abs"])
acos = _register_elementwise("acos", clang.acos, ["torch.acos"])
asin = _register_elementwise("asin", clang.asin, ["torch.asin"])
atan = _register_elementwise("atan", clang.atan, ["torch.atan"])
ceil = _register_elementwise("ceil", clang.ceil, ["torch.ceil"])
cos = _register_elementwise("cos", clang.cos, ["torch.cos", "torch.Tensor.cos"])
cosh = _register_elementwise("cosh", clang.cosh, ["torch.cosh"])
erf = _register_elementwise("erf", clang.erf, ["torch.erf"])
exp = _register_elementwise("exp", clang.exp, ["torch.exp", "torch.Tensor.exp"])
expm1 = _register_elementwise("expm1", clang.expm1, ["torch.expm1"])
floor = _register_elementwise("floor", clang.floor, ["torch.floor"])
isfinite = _register_elementwise("isfinite", clang.isfinite, ["torch.isfinite"])
isinf = _register_elementwise("isinf", clang.isinf, ["torch.isinf"])
isnan = _register_elementwise("isnan", clang.isnan, ["torch.isnan"])
log = _register_elementwise("log", clang.log, ["torch.log", "torch.Tensor.log"])
log1p = _register_elementwise("log1p", clang.log1p, ["torch.log1p"])
log2 = _register_elementwise("log2", clang.log2, ["torch.log2"])
neg = _register_elementwise("neg", clang.neg, ["torch.neg"])
reciprocal = _register_elementwise("reciprocal", clang.reciprocal, ["torch.reciprocal"])
round = _register_elementwise("round", clang.round, ["torch.round"])
rsqrt = _register_elementwise("rsqrt", clang.rsqrt, ["torch.rsqrt"])
sign = _register_elementwise("sign", clang.sign, ["torch.sign"])
sin = _register_elementwise("sin", clang.sin, ["torch.sin", "torch.Tensor.sin"])
sinh = _register_elementwise("sinh", clang.sinh, ["torch.sinh"])
sqrt = _register_elementwise("sqrt", clang.sqrt, ["torch.sqrt", "torch.Tensor.sqrt"])
tan = _register_elementwise("tan", clang.tan, ["torch.tan"])
tanh = _register_elementwise("tanh", clang.tanh, ["torch.tanh", "torch.Tensor.tanh"])
trunc = _register_elementwise("trunc", clang.trunc, ["torch.trunc"])
logical_not = _register_elementwise("logical_not", clang.logical_not, ["torch.logical_not"])
acosh = _register_elementwise("acosh", clang.acosh, ["torch.acosh", "torch.arccosh"])
asinh = _register_elementwise("asinh", clang.asinh, ["torch.asinh", "torch.arcsinh"])
atanh = _register_elementwise("atanh", clang.atanh, ["torch.atanh", "torch.arctanh"])
bitwise_not = _register_elementwise("bitwise_not", clang.bitwise_not, ["torch.bitwise_not"])
digamma = _register_elementwise("digamma", clang.digamma, ["torch.digamma", "torch.special.digamma"])
erfc = _register_elementwise("erfc", clang.erfc, ["torch.erfc", "torch.special.erfc"])
erfinv = _register_elementwise("erfinv", clang.erfinv, ["torch.erfinv", "torch.special.erfinv"])
exp2 = _register_elementwise("exp2", clang.exp2, ["torch.exp2", "torch.special.exp2"])
lgamma = _register_elementwise("lgamma", clang.lgamma, ["torch.lgamma", "torch.special.gammaln"])
log10 = _register_elementwise("log10", clang.log10, ["torch.log10"])
signbit = _register_elementwise("signbit", clang.signbit, ["torch.signbit"])
sgn = _register_elementwise("sgn", clang.sign, ["torch.sgn", "torch.Tensor.sgn"])


@torchsymbol("torch.square", method_name="square")
def square(a):
    return clang.mul(a, a)


@torchsymbol("torch.frac", method_name="frac")
def frac(a):
    return clang.sub(a, clang.trunc(a))


@torchsymbol("torch.rad2deg")
def rad2deg(a):
    return clang.mul(a, 180.0 / math.pi)


@torchsymbol("torch.deg2rad")
def deg2rad(a):
    return clang.mul(a, math.pi / 180.0)


@torchsymbol("torch.logit", "torch.special.logit")
def logit(a, eps: Optional[float] = None):
    if eps is not None:
        a = clang.clamp(a, eps, 1.0 - eps)
    return clang.log(clang.true_divide(a, clang.sub(1.0, a)))


@torchsymbol("torch.sinc", "torch.special.sinc")
def sinc(a):
    # sin(pi x)/(pi x), with the removable singularity patched at 0.
    px = clang.mul(a, math.pi)
    safe = clang.where(clang.eq(a, 0), clang.ones_like(px), px)
    return clang.where(clang.eq(a, 0), clang.ones_like(px), clang.true_divide(clang.sin(safe), safe))


@torchsymbol("torch.nan_to_num", method_name="nan_to_num")
def nan_to_num(a, nan: float = 0.0, posinf: Optional[float] = None, neginf: Optional[float] = None):
    check(isinstance(a, TensorProxy), "nan_to_num expects a tensor")
    if not dtypes.is_float_dtype(a.dtype):
        return prims.shallow_copy(a)
    if posinf is None:
        posinf = float(dtypes.finfo_max(a.dtype))
    if neginf is None:
        neginf = -float(dtypes.finfo_max(a.dtype))
    r = clang.where(clang.isnan(a), clang.full_like(a, 0.0 if nan is None else nan), a)
    r = clang.where(clang.eq(a, float("inf")), clang.full_like(a, posinf), r)
    return clang.where(clang.eq(a, float("-inf")), clang.full_like(a, neginf), r)


@torchsymbol("torch.polygamma", "torch.special.polygamma")
def polygamma(n: int, a):
    return clang.polygamma(int(pyval(n)), a)

# binary
@torchsymbol("torch.add", "torch.Tensor.add", method_name="add")
def add(a, b, *, alpha=None):
    if alpha is not None and pyval(alpha) != 1:
        b = clang.mul(b, alpha)
    return clang.add(a, b)


add_sym = add  # backwards-compatible alias


@torchsymbol("torch.sub", "torch.subtract", "torch.Tensor.sub", method_name="sub")
def sub(a, b, *, alpha=None):
    if alpha is not None and pyval(alpha) != 1:
        b = clang.mul(b, alpha)
    return clang.sub(a, b)


@torchsymbol("torch.rsub", "torch.Tensor.rsub", method_name="rsub")
def rsub(a, b, *, alpha=None):
    if alpha is not None and pyval(alpha) != 1:
        a = clang.mul(a, alpha)
    return clang.sub(b, a)


@torchsymbol("torch.div", "torch.true_divide", "torch.Tensor.div", method_name="div")
def div_sym(a, b, *, rounding_mode: Optional[str] = None):
    if rounding_mode is None:
        return clang.true_divide(a, b)
    if rounding_mode == "floor":
        return clang.floor_divide(a, b)
    check(rounding_mode == "trunc", lambda: f"Unknown rounding_mode {rounding_mode}")
    r = clang.true_divide(a, b)
    if dtypes.is_float_dtype(r.dtype):
        r = clang.trunc(r)
    from_int = all(
        not isinstance(x, TensorProxy) or dtypes.is_exact_dtype(x.dtype) for x in (a, b)
    ) and not any(isinstance(x, float) for x in (a, b) if not isinstance(x, TensorProxy))
    if from_int:
        ref = a if isinstance(a, TensorProxy) else b
        if isinstance(ref, TensorProxy) and dtypes.is_exact_dtype(ref.dtype):
            r = clang.maybe_convert_to_dtype(r, ref.dtype)
    return r


atan2 = _register_elementwise("atan2", clang.atan2, ["torch.atan2"])
bitwise_and = _register_elementwise("bitwise_and", clang.bitwise_and, ["torch.bitwise_and"])
bitwise_or = _register_elementwise("bitwise_or", clang.bitwise_or, ["torch.bitwise_or"])
bitwise_xor = _register_elementwise("bitwise_xor", clang.bitwise_xor, ["torch.bitwise_xor"])
div = div_sym
eq = _register_elementwise("eq", clang.eq, ["torch.eq"])
floor_divide = _register_elementwise("floor_divide", clang.floor_divide, ["torch.floor_divide"])
fmod = _register_elementwise("fmod", clang.fmod, ["torch.fmod"])
ge = _register_elementwise("ge", clang.ge, ["torch.ge"])
gt = _register_elementwise("gt", clang.gt, ["torch.gt"])
le = _register_elementwise("le", clang.le, ["torch.le"])
lt = _register_elementwise("lt", clang.lt, ["torch.lt"])
maximum = _register_elementwise("maximum", clang.maximum, ["torch.maximum"])
minimum = _register_elementwise("minimum", clang.minimum, ["torch.minimum"])
mul = _register_elementwise("mul", clang.mul, ["torch.mul", "torch.Tensor.mul"])
ne = _register_elementwise("ne", clang.ne, ["torch.ne"])
pow = _register_elementwise("pow", clang.pow, ["torch.pow", "torch.Tensor.pow"])
remainder = _register_elementwise("remainder", clang.remainder, ["torch.remainder"])
copysign = _register_elementwise("copysign", clang.copysign, ["torch.copysign"])
clamp = _register_elementwise("clamp", clang.clamp, ["torch.clamp", "torch.Tensor.clamp"])
clamp_min = _register_elementwise("clamp_min", lambda a, m: clang.clamp(a, m, None), ["torch.clamp_min", "torch.Tensor.clamp_min"], method="clamp_min")
clamp_max = _register_elementwise("clamp_max", lambda a, m: clang.clamp(a, None, m), ["torch.clamp_max", "torch.Tensor.clamp_max"], method="clamp_max")


@torchsymbol("torch.sigmoid", "torch.nn.functional.sigmoid", method_name="sigmoid")
def sigmoid(a):
    return clang.sigmoid(a)


@torchsymbol("torch.nn.functional.softplus")
def softplus(a, beta: float = 1.0, threshold: float = 20.0):
    scaled = clang.mul(a, beta)
    soft = clang.true_divide(clang.log1p(clang.exp(scaled)), beta)
    return clang.where(clang.gt(scaled, threshold), a, soft)


# =============================================================================
# Activations
# =============================================================================


@torchsymbol("torch.nn.functional.relu", method_name="relu")
def relu(a, inplace: bool = False):
    return clang.maximum(a, 0)


@torchsymbol("torch.nn.functional.leaky_relu")
def leaky_relu(a, negative_slope: float = 0.01, inplace: bool = False):
    return clang.where(clang.gt(a, 0), a, clang.mul(a, negative_slope))


@torchsymbol("torch.nn.functional.elu")
def elu(a, alpha: float = 1.0, inplace: bool = False):
    return clang.where(clang.gt(a, 0), a, clang.mul(alpha, clang.expm1(a)))


@torchsymbol("torch.nn.functional.gelu")
def gelu(a, approximate: str = "none"):
    if approximate == "tanh":
        inner = clang.mul(math.sqrt(2.0 / math.pi), clang.add(a, clang.mul(0.044715, clang.mul(a, clang.mul(a, a)))))
        return clang.mul(clang.mul(0.5, a), clang.add(1.0, clang.tanh(inner)))
    return clang.mul(clang.mul(0.5, a), clang.add(1.0, clang.erf(clang.mul(a, 1.0 / math.sqrt(2.0)))))


@torchsymbol("torch.nn.functional.silu")
def silu(a, inplace: bool = False):
    return clang.mul(a, sigmoid(a))


@torchsymbol("torch.nn.functional.mish")
def mish(a, inplace: bool = False):
    return clang.mul(a, clang.tanh(softplus(a)))


@torchsymbol("torch.nn.functional.hardswish")
def hardswish(a, inplace: bool = False):
    return clang.mul(a, clang.true_divide(clang.clamp(clang.add(a, 3.0), 0.0, 6.0), 6.0))


@torchsymbol("torch.softmax", "torch.nn.functional.softmax", method_name="softmax")
def softmax(a, dim: int, dtype=None, _stacklevel=3):
    # _stacklevel: torch-internal deprecation-warning plumbing
    # (F.softmax passes it through HF's T5 attention); accepted + ignored.
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, to_dtype(dtype))
    shifted = clang.sub(a, clang.amax(a, (d,), True))
    e = clang.exp(shifted)
    return clang.true_divide(e, clang.sum(e, (d,), True))


@torchsymbol("torch.log_softmax", "torch.nn.functional.log_softmax", method_name="log_softmax")
def log_softmax(a, dim: int, dtype=None, _stacklevel=3):
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, to_dtype(dtype))
    shifted = clang.sub(a, clang.amax(a, (d,), True))
    return clang.sub(shifted, clang.log(clang.sum(clang.exp(shifted), (d,), True)))


# =============================================================================
# Reductions
# =============================================================================


@torchsymbol("torch.sum", method_name="sum")
def sum(a, dim=None, keepdim: bool = False, *, dtype=None):
    return clang.sum(a, _dim_seq(dim), keepdim, dtype=to_dtype(dtype))


@torchsymbol("torch.mean", method_name="mean")
def mean(a, dim=None, keepdim: bool = False, *, dtype=None):
    return clang.mean(a, _dim_seq(dim), keepdim, dtype=to_dtype(dtype))


@torchsymbol("torch.prod", method_name="prod")
def prod(a, dim=None, keepdim: bool = False, *, dtype=None):
    r = clang.prod(a, _dim_seq(dim), keepdim)
    if dtype is not None:
        r = clang.maybe_convert_to_dtype(r, to_dtype(dtype))
    return r


@torchsymbol("torch.amax", method_name="amax")
def amax(a, dim=None, keepdim: bool = False):
    return clang.amax(a, _dim_seq(dim), keepdim)


@torchsymbol("torch.amin", method_name="amin")
def amin(a, dim=None, keepdim: bool = False):
    return clang.amin(a, _dim_seq(dim), keepdim)


@torchsymbol("torch.max", method_name="max")
def max(a, dim=None, keepdim: bool = False):
    if isinstance(dim, TensorProxy):
        return clang.maximum(a, dim)
    if dim is None:
        return clang.amax(a, None, False)
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    return clang.amax(a, (d,), keepdim), clang.argmax(a, d, keepdim)


@torchsymbol("torch.min", method_name="min")
def min(a, dim=None, keepdim: bool = False):
    if isinstance(dim, TensorProxy):
        return clang.minimum(a, dim)
    if dim is None:
        return clang.amin(a, None, False)
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    return clang.amin(a, (d,), keepdim), clang.argmin(a, d, keepdim)


@torchsymbol("torch.argmax", method_name="argmax")
def argmax(a, dim=None, keepdim: bool = False):
    return clang.argmax(a, dim if dim is None else int(pyval(dim)), keepdim)


@torchsymbol("torch.argmin", method_name="argmin")
def argmin(a, dim=None, keepdim: bool = False):
    return clang.argmin(a, dim if dim is None else int(pyval(dim)), keepdim)


@torchsymbol("torch.var", method_name="var")
def var(a, dim=None, *, correction: Number = 1, keepdim: bool = False):
    return clang.var(a, _dim_seq(dim), correction=correction, keepdim=keepdim)


@torchsymbol("torch.var_mean")
def var_mean(a, dim=None, *, correction: Number = 1, keepdim: bool = False):
    return clang.var_mean(a, _dim_seq(dim), correction=correction, keepdim=keepdim)


@torchsymbol("torch.std", method_name="std")
def std(a, dim=None, *, correction: Number = 1, keepdim: bool = False):
    return clang.std(a, _dim_seq(dim), correction=correction, keepdim=keepdim)


@torchsymbol("torch.all", method_name="all")
def all(a, dim=None, keepdim: bool = False):
    return clang.all_tensor(a, _dim_seq(dim), keepdim)


@torchsymbol("torch.any", method_name="any")
def any(a, dim=None, keepdim: bool = False):
    return clang.any_tensor(a, _dim_seq(dim), keepdim)


# =============================================================================
# Linear algebra / NN ops
# =============================================================================


@torchsymbol("torch.matmul", method_name="matmul")
def matmul(a, b):
    return clang.matmul(a, b)


@torchsymbol("torch.bmm", method_name="bmm")
def bmm(a, b):
    check(a.ndim == 3 and b.ndim == 3, "bmm requires rank-3 tensors")
    return clang.matmul(a, b)


@torchsymbol("torch.nn.functional.linear")
def linear(a, w, bias=None):
    return clang.linear(a, w, bias)


@torchsymbol("torch.outer", method_name="outer")
def outer(a, b):
    check(a.ndim == 1 and b.ndim == 1, "outer requires rank-1 tensors")
    return clang.mul(clang.unsqueeze(a, 1), clang.unsqueeze(b, 0))


@torchsymbol("torch.einsum")
def einsum(equation: str, *operands):
    """Einstein summation decomposed to transpose/reshape/matmul prims (so
    the contraction lands on the MXU). Supports 1-2 operands, no repeated
    indices within an operand; '...' broadcasting is not supported yet."""
    if len(operands) == 1 and isinstance(operands[0], (tuple, list)):
        operands = tuple(operands[0])
    check("..." not in equation, "einsum ellipsis is not supported yet")
    eq = equation.replace(" ", "")
    if "->" in eq:
        lhs, out_spec = eq.split("->")
    else:
        lhs = eq
        # implicit output: non-repeated indices, sorted
        counts: dict[str, int] = {}
        for ch in lhs.replace(",", ""):
            counts[ch] = counts.get(ch, 0) + 1
        out_spec = "".join(sorted(ch for ch, n in counts.items() if n == 1))
    specs = lhs.split(",")
    check(len(specs) == len(operands), "einsum operand count mismatch")
    check(len(operands) in (1, 2), "einsum supports 1 or 2 operands")

    if len(operands) == 1:
        (spec,), (a,) = specs, operands
        check(len(set(spec)) == len(spec), "repeated in-operand indices unsupported")
        # sum out dims absent from output, then permute
        sum_dims = tuple(i for i, ch in enumerate(spec) if ch not in out_spec)
        if sum_dims:
            a = clang.sum(a, sum_dims)
            spec = "".join(ch for ch in spec if ch in out_spec)
        perm = tuple(spec.index(ch) for ch in out_spec)
        return clang.permute(a, perm) if perm != tuple(range(len(perm))) else a

    sa, sb = specs
    a, b = operands
    check(len(set(sa)) == len(sa) and len(set(sb)) == len(sb),
          "repeated in-operand indices unsupported")
    # classify indices
    batch = [ch for ch in sa if ch in sb and ch in out_spec]
    contract = [ch for ch in sa if ch in sb and ch not in out_spec]
    free_a = [ch for ch in sa if ch not in sb]
    free_b = [ch for ch in sb if ch not in sa]
    # sum out indices appearing in only one operand and not the output
    pre_a = tuple(i for i, ch in enumerate(sa) if ch in free_a and ch not in out_spec)
    if pre_a:
        a = clang.sum(a, pre_a)
        sa = "".join(ch for i, ch in enumerate(sa) if i not in pre_a)
        free_a = [ch for ch in free_a if ch in sa]
    pre_b = tuple(i for i, ch in enumerate(sb) if ch in free_b and ch not in out_spec)
    if pre_b:
        b = clang.sum(b, pre_b)
        sb = "".join(ch for i, ch in enumerate(sb) if i not in pre_b)
        free_b = [ch for ch in free_b if ch in sb]

    def dims_of(spec, chs):
        return {ch: spec.index(ch) for ch in chs}

    da, db = dims_of(sa, sa), dims_of(sb, sb)
    size = {}
    for spec, op in ((sa, a), (sb, b)):
        for i, ch in enumerate(spec):
            size[ch] = op.shape[i]

    def prod(chs):
        n = 1
        for ch in chs:
            n *= size[ch]
        return n

    # a → (batch, free_a, contract); b → (batch, contract, free_b)
    a_perm = tuple(da[ch] for ch in batch + free_a + contract)
    b_perm = tuple(db[ch] for ch in batch + contract + free_b)
    a2 = clang.reshape(clang.permute(a, a_perm), (prod(batch), prod(free_a), prod(contract)))
    b2 = clang.reshape(clang.permute(b, b_perm), (prod(batch), prod(contract), prod(free_b)))
    o = clang.matmul(a2, b2)  # (batch, free_a, free_b)
    o = clang.reshape(o, tuple(size[ch] for ch in batch) + tuple(size[ch] for ch in free_a)
                      + tuple(size[ch] for ch in free_b))
    cur = batch + free_a + free_b
    perm = tuple(cur.index(ch) for ch in out_spec)
    return clang.permute(o, perm) if perm != tuple(range(len(perm))) else o


@torchsymbol("torch.nn.functional.embedding")
def embedding(indices, weight, padding_idx=None, max_norm=None, norm_type: float = 2.0,
              scale_grad_by_freq: bool = False, sparse: bool = False):
    check(max_norm is None, "embedding max_norm is not supported")
    check(weight.ndim == 2, lambda: f"embedding weight must be rank 2, got shape {tuple(weight.shape)}")
    return clang.embedding(indices, weight)


@torchsymbol("torch.nn.functional.conv1d")
def conv1d(a, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1):
    return _convnd(a, weight, bias, stride, padding, dilation, groups, 1)


@torchsymbol("torch.nn.functional.conv2d")
def conv2d(a, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1):
    return _convnd(a, weight, bias, stride, padding, dilation, groups, 2)


@torchsymbol("torch.nn.functional.conv3d")
def conv3d(a, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1):
    return _convnd(a, weight, bias, stride, padding, dilation, groups, 3)


def _convnd(a, weight, bias, stride, padding, dilation, groups, spatial):
    def _seq(x):
        return (x,) * spatial if isinstance(x, (int, NumberProxy)) else tuple(x)

    return clang.convolution(a, weight, bias, _seq(stride), _seq(padding), _seq(dilation), groups)


# =============================================================================
# Normalization
# =============================================================================


@torchsymbol("torch.nn.functional.layer_norm")
def layer_norm(a, normalized_shape, weight=None, bias=None, eps: float = 1e-5):
    n = len(tuple(normalized_shape))
    dims = tuple(range(a.ndim - n, a.ndim))
    # Compute statistics in f32 for bf16 inputs (torch's mixed-precision
    # layer_norm semantics; also the numerically right call on TPU).
    compute_dtype = dtypes.float32 if a.dtype in (dtypes.bfloat16, dtypes.float16) else a.dtype
    x = clang.maybe_convert_to_dtype(a, compute_dtype)
    v, m = clang.var_mean(x, dims, correction=0, keepdim=True)
    normed = clang.mul(clang.sub(x, m), clang.rsqrt(clang.add(v, eps)))
    normed = clang.maybe_convert_to_dtype(normed, a.dtype)
    if weight is not None:
        normed = clang.mul(normed, weight)
    if bias is not None:
        normed = clang.add(normed, bias)
    return normed


@torchsymbol("torch.nn.functional.rms_norm")
def rms_norm(a, normalized_shape, weight=None, eps: Optional[float] = None):
    if eps is None:
        eps = 1e-6
    n = len(tuple(normalized_shape))
    dims = tuple(range(a.ndim - n, a.ndim))
    compute_dtype = dtypes.float32 if a.dtype in (dtypes.bfloat16, dtypes.float16) else a.dtype
    x = clang.maybe_convert_to_dtype(a, compute_dtype)
    ms = clang.mean(clang.mul(x, x), dims, True)
    normed = clang.mul(x, clang.rsqrt(clang.add(ms, eps)))
    normed = clang.maybe_convert_to_dtype(normed, a.dtype)
    if weight is not None:
        normed = clang.mul(normed, weight)
    return normed


@torchsymbol("torch.nn.functional.group_norm")
def group_norm(a, num_groups: int, weight=None, bias=None, eps: float = 1e-5):
    check(a.ndim >= 2, "group_norm requires rank >= 2")
    N, C = a.shape[0], a.shape[1]
    check(C % num_groups == 0, "channels must divide num_groups")
    spatial = a.shape[2:]
    x = clang.reshape(a, (N, num_groups, C // num_groups) + tuple(spatial))
    dims = tuple(range(2, x.ndim))
    v, m = clang.var_mean(x, dims, correction=0, keepdim=True)
    normed = clang.mul(clang.sub(x, m), clang.rsqrt(clang.add(v, eps)))
    normed = clang.reshape(normed, tuple(a.shape))
    shape = (1, C) + (1,) * len(spatial)
    if weight is not None:
        normed = clang.mul(normed, clang.reshape(weight, shape))
    if bias is not None:
        normed = clang.add(normed, clang.reshape(bias, shape))
    return normed


# =============================================================================
# Dropout and losses
# =============================================================================


@torchsymbol("torch.nn.functional.dropout")
def dropout(a, p: float = 0.5, training: bool = True, inplace: bool = False):
    p = float(pyval(p))
    if not training or p == 0.0:
        return a
    check(0.0 <= p < 1.0, lambda: f"dropout p must be in [0, 1), got {p}")
    mask = clang.lt(clang.uniform(a.shape, 0.0, 1.0, device=a.device, dtype=a.dtype), 1.0 - p)
    return clang.mul(clang.where(mask, a, clang.zeros_like(a)), 1.0 / (1.0 - p))


@torchsymbol("torch.nn.functional.cross_entropy")
def cross_entropy(input, target, weight=None, ignore_index: int = -100, reduction: str = "mean",
                  label_smoothing: float = 0.0):
    """Fused-friendly cross-entropy: log_softmax + gather. Kept composite so
    the Pallas CE executor can claim it whole (reference: the Triton/Apex
    cross-entropy executor seats, thunder/executors/triton_crossentropy.py)."""
    check(input.ndim == 2, "cross_entropy expects (N, C) logits (flatten upstream)")
    check(target.ndim == 1, "cross_entropy expects (N,) integer targets")
    check(weight is None, "cross_entropy class weights not supported yet")
    N, C = input.shape
    logp = log_softmax(input, 1)
    picked = clang.squeeze(clang.take_along_axis(logp, clang.reshape(clang.maximum(target, 0), (N, 1)), 1), (1,))
    nll = clang.neg(picked)
    if label_smoothing > 0.0:
        smooth = clang.neg(clang.mean(logp, (1,)))
        nll = clang.add(clang.mul(nll, 1.0 - label_smoothing), clang.mul(smooth, label_smoothing))
    valid = clang.ne(target, ignore_index)
    nll = clang.where(valid, nll, clang.zeros_like(nll))
    if reduction == "none":
        return nll
    total = clang.sum(nll, None)
    if reduction == "sum":
        return total
    count = clang.sum(clang.maybe_convert_to_dtype(valid, nll.dtype), None)
    return clang.true_divide(total, clang.maximum(count, 1.0))


@torchsymbol("torch.nn.functional.nll_loss")
def nll_loss(input, target, weight=None, ignore_index: int = -100, reduction: str = "mean"):
    check(input.ndim == 2 and target.ndim == 1, "nll_loss expects (N, C) and (N,)")
    check(weight is None, "nll_loss class weights not supported yet")
    N, C = input.shape
    picked = clang.squeeze(clang.take_along_axis(input, clang.reshape(clang.maximum(target, 0), (N, 1)), 1), (1,))
    nll = clang.neg(picked)
    valid = clang.ne(target, ignore_index)
    nll = clang.where(valid, nll, clang.zeros_like(nll))
    if reduction == "none":
        return nll
    total = clang.sum(nll, None)
    if reduction == "sum":
        return total
    count = clang.sum(clang.maybe_convert_to_dtype(valid, nll.dtype), None)
    return clang.true_divide(total, clang.maximum(count, 1.0))


@torchsymbol("torch.nn.functional.mse_loss")
def mse_loss(input, target, reduction: str = "mean"):
    d = clang.sub(input, target)
    sq = clang.mul(d, d)
    if reduction == "none":
        return sq
    if reduction == "sum":
        return clang.sum(sq, None)
    return clang.mean(sq, None)


# =============================================================================
# Attention
# =============================================================================


@torchsymbol("torch.nn.functional.scaled_dot_product_attention")
def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p: float = 0.0,
                                 is_causal: bool = False, scale: Optional[float] = None,
                                 enable_gqa: bool = False):
    """SDPA over (..., H, S, E) — decomposes to matmul/softmax/matmul; kept
    composite so the Pallas flash-attention executor claims it whole
    (reference: the cudnnex/sdpaex executor seats)."""
    check(dropout_p == 0.0, "sdpa dropout is not supported yet")
    E = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(E)

    if enable_gqa and key.shape[-3] != query.shape[-3]:
        rep = query.shape[-3] // key.shape[-3]
        key = repeat_interleave(key, rep, -3)
        value = repeat_interleave(value, rep, -3)

    # Attention scores in f32 for bf16 inputs: softmax accumulates in f32 on
    # the VPU; the two matmuls stay bf16 on the MXU.
    q = clang.mul(query, scale)
    scores = clang.matmul(q, clang.transpose(key, -2, -1))
    scores = clang.maybe_convert_to_dtype(scores, dtypes.float32)

    S, L = query.shape[-2], key.shape[-2]
    if is_causal:
        check(attn_mask is None, "is_causal and attn_mask are mutually exclusive")
        mask = clang.diagonal_mask(S, L, offset=L - S, upper=False, device=query.device)
        scores = clang.where(clang.expand_to(mask, scores.shape), scores, clang.full_like(scores, -float("inf")))
    elif attn_mask is not None:
        if dtypes.is_boolean_dtype(attn_mask.dtype):
            scores = clang.where(clang.expand_to(attn_mask, scores.shape), scores,
                                 clang.full_like(scores, -float("inf")))
        else:
            scores = clang.add(scores, clang.maybe_convert_to_dtype(attn_mask, dtypes.float32))

    probs = _safe_softmax(scores)
    probs = clang.maybe_convert_to_dtype(probs, value.dtype)
    return clang.matmul(probs, value)


def _safe_softmax(scores):
    """torch-sdpa semantics: a fully-masked row (all -inf) produces ZEROS,
    not NaN (torch's math backend safe-softmax) — without this, padding
    rows poison later layers through 0·NaN products."""
    row_max = clang.amax(scores, (-1,), True)
    probs = softmax(scores, -1)
    dead = clang.eq(row_max, -float("inf"))
    return clang.where(clang.expand_to(dead, probs.shape), clang.full_like(probs, 0.0), probs)


# =============================================================================
# Backward composites (claimable by fast executors; decompose for fallback)
# =============================================================================


@torchsymbol(id="torch.sdpa_bwd")
def sdpa_bwd(g, query, key, value, attn_mask=None, is_causal: bool = False,
             scale: Optional[float] = None, enable_gqa: bool = False):
    """(dq, dk, dv) of causal/masked/plain SDPA by recompute — the flash
    executor replaces this whole op with the Pallas flash-attention backward
    (reference analogue: cudnnex's sdpa backward graph, cudnnex.py:375,
    which likewise takes the attn-mask bias as an input)."""
    E = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(E)
    H = query.shape[-3]
    G = key.shape[-3]

    k, v = key, value
    if enable_gqa and G != H:
        rep = H // G
        k = repeat_interleave(k, rep, -3)
        v = repeat_interleave(v, rep, -3)

    qf = clang.maybe_convert_to_dtype(query, dtypes.float32)
    kf = clang.maybe_convert_to_dtype(k, dtypes.float32)
    vf = clang.maybe_convert_to_dtype(v, dtypes.float32)
    gf = clang.maybe_convert_to_dtype(g, dtypes.float32)

    s = clang.mul(clang.matmul(qf, clang.transpose(kf, -2, -1)), scale)
    S, L = query.shape[-2], key.shape[-2]
    if is_causal:
        cmask = clang.diagonal_mask(S, L, offset=L - S, upper=False, device=query.device)
        s = clang.where(clang.expand_to(cmask, s.shape), s, clang.full_like(s, -float("inf")))
    elif attn_mask is not None:
        if dtypes.is_boolean_dtype(attn_mask.dtype):
            s = clang.where(clang.expand_to(attn_mask, s.shape), s, clang.full_like(s, -float("inf")))
        else:
            s = clang.add(s, clang.maybe_convert_to_dtype(attn_mask, dtypes.float32))
    p = _safe_softmax(s)

    dv = clang.matmul(clang.transpose(p, -2, -1), gf)
    dp = clang.matmul(gf, clang.transpose(vf, -2, -1))
    ds = clang.mul(p, clang.sub(dp, clang.sum(clang.mul(dp, p), (-1,), True)))
    dq = clang.mul(clang.matmul(ds, kf), scale)
    dk = clang.mul(clang.matmul(clang.transpose(ds, -2, -1), qf), scale)

    if enable_gqa and G != H:
        rep = H // G
        bshape = tuple(dk.shape[:-3])
        dk = clang.sum(clang.reshape(dk, bshape + (G, rep) + tuple(dk.shape[-2:])), (len(bshape) + 1,))
        dv = clang.sum(clang.reshape(dv, bshape + (G, rep) + tuple(dv.shape[-2:])), (len(bshape) + 1,))

    dq = clang.maybe_convert_to_dtype(dq, query.dtype)
    dk = clang.maybe_convert_to_dtype(dk, key.dtype)
    dv = clang.maybe_convert_to_dtype(dv, value.dtype)
    return dq, dk, dv


@torchsymbol(id="torch.layer_norm_bwd")
def layer_norm_bwd(g, a, weight, bias, eps: float):
    """(dx, dw, db) of last-dim LayerNorm — composite for the fused-norm
    executor (reference seat: cudnn_layernormex.py:134)."""
    compute_dtype = dtypes.float32 if a.dtype in (dtypes.bfloat16, dtypes.float16) else a.dtype
    xf = clang.maybe_convert_to_dtype(a, compute_dtype)
    gf = clang.maybe_convert_to_dtype(g, compute_dtype)
    v, mu = clang.var_mean(xf, (-1,), correction=0, keepdim=True)
    rstd = clang.rsqrt(clang.add(v, eps))
    xhat = clang.mul(clang.sub(xf, mu), rstd)
    wg = gf if weight is None else clang.mul(gf, clang.maybe_convert_to_dtype(weight, compute_dtype))
    m1 = clang.mean(wg, (-1,), True)
    m2 = clang.mean(clang.mul(wg, xhat), (-1,), True)
    dx = clang.mul(rstd, clang.sub(clang.sub(wg, m1), clang.mul(xhat, m2)))
    dx = clang.maybe_convert_to_dtype(dx, a.dtype)
    red_dims = tuple(range(a.ndim - 1))
    dw = db = None
    if weight is not None:
        dw = clang.maybe_convert_to_dtype(
            clang.sum(clang.mul(gf, xhat), red_dims) if red_dims else clang.mul(gf, xhat),
            weight.dtype,
        )
    if bias is not None:
        db = clang.maybe_convert_to_dtype(
            clang.sum(gf, red_dims) if red_dims else gf, bias.dtype
        )
    return dx, dw, db


@torchsymbol(id="torch.rms_norm_bwd")
def rms_norm_bwd(g, a, weight, eps: float):
    """(dx, dw) of last-dim RMSNorm — kept composite so the Pallas fused
    norm kernel claims it whole (reference seat: the cudnn fused-norm
    executor, cudnn_layernormex.py:134)."""
    D = a.shape[-1]
    compute_dtype = dtypes.float32 if a.dtype in (dtypes.bfloat16, dtypes.float16) else a.dtype
    xf = clang.maybe_convert_to_dtype(a, compute_dtype)
    gf = clang.maybe_convert_to_dtype(g, compute_dtype)
    ms = clang.mean(clang.mul(xf, xf), (-1,), True)
    rstd = clang.rsqrt(clang.add(ms, eps))
    xhat = clang.mul(xf, rstd)
    wg = gf if weight is None else clang.mul(gf, clang.maybe_convert_to_dtype(weight, compute_dtype))
    dot = clang.mean(clang.mul(wg, xhat), (-1,), True)
    dx = clang.mul(rstd, clang.sub(wg, clang.mul(xhat, dot)))
    dx = clang.maybe_convert_to_dtype(dx, a.dtype)
    if weight is None:
        return dx, None
    red_dims = tuple(range(a.ndim - 1))
    dw = clang.sum(clang.mul(gf, xhat), red_dims) if red_dims else clang.mul(gf, xhat)
    dw = clang.maybe_convert_to_dtype(dw, weight.dtype)
    return dx, dw


@torchsymbol(id="torch.apply_rope")
def apply_rope(x, cos, sin):
    """Rotate-half rotary embedding over the last dim (HF NeoX/Llama
    convention; litgpt ``apply_rope``): x (..., T, hs), cos/sin (T, n) with
    n ≤ hs built as cat([freqs, freqs]) — features beyond n pass through.

    Kept composite so the Pallas rope kernel (pallasex) claims it whole:
    the decomposed rotate-half (two 50-lane slices + concat at hs=100) is
    badly lane-misaligned on the VPU — the r4 profile showed ~14 ms/iter of
    (.., 50)-shaped fusions on the 3B bench."""
    n = cos.shape[-1]
    half = n // 2
    rot = x[..., :n] if n != x.shape[-1] else x
    x1 = rot[..., :half]
    x2 = rot[..., half:]
    rotated = cat([-x2, x1], dim=-1)
    roped = rot * cos + rotated * sin
    if n == x.shape[-1]:
        return roped
    return cat([roped, x[..., n:]], dim=-1)


@torchsymbol(id="torch.sdpa_fwd_res")
def sdpa_fwd_res(query, key, value, attn_mask=None, is_causal: bool = False,
                 scale: Optional[float] = None, enable_gqa: bool = False):
    """SDPA returning ``(out, lse)`` where lse is the per-row logsumexp of
    the scaled (masked) scores, f32 of shape (..., H, Sq).

    This is the augmented forward the attention-residual pass
    (transforms/attention_residuals.py) swaps in so the flash backward can
    run from saved residuals instead of recomputing the forward kernel —
    the reference's cudnnex saves exactly this softmax_stats tensor between
    its fwd and bwd graphs (cudnnex.py:375)."""
    E = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(E)
    H = query.shape[-3]
    G = key.shape[-3]
    k, v = key, value
    if enable_gqa and G != H:
        rep = H // G
        k = repeat_interleave(k, rep, -3)
        v = repeat_interleave(v, rep, -3)

    s = clang.matmul(clang.mul(query, scale), clang.transpose(k, -2, -1))
    s = clang.maybe_convert_to_dtype(s, dtypes.float32)
    S, L = query.shape[-2], key.shape[-2]
    if is_causal:
        cmask = clang.diagonal_mask(S, L, offset=L - S, upper=False, device=query.device)
        s = clang.where(clang.expand_to(cmask, s.shape), s, clang.full_like(s, -float("inf")))
    elif attn_mask is not None:
        if dtypes.is_boolean_dtype(attn_mask.dtype):
            s = clang.where(clang.expand_to(attn_mask, s.shape), s, clang.full_like(s, -float("inf")))
        else:
            s = clang.add(s, clang.maybe_convert_to_dtype(attn_mask, dtypes.float32))
    m = clang.amax(s, (-1,), True)
    lse = clang.add(clang.log(clang.sum(clang.exp(clang.sub(s, m)), (-1,), True)), m)
    p = clang.exp(clang.sub(s, lse))
    dead = clang.eq(m, -float("inf"))
    p = clang.where(clang.expand_to(dead, p.shape), clang.full_like(p, 0.0), p)
    out = clang.matmul(clang.maybe_convert_to_dtype(p, value.dtype), v)
    return out, clang.squeeze(lse, (lse.ndim - 1,))


@torchsymbol(id="torch.sdpa_bwd_res")
def sdpa_bwd_res(g, query, key, value, out, lse, attn_mask=None, is_causal: bool = False,
                 scale: Optional[float] = None, enable_gqa: bool = False):
    """(dq, dk, dv) from saved residuals: probabilities are reconstructed as
    exp(s − lse) instead of a fresh softmax — one reduction cheaper, and the
    form the flash backward kernels consume (reference: cudnnex.py:375 feeds
    its bwd graph the saved softmax stats)."""
    E = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(E)
    H = query.shape[-3]
    G = key.shape[-3]
    k, v = key, value
    if enable_gqa and G != H:
        rep = H // G
        k = repeat_interleave(k, rep, -3)
        v = repeat_interleave(v, rep, -3)

    qf = clang.maybe_convert_to_dtype(query, dtypes.float32)
    kf = clang.maybe_convert_to_dtype(k, dtypes.float32)
    vf = clang.maybe_convert_to_dtype(v, dtypes.float32)
    gf = clang.maybe_convert_to_dtype(g, dtypes.float32)

    s = clang.mul(clang.matmul(qf, clang.transpose(kf, -2, -1)), scale)
    S, L = query.shape[-2], key.shape[-2]
    if is_causal:
        cmask = clang.diagonal_mask(S, L, offset=L - S, upper=False, device=query.device)
        s = clang.where(clang.expand_to(cmask, s.shape), s, clang.full_like(s, -float("inf")))
    elif attn_mask is not None:
        if dtypes.is_boolean_dtype(attn_mask.dtype):
            s = clang.where(clang.expand_to(attn_mask, s.shape), s, clang.full_like(s, -float("inf")))
        else:
            s = clang.add(s, clang.maybe_convert_to_dtype(attn_mask, dtypes.float32))
    lse_col = clang.unsqueeze(lse, lse.ndim)
    p = clang.exp(clang.sub(s, clang.maybe_convert_to_dtype(lse_col, dtypes.float32)))

    dv = clang.matmul(clang.transpose(p, -2, -1), gf)
    dp = clang.matmul(gf, clang.transpose(vf, -2, -1))
    # di = rowsum(dout * out) == rowsum(dp * p); the saved-out form avoids
    # materializing dp*p twice
    di = clang.sum(clang.mul(gf, clang.maybe_convert_to_dtype(out, dtypes.float32)), (-1,), True)
    ds = clang.mul(p, clang.sub(dp, di))
    dq = clang.mul(clang.matmul(ds, kf), scale)
    dk = clang.mul(clang.matmul(clang.transpose(ds, -2, -1), qf), scale)

    if enable_gqa and G != H:
        rep = H // G
        bshape = tuple(dk.shape[:-3])
        dk = clang.sum(clang.reshape(dk, bshape + (G, rep) + tuple(dk.shape[-2:])), (len(bshape) + 1,))
        dv = clang.sum(clang.reshape(dv, bshape + (G, rep) + tuple(dv.shape[-2:])), (len(bshape) + 1,))

    dq = clang.maybe_convert_to_dtype(dq, query.dtype)
    dk = clang.maybe_convert_to_dtype(dk, key.dtype)
    dv = clang.maybe_convert_to_dtype(dv, value.dtype)
    return dq, dk, dv


@torchsymbol(id="torch.cross_entropy_bwd")
def cross_entropy_bwd(g, input, target, ignore_index: int = -100, reduction: str = "mean"):
    """dlogits of fused cross-entropy: (softmax − onehot) · g/count. The
    Pallas executor replaces this whole op (reference analogue: the Triton
    CE backward kernels, triton_crossentropy.py:270,343)."""
    N, C = input.shape
    p = softmax(clang.maybe_convert_to_dtype(input, dtypes.float32), 1)
    cols = clang.expand_to(clang.arange(0, C, 1, device=input.device, dtype=dtypes.int64), (N, C))
    onehot = clang.maybe_convert_to_dtype(clang.eq(cols, clang.unsqueeze(clang.maximum(target, 0), 1)),
                                          dtypes.float32)
    valid = clang.ne(target, ignore_index)
    validf = clang.maybe_convert_to_dtype(valid, dtypes.float32)
    if reduction == "mean":
        count = clang.maximum(clang.sum(validf, None), 1.0)
        row_scale = clang.true_divide(clang.mul(g, validf), count)
    else:  # sum
        row_scale = clang.mul(g, validf)
    d = clang.mul(clang.sub(p, onehot), clang.unsqueeze(row_scale, 1))
    return clang.maybe_convert_to_dtype(d, input.dtype)


def _register_composite_vjps():
    from thunder_tpu.transforms.autodiff import register_vjp

    def _sdpa_args(args, kwargs):
        names = ("query", "key", "value", "attn_mask", "dropout_p", "is_causal", "scale", "enable_gqa")
        defaults = {"attn_mask": None, "dropout_p": 0.0, "is_causal": False, "scale": None, "enable_gqa": False}
        bound = dict(zip(names, args))
        bound.update(kwargs)
        for k, dflt in defaults.items():
            bound.setdefault(k, dflt)
        return bound

    def _sdpa_checker(*args, **kwargs):
        b = _sdpa_args(args, kwargs)
        m = b["attn_mask"]
        # Masked SDPA keeps the composite backward (no mask cotangent is
        # produced) unless the mask itself requires grad.
        mask_ok = m is None or not getattr(m, "requires_grad", False)
        return mask_ok and float(pyval(b["dropout_p"])) == 0.0

    @register_vjp("torch.scaled_dot_product_attention", checker=_sdpa_checker)
    def _sdpa_vjp(bsym, g):
        from thunder_tpu.transforms.autodiff import grads_by_name

        b = _sdpa_args(bsym.args, bsym.kwargs)
        dq, dk, dv = sdpa_bwd(g, b["query"], b["key"], b["value"], b["attn_mask"],
                              b["is_causal"], b["scale"], b["enable_gqa"])
        names = ("query", "key", "value", "attn_mask", "dropout_p", "is_causal",
                 "scale", "enable_gqa")
        return grads_by_name(bsym, names, {"query": dq, "key": dk, "value": dv})

    def _ce_checker(input, target, weight=None, ignore_index=-100, reduction="mean", label_smoothing=0.0):
        return weight is None and float(pyval(label_smoothing)) == 0.0 and reduction in ("mean", "sum")

    @register_vjp("torch.cross_entropy", checker=_ce_checker)
    def _ce_vjp(bsym, g):
        bound = dict(zip(("input", "target", "weight", "ignore_index", "reduction"), bsym.args))
        bound.update(bsym.kwargs)
        d = cross_entropy_bwd(
            g, bound["input"], bound["target"],
            bound.get("ignore_index", -100), bound.get("reduction", "mean"),
        )
        return (d,) + (None,) * (len(bsym.args) - 1)

    def _rms_checker(a, normalized_shape, weight=None, eps=None):
        return len(tuple(normalized_shape)) == 1  # last-dim norm only

    def _ln_checker(a, normalized_shape, weight=None, bias=None, eps=1e-5):
        return len(tuple(normalized_shape)) == 1

    @register_vjp("torch.layer_norm", checker=_ln_checker)
    def _layer_norm_vjp(bsym, g):
        from thunder_tpu.transforms.autodiff import grads_by_name

        names = ("a", "normalized_shape", "weight", "bias", "eps")
        bound = dict(zip(names, bsym.args))
        bound.update(bsym.kwargs)
        eps = bound.get("eps", 1e-5)
        dx, dw, db = layer_norm_bwd(g, bound["a"], bound.get("weight"), bound.get("bias"),
                                    float(pyval(eps)))
        grad_map = {"a": dx}
        if bound.get("weight") is not None:
            grad_map["weight"] = dw
        if bound.get("bias") is not None:
            grad_map["bias"] = db
        return grads_by_name(bsym, names, grad_map)

    @register_vjp("torch.rms_norm", checker=_rms_checker)
    def _rms_norm_vjp(bsym, g):
        from thunder_tpu.transforms.autodiff import grads_by_name

        names = ("a", "normalized_shape", "weight", "eps")
        bound = dict(zip(names, bsym.args))
        bound.update(bsym.kwargs)
        eps = bound.get("eps")
        dx, dw = rms_norm_bwd(g, bound["a"], bound.get("weight"),
                              1e-6 if eps is None else float(pyval(eps)))
        grad_map = {"a": dx}
        if bound.get("weight") is not None:
            grad_map["weight"] = dw
        return grads_by_name(bsym, names, grad_map)

    @register_vjp("torch.apply_rope")
    def _rope_vjp(bsym, g):
        # y = x*cos + rot(x)*sin with rot adjoint = -rot and both cos/sin
        # halves equal ⇒ dx = apply_rope(g, cos, -sin): the backward is the
        # SAME composite (and the same Pallas kernel claims it).
        x, cos, sin = bsym.args
        return (apply_rope(g, cos, clang.neg(sin)), None, None)


_register_composite_vjps()


# =============================================================================
# Additional binary / ternary ops
# =============================================================================


@torchsymbol("torch.logaddexp")
def logaddexp(a, b):
    m = clang.maximum(a, b)
    d = clang.neg(clang.abs(clang.sub(a, b)))
    r = clang.add(m, clang.log1p(clang.exp(d)))
    # When both are -inf the max is -inf and the sum is -inf, not nan.
    return clang.where(clang.isinf(m), m, r)


@torchsymbol("torch.logaddexp2")
def logaddexp2(a, b):
    ln2 = math.log(2.0)
    return clang.mul(logaddexp(clang.mul(a, ln2), clang.mul(b, ln2)), 1.0 / ln2)


@torchsymbol("torch.hypot")
def hypot(a, b):
    return clang.sqrt(clang.add(clang.mul(a, a), clang.mul(b, b)))


@torchsymbol("torch.logical_and", method_name="logical_and")
def logical_and(a, b):
    return clang.logical_and(a, b)


@torchsymbol("torch.logical_or", method_name="logical_or")
def logical_or(a, b):
    return clang.logical_or(a, b)


@torchsymbol("torch.logical_xor", method_name="logical_xor")
def logical_xor(a, b):
    ba = clang.ne(a, 0) if not dtypes.is_boolean_dtype(a.dtype) else a
    bb = clang.ne(b, 0) if not dtypes.is_boolean_dtype(b.dtype) else b
    return clang.ne(ba, bb)


@torchsymbol("torch.xlogy", "torch.special.xlogy")
def xlogy(a, b):
    safe = clang.where(clang.eq(a, 0), clang.ones_like(b), b)
    return clang.where(clang.eq(a, 0), clang.zeros_like(clang.mul(a, b)), clang.mul(a, clang.log(safe)))


@torchsymbol("torch.addcmul", method_name="addcmul")
def addcmul(a, t1, t2, *, value=1):
    prod_ = clang.mul(t1, t2)
    if pyval(value) != 1:
        prod_ = clang.mul(prod_, value)
    return clang.add(a, prod_)


@torchsymbol("torch.addcdiv", method_name="addcdiv")
def addcdiv(a, t1, t2, *, value=1):
    q = clang.true_divide(t1, t2)
    if pyval(value) != 1:
        q = clang.mul(q, value)
    return clang.add(a, q)


@torchsymbol("torch.lerp", method_name="lerp")
def lerp(start, end, weight):
    return clang.add(start, clang.mul(clang.sub(end, start), weight))


@torchsymbol("torch.isclose", method_name="isclose")
def isclose(a, b, rtol: float = 1e-5, atol: float = 1e-8, equal_nan: bool = False):
    close = clang.le(clang.abs(clang.sub(a, b)), clang.add(atol, clang.mul(rtol, clang.abs(b))))
    if equal_nan:
        close = clang.logical_or(close, clang.logical_and(clang.isnan(a), clang.isnan(b)))
    return close


@torchsymbol("torch.heaviside")
def heaviside(a, values):
    zero = clang.zeros_like(a)
    one = clang.ones_like(a)
    return clang.where(clang.gt(a, 0), one, clang.where(clang.lt(a, 0), zero, values))


# =============================================================================
# Additional shape / indexing ops
# =============================================================================


@torchsymbol("torch.narrow", method_name="narrow")
def narrow(a, dim: int, start: int, length: int):
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    s = int(pyval(start))
    if s < 0:
        s += a.shape[d]
    return clang.slice_in_dim(a, s, s + int(pyval(length)), dim=d)


@torchsymbol("torch.select", method_name="select")
def select(a, dim: int, index: int):
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    i = int(pyval(index))
    if i < 0:
        i += a.shape[d]
    return clang.squeeze(clang.slice_in_dim(a, i, i + 1, dim=d), (d,))


@torchsymbol("torch.unbind", method_name="unbind")
def unbind(a, dim: int = 0):
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    return tuple(select(a, d, i) for i in range(a.shape[d]))


@torchsymbol("torch.roll", method_name="roll")
def roll(a, shifts, dims=None):
    shifts = (int(pyval(shifts)),) if isinstance(shifts, (int, NumberProxy)) else tuple(int(pyval(s)) for s in shifts)
    if dims is None:
        check(len(shifts) == 1, "roll without dims takes a single shift")
        flat = flatten(a)
        return reshape(roll(flat, shifts, (0,)), tuple(a.shape))
    dims = (int(pyval(dims)),) if isinstance(dims, (int, NumberProxy)) else tuple(int(pyval(d)) for d in dims)
    check(len(shifts) == len(dims), "roll shifts/dims length mismatch")
    r = a
    for s, d in zip(shifts, dims):
        d = canonicalize_dim(r.ndim, d)
        n = r.shape[d]
        if n == 0:
            continue
        s = s % n
        if s == 0:
            continue
        head = clang.slice_in_dim(r, n - s, n, dim=d)
        tail = clang.slice_in_dim(r, 0, n - s, dim=d)
        r = clang.cat([head, tail], d)
    return r


@torchsymbol("torch.broadcast_to", method_name="broadcast_to")
def broadcast_to(a, shape):
    return clang.expand(a, tuple(int(pyval(s)) for s in shape))


@torchsymbol("torch.tile", method_name="tile")
def tile(a, *reps):
    reps = reps[0] if len(reps) == 1 and isinstance(reps[0], (tuple, list)) else reps
    reps = tuple(int(pyval(r)) for r in reps)
    if len(reps) < a.ndim:
        reps = (1,) * (a.ndim - len(reps)) + reps
    return repeat(a, *reps)


@torchsymbol("torch.swapaxes", "torch.swapdims", method_name="swapaxes")
def swapaxes(a, dim0: int, dim1: int):
    return clang.transpose(a, int(pyval(dim0)), int(pyval(dim1)))


@torchsymbol("torch.ravel", method_name="ravel")
def ravel(a):
    return flatten(a)


@torchsymbol("torch.unflatten", method_name="unflatten")
def unflatten(a, dim: int, sizes):
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    sizes = [int(pyval(s)) for s in sizes]
    if -1 in sizes:
        idx = sizes.index(-1)
        known = 1
        for i, s in enumerate(sizes):
            if i != idx:
                known *= s
        sizes[idx] = a.shape[d] // known
    return clang.reshape(a, tuple(a.shape[:d]) + tuple(sizes) + tuple(a.shape[d + 1 :]))


@torchsymbol("torch.Tensor.unfold", method_name="unfold")
def unfold(a, dimension: int, size: int, step: int):
    """Sliding windows along ``dimension``: dim is replaced by the window
    count and a trailing dim of ``size`` is appended (torch.Tensor.unfold)."""
    d = canonicalize_dim(a.ndim, int(pyval(dimension)))
    size, step = int(pyval(size)), int(pyval(step))
    L = a.shape[d]
    check(size <= L, lambda: f"unfold size {size} > dim size {L}")
    n = (L - size) // step + 1
    starts = clang.mul(clang.arange(0, n, 1, device=a.device, dtype=dtypes.int64), step)
    offs = clang.arange(0, size, 1, device=a.device, dtype=dtypes.int64)
    idx = clang.add(clang.unsqueeze(starts, 1), clang.unsqueeze(offs, 0))  # (n, size)
    moved = clang.movedim(a, d, -1)
    flat_idx = clang.reshape(idx, (n * size,))
    taken = prims.take(moved, flat_idx, moved.ndim - 1)
    win = clang.reshape(taken, tuple(moved.shape[:-1]) + (n, size))
    return clang.movedim(win, -2, d)


@torchsymbol("torch.diag")
def diag(a, diagonal: int = 0):
    k = int(pyval(diagonal))
    if a.ndim == 1:
        n = a.shape[0] + builtins_abs(k)
        rows = clang.arange(0, n, 1, device=a.device, dtype=dtypes.int64)
        cols = clang.arange(0, n, 1, device=a.device, dtype=dtypes.int64)
        eye_mask = clang.eq(clang.sub(clang.unsqueeze(cols, 0), clang.unsqueeze(rows, 1)), k)
        padded = a
        if k > 0:
            padded = prims.pad(a, 0, ((k, 0, 0),))
        elif k < 0:
            padded = prims.pad(a, 0, ((0, -k, 0),))
        return clang.where(eye_mask, clang.expand_to(clang.unsqueeze(padded, 0), (n, n)), 0)
    check(a.ndim == 2, "diag expects a 1D or 2D tensor")
    return diagonal_sym(a, k, 0, 1)


@torchsymbol("torch.diagonal", method_name="diagonal", id="torch.diagonal")
def diagonal_sym(a, offset: int = 0, dim1: int = 0, dim2: int = 1):
    return clang.diagonal(a, offset, dim1, dim2)


@torchsymbol("torch.index_add", method_name="index_add")
def index_add(a, dim: int, index, source, *, alpha=1):
    return clang.index_add(a, dim, index, source, alpha)


@torchsymbol("torch.index_copy", method_name="index_copy")
def index_copy(a, dim: int, index, source):
    return clang.index_copy(a, dim, index, source)


@torchsymbol("torch.hstack")
def hstack(tensors):
    tensors = list(tensors)
    return cat(tensors, 0 if tensors[0].ndim == 1 else 1)


@torchsymbol("torch.vstack", "torch.row_stack")
def vstack(tensors):
    tensors = [reshape(t, (1,) + tuple(t.shape)) if t.ndim == 1 else t for t in tensors]
    return cat(tensors, 0)


# =============================================================================
# Additional reductions
# =============================================================================


@torchsymbol("torch.logsumexp", method_name="logsumexp")
def logsumexp(a, dim, keepdim: bool = False):
    dims = _dim_seq(dim)
    m = clang.amax(a, dims, True)
    m = clang.where(clang.isfinite(m), m, clang.zeros_like(m))
    r = clang.add(clang.log(clang.sum(clang.exp(clang.sub(a, m)), dims, True)), m)
    if not keepdim:
        canon = tuple(canonicalize_dim(a.ndim, d) for d in dims)
        r = clang.squeeze(r, canon)
    return r


@torchsymbol("torch.cumprod", method_name="cumprod")
def cumprod(a, dim: int, *, dtype=None):
    r = prims.cumprod(a, canonicalize_dim(a.ndim, int(pyval(dim))))
    if dtype is not None:
        r = clang.maybe_convert_to_dtype(r, to_dtype(dtype))
    return r


@torchsymbol("torch.count_nonzero", method_name="count_nonzero")
def count_nonzero(a, dim=None):
    return clang.sum(clang.maybe_convert_to_dtype(clang.ne(a, 0), dtypes.int64), _dim_seq(dim))


@torchsymbol("torch.norm", "torch.linalg.vector_norm", method_name="norm")
def norm(a, p=2, dim=None, keepdim: bool = False, *, dtype=None):
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, to_dtype(dtype))
    dims = _dim_seq(dim)
    if isinstance(p, str):
        check(p == "fro", lambda: f"Unsupported norm order {p}")
        p = 2
    p = pyval(p)
    if p == float("inf"):
        return clang.amax(clang.abs(a), dims, keepdim)
    if p == float("-inf"):
        return clang.amin(clang.abs(a), dims, keepdim)
    if p == 0:
        return clang.sum(clang.maybe_convert_to_dtype(clang.ne(a, 0), a.dtype), dims, keepdim)
    if p == 1:
        return clang.sum(clang.abs(a), dims, keepdim)
    if p == 2:
        return clang.sqrt(clang.sum(clang.mul(a, a), dims, keepdim))
    return clang.pow(clang.sum(clang.pow(clang.abs(a), p), dims, keepdim), 1.0 / p)


@torchsymbol("torch.std_mean")
def std_mean(a, dim=None, *, correction: Number = 1, keepdim: bool = False):
    v, m = clang.var_mean(a, _dim_seq(dim), correction=correction, keepdim=keepdim)
    return clang.sqrt(v), m


# =============================================================================
# Additional matmul family
# =============================================================================


@torchsymbol("torch.mm", method_name="mm")
def mm(a, b):
    check(a.ndim == 2 and b.ndim == 2, "mm requires rank-2 tensors")
    return clang.matmul(a, b)


@torchsymbol("torch.mv", method_name="mv")
def mv(a, b):
    check(a.ndim == 2 and b.ndim == 1, "mv requires a matrix and a vector")
    return clang.matmul(a, b)


@torchsymbol("torch.dot", method_name="dot")
def dot(a, b):
    check(a.ndim == 1 and b.ndim == 1, "dot requires rank-1 tensors")
    return clang.matmul(a, b)


@torchsymbol("torch.vdot", method_name="vdot")
def vdot(a, b):
    check(a.ndim == 1 and b.ndim == 1, "vdot requires rank-1 tensors")
    return clang.matmul(a, b)  # real dtypes only; conj is identity


@torchsymbol("torch.addmm", method_name="addmm")
def addmm(a, m1, m2, *, beta=1, alpha=1):
    r = clang.matmul(m1, m2)
    if pyval(alpha) != 1:
        r = clang.mul(r, alpha)
    if pyval(beta) == 0:
        return r
    return clang.add(r, a if pyval(beta) == 1 else clang.mul(a, beta))


@torchsymbol("torch.baddbmm", method_name="baddbmm")
def baddbmm(a, b1, b2, *, beta=1, alpha=1):
    check(b1.ndim == 3 and b2.ndim == 3, "baddbmm requires rank-3 batches")
    r = clang.matmul(b1, b2)
    if pyval(alpha) != 1:
        r = clang.mul(r, alpha)
    if pyval(beta) == 0:
        return r
    return clang.add(r, a if pyval(beta) == 1 else clang.mul(a, beta))


@torchsymbol("torch.addbmm", method_name="addbmm")
def addbmm(a, b1, b2, *, beta=1, alpha=1):
    r = clang.sum(clang.matmul(b1, b2), (0,))
    if pyval(alpha) != 1:
        r = clang.mul(r, alpha)
    if pyval(beta) == 0:
        return r
    return clang.add(r, a if pyval(beta) == 1 else clang.mul(a, beta))


# =============================================================================
# Additional creation ops
# =============================================================================


@torchsymbol("torch.empty_like")
def empty_like(a, *, dtype=None, device=None, requires_grad: bool = False):
    return clang.zeros_like(a, device=device, dtype=to_dtype(dtype))


@torchsymbol("torch.rand_like")
def rand_like(a, *, dtype=None, device=None, requires_grad: bool = False):
    dt = to_dtype(dtype) or a.dtype
    return clang.uniform(tuple(a.shape), 0.0, 1.0, device=device or a.device, dtype=dt)


@torchsymbol("torch.randn_like")
def randn_like(a, *, dtype=None, device=None, requires_grad: bool = False):
    dt = to_dtype(dtype) or a.dtype
    return clang.randn(tuple(a.shape), device=device or a.device, dtype=dt)


@torchsymbol("torch.randint")
def randint(low, high=None, size=None, *, dtype=None, device=None, requires_grad: bool = False, generator=None):
    if high is None:  # randint(high, size)
        low, high = 0, low
    check(size is not None, "randint requires a size")
    lo, hi = int(pyval(low)), int(pyval(high))
    u = clang.uniform(tuple(size), float(lo), float(hi), device=device, dtype=dtypes.float32)
    return clang.maybe_convert_to_dtype(clang.floor(u), to_dtype(dtype) or dtypes.int64)


@torchsymbol("torch.bernoulli")
def bernoulli(a, *, generator=None):
    u = clang.uniform(tuple(a.shape), 0.0, 1.0, device=a.device, dtype=a.dtype)
    return clang.maybe_convert_to_dtype(clang.lt(u, a), a.dtype)


@torchsymbol("torch.eye")
def eye(n: int, m: Optional[int] = None, *, dtype=None, device=None, requires_grad: bool = False):
    n = int(pyval(n))
    m = n if m is None else int(pyval(m))
    rows = clang.arange(0, n, 1, device=device, dtype=dtypes.int64)
    cols = clang.arange(0, m, 1, device=device, dtype=dtypes.int64)
    mask = clang.eq(clang.unsqueeze(rows, 1), clang.unsqueeze(cols, 0))
    return clang.maybe_convert_to_dtype(mask, to_dtype(dtype) or dtypes.float32)


@torchsymbol("torch.linspace")
def linspace(start, end, steps: int, *, dtype=None, device=None, requires_grad: bool = False):
    steps = int(pyval(steps))
    dt = to_dtype(dtype) or dtypes.float32
    if steps == 1:
        return clang.full((1,), start, device=device, dtype=dt)
    i = clang.arange(0, steps, 1, device=device, dtype=dtypes.float32)
    v = clang.add(clang.mul(i, (pyval(end) - pyval(start)) / (steps - 1)), pyval(start))
    return clang.maybe_convert_to_dtype(v, dt)


# =============================================================================
# Pooling (XLA reduce_window via the pool prim; the prim seat matches the
# reference's torch max/avg_poolNd ATen calls, thunder/torch/__init__.py)
# =============================================================================


def _pool_nd(a, kind: str, kernel, stride, padding, spatial: int, ceil_mode: bool, dilation=1):
    def _seq(x):
        return (int(pyval(x)),) * spatial if isinstance(x, (int, NumberProxy)) else tuple(int(pyval(v)) for v in x)

    check(not ceil_mode, "pool ceil_mode is not supported yet")
    d = _seq(dilation)
    check(builtins_max(d) == 1, "pool dilation is not supported yet")
    k = _seq(kernel)
    s = _seq(stride) if stride is not None else k
    p = _seq(padding)
    for pi, ki in zip(p, k):
        check(pi <= ki // 2, "pool padding must be <= half the kernel size")
    check(a.ndim in (spatial + 1, spatial + 2), lambda: f"pool expects rank {spatial + 1} or {spatial + 2}")
    pad_cfg = tuple((pi, pi) for pi in p)
    return prims.pool(a, kind, k, s, pad_cfg)


@torchsymbol("torch.nn.functional.max_pool1d")
def max_pool1d(a, kernel_size, stride=None, padding=0, dilation=1, ceil_mode: bool = False,
               return_indices: bool = False):
    check(not return_indices, "max_pool return_indices is not supported yet")
    return _pool_nd(a, "max", kernel_size, stride, padding, 1, ceil_mode, dilation)


@torchsymbol("torch.nn.functional.max_pool2d")
def max_pool2d(a, kernel_size, stride=None, padding=0, dilation=1, ceil_mode: bool = False,
               return_indices: bool = False):
    check(not return_indices, "max_pool return_indices is not supported yet")
    return _pool_nd(a, "max", kernel_size, stride, padding, 2, ceil_mode, dilation)


@torchsymbol("torch.nn.functional.max_pool3d")
def max_pool3d(a, kernel_size, stride=None, padding=0, dilation=1, ceil_mode: bool = False,
               return_indices: bool = False):
    check(not return_indices, "max_pool return_indices is not supported yet")
    return _pool_nd(a, "max", kernel_size, stride, padding, 3, ceil_mode, dilation)


@torchsymbol("torch.nn.functional.avg_pool1d")
def avg_pool1d(a, kernel_size, stride=None, padding=0, ceil_mode: bool = False,
               count_include_pad: bool = True):
    check(count_include_pad, "avg_pool count_include_pad=False is not supported yet")
    return _pool_nd(a, "avg", kernel_size, stride, padding, 1, ceil_mode)


@torchsymbol("torch.nn.functional.avg_pool2d")
def avg_pool2d(a, kernel_size, stride=None, padding=0, ceil_mode: bool = False,
               count_include_pad: bool = True, divisor_override=None):
    check(count_include_pad, "avg_pool count_include_pad=False is not supported yet")
    check(divisor_override is None, "avg_pool divisor_override is not supported yet")
    return _pool_nd(a, "avg", kernel_size, stride, padding, 2, ceil_mode)


@torchsymbol("torch.nn.functional.avg_pool3d")
def avg_pool3d(a, kernel_size, stride=None, padding=0, ceil_mode: bool = False,
               count_include_pad: bool = True, divisor_override=None):
    check(count_include_pad, "avg_pool count_include_pad=False is not supported yet")
    check(divisor_override is None, "avg_pool divisor_override is not supported yet")
    return _pool_nd(a, "avg", kernel_size, stride, padding, 3, ceil_mode)


def _adaptive_avg_pool(a, output_size, spatial: int):
    out = (int(pyval(output_size)),) * spatial if isinstance(output_size, (int, NumberProxy)) else tuple(
        int(pyval(v)) for v in output_size
    )
    in_sizes = tuple(a.shape[-spatial:])
    for i, (s, o) in enumerate(zip(in_sizes, out)):
        check(s % o == 0, lambda: f"adaptive pool requires divisible sizes, got {s}->{o}")
    # Reshape each spatial dim (s,) -> (o, s//o) and mean the inner factor.
    lead = tuple(a.shape[: a.ndim - spatial])
    new_shape = lead + builtins_sum(((o, s // o) for s, o in zip(in_sizes, out)), ())
    r = clang.reshape(a, new_shape)
    red_dims = tuple(len(lead) + 2 * i + 1 for i in range(spatial))
    return clang.mean(r, red_dims)


@torchsymbol("torch.nn.functional.adaptive_avg_pool1d")
def adaptive_avg_pool1d(a, output_size):
    return _adaptive_avg_pool(a, output_size, 1)


@torchsymbol("torch.nn.functional.adaptive_avg_pool2d")
def adaptive_avg_pool2d(a, output_size):
    return _adaptive_avg_pool(a, output_size, 2)


@torchsymbol("torch.nn.functional.adaptive_avg_pool3d")
def adaptive_avg_pool3d(a, output_size):
    return _adaptive_avg_pool(a, output_size, 3)


# =============================================================================
# Padding
# =============================================================================


@torchsymbol("torch.nn.functional.pad")
def pad(a, pad, mode: str = "constant", value=None):
    """F.pad: ``pad`` pairs run last-dim-first. constant lowers to the pad
    prim (XLA pad, negative = crop); reflect/replicate/circular decompose to
    slice+flip+cat per dim."""
    pad = tuple(int(pyval(p)) for p in pad)
    check(len(pad) % 2 == 0, "pad takes (lo, hi) pairs")
    npairs = len(pad) // 2
    check(npairs <= a.ndim, "more pad pairs than dims")
    if mode == "constant":
        cfg = []
        pairs = list(zip(pad[0::2], pad[1::2]))  # last dim first
        for i in range(a.ndim):
            j = a.ndim - 1 - i
            if j < npairs:
                lo, hi = pairs[j]
                cfg.append((lo, hi, 0))
            else:
                cfg.append((0, 0, 0))
        return prims.pad(a, 0 if value is None else value, tuple(cfg))

    check(mode in ("reflect", "replicate", "circular"), lambda: f"Unknown pad mode {mode}")
    r = a
    for j in range(npairs):
        lo, hi = pad[2 * j], pad[2 * j + 1]
        if lo == 0 and hi == 0:
            continue
        d = r.ndim - 1 - j
        n = r.shape[d]
        check(lo >= 0 and hi >= 0, "negative padding only supported in constant mode")
        pieces = []
        if mode == "circular":
            check(lo <= n and hi <= n, "circular pad wider than dim")
            if lo:
                pieces.append(clang.slice_in_dim(r, n - lo, n, dim=d))
            pieces.append(r)
            if hi:
                pieces.append(clang.slice_in_dim(r, 0, hi, dim=d))
        elif mode == "replicate":
            if lo:
                edge = clang.slice_in_dim(r, 0, 1, dim=d)
                shape = list(edge.shape)
                shape[d] = lo
                pieces.append(clang.expand(edge, tuple(shape)))
            pieces.append(r)
            if hi:
                edge = clang.slice_in_dim(r, n - 1, n, dim=d)
                shape = list(edge.shape)
                shape[d] = hi
                pieces.append(clang.expand(edge, tuple(shape)))
        else:  # reflect
            check(lo < n and hi < n, "reflect pad must be < dim size")
            if lo:
                pieces.append(clang.flip(clang.slice_in_dim(r, 1, lo + 1, dim=d), (d,)))
            pieces.append(r)
            if hi:
                pieces.append(clang.flip(clang.slice_in_dim(r, n - 1 - hi, n - 1, dim=d), (d,)))
        r = clang.cat(pieces, d) if len(pieces) > 1 else pieces[0]
    return r


# =============================================================================
# One-hot / normalization / interpolation
# =============================================================================


@torchsymbol("torch.nn.functional.one_hot")
def one_hot(a, num_classes: int = -1):
    check(int(pyval(num_classes)) > 0, "one_hot requires an explicit num_classes under tracing")
    C = int(pyval(num_classes))
    cols = clang.arange(0, C, 1, device=a.device, dtype=dtypes.int64)
    shape_ones = (1,) * a.ndim
    cols = clang.reshape(cols, shape_ones + (C,))
    return clang.maybe_convert_to_dtype(
        clang.eq(clang.unsqueeze(a, a.ndim), cols), dtypes.int64
    )


@torchsymbol("torch.nn.functional.normalize")
def normalize(a, p: float = 2.0, dim: int = 1, eps: float = 1e-12):
    n = norm(a, p, dim, True)
    return clang.true_divide(a, clang.clamp(n, eps, None))


@torchsymbol(id="torch.batch_norm_stats")
def _batch_norm_stats(input, running_mean=None, running_var=None, weight=None, bias=None,
                      training: bool = False, momentum: float = 0.1, eps: float = 1e-5):
    """Functional batch_norm returning (out, new_running_mean, new_running_var)
    — the user-facing wrapper (``batch_norm``) forwards the running-stat
    proxies so buffer mutation functionalizes (reference: F.batch_norm's
    in-place running-stat update + epilogue replay, jit_ext.py:1302)."""
    check(input.ndim >= 2, "batch_norm expects (N, C, ...)")
    C = input.shape[1]
    red = (0,) + tuple(range(2, input.ndim))
    stat_shape = (1, C) + (1,) * (input.ndim - 2)
    compute_dtype = dtypes.float32 if input.dtype in (dtypes.bfloat16, dtypes.float16) else input.dtype
    x = clang.maybe_convert_to_dtype(input, compute_dtype)

    use_batch_stats = training or running_mean is None
    if use_batch_stats:
        var_b, mean = clang.var_mean(x, red, correction=0, keepdim=False)
        new_mean, new_var = None, None
        if training and running_mean is not None:
            m = float(pyval(momentum))
            n_elem = 1
            for d in red:
                n_elem *= input.shape[d]
            var_unbiased = clang.mul(var_b, n_elem / builtins_max(n_elem - 1, 1))
            new_mean = clang.add(clang.mul(clang.maybe_convert_to_dtype(mean, running_mean.dtype), m),
                                 clang.mul(running_mean, 1.0 - m))
            new_var = clang.add(clang.mul(clang.maybe_convert_to_dtype(var_unbiased, running_var.dtype), m),
                                clang.mul(running_var, 1.0 - m))
        use_mean, use_var = mean, var_b
    else:
        use_mean = clang.maybe_convert_to_dtype(running_mean, compute_dtype)
        use_var = clang.maybe_convert_to_dtype(running_var, compute_dtype)
        new_mean, new_var = None, None

    normed = clang.mul(
        clang.sub(x, clang.reshape(use_mean, stat_shape)),
        clang.rsqrt(clang.add(clang.reshape(use_var, stat_shape), eps)),
    )
    normed = clang.maybe_convert_to_dtype(normed, input.dtype)
    if weight is not None:
        normed = clang.mul(normed, clang.reshape(weight, stat_shape))
    if bias is not None:
        normed = clang.add(normed, clang.reshape(bias, stat_shape))
    return normed, new_mean, new_var


def batch_norm(input, running_mean=None, running_var=None, weight=None, bias=None,
               training: bool = False, momentum: float = 0.1, eps: float = 1e-5):
    out, new_mean, new_var = _batch_norm_stats(
        input, running_mean, running_var, weight, bias, training, momentum, eps
    )
    if new_mean is not None and isinstance(running_mean, TensorProxy):
        _mark_inplace(running_mean, new_mean)
    if new_var is not None and isinstance(running_var, TensorProxy):
        _mark_inplace(running_var, new_var)
    return out


for _path in ("torch.nn.functional.batch_norm", "torch.batch_norm"):
    _obj = _resolve_torch_attr(_path)
    if _obj is not None:
        _torch_to_thunder_function_map[_obj] = batch_norm


@torchsymbol("torch.nn.functional.instance_norm")
def instance_norm(input, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats: bool = True, momentum: float = 0.1, eps: float = 1e-5):
    check(running_mean is None and running_var is None,
          "instance_norm running stats are not supported yet")
    check(use_input_stats, "instance_norm requires use_input_stats without running stats")
    check(input.ndim >= 3, "instance_norm expects (N, C, ...)")
    red = tuple(range(2, input.ndim))
    compute_dtype = dtypes.float32 if input.dtype in (dtypes.bfloat16, dtypes.float16) else input.dtype
    x = clang.maybe_convert_to_dtype(input, compute_dtype)
    v, m = clang.var_mean(x, red, correction=0, keepdim=True)
    normed = clang.maybe_convert_to_dtype(
        clang.mul(clang.sub(x, m), clang.rsqrt(clang.add(v, eps))), input.dtype
    )
    C = input.shape[1]
    stat_shape = (1, C) + (1,) * (input.ndim - 2)
    if weight is not None:
        normed = clang.mul(normed, clang.reshape(weight, stat_shape))
    if bias is not None:
        normed = clang.add(normed, clang.reshape(bias, stat_shape))
    return normed


def _resize_dim(x, d: int, out_size: int, mode: str, align_corners: bool):
    L = x.shape[d]
    if out_size == L:
        return x
    if mode == "nearest":
        i = clang.arange(0, out_size, 1, device=x.device, dtype=dtypes.float32)
        idx = clang.maybe_convert_to_dtype(clang.floor(clang.mul(i, L / out_size)), dtypes.int64)
        return prims.take(x, idx, d)
    # linear
    i = clang.arange(0, out_size, 1, device=x.device, dtype=dtypes.float32)
    if align_corners and out_size > 1:
        src = clang.mul(i, (L - 1) / (out_size - 1))
    else:
        src = clang.clamp(clang.sub(clang.mul(clang.add(i, 0.5), L / out_size), 0.5), 0.0, float(L - 1))
    i0f = clang.floor(src)
    w = clang.sub(src, i0f)
    i0 = clang.maybe_convert_to_dtype(i0f, dtypes.int64)
    i1 = clang.clamp(clang.add(i0, 1), 0, L - 1)
    x0 = prims.take(x, i0, d)
    x1 = prims.take(x, i1, d)
    wshape = [1] * x.ndim
    wshape[d] = out_size
    w = clang.reshape(w, tuple(wshape))
    w = clang.maybe_convert_to_dtype(w, x0.dtype) if dtypes.is_float_dtype(x0.dtype) else w
    return clang.add(x0, clang.mul(clang.sub(x1, x0), w))


@torchsymbol("torch.nn.functional.interpolate")
def interpolate(a, size=None, scale_factor=None, mode: str = "nearest",
                align_corners: Optional[bool] = None, recompute_scale_factor=None,
                antialias: bool = False):
    check(not antialias, "interpolate antialias is not supported yet")
    spatial = a.ndim - 2
    check(spatial >= 1, "interpolate expects (N, C, ...) input")
    check(mode in ("nearest", "linear", "bilinear", "trilinear"),
          lambda: f"interpolate mode {mode} is not supported yet")
    if size is not None:
        out = (int(pyval(size)),) * spatial if isinstance(size, (int, NumberProxy)) else tuple(
            int(pyval(s)) for s in size
        )
    else:
        check(scale_factor is not None, "interpolate needs size or scale_factor")
        sf = (float(pyval(scale_factor)),) * spatial if isinstance(scale_factor, (int, float, NumberProxy)) else tuple(
            float(pyval(s)) for s in scale_factor
        )
        out = tuple(int(math.floor(a.shape[2 + i] * sf[i])) for i in range(spatial))
    interp_mode = "nearest" if mode == "nearest" else "linear"
    ac = bool(align_corners) if align_corners is not None else False
    r = a
    for i in range(spatial):
        r = _resize_dim(r, 2 + i, out[i], interp_mode, ac)
    return r


# =============================================================================
# Additional activations
# =============================================================================


@torchsymbol("torch.nn.functional.glu")
def glu(a, dim: int = -1):
    d = canonicalize_dim(a.ndim, int(pyval(dim)))
    n = a.shape[d]
    check(n % 2 == 0, "glu dim must be even")
    x = clang.slice_in_dim(a, 0, n // 2, dim=d)
    g = clang.slice_in_dim(a, n // 2, n, dim=d)
    return clang.mul(x, sigmoid(g))


@torchsymbol("torch.nn.functional.hardtanh")
def hardtanh(a, min_val: float = -1.0, max_val: float = 1.0, inplace: bool = False):
    return clang.clamp(a, min_val, max_val)


@torchsymbol("torch.nn.functional.relu6")
def relu6(a, inplace: bool = False):
    return clang.clamp(a, 0.0, 6.0)


@torchsymbol("torch.nn.functional.hardsigmoid")
def hardsigmoid(a, inplace: bool = False):
    return clang.true_divide(clang.clamp(clang.add(a, 3.0), 0.0, 6.0), 6.0)


@torchsymbol("torch.nn.functional.logsigmoid")
def logsigmoid(a):
    # -softplus(-x), stable.
    return clang.neg(softplus(clang.neg(a)))


@torchsymbol("torch.nn.functional.selu")
def selu(a, inplace: bool = False):
    alpha = 1.6732632423543772848170429916717
    scale = 1.0507009873554804934193349852946
    return clang.mul(scale, clang.where(clang.gt(a, 0), a, clang.mul(alpha, clang.expm1(a))))


@torchsymbol("torch.nn.functional.celu")
def celu(a, alpha: float = 1.0, inplace: bool = False):
    return clang.where(clang.gt(a, 0), a, clang.mul(alpha, clang.expm1(clang.true_divide(a, alpha))))


@torchsymbol("torch.nn.functional.prelu")
def prelu(a, weight):
    if weight.numel > 1:
        wshape = [1] * a.ndim
        if a.ndim >= 2:
            wshape[1] = weight.numel
        weight = clang.reshape(weight, tuple(wshape))
    return clang.where(clang.gt(a, 0), a, clang.mul(a, weight))


@torchsymbol("torch.nn.functional.softmin")
def softmin(a, dim: int, dtype=None):
    return softmax(clang.neg(a), dim, dtype)


@torchsymbol("torch.nn.functional.softsign")
def softsign(a):
    return clang.true_divide(a, clang.add(clang.abs(a), 1.0))


@torchsymbol("torch.nn.functional.tanhshrink")
def tanhshrink(a):
    return clang.sub(a, clang.tanh(a))


@torchsymbol("torch.nn.functional.hardshrink")
def hardshrink(a, lambd: float = 0.5):
    keep = clang.gt(clang.abs(a), lambd)
    return clang.where(keep, a, clang.zeros_like(a))


@torchsymbol("torch.nn.functional.softshrink")
def softshrink(a, lambd: float = 0.5):
    mag = clang.sub(clang.abs(a), lambd)
    return clang.where(clang.gt(clang.abs(a), lambd), clang.mul(clang.sign(a), mag), clang.zeros_like(a))


@torchsymbol("torch.nn.functional.threshold")
def threshold(a, threshold_: float, value: float, inplace: bool = False):
    return clang.where(clang.gt(a, threshold_), a, clang.full_like(a, value))


# =============================================================================
# Additional losses
# =============================================================================


def _reduce_loss(l, reduction: str):
    if reduction == "none":
        return l
    if reduction == "sum":
        return clang.sum(l, None)
    check(reduction == "mean", lambda: f"Unknown reduction {reduction}")
    return clang.mean(l, None)


@torchsymbol("torch.nn.functional.l1_loss")
def l1_loss(input, target, reduction: str = "mean"):
    return _reduce_loss(clang.abs(clang.sub(input, target)), reduction)


@torchsymbol("torch.nn.functional.smooth_l1_loss")
def smooth_l1_loss(input, target, reduction: str = "mean", beta: float = 1.0):
    d = clang.abs(clang.sub(input, target))
    quad = clang.true_divide(clang.mul(clang.mul(d, d), 0.5), beta)
    lin = clang.sub(d, 0.5 * beta)
    return _reduce_loss(clang.where(clang.lt(d, beta), quad, lin), reduction)


@torchsymbol("torch.nn.functional.huber_loss")
def huber_loss(input, target, reduction: str = "mean", delta: float = 1.0):
    d = clang.abs(clang.sub(input, target))
    quad = clang.mul(clang.mul(d, d), 0.5)
    lin = clang.mul(delta, clang.sub(d, 0.5 * delta))
    return _reduce_loss(clang.where(clang.lt(d, delta), quad, lin), reduction)


@torchsymbol("torch.nn.functional.binary_cross_entropy")
def binary_cross_entropy(input, target, weight=None, reduction: str = "mean"):
    eps = 1e-12
    l = clang.neg(clang.add(
        clang.mul(target, clang.log(clang.clamp(input, eps, None))),
        clang.mul(clang.sub(1.0, target), clang.log(clang.clamp(clang.sub(1.0, input), eps, None))),
    ))
    if weight is not None:
        l = clang.mul(l, weight)
    return _reduce_loss(l, reduction)


@torchsymbol("torch.nn.functional.binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(input, target, weight=None, pos_weight=None,
                                     reduction: str = "mean"):
    # max(x,0) - x*t + log(1+exp(-|x|)) — the numerically stable form.
    neg_abs = clang.neg(clang.abs(input))
    if pos_weight is None:
        base = clang.add(clang.sub(clang.maximum(input, 0), clang.mul(input, target)),
                         clang.log1p(clang.exp(neg_abs)))
    else:
        # loss = (1-t)*x + (1+(pw-1)*t) * softplus(-x), with
        # softplus(-x) = log1p(exp(-|x|)) - min(x, 0)  (stable).
        lw = clang.add(1.0, clang.mul(clang.sub(pos_weight, 1.0), target))
        softplus_neg = clang.sub(clang.log1p(clang.exp(neg_abs)), clang.minimum(input, 0))
        base = clang.add(clang.mul(clang.sub(1.0, target), input), clang.mul(lw, softplus_neg))
    l = base
    if weight is not None:
        l = clang.mul(l, weight)
    return _reduce_loss(l, reduction)


@torchsymbol("torch.nn.functional.kl_div")
def kl_div(input, target, reduction: str = "mean", log_target: bool = False):
    if log_target:
        l = clang.mul(clang.exp(target), clang.sub(target, input))
    else:
        l = clang.sub(xlogy(target, target), clang.mul(target, input))
    if reduction == "batchmean":
        return clang.true_divide(clang.sum(l, None), input.shape[0])
    return _reduce_loss(l, reduction)


# =============================================================================
# In-place ops (functionalized: compute out-of-place, forward the stale proxy)
# =============================================================================


def _mark_inplace(old, new):
    """Functionalize an in-place update: cast the result back to the target's
    dtype (torch in-place ops keep self's dtype), register forwarding so every
    later consumer of ``old`` sees ``new``, and flag the trace so
    Symbol.__call__ resolves proxies (reference analogue: thunder's implicit
    functionalization of in-place torch ops)."""
    from thunder_tpu.core.trace import get_tracectx

    check(isinstance(old, TensorProxy), "in-place op target must be a traced tensor")
    if isinstance(new, TensorProxy) and new.dtype != old.dtype:
        new = clang.maybe_convert_to_dtype(new, old.dtype)
    if isinstance(new, TensorProxy) and tuple(new.shape) != tuple(old.shape):
        new = clang.expand_to(new, tuple(old.shape))
    trc = get_tracectx()
    if trc is not None:
        trc._inplace_seen = True
        targets = getattr(trc, "_inplace_targets", None)
        if targets is None:
            targets = trc._inplace_targets = {}
        # Keyed by the ORIGINAL proxy so module epilogues can map a
        # param/buffer to its final value after any number of updates.
        targets[old.name] = old
    old._inplace_forward = new
    return new


def _inplace(name: str, functional: Callable):
    def impl(a, *args, **kwargs):
        return _mark_inplace(a, functional(a, *args, **kwargs))

    impl.__name__ = name
    obj = _resolve_torch_attr(f"torch.Tensor.{name}")
    if obj is not None:
        _torch_to_thunder_function_map[obj] = impl
    _torch_ctx.register_method(name, impl)
    return impl


add_ = _inplace("add_", add)
sub_ = _inplace("sub_", sub)
mul_ = _inplace("mul_", mul)
div_ = _inplace("div_", div_sym)
pow_ = _inplace("pow_", pow)
neg_ = _inplace("neg_", clang.neg)
abs_ = _inplace("abs_", clang.abs)
exp_ = _inplace("exp_", clang.exp)
log_ = _inplace("log_", clang.log)
sqrt_ = _inplace("sqrt_", clang.sqrt)
rsqrt_ = _inplace("rsqrt_", clang.rsqrt)
sigmoid_ = _inplace("sigmoid_", lambda a: sigmoid(a))
tanh_ = _inplace("tanh_", clang.tanh)
relu_ = _inplace("relu_", lambda a: clang.maximum(a, 0))
floor_ = _inplace("floor_", clang.floor)
ceil_ = _inplace("ceil_", clang.ceil)
round_ = _inplace("round_", clang.round)
trunc_ = _inplace("trunc_", clang.trunc)
erf_ = _inplace("erf_", clang.erf)
zero_ = _inplace("zero_", lambda a: clang.zeros_like(a))
fill_ = _inplace("fill_", lambda a, v: clang.full_like(a, v))
masked_fill_ = _inplace("masked_fill_", masked_fill)
setitem_ = _inplace("setitem_", setitem)
clamp_ = _inplace("clamp_", clang.clamp)
clamp_min_ = _inplace("clamp_min_", lambda a, m: clang.clamp(a, m, None))
clamp_max_ = _inplace("clamp_max_", lambda a, m: clang.clamp(a, None, m))
copy_ = _inplace("copy_", lambda a, src, non_blocking=False: src)
addcmul_ = _inplace("addcmul_", addcmul)
addcdiv_ = _inplace("addcdiv_", addcdiv)
lerp_ = _inplace("lerp_", lerp)
tril_ = _inplace("tril_", tril)
triu_ = _inplace("triu_", triu)
scatter_add_ = _inplace("scatter_add_", scatter_add)
index_add_ = _inplace("index_add_", index_add)
index_copy_ = _inplace("index_copy_", index_copy)
uniform_ = _inplace(
    "uniform_",
    lambda a, from_=0.0, to=1.0, generator=None: clang.uniform(
        tuple(a.shape), float(pyval(from_)), float(pyval(to)), device=a.device,
        dtype=a.dtype if dtypes.is_float_dtype(a.dtype) else dtypes.float32,
    ),
)
normal_ = _inplace(
    "normal_",
    lambda a, mean=0.0, std=1.0, generator=None: clang.add(
        clang.mul(
            clang.randn(tuple(a.shape), device=a.device,
                        dtype=a.dtype if dtypes.is_float_dtype(a.dtype) else dtypes.float32),
            std,
        ),
        mean,
    ),
)


def _requires_grad_(a, requires_grad: bool = True):
    a._requires_grad = bool(requires_grad) and dtypes.is_inexact_dtype(a.dtype)
    return a


def _detach_(a):
    return _mark_inplace(a, prims.stop_gradient(a))


_torch_ctx.register_method("requires_grad_", _requires_grad_)
_torch_ctx.register_method("detach_", _detach_)
for _nm, _fn in (("requires_grad_", _requires_grad_), ("detach_", _detach_)):
    _obj = _resolve_torch_attr(f"torch.Tensor.{_nm}")
    if _obj is not None:
        _torch_to_thunder_function_map[_obj] = _fn


# =============================================================================
# Misc tensor methods
# =============================================================================


def _size(a, dim: Optional[int] = None):
    if dim is None:
        return tuple(a.shape)
    return a.shape[canonicalize_dim(a.ndim, int(pyval(dim)))]


_torch_ctx.register_method("size", _size)
_torch_ctx.register_method("dim", lambda a: a.ndim)
_torch_ctx.register_method("numel", lambda a: a.numel)
_torch_ctx.register_method("float", lambda a: clang.maybe_convert_to_dtype(a, dtypes.float32))
_torch_ctx.register_method("type", lambda a, dt=None: a.dtype if dt is None else clang.maybe_convert_to_dtype(a, dtypes.to_dtype(dt)))


# Generated code prints ltorch symbols qualified as ``ltorch.<name>``.
register_module("ltorch", __import__("sys").modules[__name__])


def torch_function_map() -> dict:
    return _torch_to_thunder_function_map
