"""The jit entry point: acquisition → transforms → claiming → XLA staging.

Reference parity: thunder/__init__.py (`jit:299`, `get_computation_and_inputs:371`,
the prologue-guarded cache loop `:409-447`, `fn_:602`) and the functional
(eager-unpacking) frontend of thunder/functional.py (`jit:444`,
`_eager_unpacking_interpreter:301`).

TPU-first execution model: where the reference's generated Python dispatches
one torch/nvFuser call per line every iteration, here the generated trace
callable is staged **whole** under ``jax.jit`` at compile time — steady-state
cost is one guard re-execution plus one XLA executable launch (the
CUDA-graphs endgame, as the default).
"""

from __future__ import annotations

import functools
from numbers import Number
from typing import Any, Callable, Optional, Sequence

from thunder_tpu import clang  # registers the clang language  # noqa: F401
from thunder_tpu.common import (
    CACHE_OPTIONS,
    SHARP_EDGES_OPTIONS,
    CacheEntry,
    CompileData,
    CompileStats,
    resolve_cache_option,
    resolve_sharp_edges_option,
    sharp_edge,
    sharp_edges_policy,
    timer_ns,
)
from thunder_tpu.core import dtypes, prims
from thunder_tpu.core.baseutils import GuardFailure, check
from thunder_tpu.core.codeutils import SigInfo
from thunder_tpu.core.langctxs import Languages, langctx_ctx, resolve_language
from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import (
    CollectionProxy,
    NumberProxy,
    Proxy,
    StringProxy,
    TensorProxy,
    proxy,
    tensorproxy_from_concrete,
)
from thunder_tpu.core.pytree import tree_flatten, tree_map, tree_unflatten
from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx
from thunder_tpu.executors import bridge, jaxex, pythonex  # register executors  # noqa: F401
from thunder_tpu.executors import flashex, pallasex  # higher-priority kernel executors  # noqa: F401
from thunder_tpu.executors import quantex  # opt-in int8 executor (registered, not default)  # noqa: F401
from thunder_tpu.executors.passes import del_last_used, transform_for_execution
from thunder_tpu.extend import resolve_executors
from thunder_tpu.observability import events as obs_events
from thunder_tpu.observability import metrics as obsm
from thunder_tpu.resilience import chaos as chaos_mod
from thunder_tpu.resilience import deopt as deopt_mod
from thunder_tpu.resilience import watchdog as watchdog_mod
from thunder_tpu.transforms.common import cse, dce
from thunder_tpu.transforms.rng import RNG_TAG, functionalize_rng_ops


# =============================================================================
# Acquisition (functional frontend)
# =============================================================================


def _proxy_input(x: Any, comp_trc: TraceCtx) -> Any:
    """Leaf → proxy, under the computation trace's name pool."""
    if bridge.is_concrete_tensor(x):
        return tensorproxy_from_concrete(x)
    if isinstance(x, (bool, int, float, complex, str)) or x is None:
        return x if x is None else proxy(x)
    if isinstance(x, Proxy):
        return x
    return proxy(x)  # AnyProxy


def _proxify_tree(tree: Any, comp_trc: TraceCtx) -> Any:
    if isinstance(tree, (tuple, list)):
        return type(tree)(_proxify_tree(v, comp_trc) for v in tree)
    if isinstance(tree, dict):
        return {k: _proxify_tree(v, comp_trc) for k, v in tree.items()}
    return _proxy_input(tree, comp_trc)


def _collect_leaves(proxied: Any, out: list) -> None:
    if isinstance(proxied, (tuple, list)):
        for v in proxied:
            _collect_leaves(v, out)
    elif isinstance(proxied, dict):
        for v in proxied.values():
            _collect_leaves(v, out)
    else:
        out.append(proxied)


def _build_prologue(
    args: tuple, kwargs: dict, proxied_args: tuple, proxied_kwargs: dict, tensor_leaves: list
) -> TraceCtx:
    """Construct the guard trace: unpack the input structure, validate every
    leaf's metadata/value, and return the flat tensor leaves.

    Reference parity: thunder/core/jit_ext.py `unpack_inputs:1098` — guards
    implement CONSTANT_VALUES caching: tensor metadata and Python-number
    values are checked; on mismatch the cache entry is skipped.
    """
    plg = TraceCtx(prologue=True)
    plg.name = "prologue"
    plg.set_siginfo(SigInfo("prologue", [], varargs="args", varkwargs="kwargs"))

    for t in tensor_leaves:
        plg.add_name(t.name)

    with tracectx(plg):
        args_coll = CollectionProxy(args, name="args")
        kwargs_coll = CollectionProxy(kwargs, name="kwargs")

        from thunder_tpu.core.proxies import AnyProxy

        def slot_proxy(p: Any):
            """Unpack-output proxy for a leaf. None leaves get a fresh
            prologue-local AnyProxy so the slot can be guarded with
            check_none — a None→tensor change must be a controlled miss, not
            a silent reuse of the trace that baked the constant None in."""
            return AnyProxy(None, prefix="nil") if p is None else p

        def guard_leaf(p: Any, concrete: Any) -> None:
            if isinstance(p, TensorProxy):
                sdims = getattr(p, "_symbolic_dims", None)
                if sdims:
                    # Symbolic-values caching: marked dims guard only RANK here
                    # (None = wildcard extent); each marked dim is lifted into a
                    # NumberProxy and bucket-constrained, so one entry serves
                    # every extent in the bucket (core/bucketing.py).
                    shape_spec = tuple(
                        None if i in sdims else int(s) for i, s in enumerate(p.shape)
                    )
                    prims.check_tensor_shape_and_metadata(
                        p, shape_spec, str(p.device), p.true_dtype, p.requires_grad,
                        bridge.framework_of(concrete),
                    )
                    for i in sorted(sdims):
                        lo, hi, _cid = sdims[i]
                        d = prims.unpack_dim(p, i)
                        prims.check_dim_bucket(d, lo, hi)
                    return
                prims.check_tensor_shape_and_metadata(
                    p, tuple(p.shape), str(p.device), p.true_dtype, p.requires_grad, bridge.framework_of(concrete)
                )
            elif isinstance(p, NumberProxy):
                prims.check_number_type_and_value(p, p.value)
            elif isinstance(p, StringProxy):
                prims.check_string_value(p, p.value)
            elif isinstance(p, AnyProxy) and p.value is None:
                prims.check_none(p)
            else:
                # Unguardable leaf: its observed value is baked into the
                # trace with no prologue check — report per the sharp-edges
                # policy (reference: jit_ext.py `_general_jit_sharp_edge:468`).
                sharp_edge(
                    f"input {getattr(p, 'name', p)!r} of type "
                    f"{type(getattr(p, 'value', concrete)).__name__} cannot be guarded"
                )

        def unpack_into(coll_proxy: CollectionProxy, concrete: Any, proxied: Any) -> None:
            if isinstance(concrete, (tuple, list)):
                # Structural guard first: a different length raises GuardFailure
                # (controlled miss) instead of a raw unpack ValueError.
                prims.check_len(coll_proxy, len(concrete))
                outs = []
                sub = []  # (collproxy, concrete, proxied) to recurse
                leaf_slots = []  # (slot, concrete) to guard
                for c, p in zip(concrete, proxied):
                    if isinstance(c, (tuple, list, dict)):
                        cp = CollectionProxy(c)
                        outs.append(cp)
                        sub.append((cp, c, p))
                    else:
                        slot = slot_proxy(p)
                        outs.append(slot)
                        leaf_slots.append((slot, c))
                bsym = prims.unpack_sequence.bind(coll_proxy, len(concrete), output=outs)
                plg.bound_symbols.append(bsym)
                for slot, c in leaf_slots:
                    guard_leaf(slot, c)
                for cp, c, p in sub:
                    unpack_into(cp, c, p)
            elif isinstance(concrete, dict):
                prims.check_keys(coll_proxy, tuple(concrete.keys()))
                for k, c in concrete.items():
                    p = proxied[k]
                    if isinstance(c, (tuple, list, dict)):
                        cp = CollectionProxy(c)
                        bsym = prims.unpack_key.bind(coll_proxy, k, output=cp)
                        plg.bound_symbols.append(bsym)
                        unpack_into(cp, c, p)
                    else:
                        slot = slot_proxy(p)
                        bsym = prims.unpack_key.bind(coll_proxy, k, output=slot)
                        plg.bound_symbols.append(bsym)
                        guard_leaf(slot, c)
            else:
                raise NotImplementedError(f"Cannot unpack {type(concrete)}")

        if args:
            unpack_into(args_coll, args, proxied_args)
        else:
            prims.check_len(args_coll, 0)
        if kwargs:
            unpack_into(kwargs_coll, kwargs, proxied_kwargs)
        else:
            prims.check_len(kwargs_coll, 0)

        prims.python_return(tuple(tensor_leaves))

    plg.output = tuple(tensor_leaves)
    return plg


def _copy_container_tree(tree: Any) -> Any:
    """Structural copy (fresh containers, shared leaf proxies) — the pristine
    baseline for input-mutation detection."""
    if isinstance(tree, (tuple, list)):
        return type(tree)(_copy_container_tree(v) for v in tree)
    if isinstance(tree, dict):
        return {k: _copy_container_tree(v) for k, v in tree.items()}
    return tree


_MISSING = object()


def _mutation_value_spec(v: Any, extras: list):
    """Encode a mutated-in value: trace proxies become extra computation
    outputs (("out", j)); plain Python data is stored inline."""
    from thunder_tpu.core.proxies import pyval
    from thunder_tpu.core.symbol import resolve_inplace

    if isinstance(v, TensorProxy):
        extras.append(resolve_inplace(v))
        return ("out", len(extras) - 1)
    if isinstance(v, NumberProxy):
        return ("const", pyval(v))
    if isinstance(v, dict):
        return ("dict", {k: _mutation_value_spec(x, extras) for k, x in v.items()})
    if isinstance(v, (list, tuple)):
        tag = "list" if isinstance(v, list) else "tuple"
        return (tag, [_mutation_value_spec(x, extras) for x in v])
    return ("const", v)


def _same_container_type(a: Any, b: Any) -> bool:
    return (
        (isinstance(a, dict) and isinstance(b, dict))
        or (isinstance(a, list) and isinstance(b, list))
        or (isinstance(a, tuple) and isinstance(b, tuple))
    )


def _tuple_replaced(cur: tuple, orig: tuple) -> bool:
    """Did a tuple VALUE change? Tuples are immutable, so any leaf identity
    difference means the enclosing slot was rebound to a new tuple — the
    parent must record a wholesale set (recursion alone would drop it)."""
    if len(cur) != len(orig):
        return True
    for a, b in zip(cur, orig):
        if isinstance(a, tuple) and isinstance(b, tuple):
            if _tuple_replaced(a, b):
                return True
        elif _same_container_type(a, b):
            continue  # mutable containers inside tuples: diffed in place
        elif a is not b:
            return True
    return False


def _diff_container_tree(cur: Any, orig: Any, path: tuple, muts: list, extras: list) -> None:
    """Record container mutations fn made to its (proxied) inputs.

    Reference parity: thunder/core/jit_ext.py `process_recorded_modifications
    :1302` — the VM records STORE_SUBSCR et al.; here the proxied containers
    are diffed against a pristine structural copy after tracing. The pristine
    copy has FRESH container objects at every level, so container-typed
    values are compared by recursion, never by identity."""
    if isinstance(orig, dict) and isinstance(cur, dict):
        for k in orig:
            if k not in cur:
                muts.append(("del", path, k))
        for k, v in cur.items():
            ov = orig.get(k, _MISSING)
            if isinstance(v, tuple) and isinstance(ov, tuple):
                if _tuple_replaced(v, ov):
                    muts.append(("set", path, k, _mutation_value_spec(v, extras)))
                else:
                    _diff_container_tree(v, ov, path + (k,), muts, extras)
            elif _same_container_type(v, ov):
                _diff_container_tree(v, ov, path + (k,), muts, extras)
            elif ov is _MISSING or ov is not v:
                muts.append(("set", path, k, _mutation_value_spec(v, extras)))
    elif isinstance(orig, list) and isinstance(cur, list):
        if len(cur) != len(orig) or any(
            (a is not b and not _same_container_type(a, b))
            or (isinstance(a, tuple) and isinstance(b, tuple) and _tuple_replaced(a, b))
            for a, b in zip(cur, orig)
        ):
            muts.append(("resync", path, [_mutation_value_spec(v, extras) for v in cur]))
        else:
            for i, (a, b) in enumerate(zip(cur, orig)):
                _diff_container_tree(a, b, path + (i,), muts, extras)
    elif isinstance(orig, tuple) and isinstance(cur, tuple) and len(orig) == len(cur):
        # Top-level / nested positional structure: elements can't be rebound
        # in the CALLER (tuples are immutable), so recursion alone is right.
        for i, (a, b) in enumerate(zip(cur, orig)):
            _diff_container_tree(a, b, path + (i,), muts, extras)


def _collect_input_mutations(
    proxied_args, proxied_kwargs, pristine_args, pristine_kwargs, tensor_leaves
) -> tuple[list, list]:
    """(mutation records, extra output proxies) for epilogue replay.

    Two classes (reference: jit_ext.py:1302 + the input-mutation sharp edge
    at jit_ext.py:468): container mutations (``d["k"] = t``) and in-place
    tensor updates on INPUT tensors (``x.add_(1)``)."""
    from thunder_tpu.core.symbol import resolve_inplace

    muts: list = []
    extras: list = []
    _diff_container_tree(proxied_args, pristine_args, ("args",), muts, extras)
    _diff_container_tree(proxied_kwargs, pristine_kwargs, ("kwargs",), muts, extras)
    for i, p in enumerate(tensor_leaves):
        fp = resolve_inplace(p)
        if fp is not p:
            extras.append(fp)
            muts.append(("tensor", i, ("out", len(extras) - 1)))
    return muts, extras


def trace_program(
    fn: Callable, args: tuple, kwargs: dict, *, record_input_mutations: bool = False,
    symbolic_marks: Optional[dict] = None,
) -> tuple[TraceCtx, TraceCtx]:
    """Acquire ``fn`` as (prologue_trace, computation_trace).

    With ``record_input_mutations`` (the jit() path), mutations fn makes to
    its inputs (container writes, in-place tensor updates) are detected
    post-trace and recorded on ``comp_trc._input_mutations``; the
    computation output is then wrapped as ``{"__out": ..., "__muts": (...)}``
    so the staged program computes the final values and the caller replays
    them (CacheEntry.epilogue_fn). The module frontend has its own epilogue
    (frontend/module.py) and keeps this off."""
    comp_trc = TraceCtx(fn)
    comp_trc.name = "computation"

    with tracectx(comp_trc):
        proxied_args = _proxify_tree(args, comp_trc)
        proxied_kwargs = _proxify_tree(kwargs, comp_trc)
    pristine_args = _copy_container_tree(proxied_args)
    pristine_kwargs = _copy_container_tree(proxied_kwargs)

    # Canonical leaf order = jax.tree_util flatten order (sorted dict keys),
    # so grads, prologue outputs, and computation args all align with what
    # tree_flatten(params) gives the user.
    leaves, _ = tree_flatten((proxied_args, proxied_kwargs))
    tensor_leaves = [p for p in leaves if isinstance(p, TensorProxy)]

    if symbolic_marks:
        # cache="symbolic values": the caller traces on bucket-padded example
        # inputs; marked dims carry their bucket so the prologue guards
        # membership instead of the exact extent (core/bucketing.py).
        for li, dims in symbolic_marks.items():
            tensor_leaves[li]._symbolic_dims = dict(dims)

    comp_trc.args = tuple(tensor_leaves)
    # Concrete example inputs aligned with the tensor args: lets traced
    # Python coerce input-derived scalars (bool/int/float of a proxy) via
    # guarded concretization (core/concrete.py).
    flat_concrete, _ = tree_flatten((args, kwargs))
    comp_trc._concrete_leaves = [
        c for c, p in zip(flat_concrete, leaves) if isinstance(p, TensorProxy)
    ]

    from thunder_tpu.frontend.sharp import sharp_edge_interceptors

    with tracectx(comp_trc):
        with langctx_ctx(Languages.TORCH if _torch_lang_available() else Languages.CLANG), \
                sharp_edge_interceptors():
            result = fn(*proxied_args, **proxied_kwargs)
        if getattr(comp_trc, "_inplace_seen", False):
            # A returned proxy may have been updated in place after it was
            # produced — return its latest functional value.
            from thunder_tpu.core.symbol import resolve_inplace_tree

            result = resolve_inplace_tree(result)

        # Mutations are always DETECTED (so every staging path — jit, grad,
        # vmap/jvp — can see them on comp_trc._input_mutations); only the
        # jit() path (record_input_mutations) REPLAYS them via the epilogue.
        muts, extras = _collect_input_mutations(
            proxied_args, proxied_kwargs, pristine_args, pristine_kwargs, tensor_leaves
        )
        comp_trc._input_mutations = muts
        if muts and record_input_mutations:
            from thunder_tpu.common import sharp_edge

            kinds = sorted({m[0] for m in muts})
            sharp_edge(
                f"traced function mutates its inputs ({', '.join(kinds)}): the "
                "final values are replayed onto the caller's objects after "
                "execution (epilogue)"
            )
            result = {"__out": result, "__muts": tuple(extras)}
        prims.python_return(result)
    comp_trc.output = result

    # The prologue guards/unpacks the CALLER's structure — build it from the
    # pristine copies so fn's container mutations can't skew the guards.
    plg = _build_prologue(args, kwargs, pristine_args, pristine_kwargs, tensor_leaves)
    # Concretization is only possible while the user function executes; drop
    # the concrete-input references so cached trace objects don't pin the
    # first call's tensors (and params) for the process lifetime. Same for
    # the tensor-constant memo: its id-reuse guard matters only WHILE
    # tracing, and keeping it would pin every captured host tensor alongside
    # the baked device copy for the cache entry's lifetime.
    comp_trc._concrete_leaves = None
    if getattr(comp_trc, "_tconst_memo", None) is not None:
        comp_trc._tconst_memo = None
    return plg, comp_trc


def _torch_lang_available() -> bool:
    try:
        resolve_language(Languages.TORCH)
        return True
    except KeyError:
        return False


# =============================================================================
# Compilation
# =============================================================================


def _has_tag_in_trace(trc: TraceCtx, tag: OpTags) -> bool:
    return any(tag in b.sym.tags for b in trc.bound_symbols)


def _compile_entry(cd: CompileData, cs: CompileStats, args: tuple, kwargs: dict) -> CacheEntry:
    # debug_checks=True/False scopes the trace verifier (analysis/) over the
    # whole pass pipeline; None defers to THUNDER_TPU_CHECKS. Each pass's
    # provenance stamping (wrap_in_trace_provenance/mark in core/trace.py)
    # verifies its output, so a violation names the pass that introduced it.
    from thunder_tpu.core.trace import debug_checks
    from thunder_tpu.observability import events

    with debug_checks(cd.compile_options.get("debug_checks")), \
            events.compile_scope(getattr(cd, "_event_log", None)) as compile_id:
        events.emit_event(
            "compile_start",
            compile_id=compile_id,
            fn=getattr(cd.fn, "__name__", repr(cd.fn)),
            cache_option=cd.cache_option.name.lower(),
            call=cs.calls,
        )
        # De-opt ladder L3 (resilience/deopt.py): exact shapes — no bucket
        # padding — shrinks the entry's live memory after repeated OOMs.
        if (cd.cache_option is CACHE_OPTIONS.SYMBOLIC_VALUES
                and deopt_mod.current_level(cd) < 3):
            sym_spec = _symbolic_spec_for_call(cd, cs, args, kwargs)
            if sym_spec is not None:
                events.emit_event(
                    "bucket_select", compile_id=compile_id,
                    buckets=sym_spec.describe(),
                    marks={str(li): sorted(d.keys()) for li, d in sym_spec.marks.items()},
                )
                pargs, pkwargs = _pad_example(args, kwargs, sym_spec)
                return _compile_entry_checked(cd, cs, pargs, pkwargs, sym_spec,
                                              compile_id=compile_id)
        return _compile_entry_checked(cd, cs, args, kwargs, None, compile_id=compile_id)


def _compile_entry_checked(
    cd: CompileData, cs: CompileStats, args: tuple, kwargs: dict, sym_spec,
    compile_id: Optional[int] = None,
) -> CacheEntry:
    # De-opt ladder position (resilience/deopt.py): 0 = normal; ≥1 disables
    # fusion passes + buffer donation; ≥2 compiles under aggressive
    # rematerialization (scoped HERE so an aborted compile can't leak the
    # contextvar); ≥3 was applied upstream (exact shapes).
    deopt_level = deopt_mod.current_level(cd)
    if deopt_level >= 2:
        from thunder_tpu.transforms.rematerialization import aggressive_remat

        with aggressive_remat():
            return _compile_entry_impl(cd, cs, args, kwargs, sym_spec,
                                       compile_id, deopt_level)
    return _compile_entry_impl(cd, cs, args, kwargs, sym_spec, compile_id, deopt_level)


# Persistent-XLA-cache verdicts, tapped from jax's monitoring events: the
# compile_phase span for an entry's first run says whether the seconds went
# to a real backend compile (cache miss) or a cache-entry deserialize (hit)
# — the distinction that explains 2x swings in xla-compile totals between
# otherwise identical rounds (BENCHMARKS.md, r4→r5 diagnosis).
_jax_cache_events = {
    "hits": 0, "misses": 0, "backend_compile_s": 0.0, "cache_get_s": 0.0,
    "installed": False,
}


def _install_jax_cache_listener() -> None:
    if _jax_cache_events["installed"]:
        return
    _jax_cache_events["installed"] = True
    try:
        from jax._src import monitoring

        def _on_event(event: str, **kw) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                _jax_cache_events["hits"] += 1
            elif event == "/jax/compilation_cache/cache_misses":
                _jax_cache_events["misses"] += 1

        def _on_duration(event: str, duration: float, **kw) -> None:
            if event == "/jax/core/compile/backend_compile_duration":
                _jax_cache_events["backend_compile_s"] += duration
            elif event == "/jax/compilation_cache/cache_retrieval_time_sec":
                _jax_cache_events["cache_get_s"] += duration

        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # internal jax API: absence degrades to cache=None
        _jax_cache_events["installed"] = False


def _jax_cache_counts() -> dict:
    return {k: _jax_cache_events[k]
            for k in ("hits", "misses", "backend_compile_s", "cache_get_s")}


def _record_compile_phase(compile_id, phase: str, seconds: float, *,
                          log=None, **extra) -> None:
    """One compile-pipeline span: a ``compile_phase`` event (correlated by
    compile_id) + the ``thunder_tpu_compile_phase_s{phase=...}`` histogram.
    Together the spans decompose what ``thunder_tpu_xla_compile_s`` reports
    as one opaque number."""
    extra = {k: v for k, v in extra.items() if v is not None}
    if obsm.enabled():
        labels = {"phase": phase}
        if extra.get("cache"):
            labels["cache"] = extra["cache"]
        obsm.COMPILE_PHASE_S.observe(seconds, **labels)
    target = log if log is not None else obs_events.active_log()
    if target is not None:
        target.emit("compile_phase", compile_id=compile_id, phase=phase,
                    s=round(seconds, 6), **extra)
    else:
        # No JSONL sink: the ops-plane taps (flight ring) still get the
        # span — compile phases are exactly the context a fault dump needs.
        obs_events.tap_event("compile_phase", dict(
            compile_id=compile_id, phase=phase, s=round(seconds, 6), **extra))


def _compile_entry_impl(
    cd: CompileData, cs: CompileStats, args: tuple, kwargs: dict, sym_spec,
    compile_id: Optional[int], deopt_level: int,
) -> CacheEntry:
    import jax

    from thunder_tpu.core.trace import mark

    build_start = timer_ns()
    phases: dict[str, Any] = {}
    cs.compile_count += 1
    # Chaos seam: injected XLA compile failure/timeout — lands on the same
    # recovery path (the de-opt ladder) as the real thing.
    chaos_mod.compile_seam(getattr(cd.fn, "__name__", repr(cd.fn)))
    cs.last_trace_tracing_start = timer_ns()
    with sharp_edges_policy(cd.sharp_edges):
        plg_trc, comp_trc = trace_program(
            cd.fn, args, kwargs, record_input_mutations=True,
            symbolic_marks=sym_spec.marks if sym_spec is not None else None,
        )
    # Stamp (and, under debug checks, verify) the freshly acquired traces so
    # an acquisition bug is attributed to acquisition, not the first pass.
    mark(comp_trc, "Acquisition")
    mark(plg_trc, "Prologue construction")
    cs.last_trace_tracing_stop = timer_ns()
    phases["trace"] = (cs.last_trace_tracing_stop - cs.last_trace_tracing_start) / 1e9
    _phase_mark = timer_ns()

    input_mutations = getattr(comp_trc, "_input_mutations", None) or []
    if input_mutations and cd.compile_options.get("_trace_transforms"):
        raise NotImplementedError(
            "the traced function mutates its inputs, which cannot be combined "
            "with trace transforms (grad/value_and_grad/autocast) — make the "
            "function pure or apply updates outside it"
        )

    from thunder_tpu.core.concrete import value_guards_of

    value_guards = value_guards_of(comp_trc)

    computation_traces = [comp_trc]
    comp_trc = dce(comp_trc)
    computation_traces.append(comp_trc)
    comp_trc = cse(comp_trc)
    computation_traces.append(comp_trc)

    if sym_spec is not None:
        # Thread validity masks through reductions over bucket-padded dims and
        # derive the output crop plan — BEFORE grad, so the masked program is
        # what gets differentiated (masks are constants w.r.t. the inputs).
        from thunder_tpu.transforms.padmask import thread_pad_masks

        comp_trc, mask_classes, crop_plan, pad_warnings = thread_pad_masks(comp_trc, sym_spec)
        comp_trc = dce(comp_trc)  # sweep replaced reductions' dead count constants
        computation_traces.append(comp_trc)
        sym_spec.mask_classes = mask_classes
        sym_spec.crop_plan = crop_plan
        if pad_warnings:
            import warnings

            for w in pad_warnings:
                warnings.warn(f"cache='symbolic values': {w}", stacklevel=2)

    # Trace-to-trace transforms requested at jit() time (grad, autocast, ...).
    trace_transforms = cd.compile_options.get("_trace_transforms", ())
    for tt in trace_transforms:
        comp_trc = tt(comp_trc)
        computation_traces.append(comp_trc)
    if sym_spec is not None and trace_transforms:
        # The grad/autocast rewrite minted new output proxies (grads); re-run
        # the provenance analysis on the transformed trace so the crop plan
        # covers them exactly (transforms/padmask.py).
        from thunder_tpu.transforms.padmask import analyze_crop_plan

        sym_spec.crop_plan = analyze_crop_plan(comp_trc, sym_spec)

    # Joint-trace attention-residual saving: when grad produced fw+bw in one
    # trace, let the flash backward consume saved (out, lse) instead of
    # recomputing the forward kernel (transforms/attention_residuals.py).
    # Skipped at de-opt ladder level ≥ 1 ("disable fusion").
    if deopt_level < 1:
        from thunder_tpu.transforms.attention_residuals import save_sdpa_residuals_joint

        comp_trc = save_sdpa_residuals_joint(comp_trc, cd.executors_list)

    comp_trc = functionalize_rng_ops(comp_trc)
    if comp_trc.tags.get(RNG_TAG):
        computation_traces.append(comp_trc)

    phases["transforms"] = (timer_ns() - _phase_mark) / 1e9
    _phase_mark = timer_ns()
    extrace = transform_for_execution(comp_trc, cd.executors_list)
    computation_traces.append(extrace)
    phases["claim"] = (timer_ns() - _phase_mark) / 1e9
    _phase_mark = timer_ns()

    # Chaos seam: NaN-poison a chosen BoundSymbol (after claiming, so the
    # poison survives into both the staged entry and the instrumented
    # attribution re-run the on_nan guard performs).
    poisoned = chaos_mod.maybe_poison_nan(extrace)
    if poisoned is not extrace:
        extrace = poisoned
        computation_traces.append(extrace)
    # The claimed (pre-instrumentation, pre-del) trace: what the on_nan
    # guard re-runs under a NaN watcher to attribute a non-finite step.
    claimed_extrace = extrace

    # -- static planner suite (ISSUE 10) --------------------------------------
    # Runs on every compile (O(trace), its seconds are a gated compile phase):
    # stamps donation metadata on the claimed trace, predicts the per-device
    # peak HBM live-set (consulted by the de-opt ladder on an OOM), and
    # certifies the collective schedule (consumed by the watchdog's timeout
    # diagnosis and the sched.* verifier rule).
    _phase_mark = timer_ns()
    on_nan_opt = cd.compile_options.get("on_nan")
    # Resolved here (not at the staging block) because donation only happens
    # when the entry actually stages under jax.jit: an unstaged entry
    # (disable_jit_staging / device-sync ops / instrumentation) donates
    # nothing, and the planner must price — and the donation.* rules must
    # see — what will really run.
    instrument_hooks = _resolve_instrument_hooks(cd)
    device_sync = _has_tag_in_trace(extrace, OpTags.DEVICE_SYNC_OP)
    will_stage = not (cd.disable_jit_staging or device_sync or instrument_hooks)
    donate_buckets = (
        will_stage
        and sym_spec is not None
        and deopt_level < 1
        and on_nan_opt != "rerun-instrumented"
        and jaxex._donation_active()
    )
    extrace, static_plan, static_cert = _static_planner(
        extrace, sym_spec,
        donate=donate_buckets,
        rerun_capable=on_nan_opt == "rerun-instrumented",
        # The comm scheduler rides the same advisory phase; the de-opt
        # ladder disables it from L1 up (like fusion) so a bad schedule
        # demotes cleanly through the existing recovery loop.
        comm_schedule=deopt_level < 1,
    )
    if extrace is not claimed_extrace:
        computation_traces.append(extrace)
    phases["static_analysis"] = (timer_ns() - _phase_mark) / 1e9
    _phase_mark = timer_ns()  # codegen span starts after the planner

    # Per-op instrumentation (observability/instrument.py): bracket every
    # value-producing bsym with host pre/post hooks. Runs after claiming (so
    # records carry the executor) and before del_last_used (so dels land
    # after the hooks that consume the values). Instrumented entries execute
    # UNSTAGED — the hooks are host side effects XLA cannot stage. (Hooks
    # were resolved above, before the static planner, so the donation
    # decision already knows this entry won't stage.)
    if instrument_hooks:
        from thunder_tpu.observability.instrument import instrument_for_execution

        extrace = instrument_for_execution(extrace, instrument_hooks)
        computation_traces.append(extrace)

    extrace = del_last_used(extrace)
    computation_traces.append(extrace)

    plg_traces = [plg_trc]
    from thunder_tpu.extend import get_executor

    if cd.cache_option is CACHE_OPTIONS.SAME_INPUT:
        # SAME_INPUT semantics (reference: thunder/__init__.py:449 +
        # core/options.py:78-104): the user asserts every later call has
        # the same metadata AND values — guards are STRIPPED from the
        # prologue, so subsequent calls skip all checks. Unsafe by design;
        # differing inputs silently reuse the first specialization.
        check_ids = {
            PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA,
            PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
            PrimIDs.CHECK_STRING_VALUE,
            PrimIDs.CHECK_LEN,
            PrimIDs.CHECK_KEYS,
            PrimIDs.CHECK_NONE,
        }
        stripped = from_trace(plg_trc)
        stripped.bound_symbols.extend(
            b for b in plg_trc.bound_symbols if b.sym.id not in check_ids
        )
        stripped.set_siginfo(plg_trc.siginfo)
        plg_trc = stripped
        plg_traces.append(plg_trc)

    plg_ex = transform_for_execution(plg_trc, (get_executor("python"),))
    plg_traces.append(plg_ex)

    _maybe_dump_trace(extrace)
    prologue_fn = plg_ex.python_callable()
    trace_callable = extrace.python_callable()
    # Everything between claiming and here: chaos/instrument passes,
    # del_last_used, the prologue claim, and source codegen + exec.
    phases["codegen"] = (timer_ns() - _phase_mark) / 1e9
    _phase_mark = timer_ns()

    needs_rng = bool(extrace.tags.get(RNG_TAG))
    if not will_stage:
        computation_fn = trace_callable
    elif sym_spec is not None:
        # Bucketed staging: padded input buffers are dispatch-owned
        # temporaries, donated to XLA off-CPU (executors/jaxex.py) — unless
        # the de-opt ladder disabled donation (level ≥ 1), or the on_nan
        # guard may re-run these exact buffers through the instrumented
        # trace (donated arrays are deleted after the staged run).
        # donate_buckets is THE donation predicate, computed once above for
        # the static planner — staging must not re-derive it (drift between
        # what was planned and what the executor does).
        computation_fn = jaxex.stage_bucketed(
            trace_callable, sorted(sym_spec.marks), donate=donate_buckets,
        )
        # Reconcile the trace's donation metadata with what the executor
        # actually stamped — by construction they agree (one predicate), but
        # the donation.* rules must read the executor's truth, not a plan.
        actual = getattr(computation_fn, "_thunder_donated_argnums", None)
        if actual is not None and not actual and extrace.tags.get("donated_inputs"):
            extrace.tags["donated_inputs"] = ()
    else:
        computation_fn = jax.jit(trace_callable)
    # jax.jit wrapper construction only — the XLA compile itself happens at
    # the entry's first run (the xla_compile phase recorded in fn_).
    phases["staging"] = (timer_ns() - _phase_mark) / 1e9

    torch_facing = any(bridge.is_torch_tensor(x) for x in tree_flatten((args, kwargs))[0])

    flat_call, call_treedef = tree_flatten((args, kwargs))
    on_nan = cd.compile_options.get("on_nan")
    entry = CacheEntry(
        prologue_fn=prologue_fn,
        computation_fn=computation_fn,
        epilogue_fn=_build_epilogue(input_mutations) if input_mutations else None,
        backward_fn=None,
        prologue_traces=plg_traces,
        computation_traces=computation_traces,
        backward_traces=[],
        torch_facing=torch_facing,
        needs_rng=needs_rng,
        value_guards=value_guards,
        sym_spec=sym_spec,
        treedef=call_treedef,
        leaf_meta=_leaf_meta(flat_call),
        on_nan=on_nan,
        claimed_extrace=claimed_extrace if on_nan else None,
    )
    entry.stats.trace_s = (timer_ns() - build_start) / 1e9
    entry.stats.degradation_level = deopt_level
    entry.stats.phases = phases
    entry.compile_id = compile_id
    if static_plan is not None:
        entry.stats.predicted_peak_bytes = int(static_plan.peak_bytes)
    entry.schedule_certificate = static_cert
    cs.trace_seconds += entry.stats.trace_s
    comm_sched_tag = extrace.tags.get("comm_schedule")
    for phase in ("trace", "transforms", "claim", "static_analysis", "codegen",
                  "staging"):
        extra = {}
        if phase == "static_analysis" and static_plan is not None:
            extra = dict(
                predicted_peak_bytes=int(static_plan.peak_bytes),
                collective_sites=len(static_cert.sites) if static_cert else 0,
            )
            # Comm-scheduler outcome, by PRESENCE only: entries the pass
            # never touched (no collectives, disabled, de-opted) carry none.
            if comm_sched_tag:
                extra["comm_schedule_moves"] = comm_sched_tag.get("moves")
                extra["comm_schedule_exposed_pct"] = comm_sched_tag.get(
                    "exposed_pct_after"
                )
        _record_compile_phase(compile_id, phase, phases.get(phase, 0.0), **extra)

    # Observability: compile-side metrics + the compile_end event carrying
    # the executor-claim breakdown and static collective traffic of the
    # final execution trace (executors/passes.py stamps them into tags).
    from thunder_tpu.observability import events

    claims = extrace.tags.get("claim_breakdown") or {}
    collective_bytes = int(extrace.tags.get("collective_bytes") or 0)
    if obsm.enabled():
        obsm.COMPILES.inc()
        if cs.compile_count > 1:
            obsm.RECOMPILES.inc()
        if sym_spec is not None:
            obsm.BUCKET_COMPILES.inc()
        obsm.COMPILE_MS.observe(entry.stats.trace_s * 1e3)
        for ex_name, n in claims.items():
            obsm.CLAIMED_BSYMS.inc(n, executor=ex_name)
        if collective_bytes:
            obsm.COLLECTIVE_BYTES.inc(collective_bytes)
    events.emit_compile_end(
        compile_id,
        getattr(cd.fn, "__name__", repr(cd.fn)),
        entry.stats.trace_s * 1e3,
        extrace,
        symbolic=sym_spec is not None,
        recompile=cs.compile_count > 1,
        staged=computation_fn is not trace_callable,
    )

    cs.last_traces = computation_traces
    cs.last_prologue_traces = plg_traces
    if cd.cache_option is not CACHE_OPTIONS.NO_CACHING:
        cs.cache_entries.append(entry)
    return entry


def _static_planner(extrace: TraceCtx, sym_spec, *, donate: bool,
                    rerun_capable: bool, comm_schedule: bool = False):
    """The compile pipeline's static_analysis phase (ISSUE 10 + 13): stamp
    donation metadata on the claimed execution trace, run the certificate-
    driven collective-overlap scheduler (``transforms/comm_schedule.py`` —
    the donation tags must land first so its liveness back-off prices the
    real plan), plan the result's HBM liveness, and certify its collective
    schedule. Returns ``(extrace, MemoryPlan | None, ScheduleCertificate |
    None)`` — planning/scheduling failures degrade to the input trace and
    None, never break a compile."""
    try:
        from thunder_tpu.analysis import liveness as live_mod
        from thunder_tpu.analysis import schedule as sched_mod

        donated_names: tuple = ()
        if donate and sym_spec is not None:
            args = [a for a in extrace.args if isinstance(a, TensorProxy)]
            donated_names = tuple(
                args[li].name for li in sorted(sym_spec.marks) if li < len(args)
            )
        extrace.tags["donated_inputs"] = donated_names
        if rerun_capable:
            extrace.tags["rerun_reads_inputs"] = True
        if comm_schedule:
            from thunder_tpu.transforms import comm_schedule as comm_sched

            if comm_sched.enabled():
                extrace, _ = comm_sched.schedule_collectives(extrace)
        plan = live_mod.plan_liveness(
            extrace, donated=donated_names, include_rows=False
        )
        # Certify + stamp the per-axis collective order baseline; the
        # sched.uncertified-reorder rule diffs later passes against it, and
        # the watchdog attaches the axis order to timeout diagnoses. (The
        # scheduler already recertified its own output; stamping again is
        # idempotent on the preserved per-axis order.)
        cert = sched_mod.stamp(extrace)
        return extrace, plan, cert
    except Exception:  # noqa: BLE001 — the planner is advisory, never fatal
        return extrace, None, None


def _resolve_instrument_hooks(cd: CompileData) -> tuple:
    """Hooks from jit(debug_watch=..., instrument=...), resolved ONCE per
    compiled function (not per entry) and stashed on cd: every cache entry
    of the function shares the same hook instances, so an OpTimer created
    from ``instrument="time"`` accumulates across shape specializations and
    ``instrument_reports`` sees all of it. Empty tuple (the common case)
    means no instrumentation pass runs and the entry stages whole under
    XLA — observability-off costs nothing."""
    hooks = getattr(cd, "_instrument_hooks", None)
    if hooks is not None:
        return hooks
    dw = cd.compile_options.get("debug_watch")
    ins = cd.compile_options.get("instrument")
    if not dw and ins is None:
        cd._instrument_hooks = ()
        return ()
    from thunder_tpu.observability.instrument import resolve_hooks

    hooks = resolve_hooks(dw, ins)
    cd._instrument_hooks = hooks
    return hooks


# Trace-dump-and-edit hook (reference: thunder/__init__.py:168-170 +
# trace.py:400-415 — write the final program to a file so a human can read
# or edit it; the canonical debugging tool is reading the generated Python).
_execution_callback_file = {"path": None}


def set_execution_callback_file(path: Optional[str]) -> None:
    _execution_callback_file["path"] = path


def _maybe_dump_trace(trc: TraceCtx) -> None:
    path = _execution_callback_file["path"]
    if path:
        with open(path, "a") as f:
            f.write(trc.python())
            f.write("\n\n")


_global_rng = {"seed": 0}


def seed(n: int) -> None:
    """Set the global RNG seed used for traces with random ops."""
    _global_rng["seed"] = n


def _next_key():
    import jax

    _global_rng["seed"] += 1
    return jax.random.PRNGKey(_global_rng["seed"])


def _build_epilogue(muts: list) -> Callable:
    """Side-effect replay for input-mutating traced functions (reference:
    jit_ext.py `process_recorded_modifications:1302`).

    Called per execution with the caller's (args, kwargs), the prologue's
    flat tensor leaves, and the raw {"__out", "__muts"} computation output;
    applies each recorded mutation to the CALLER's objects and returns the
    user-visible output."""

    def navigate(args, kwargs, path):
        obj = args if path[0] == "args" else kwargs
        for k in path[1:]:
            obj = obj[k]
        return obj

    def build_value(spec, extras):
        tag, payload = spec
        if tag == "out":
            return extras[payload]
        if tag == "const":
            return payload
        if tag == "dict":
            return {k: build_value(v, extras) for k, v in payload.items()}
        if tag == "list":
            return [build_value(v, extras) for v in payload]
        return tuple(build_value(v, extras) for v in payload)  # "tuple"

    def epilogue(args, kwargs, flat_inps, raw_out):
        import numpy as np

        extras = raw_out["__muts"]
        for rec in muts:
            if rec[0] == "tensor":
                _, i, spec = rec
                target = flat_inps[i]
                val = build_value(spec, extras)
                if bridge.is_torch_tensor(target):
                    import torch

                    with torch.no_grad():
                        target.copy_(bridge.to_torch(val).to(target.dtype))
                elif isinstance(target, np.ndarray):
                    np.copyto(target, np.asarray(val).astype(target.dtype, copy=False))
                else:
                    # jax.Array inputs are immutable — nothing to write back;
                    # the functional value is still available via the output.
                    import warnings

                    warnings.warn(
                        "in-place update of an immutable (jax) input tensor "
                        "cannot be replayed onto the caller's array",
                        stacklevel=3,
                    )
            elif rec[0] == "set":
                _, path, key, spec = rec
                navigate(args, kwargs, path)[key] = build_value(spec, extras)
            elif rec[0] == "del":
                _, path, key = rec
                container = navigate(args, kwargs, path)
                container.pop(key, None)
            else:  # "resync": a list changed length/identity — rebuild it
                _, path, specs = rec
                container = navigate(args, kwargs, path)
                container[:] = [build_value(s, extras) for s in specs]
        return raw_out["__out"]

    return epilogue


def _prepare_inputs(entry: CacheEntry, flat_inps) -> tuple[list, Optional[dict]]:
    """(jax inputs — bucket-padded for symbolic entries, true extents) for an
    entry. Shared by value-guard evaluation and execution so a value-guarded
    dispatch converts/pads each leaf exactly once."""
    inps = [bridge.to_jax(x) for x in flat_inps]
    true_extents = None
    if entry.sym_spec is not None:
        true_extents = entry.sym_spec.true_extents(flat_inps)
        inps = jaxex.pad_to_bucket(inps, entry.sym_spec)
    return inps, true_extents


def _run_entry(entry: CacheEntry, flat_inps: tuple, prepared=None) -> Any:
    inps, true_extents = prepared if prepared is not None else _prepare_inputs(entry, flat_inps)
    if entry.sym_spec is not None:
        import numpy as np

        # Runtime true extents feed the reduction masks (transforms/padmask.py)
        # — and the de-opt ladder's L3 exact-shape peak prediction for THIS
        # call, should this dispatch OOM (resilience/deopt.py).
        entry.last_true_extents = true_extents
        inps = inps + [
            np.asarray(true_extents[cid], np.int32) for cid in entry.sym_spec.mask_classes
        ]
    if entry.needs_rng:
        inps = inps + [_next_key()]
    if getattr(entry, "_hlo_audit_pending", False):
        # First run of a fresh entry: snapshot the staged callable's input
        # avals so the post-compile HLO auditor (_maybe_hlo_audit) can
        # re-lower without holding references to (possibly donated) buffers.
        entry._hlo_audit_pending = False
        try:
            import jax

            entry.hlo_audit_avals = tuple(
                jax.ShapeDtypeStruct(tuple(x.shape), x.dtype) for x in inps
            )
        except Exception:  # noqa: BLE001 — advisory capture only
            entry.hlo_audit_avals = None
    if chaos_mod.enabled():
        # Chaos seams: injected device OOM (recovered by the de-opt ladder)
        # and the collective-straggler delay. One contextvar probe when
        # chaos is inactive.
        trc = entry.computation_traces[-1] if entry.computation_traces else None
        chaos_mod.run_seam(
            has_collectives=bool(
                trc is not None and int(trc.tags.get("collective_bytes") or 0)
            ),
            deopt_level=entry.stats.degradation_level,
        )
    if watchdog_mod.active_timeout() is not None:
        # Collective watchdog (ISSUE 9): a dispatch whose trace contains
        # dist_prims collectives runs under the configured timeout, so a
        # peer that stops participating raises a typed CollectiveTimeoutError
        # naming the pending trace lines instead of hanging this host
        # forever. One dict probe per call when no timeout is configured.
        if entry.collective_lines is None:
            from thunder_tpu.distributed import prims as dist_prims

            trc = entry.computation_traces[-1] if entry.computation_traces else None
            entry.collective_lines = tuple(dist_prims.collective_trace_lines(trc))
        if entry.collective_lines:
            cert = entry.schedule_certificate
            out = watchdog_mod.guard_call(
                entry.computation_fn, tuple(inps),
                fn_name=getattr(entry.computation_fn, "__name__", "computation"),
                trace_lines=entry.collective_lines,
                schedule=cert.axis_labels() if cert is not None else None,
            )
        else:
            out = entry.computation_fn(*inps)
    else:
        out = entry.computation_fn(*inps)
    if entry.sym_spec is not None:
        out = jaxex.crop_to_extents(out, entry.sym_spec, true_extents)
    if entry.on_nan is not None and not deopt_mod.outputs_finite(out):
        # Post-step isfinite guard (jit(on_nan=...)), checked on the CROPPED
        # output — padding lanes of a bucketed entry may legitimately hold
        # inf/NaN (e.g. 1/0 on zero-padded rows) that the crop discards.
        # Attribution re-runs the SAME (padded) inputs instrumented.
        deopt_mod.handle_nonfinite(entry, inps, entry.on_nan)
    if entry.torch_facing:
        import jax

        out = tree_map(lambda x: bridge.to_torch(x) if isinstance(x, jax.Array) else x, out)
    return out


def _hlo_audit_enabled() -> bool:
    import os

    return os.environ.get("THUNDER_TPU_HLO_AUDIT", "1").strip().lower() not in (
        "0", "false", "off",
    )


def _bucket_pad_fractions(entry: CacheEntry) -> dict:
    """Bucket class label → padded-away fraction (1 − true/padded extent) of
    a symbolic entry's last dispatch — the ``hlo.padding-waste`` rule input."""
    spec = entry.sym_spec
    true_ext = getattr(entry, "last_true_extents", None)
    if spec is None or not true_ext:
        return {}
    out: dict = {}
    for cid, (li, d, _lo, hi) in spec.classes.items():
        t = true_ext.get(cid)
        if t is None or hi <= 0:
            continue
        out[f"leaf{li}.dim{d}"] = round(max(0.0, 1.0 - t / hi), 4)
    return out


def _maybe_hlo_audit(entry: CacheEntry, log=None) -> None:
    """Post-``xla_compile`` compile phase: audit the entry's compiled HLO
    (analysis/hlo_audit.py) — partitioner-inserted collectives, layout
    copies, host transfers, static exposed-wire — and attach the report to
    the entry and the extrace tags (``hlo_audit``), where the advisory
    ``hlo.*`` verifier rules read it. Advisory-safe by contract: any
    auditor failure emits a ``sharp_edge`` and never breaks the compile;
    ``THUNDER_TPU_HLO_AUDIT=0`` is the kill switch."""
    import time as _time

    avals = getattr(entry, "hlo_audit_avals", None)
    jfn = entry.computation_fn
    if not avals or jfn is None or not hasattr(jfn, "lower"):
        return
    t0 = _time.perf_counter()
    try:
        from thunder_tpu.analysis import hlo_audit as _hlo_audit_mod

        text = jfn.lower(*avals).compile().as_text()
        acquire_s = _time.perf_counter() - t0
        report = _hlo_audit_mod.audit_hlo(text, pad_fractions=_bucket_pad_fractions(entry))
        total_s = _time.perf_counter() - t0
        report.audit_s = total_s
        entry.hlo_audit = report
        if entry.computation_traces:
            entry.computation_traces[-1].tags["hlo_audit"] = report
        entry.stats.phases["hlo_audit"] = total_s
        # Optional fields by PRESENCE (PR 10 discipline): an absent field
        # means the audit had nothing to say there, not zero.
        extra: dict = dict(
            hlo_ops=report.n_ops,
            hlo_acquire_s=round(acquire_s, 6),
            hlo_analyze_s=round(total_s - acquire_s, 6),
        )
        if report.sites:
            extra["hlo_collectives"] = len(report.sites)
            extra["hlo_inserted_collectives"] = report.inserted_collectives
            extra["hlo_exposed_pct"] = round(report.exposed_pct, 2)
        if report.host_transfers:
            extra["hlo_host_transfers"] = report.host_transfers
        _record_compile_phase(entry.compile_id, "hlo_audit", total_s, log=log, **extra)
    except Exception as e:  # noqa: BLE001 — the auditor must never break a compile
        sharp_edge(f"hlo_audit failed (advisory): {type(e).__name__}: {e}")


# =============================================================================
# Dispatch: O(1) fast path + symbolic-values (bucketed) compilation
# =============================================================================


def _leaf_meta(flat: list) -> tuple:
    """Hashable per-leaf metadata covering everything the prologue guards:
    tensor (shape, dtype, device kind, requires_grad, framework), number
    type+value, string value, None. Opaque objects key by type only — the
    prologue cannot guard them either (sharp edge)."""
    parts = []
    for x in flat:
        if bridge.is_concrete_tensor(x):
            shape, dev, dt, rg = bridge.tensor_metadata(x)
            parts.append(
                ("T", tuple(int(s) for s in shape), str(dt), str(dev).split(":")[0],
                 rg, bridge.framework_of(x))
            )
        elif isinstance(x, (bool, int, float, complex, str)) or x is None:
            parts.append((type(x).__name__, x))
        else:
            parts.append(("O", type(x).__name__))
    return tuple(parts)


_FAST_CACHE_MAX = 4096


def _probe_entries(cs: CompileStats, args: tuple, kwargs: dict):
    """Full prologue scan, newest entries first (the slow path): each probe
    executes the candidate's prologue; GuardFailure is the controlled miss
    signal (reference: thunder/__init__.py:409-447). Returns (entry,
    flat_inps, prepared) — ``prepared`` is the converted/padded input set
    when value guards forced preparing it (reused by _run_entry)."""
    from thunder_tpu.core.concrete import check_value_guards

    for entry in reversed(cs.cache_entries):
        cs.prologue_runs += 1
        entry.stats.prologue_runs += 1
        try:
            flat_inps = entry.prologue_fn(*args, **kwargs)
        except GuardFailure:
            # Controlled signal from a CHECK_* prim: this entry's guards
            # don't match → probe the next entry. Any other exception is a
            # genuine bug (in guard code or user input) and propagates.
            entry.stats.guard_fails += 1
            continue
        prepared = None
        if entry.value_guards:
            # The guard subprograms were staged on the (padded) trace shapes.
            prepared = _prepare_inputs(entry, flat_inps)
            if not check_value_guards(entry.value_guards, prepared[0]):
                entry.stats.guard_fails += 1
                continue
        return entry, flat_inps, prepared
    return None, None, None


def _symbolic_spec_for_call(cd: CompileData, cs: CompileStats, args: tuple, kwargs: dict):
    """Which dims to lift symbolic for THIS compile, or None for an exact
    entry. Explicit ``symbolic_dims`` marks apply from the first call;
    ``"auto"`` (the default) marks the dims observed VARYING against a cached
    entry of the same shape class — parameters never vary, so they are never
    padded, while batch/sequence dims self-discover."""
    from thunder_tpu.core.bucketing import make_symbolic_spec

    flat, treedef = tree_flatten((args, kwargs))
    tensor_pos = [i for i, x in enumerate(flat) if bridge.is_concrete_tensor(x)]
    shapes = {li: tuple(int(s) for s in flat[i].shape) for li, i in enumerate(tensor_pos)}

    explicit = cd.compile_options.get("symbolic_dims", "auto")
    if explicit is None or explicit == "auto":
        marks_dims = _marks_from_variation(cs, _leaf_meta(flat), treedef)
    elif explicit == "all":
        marks_dims = {li: tuple(range(len(s))) for li, s in shapes.items()}
    elif isinstance(explicit, dict):
        marks_dims = {int(li): tuple(ds) for li, ds in explicit.items()}
    elif isinstance(explicit, (tuple, list)):
        marks_dims = {
            li: tuple(d for d in explicit if d < len(s)) for li, s in shapes.items()
        }
        marks_dims = {li: ds for li, ds in marks_dims.items() if ds}
    else:
        raise ValueError(
            f"symbolic_dims: expected 'auto', 'all', a dict of leaf->dims, or a "
            f"dim tuple; got {explicit!r}"
        )
    marks_dims = {li: ds for li, ds in marks_dims.items() if ds}
    if not marks_dims:
        return None
    # jit() resolves the policy whenever cache_option is SYMBOLIC_VALUES —
    # the only path that reaches this function.
    return make_symbolic_spec(marks_dims, shapes, cd.compile_options["_bucket_policy"])


def _marks_from_variation(cs: CompileStats, cur_meta: tuple, treedef) -> dict:
    """Compare the call's leaf metadata against cached entries of the same
    shape class; the dims whose extents differ (plus the entry's existing
    symbolic dims) become the new entry's marks."""
    for entry in reversed(cs.cache_entries):
        if entry.treedef != treedef or len(entry.leaf_meta) != len(cur_meta):
            continue
        entry_marks = entry.sym_spec.marks if entry.sym_spec is not None else {}
        marks: dict[int, tuple] = {}
        li = -1
        ok = True
        for cm, em in zip(cur_meta, entry.leaf_meta):
            if cm[0] == "T" or em[0] == "T":
                if cm[0] != "T" or em[0] != "T":
                    ok = False
                    break
                li += 1
                if cm[2:] != em[2:] or len(cm[1]) != len(em[1]):
                    ok = False  # dtype/device/rank class differs: not this entry
                    break
                inherited = set(entry_marks.get(li, {}).keys())
                diff = {d for d in range(len(cm[1])) if cm[1][d] != em[1][d]}
                dims = inherited | diff
                if dims:
                    marks[li] = tuple(sorted(dims))
            elif cm != em:
                ok = False
                break
        if ok and marks:
            return marks
    return {}


def _pad_example(args: tuple, kwargs: dict, sym_spec) -> tuple[tuple, dict]:
    """Zero-pad the example inputs up to the spec's bucket ceilings — the
    shapes the symbolic trace is acquired on."""
    flat, treedef = tree_flatten((args, kwargs))
    tensor_pos = [i for i, x in enumerate(flat) if bridge.is_concrete_tensor(x)]
    for li, dims in sym_spec.marks.items():
        i = tensor_pos[li]
        flat[i] = _pad_concrete(flat[i], {d: hi for d, (_lo, hi, _cid) in dims.items()})
    return tree_unflatten(treedef, flat)


def _pad_concrete(x: Any, targets: dict):
    widths = [(0, 0)] * len(x.shape)
    padded = False
    for d, t in targets.items():
        delta = int(t) - int(x.shape[d])
        if delta > 0:
            widths[d] = (0, delta)
            padded = True
    if not padded:
        return x
    if bridge.is_torch_tensor(x):
        import torch

        for d, (_z, delta) in enumerate(widths):
            if delta:
                pad_shape = list(x.shape)
                pad_shape[d] = delta
                x = torch.cat(
                    [x, torch.zeros(pad_shape, dtype=x.dtype, device=x.device)], dim=d
                )
        return x
    import numpy as np

    if isinstance(x, np.ndarray):
        return np.pad(x, widths)
    import jax.numpy as jnp

    return jnp.pad(x, widths)


def _sum_phases(entries) -> dict:
    out: dict[str, float] = {}
    for e in entries:
        for phase, v in e.stats.phases.items():
            if isinstance(v, (int, float)):
                out[phase] = out.get(phase, 0.0) + v
    return {k: round(v, 6) for k, v in sorted(out.items())}


# Live jitted functions, weakly held — the ops plane's /debug/state reads
# each one's cache/compile summary without the operator having to hold a
# handle (observability/opsplane.py). WeakSet: registration must never be
# the thing keeping a dropped function's cache entries alive.
import weakref as _weakref

_live_functions: "_weakref.WeakSet" = _weakref.WeakSet()


def live_function_state() -> list[dict]:
    """Per-function cache/compile summaries across every live jitted
    function — :func:`cache_info` trimmed to what an operator scans (entry
    lists collapsed to counts + per-entry de-opt levels)."""
    out = []
    for f in list(_live_functions):
        try:
            info = cache_info(f)
        except Exception:
            continue
        entries = info.pop("entries", [])
        info["n_entries"] = len(entries)
        info["entry_degradation_levels"] = [
            e.get("degradation_level", 0) for e in entries
        ]
        info["fn"] = getattr(f, "__name__", "?")
        info["trace_seconds"] = round(info.get("trace_seconds") or 0.0, 4)
        info["first_run_seconds"] = round(info.get("first_run_seconds") or 0.0, 4)
        out.append(info)
    return sorted(out, key=lambda i: str(i.get("fn")))


def cache_info(fn: Callable) -> dict:
    """Cache observability for a thunder_tpu-compiled function: aggregate and
    per-entry hit/miss/recompile counters plus cumulative trace/first-run
    seconds (ISSUE 2; printed by ``examine.lint``'s summary)."""
    cs = _get_cs(fn)
    cd = getattr(fn, "_lc_cd", None)
    return {
        "cache_option": cd.cache_option.name.lower() if cd is not None else None,
        "calls": cs.calls,
        "hits": cs.cache_hits,
        "misses": cs.cache_misses,
        "fast_hits": cs.fast_hits,
        "slow_hits": cs.slow_hits,
        "prologue_runs": cs.prologue_runs,
        "compiles": cs.compile_count,
        "recompiles": cs.recompile_count,
        "trace_seconds": cs.trace_seconds,
        "first_run_seconds": cs.first_run_seconds,
        "cache_lookup_us_total": cs.cache_lookup_ns / 1e3,
        # Compile-phase rollup across entries (seconds per phase): the
        # decomposition of trace_seconds + first_run_seconds the
        # compile_phase events record per compile (docs/observability.md).
        "compile_phase_seconds": _sum_phases(cs.cache_entries),
        # De-opt ladder position new compiles use (per-entry levels are in
        # each entry's stats below) — resilience/deopt.py.
        "degradation_level": deopt_mod.current_level(cd) if cd is not None else 0,
        "entries": [
            dict(
                index=i,
                symbolic=(e.sym_spec is not None),
                buckets=(e.sym_spec.describe() if e.sym_spec is not None else "exact"),
                **e.stats.as_dict(),
            )
            for i, e in enumerate(cs.cache_entries)
        ],
    }


# =============================================================================
# jit()
# =============================================================================


def _ensure_runtime() -> None:
    """Configure JAX for torch-faithful dtype semantics, once, at first use.

    ``jax_enable_x64`` is required so int64 indices and requested float64
    round-trip exactly (the hot compute path is explicitly bf16/f32 in
    traces, so this costs nothing on TPU). Done lazily here — not at import
    — so merely importing thunder_tpu does not mutate an unrelated host
    process's JAX configuration.
    """
    import jax

    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)

    # Tap jax's compilation-cache monitoring events so first-run compile
    # spans can say "hit" (deserialize) vs "miss" (real backend compile).
    _install_jax_cache_listener()

    # Ops plane autostart (ISSUE 15): THUNDER_TPU_OPS_PORT arms the live
    # endpoints + flight recorder with zero code changes — the scheduler
    # exports one port per process and the fleet is scrapeable. One env
    # probe here; nothing is imported (let alone served) without it.
    import os as _os

    if _os.environ.get("THUNDER_TPU_OPS_PORT", "").strip():
        from thunder_tpu.observability import opsplane as _opsplane

        _opsplane.maybe_autostart()

    # Persistent XLA compilation cache (reference analogue: nvFuser's
    # descriptor-keyed compiled-fusion cache, SURVEY.md §2.2 — here the
    # cache survives processes, so warm-start recompiles of the same
    # program are file reads, not 80-second XLA runs). Opt out with
    # THUNDER_TPU_NO_COMPILE_CACHE=1. A user-configured cache (dir already
    # set, or the JAX_PERSISTENT_CACHE_* env knobs) is respected untouched.
    import os

    if not os.environ.get("THUNDER_TPU_NO_COMPILE_CACHE"):
        try:
            cache_dir = jax.config.jax_compilation_cache_dir
            if not cache_dir:
                cache_dir = os.environ.get(
                    "THUNDER_TPU_COMPILE_CACHE", os.path.expanduser("~/.cache/thunder_tpu_xla")
                )
                os.makedirs(cache_dir, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", cache_dir)
                _set_unless_user_configured(
                    jax, "jax_persistent_cache_min_compile_time_secs", 1.0
                )
                _set_unless_user_configured(
                    jax, "jax_persistent_cache_min_entry_size_bytes", 0
                )
            if _cache_dir_logged["dir"] != cache_dir:
                # First sight of this cache dir in the process: the chaos
                # cache_corrupt seam may truncate an entry here (no-op unless
                # armed), then the sweep removes corrupted/truncated entries
                # (torn writes from a crashed or disk-full predecessor) so a
                # poisoned entry recompiles instead of crashing the load
                # (resilience/compile_cache.py).
                from thunder_tpu.resilience.compile_cache import sweep_corrupt_entries

                chaos_mod.corrupt_cache_seam(cache_dir)
                sweep_corrupt_entries(cache_dir)
            _log_cache_dir_once(cache_dir)
        except Exception:
            pass  # older jax without the persistent-cache config


def _set_unless_user_configured(jax_mod, name: str, value) -> None:
    """Apply our persistent-cache tuning only when the user has not already
    configured the knob — via the env var jax reads, or programmatically.
    The values we set equal jax's own defaults, so a current value that
    differs from ours can only mean the user changed it: respect it."""
    import os

    if os.environ.get(name.upper()) is not None:
        return
    if getattr(jax_mod.config, name) != value:
        return
    jax_mod.config.update(name, value)


_cache_dir_logged = {"dir": None}


def _log_cache_dir_once(cache_dir: str) -> None:
    if _cache_dir_logged["dir"] == cache_dir:
        return
    _cache_dir_logged["dir"] = cache_dir
    import logging

    logging.getLogger("thunder_tpu").info("persistent XLA compile cache: %s", cache_dir)


def jit(
    fn: Optional[Callable] = None,
    *,
    executors: Optional[Sequence] = None,
    cache: str | CACHE_OPTIONS = CACHE_OPTIONS.CONSTANT_VALUES,
    sharp_edges: str | SHARP_EDGES_OPTIONS = SHARP_EDGES_OPTIONS.ALLOW,
    disable_jit_staging: bool = False,
    debug_checks: Optional[bool] = None,
    events: Optional[str] = None,
    debug_watch: Optional[str] = None,
    instrument: Any = None,
    chaos: Any = None,
    on_nan: Optional[str] = None,
    **compile_options,
) -> Callable:
    """Compile ``fn`` for TPU execution (reference: thunder/__init__.py `jit:299`).

    ``fn`` may be written against thunder_tpu's torch-mirror language, be a
    real ``torch.nn.Module``/torch function (acquired via the torch
    frontend), or operate on jax/numpy arrays directly.

    ``debug_checks=True`` runs the static trace verifier (thunder_tpu/analysis)
    after every transform pass, raising ``TraceVerificationError`` attributed
    to the pass that broke an invariant; ``False`` disables it; ``None``
    (default) defers to the ``THUNDER_TPU_CHECKS`` environment variable.

    ``cache="symbolic values"`` enables shape-polymorphic caching: marked
    tensor dims are lifted into bucket guards (``lo < d <= hi``) instead of
    exact extents, inputs are zero-padded up to the bucket ceiling at
    dispatch, reductions over padded dims are masked against the runtime
    true extents, and outputs are cropped back — one trace + one XLA compile
    per bucket. Options: ``symbolic_dims`` ("auto" = mark dims observed
    varying, "all", a ``{tensor_leaf_index: (dims...)}`` dict, or a dim
    tuple) and ``buckets`` (e.g. ``{"batch": "pow2", "seq": 128}``; also the
    ``THUNDER_TPU_BUCKETS`` env var). See docs/caching.md.

    Observability (docs/observability.md):

    - ``events="<path>"`` writes this function's compile/cache/bucket events
      as JSONL to ``path`` (overriding the process-wide ``THUNDER_TPU_EVENTS``
      log for this function);
    - ``debug_watch="nan"`` (or ``"inf"``/``"nan+inf"``) instruments every
      bound symbol and raises :class:`~thunder_tpu.observability.instrument.
      NaNWatchError` — with the offending BoundSymbol name, generated trace
      line, and pass provenance — the moment an output turns non-finite;
    - ``instrument`` takes ``"time"``, ``"memory"``, a custom
      ``InstrumentationHook``, a bare ``fn(rec, outputs)`` callable, or a
      list of those. Instrumented entries run unstaged (op-by-op); with
      neither option the entry stages whole under XLA as usual.

    Resilience (docs/robustness.md):

    - ``chaos`` takes a chaos spec string (or ``ChaosConfig``) activating
      deterministic fault injection for this function's compiles and runs —
      the programmatic spelling of ``THUNDER_TPU_CHAOS``;
    - ``on_nan`` arms a cheap post-step isfinite guard over the outputs:
      ``"raise"`` raises :class:`~thunder_tpu.resilience.NonFiniteOutputError`,
      ``"rerun-instrumented"`` first re-runs the failing step once under a
      NaN watcher so the error names the producing op, ``"warn"`` warns and
      returns the result.
    """
    if fn is None:
        return functools.partial(
            jit,
            executors=executors,
            cache=cache,
            sharp_edges=sharp_edges,
            disable_jit_staging=disable_jit_staging,
            debug_checks=debug_checks,
            events=events,
            debug_watch=debug_watch,
            instrument=instrument,
            chaos=chaos,
            on_nan=on_nan,
            **compile_options,
        )

    _ensure_runtime()

    # autocast option → a trace transform running before grad/claiming
    # (reference: thunder/__init__.py:543 applies autocast pre-split).
    ac = compile_options.pop("autocast", None)
    if ac:
        from thunder_tpu.transforms.autocast import autocast as _ac_transform

        ac_dtype = dtypes.to_dtype(ac) if not isinstance(ac, bool) else dtypes.bfloat16
        tts = tuple(compile_options.get("_trace_transforms", ()))
        compile_options["_trace_transforms"] = (lambda trc: _ac_transform(trc, ac_dtype),) + tts

    # torch nn.Module → ThunderModule wrapper (the torch frontend).
    _torch = None
    try:
        import torch as _torch
    except ImportError:
        pass
    if _torch is not None and isinstance(fn, _torch.nn.Module):
        if debug_watch or instrument is not None:
            raise NotImplementedError(
                "debug_watch/instrument are not yet supported on the torch "
                "nn.Module frontend — jit the functional forward instead"
            )
        if chaos is not None or on_nan is not None:
            raise NotImplementedError(
                "chaos/on_nan are not yet supported on the torch nn.Module "
                "frontend — use THUNDER_TPU_CHAOS for process-wide chaos, or "
                "jit the functional forward instead"
            )
        from thunder_tpu.frontend.module import thunder_module

        return thunder_module(
            fn, executors=executors, cache=cache, sharp_edges=sharp_edges,
            disable_jit_staging=disable_jit_staging, debug_checks=debug_checks,
            events=events, **compile_options
        )

    cache_option = resolve_cache_option(cache)
    if cache_option is CACHE_OPTIONS.SYMBOLIC_VALUES:
        # Resolve the shape-bucketing policy once, at jit() time: defaults
        # (pow2 batch, 128-multiple seq) <- THUNDER_TPU_BUCKETS <- buckets=.
        from thunder_tpu.core.bucketing import BucketPolicy

        compile_options["_bucket_policy"] = BucketPolicy.resolve(
            compile_options.pop("buckets", None)
        )
    else:
        compile_options.pop("buckets", None)

    cd = CompileData(
        fn=fn,
        executors_list=resolve_executors(executors),
        cache_option=cache_option,
        sharp_edges=resolve_sharp_edges_option(sharp_edges),
        disable_jit_staging=disable_jit_staging,
        compile_options=dict(
            compile_options, debug_checks=debug_checks,
            debug_watch=debug_watch, instrument=instrument,
            on_nan=deopt_mod.resolve_on_nan(on_nan),
        ),
    )
    # Per-function chaos config (resilience/chaos.py): parsed once here,
    # activated around every dispatch of this function.
    cd._chaos = chaos_mod.resolve(chaos)
    if events:
        cd._event_log = obs_events.log_for_path(events)
    cs = CompileStats()

    @functools.wraps(fn)
    def fn_(*args, **kwargs):
        log = getattr(cd, "_event_log", None)
        if cd._chaos is None and log is None:
            return _dispatch(args, kwargs)
        import contextlib

        # The function's own event log and chaos config cover the WHOLE
        # dispatch (not just the compile scope): fault injections, demotions,
        # and de-opt events fire at run time and must land in the same log
        # their compile events do.
        with contextlib.ExitStack() as stack:
            if log is not None:
                stack.enter_context(obs_events.event_scope(log))
            if cd._chaos is not None:
                stack.enter_context(chaos_mod.chaos_scope(cd._chaos))
            return _dispatch(args, kwargs)

    def _dispatch(args: tuple, kwargs: dict):
        from thunder_tpu.core.concrete import check_value_guards

        cs.calls += 1
        cs.last_trace_host_start = timer_ns()
        cs.last_trace_cache_start = timer_ns()
        co = cd.cache_option
        entry = None
        flat_inps = None
        prepared = None
        key = None
        hit_kind = "hit"
        if co in (CACHE_OPTIONS.CONSTANT_VALUES, CACHE_OPTIONS.SYMBOLIC_VALUES):
            flat, treedef = tree_flatten((args, kwargs))
            key = (treedef, _leaf_meta(flat))

        if co is CACHE_OPTIONS.SAME_INPUT and cs.cache_entries:
            # SAME_INPUT short-circuits to the NEWEST entry: the user asserts
            # every call repeats the first one's metadata AND values, so no
            # probing (and no value-guard re-evaluation) happens — previously
            # a value-guard miss could compile a second entry and the reversed
            # scan could then bounce between specializations.
            entry = cs.cache_entries[-1]
            cs.prologue_runs += 1
            entry.stats.prologue_runs += 1
            flat_inps = entry.prologue_fn(*args, **kwargs)
            hit_kind = "same_input"
        elif key is not None and cs.cache_entries:
            # Two-tier dispatch. Tier 1: O(1) key hit — (tree structure, per
            # leaf rank/shape/dtype/device/value metadata) → entry, learned on
            # the first slow hit; no prologue executes on the warm path.
            cand = cs.fast_cache.get(key)
            if cand is not None:
                leaves = [x for x in flat if bridge.is_concrete_tensor(x)]
                guards_ok = True
                if cand.value_guards:
                    prepared = _prepare_inputs(cand, leaves)
                    guards_ok = check_value_guards(cand.value_guards, prepared[0])
                if guards_ok:
                    entry = cand
                    flat_inps = leaves
                    cs.fast_hits += 1
                    entry.stats.fast_hits += 1
                    hit_kind = "fast"
                else:
                    prepared = None
            if entry is None:
                # Tier 2: full prologue scan, newest first; a hit teaches the
                # fast path this key.
                entry, flat_inps, prepared = _probe_entries(cs, args, kwargs)
                if entry is not None:
                    cs.slow_hits += 1
                    hit_kind = "slow"
                    if len(cs.fast_cache) > _FAST_CACHE_MAX:
                        cs.fast_cache.clear()
                    cs.fast_cache[key] = entry

        if entry is not None:
            cs.cache_hits += 1
            entry.stats.hits += 1
            cs.last_trace_cache_stop = timer_ns()
            cs.cache_lookup_ns += cs.last_trace_cache_stop - cs.last_trace_cache_start
            try:
                result = _run_entry(entry, flat_inps, prepared)
            except Exception as e:
                # Resilience (resilience/deopt.py): a kernel/OOM failure on a
                # warm entry evicts it, quarantines or de-opts, and falls
                # through to the recompile path below. Anything unrecognized
                # propagates untouched.
                if not deopt_mod.handle_run_failure(e, cd, cs, entry, 0):
                    # Unhandled dispatch fault: the flight ring's preceding
                    # context dumps before the raise unwinds (ISSUE 15;
                    # no-op one-probe when the ops plane is off).
                    obs_events.flight_dump("dispatch_fault")
                    raise
                entry = None
                # Re-account the call as a miss (it recompiles below), and
                # don't bill the failed run's wall time as cache-lookup time.
                cs.cache_hits -= 1
                cs.last_trace_cache_start = timer_ns()
            if entry is not None:
                if entry.epilogue_fn is not None:
                    result = entry.epilogue_fn(args, kwargs, flat_inps, result)
                cs.last_trace_host_stop = timer_ns()
                if obsm.enabled():
                    # Single flag check on the warm path when metrics are off
                    # (BENCHMARKS.md budgets: <1% off, <5% on).
                    obsm.CACHE_HITS.inc(kind=hit_kind)
                    obsm.CACHE_LOOKUP_US.observe(
                        (cs.last_trace_cache_stop - cs.last_trace_cache_start) / 1e3
                    )
                    obsm.DISPATCH_US.observe(
                        (cs.last_trace_host_stop - cs.last_trace_host_start) / 1e3
                    )
                return result
        cs.last_trace_cache_stop = timer_ns()
        cs.cache_lookup_ns += cs.last_trace_cache_stop - cs.last_trace_cache_start

        cs.cache_misses += 1
        if obsm.enabled():
            obsm.CACHE_MISSES.inc()
        # emit_event: fn_ already routed the per-function log (event_scope),
        # so the active log is the right sink — and the ops-plane taps see
        # the miss even with no log configured (ISSUE 15).
        obs_events.emit_event(
            "cache_miss", fn=getattr(cd.fn, "__name__", repr(cd.fn)), call=cs.calls
        )
        # Compile + first run under the recovery driver: a failure that
        # classifies as a kernel fault demotes the claimed executor and
        # re-claims; a compile failure/OOM climbs the de-opt ladder; both
        # retry bounded with backoff. Unrecognized failures propagate on the
        # first throw.
        attempt = 0
        while True:
            try:
                entry = _compile_entry(cd, cs, args, kwargs)
            except Exception as e:
                if deopt_mod.handle_compile_failure(e, cd, cs, attempt):
                    attempt += 1
                    continue
                obs_events.flight_dump("dispatch_fault")
                raise
            if key is not None:
                if len(cs.fast_cache) > _FAST_CACHE_MAX:
                    cs.fast_cache.clear()
                cs.fast_cache[key] = entry
            entry.stats.hits += 1
            cs.prologue_runs += 1
            entry.stats.prologue_runs += 1
            flat_inps = entry.prologue_fn(*args, **kwargs)
            # Aval capture is unconditional (one-time, bytes-cheap) so
            # examine.hlo_report can audit on demand even when the
            # compile-time phase is disabled; only the audit itself gates
            # on THUNDER_TPU_HLO_AUDIT.
            entry._hlo_audit_pending = True
            jax_compile0 = _jax_cache_counts()
            run_start = timer_ns()
            try:
                result = _run_entry(entry, flat_inps)
            except Exception as e:
                if deopt_mod.handle_run_failure(e, cd, cs, entry, attempt):
                    if key is not None:
                        cs.fast_cache.clear()
                    attempt += 1
                    continue
                obs_events.flight_dump("dispatch_fault")
                raise
            break
        entry.stats.first_run_s = (timer_ns() - run_start) / 1e9
        cs.first_run_seconds += entry.stats.first_run_s
        # Persistent-XLA-cache verdict of the first run: "hit" means those
        # seconds were a deserialize, "miss" a real backend compile — the
        # phase split that tells a cold-start regression from a cache-key
        # change (docs/observability.md, compile-phase spans). The backend-
        # compile and cache-retrieval sub-spans come from jax's own
        # monitoring durations, so the wall total decomposes further.
        jax_compile1 = _jax_cache_counts()
        cache_verdict = None
        if jax_compile1["misses"] > jax_compile0["misses"]:
            cache_verdict = "miss"
        elif jax_compile1["hits"] > jax_compile0["hits"]:
            cache_verdict = "hit"
        entry.stats.phases["xla_compile"] = entry.stats.first_run_s
        if cache_verdict:
            entry.stats.phases["persistent_cache"] = cache_verdict
        _entry_log = getattr(cd, "_event_log", None)
        for sub, key in (("xla_backend_compile", "backend_compile_s"),
                         ("persistent_cache_get", "cache_get_s")):
            delta = jax_compile1[key] - jax_compile0[key]
            if delta > 0.0:
                entry.stats.phases[sub] = delta
                _record_compile_phase(entry.compile_id, sub, delta, log=_entry_log)
        _record_compile_phase(
            entry.compile_id, "xla_compile", entry.stats.first_run_s,
            log=_entry_log, cache=cache_verdict,
        )
        if _hlo_audit_enabled():
            _maybe_hlo_audit(entry, log=_entry_log)
        if obsm.enabled():
            # The entry's first run is where jax.jit actually compiles: this
            # is the end-to-end XLA compile cost per compile class — the
            # total that can silently double while per-pass ms stays flat.
            obsm.XLA_COMPILE_S.observe(
                entry.stats.first_run_s,
                cls="bucketed" if entry.sym_spec is not None else "exact",
            )
        if entry.epilogue_fn is not None:
            result = entry.epilogue_fn(args, kwargs, flat_inps, result)
        cs.last_trace_host_stop = timer_ns()
        return result

    fn_._lc_cd = cd
    fn_._lc_cs = cs
    _live_functions.add(fn_)  # ops-plane /debug/state enumeration
    return fn_


# =============================================================================
# Autodiff entry points (reference: thunder/__init__.py `grad:888`)
# =============================================================================


def grad(fn: Optional[Callable] = None, **jit_kwargs) -> Callable:
    """Compile ``fn`` (a scalar-loss function) into a function returning
    gradients w.r.t. its float tensor inputs, staged fw+bw under one XLA jit.

    Grads are returned as a tuple ordered like the function's float tensor
    leaves (pytree inputs are flattened in argument order).

    ``grad(vmap(f))`` composes: the pullback of the batched program is taken
    with ones cotangents on every output — the reference's value_and_grad
    semantics for non-scalar outputs (transforms.py:3704 seeds
    ``ones_like``)."""
    if fn is None:
        return functools.partial(grad, **jit_kwargs)
    if getattr(fn, "_lc_vmap_spec", None) is not None:
        return _grad_of_vmapped(fn, return_value=False, jit_kwargs=jit_kwargs)
    from thunder_tpu.transforms.autodiff import grad_transform

    return jit(fn, _trace_transforms=(lambda trc: grad_transform(trc, return_value=False),), **jit_kwargs)


def value_and_grad(fn: Optional[Callable] = None, **jit_kwargs) -> Callable:
    """Like :func:`grad` but returns ``(value, grads)``."""
    if fn is None:
        return functools.partial(value_and_grad, **jit_kwargs)
    if getattr(fn, "_lc_vmap_spec", None) is not None:
        return _grad_of_vmapped(fn, return_value=True, jit_kwargs=jit_kwargs)
    from thunder_tpu.transforms.autodiff import grad_transform

    return jit(fn, _trace_transforms=(lambda trc: grad_transform(trc, return_value=True),), **jit_kwargs)


def _grad_of_vmapped(vfn: Callable, *, return_value: bool,
                     jit_kwargs: Optional[dict] = None) -> Callable:
    """grad/value_and_grad of a :func:`vmap`-ed function.

    The batched staged program's pullback is evaluated with ones cotangents
    (reference value_and_grad semantics for non-scalar outputs) w.r.t. the
    FLOAT tensor leaves, all under one jax.jit. Staging is cached on input
    metadata like vmap itself. Of jit()'s options only ``executors`` applies
    on this path (there is no prologue/cache machinery to configure) — any
    other option is rejected loudly rather than silently dropped."""
    import jax
    import jax.numpy as jnp

    jit_kwargs = dict(jit_kwargs or {})
    user_executors = jit_kwargs.pop("executors", None)
    if jit_kwargs:
        raise ValueError(
            f"grad(vmap(f)) supports only the 'executors' option; got "
            f"{sorted(jit_kwargs)}"
        )
    executor_stacks = (
        (user_executors, ["jax"]) if user_executors is not None else (None, ["jax"])
    )

    spec = vfn._lc_vmap_spec
    inner_fn, inner_tts = _unwrap_compiled(spec["fn"])
    in_axes, out_axes = spec["in_axes"], spec["out_axes"]
    cache: dict = {}
    cs = CompileStats()

    def wrapper(*args, **kwargs):
        cs.calls += 1
        axes, flat_axes, flat_args = _vmap_flatten(args, kwargs, in_axes)
        diff_idx = tuple(
            i for i, x in enumerate(flat_args) if jnp.issubdtype(x.dtype, jnp.floating)
        )

        key = _meta_key(
            tree_flatten((args, kwargs))[0], extra=(tuple(flat_axes), out_axes, return_value)
        )
        staged = cache.get(key)
        if staged is not None:
            cs.cache_hits += 1
            result = staged(*flat_args)
            return result if return_value else result[1]
        cs.cache_misses += 1

        example = _vmap_example(args, axes)
        for ex_list in executor_stacks:
            flat_fn = _staged_flat_fn(
                inner_fn, example, kwargs, executors=ex_list, trace_transforms=inner_tts
            )
            batched = jax.vmap(flat_fn, in_axes=flat_axes, out_axes=out_axes)

            def vg(*flat, _batched=batched):
                def diff_only(*diff):
                    full = list(flat)
                    for i, d in zip(diff_idx, diff):
                        full[i] = d
                    return _batched(*full)

                out, pullback = jax.vjp(diff_only, *[flat[i] for i in diff_idx])
                cts = tree_map(jnp.ones_like, out)
                grads = pullback(cts)
                return out, grads

            staged = jax.jit(vg)
            try:
                result = staged(*flat_args)
            except Exception as e:  # noqa: BLE001 — narrowly re-matched below
                if ex_list is not None or not _is_kernel_transform_error(e):
                    raise
                continue
            cache[key] = staged
            return result if return_value else result[1]

    wrapper._lc_cs = cs
    return wrapper


# =============================================================================
# Function transforms: vmap / jvp (reference: transforms.py:2051,2324 —
# experimental there; here they compose at the staged-function level, where
# XLA's native batching/forward-mode rules apply to the claimed trace)
# =============================================================================


def _staged_flat_fn(fn: Callable, args: tuple, kwargs: Optional[dict] = None,
                    executors: Optional[Sequence] = None,
                    trace_transforms: Sequence[Callable] = ()) -> Callable:
    """Trace+claim fn for the given example args → flat jax callable whose
    inputs are the TENSOR leaves of (args, kwargs) in pytree order (number/
    string leaves are prologue-guarded constants baked into the trace).
    ``trace_transforms`` (e.g. grad_transform) run after dce/cse, mirroring
    _compile_entry's pipeline — this is what lets vmap compose with a
    grad-compiled function."""
    from thunder_tpu.executors.passes import transform_for_execution

    _, comp = trace_program(fn, args, kwargs or {})
    if getattr(comp, "_input_mutations", None):
        # ADVICE r5 #2: this path re-stages without the jit epilogue, so a
        # function that mutates its inputs would silently lose those writes
        # under vmap/jvp — fail loudly like the grad path does.
        kinds = sorted({m[0] for m in comp._input_mutations})
        raise NotImplementedError(
            f"the traced function mutates its inputs ({', '.join(kinds)}), "
            "which cannot be combined with vmap/jvp re-staging (the mutation "
            "epilogue does not run on this path) — make the function pure or "
            "apply updates outside it"
        )
    comp = cse(dce(comp))
    for tt in trace_transforms:
        comp = tt(comp)
    extrace = transform_for_execution(comp, resolve_executors(executors))
    return extrace.python_callable()


def _unwrap_compiled(fn: Callable) -> tuple[Callable, tuple]:
    """(inner_fn, trace_transforms) for a thunder-compiled function —
    lets vmap/jvp re-stage the ORIGINAL function with its transforms
    (grad, autocast) instead of tracing through the compiled wrapper."""
    cd = getattr(fn, "_lc_cd", None)
    if cd is not None:
        return cd.fn, tuple(cd.compile_options.get("_trace_transforms", ()))
    return fn, ()


def _is_kernel_transform_error(e: BaseException) -> bool:
    """Narrowly match 'this kernel claim cannot run under the requested jax
    transform' (ADVICE r3: the old blanket TypeError catch masked genuine
    user TypeErrors behind a silent re-stage): a Pallas claim without a
    batching rule raises NotImplementedError mentioning batching/vmap, and a
    custom-VJP claim under jvp raises TypeError mentioning custom_vjp/JVP."""
    msg = str(e).lower()
    if isinstance(e, NotImplementedError):
        return "batching" in msg or "vmap" in msg
    if isinstance(e, TypeError):
        return "custom_vjp" in msg or "jvp" in msg or "custom_jvp" in msg
    return False


def _meta_key(flat_values, extra=()) -> tuple:
    parts = []
    for x in flat_values:
        if bridge.is_concrete_tensor(x):
            shape, dev, dt, rg = bridge.tensor_metadata(x)
            parts.append((tuple(shape), str(dt)))
        elif isinstance(x, (int, float, bool, str, type(None))):
            parts.append(x)
        else:
            parts.append(type(x).__name__)
    return tuple(parts) + tuple(extra)


def _vmap_flatten(args: tuple, kwargs: dict, in_axes):
    """Normalize per-arg axes and flatten to (axes, flat_axes, flat_args):
    tensor leaves only, kwargs leaves unbatched — the one flattening
    protocol shared by vmap and grad-of-vmap."""
    if isinstance(in_axes, (tuple, list)):
        check(
            len(in_axes) == len(args),
            lambda: f"vmap in_axes has {len(in_axes)} entries but the call has "
                    f"{len(args)} positional arguments",
            ValueError,
        )
        axes = tuple(in_axes)
    else:
        axes = (in_axes,) * len(args)

    flat_axes: list = []
    flat_args: list = []
    for a, ax in zip(args, axes):
        for x in tree_flatten(a)[0]:
            if bridge.is_concrete_tensor(x):
                flat_axes.append(ax)
                flat_args.append(bridge.to_jax(x))
    for x in tree_flatten(kwargs)[0]:
        if bridge.is_concrete_tensor(x):
            flat_axes.append(None)
            flat_args.append(bridge.to_jax(x))
    return axes, flat_axes, flat_args


def _vmap_example(args: tuple, axes: tuple) -> tuple:
    """Slice axis-0 (per the in_axes) off every batched tensor leaf — the
    one-slice example the staged trace is acquired on."""

    def slice_ax(x, ax):
        if ax is None or not hasattr(x, "shape"):
            return x
        import numpy as np

        return np.asarray(x).take(0, axis=ax)

    return tuple(
        tree_map(lambda x, _ax=ax: slice_ax(x, _ax), a) for a, ax in zip(args, axes)
    )


def vmap(fn: Callable, in_axes=0, out_axes=0) -> Callable:
    """Vectorizing map over the traced program (experimental; reference
    transforms.py `vmap:2051` is experimental too).

    Traces ``fn`` on one slice with the FULL executor list (kernel claims
    included), then batches the staged callable under ``jax.vmap``; if a
    claimed kernel has no batching rule, the call transparently re-stages
    with the jax executor only. kwargs are passed through unbatched.

    Staging is cached on input metadata (shapes/dtypes/axes): repeat calls
    do zero tracing (observable via ``compile_stats(vmapped)``).

    Composes with :func:`grad`/:func:`value_and_grad`: ``vmap(grad(f))``
    re-stages the ORIGINAL f with its grad transform and batches the staged
    gradient program (per-sample gradients, reference: transforms.py:2051)."""
    import jax

    inner_fn, inner_tts = _unwrap_compiled(fn)
    cache: dict = {}
    cs = CompileStats()

    def vmapped(*args, **kwargs):
        cs.calls += 1
        # The staged computation's inputs are the TENSOR leaves only (number/
        # string leaves are prologue-guarded constants baked into the trace).
        axes, flat_axes, flat_args = _vmap_flatten(args, kwargs, in_axes)

        # The key must cover EVERY leaf (scalars included): non-tensor leaves
        # are baked into the staged trace as constants, so a changed scalar
        # must be a cache miss, not a silent reuse.
        key = _meta_key(
            tree_flatten((args, kwargs))[0], extra=(tuple(flat_axes), out_axes)
        )
        batched = cache.get(key)
        if batched is not None:
            cs.cache_hits += 1
            return batched(*flat_args)
        cs.cache_misses += 1

        # Trace on one slice; batch the staged function. Per-arg in_axes
        # apply to every tensor leaf of that arg (pytree args included).
        example = _vmap_example(args, axes)
        cs.last_trace_tracing_start = timer_ns()
        for ex_list in (None, ["jax"]):
            flat_fn = _staged_flat_fn(
                inner_fn, example, kwargs, executors=ex_list, trace_transforms=inner_tts
            )
            batched = jax.jit(jax.vmap(flat_fn, in_axes=flat_axes, out_axes=out_axes))
            try:
                result = batched(*flat_args)
            except Exception as e:  # noqa: BLE001 — narrowly re-matched below
                if ex_list is not None or not _is_kernel_transform_error(e):
                    raise
                # A claimed kernel without a batching rule: fall back to the
                # pure-jax claiming and let XLA batch the decomposition.
                continue
            cs.last_trace_tracing_stop = timer_ns()
            cache[key] = batched
            return result

    vmapped._lc_cs = cs
    vmapped._lc_vmap_spec = {"fn": fn, "in_axes": in_axes, "out_axes": out_axes}
    return vmapped


class _JvpCache:
    """Staged-jvp cache keyed on a WEAKREF to the function, not ``id(fn)``.

    ``id(fn)`` aliases after GC — a new closure at a reused address would
    silently receive a dead function's staged callable (ADVICE r4). A
    weakref key can't alias (entries are purged the moment the function
    dies) and holds no reference to the closure or anything it captures
    (the cached staged callable is built from the trace, not from ``fn``).
    Non-weakrefable callables fall back to a strong key (bounded by the
    LRU); unhashable callables simply skip caching. Eviction is LRU, not
    the previous clear-all."""

    MAX_ENTRIES = 256

    def __init__(self):
        from collections import OrderedDict

        self._entries = OrderedDict()

    def _purge(self, dead_ref) -> None:
        for k in [k for k in self._entries if k[0] is dead_ref]:
            del self._entries[k]

    def get(self, fn, key):
        import weakref

        try:
            ref = weakref.ref(fn)
        except TypeError:
            ref = fn
        try:
            value = self._entries.get((ref, key))
        except TypeError:  # unhashable callable: never cached
            return None
        if value is not None:
            self._entries.move_to_end((ref, key))
        return value

    def put(self, fn, key, value) -> None:
        import weakref

        try:
            ref = weakref.ref(fn, self._purge)
        except TypeError:
            ref = fn
        try:
            self._entries[(ref, key)] = value
            self._entries.move_to_end((ref, key))
        except TypeError:  # unhashable callable: skip caching
            return
        while len(self._entries) > self.MAX_ENTRIES:
            self._entries.popitem(last=False)

    def __len__(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        self._entries.clear()


_jvp_cache = _JvpCache()


def jvp(fn: Callable, primals: tuple, tangents: tuple):
    """Forward-mode derivative of the traced program (experimental;
    reference `jvp:2324`). Kernel claims are attempted first; custom-VJP
    kernels (no JVP rule) transparently re-stage with the jax executor.
    Staging is cached per (fn, input metadata) — repeat calls don't retrace."""
    import jax

    flat_p = [bridge.to_jax(x) for x in tree_flatten((tuple(primals), {}))[0]
              if bridge.is_concrete_tensor(x)]
    flat_t = [bridge.to_jax(x) for x in tree_flatten((tuple(tangents), {}))[0]
              if bridge.is_concrete_tensor(x)]
    # Key over every primal leaf — non-tensor primals are baked constants.
    key = _meta_key(tree_flatten((tuple(primals), {}))[0])
    cached = _jvp_cache.get(fn, key)
    if cached is not None:
        return jax.jvp(cached, tuple(flat_p), tuple(flat_t))
    for ex_list in (None, ["jax"]):
        flat_fn = _staged_flat_fn(fn, tuple(primals), executors=ex_list)
        try:
            result = jax.jvp(flat_fn, tuple(flat_p), tuple(flat_t))
        except Exception as e:  # noqa: BLE001 — narrowly re-matched below
            if ex_list is not None or not _is_kernel_transform_error(e):
                raise
            continue
        _jvp_cache.put(fn, key, flat_fn)
        return result


# =============================================================================
# Introspection (reference: thunder/__init__.py:697-793)
# =============================================================================


def _get_cs(fn: Callable) -> CompileStats:
    cs = getattr(fn, "_lc_cs", None)
    check(cs is not None, "Not a thunder_tpu-compiled function", ValueError)
    return cs


def _get_cd(fn: Callable) -> CompileData:
    cd = getattr(fn, "_lc_cd", None)
    check(cd is not None, "Not a thunder_tpu-compiled function", ValueError)
    return cd


def compile_data(fn: Callable) -> CompileData:
    return _get_cd(fn)


def compile_stats(fn: Callable) -> CompileStats:
    return _get_cs(fn)


def last_traces(fn: Callable) -> list:
    return _get_cs(fn).last_traces


def last_prologue_traces(fn: Callable) -> list:
    return _get_cs(fn).last_prologue_traces


def last_backward_traces(fn: Callable) -> list:
    return _get_cs(fn).last_backward_traces


def cache_hits(fn: Callable) -> int:
    return _get_cs(fn).cache_hits


def cache_misses(fn: Callable) -> int:
    return _get_cs(fn).cache_misses


def last_compile_options(fn: Callable) -> dict:
    return _get_cd(fn).last_compile_options()
