"""Examine: support reporting, trace inspection, static memory estimation.

Reference parity: thunder/examine/__init__.py (`examine:49` — reports which
torch ops in a callable are unsupported; `get_fusions:190`) and
examine/memory_caculation.py (`get_alloc_memory:120` — static peak-memory
estimate over a trace).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import TensorProxy, variableify
from thunder_tpu.core.pytree import tree_flatten
from thunder_tpu.core.trace import TraceCtx


def examine(fn: Callable, *args, **kwargs) -> dict:
    """Report whether ``fn`` can be traced, and which torch operations are
    not supported (reference: examine/__init__.py:49 — there via a
    TorchFunctionMode collector; here by running the acquisition itself and
    collecting dispatch failures)."""
    import torch

    from thunder_tpu.frontend.module import ThunderModule
    from thunder_tpu.api import trace_program

    unsupported: list[str] = []
    report: dict[str, Any] = {"supported": False, "unsupported_ops": unsupported, "trace": None}

    try:
        if isinstance(fn, torch.nn.Module):
            tm = ThunderModule(fn)
            entry = tm._compile(args, kwargs)
            comp = entry["traces"][0]
        else:
            _, comp = trace_program(fn, args, kwargs)
        report["supported"] = True
        report["trace"] = comp
    except NotImplementedError as e:
        unsupported.append(str(e))
    except Exception as e:  # noqa: BLE001
        report["error"] = f"{type(e).__name__}: {e}"
    return report


def get_fusions(trace: TraceCtx) -> list[tuple[str, Any]]:
    """Executor-claimed regions of a trace (reference: examine:190). Under
    whole-trace XLA staging every claimed bsym is one 'fusion seed'; returns
    (executor_name, bsym) pairs for non-default executors."""
    out = []
    for bsym in trace.bound_symbols:
        ex = bsym.sym.executor
        if ex is not None and ex.name not in ("python",):
            out.append((ex.name, bsym))
    return out


_DEL_IDS = {PrimIDs.DEL}
_NO_ALLOC_IDS = {
    PrimIDs.RETURN, PrimIDs.COMMENT, PrimIDs.PRINT,
    PrimIDs.UNPACK_TRIVIAL, PrimIDs.UNPACK_SEQUENCE, PrimIDs.UNPACK_KEY, PrimIDs.UNPACK_ATTR,
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA, PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    PrimIDs.CHECK_STRING_VALUE, PrimIDs.CHECK_LEN, PrimIDs.CHECK_NONE,
    PrimIDs.SHALLOW_COPY, PrimIDs.STOP_GRADIENT,
}


def get_alloc_memory(trace: TraceCtx) -> tuple[int, dict[str, int]]:
    """Static peak-allocation estimate over a trace in bytes
    (reference: examine/memory_caculation.py:120).

    Walks the program keeping a live-set of tensor buffers: inputs are live
    at entry, outputs of each bsym allocate, and ``del`` frees. Aliasing
    ops (shallow_copy/stop_gradient/views) are counted as allocations only
    when XLA would materialize them (reshape/transpose are not charged).
    """
    live: dict[str, int] = {}
    flat_args, _ = tree_flatten((trace.args, trace.kwargs))
    for a in flat_args:
        if isinstance(a, TensorProxy):
            live[a.name] = a.size_bytes

    peak = sum(live.values())
    timeline: dict[str, int] = {"inputs": peak}

    for i, bsym in enumerate(trace.bound_symbols):
        if bsym.sym.id in _DEL_IDS:
            for p in bsym.flat_proxy_args:
                live.pop(p.name, None)
            continue
        if bsym.sym.id in _NO_ALLOC_IDS:
            continue
        for o in bsym.flat_proxy_outs:
            if isinstance(o, TensorProxy) and o.name not in live:
                live[o.name] = o.size_bytes
        cur = sum(live.values())
        if cur > peak:
            peak = cur
            timeline[f"{i}:{bsym.sym.name}"] = cur
    return peak, timeline
