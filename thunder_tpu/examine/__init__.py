"""Examine: support reporting, trace inspection, static memory estimation.

Reference parity: thunder/examine/__init__.py (`examine:49` — reports which
torch ops in a callable are unsupported; `get_fusions:190`) and
examine/memory_caculation.py (`get_alloc_memory:120` — static peak-memory
estimate over a trace).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from thunder_tpu.analysis.cost import cost_report, trace_cost  # noqa: F401  (examine.cost_report)
from thunder_tpu.analysis.liveness import memory_report, plan_liveness  # noqa: F401  (examine.memory_report)
from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import TensorProxy, variableify
from thunder_tpu.core.pytree import tree_flatten
from thunder_tpu.core.trace import TraceCtx


def _collect_unsupported(fn: Callable, args, kwargs) -> tuple[list[str], Optional[str]]:
    """One eager pass under a recording TorchFunctionMode: every torch call
    is checked for ltorch coverage and then executed FOR REAL, so ALL
    unsupported ops are enumerated in a single run (reference:
    examine/__init__.py:17-49 — the same collector design). Returns
    (unsupported op names, user error or None)."""
    import torch
    from torch.overrides import TorchFunctionMode

    from thunder_tpu.core.langctxs import Languages, resolve_language
    from thunder_tpu.torch import torch_function_map

    fmap = torch_function_map()
    ctx = resolve_language(Languages.TORCH)
    seen: list[str] = []
    seen_set: set[str] = set()

    # Mirrors frontend/dispatch.py: mapped directly, or resolvable as an
    # ltorch method by name.
    def covered(func) -> bool:
        if func in fmap:
            return True
        name = getattr(func, "__name__", None)
        return bool(name and ctx.has_method(name))

    class Collector(TorchFunctionMode):
        def __torch_function__(self, func, types, f_args=(), f_kwargs=None):
            name = getattr(func, "__name__", "")
            # attribute-descriptor plumbing (Tensor.real's __get__ etc.) is
            # not an op the user wrote
            if not covered(func) and not (name.startswith("__") and name.endswith("__")):
                label = getattr(func, "__qualname__", name or repr(func))
                if label not in seen_set:
                    seen_set.add(label)
                    seen.append(label)
            return func(*f_args, **(f_kwargs or {}))

    user_error: Optional[str] = None
    try:
        with Collector():
            fn(*args, **kwargs)
    except Exception as e:  # noqa: BLE001 — eager failure is a USER bug, reported separately
        user_error = f"{type(e).__name__}: {e}"
    return seen, user_error


def examine(fn: Callable, *args, **kwargs) -> dict:
    """Report whether ``fn`` can be traced, and which torch operations are
    not supported (reference: examine/__init__.py:49).

    Torch-facing callables get the full collector pass — a model with three
    unsupported ops lists all three, and an exception raised by the model
    itself is reported as ``user_error`` rather than conflated with missing
    coverage. The acquisition itself is then attempted to produce a trace."""
    try:
        import torch
    except ImportError:
        torch = None

    from thunder_tpu.api import trace_program
    from thunder_tpu.frontend.module import ThunderModule

    unsupported: list[str] = []
    report: dict[str, Any] = {"supported": False, "unsupported_ops": unsupported, "trace": None}

    is_torch_module = torch is not None and isinstance(fn, torch.nn.Module)
    if is_torch_module:
        ops, user_error = _collect_unsupported(fn, args, kwargs)
        unsupported.extend(ops)
        if user_error is not None:
            report["user_error"] = user_error
        if unsupported or user_error:
            return report

    try:
        if is_torch_module:
            tm = ThunderModule(fn)
            entry = tm._compile(args, kwargs)
            comp = entry["traces"][0]
        else:
            _, comp = trace_program(fn, args, kwargs)
        report["supported"] = True
        report["trace"] = comp
    except NotImplementedError as e:
        unsupported.append(str(e))
    except Exception as e:  # noqa: BLE001
        report["error"] = f"{type(e).__name__}: {e}"
    return report


def lint(fn: Callable, *args, executors: Optional[Any] = None, verbose: bool = True, **kwargs) -> list:
    """Trace ``fn`` on the given example inputs, run the default pass
    pipeline (acquisition → DCE → CSE → claiming → del_last_used), and run
    the static verifier (thunder_tpu/analysis) over every stage. Returns the
    full list of :class:`~thunder_tpu.analysis.Diagnostic`s; with ``verbose``
    pretty-prints each one with the offending generated trace line.

    Unlike ``THUNDER_TPU_CHECKS=1`` (which raises at the first failing pass),
    lint collects everything — including warnings and info-level findings —
    so it doubles as a trace-quality report. Rule ids and the
    suppression/extension story: docs/trace_invariants.md.
    """
    from thunder_tpu.analysis import attach_trace_lines, verify
    from thunder_tpu.api import trace_program
    from thunder_tpu.core.trace import debug_checks, mark
    from thunder_tpu.executors.passes import del_last_used, transform_for_execution
    from thunder_tpu.extend import resolve_executors
    from thunder_tpu.transforms.common import cse, dce

    # A thunder-compiled function: lint the UNDERLYING function (tracing the
    # wrapper would trace the dispatch machinery) and report its cache state
    # in the summary (ISSUE 2: cache observability).
    compiled = fn if getattr(fn, "_lc_cs", None) is not None else None
    cd = getattr(fn, "_lc_cd", None)
    if cd is not None:
        fn = cd.fn

    # The pipeline below must not raise mid-way even when THUNDER_TPU_CHECKS
    # is set globally — lint's contract is collect-everything.
    with debug_checks(False):
        # record_input_mutations=True mirrors the jit() pipeline: an
        # input-mutating fn gets the same {"__out", "__muts"} epilogue
        # structure in its trace, so lint verifies the program that would
        # actually compile.
        plg, comp = trace_program(fn, args, kwargs, record_input_mutations=True)
        mark(comp, "Acquisition")
        mark(plg, "Prologue construction")
        stages: list[tuple[str, TraceCtx]] = [("Prologue construction", plg), ("Acquisition", comp)]
        comp = dce(comp)
        stages.append(("Dead Code Elimination", comp))
        comp = cse(comp)
        stages.append(("Common Subexpression Elimination", comp))
        extrace = transform_for_execution(comp, resolve_executors(executors))
        stages.append(("Transform for execution", extrace))
        extrace = del_last_used(extrace)
        stages.append(("Delete Last Used", extrace))

    diagnostics = []
    for name, trc in stages:
        diags = verify(trc, pass_name=name)
        attach_trace_lines(diags, trc)
        diagnostics.extend(diags)

    if verbose:
        if not diagnostics:
            print(f"lint: {len(stages)} stages verified clean ({len(extrace.bound_symbols)} symbols)")
        for d in diagnostics:
            print(d.format())
        if compiled is not None:
            print(format_cache_report(compiled))
        from thunder_tpu.observability import metrics as obsm

        if obsm.enabled():
            print(format_metrics_report())
    return diagnostics


def hlo_report(fn: Callable, *args, device: Optional[Any] = None,
               verbose: bool = True, **kwargs):
    """Audit the compiled-HLO executable behind ``fn`` — the static view of
    what the XLA SPMD partitioner actually emitted (partitioner-inserted
    collectives, fusions, layout copies, host transfers, exposed wire time),
    which no trace-level tool can see (ROADMAP item 3).

    Accepts, in order of preference:

    - a ``thunder_tpu.jit``-compiled function: returns the report the
      ``hlo_audit`` compile phase attached to its latest cache entry,
      compiling on the example args first if needed;
    - an already-jitted jax callable (``jax.jit`` object or AOT
      ``Compiled``) — e.g. the ``build_train_step`` pjit step function:
      lowered and audited on the example args;
    - a plain callable: compiled through ``thunder_tpu.jit`` first.

    Returns the :class:`~thunder_tpu.analysis.hlo_audit.HloScheduleReport`;
    with ``verbose`` pretty-prints it plus the advisory ``hlo.*`` findings.
    Docs: docs/performance.md (§HLO auditor)."""
    from thunder_tpu.analysis.hlo_audit import audit_jitted

    report = None
    cs = getattr(fn, "_lc_cs", None)
    if cs is None and not hasattr(fn, "lower") and not hasattr(fn, "as_text"):
        from thunder_tpu.api import jit as _tt_jit

        fn = _tt_jit(fn)
        cs = fn._lc_cs
    if cs is not None:
        entry = cs.cache_entries[-1] if cs.cache_entries else None
        report = getattr(entry, "hlo_audit", None) if entry is not None else None
        if report is None:
            fn(*args, **kwargs)
            entry = cs.cache_entries[-1]
            report = getattr(entry, "hlo_audit", None)
        if report is None:
            # Compile-time audit disabled (THUNDER_TPU_HLO_AUDIT=0) or it
            # degraded to a sharp_edge — audit on demand from the captured
            # first-run avals.
            avals = getattr(entry, "hlo_audit_avals", None)
            if avals and hasattr(entry.computation_fn, "lower"):
                report = audit_jitted(entry.computation_fn, *avals, device=device)
        if report is None:
            raise RuntimeError(
                "no HLO audit available for this compiled function (the "
                "compile-time audit failed and no input avals were captured); "
                "see the sharp_edge events for the failure"
            )
    else:
        report = audit_jitted(fn, *args, device=device, **kwargs)
    if verbose:
        print(report.format())
        for d in report.diagnostics():
            print(d.format())
    return report


def format_cache_report(jfn: Callable) -> str:
    """Human-readable cache summary for a compiled function: aggregate and
    per-entry hit/miss/recompile counters plus trace/first-run seconds —
    recompile storms become visible instead of inferred."""
    from thunder_tpu.api import cache_info

    info = cache_info(jfn)
    lines = [
        f"cache[{info['cache_option']}]: {info['calls']} calls, "
        f"{info['hits']} hits ({info['fast_hits']} O(1) fast, {info['slow_hits']} "
        f"prologue-scan), {info['misses']} misses, {info['compiles']} compiles "
        f"({info['recompiles']} recompiles), {info['prologue_runs']} prologue runs",
        f"  trace {info['trace_seconds']:.3f}s, first-run (incl. XLA compile) "
        f"{info['first_run_seconds']:.3f}s, cache lookups "
        f"{info['cache_lookup_us_total']:.0f}us total",
    ]
    for e in info["entries"]:
        lines.append(
            f"  entry {e['index']} [{e['buckets']}]: {e['hits']} hits "
            f"({e['fast_hits']} fast), {e['prologue_runs']} prologue runs, "
            f"{e['guard_fails']} guard fails, trace {e['trace_s']:.3f}s, "
            f"first run {e['first_run_s']:.3f}s"
        )
    return "\n".join(lines)


def format_metrics_report() -> str:
    """One-screen summary of the process-wide observability metrics
    (``thunder_tpu.monitor``): compiles/recompiles, cache traffic, claim
    breakdown, padding waste — the cross-function counterpart of
    :func:`format_cache_report`. Empty series are elided."""
    from thunder_tpu.observability.metrics import REGISTRY

    flat = REGISTRY.report_compact()
    if not flat:
        return "metrics: enabled, no samples yet"
    lines = ["metrics (process-wide, thunder_tpu.monitor.report()):"]
    for name, v in flat.items():
        if isinstance(v, dict):  # histogram summary
            lines.append(
                f"  {name}: n={v['count']} mean={v['mean']:.1f} "
                f"min={v['min']:.1f} max={v['max']:.1f}"
            )
        else:
            lines.append(f"  {name}: {v}")
    return "\n".join(lines)


def get_fusions(trace: TraceCtx) -> list[tuple[str, Any]]:
    """Executor-claimed regions of a trace (reference: examine:190). Under
    whole-trace XLA staging every claimed bsym is one 'fusion seed'; returns
    (executor_name, bsym) pairs for non-default executors."""
    out = []
    for bsym in trace.bound_symbols:
        ex = bsym.sym.executor
        if ex is not None and ex.name not in ("python",):
            out.append((ex.name, bsym))
    return out


_DEL_IDS = {PrimIDs.DEL}
_NO_ALLOC_IDS = {
    PrimIDs.RETURN, PrimIDs.COMMENT, PrimIDs.PRINT,
    PrimIDs.UNPACK_TRIVIAL, PrimIDs.UNPACK_SEQUENCE, PrimIDs.UNPACK_KEY, PrimIDs.UNPACK_ATTR,
    PrimIDs.UNPACK_DIM,
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA, PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    PrimIDs.CHECK_STRING_VALUE, PrimIDs.CHECK_LEN, PrimIDs.CHECK_KEYS, PrimIDs.CHECK_NONE,
    PrimIDs.CHECK_DIM_BUCKET,
    PrimIDs.SHALLOW_COPY, PrimIDs.STOP_GRADIENT,
}


def get_alloc_memory(trace: TraceCtx) -> tuple[int, dict[str, int]]:
    """Static peak-allocation estimate over a trace in bytes
    (reference: examine/memory_caculation.py:120).

    Walks the program keeping a live-set of tensor buffers: inputs are live
    at entry, outputs of each bsym allocate, and ``del`` frees. Aliasing
    ops (shallow_copy/stop_gradient/views) are counted as allocations only
    when XLA would materialize them (reshape/transpose are not charged).
    """
    live: dict[str, int] = {}
    flat_args, _ = tree_flatten((trace.args, trace.kwargs))
    for a in flat_args:
        if isinstance(a, TensorProxy):
            live[a.name] = a.size_bytes

    peak = sum(live.values())
    timeline: dict[str, int] = {"inputs": peak}

    for i, bsym in enumerate(trace.bound_symbols):
        if bsym.sym.id in _DEL_IDS:
            for p in bsym.flat_proxy_args:
                live.pop(p.name, None)
            continue
        if bsym.sym.id in _NO_ALLOC_IDS:
            continue
        for o in bsym.flat_proxy_outs:
            if isinstance(o, TensorProxy) and o.name not in live:
                live[o.name] = o.size_bytes
        cur = sum(live.values())
        if cur > peak:
            peak = cur
            timeline[f"{i}:{bsym.sym.name}"] = cur
    return peak, timeline
