"""The GPT family: a functional, trace-friendly transformer.

Reference parity: the litgpt ``GPT`` exercised throughout the reference's
tests and benchmarks (thunder/tests/lit_gpt_model.py,
thunder/benchmarks/benchmark_litgpt.py:41) — GPT-NeoX (pythia) and
Llama/Mistral architectural variants: parallel vs sequential residual,
LayerNorm vs RMSNorm, GptNeoxMLP vs SwiGLU, partial-rotary RoPE, and
grouped-query attention.

TPU-first design: the model is a *pure function* ``forward(params, idx)``
over a params pytree — no module object, no buffers, no in-place state. That
makes it directly traceable by the functional frontend, jittable whole,
shardable by annotating the params pytree with PartitionSpecs, and
differentiable by the trace VJP. Weights live in bf16 (MXU-native); norms
and softmax compute in f32 (handled inside ltorch ops).

Layout notes:
- qkv is one fused projection (q heads, then k, then v) — a single large
  MXU matmul instead of three.
- RoPE uses the rotate-half convention (HF NeoX/Llama compatible) with
  ``rotary_percentage`` of head_size rotated; cos/sin are built from iota
  inside the trace, so XLA constant-folds them into the compiled step.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional

import numpy as np

import thunder_tpu.torch as ttorch
from thunder_tpu.core import dtypes


@dataclass(frozen=True)
class GPTConfig:
    name: str = "gpt"
    block_size: int = 2048
    vocab_size: int = 50254
    padded_vocab_size: int = 50304
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    n_query_groups: Optional[int] = None  # None → MHA (== n_head)
    rotary_percentage: float = 0.25
    parallel_residual: bool = True
    shared_attention_norm: bool = False
    bias: bool = True
    norm_class: str = "LayerNorm"  # or "RMSNorm"
    norm_eps: float = 1e-5
    mlp_class: str = "GptNeoxMLP"  # or "LLaMAMLP" / "MoEMLP"
    intermediate_size: Optional[int] = None
    rope_base: int = 10000
    # MoE (mlp_class="MoEMLP", mixtral-style SwiGLU experts):
    n_expert: int = 0
    n_expert_per_token: int = 2

    @property
    def head_size(self) -> int:
        return self.n_embd // self.n_head

    @property
    def query_groups(self) -> int:
        return self.n_query_groups if self.n_query_groups is not None else self.n_head

    @property
    def rope_n_elem(self) -> int:
        return int(self.rotary_percentage * self.head_size)

    @property
    def mlp_hidden(self) -> int:
        return self.intermediate_size if self.intermediate_size is not None else 4 * self.n_embd

    @property
    def qkv_out(self) -> int:
        return (self.n_head + 2 * self.query_groups) * self.head_size


configs: dict[str, GPTConfig] = {}


def _add(cfg: GPTConfig) -> GPTConfig:
    configs[cfg.name] = cfg
    return cfg


# Tiny configs for tests/dryruns.
_add(GPTConfig(name="gpt-tiny", block_size=64, vocab_size=96, padded_vocab_size=96, n_layer=2,
               n_head=2, n_embd=32, rotary_percentage=1.0, intermediate_size=64))
_add(GPTConfig(name="llama-tiny", block_size=64, vocab_size=96, padded_vocab_size=96, n_layer=2,
               n_head=4, n_embd=32, n_query_groups=2, rotary_percentage=1.0,
               parallel_residual=False, bias=False, norm_class="RMSNorm", mlp_class="LLaMAMLP",
               intermediate_size=88))

# Pythia (GPT-NeoX) family — reference benchmark ladder step 2.
_add(GPTConfig(name="pythia-160m", block_size=2048, vocab_size=50254, padded_vocab_size=50304,
               n_layer=12, n_head=12, n_embd=768, rotary_percentage=0.25, parallel_residual=True,
               bias=True, norm_class="LayerNorm", mlp_class="GptNeoxMLP", intermediate_size=3072))
_add(GPTConfig(name="pythia-410m", block_size=2048, vocab_size=50254, padded_vocab_size=50304,
               n_layer=24, n_head=16, n_embd=1024, rotary_percentage=0.25, parallel_residual=True,
               bias=True, norm_class="LayerNorm", mlp_class="GptNeoxMLP", intermediate_size=4096))
_add(GPTConfig(name="pythia-1b", block_size=2048, vocab_size=50254, padded_vocab_size=50304,
               n_layer=16, n_head=8, n_embd=2048, rotary_percentage=0.25, parallel_residual=True,
               bias=True, norm_class="LayerNorm", mlp_class="GptNeoxMLP", intermediate_size=8192))

# Llama-2 family — reference benchmark ladder steps 3-4 / north star.
_add(GPTConfig(name="llama-2-7b", block_size=4096, vocab_size=32000, padded_vocab_size=32000,
               n_layer=32, n_head=32, n_embd=4096, rotary_percentage=1.0, parallel_residual=False,
               bias=False, norm_class="RMSNorm", norm_eps=1e-5, mlp_class="LLaMAMLP",
               intermediate_size=11008))
_add(GPTConfig(name="llama-2-13b", block_size=4096, vocab_size=32000, padded_vocab_size=32000,
               n_layer=40, n_head=40, n_embd=5120, rotary_percentage=1.0, parallel_residual=False,
               bias=False, norm_class="RMSNorm", norm_eps=1e-5, mlp_class="LLaMAMLP",
               intermediate_size=13824))
_add(GPTConfig(name="open_llama_3b", block_size=2048, vocab_size=32000, padded_vocab_size=32000,
               n_layer=26, n_head=32, n_embd=3200, rotary_percentage=1.0, parallel_residual=False,
               bias=False, norm_class="RMSNorm", norm_eps=1e-6, mlp_class="LLaMAMLP",
               intermediate_size=8640))

# Mixtral-style MoE family (beyond-reference: SURVEY §2.3 has no EP/MoE).
_add(GPTConfig(name="mixtral-tiny", block_size=64, vocab_size=96, padded_vocab_size=96,
               n_layer=2, n_head=4, n_embd=32, n_query_groups=2, rotary_percentage=1.0,
               parallel_residual=False, bias=False, norm_class="RMSNorm",
               mlp_class="MoEMLP", intermediate_size=64, n_expert=4, n_expert_per_token=2))
_add(GPTConfig(name="mixtral-8x7b", block_size=4096, vocab_size=32000, padded_vocab_size=32000,
               n_layer=32, n_head=32, n_embd=4096, n_query_groups=8, rotary_percentage=1.0,
               parallel_residual=False, bias=False, norm_class="RMSNorm", norm_eps=1e-5,
               mlp_class="MoEMLP", intermediate_size=14336, n_expert=8, n_expert_per_token=2))

# Mistral — reference benchmark ladder step 5 (GQA).
_add(GPTConfig(name="mistral-7b", block_size=4096, vocab_size=32000, padded_vocab_size=32000,
               n_layer=32, n_head=32, n_embd=4096, n_query_groups=8, rotary_percentage=1.0,
               parallel_residual=False, bias=False, norm_class="RMSNorm", norm_eps=1e-5,
               mlp_class="LLaMAMLP", intermediate_size=14336))

# Falcon family — MQA (one KV head) + shared-attention-norm parallel residual
# (the litgpt registry's falcon geometry; reference tests run falcon-7b-like
# configs through thunder).
_add(GPTConfig(name="falcon-7b", block_size=2048, vocab_size=65024, padded_vocab_size=65024,
               n_layer=32, n_head=71, n_embd=4544, n_query_groups=1, rotary_percentage=1.0,
               parallel_residual=True, shared_attention_norm=True, bias=False,
               norm_class="LayerNorm", mlp_class="GptNeoxMLP", intermediate_size=18176))
_add(GPTConfig(name="falcon-tiny", block_size=64, vocab_size=96, padded_vocab_size=96,
               n_layer=2, n_head=4, n_embd=32, n_query_groups=1, rotary_percentage=1.0,
               parallel_residual=True, shared_attention_norm=True, bias=False,
               norm_class="LayerNorm", mlp_class="GptNeoxMLP", intermediate_size=128))

# Phi-2 — partial-rotary parallel-residual with biases.
_add(GPTConfig(name="phi-2", block_size=2048, vocab_size=50257, padded_vocab_size=51200,
               n_layer=32, n_head=32, n_embd=2560, rotary_percentage=0.4,
               parallel_residual=True, shared_attention_norm=True, bias=True,
               norm_class="LayerNorm", mlp_class="GptNeoxMLP", intermediate_size=10240))


def name_to_config(name: str) -> GPTConfig:
    return configs[name]


# =============================================================================
# Parameter initialization
# =============================================================================


def init_params(config: GPTConfig, *, dtype=dtypes.bfloat16, seed: int = 0, device_init: bool = False) -> dict:
    """Nested-dict params pytree.

    ``device_init=False`` (default): reproducible numpy init, suitable for
    tests and parity checks. ``device_init=True``: weights are generated
    directly on the accelerator with jax.random — required for multi-GB
    models where a host-side f32 copy would not fit (and is ~100× faster).
    """
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    jdt = dtypes.to_jax_dtype(dtypes.to_dtype(dtype))

    if device_init:
        key_holder = {"k": jax.random.PRNGKey(seed)}

        def w(*shape, std=0.02):
            key_holder["k"], sub = jax.random.split(key_holder["k"])
            return (jax.random.normal(sub, shape, dtype=jnp.float32) * std).astype(jdt)

    else:

        def w(*shape, std=0.02):
            return jnp.asarray(rng.normal(0.0, std, size=shape).astype(np.float32), dtype=jdt)

    def zeros(*shape):
        return jnp.zeros(shape, dtype=jdt)

    def ones(*shape):
        return jnp.ones(shape, dtype=jdt)

    C = config
    def norm_params():
        p = {"weight": ones(C.n_embd)}
        if C.norm_class == "LayerNorm":
            p["bias"] = zeros(C.n_embd)
        return p

    def block_params(i):
        p: dict[str, Any] = {
            "norm_1": norm_params(),
            "attn": {
                "qkv_w": w(C.qkv_out, C.n_embd),
                "proj_w": w(C.n_embd, C.n_head * C.head_size, std=0.02 / np.sqrt(2 * C.n_layer)),
            },
            "mlp": {},
        }
        if not C.shared_attention_norm:
            p["norm_2"] = norm_params()
        if C.bias:
            p["attn"]["qkv_b"] = zeros(C.qkv_out)
            p["attn"]["proj_b"] = zeros(C.n_embd)
        if C.mlp_class == "MoEMLP":
            E, H = C.n_expert, C.mlp_hidden
            p["mlp"]["router_w"] = w(E, C.n_embd)
            p["mlp"]["w1"] = w(E, H, C.n_embd)
            p["mlp"]["w3"] = w(E, H, C.n_embd)
            p["mlp"]["w2"] = w(E, C.n_embd, H, std=0.02 / np.sqrt(2 * C.n_layer))
        elif C.mlp_class == "LLaMAMLP":
            p["mlp"]["fc_1_w"] = w(C.mlp_hidden, C.n_embd)
            p["mlp"]["fc_2_w"] = w(C.mlp_hidden, C.n_embd)
            p["mlp"]["proj_w"] = w(C.n_embd, C.mlp_hidden, std=0.02 / np.sqrt(2 * C.n_layer))
            if C.bias:
                p["mlp"]["fc_1_b"] = zeros(C.mlp_hidden)
                p["mlp"]["fc_2_b"] = zeros(C.mlp_hidden)
                p["mlp"]["proj_b"] = zeros(C.n_embd)
        else:
            p["mlp"]["fc_w"] = w(C.mlp_hidden, C.n_embd)
            p["mlp"]["proj_w"] = w(C.n_embd, C.mlp_hidden, std=0.02 / np.sqrt(2 * C.n_layer))
            if C.bias:
                p["mlp"]["fc_b"] = zeros(C.mlp_hidden)
                p["mlp"]["proj_b"] = zeros(C.n_embd)
        return p

    return {
        "wte": w(C.padded_vocab_size, C.n_embd),
        "blocks": [block_params(i) for i in range(C.n_layer)],
        "ln_f": norm_params(),
        "lm_head_w": w(C.padded_vocab_size, C.n_embd),
    }


# =============================================================================
# Forward
# =============================================================================


def _norm(x, p, config: GPTConfig):
    if config.norm_class == "RMSNorm":
        return ttorch.rms_norm(x, (config.n_embd,), p["weight"], eps=config.norm_eps)
    return ttorch.layer_norm(x, (config.n_embd,), p["weight"], p.get("bias"), eps=config.norm_eps)


def _rope_cache(T: int, config: GPTConfig, device, dtype):
    """cos/sin of shape (T, rope_n_elem) — built from iota, so XLA folds them
    into constants of the compiled executable."""
    n = config.rope_n_elem
    half = n // 2
    import thunder_tpu.clang as clang

    theta = clang.pow(float(config.rope_base), clang.true_divide(
        clang.mul(clang.arange(0, half, 1, device=device, dtype=dtypes.float32), -2.0), float(n)))
    pos = clang.arange(0, T, 1, device=device, dtype=dtypes.float32)
    freqs = clang.mul(clang.unsqueeze(pos, 1), clang.unsqueeze(theta, 0))  # (T, half)
    emb = clang.cat([freqs, freqs], dim=1)  # (T, n) rotate-half convention
    return clang.maybe_convert_to_dtype(clang.cos(emb), dtype), clang.maybe_convert_to_dtype(clang.sin(emb), dtype)


def _apply_rope(x, cos, sin, config: GPTConfig):
    """x: (B, H, T, hs); rotate the first rope_n_elem features. Composite op
    so the Pallas rope kernel claims it (pallasex; the decomposed
    rotate-half is lane-misaligned at hs=100)."""
    return ttorch.apply_rope(x, cos, sin)


def _attention(x, p, cos, sin, config: GPTConfig):
    B, T, C = x.shape
    H, G, hs = config.n_head, config.query_groups, config.head_size

    qkv = ttorch.linear(x, p["qkv_w"], p.get("qkv_b"))  # (B, T, (H+2G)*hs)
    q = qkv[..., : H * hs]
    k = qkv[..., H * hs : (H + G) * hs]
    v = qkv[..., (H + G) * hs :]

    q = ttorch.permute(ttorch.reshape(q, (B, T, H, hs)), (0, 2, 1, 3))  # (B,H,T,hs)
    k = ttorch.permute(ttorch.reshape(k, (B, T, G, hs)), (0, 2, 1, 3))
    v = ttorch.permute(ttorch.reshape(v, (B, T, G, hs)), (0, 2, 1, 3))

    q = _apply_rope(q, cos, sin, config)
    k = _apply_rope(k, cos, sin, config)

    y = ttorch.scaled_dot_product_attention(q, k, v, is_causal=True, enable_gqa=(G != H))
    y = ttorch.reshape(ttorch.permute(y, (0, 2, 1, 3)), (B, T, H * hs))
    return ttorch.linear(y, p["proj_w"], p.get("proj_b"))


def _moe_mlp(x, p, config: GPTConfig):
    """Mixtral-style MoE: top-k softmax routing over SwiGLU experts,
    renormalized gate weights. Dense per-token formulation at the trace
    level (every expert computed, top-k selected) — static shapes the MXU
    tiles; the distributed execution path with real token dispatch over an
    ``ep`` mesh axis is thunder_tpu.parallel.moe.moe_mlp."""
    B, T, C = x.shape
    k = config.n_expert_per_token
    xf = ttorch.reshape(x, (B * T, C))
    gate_logits = ttorch.linear(xf, p["router_w"])            # (N, E)
    top_logits, top_i = ttorch.topk(gate_logits, k, -1)       # (N, k)
    gate = ttorch.softmax(top_logits, -1)                     # renormalized over the k chosen
    h = ttorch.silu(ttorch.einsum("nd,ehd->neh", xf, p["w1"])) * ttorch.einsum(
        "nd,ehd->neh", xf, p["w3"]
    )
    all_out = ttorch.einsum("neh,edh->ned", h, p["w2"])       # (N, E, C)
    idx3 = ttorch.expand(ttorch.unsqueeze(top_i, -1), (B * T, k, C))
    sel = ttorch.take_along_dim(all_out, idx3, 1)             # (N, k, C)
    out = ttorch.sum(sel * ttorch.unsqueeze(gate, -1), 1)
    return ttorch.reshape(out, (B, T, C))


def _mlp(x, p, config: GPTConfig):
    if config.mlp_class == "MoEMLP":
        return _moe_mlp(x, p, config)
    if config.mlp_class == "LLaMAMLP":
        h = ttorch.silu(ttorch.linear(x, p["fc_1_w"], p.get("fc_1_b"))) * ttorch.linear(
            x, p["fc_2_w"], p.get("fc_2_b")
        )
        return ttorch.linear(h, p["proj_w"], p.get("proj_b"))
    h = ttorch.gelu(ttorch.linear(x, p["fc_w"], p.get("fc_b")))
    return ttorch.linear(h, p["proj_w"], p.get("proj_b"))


def _block(x, p, cos, sin, config: GPTConfig):
    n1 = _norm(x, p["norm_1"], config)
    attn_out = _attention(n1, p["attn"], cos, sin, config)
    if config.parallel_residual:
        n2 = n1 if config.shared_attention_norm else _norm(x, p["norm_2"], config)
        return x + attn_out + _mlp(n2, p["mlp"], config)
    x = x + attn_out
    return x + _mlp(_norm(x, p["norm_2"], config), p["mlp"], config)


def forward(params: dict, idx, config: GPTConfig):
    """Token ids (B, T) int → logits (B, T, padded_vocab_size)."""
    B, T = idx.shape
    x = ttorch.embedding(idx, params["wte"])  # (B, T, C)
    cos, sin = _rope_cache(T, config, device=x.device, dtype=x.dtype)
    for p in params["blocks"]:
        x = _block(x, p, cos, sin, config)
    x = _norm(x, params["ln_f"], config)
    return ttorch.linear(x, params["lm_head_w"])


def loss_fn(params: dict, idx, targets, config: GPTConfig):
    """Next-token cross-entropy; logits in f32 for a stable softmax."""
    logits = forward(params, idx, config)
    B, T, V = logits.shape
    logits = ttorch.reshape(logits.float(), (B * T, V))
    return ttorch.cross_entropy(logits, ttorch.reshape(targets, (B * T,)))
