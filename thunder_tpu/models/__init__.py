"""Model zoo: litgpt-style transformer family used by the reference's
benchmarks (reference: thunder/tests/lit_gpt_model.py, litgpt's GPT —
pythia/llama/mistral configs exercised in
thunder/benchmarks/benchmark_litgpt.py).
"""

from thunder_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    configs,
    forward,
    init_params,
    loss_fn,
    name_to_config,
)
