"""The verifier's rule registry: every invariant is a named, suppressible rule.

Rules register under a stable dotted id (``ssa.use-before-def``,
``dist.group-size-mismatch``); :func:`thunder_tpu.analysis.verify` runs every
enabled rule over one shared :class:`~thunder_tpu.analysis.context.VerifyContext`
(the trace is walked once; rules consume the precomputed def/use indexes).

Extending: third-party passes register their own invariants with
``@register_rule("mypass.my-invariant")`` — the function receives the
VerifyContext and reports via ``ctx.report(...)``. Suppressing: pass
``disable={"rule.id", ...}`` to ``verify``/``verify_or_raise``, or disable a
rule globally for a process with :func:`set_rule_enabled`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Iterable, Optional


@dataclass
class Rule:
    id: str
    description: str
    fn: Callable
    enabled: bool = True


_RULES: dict[str, Rule] = {}


def register_rule(id: str, description: str = "") -> Callable:
    """Decorator: register ``fn(ctx: VerifyContext) -> None`` under ``id``.

    Re-registering an id replaces the rule (lets tests shadow a built-in).
    """

    def deco(fn: Callable) -> Callable:
        _RULES[id] = Rule(id=id, description=description or (fn.__doc__ or "").strip(), fn=fn)
        return fn

    return deco


def all_rules() -> dict[str, Rule]:
    _ensure_builtin_rules()
    return dict(_RULES)


def get_rule(id: str) -> Optional[Rule]:
    _ensure_builtin_rules()
    return _RULES.get(id)


def set_rule_enabled(id: str, enabled: bool) -> None:
    _ensure_builtin_rules()
    rule = _RULES.get(id)
    if rule is None:
        raise KeyError(f"No such verifier rule: {id!r} (known: {sorted(_RULES)})")
    rule.enabled = enabled


def enabled_rules(disable: Iterable[str] = ()) -> list[Rule]:
    _ensure_builtin_rules()
    off = set(disable)
    return [r for r in _RULES.values() if r.enabled and r.id not in off]


_builtins_loaded = False


def _ensure_builtin_rules() -> None:
    """Import the built-in rule modules exactly once (registration happens at
    module import). Deferred so registry import carries no dependency weight."""
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    from thunder_tpu.analysis import collectives, hlo_audit, liveness, rules, schedule  # noqa: F401
