"""VerifyContext: one walk over a trace, shared by every rule.

The context precomputes the def/use structure of the top-level bound symbols
— producing bsym per proxy name, every consuming site, trace inputs (signature
params + arg/kwarg proxies), trace outputs — so each rule is a cheap pass over
indexes rather than another O(trace) walk with its own pytree flattening.
"""

from __future__ import annotations

from typing import Any, Optional

from thunder_tpu.analysis.diagnostics import Diagnostic, Severity
from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.core.proxies import (
    AnyProxy,
    CollectionProxy,
    FutureTensorProxy,
    NumberProxy,
    Proxy,
    TensorProxy,
)
from thunder_tpu.core.pytree import tree_flatten
from thunder_tpu.core.trace import TraceCtx


def pass_name_of(trace: TraceCtx) -> Optional[str]:
    """The provenance pass name, stripped of the timing suffix."""
    if trace.provenance is None:
        return None
    return trace.provenance.pss.split(" (took ")[0]


def needs_definition(p: Proxy) -> bool:
    """Whether a consumed proxy must have a producer (or be a trace input).

    Tensor/future/collection proxies always flow through defs. Number and
    string proxies with a *known* value are guard-baked constants — legal to
    reference without a producer — but an unknown number (e.g. ``item()``'s
    result) must be produced in-trace. ``AnyProxy`` wraps unguardable baked
    leaves and is exempt.
    """
    if isinstance(p, (TensorProxy, CollectionProxy)):
        return True
    if isinstance(p, NumberProxy):
        return p.value is None
    return False


class VerifyContext:
    def __init__(self, trace: TraceCtx, pass_name: Optional[str] = None):
        self.trace = trace
        self.pass_name = pass_name if pass_name is not None else pass_name_of(trace)
        self.diagnostics: list[Diagnostic] = []
        self.bsyms = list(trace.bound_symbols)

        # -- trace inputs ----------------------------------------------------
        self.input_names: set[str] = set()
        flat_inputs, _ = tree_flatten((trace.args, trace.kwargs))
        for p in flat_inputs:
            if isinstance(p, Proxy):
                self.input_names.add(p.name)
        sig = trace.siginfo
        self.input_names.update(n for n in sig.params if isinstance(n, str))
        if sig.varargs:
            self.input_names.add(sig.varargs)
        if sig.varkwargs:
            self.input_names.add(sig.varkwargs)

        # -- trace outputs ---------------------------------------------------
        self.output_names: set[str] = set()
        self.output_proxies: list[Proxy] = []
        flat_out, _ = tree_flatten(trace.output)
        for p in flat_out:
            if isinstance(p, Proxy):
                self.output_names.add(p.name)
                self.output_proxies.append(p)

        # -- one walk: defs, redefs, uses ------------------------------------
        # name -> (bsym index of producer, proxy object)
        self.defs: dict[str, tuple[int, Proxy]] = {}
        # (bsym index, name, index of previous producer)
        self.redefs: list[tuple[int, str, int]] = []
        # name -> all consuming bsym indexes (python_del included)
        self.uses: dict[str, list[int]] = {}
        # name -> consuming bsym indexes that keep the value live (del excluded)
        self.live_uses: dict[str, list[int]] = {}
        # names produced as FutureTensorProxy: name -> producer index
        self.future_defs: dict[str, int] = {}

        for i, bsym in enumerate(self.bsyms):
            is_del = bsym.sym.id is PrimIDs.DEL
            arg_names: set[str] = set()
            for p in bsym.flat_proxy_args:
                arg_names.add(p.name)
                sites = self.uses.setdefault(p.name, [])
                if not sites or sites[-1] != i:  # one entry per consuming bsym
                    sites.append(i)
                if not is_del:
                    live = self.live_uses.setdefault(p.name, [])
                    if not live or live[-1] != i:
                        live.append(i)
            seen_out: set[str] = set()
            for o in bsym.flat_proxy_outs:
                # Pass-through (output IS an operand, e.g. unpack_trivial or an
                # identity composite) is not a definition; so is the same proxy
                # repeated within one output tree (e.g. (t, t)).
                if o.name in arg_names or o.name in seen_out:
                    continue
                seen_out.add(o.name)
                prev = self.defs.get(o.name)
                if prev is not None:
                    self.redefs.append((i, o.name, prev[0]))
                    continue
                self.defs[o.name] = (i, o)
                if isinstance(o, FutureTensorProxy):
                    self.future_defs[o.name] = i

    # -- queries used by rules ------------------------------------------------

    def defined_before(self, name: str, index: int) -> bool:
        if name in self.input_names:
            return True
        d = self.defs.get(name)
        return d is not None and d[0] < index

    def is_live_output(self, name: str) -> bool:
        return name in self.output_names

    def consumed_after(self, name: str, index: int, *, live_only: bool = True) -> Optional[int]:
        """First bsym index > ``index`` consuming ``name`` (None if none)."""
        sites = (self.live_uses if live_only else self.uses).get(name, ())
        for i in sites:
            if i > index:
                return i
        return None

    # -- reporting -------------------------------------------------------------

    def report(
        self,
        rule: str,
        severity: Severity,
        message: str,
        *,
        bsym_index: Optional[int] = None,
        hint: Optional[str] = None,
    ) -> None:
        self.diagnostics.append(
            Diagnostic(
                rule=rule,
                severity=severity,
                message=message,
                bsym_index=bsym_index,
                pass_name=self.pass_name,
                hint=hint,
            )
        )
