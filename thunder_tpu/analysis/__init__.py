"""Static trace verification and linting.

A rule-based verifier over :class:`~thunder_tpu.core.trace.TraceCtx`: the
trace is walked once into a :class:`VerifyContext` and a registry of named
rules checks the invariants every transform pass must preserve —

- ``ssa.*``           def-use discipline (use-before-def, redefinition, live outputs)
- ``meta.*``          output shape/dtype/device vs re-running the prim's meta
- ``alias.*``         in-place ops whose destination is still consumed later
- ``dce.*``           side-effect-free symbols with no consumers
- ``names.*``         name-registry hygiene
- ``dist.*``          collective mesh-axis/group consistency, future/wait pairing,
                      fw/bw collective balance
- ``donation.*``      donated-buffer hazards (rerun paths reading donated
                      inputs, donated inputs returned as outputs)
- ``mem.*``           predicted peak HBM vs device capacity (liveness planner)
- ``sched.*``         per-axis collective ordering vs the stamped schedule
                      certificate
- ``hlo.*``           compiled-HLO findings (partitioner-inserted exposed
                      collectives, layout copies, padding waste, host
                      transfers) from the post-compile auditor (hlo_audit.py)

Pipeline wiring: with ``THUNDER_TPU_CHECKS=1`` (or ``jit(debug_checks=True)``)
every pass's ``wrap_in_trace_provenance``/``mark`` runs :func:`verify_or_raise`
on its output, attributing the first failing diagnostic to the pass that
introduced it. User-facing: ``thunder_tpu.examine.lint(fn, *args)``.

Docs: docs/trace_invariants.md lists every rule id and the suppression and
extension (``register_rule``) story.
"""

from __future__ import annotations

from typing import Iterable, Optional

from thunder_tpu.analysis.diagnostics import (  # noqa: F401
    Diagnostic,
    Severity,
    TraceVerificationError,
    attach_trace_lines,
    max_severity,
)
from thunder_tpu.analysis.context import VerifyContext, pass_name_of  # noqa: F401
from thunder_tpu.analysis.cost import (  # noqa: F401
    DEVICE_SPECS,
    DeviceSpec,
    OpCost,
    TraceCost,
    bsym_cost,
    calibrate_ici,
    collective_sym_class,
    cost_report,
    resolve_device_spec,
    trace_cost,
)
from thunder_tpu.analysis.events import format_replay, merge_event_logs, replay_events  # noqa: F401
from thunder_tpu.analysis.hlo_audit import (  # noqa: F401
    HloCollectiveSite,
    HloScheduleReport,
    audit_hlo,
    audit_jitted,
    parse_hlo_module,
)
from thunder_tpu.analysis.liveness import (  # noqa: F401
    MemoryPlan,
    arg_divisors_from_specs,
    device_capacity_bytes,
    memory_report,
    plan_liveness,
    predict_level_peaks,
)
from thunder_tpu.analysis.schedule import (  # noqa: F401
    CollectiveSite,
    OverlapPrediction,
    ScheduleCertificate,
    SiteOverlap,
    certify,
    predict_overlap,
    recertify,
)
from thunder_tpu.analysis.registry import (  # noqa: F401
    Rule,
    all_rules,
    enabled_rules,
    get_rule,
    register_rule,
    set_rule_enabled,
)
from thunder_tpu.core.trace import TraceCtx, tracectx


def verify(
    trace: TraceCtx,
    *,
    pass_name: Optional[str] = None,
    disable: Iterable[str] = (),
    with_trace_lines: bool = False,
) -> list[Diagnostic]:
    """Run every enabled rule over ``trace``; return structured diagnostics.

    ``pass_name`` overrides the provenance-derived attribution. ``disable``
    suppresses rule ids (both rule execution and their findings). Rules run
    under a detached (None) trace context so meta re-runs can never record
    into, or mint names in, a live trace.
    """
    off = set(disable)
    ctx = VerifyContext(trace, pass_name=pass_name)
    with tracectx(None):
        for rule in enabled_rules(disable=off):
            rule.fn(ctx)
    diags = [d for d in ctx.diagnostics if d.rule not in off]
    if with_trace_lines:
        attach_trace_lines(diags, trace)
    return diags


def verify_or_raise(
    trace: TraceCtx,
    *,
    pass_name: Optional[str] = None,
    disable: Iterable[str] = (),
    min_severity: Severity = Severity.ERROR,
) -> list[Diagnostic]:
    """Verify ``trace``; raise :class:`TraceVerificationError` if any
    diagnostic reaches ``min_severity``. Returns the (sub-threshold)
    diagnostics otherwise, so callers can surface warnings."""
    diags = verify(trace, pass_name=pass_name, disable=disable, with_trace_lines=True)
    failing = [d for d in diags if d.severity >= min_severity]
    if failing:
        raise TraceVerificationError(diags, pass_name=pass_name or pass_name_of(trace))
    return diags
