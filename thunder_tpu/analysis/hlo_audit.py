"""HLO-level static auditor: the compiled-executable twin of the trace verifier.

The PR 10/12 static-analysis stack (liveness planner, ScheduleCertificate,
comm scheduler) reads *traces* — it only sees collectives the program spells
out as ``dist_prims``. The production pjit path (``parallel/train.py
build_train_step``) spells out none: its collectives are inserted by the XLA
SPMD partitioner during lowering and are invisible to every trace-level rule
(ROADMAP item 3). This module closes that blind spot by auditing the artifact
the partitioner actually produced: the compiled-HLO text, reached through the
same access path the measured half already trusts
(``attribution.scope_map_of`` → ``lowered.compile().as_text()``).

Pipeline:

1. **Parse** the HLO text into computations of :class:`HloOp`s — one shared
   line lexer (:func:`iter_op_metadata` is the second consumer, backing
   ``observability/attribution.hlo_scope_map`` so the two HLO readers cannot
   drift).
2. **Classify** every op: collective family (all-gather / all-reduce /
   reduce-scatter / collective-permute / ...), fusion, layout copy, host
   transfer; collectives are split into *partitioner-inserted* vs *explicit*
   by whether their ``op_name`` metadata scope resolves to a trace-level
   collective symbol. A CPU/GPU-partitioner idiom is recovered structurally:
   an all-reduce whose every consumer slices a strict shard of its output is
   a reduce-scatter the backend chose to spell as all-reduce+slice, and is
   classified (and priced, at the (g−1)/g ring factor) as ``reduce-scatter``
   with ``derived=True``.
3. **Price** each op against the PR 5 cost model
   (:func:`analysis.cost.hlo_op_cost` — the HLO-op → FLOPs/HBM/ICI rules,
   shapes and dtypes parsed from the HLO types).
4. **Schedule-analyze**: the happens-before / exposed-wire analysis of
   ``sched.exposed-collective`` re-run at HLO level — per collective site,
   the roofline compute between the site and its first consumer is the
   overlap window; windows share a per-op budget so two sites never claim
   the same fusion. The resulting :class:`HloScheduleReport` carries
   ``exposed_pct`` — committed by ``scripts/bench_multichip.py`` as
   ``spmd_collective_exposed_pct_static``, the baseline number ROADMAP
   item 3's scheduling-hints work is measured against.

Advisory by construction: the ``hlo.*`` verifier rules report INFO/WARNING
only, and the ``api.py`` compile phase wraps the whole audit in a
``sharp_edge`` guard — a corrupted HLO text never fails a compile.

User entry point: ``thunder_tpu.examine.hlo_report(fn, *args)``.
Docs: docs/performance.md (§HLO auditor), docs/trace_invariants.md (rules).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from thunder_tpu.analysis.diagnostics import Diagnostic, Severity
from thunder_tpu.analysis.registry import register_rule

__all__ = [
    "HloOp",
    "HloComputation",
    "HloModule",
    "HloCollectiveSite",
    "HloScheduleReport",
    "parse_hlo_module",
    "iter_op_metadata",
    "audit_hlo",
    "audit_jitted",
]


# =============================================================================
# Shared line lexer (one tokenizer, two consumers)
# =============================================================================

# One instruction per line: `%name = <type> <opcode>(<operands>), attrs...`.
# The metadata sub-pattern is the exact historical `attribution._HLO_META_RE`
# so the scope-map consumer stays byte-identical across the refactor.
_NAME_META_RE = re.compile(r"%([\w.\-]+)\s*=.*?op_name=\"([^\"]+)\"")
_INSTR_HEAD_RE = re.compile(r"^\s*(ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_COMP_HEAD_RE = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s*\(")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")
_COMMENT_RE = re.compile(r"/\*.*?\*/")
_OP_NAME_RE = re.compile(r"op_name=\"([^\"]+)\"")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{\{([0-9,]*)\}")
_REPLICA_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_SOURCE_TARGET_RE = re.compile(r"source_target_pairs=\{\{")
_CALLS_RE = re.compile(r"calls=%([\w.\-]+)")
_TO_APPLY_RE = re.compile(r"to_apply=%([\w.\-]+)")
_BODY_RE = re.compile(r"body=%([\w.\-]+)")
_COND_RE = re.compile(r"condition=%([\w.\-]+)")
_LHS_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_CUSTOM_TARGET_RE = re.compile(r"custom_call_target=\"([^\"]+)\"")


def iter_op_metadata(hlo_text: str) -> Iterator[tuple[str, str]]:
    """Yield ``(hlo op name, metadata op_name)`` per instruction line carrying
    ``op_name`` metadata — the lexer slice behind
    ``observability/attribution.hlo_scope_map`` (its historical per-line
    regex semantics: one entry per line, later duplicates overwrite)."""
    for m in _NAME_META_RE.finditer(hlo_text):
        yield m.group(1), m.group(2)


# HLO primitive-type widths in bytes (sub-byte types rounded up: the HBM
# picture of a packed s4 tensor is still byte-granular per XLA's layouts).
HLO_DTYPE_BYTES: dict[str, int] = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e5m2": 1, "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1, "f8e3m4": 1, "f8e8m0fnu": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "c64": 8,
    "s64": 8, "u64": 8, "f64": 8, "c128": 16,
    "token": 0, "opaque": 0,
}


def _dtype_bytes(dtype: str) -> int:
    return HLO_DTYPE_BYTES.get(dtype, 4)


def _dtype_class(dtype: str) -> str:
    """Peak-FLOPs class of an HLO primitive type (DeviceSpec.peak_flops key)."""
    n = _dtype_bytes(dtype)
    if dtype.startswith(("s", "u", "pred")):
        return "int8" if n <= 1 else "f32"
    return "bf16" if n <= 2 else "f32"


def _numel(dims: tuple) -> float:
    n = 1.0
    for d in dims:
        n *= d
    return n


# Collective opcodes; `-start`/`-done` suffixes map onto the same family.
_COLLECTIVE_FAMILIES = frozenset({
    "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    "all-to-all", "collective-broadcast", "ragged-all-to-all",
})

_HOST_TRANSFER_OPCODES = frozenset({
    "send", "recv", "send-done", "recv-done", "infeed", "outfeed",
})


@dataclass
class HloOp:
    """One parsed HLO instruction with the derived scalars the cost model
    prices (:func:`analysis.cost.hlo_op_cost` consumes exactly these
    fields — keep them in sync with its documented protocol)."""

    name: str
    opcode: str
    result_type: str
    shapes: list  # [(dtype, (dims...)), ...] — tuple results carry several
    operands: list  # operand op names (same computation)
    index: int
    computation: str = ""
    is_root: bool = False
    op_name: str = ""  # metadata op_name path ("" when absent)
    attrs_text: str = ""
    # -- derived, filled by the parser/auditor --
    result_numel: float = 0.0
    result_bytes: float = 0.0
    operand_numel: float = 0.0
    operand_bytes: float = 0.0
    group_size: int = 1
    k_dim: float = 0.0  # dot/conv contraction size
    family: Optional[str] = None  # collective family after classification
    derived: bool = False  # True: all-reduce+slice recovered as reduce-scatter
    calls: Optional[str] = None  # fusion/called computation name

    @property
    def base_family(self) -> Optional[str]:
        """Collective family straight from the opcode (before the derived
        reduce-scatter reclassification), or None."""
        op = self.opcode
        for suffix in ("-start", "-done"):
            if op.endswith(suffix):
                op = op[: -len(suffix)]
        return op if op in _COLLECTIVE_FAMILIES else None

    @property
    def is_collective_site(self) -> bool:
        """True for the issuing op of a collective (`-done` halves excluded)."""
        return self.base_family is not None and not self.opcode.endswith("-done")


@dataclass
class HloComputation:
    name: str
    is_entry: bool = False
    ops: list = field(default_factory=list)
    defs: dict = field(default_factory=dict)  # op name -> index

    def consumers_of(self, name: str) -> list:
        return [op for op in self.ops if name in op.operands]


@dataclass
class HloModule:
    name: str
    computations: list = field(default_factory=list)
    by_name: dict = field(default_factory=dict)

    @property
    def entry(self) -> Optional[HloComputation]:
        for c in self.computations:
            if c.is_entry:
                return c
        return self.computations[-1] if self.computations else None

    @property
    def n_ops(self) -> int:
        return sum(len(c.ops) for c in self.computations)


def _split_result_type(rest: str) -> tuple[str, str]:
    """Split `<type> <opcode>(...)` into (type string, remainder). Tuple
    result types are parenthesized and contain spaces; scalar/array types
    contain none."""
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return rest[: i + 1], rest[i + 1:].lstrip()
        return rest, ""
    type_str, _, remainder = rest.partition(" ")
    return type_str, remainder.lstrip()


def _split_call(remainder: str) -> tuple[str, str, str]:
    """Split `opcode(operands), attrs` into (opcode, operands, attrs)."""
    lp = remainder.find("(")
    if lp < 0:
        return remainder.strip(), "", ""
    opcode = remainder[:lp].strip()
    depth = 0
    for i in range(lp, len(remainder)):
        ch = remainder[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return opcode, remainder[lp + 1: i], remainder[i + 1:]
    return opcode, remainder[lp + 1:], ""


def _parse_shapes(type_str: str) -> list:
    return [
        (m.group(1), tuple(int(d) for d in m.group(2).split(",") if d))
        for m in _SHAPE_RE.finditer(type_str)
    ]


def _parse_instruction(line: str, index: int) -> Optional[HloOp]:
    m = _INSTR_HEAD_RE.match(line)
    if m is None:
        return None
    rest = _COMMENT_RE.sub("", m.group(3)).strip()
    type_str, remainder = _split_result_type(rest)
    opcode, operand_str, attrs = _split_call(remainder)
    if not opcode or not opcode[0].isalpha():
        return None
    shapes = _parse_shapes(type_str)
    op = HloOp(
        name=m.group(2),
        opcode=opcode,
        result_type=type_str,
        shapes=shapes,
        operands=_OPERAND_RE.findall(operand_str),
        index=index,
        is_root=bool(m.group(1)),
        attrs_text=attrs,
    )
    nm = _OP_NAME_RE.search(attrs)
    if nm:
        op.op_name = nm.group(1)
    cm = _CALLS_RE.search(attrs)
    if cm:
        op.calls = cm.group(1)
    op.result_numel = sum(_numel(dims) for _, dims in shapes) if shapes else 0.0
    op.result_bytes = sum(_numel(dims) * _dtype_bytes(dt) for dt, dims in shapes)
    op.group_size = _parse_group_size(attrs)
    km = _LHS_CONTRACT_RE.search(attrs)
    if km:
        op._lhs_contract = tuple(int(d) for d in km.group(1).split(",") if d)
    return op


def _parse_group_size(attrs: str) -> int:
    m = _REPLICA_GROUPS_RE.search(attrs)
    if m:
        ids = [t for t in m.group(1).split(",") if t]
        return max(1, len(ids))
    m = _REPLICA_IOTA_RE.search(attrs)
    if m:  # iota v2 format: [num_groups, group_size]<=[...]
        return max(1, int(m.group(2)))
    if _SOURCE_TARGET_RE.search(attrs):
        return 2  # permute: pairwise — factor is 1.0 regardless
    return 1


def parse_hlo_module(hlo_text: str) -> HloModule:
    """Parse compiled-HLO text into an :class:`HloModule` op graph.

    Raises ``ValueError`` when the text contains no parseable computation —
    the signal the advisory wrapper turns into a ``sharp_edge``."""
    if not isinstance(hlo_text, str) or not hlo_text.strip():
        raise ValueError("empty HLO text")
    module_name = ""
    mm = re.match(r"HloModule\s+([\w.\-]+)", hlo_text)
    if mm:
        module_name = mm.group(1)
    module = HloModule(name=module_name)
    current: Optional[HloComputation] = None
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith("//"):
            continue
        if stripped == "}":
            current = None
            continue
        if not line[:1].isspace():
            ch = _COMP_HEAD_RE.match(line)
            if ch and stripped.endswith("{"):
                current = HloComputation(name=ch.group(2), is_entry=bool(ch.group(1)))
                module.computations.append(current)
                module.by_name[current.name] = current
            continue
        if current is None:
            continue
        op = _parse_instruction(line, len(current.ops))
        if op is None:
            continue
        op.computation = current.name
        current.ops.append(op)
        current.defs[op.name] = op.index
    module.computations = [c for c in module.computations if c.ops]
    module.by_name = {c.name: c for c in module.computations}
    if not module.computations:
        raise ValueError("no parseable HLO computations found")
    # Resolve per-op operand totals (operands are in-computation: parameters
    # are instruction lines too) and the dot contraction size.
    for comp in module.computations:
        index = {op.name: op for op in comp.ops}
        for op in comp.ops:
            for o in op.operands:
                src = index.get(o)
                if src is not None:
                    op.operand_numel += src.result_numel
                    op.operand_bytes += src.result_bytes
            if op.opcode in ("dot", "convolution") and op.operands:
                op.k_dim = _contract_k(op, index)
    return module


def _contract_k(op: HloOp, index: dict) -> float:
    lhs = index.get(op.operands[0])
    if lhs is None or not lhs.shapes:
        return 0.0
    dims = lhs.shapes[0][1]
    if op.opcode == "convolution":
        # cin·∏kernel of the weight operand — out-feature dim divided out.
        w = index.get(op.operands[1]) if len(op.operands) > 1 else None
        if w is not None and w.shapes and w.shapes[0][1]:
            wd = w.shapes[0][1]
            return _numel(wd) / max(1, wd[0])
        return 0.0
    contract = getattr(op, "_lhs_contract", None)
    if contract:
        k = 1.0
        for d in contract:
            if 0 <= d < len(dims):
                k *= dims[d]
        return k
    return float(dims[-1]) if dims else 0.0


# =============================================================================
# Classification
# =============================================================================

_SLICE_OPCODES = frozenset({"slice", "dynamic-slice"})


def _is_shard_slice(consumer: HloOp, producer: HloOp, module: HloModule) -> bool:
    """Whether ``consumer`` takes a strict shard of ``producer``'s output —
    a direct slice, or a kLoop fusion whose body slices (the partitioner's
    spelling after fusion)."""
    if consumer.result_numel <= 0 or consumer.result_numel >= producer.result_numel:
        return False
    if consumer.opcode in _SLICE_OPCODES:
        return True
    if consumer.opcode == "fusion" and consumer.calls:
        body = module.by_name.get(consumer.calls)
        if body is not None:
            return any(o.opcode in _SLICE_OPCODES for o in body.ops)
    return False


def _classify_collectives(module: HloModule) -> None:
    """Stamp ``op.family`` on every collective site; recover the
    all-reduce+shard-slice spelling of reduce-scatter (the partitioner emits
    it on backends without a native reduce-scatter pass — every consumer
    slices a strict shard, so the program provably only needs the scattered
    result and the ring only needs to move (g−1)/g of it)."""
    for comp in module.computations:
        consumers: dict[str, list] = {}
        for op in comp.ops:
            for o in op.operands:
                consumers.setdefault(o, []).append(op)
        for op in comp.ops:
            fam = op.base_family
            if fam is None:
                continue
            op.family = fam
            if fam != "all-reduce" or op.opcode.endswith("-done"):
                continue
            cons = [c for c in consumers.get(op.name, []) if c.base_family is None]
            if cons and all(_is_shard_slice(c, op, module) for c in cons):
                op.family = "reduce-scatter"
                op.derived = True


def _scope_sym(op_name: str) -> Optional[str]:
    from thunder_tpu.observability.attribution import parse_scope

    ref = parse_scope(op_name)
    return ref.sym if ref is not None else None


def _is_inserted(op: HloOp) -> bool:
    """Partitioner-inserted vs explicit: an explicit ``dist_prims``
    collective lowers under its own trace line's scope, so its metadata
    scope symbol maps to a collective family; anything else (a compute-op
    scope, or no scope at all) was inserted during partitioning."""
    from thunder_tpu.observability.attribution import COLLECTIVE_SYM_CLASS

    sym = _scope_sym(op.op_name)
    return not (sym is not None and sym in COLLECTIVE_SYM_CLASS)


# =============================================================================
# Schedule analysis + report
# =============================================================================


@dataclass
class HloCollectiveSite:
    """One collective site in the compiled executable: wire bytes/time from
    the cost model, window/hidden from the HLO-level happens-before scan —
    the pjit-path twin of :class:`analysis.schedule.SiteOverlap`."""

    name: str
    opcode: str
    family: str
    computation: str
    index: int
    group_size: int
    wire_bytes: float
    wire_us: float
    window_us: float
    hidden_us: float
    first_consumer: Optional[int] = None
    inserted: bool = True
    derived: bool = False
    scope: str = ""

    @property
    def exposed_us(self) -> float:
        return max(0.0, self.wire_us - self.hidden_us)

    def label(self) -> str:
        return f"{self.computation}/%{self.name}"

    def to_json(self) -> dict:
        return {
            "name": self.name, "opcode": self.opcode, "family": self.family,
            "computation": self.computation, "index": self.index,
            "group_size": self.group_size,
            "wire_bytes": self.wire_bytes,
            "wire_us": round(self.wire_us, 3),
            "window_us": round(self.window_us, 3),
            "hidden_us": round(self.hidden_us, 3),
            "exposed_us": round(self.exposed_us, 3),
            "first_consumer": self.first_consumer,
            "inserted": self.inserted, "derived": self.derived,
            "scope": self.scope,
        }


@dataclass
class HloScheduleReport:
    """Everything the auditor recovered from one compiled executable."""

    module: str
    device: str
    n_ops: int = 0
    n_computations: int = 0
    sites: list = field(default_factory=list)
    by_family: dict = field(default_factory=dict)
    fusions: int = 0
    layout_copies: int = 0
    layout_copy_bytes: float = 0.0
    host_transfers: int = 0
    host_transfer_ops: list = field(default_factory=list)
    flops: float = 0.0
    hbm_bytes: float = 0.0
    comm_bytes: float = 0.0
    compute_us: float = 0.0
    pad_fractions: dict = field(default_factory=dict)
    audit_s: float = 0.0

    @property
    def wire_us(self) -> float:
        return sum(s.wire_us for s in self.sites)

    @property
    def hidden_us(self) -> float:
        return sum(s.hidden_us for s in self.sites)

    @property
    def exposed_us(self) -> float:
        return sum(s.exposed_us for s in self.sites)

    @property
    def exposed_pct(self) -> float:
        """Exposed fraction of total predicted wire time (percent) — the
        static base of ``spmd_collective_exposed_pct``."""
        return self.exposed_us / self.wire_us * 100.0 if self.wire_us else 0.0

    @property
    def inserted_collectives(self) -> int:
        return sum(1 for s in self.sites if s.inserted)

    @property
    def explicit_collectives(self) -> int:
        return sum(1 for s in self.sites if not s.inserted)

    def to_json(self) -> dict:
        return {
            "v": 1,
            "module": self.module,
            "device": self.device,
            "n_ops": self.n_ops,
            "n_computations": self.n_computations,
            "collectives": {k: dict(v) for k, v in sorted(self.by_family.items())},
            "inserted_collectives": self.inserted_collectives,
            "explicit_collectives": self.explicit_collectives,
            "fusions": self.fusions,
            "layout_copies": {"count": self.layout_copies, "bytes": self.layout_copy_bytes},
            "host_transfers": self.host_transfers,
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "comm_bytes": self.comm_bytes,
            "compute_us": round(self.compute_us, 3),
            "wire_us": round(self.wire_us, 3),
            "hidden_us": round(self.hidden_us, 3),
            "exposed_us": round(self.exposed_us, 3),
            "exposed_pct": round(self.exposed_pct, 2),
            "pad_fractions": dict(self.pad_fractions),
            "audit_s": self.audit_s,
            "sites": [s.to_json() for s in self.sites],
        }

    def format(self) -> str:
        lines = [
            f"hlo audit [{self.module or 'module'} @ {self.device}]: "
            f"{self.n_ops} ops / {self.n_computations} computations, "
            f"{len(self.sites)} collectives ({self.inserted_collectives} "
            f"partitioner-inserted), {self.fusions} fusions, "
            f"{self.layout_copies} layout copies, {self.host_transfers} host transfers",
            f"  wire {self.wire_us:.1f}us, hidden {self.hidden_us:.1f}us, "
            f"exposed {self.exposed_us:.1f}us ({self.exposed_pct:.1f}%)",
        ]
        for fam, agg in sorted(self.by_family.items()):
            lines.append(
                f"  {fam:<20} n={agg['count']:<3} wire {agg['wire_bytes']/1e6:9.3f} MB"
                f"  {agg['wire_us']:9.1f}us"
            )
        lines.append(
            f"  {'site':<34} {'family':<16} {'wire us':>9} {'window':>9} "
            f"{'hidden':>9} {'exposed':>9}"
        )
        for s in sorted(self.sites, key=lambda s: -s.wire_us)[:20]:
            lines.append(
                f"  {s.label():<34.34} {s.family + ('*' if s.derived else ''):<16} "
                f"{s.wire_us:>9.2f} {s.window_us:>9.2f} {s.hidden_us:>9.2f} "
                f"{s.exposed_us:>9.2f}"
            )
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.format()

    def diagnostics(self) -> list:
        """The ``hlo.*`` rule findings over this report, context-free — what
        ``examine.hlo_report`` prints without needing a trace to verify."""
        diags: list[Diagnostic] = []
        _report_exposed(self, lambda *a, **k: diags.append(_diag(*a, **k)))
        _report_layout_copy(self, lambda *a, **k: diags.append(_diag(*a, **k)))
        _report_padding(self, lambda *a, **k: diags.append(_diag(*a, **k)))
        _report_host_transfer(self, lambda *a, **k: diags.append(_diag(*a, **k)))
        return diags


def _diag(rule: str, severity: Severity, message: str, *, hint: Optional[str] = None,
          bsym_index: Optional[int] = None) -> Diagnostic:
    return Diagnostic(rule=rule, severity=severity, message=message, hint=hint,
                      bsym_index=bsym_index)


def audit_hlo(hlo_text: str, *, device: Any = None,
              pad_fractions: Optional[dict] = None) -> HloScheduleReport:
    """Parse, classify, price, and schedule-analyze one compiled-HLO text.

    Raises on unparseable input (the ``api.py`` phase and ``examine`` wrap
    this in the advisory ``sharp_edge`` guard). ``pad_fractions`` (class
    label → padded-away fraction, from the bucket spec) ride along for the
    ``hlo.padding-waste`` rule."""
    from thunder_tpu.analysis.cost import hlo_op_cost, resolve_device_spec

    dev = resolve_device_spec(device)
    module = parse_hlo_module(hlo_text)
    _classify_collectives(module)

    report = HloScheduleReport(
        module=module.name, device=dev.name,
        n_ops=module.n_ops, n_computations=len(module.computations),
        pad_fractions=dict(pad_fractions or {}),
    )

    # Computations a fusion op calls are priced at their call site (boundary
    # bytes + body FLOPs); everything else (entry, while bodies, reducers)
    # is priced standalone, once.
    fused_comps = {
        op.calls
        for comp in module.computations
        for op in comp.ops
        if op.opcode == "fusion" and op.calls
    }

    def inner_flops(comp_name: Optional[str]) -> float:
        body = module.by_name.get(comp_name or "")
        if body is None:
            return 0.0
        total = 0.0
        for o in body.ops:
            c = hlo_op_cost(o)
            if c is not None:
                total += c.flops
        return total

    for comp in module.computations:
        if comp.name in fused_comps:
            continue
        # def-use within the computation: the happens-before order is the
        # instruction order (compiled modules are scheduled).
        first_consumer: dict[str, int] = {}
        for op in comp.ops:
            for o in op.operands:
                first_consumer.setdefault(o, op.index)

        compute_us: dict[int, float] = {}
        rows: dict[int, tuple] = {}
        for op in comp.ops:
            cost = hlo_op_cost(
                op, inner_flops=inner_flops(op.calls) if op.opcode == "fusion" else 0.0
            )
            if cost is None:
                continue
            dclass = op.shapes[0][0] if op.shapes else "f32"
            t = 0.0
            if cost.flops:
                t = max(t, cost.flops / dev.peak_flops.get(_dtype_class(dclass), dev.peak_flops["f32"]))
            if cost.bytes_moved and dev.hbm_bw:
                t = max(t, cost.bytes_moved / dev.hbm_bw)
            report.flops += cost.flops
            report.hbm_bytes += cost.bytes_moved
            report.comm_bytes += cost.comm_bytes
            rows[op.index] = (cost, t)
            if cost.kind == "fusion":
                report.fusions += 1
            if op.opcode in ("copy", "copy-start"):
                report.layout_copies += 1
                report.layout_copy_bytes += 2.0 * op.result_bytes
            if op.opcode in _HOST_TRANSFER_OPCODES or (
                op.opcode == "custom-call" and _is_host_custom_call(op)
            ) or ":S(" in op.result_type:
                report.host_transfers += 1
                report.host_transfer_ops.append(f"{comp.name}/%{op.name}")
            if not op.is_collective_site:
                compute_us[op.index] = t * 1e6

        # Shared-budget window scan — the exact predict_overlap model, over
        # HLO instruction order: window compute between a site and its first
        # consumer hides wire time; each op's budget is consumed in program
        # order so two sites never claim the same fusion.
        budget = dict(compute_us)
        for op in comp.ops:
            if not op.is_collective_site:
                continue
            cost, _t = rows.get(op.index, (None, 0.0))
            wire_bytes = cost.comm_bytes if cost is not None else 0.0
            fam = op.family or "all-reduce"
            bw = dev.ici_bw_for(fam)
            wire_us = wire_bytes / bw * 1e6 if bw else 0.0
            consumer = first_consumer.get(op.name)
            if consumer is not None:
                done = comp.ops[consumer]
                if done.opcode.endswith("-done"):
                    consumer = first_consumer.get(done.name)
            window = 0.0
            hidden = 0.0
            if consumer is not None:
                for j in range(op.index + 1, consumer):
                    avail = budget.get(j, 0.0)
                    window += compute_us.get(j, 0.0)
                    if avail and hidden < wire_us:
                        take = min(avail, wire_us - hidden)
                        budget[j] = avail - take
                        hidden += take
            site = HloCollectiveSite(
                name=op.name, opcode=op.opcode, family=fam,
                computation=comp.name, index=op.index,
                group_size=op.group_size, wire_bytes=wire_bytes,
                wire_us=wire_us, window_us=window,
                hidden_us=min(hidden, wire_us), first_consumer=consumer,
                inserted=_is_inserted(op), derived=op.derived,
                scope=op.op_name,
            )
            report.sites.append(site)
            agg = report.by_family.setdefault(
                fam, {"count": 0, "wire_bytes": 0.0, "wire_us": 0.0, "inserted": 0}
            )
            agg["count"] += 1
            agg["wire_bytes"] += wire_bytes
            agg["wire_us"] += wire_us
            if site.inserted:
                agg["inserted"] += 1
        report.compute_us += sum(compute_us.values())
    for agg in report.by_family.values():
        agg["wire_us"] = round(agg["wire_us"], 3)
    return report


def _is_host_custom_call(op: HloOp) -> bool:
    m = _CUSTOM_TARGET_RE.search(op.attrs_text)
    return bool(m and "host" in m.group(1).lower())


def audit_jitted(jfn: Any, *args, device: Any = None,
                 pad_fractions: Optional[dict] = None, **kwargs) -> HloScheduleReport:
    """Audit an already-jitted callable (``jax.jit`` object or ``Compiled``),
    lowering on the example args if needed — the same access path as
    ``attribution.scope_map_of``."""
    if hasattr(jfn, "as_text"):
        text = jfn.as_text()
    elif hasattr(jfn, "lower"):
        text = jfn.lower(*args, **kwargs).compile().as_text()
    else:
        raise TypeError(
            f"audit_jitted needs a jax.jit callable or Compiled, got {type(jfn).__name__}"
        )
    return audit_hlo(text, device=device, pad_fractions=pad_fractions)


# =============================================================================
# hlo.* verifier rules (advisory — INFO/WARNING only, never gate a compile)
# =============================================================================

# Sub-µs wire predictions are bookkeeping noise; same floor as sched.*.
_HLO_EXPOSED_MIN_WIRE_US = 1.0
# A layout copy under 1 MiB round-trip is fusion fodder, not a finding.
_HLO_LAYOUT_COPY_MIN_BYTES = float(1 << 20)
# Below a quarter padded-away the bucket policy is working as designed.
_HLO_PAD_WASTE_MIN_FRAC = 0.25


def _audit_report_of(ctx) -> Optional[HloScheduleReport]:
    tags = getattr(ctx.trace, "tags", None)
    rep = tags.get("hlo_audit") if isinstance(tags, dict) else None
    return rep if isinstance(rep, HloScheduleReport) else None


def _report_exposed(rep: HloScheduleReport, emit) -> None:
    for s in rep.sites:
        if s.wire_us < _HLO_EXPOSED_MIN_WIRE_US or s.exposed_us <= 0.0:
            continue
        kind = "partitioner-inserted" if s.inserted else "explicit"
        emit(
            "hlo.exposed-collective",
            Severity.INFO,
            f"{s.label()} [{s.family}{'*' if s.derived else ''}, {kind}]: "
            f"predicted {s.exposed_us:.1f}us of {s.wire_us:.1f}us wire exposed "
            f"({s.hidden_us:.1f}us hidden under the {s.window_us:.1f}us window "
            "to its first consumer)",
            hint="partitioner-inserted sites need XLA-side levers (sharding "
            "hints, xla_tpu_enable_async_collective_* flags, latency-hiding "
            "scheduler budget) — the trace-level comm scheduler cannot move "
            "ops it cannot see (ROADMAP item 3)",
        )


def _report_layout_copy(rep: HloScheduleReport, emit) -> None:
    if rep.layout_copies == 0 or rep.layout_copy_bytes < _HLO_LAYOUT_COPY_MIN_BYTES:
        return
    emit(
        "hlo.layout-copy",
        Severity.INFO,
        f"{rep.layout_copies} layout copies move {rep.layout_copy_bytes/1e6:.2f} MB "
        "through HBM in the compiled executable",
        hint="a copy is XLA materializing a layout change the program forced "
        "(transpose chains, mixed minor-to-major constraints); align the "
        "producing op's layout or fuse the consumer",
    )


def _report_padding(rep: HloScheduleReport, emit) -> None:
    for label, frac in sorted(rep.pad_fractions.items()):
        if frac < _HLO_PAD_WASTE_MIN_FRAC:
            continue
        emit(
            "hlo.padding-waste",
            Severity.WARNING,
            f"bucket dim {label}: {frac * 100.0:.0f}% of the padded extent is "
            "padding — every op touching it pays full-bucket FLOPs/HBM",
            hint="a tighter BucketPolicy (smaller multiple, or pow2 → multiple) "
            "trades recompiles for less padded compute; core/bucketing.py",
        )


def _report_host_transfer(rep: HloScheduleReport, emit) -> None:
    if rep.host_transfers == 0:
        return
    ops = ", ".join(rep.host_transfer_ops[:4])
    emit(
        "hlo.host-transfer-in-step",
        Severity.WARNING,
        f"{rep.host_transfers} host transfer(s) inside the compiled step "
        f"({ops}{'…' if rep.host_transfers > 4 else ''})",
        hint="a host round-trip serializes the device pipeline every step; "
        "move the offending computation on-device or out of the step",
    )


def _make_rule(reporter):
    def rule(ctx) -> None:
        rep = _audit_report_of(ctx)
        if rep is None:
            return
        reporter(rep, lambda rule_id, sev, msg, **kw: ctx.report(rule_id, sev, msg, **kw))
    return rule


register_rule(
    "hlo.exposed-collective",
    "Partitioner-inserted collective wire time is predicted hidden at HLO level",
)(_make_rule(_report_exposed))
register_rule(
    "hlo.layout-copy",
    "Compiled executable materializes significant layout-change copies",
)(_make_rule(_report_layout_copy))
register_rule(
    "hlo.padding-waste",
    "Bucket padding wastes a large fraction of every padded dim's compute",
)(_make_rule(_report_padding))
register_rule(
    "hlo.host-transfer-in-step",
    "Compiled step round-trips through the host",
)(_make_rule(_report_host_transfer))
