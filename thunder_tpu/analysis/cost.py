"""Static per-op cost model and roofline analysis over traces.

The *predicted* half of the performance-attribution observatory (the
*measured* half is ``thunder_tpu/observability/attribution.py``): every
value-producing BoundSymbol is assigned FLOPs, HBM bytes, and interconnect
bytes from its tensor metadata alone — no execution — and the rollup is
scored against a device spec (peak FLOP/s + HBM bandwidth) to yield
per-op and whole-trace roofline step-time lower bounds:

    t_op >= max(flops / peak_flops, bytes / hbm_bw, comm_bytes / ici_bw)

An op whose arithmetic intensity (flops/byte) exceeds the device ridge
point (peak/bw) is *compute-bound*; below it, *memory-bound*. Matmuls at
LLM shapes sit far above the ridge; elementwise/reduction/shape ops sit far
below — which is why the roofline table, joined with measured device time
(``monitor.attribution_report``), says whether a slow op is worth a kernel
or a fusion fix (compute-bound: better MXU utilization; memory-bound: fuse
away the HBM round-trip).

Conventions (documented so golden tests are exact):

- matmul/linear: ``2·m·n·k`` FLOPs (multiply+add), bias adds counted.
- SDPA: two T×T matmuls = ``4·B·H·Tq·Tk·D`` plus 5 FLOPs per attention
  score for the online softmax; causal masks halve both. Flash-claimed
  SDPA reads only q/k/v and writes only out (+lse) — the T×T score matrix
  never touches HBM.
- elementwise: 1 FLOP per output element regardless of transcendence —
  they are bandwidth-bound on every spec in the table, so FLOP-weighting
  transcendentals would change no classification while making totals
  noisier against analytic estimates.
- reductions: 1 FLOP per *input* element (variance: 2).
- collectives: 0 FLOPs; ring-algorithm wire bytes — all_reduce moves
  ``2·(g−1)/g·nbytes``, all_gather/reduce_scatter ``(g−1)/g·nbytes``.
- pure layout ops (reshape/squeeze/broadcast): free — XLA fuses them;
  data-moving shape ops (transpose/cat/pad/take/...) are charged in+out
  bytes at 0 FLOPs.

Device peaks are datasheet numbers; override by passing your own
:class:`DeviceSpec` (docs/performance.md shows how to add a chip).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import TensorProxy, pyval
from thunder_tpu.core.trace import TraceCtx

# =============================================================================
# Device specs
# =============================================================================


@dataclass(frozen=True)
class DeviceSpec:
    """Peak numbers for one chip. ``peak_flops`` maps a dtype class
    ("bf16" — the MXU path for f16/bf16, "f32", "int8") to FLOP/s;
    ``hbm_bw`` and ``ici_bw`` are bytes/s. Datasheet values — real kernels
    see less; the roofline is a *lower bound* on step time."""

    name: str
    peak_flops: dict[str, float]
    hbm_bw: float
    ici_bw: float = 0.0
    # Cross-slice (data-center network) wire bandwidth in bytes/s — the
    # second interconnect class of a federated mesh (ISSUE 18). An order of
    # magnitude below ICI on every real pod: collectives on the "dcn" mesh
    # axis (and the cross-slice leg of hier_all_reduce) price at this rate.
    # 0 means no DCN tier: cross-slice traffic falls back to ici_bw.
    dcn_bw: float = 0.0
    # Per-chip HBM capacity in bytes (datasheet; the runtime reserves a
    # fraction — analysis/liveness.device_capacity_bytes prefers the live
    # backend's bytes_limit and the THUNDER_TPU_HBM_BYTES override). 0 means
    # unknown: the liveness planner's fit checks are skipped.
    hbm_bytes: float = 0.0
    # Effective per-collective-family wire bandwidth (bytes/s), fitted from a
    # measured per-collective table via :func:`calibrate_ici`. Datasheet
    # ``ici_bw`` is the link rate; real collectives see less (latency,
    # algorithm inefficiency — ~1000× less on an emulated CPU mesh, where
    # "wire" time is thread rendezvous). None = uncalibrated: price at the
    # datasheet rate.
    ici_class_bw: Optional[dict] = None

    def peak_for(self, dtype: Any) -> float:
        return self.peak_flops.get(_dtype_class(dtype), self.peak_flops["bf16"])

    def ici_bw_for(self, cls: Optional[str]) -> float:
        """Wire bandwidth used to price a collective of HLO family ``cls``
        (``all-gather``/``all-reduce``/...): the calibrated per-class rate
        when one was fitted, else the datasheet ``ici_bw``."""
        if cls and self.ici_class_bw:
            bw = self.ici_class_bw.get(cls)
            if bw:
                return float(bw)
        return self.ici_bw

    @property
    def dcn_bw_or_ici(self) -> float:
        """The rate DCN-tier wire bytes price at: ``dcn_bw`` when the spec
        has a DCN class, else ``ici_bw`` (single-interconnect specs)."""
        return self.dcn_bw or self.ici_bw

    def ridge(self, dtype: Any) -> float:
        """Arithmetic intensity (FLOP/byte) at which compute and memory
        time are equal — ops above it are compute-bound."""
        return self.peak_for(dtype) / self.hbm_bw


def _dtype_class(dtype: Any) -> str:
    nbytes = getattr(dtype, "bytes", 4)
    if getattr(dtype, "kind", "float") in ("int", "uint", "bool"):
        return "int8" if nbytes <= 1 else "f32"
    return "bf16" if nbytes <= 2 else "f32"


# Datasheet peaks. f32 on TPU runs through the MXU at roughly half bf16
# throughput (XLA splits f32 matmuls); "cpu" is a deliberately small spec so
# host-platform tests still classify sensibly.
# dcn_bw: per-chip share of the data-center network between slices — NIC
# line rate divided across the host's chips, an order of magnitude (or two)
# below ICI everywhere. These drive the federated-mesh roofline (ISSUE 18),
# not any single-slice number.
DEVICE_SPECS: dict[str, DeviceSpec] = {
    "v5e": DeviceSpec("v5e", {"bf16": 197e12, "f32": 98.5e12, "int8": 394e12},
                      hbm_bw=819e9, ici_bw=186e9, dcn_bw=6.25e9, hbm_bytes=16e9),
    "v5p": DeviceSpec("v5p", {"bf16": 459e12, "f32": 229.5e12, "int8": 918e12},
                      hbm_bw=2765e9, ici_bw=600e9, dcn_bw=25e9, hbm_bytes=95e9),
    "v4": DeviceSpec("v4", {"bf16": 275e12, "f32": 137.5e12, "int8": 275e12},
                     hbm_bw=1228e9, ici_bw=300e9, dcn_bw=6.25e9, hbm_bytes=32e9),
    "v6e": DeviceSpec("v6e", {"bf16": 918e12, "f32": 459e12, "int8": 1836e12},
                      hbm_bw=1640e9, ici_bw=448e9, dcn_bw=12.5e9, hbm_bytes=32e9),
    "a100": DeviceSpec("a100", {"bf16": 312e12, "f32": 19.5e12, "int8": 624e12},
                       hbm_bw=1555e9, ici_bw=600e9, dcn_bw=25e9, hbm_bytes=80e9),
    # Host RAM is not a fixed datasheet number; 0 = capacity unknown, so the
    # liveness fit checks defer to memory_stats / THUNDER_TPU_HBM_BYTES.
    "cpu": DeviceSpec("cpu", {"bf16": 2e11, "f32": 2e11, "int8": 4e11},
                      hbm_bw=5e10, ici_bw=1e10, dcn_bw=1e9, hbm_bytes=0.0),
}


def collective_sym_class(sym_name: str) -> Optional[str]:
    """HLO collective family ("all-gather"/"all-reduce"/...) of a trace-level
    collective symbol name, or None. One authoritative sym→family map,
    shared with the measured half (observability/attribution.py)."""
    from thunder_tpu.observability.attribution import COLLECTIVE_SYM_CLASS

    return COLLECTIVE_SYM_CLASS.get(sym_name)


def calibrate_ici(spec: DeviceSpec, samples: Sequence[tuple]) -> DeviceSpec:
    """Fit an effective per-class ICI bandwidth from measured collectives.

    ``samples``: ``(cls, comm_bytes, measured_s)`` rows — the cost model's
    ring-factor wire bytes for a collective joined with its measured device
    time (``scripts/bench_multichip.py`` feeds the lane-segmentation table).
    The fit is the aggregate rate per family, ``Σ bytes / Σ seconds``,
    clamped to the datasheet ``ici_bw`` from above (a measurement can only
    reveal the wire to be *slower* than the link rate). Returns a new spec
    whose :meth:`DeviceSpec.ici_bw_for` prices each family at its fitted
    rate — the order-of-magnitude correction the comm scheduler's placement
    decisions need on meshes whose collective cost is rendezvous-dominated
    (the emulated CPU mesh measures ~1000× the datasheet wire time)."""
    import dataclasses

    by_cls: dict[str, list[float]] = {}
    for cls, comm_bytes, measured_s in samples:
        if not cls or not comm_bytes or not measured_s or measured_s <= 0:
            continue
        agg = by_cls.setdefault(str(cls), [0.0, 0.0])
        agg[0] += float(comm_bytes)
        agg[1] += float(measured_s)
    fitted = {
        cls: min(b / s, spec.ici_bw) if spec.ici_bw else b / s
        for cls, (b, s) in by_cls.items()
        if s > 0 and b > 0
    }
    if not fitted:
        return spec
    return dataclasses.replace(spec, ici_class_bw=fitted)


def resolve_device_spec(device: Any = None) -> DeviceSpec:
    """A :class:`DeviceSpec` from a spec object, a table name, or None
    (autodetect: cpu when the local platform is cpu, else the chip from
    ``thunder_tpu.benchmarks.tpu_generation()`` — the same sniffing the
    bench uses, PALLAS_AXON_TPU_GEN env first). An autodetected generation
    missing from the table warns before falling back to v5e; a *named*
    unknown spec raises."""
    if isinstance(device, DeviceSpec):
        return device
    if isinstance(device, str):
        spec = DEVICE_SPECS.get(device.lower())
        if spec is None:
            raise ValueError(
                f"unknown device spec {device!r}; known: {sorted(DEVICE_SPECS)} "
                "(pass a DeviceSpec to add a chip)"
            )
        return spec
    try:
        import jax

        if jax.devices()[0].platform == "cpu":
            return DEVICE_SPECS["cpu"]
    except Exception:
        pass
    from thunder_tpu.benchmarks import tpu_generation

    gen = tpu_generation()
    spec = DEVICE_SPECS.get(gen)
    if spec is None:
        import warnings

        warnings.warn(
            f"no DeviceSpec for detected chip {gen!r}; roofline numbers will "
            f"use the v5e spec — pass device=DeviceSpec(...) for real bounds",
            stacklevel=2,
        )
        return DEVICE_SPECS["v5e"]
    return spec


# =============================================================================
# Per-op cost rules
# =============================================================================


@dataclass
class OpCost:
    """Static cost of one BoundSymbol. ``bytes_moved`` is HBM traffic
    (reads + writes); ``comm_bytes`` is TOTAL interconnect wire traffic, of
    which ``dcn_bytes`` crosses the cross-slice DCN tier (ISSUE 18) and
    prices at :attr:`DeviceSpec.dcn_bw` instead of ICI."""

    flops: float = 0.0
    bytes_moved: float = 0.0
    comm_bytes: float = 0.0
    dcn_bytes: float = 0.0
    kind: str = "other"

    @property
    def arithmetic_intensity(self) -> float:
        return self.flops / self.bytes_moved if self.bytes_moved else float("inf")


def _tensor_args(bsym) -> list[TensorProxy]:
    return [p for p in bsym.flat_proxy_args if isinstance(p, TensorProxy)]


def _tensor_outs(bsym) -> list[TensorProxy]:
    return [p for p in bsym.flat_proxy_outs if isinstance(p, TensorProxy)]


def _numel(shape: Sequence[Any]) -> int:
    n = 1
    for s in shape:
        v = pyval(s)
        n *= int(v) if v is not None else int(s)
    return n


def _io_bytes(bsym) -> float:
    return float(sum(p.size_bytes for p in _tensor_args(bsym))
                 + sum(p.size_bytes for p in _tensor_outs(bsym)))


def _out_numel(bsym) -> int:
    return sum(p.numel for p in _tensor_outs(bsym))


def _in_numel(bsym) -> int:
    return sum(p.numel for p in _tensor_args(bsym))


# Bookkeeping prims with no runtime cost at all.
_FREE_IDS = {
    PrimIDs.DEL, PrimIDs.RETURN, PrimIDs.COMMENT, PrimIDs.PRINT,
    PrimIDs.UNPACK_TRIVIAL, PrimIDs.UNPACK_SEQUENCE, PrimIDs.UNPACK_KEY,
    PrimIDs.UNPACK_ATTR, PrimIDs.UNPACK_DIM,
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA, PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    PrimIDs.CHECK_STRING_VALUE, PrimIDs.CHECK_LEN, PrimIDs.CHECK_KEYS,
    PrimIDs.CHECK_NONE, PrimIDs.CHECK_DIM_BUCKET,
    PrimIDs.SHALLOW_COPY, PrimIDs.STOP_GRADIENT, PrimIDs.ITEM,
}

# Layout-only ops XLA compiles away (no data movement charged).
_LAYOUT_IDS = {PrimIDs.RESHAPE, PrimIDs.SQUEEZE, PrimIDs.BROADCAST_IN_DIM}

# Data-moving shape ops: 0 FLOPs, in+out bytes.
_MOVE_IDS = {
    PrimIDs.TRANSPOSE, PrimIDs.CAT, PrimIDs.PAD, PrimIDs.SLICE, PrimIDs.FLIP,
    PrimIDs.TAKE, PrimIDs.TAKE_ALONG_AXIS, PrimIDs.GATHER, PrimIDs.SETITEM,
    PrimIDs.INDEX_PUT, PrimIDs.TENSOR_FROM_SEQUENCE, PrimIDs.DEVICE_PUT,
    PrimIDs.CONVERT_ELEMENT_TYPE, PrimIDs.COPY_, PrimIDs.TENSOR_CONSTANT,
}

# 2-FLOP-per-input-element reductions (mean+var in one pass).
_VAR_IDS = {PrimIDs.VAR, PrimIDs.VAR_MEAN}

_SDPA_FWD_IDS = {"torch.scaled_dot_product_attention", "torch.sdpa_fwd_res"}
_SDPA_BWD_IDS = {"torch.sdpa_bwd", "torch.sdpa_bwd_res"}

# Ring-collective wire-traffic factors as a function of group size g.
_COLLECTIVE_FACTORS: dict[str, Callable[[int], float]] = {
    "all_reduce": lambda g: 2.0 * (g - 1) / g,
    "all_gather": lambda g: (g - 1) / g,
    "reduce_scatter": lambda g: (g - 1) / g,
    "broadcast": lambda g: (g - 1) / g,
    "all_to_all": lambda g: (g - 1) / g,
    "ppermute": lambda g: 1.0,
    "mask_to_rank": lambda g: 0.0,
    "synchronize": lambda g: 0.0,
    "wait": lambda g: 0.0,
}


def _matmul_cost(bsym) -> OpCost:
    # out (..., m, n) = a (..., m, k) @ b (..., k, n): 2·m·n·k per batch.
    a = _tensor_args(bsym)[0]
    k = int(pyval(a.shape[-1]) or a.shape[-1])
    return OpCost(flops=2.0 * _out_numel(bsym) * k, bytes_moved=_io_bytes(bsym), kind="matmul")


def _linear_cost(bsym) -> OpCost:
    # out (..., n) = a (..., k) @ w.T (k, n) [+ bias]: 2·m·n·k + bias adds.
    tas = _tensor_args(bsym)
    a = tas[0]
    k = int(pyval(a.shape[-1]) or a.shape[-1])
    out_n = _out_numel(bsym)
    flops = 2.0 * out_n * k
    if len(tas) > 2:  # bias present
        flops += out_n
    return OpCost(flops=flops, bytes_moved=_io_bytes(bsym), kind="matmul")


def _conv_cost(bsym, *, bwd: bool = False) -> OpCost:
    # out numel × 2 × (cin/groups · ∏kernel); backward does ~2× the work
    # (grad-input + grad-weight each cost one forward).
    tas = _tensor_args(bsym)
    w = tas[1]
    k_work = _numel(w.shape[1:])  # cin/groups · ∏kernel
    flops = 2.0 * _out_numel(bsym) * k_work * (2.0 if bwd else 1.0)
    return OpCost(flops=flops, bytes_moved=_io_bytes(bsym), kind="matmul")


def _sdpa_dims(bsym) -> tuple[float, float, float, float, float, bool]:
    tas = _tensor_args(bsym)
    q, k = tas[0], tas[1]
    b = _numel(q.shape[:-2])  # B·H (grouped-query: q carries the full H)
    tq = int(pyval(q.shape[-2]) or q.shape[-2])
    tk = int(pyval(k.shape[-2]) or k.shape[-2])
    d = int(pyval(q.shape[-1]) or q.shape[-1])
    causal = bool(pyval(bsym.kwargs.get("is_causal", False)) or
                  any(a is True for a in bsym.args if isinstance(a, bool)))
    return b, tq, tk, d, 0.5 if causal else 1.0, causal


def _sdpa_cost(bsym, *, bwd: bool = False) -> OpCost:
    b, tq, tk, d, frac, _ = _sdpa_dims(bsym)
    # QKᵀ and AV: 2·(2·B·H·Tq·Tk·D); online softmax ≈ 5 FLOPs/score.
    flops = frac * (4.0 * b * tq * tk * d + 5.0 * b * tq * tk)
    if bwd:
        # dQ, dK, dV plus the flash re-descent of the forward ≈ 2.5× fwd.
        flops *= 2.5
    # Flash kernels never materialize the score matrix: HBM traffic is the
    # q/k/v/out (+residual) tensors only — exactly the proxy operands.
    return OpCost(flops=flops, bytes_moved=_io_bytes(bsym), kind="sdpa")


# The mesh axis whose hops cross slice boundaries (parallel/mesh.DCN_AXIS;
# the literal avoids importing jax-adjacent modules into the cost model).
_DCN_AXIS = "dcn"


def _collective_axis(bsym) -> Optional[str]:
    """The (first) mesh-axis operand of a collective bsym, when it is a
    string — the axis-aware bandwidth selection key (ISSUE 18)."""
    axis = bsym.args[1] if len(bsym.args) > 1 else bsym.kwargs.get("axis")
    return axis if isinstance(axis, str) else None


def _hier_all_reduce_cost(bsym) -> OpCost:
    """Wire bytes of the hierarchical all-reduce (dist_prims.hier_all_reduce):
    in-slice reduce-scatter + all-gather move ``2·(g_in−1)/g_in·nbytes``
    over ICI; the cross-slice all-reduce moves ``2·(g_out−1)/g_out`` of the
    1/g_in SHARD over DCN — the whole point of the lowering."""
    nbytes = float(sum(p.size_bytes for p in _tensor_args(bsym)))
    args = list(bsym.args) + [bsym.kwargs.get(k) for k in ()]
    g_in = args[3] if len(args) > 3 else bsym.kwargs.get("inner_size", 1)
    g_out = args[4] if len(args) > 4 else bsym.kwargs.get("outer_size", 1)
    g_in = int(pyval(g_in) or 1)
    g_out = int(pyval(g_out) or 1)
    ici = 2.0 * (g_in - 1) / g_in * nbytes if g_in > 1 else 0.0
    shard = nbytes / max(1, g_in)
    dcn = 2.0 * (g_out - 1) / g_out * shard if g_out > 1 else 0.0
    return OpCost(comm_bytes=ici + dcn, dcn_bytes=dcn, kind="collective")


def _collective_cost(bsym) -> OpCost:
    name = bsym.sym.name
    if name == "hier_all_reduce":
        return _hier_all_reduce_cost(bsym)
    factor_fn = _COLLECTIVE_FACTORS.get(name)
    nbytes = float(sum(p.size_bytes for p in _tensor_args(bsym)))
    on_dcn = _collective_axis(bsym) == _DCN_AXIS
    if factor_fn is None:
        return OpCost(comm_bytes=nbytes, dcn_bytes=nbytes if on_dcn else 0.0,
                      kind="collective")
    g = 1
    for a in bsym.flat_args:
        v = pyval(a)
        if isinstance(v, int) and not isinstance(v, bool) and v > 1:
            g = v
            break
    # Gather-type ops consume the SHARD but the ring moves (g-1)/g of the
    # FULL tensor — the output. This covers `synchronize` on a sharded fsdp
    # param (trace-level all-gather; the replicated passthrough keeps its
    # zero factor since out == in) so the overlap report's predicted column
    # prices the dominant FSDP collective instead of calling it free.
    if name in ("all_gather", "synchronize"):
        out = bsym.output
        out_bytes = float(getattr(out, "size_bytes", 0.0) or 0.0)
        if out_bytes > nbytes:
            wire = (g - 1) / g * out_bytes
            return OpCost(comm_bytes=wire, dcn_bytes=wire if on_dcn else 0.0,
                          kind="collective")
    wire = factor_fn(g) * nbytes
    return OpCost(comm_bytes=wire, dcn_bytes=wire if on_dcn else 0.0,
                  kind="collective")


def bsym_cost(bsym) -> Optional[OpCost]:
    """Static cost of one BoundSymbol, or None for pure bookkeeping
    (unpacks, guards, del/return). Dispatches on the prim id, the
    executor-claimed symbol id (SDPA family), and the COMM_OP tag."""
    sid = bsym.sym.id
    if sid in _FREE_IDS:
        return None
    if OpTags.COMM_OP in bsym.sym.tags:
        return _collective_cost(bsym)
    if isinstance(sid, str):
        if sid in _SDPA_FWD_IDS:
            return _sdpa_cost(bsym)
        if sid in _SDPA_BWD_IDS:
            return _sdpa_cost(bsym, bwd=True)
    if sid is PrimIDs.MATMUL:
        return _matmul_cost(bsym)
    if sid is PrimIDs.LINEAR:
        return _linear_cost(bsym)
    if sid is PrimIDs.CONVOLUTION:
        return _conv_cost(bsym)
    if sid is PrimIDs.CONVOLUTION_BWD:
        return _conv_cost(bsym, bwd=True)
    if sid in (PrimIDs.EMBEDDING, PrimIDs.EMBEDDING_BACKWARD):
        return OpCost(bytes_moved=_io_bytes(bsym), kind="gather")
    if sid in _LAYOUT_IDS:
        return OpCost(kind="layout")
    if sid in _MOVE_IDS:
        return OpCost(bytes_moved=_io_bytes(bsym), kind="shape")
    if not _tensor_outs(bsym):
        return None
    tags = bsym.sym.tags
    if OpTags.REDUCTION_OP in tags or sid in _VAR_IDS or sid in (
        PrimIDs.SUM, PrimIDs.PROD, PrimIDs.AMAX, PrimIDs.AMIN,
        PrimIDs.ARGMAX, PrimIDs.ARGMIN, PrimIDs.VAR, PrimIDs.VAR_MEAN,
        PrimIDs.CUMSUM, PrimIDs.CUMPROD,
    ):
        mult = 2.0 if sid in _VAR_IDS else 1.0
        return OpCost(flops=mult * _in_numel(bsym), bytes_moved=_io_bytes(bsym),
                      kind="reduction")
    if sid in (PrimIDs.SORT, PrimIDs.ARGSORT, PrimIDs.TOPK):
        return OpCost(flops=float(_in_numel(bsym)), bytes_moved=_io_bytes(bsym),
                      kind="sort")
    if sid in (PrimIDs.FULL, PrimIDs.IOTA, PrimIDs.UNIFORM, PrimIDs.RANDN,
               PrimIDs.UNIFORM_KEYED, PrimIDs.RANDN_KEYED, PrimIDs.UNIFORM_PHILOX):
        return OpCost(
            flops=float(_out_numel(bsym)),
            bytes_moved=float(sum(p.size_bytes for p in _tensor_outs(bsym))),
            kind="fill",
        )
    # Elementwise (and the unknown-op fallback): 1 FLOP per output element.
    kind = "elementwise" if (
        OpTags.ELEMENTWISE_UNARY_OP in tags or OpTags.ELEMENTWISE_BINARY_OP in tags
        or sid is PrimIDs.WHERE
    ) else "other"
    return OpCost(flops=float(_out_numel(bsym)), bytes_moved=_io_bytes(bsym), kind=kind)


# =============================================================================
# Trace rollup + roofline
# =============================================================================


@dataclass
class OpCostRow:
    """One trace line's cost, scored against the device spec."""

    index: int
    sym: str
    kind: str
    flops: float
    bytes_moved: float
    comm_bytes: float
    roofline_s: float
    bound: str  # "compute" | "memory" | "comm" | "free"
    intensity: float
    line: str = ""


@dataclass
class TraceCost:
    """Cost rollup of one trace against one device spec."""

    device: DeviceSpec
    rows: list[OpCostRow] = field(default_factory=list)
    total_flops: float = 0.0
    total_bytes: float = 0.0
    total_comm_bytes: float = 0.0
    # DCN-tier portion of total_comm_bytes: bytes a federated mesh moves
    # across the slice boundary (the "dcn" axis), priced at dcn_bw.
    total_dcn_bytes: float = 0.0
    # Σ flops/peak at each op's OWN dtype peak (accumulated by trace_cost so
    # the pure-compute bound agrees with the per-row roofline terms — a
    # bf16 trace must not be scored at the f32 peak here).
    _compute_s: float = 0.0

    @property
    def roofline_s(self) -> float:
        """Step-time lower bound with no cross-op fusion: Σ per-op bounds."""
        return sum(r.roofline_s for r in self.rows)

    @property
    def compute_s(self) -> float:
        """Pure-compute bound (every byte free), at per-op dtype peaks."""
        return self._compute_s

    @property
    def memory_s(self) -> float:
        """Pure-bandwidth bound (every FLOP free)."""
        return self.total_bytes / self.device.hbm_bw

    @property
    def comm_s(self) -> float:
        """Pure-wire bound: in-slice traffic at ICI bandwidth plus the
        DCN-tier portion at the spec's DCN class (0 when the trace has no
        collectives or the spec has no ICI)."""
        if not self.total_comm_bytes or not self.device.ici_bw:
            return 0.0
        ici = self.total_comm_bytes - self.total_dcn_bytes
        return ici / self.device.ici_bw + self.total_dcn_bytes / self.device.dcn_bw_or_ici

    def collective_rows(self) -> list[OpCostRow]:
        """The trace's collective ops — the predicted half of the
        compute–comm overlap report (observability/attribution.py joins
        these against measured hidden/exposed wire time)."""
        return [r for r in self.rows if r.kind == "collective"]

    def by_kind(self) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for r in self.rows:
            d = out.setdefault(r.kind, {"flops": 0.0, "bytes": 0.0, "roofline_s": 0.0, "ops": 0})
            d["flops"] += r.flops
            d["bytes"] += r.bytes_moved
            d["roofline_s"] += r.roofline_s
            d["ops"] += 1
        return out

    def mfu_at(self, measured_s: float) -> float:
        """Model FLOPs utilization if the trace ran once in ``measured_s``."""
        return self.total_flops / measured_s / self.device.peak_flops["bf16"] if measured_s else 0.0

    def top(self, k: int = 10) -> list[OpCostRow]:
        return sorted(self.rows, key=lambda r: r.roofline_s, reverse=True)[:k]

    def format(self, top_k: int = 10) -> str:
        dev = self.device
        lines = [
            f"cost model [{dev.name}: {dev.peak_flops['bf16'] / 1e12:.0f} bf16 TFLOP/s, "
            f"{dev.hbm_bw / 1e9:.0f} GB/s HBM]",
            f"  total: {self.total_flops / 1e9:.3f} GFLOP, "
            f"{self.total_bytes / 1e6:.2f} MB moved"
            + (f", {(self.total_comm_bytes - self.total_dcn_bytes) / 1e6:.2f} MB on ICI" if self.total_comm_bytes else "")
            + (f", {self.total_dcn_bytes / 1e6:.2f} MB on DCN" if self.total_dcn_bytes else ""),
            f"  roofline step-time bound: {self.roofline_s * 1e3:.3f} ms unfused "
            f"(compute {self.compute_s * 1e3:.3f} ms, memory {self.memory_s * 1e3:.3f} ms)",
            f"  {'line':>5} {'sym':<28} {'kind':<12} {'GFLOP':>10} {'MB':>9} "
            f"{'AI':>8} {'bound':>8} {'us':>9}",
        ]
        for r in self.top(top_k):
            ai = f"{r.intensity:.1f}" if r.intensity != float("inf") else "inf"
            lines.append(
                f"  L{r.index:>4} {r.sym:<28.28} {r.kind:<12} {r.flops / 1e9:>10.4f} "
                f"{r.bytes_moved / 1e6:>9.3f} {ai:>8} {r.bound:>8} {r.roofline_s * 1e6:>9.1f}"
            )
        kinds = self.by_kind()
        if kinds:
            lines.append("  by kind: " + ", ".join(
                f"{k}={v['roofline_s'] * 1e6:.0f}us/{v['ops']}ops"
                for k, v in sorted(kinds.items(), key=lambda kv: -kv[1]["roofline_s"])
            ))
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.format()


def trace_cost(trace: TraceCtx, device: Any = None) -> TraceCost:
    """Roll :func:`bsym_cost` up over ``trace`` and score each op against
    ``device`` (a :class:`DeviceSpec`, a name from ``DEVICE_SPECS``, or
    None to autodetect the local chip)."""
    dev = resolve_device_spec(device)
    tc = TraceCost(device=dev)
    for i, bsym in enumerate(trace.bound_symbols):
        c = bsym_cost(bsym)
        if c is None:
            continue
        outs = _tensor_outs(bsym)
        dtype = outs[0].dtype if outs else None
        t_compute = c.flops / dev.peak_for(dtype)
        t_memory = c.bytes_moved / dev.hbm_bw
        ici_bw = dev.ici_bw_for(collective_sym_class(bsym.sym.name)) if c.comm_bytes else 0.0
        if ici_bw and c.comm_bytes:
            # Price the two wire classes separately: in-slice bytes at the
            # (family-fitted) ICI rate, cross-slice bytes at the DCN rate.
            t_comm = (c.comm_bytes - c.dcn_bytes) / ici_bw
            t_comm += c.dcn_bytes / dev.dcn_bw_or_ici
        else:
            t_comm = 0.0
        t = max(t_compute, t_memory, t_comm)
        if t == 0.0:
            bound = "free"
        elif t == t_comm:
            bound = "comm"
        elif t == t_compute:
            bound = "compute"
        else:
            bound = "memory"
        tc.rows.append(OpCostRow(
            index=i, sym=bsym.sym.name, kind=c.kind, flops=c.flops,
            bytes_moved=c.bytes_moved, comm_bytes=c.comm_bytes,
            roofline_s=t, bound=bound, intensity=c.arithmetic_intensity,
            line=bsym.one_line(),
        ))
        tc.total_flops += c.flops
        tc.total_bytes += c.bytes_moved
        tc.total_comm_bytes += c.comm_bytes
        tc.total_dcn_bytes += c.dcn_bytes
        tc._compute_s += t_compute
    return tc


def cost_report(fn: Callable, *args, executors: Any = None, device: Any = None,
                **kwargs) -> TraceCost:
    """Trace ``fn`` on the example inputs through the default pass pipeline
    (acquisition → DCE → CSE → claiming) and return the :class:`TraceCost`
    of the resulting execution trace — the static half of the attribution
    workflow (``examine.cost_report`` re-exports this; docs/performance.md).

    For an already-compiled ``thunder_tpu.jit`` function, the underlying
    function is traced (mirroring ``examine.lint``); to cost the exact
    trace an entry executed, call :func:`trace_cost` on
    ``compile_stats(jfn).last_traces[-1]`` instead."""
    from thunder_tpu.api import trace_program
    from thunder_tpu.core.trace import debug_checks
    from thunder_tpu.executors.passes import transform_for_execution
    from thunder_tpu.extend import resolve_executors
    from thunder_tpu.transforms.common import cse, dce

    cd = getattr(fn, "_lc_cd", None)
    if cd is not None:
        fn = cd.fn
    with debug_checks(False):
        _, comp = trace_program(fn, args, kwargs)
        comp = cse(dce(comp))
        extrace = transform_for_execution(comp, resolve_executors(executors))
    return trace_cost(extrace, device)


# =============================================================================
# HLO-op pricing (the compiled-executable twin of bsym_cost)
# =============================================================================

# Ring-collective wire-traffic factors by HLO family name — the compiled-HLO
# counterpart of _COLLECTIVE_FACTORS (keyed by trace sym name above). The
# derived reduce-scatter (an all-reduce whose consumers all slice a shard,
# recovered by analysis/hlo_audit) prices at the reduce-scatter factor: the
# program provably needs only the scattered result.
HLO_COLLECTIVE_FACTORS: dict[str, Callable[[int], float]] = {
    "all-reduce": lambda g: 2.0 * (g - 1) / g,
    "all-gather": lambda g: (g - 1) / g,
    "reduce-scatter": lambda g: (g - 1) / g,
    "collective-broadcast": lambda g: (g - 1) / g,
    "all-to-all": lambda g: (g - 1) / g,
    "ragged-all-to-all": lambda g: (g - 1) / g,
    "collective-permute": lambda g: 1.0,
}


def hlo_collective_wire_bytes(family: str, full_bytes: float, group_size: int) -> float:
    """Ring wire traffic of one HLO collective: the family factor applied to
    the FULL tensor bytes (gather output / reduce input — the caller picks
    the full side, :func:`hlo_op_cost` does for parsed ops)."""
    factor_fn = HLO_COLLECTIVE_FACTORS.get(family)
    if factor_fn is None or group_size <= 1:
        return full_bytes if factor_fn is not None else 0.0
    return factor_fn(group_size) * full_bytes


# Opcode classes, mirroring the bsym conventions in the module docstring:
# layout-only ops are free (XLA fuses them), data movers are charged in+out
# bytes at 0 FLOPs, elementwise is 1 FLOP per output element, reductions
# 1 FLOP per input element. Call-like ops are free at the call site — their
# bodies are priced standalone (or folded into the fusion) by the auditor.
_HLO_FREE_OPS = frozenset({
    "parameter", "constant", "iota", "bitcast", "bitcast-convert", "reshape",
    "broadcast", "get-tuple-element", "tuple", "after-all", "partition-id",
    "replica-id", "domain", "opt-barrier", "while", "call", "conditional",
    "custom-call", "rng-get-and-update-state", "get-dimension-size",
    "add-dependency", "token",
})
_HLO_MOVE_OPS = frozenset({
    "slice", "dynamic-slice", "dynamic-update-slice", "concatenate", "pad",
    "gather", "transpose", "reverse", "copy", "copy-start", "copy-done",
    "send", "recv", "send-done", "recv-done", "infeed", "outfeed",
})
_HLO_REDUCE_OPS = frozenset({"reduce", "reduce-window", "scatter", "sort", "select-and-scatter"})


def hlo_op_cost(op: Any, *, inner_flops: float = 0.0) -> Optional[OpCost]:
    """Static cost of one parsed HLO instruction — the HLO-op → FLOPs/HBM/ICI
    rules the auditor (analysis/hlo_audit.py) prices every compiled op with.

    ``op`` is duck-typed (:class:`~thunder_tpu.analysis.hlo_audit.HloOp`):
    ``opcode``, ``result_bytes``/``result_numel``, ``operand_bytes``/
    ``operand_numel``, ``group_size``, ``k_dim`` (dot/conv contraction size),
    ``family`` (collective family after classification, None otherwise).
    ``inner_flops`` carries a fusion body's summed FLOPs — the fusion is
    charged its boundary bytes plus the body's arithmetic, and the body's
    ops are NOT priced standalone (hlo_audit skips fusion-called
    computations). Returns None for `-done` completion halves (their
    `-start` op carries the cost)."""
    opcode = op.opcode
    fam = getattr(op, "family", None) or (
        opcode[:-6] if opcode.endswith("-start") and opcode[:-6] in HLO_COLLECTIVE_FACTORS
        else opcode if opcode in HLO_COLLECTIVE_FACTORS else None
    )
    if fam is not None:
        if opcode.endswith("-done"):
            return None
        # The ring moves (g−1)/g of the FULL tensor: the gathered output for
        # all-gather (result is full), the reduced input for a native
        # reduce-scatter (operand is full); all-reduce and the derived
        # reduce-scatter have out == in == full.
        full = op.operand_bytes if opcode.startswith("reduce-scatter") else op.result_bytes
        return OpCost(
            comm_bytes=hlo_collective_wire_bytes(fam, full, max(1, int(op.group_size))),
            kind="collective",
        )
    io = op.operand_bytes + op.result_bytes
    if opcode == "fusion":
        return OpCost(flops=inner_flops, bytes_moved=io, kind="fusion")
    if opcode == "dot":
        return OpCost(flops=2.0 * op.result_numel * max(1.0, op.k_dim),
                      bytes_moved=io, kind="matmul")
    if opcode == "convolution":
        return OpCost(flops=2.0 * op.result_numel * max(1.0, op.k_dim),
                      bytes_moved=io, kind="matmul")
    if opcode in _HLO_FREE_OPS:
        return None
    if opcode in _HLO_MOVE_OPS:
        return OpCost(bytes_moved=io, kind="layout" if opcode.startswith("copy") else "shape")
    if opcode in _HLO_REDUCE_OPS:
        return OpCost(flops=op.operand_numel, bytes_moved=io, kind="reduction")
    # Everything else prices as elementwise: 1 FLOP per output element.
    return OpCost(flops=op.result_numel, bytes_moved=io, kind="elementwise")
