"""Collective-schedule safety analyzer: happens-before over dist_prims.

The *scheduling* third of the static trace planner suite (ISSUE 10). Every
host of an SPMD job executes the same trace, so collectives complete only
when all hosts reach them **in the same order** — two collectives on one
mesh axis that different hosts issue in different orders deadlock the ICI.
Any scheduler that wants to sink or hoist a collective (the compute/comm
overlap work, ROADMAP 5) therefore needs a proof that the move preserves:

1. data dependencies (the collective's operands exist, its consumers wait);
2. future/wait pairing (a ``wait`` never crosses before its future's start);
3. per-axis program order between collectives (the cross-host agreement
   invariant — the one a single-trace verifier can actually certify).

:func:`certify` builds that proof as a :class:`ScheduleCertificate`: for
each collective dispatch site, the legal placement interval
``[earliest, latest]`` under the three constraints, plus the per-axis
program order and its fingerprint. Passes that legally reorder collectives
re-stamp the trace via :func:`recertify`; the ``sched.uncertified-reorder``
verifier rule compares every pass output against the stamped order
(``trace.tags["collective_order"]``, inherited through ``from_trace``) and
attributes any uncertified divergence to the pass that introduced it.

Consumers: the future overlap scheduler (ROADMAP 5) reads the movable
ranges; the collective watchdog (``resilience/watchdog.py``) attaches the
per-axis order to its :class:`~thunder_tpu.resilience.watchdog.
CollectiveTimeoutError` so a timeout names not just the pending line but
the collectives that must already have completed before it.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional

from thunder_tpu.analysis.context import VerifyContext
from thunder_tpu.analysis.diagnostics import Severity
from thunder_tpu.analysis.registry import register_rule
from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.core.trace import TraceCtx


def _collective_axis(bsym) -> Optional[str]:
    """Axis of a collective site for scheduling purposes: the shared
    calling-convention helper (analysis/collectives.collective_axis), with
    two schedule-specific guards — a wait pairs with its future rather than
    an axis slot, and a malformed non-str axis (dist.axis reports it) has
    no ordering lane."""
    from thunder_tpu.analysis.collectives import collective_axis_of
    from thunder_tpu.distributed.prims import DistOpIDs

    if bsym.sym.id is DistOpIDs.WAIT:
        return None
    ax = collective_axis_of(bsym)
    return ax if isinstance(ax, str) else None


def _site_key(index: int, bsym, axis: Optional[str]) -> str:
    """Stable identity of a collective across passes: sym name + axis +
    output proxy name (from_trace shares the name pool, so output names
    survive pass rewrites that don't rebuild the op)."""
    out = next(iter(bsym.flat_proxy_outs), None)
    out_name = getattr(out, "name", f"@{index}")
    return f"{bsym.sym.name}[{axis or '-'}]->{out_name}"


@dataclass
class CollectiveSite:
    """One collective dispatch site and its legal placement interval."""

    index: int
    sym: str
    axis: Optional[str]
    key: str
    line: str
    earliest: int          # first bsym index the site may legally occupy
    latest: int            # last bsym index the site may legally occupy
    deps_before: tuple = ()   # bsym indexes that must precede (data + axis)
    deps_after: tuple = ()    # bsym indexes that must follow
    # First bsym index that consumes one of the site's outputs (the RETURN
    # index when only the return reads it): the right end of the overlap
    # window — compute strictly between the site and this line can hide the
    # wire transfer (predict_overlap; the comm scheduler maximizes it).
    first_consumer: Optional[int] = None

    @property
    def hoistable(self) -> bool:
        return self.earliest < self.index

    @property
    def sinkable(self) -> bool:
        return self.latest > self.index

    def label(self) -> str:
        return f"L{self.index}.{self.sym}"


@dataclass
class ScheduleCertificate:
    """The proof object: per-site movable ranges + the per-axis order whose
    preservation is the cross-host safety invariant."""

    trace_name: str
    pass_name: Optional[str]
    sites: list = field(default_factory=list)
    axis_order: dict = field(default_factory=dict)  # axis -> (site key, ...)
    fingerprint: str = ""

    def site_at(self, index: int) -> Optional[CollectiveSite]:
        return next((s for s in self.sites if s.index == index), None)

    def movable_sites(self) -> list:
        return [s for s in self.sites if s.sinkable or s.hoistable]

    def axis_labels(self) -> dict:
        """{axis: [L<i>.<sym>, ...]} — the watchdog's pending-line context:
        everything left of a pending collective must already have completed
        on every healthy host. Memoized: the certificate is immutable once
        built and this sits on the per-dispatch watchdog path."""
        cached = getattr(self, "_axis_labels_cache", None)
        if cached is not None:
            return cached
        by_index = {s.key: s for s in self.sites}
        cached = {
            axis: [by_index[k].label() for k in keys if k in by_index]
            for axis, keys in self.axis_order.items()
        }
        self._axis_labels_cache = cached
        return cached

    def legal_order(self, new_axis_order: dict) -> bool:
        """Whether another trace's per-axis order is a legal evolution of
        this certificate's: sites present in both keep their relative order
        per axis (additions and deletions are fine — grad transforms add
        reduce_scatters, DCE drops dead collectives)."""
        for axis, old in self.axis_order.items():
            new = new_axis_order.get(axis, ())
            pos = {k: p for p, k in enumerate(new)}
            common = [pos[k] for k in old if k in pos]
            if common != sorted(common):
                return False
        return True

    def format(self) -> str:
        lines = [
            f"schedule certificate [{self.trace_name}"
            + (f" after {self.pass_name}" if self.pass_name else "")
            + f"]: {len(self.sites)} collective site(s), "
            f"fingerprint {self.fingerprint[:12]}"
        ]
        for s in self.sites:
            move = []
            if s.hoistable:
                move.append(f"hoistable to L{s.earliest}")
            if s.sinkable:
                move.append(f"sinkable to L{s.latest}")
            lines.append(
                f"  {s.label():<24} axis={s.axis or '-':<6} "
                + (", ".join(move) if move else "pinned")
            )
        for axis, keys in sorted(self.axis_order.items()):
            lines.append(f"  order[{axis}]: " + " -> ".join(keys))
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.format()


def _axis_key_order(bsyms) -> dict:
    """{axis: (site key, ...)} in program order — the comparison object the
    ``sched.uncertified-reorder`` rule stamps and checks."""
    from thunder_tpu.distributed.prims import is_collective_bsym

    order: dict[str, list] = {}
    for i, bsym in enumerate(bsyms):
        if not is_collective_bsym(bsym):
            continue
        axis = _collective_axis(bsym)
        if axis is None:
            continue
        order.setdefault(axis, []).append(_site_key(i, bsym, axis))
    return {a: tuple(ks) for a, ks in order.items()}


def certify(trace: TraceCtx, *, ctx: Optional[VerifyContext] = None) -> ScheduleCertificate:
    """Build the :class:`ScheduleCertificate` for ``trace``.

    Placement intervals: ``earliest`` is one past the last producer of any
    operand (and the previous same-axis collective, and any earlier
    in-place mutation of an operand's buffer); ``latest`` is one before the
    first consumer of any output (and the next same-axis collective, and
    any later in-place mutation of an operand's buffer — anti-dependencies:
    moving a read across a ``copy_`` changes which value it reads); an
    output that is a trace output pins ``latest`` to the return. DEL sites
    do not count as consumers (a sunk collective's del sinks with it)."""
    from thunder_tpu.analysis.liveness import alias_root_fn
    from thunder_tpu.analysis.rules import INPLACE_MUTATED_ARG
    from thunder_tpu.core.prims import OpTags
    from thunder_tpu.distributed.prims import is_collective_bsym

    if ctx is None:
        ctx = VerifyContext(trace)
    bsyms = ctx.bsyms
    n = len(bsyms)
    return_idx = next(
        (i for i, b in enumerate(bsyms) if b.sym.id is PrimIDs.RETURN), n
    )

    # In-place writes, alias-rooted: (index, mutated buffer's root name).
    root = alias_root_fn(bsyms)
    inplace_writes: list = []
    for m, b in enumerate(bsyms):
        if not b.has_tag(OpTags.IN_PLACE):
            continue
        idx = INPLACE_MUTATED_ARG.get(b.sym.id, 0)
        if idx < len(b.args) and hasattr(b.args[idx], "name"):
            inplace_writes.append((m, root(b.args[idx].name)))

    cert = ScheduleCertificate(
        trace_name=trace.name, pass_name=ctx.pass_name
    )
    coll_idx = [i for i, b in enumerate(bsyms) if is_collective_bsym(b)]
    by_axis: dict[str, list] = {}
    for i in coll_idx:
        axis = _collective_axis(bsyms[i])
        if axis is not None:
            by_axis.setdefault(axis, []).append(i)

    for i in coll_idx:
        bsym = bsyms[i]
        axis = _collective_axis(bsym)
        deps_before: set[int] = set()
        deps_after: set[int] = set()

        earliest = 0
        for p in bsym.flat_proxy_args:
            d = ctx.defs.get(p.name)
            if d is not None and d[0] < i:
                deps_before.add(d[0])
                earliest = max(earliest, d[0] + 1)

        latest = max(return_idx - 1, i)
        pinned_out = False
        consumers: list[int] = []
        for o in bsym.flat_proxy_outs:
            name = getattr(o, "name", None)
            if name is None:
                continue
            if name in ctx.output_names:
                pinned_out = True
            first_live = ctx.consumed_after(name, i)  # DELs excluded
            if first_live is not None:
                deps_after.add(first_live)
                latest = min(latest, first_live - 1)
                consumers.append(first_live)
        if pinned_out:
            latest = min(latest, return_idx - 1)
            consumers.append(return_idx)

        # Anti-dependencies: an in-place write to an operand's buffer pins
        # the site between the mutations it must read between.
        if inplace_writes:
            operand_roots = {
                root(p.name) for p in bsym.flat_proxy_args
                if hasattr(p, "name")
            }
            for m, w in inplace_writes:
                if w not in operand_roots or m == i:
                    continue
                if m < i:
                    deps_before.add(m)
                    earliest = max(earliest, m + 1)
                else:
                    deps_after.add(m)
                    latest = min(latest, m - 1)

        peers = by_axis.get(axis, ()) if axis is not None else ()
        if axis is not None:
            pos = peers.index(i)
            if pos > 0:
                deps_before.add(peers[pos - 1])
                earliest = max(earliest, peers[pos - 1] + 1)
            if pos + 1 < len(peers):
                deps_after.add(peers[pos + 1])
                latest = min(latest, peers[pos + 1] - 1)

        cert.sites.append(CollectiveSite(
            index=i, sym=bsym.sym.name, axis=axis,
            key=_site_key(i, bsym, axis), line=bsym.one_line(),
            earliest=earliest, latest=max(latest, earliest),
            deps_before=tuple(sorted(deps_before)),
            deps_after=tuple(sorted(deps_after)),
            first_consumer=min(consumers) if consumers else None,
        ))

    cert.axis_order = _axis_key_order(bsyms)
    cert.fingerprint = hashlib.sha1(
        repr(sorted(cert.axis_order.items())).encode()
    ).hexdigest()
    return cert


def stamp(trace: TraceCtx, cert: Optional[ScheduleCertificate] = None) -> ScheduleCertificate:
    """Record ``cert``'s per-axis order on the trace
    (``tags["collective_order"]``) — the baseline the
    ``sched.uncertified-reorder`` rule compares later passes against.
    ``from_trace`` copies tags, so every downstream pass inherits it."""
    if cert is None:
        cert = certify(trace)
    trace.tags["collective_order"] = dict(cert.axis_order)
    return cert


def recertify(trace: TraceCtx) -> ScheduleCertificate:
    """What a pass that legally reorders collectives calls on its output:
    re-derive the certificate and replace the stamped order, so the
    verifier accepts the new schedule as the baseline going forward."""
    return stamp(trace)


# =============================================================================
# Static overlap prediction — the compile-time twin of the measured lane
# segmentation (observability/attribution.py)
# =============================================================================


@dataclass
class SiteOverlap:
    """Predicted wire/hidden/exposed time of one collective site.

    ``wire_us`` prices the site's ring-factor traffic at the device spec's
    (possibly calibrated) per-family ICI bandwidth; ``window_us`` is the
    roofline compute time of the non-collective bsyms strictly between the
    site and its first consumer — the compute a latency-hiding runtime can
    provably run while the transfer is in flight, because the certificate
    says nothing in the window depends on the collective's output."""

    index: int
    sym: str
    axis: Optional[str]
    key: str
    wire_us: float
    window_us: float
    hidden_us: float
    first_consumer: Optional[int] = None

    @property
    def exposed_us(self) -> float:
        return max(0.0, self.wire_us - self.hidden_us)

    @property
    def hidden_frac(self) -> float:
        return self.hidden_us / self.wire_us if self.wire_us else 0.0

    def label(self) -> str:
        return f"L{self.index}.{self.sym}"


@dataclass
class OverlapPrediction:
    """Per-site predicted hidden/exposed wire time over one trace."""

    device: str
    sites: list = field(default_factory=list)
    # Per-line compute budget (µs) left after every site consumed its
    # share — what the comm scheduler's hoist scan must price NEW window
    # rows at, so two sites never count the same GEMM twice.
    residual_budget: dict = field(default_factory=dict)

    @property
    def wire_us(self) -> float:
        return sum(s.wire_us for s in self.sites)

    @property
    def hidden_us(self) -> float:
        return sum(s.hidden_us for s in self.sites)

    @property
    def exposed_us(self) -> float:
        return sum(s.exposed_us for s in self.sites)

    @property
    def exposed_pct(self) -> float:
        """Exposed fraction of total predicted wire time (percent)."""
        return self.exposed_us / self.wire_us * 100.0 if self.wire_us else 0.0

    def by_key(self) -> dict:
        return {s.key: s for s in self.sites}

    def format(self) -> str:
        lines = [
            f"predicted overlap [{self.device}]: {self.wire_us:.1f}us wire, "
            f"{self.hidden_us:.1f}us hidden, {self.exposed_us:.1f}us exposed "
            f"({self.exposed_pct:.1f}%)",
            f"  {'site':<26} {'axis':<6} {'wire us':>9} {'window':>9} "
            f"{'hidden':>9} {'exposed':>9}",
        ]
        for s in sorted(self.sites, key=lambda s: -s.wire_us):
            lines.append(
                f"  {s.label():<26.26} {s.axis or '-':<6} {s.wire_us:>9.2f} "
                f"{s.window_us:>9.2f} {s.hidden_us:>9.2f} {s.exposed_us:>9.2f}"
            )
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.format()


def predict_overlap(trace: TraceCtx, *, device: Any = None,
                    cert: Optional[ScheduleCertificate] = None) -> OverlapPrediction:
    """Predict, per collective site, how much of its wire time hides under
    the compute between the site and its first consumer.

    Model: a collective issued at its trace position completes no later
    than its first consumer; the roofline time of the non-collective bsyms
    strictly between the two is the overlap window (certified independent —
    they neither produce the site's operands nor consume its outputs).
    Windows share compute: each line's budget is consumed by sites in
    program order, so two collectives cannot both claim the same GEMM.
    ``hidden = min(wire, window-budget consumed)``; the rest is exposed.
    The comm scheduler (transforms/comm_schedule.py) moves sites inside
    their certified intervals to maximize exactly this number, and the
    ``sched.exposed-collective`` rule reports it per site."""
    from thunder_tpu.analysis.cost import resolve_device_spec, trace_cost

    dev = resolve_device_spec(device)
    if cert is None:
        cert = certify(trace)
    tc = trace_cost(trace, dev)
    compute_us: dict[int, float] = {}
    wire_by_index: dict[int, float] = {}
    for r in tc.rows:
        if r.kind == "collective":
            wire_by_index[r.index] = r.roofline_s * 1e6
        else:
            compute_us[r.index] = r.roofline_s * 1e6

    pred = OverlapPrediction(device=dev.name)
    budget = dict(compute_us)
    for site in sorted(cert.sites, key=lambda s: s.index):
        wire = wire_by_index.get(site.index, 0.0)
        c = site.first_consumer
        window = 0.0
        hidden = 0.0
        if c is not None:
            for j in range(site.index + 1, c):
                avail = budget.get(j, 0.0)
                window += compute_us.get(j, 0.0)
                if avail and hidden < wire:
                    take = min(avail, wire - hidden)
                    budget[j] = avail - take
                    hidden += take
        pred.sites.append(SiteOverlap(
            index=site.index, sym=site.sym, axis=site.axis, key=site.key,
            wire_us=wire, window_us=window, hidden_us=min(hidden, wire),
            first_consumer=c,
        ))
    pred.residual_budget = budget
    return pred


def _bsym_index_of_key(bsyms, key: str) -> Optional[int]:
    from thunder_tpu.distributed.prims import is_collective_bsym

    for i, bsym in enumerate(bsyms):
        if is_collective_bsym(bsym) and _site_key(i, bsym, _collective_axis(bsym)) == key:
            return i
    return None


# =============================================================================
# Verifier rule
# =============================================================================


@register_rule(
    "sched.uncertified-reorder",
    "Collectives keep their certified per-axis program order across passes",
)
def uncertified_reorder(ctx: VerifyContext) -> None:
    """Compares the trace's per-axis collective order against the stamped
    baseline. Additions (grad's reduce_scatters) and deletions (DCE) are
    legal; an *inversion* of two surviving same-axis collectives is the
    cross-host deadlock shape and is an ERROR attributed to the pass —
    unless the pass re-certified (``schedule.recertify``) its output.
    First sight of a trace with collectives stamps the baseline."""
    current = _axis_key_order(ctx.bsyms)
    tagged = ctx.trace.tags.get("collective_order")
    if tagged is None:
        if current:
            ctx.trace.tags["collective_order"] = current
        return
    found_inversion = False
    for axis, old in tagged.items():
        new = current.get(axis, ())
        pos = {k: p for p, k in enumerate(new)}
        common = [k for k in old if k in pos]
        positions = [pos[k] for k in common]
        inversion = next(
            (
                (common[j], common[j + 1])
                for j in range(len(common) - 1)
                if positions[j] > positions[j + 1]
            ),
            None,
        )
        if inversion is not None:
            found_inversion = True
            first, second = inversion
            ctx.report(
                "sched.uncertified-reorder",
                Severity.ERROR,
                f"axis {axis!r}: collectives {first} and {second} swapped their "
                "certified program order — hosts agreeing on the OLD order would "
                "deadlock against hosts running this trace",
                bsym_index=_bsym_index_of_key(ctx.bsyms, first),
                hint="a pass moving collectives must prove the move via "
                "analysis.schedule.certify (movable range) and re-stamp with "
                "schedule.recertify(trace)",
            )
    # Refresh the baseline so the next pass diffs against THIS trace —
    # but never adopt an order we just flagged: only schedule.recertify
    # (a pass that PROVED its move) may bless a reorder, otherwise a
    # re-verify of the same flagged trace would report clean.
    if not found_inversion:
        ctx.trace.tags["collective_order"] = current


# Sub-µs wire predictions are bookkeeping noise (replicated synchronize,
# zero-factor ops) — the advisory rule only reports sites worth scheduling.
_EXPOSED_RULE_MIN_WIRE_US = 1.0


@register_rule(
    "sched.exposed-collective",
    "Collective wire time is predicted hidden under certified-independent compute",
)
def exposed_collective(ctx: VerifyContext) -> None:
    """Advisory (INFO): per collective site, the statically predicted
    hidden/exposed wire time (:func:`predict_overlap`) — the compile-time
    twin of the measured lane segmentation. A site whose predicted wire
    time is mostly exposed is a scheduling opportunity the comm scheduler
    (``transforms/comm_schedule.py``) either already declined (pinned, or
    a liveness back-off) or has not seen. Never an error: exposure is a
    speed bug, not a correctness one."""
    from thunder_tpu.distributed.prims import is_collective_bsym

    if not any(is_collective_bsym(b) for b in ctx.bsyms):
        return
    try:
        pred = predict_overlap(ctx.trace, cert=certify(ctx.trace, ctx=ctx))
    except Exception:  # noqa: BLE001 — advisory prediction must never break verify
        return
    for s in pred.sites:
        if s.wire_us < _EXPOSED_RULE_MIN_WIRE_US or s.exposed_us <= 0.0:
            continue
        ctx.report(
            "sched.exposed-collective",
            Severity.INFO,
            f"{s.label()} [{s.axis or '-'}]: predicted {s.exposed_us:.1f}us of "
            f"{s.wire_us:.1f}us wire exposed ({s.hidden_us:.1f}us hidden under "
            f"the {s.window_us:.1f}us window to its consumer"
            + (f" at L{s.first_consumer}" if s.first_consumer is not None else "")
            + ")",
            bsym_index=s.index,
            hint="transforms/comm_schedule.schedule_collectives moves the site "
            "inside its certified [earliest, latest] interval to grow the "
            "window; a pinned or backed-off site needs more independent "
            "compute or a smaller transfer (quantized collectives)",
        )
