"""Distributed collective-consistency rules.

The prerequisite for growing the EQuARX-style distributed/quantized-collective
work: before a trace stages under ``shard_map``, every collective must name a
real mesh axis, all collectives sharing an axis must agree on the replica
group size, async futures must resolve through ``wait``, and a joint fw+bw
trace must carry the backward's balancing collective for every forward
parameter sync (the all_gather/reduce_scatter pairing of the FSDP rewrite).
"""

from __future__ import annotations

from typing import Optional

from thunder_tpu.analysis.context import VerifyContext
from thunder_tpu.analysis.diagnostics import Severity
from thunder_tpu.analysis.registry import register_rule
from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.distributed.prims import DistOpIDs

# Collective prims carrying (input, axis, group_size, ...) positionally.
_GROUPED_COLLECTIVES = {
    DistOpIDs.ALL_GATHER,
    DistOpIDs.ALL_REDUCE,
    DistOpIDs.BROADCAST,
    DistOpIDs.REDUCE_SCATTER,
    DistOpIDs.SYNCHRONIZE,
    DistOpIDs.ALL_TO_ALL,
}
# Collectives with an axis but no group size at that slot.
_AXIS_ONLY_COLLECTIVES = {DistOpIDs.PPERMUTE, DistOpIDs.MASK_TO_RANK}

_COLLECTIVE_IDS = _GROUPED_COLLECTIVES | _AXIS_ONLY_COLLECTIVES


def collective_axis_of(bsym) -> Optional[str]:
    """The mesh-axis operand of a collective bsym — THE one copy of the
    (input, axis, group_size, ...) calling convention, shared by the dist.*
    rules here and the schedule certificate (analysis/schedule.py). May
    return a malformed (non-str) value; ``dist.axis`` reports those."""
    if len(bsym.args) > 1:
        return bsym.args[1]
    return bsym.kwargs.get("axis")


_collective_axis = collective_axis_of


def _collective_group_size(bsym):
    if len(bsym.args) > 2:
        return bsym.args[2]
    return bsym.kwargs.get("group_size")


def _is_fsdp_sync(bsym) -> bool:
    """A synchronize over a dim-0-sharded (fsdp) parameter."""
    from thunder_tpu.distributed.prims import _sync_is_sharded

    try:
        a = bsym.args[0] if bsym.args else bsym.kwargs.get("a")
        ptype = bsym.args[3] if len(bsym.args) > 3 else bsym.kwargs.get("parallel_type")
        return _sync_is_sharded(a, ptype)
    except Exception:  # noqa: BLE001 — malformed operand; other rules report it
        return False


@register_rule("dist.axis", "Every collective names a mesh axis (a non-empty string)")
def collective_axis(ctx: VerifyContext) -> None:
    for i, bsym in enumerate(ctx.bsyms):
        if bsym.sym.id not in _COLLECTIVE_IDS:
            continue
        axis = _collective_axis(bsym)
        if not isinstance(axis, str) or not axis:
            ctx.report(
                "dist.axis",
                Severity.ERROR,
                f"{bsym.sym.qualname} has mesh axis {axis!r} (expected a non-empty axis name)",
                bsym_index=i,
                hint="collectives lower by named mesh axis; the rewrite must thread the "
                "distributed config's axis name through",
            )


@register_rule("dist.group-size-mismatch", "Collectives sharing a mesh axis agree on the group size")
def group_size_consistency(ctx: VerifyContext) -> None:
    first_by_axis: dict[str, tuple[int, int]] = {}  # axis -> (group_size, bsym index)
    for i, bsym in enumerate(ctx.bsyms):
        if bsym.sym.id not in _GROUPED_COLLECTIVES:
            continue
        axis = _collective_axis(bsym)
        gs = _collective_group_size(bsym)
        if not isinstance(axis, str) or not isinstance(gs, int):
            continue  # dist.axis reports malformed operands
        prev = first_by_axis.get(axis)
        if prev is None:
            first_by_axis[axis] = (gs, i)
        elif prev[0] != gs:
            ctx.report(
                "dist.group-size-mismatch",
                Severity.ERROR,
                f"{bsym.sym.qualname} uses group size {gs} on axis {axis!r}, but bsym "
                f"{prev[1]} uses {prev[0]} — one mesh axis, two replica-group shapes",
                bsym_index=i,
                hint="a rewrite resized the mesh (or mixed configs); all collectives on an "
                "axis must see the same device count",
            )


@register_rule("dist.future-without-wait", "Async collective futures resolve through wait before use")
def future_without_wait(ctx: VerifyContext) -> None:
    for name, producer in ctx.future_defs.items():
        waited = False
        misused = False
        for i in ctx.live_uses.get(name, ()):
            consumer = ctx.bsyms[i]
            if consumer.sym.id is DistOpIDs.WAIT:
                waited = True
            elif consumer.sym.id is not PrimIDs.RETURN:
                misused = True
                ctx.report(
                    "dist.future-without-wait",
                    Severity.ERROR,
                    f"{consumer.sym.qualname} consumes future {name!r} directly; only "
                    "dist_prims.wait may resolve an async collective's result",
                    bsym_index=i,
                    hint="insert wait(future) (or drop async_op=True) before using the value",
                )
        if not waited and not misused and name not in ctx.output_names:
            ctx.report(
                "dist.future-without-wait",
                Severity.WARNING,
                f"future {name!r} (bsym {producer}) is never waited on — the collective's "
                "completion is unobservable",
                bsym_index=producer,
            )


@register_rule(
    "dist.unbalanced-grad-collectives",
    "In a joint fw+bw trace, every fsdp parameter sync has a backward reduce_scatter",
)
def unbalanced_grad_collectives(ctx: VerifyContext) -> None:
    """The FSDP pairing invariant of the backward rewrite: forward all-gathers
    (fsdp ``synchronize``) and backward ``reduce_scatter``s must balance per
    mesh axis. Scoped to joint grad traces (provenance "Grad transform") —
    forward-only traces legitimately carry unpaired gathers."""
    if not (ctx.pass_name or "").startswith("Grad transform"):
        return
    syncs: dict[str, list[int]] = {}
    scatters: dict[str, int] = {}
    for i, bsym in enumerate(ctx.bsyms):
        if bsym.sym.id is DistOpIDs.SYNCHRONIZE and _is_fsdp_sync(bsym):
            if bsym.kwargs.get("grad_sync", True) is False:
                continue  # no_sync: the deferred collective is outside this trace by design
            axis = _collective_axis(bsym)
            if isinstance(axis, str):
                syncs.setdefault(axis, []).append(i)
        elif bsym.sym.id is DistOpIDs.REDUCE_SCATTER:
            axis = _collective_axis(bsym)
            if isinstance(axis, str):
                scatters[axis] = scatters.get(axis, 0) + 1
    for axis, sites in syncs.items():
        n_sync, n_scatter = len(sites), scatters.get(axis, 0)
        if n_scatter < n_sync:
            ctx.report(
                "dist.unbalanced-grad-collectives",
                Severity.WARNING,
                f"axis {axis!r}: {n_sync} fsdp parameter sync(s) in the forward but only "
                f"{n_scatter} reduce_scatter(s) in the backward — a parameter's gradient "
                "is never re-sharded",
                bsym_index=sites[0],
                hint="the synchronize VJP should emit reduce_scatter(grad, axis, group) for "
                "each sharded parameter (check the grad-sync rewrite)",
            )
