"""Structured diagnostics for the trace verifier.

A ``Diagnostic`` is one finding from one rule: machine-readable (rule id,
severity, bsym index, provenance pass name) so pipelines can gate on it, and
human-readable (message, fix hint, offending trace line) so ``examine.lint``
can pretty-print it. The design follows the FX-graph validation passes of
Forge-UGC (PAPERS.md): every transform's output is checked against a rule
suite and the first violation is attributed to the pass that introduced it.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Sequence


class Severity(enum.IntEnum):
    """Ordered so thresholds compose: ``sev >= Severity.ERROR`` gates raise."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error", not "Severity.ERROR", in reports
        return self.name.lower()


@dataclass
class Diagnostic:
    """One finding: which rule fired, where, and how to fix it."""

    rule: str
    severity: Severity
    message: str
    bsym_index: Optional[int] = None
    pass_name: Optional[str] = None
    hint: Optional[str] = None
    # The offending generated line(s), filled in by formatting helpers.
    trace_line: Optional[str] = None

    def format(self) -> str:
        loc = f" @ bsym {self.bsym_index}" if self.bsym_index is not None else ""
        origin = f" [after: {self.pass_name}]" if self.pass_name else ""
        out = f"{self.severity}: [{self.rule}]{loc}{origin} {self.message}"
        if self.trace_line:
            out += f"\n    >> {self.trace_line}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


def attach_trace_lines(diagnostics: Sequence[Diagnostic], trace) -> None:
    """Fill each diagnostic's ``trace_line`` from its bsym index (best-effort:
    printers that need exec-namespace context may fail on hand-built bsyms)."""
    for d in diagnostics:
        if d.bsym_index is None or d.trace_line is not None:
            continue
        try:
            d.trace_line = trace.bound_symbols[d.bsym_index].one_line()
        except Exception:
            pass


def max_severity(diagnostics: Sequence[Diagnostic]) -> Optional[Severity]:
    return max((d.severity for d in diagnostics), default=None)


class TraceVerificationError(RuntimeError):
    """Raised when a verified trace violates an invariant at ERROR severity.

    Carries the full structured diagnostics list; the message leads with the
    first failing diagnostic and the pass that introduced it.
    """

    def __init__(self, diagnostics: Sequence[Diagnostic], pass_name: Optional[str] = None):
        self.diagnostics = list(diagnostics)
        self.pass_name = pass_name
        errors = [d for d in self.diagnostics if d.severity >= Severity.ERROR]
        head = errors[0] if errors else (self.diagnostics[0] if self.diagnostics else None)
        origin = pass_name or (head.pass_name if head else None)
        lead = f"trace verification failed after pass {origin!r}" if origin else "trace verification failed"
        body = "\n".join(d.format() for d in self.diagnostics)
        super().__init__(f"{lead}: {len(errors)} error(s)\n{body}")
