"""Static HBM liveness planner over claimed execution traces.

The *memory* third of the static trace planner suite (ISSUE 10; the other
two are ``analysis/schedule.py`` and the donation sanitizer in
``analysis/rules.py``): every value-producing BoundSymbol's tensor outputs
are assigned byte sizes from their proxy metadata alone — dtype-aware,
bucket-padding-aware (a symbolic trace's shapes ARE the padded bucket
ceilings), sharding-divided when the caller supplies PartitionSpec divisors
— and an interval walk over the program computes the per-line live set and
its peak: the predicted per-device HBM high-water of running the trace.

Lifetime model (documented so the golden tests are exact):

- trace inputs are live from entry. Non-donated inputs stay live to the end
  (the caller holds the buffer; XLA cannot reuse it). A **donated** input
  dies at its last use — donation is precisely the license to reuse it.
- every produced tensor goes live at its producing line and dies after its
  last consumer, alias-extended (a view's use keeps its root buffer alive).
  Explicit ``python_del``s (post ``del_last_used``) are ignored for
  freeing: they are per-name, so honoring one would free a root whose
  views still live; the interval analysis frees at the same point when no
  views remain and later when they do, keeping the del'd and un-del'd
  plans of one program equal.
- trace outputs never die (they are returned).
- pure layout/alias ops (reshape/squeeze/broadcast/shallow_copy/
  stop_gradient) charge **zero** bytes — XLA compiles them to views — and
  their uses extend the *root* buffer's lifetime through the alias chain.
- bookkeeping prims (unpacks, guards, del/return/comment) allocate nothing.

The prediction is a *lower bound* on the real high-water (XLA adds
executable temporaries and fragmentation); ``scripts/lint_traces.py
--static`` holds it within 15% of the ``instrument="memory"`` measured
high-water on the GPT-block bench.

Consumers: ``examine.memory_report(fn, *args)`` (user-facing),
the ``mem.predicted-oom`` verifier rule (``THUNDER_TPU_CHECKS=1`` /
``examine.lint``), and the compile de-opt ladder
(``resilience/deopt.py``), which uses :func:`predict_level_peaks` to jump
straight to the first ladder level whose predicted peak fits the device
instead of paying one failed XLA compile per level.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from thunder_tpu.analysis.cost import DeviceSpec, resolve_device_spec
from thunder_tpu.analysis.diagnostics import Severity
from thunder_tpu.analysis.registry import register_rule
from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.core.proxies import TensorProxy
from thunder_tpu.core.pytree import tree_flatten
from thunder_tpu.core.trace import TraceCtx

# Prims that allocate nothing and touch no tensor lifetimes (guards,
# unpacks, control plumbing). DEL/RETURN are handled explicitly.
_BOOKKEEPING_IDS = {
    PrimIDs.COMMENT, PrimIDs.PRINT,
    PrimIDs.UNPACK_TRIVIAL, PrimIDs.UNPACK_SEQUENCE, PrimIDs.UNPACK_KEY,
    PrimIDs.UNPACK_ATTR, PrimIDs.UNPACK_DIM,
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA, PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    PrimIDs.CHECK_STRING_VALUE, PrimIDs.CHECK_LEN, PrimIDs.CHECK_KEYS,
    PrimIDs.CHECK_NONE, PrimIDs.CHECK_DIM_BUCKET,
}

# Layout/alias ops XLA lowers to views: zero bytes; output aliases arg 0.
_ALIAS_IDS = {
    PrimIDs.RESHAPE, PrimIDs.SQUEEZE, PrimIDs.BROADCAST_IN_DIM,
    PrimIDs.SHALLOW_COPY, PrimIDs.STOP_GRADIENT,
}


def build_alias_roots(bsyms) -> dict:
    """``{view name: immediate source name}`` for every alias-op output —
    THE one copy of the view model (first tensor operand is the root),
    shared by the liveness walk, the donation/alias sanitizer rules, and
    the schedule certificate's anti-dependency analysis."""
    alias: dict = {}
    for bsym in bsyms:
        if bsym.sym.id not in _ALIAS_IDS:
            continue
        src = next(
            (p for p in bsym.flat_proxy_args if isinstance(p, TensorProxy)), None
        )
        if src is None:
            continue
        for o in bsym.flat_proxy_outs:
            if isinstance(o, TensorProxy) and o.name != src.name:
                alias[o.name] = src.name
    return alias


def alias_root_fn(bsyms):
    """``root(name) -> name`` resolving through the full view chain."""
    alias = build_alias_roots(bsyms)

    def root(name: str) -> str:
        while name in alias:
            name = alias[name]
        return name

    return root


@dataclass
class LivenessRow:
    """One value-producing trace line's live-set accounting."""

    index: int
    sym: str
    live_bytes: int       # live-set bytes AFTER this line executes
    alloc_bytes: int      # bytes this line's outputs charge
    freed_bytes: int      # bytes whose last use was this line
    line: str = ""


@dataclass
class MemoryPlan:
    """Predicted per-device HBM occupancy of one trace.

    ``peak_bytes`` is the planner's headline number: the maximum live-set
    over the program. ``eager_alloc_bytes`` sums every concrete tensor an
    *unstaged* (instrumented, op-by-op) run would materialize — produced
    tensors only, inputs excluded — the number comparable to
    ``MemoryHighWater``'s cumulative fallback estimate on backends without
    ``memory_stats`` (the CPU plugin; ``lint_traces.py --static`` uses
    whichever comparison the backend supports)."""

    device: DeviceSpec
    peak_bytes: int = 0
    peak_index: Optional[int] = None
    peak_sym: Optional[str] = None
    input_bytes: int = 0
    output_bytes: int = 0
    total_alloc_bytes: int = 0
    eager_alloc_bytes: int = 0
    donated_names: tuple = ()
    rows: list = field(default_factory=list)

    def fits(self, capacity_bytes: Optional[int] = None) -> bool:
        cap = capacity_bytes if capacity_bytes is not None else device_capacity_bytes(self.device)
        return cap is None or self.peak_bytes < cap

    def headroom(self, capacity_bytes: Optional[int] = None) -> Optional[float]:
        """capacity / predicted peak (None when capacity is unknown)."""
        cap = capacity_bytes if capacity_bytes is not None else device_capacity_bytes(self.device)
        if cap is None or not self.peak_bytes:
            return None
        return cap / self.peak_bytes

    def format(self, top_k: int = 8) -> str:
        cap = device_capacity_bytes(self.device)
        lines = [
            f"memory plan [{self.device.name}"
            + (f": {cap / 1e9:.1f} GB HBM]" if cap else "]"),
            f"  predicted peak: {self.peak_bytes / 1e6:.2f} MB"
            + (f" at L{self.peak_index} ({self.peak_sym})" if self.peak_index is not None else "")
            + (f" — {self.peak_bytes / cap * 100:.1f}% of device" if cap else ""),
            f"  inputs {self.input_bytes / 1e6:.2f} MB"
            + (f" ({len(self.donated_names)} donated)" if self.donated_names else "")
            + f", outputs {self.output_bytes / 1e6:.2f} MB, "
            f"total allocated {self.total_alloc_bytes / 1e6:.2f} MB",
        ]
        hottest = sorted(self.rows, key=lambda r: r.live_bytes, reverse=True)[:top_k]
        if hottest:
            lines.append(f"  {'line':>6} {'sym':<28} {'live MB':>10} {'alloc MB':>10}")
            for r in hottest:
                lines.append(
                    f"  L{r.index:>5} {r.sym:<28.28} {r.live_bytes / 1e6:>10.3f} "
                    f"{r.alloc_bytes / 1e6:>10.3f}"
                )
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.format()


# The backend bytes_limit probe never changes within a process; memoized so
# the mem.predicted-oom rule (which runs per pass under THUNDER_TPU_CHECKS=1)
# pays one backend query per process, not one per verify().
_backend_limit_cache: dict = {}


def _backend_bytes_limit() -> Optional[int]:
    if "limit" not in _backend_limit_cache:
        limit = None
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if stats and stats.get("bytes_limit"):
                limit = int(stats["bytes_limit"])
        except Exception:
            pass
        _backend_limit_cache["limit"] = limit
    return _backend_limit_cache["limit"]


def device_capacity_bytes(device: Any = None) -> Optional[int]:
    """Usable HBM bytes of one device: the ``THUNDER_TPU_HBM_BYTES`` env
    override first (tests, and operators who know their binary's reserved
    fraction; re-read every call so scoped overrides work), then the live
    backend's ``memory_stats()['bytes_limit']`` (memoized per process),
    then the spec's datasheet capacity. None when nothing is known."""
    env = os.environ.get("THUNDER_TPU_HBM_BYTES", "").strip()
    if env:
        try:
            return int(float(env))
        except ValueError:
            pass
    limit = _backend_bytes_limit()
    if limit:
        return limit
    try:
        spec = resolve_device_spec(device)
    except Exception:
        return None
    return spec.hbm_bytes or None


def partition_divisor(spec: Any, axis_sizes: dict) -> float:
    """How many ways a PartitionSpec splits a tensor over a mesh: the
    product of the named axes' sizes (axis tuples multiply; None/absent
    axes divide by 1)."""
    div = 1.0
    for part in tuple(spec or ()):
        for ax in (part if isinstance(part, (tuple, list)) else (part,)):
            if ax is not None:
                div *= float(axis_sizes.get(ax, 1))
    return div


def arg_divisors_from_specs(trace: TraceCtx, specs, mesh=None, axis_sizes=None) -> dict:
    """``{input proxy name: shard divisor}`` from a PartitionSpec pytree
    aligned with the trace's tensor args (``parallel/sharding.py`` plans).

    This divides INPUT buffers only: intermediates of a pjit-staged trace
    have no trace-level sharding (the SPMD partitioner decides), so a plan
    built with these divisors is an UPPER BOUND on the per-device peak —
    params at shard size, activations conservatively at global shape.
    Honest for fit checks (an upper bound that fits, fits); not a measured
    per-device number."""
    if axis_sizes is None:
        if mesh is None:
            return {}
        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    flat_specs, _ = tree_flatten(
        specs, is_leaf=lambda x: type(x).__name__ == "PartitionSpec"
    )
    args = [a for a in tree_flatten((trace.args, trace.kwargs))[0] if isinstance(a, TensorProxy)]
    out: dict[str, float] = {}
    for a, s in zip(args, flat_specs):
        d = partition_divisor(s, axis_sizes)
        if d > 1.0:
            out[a.name] = d
    return out


def _tensor_bytes(p: TensorProxy, divisors: Optional[dict]) -> int:
    b = p.size_bytes
    if divisors:
        d = divisors.get(p.name)
        if d:
            b = int(b / d)
    return int(b)


def plan_liveness(
    trace: TraceCtx,
    *,
    device: Any = None,
    donated: Sequence[str] = (),
    arg_divisors: Optional[dict] = None,
    include_rows: bool = True,
) -> MemoryPlan:
    """Interval-based liveness walk over ``trace`` → :class:`MemoryPlan`.

    ``donated`` names input proxies whose buffers XLA may reuse (they die at
    last use); defaults to the trace's ``donated_inputs`` tag when the
    compile pipeline stamped one. ``arg_divisors`` divides named input
    buffers for sharded (global-shape) traces — see
    :func:`arg_divisors_from_specs`."""
    dev = resolve_device_spec(device)
    plan = MemoryPlan(device=dev)
    if donated == () and trace.tags.get("donated_inputs"):
        donated = tuple(trace.tags["donated_inputs"])
    plan.donated_names = tuple(donated)
    donated_set = set(plan.donated_names)

    bsyms = list(trace.bound_symbols)

    # -- one pass: sizes, alias roots, last-use indexes ------------------------
    sizes: dict[str, int] = {}
    alias_root = build_alias_roots(bsyms)

    def root_of(name: str) -> str:
        while name in alias_root:
            name = alias_root[name]
        return name

    inputs: list[TensorProxy] = [
        a for a in tree_flatten((trace.args, trace.kwargs))[0] if isinstance(a, TensorProxy)
    ]
    for a in inputs:
        sizes.setdefault(a.name, _tensor_bytes(a, arg_divisors))
    input_names = {a.name for a in inputs}
    plan.input_bytes = sum(sizes[a.name] for a in inputs)

    out_names: set[str] = set()
    for p in tree_flatten(trace.output)[0]:
        if isinstance(p, TensorProxy):
            out_names.add(p.name)

    # last_use[root] = index of the last bsym consuming the root (through
    # aliases). Explicit DELs are ignored for freeing: del_last_used emits a
    # del per NAME right after its last use, which would free a view's root
    # buffer while other views still live — the alias-extended interval
    # analysis frees at the same point when no views remain, and later when
    # they do, so the del'd and un-del'd plans of one program agree.
    last_use: dict[str, int] = {}
    for i, bsym in enumerate(bsyms):
        sid = bsym.sym.id
        if sid is PrimIDs.DEL:
            continue
        for p in bsym.flat_proxy_args:
            if isinstance(p, TensorProxy):
                last_use[root_of(p.name)] = i

    # Invert last_use once: dying_at[i] = root names whose final consumer is
    # line i. The walk is then O(bsyms + values) instead of rescanning the
    # whole live set per line (the planner runs on every compile — its
    # seconds are a gated compile phase).
    dying_at: dict[int, list] = {}
    for name, i in last_use.items():
        dying_at.setdefault(i, []).append(name)

    # -- the walk --------------------------------------------------------------
    live: dict[str, int] = {}
    for a in inputs:
        live[a.name] = sizes[a.name]
    cur = sum(live.values())
    plan.peak_bytes = cur
    plan.total_alloc_bytes = cur

    def free(name: str, idx: int) -> int:
        """Free ``name`` if it may die: never outputs; inputs only when
        donated."""
        r = root_of(name)
        if r in out_names or (r in input_names and r not in donated_set):
            return 0
        return live.pop(r, 0)

    for i, bsym in enumerate(bsyms):
        sid = bsym.sym.id
        if sid in (PrimIDs.RETURN,):
            break
        if sid is PrimIDs.DEL or sid in _BOOKKEEPING_IDS:
            continue
        alloc = 0
        eager = 0
        arg_names = {p.name for p in bsym.flat_proxy_args}
        for o in bsym.flat_proxy_outs:
            if not isinstance(o, TensorProxy) or o.name in arg_names:
                continue
            b = _tensor_bytes(o, arg_divisors)
            sizes.setdefault(o.name, b)
            eager += b
            if sid in _ALIAS_IDS or o.name in alias_root:
                continue  # view: no new buffer
            if o.name not in live:
                live[o.name] = b
                alloc += b
        cur += alloc
        plan.total_alloc_bytes += alloc
        plan.eager_alloc_bytes += eager
        if cur > plan.peak_bytes:
            plan.peak_bytes = cur
            plan.peak_index = i
            plan.peak_sym = bsym.sym.name
        # Free every value whose (alias-extended) last use was this line.
        freed = 0
        dying = dying_at.get(i)
        if dying:
            out_here = {
                o.name for o in bsym.flat_proxy_outs if isinstance(o, TensorProxy)
            }
            for name in dying:
                if name not in out_here:
                    freed += free(name, i)
        cur -= freed
        if include_rows and (alloc or freed or bsym.flat_proxy_outs):
            plan.rows.append(LivenessRow(
                index=i, sym=bsym.sym.name, live_bytes=int(cur),
                alloc_bytes=int(alloc), freed_bytes=int(freed),
            ))

    plan.output_bytes = sum(sizes.get(root_of(n), 0) for n in out_names)
    return plan


# =============================================================================
# De-opt ladder prediction (resilience/deopt.py consults this)
# =============================================================================


def _marked_bytes(sym_spec, true_extents: Optional[dict],
                  arg_proxies: Optional[Sequence]) -> Optional[tuple]:
    """(padded_bytes, exact_bytes) summed over the marked input leaves —
    full numel × dtype bytes with marked dims at the bucket ceiling vs the
    failing call's exact extents (two marked dims of one leaf multiply).
    None when the spec, extents, or shapes are unknown."""
    if sym_spec is None or not true_extents:
        return None
    padded = 0.0
    exact = 0.0
    for li, dims in sym_spec.marks.items():
        if arg_proxies is None or li >= len(arg_proxies):
            return None
        p = arg_proxies[li]
        if not isinstance(p, TensorProxy):
            return None
        padded_numel = float(p.numel)
        exact_numel = padded_numel
        for d, (lo, hi, cid) in dims.items():
            e = true_extents.get(cid)
            if e is None or not hi:
                return None
            exact_numel *= float(e) / float(hi)
        padded += padded_numel * p.dtype.bytes
        exact += exact_numel * p.dtype.bytes
    if not padded:
        return None
    return padded, exact


def exact_shape_scale(sym_spec, true_extents: Optional[dict],
                      arg_proxies: Optional[Sequence] = None) -> Optional[float]:
    """Byte ratio exact/padded over the marked input leaves — how much the
    de-opt ladder's L3 ("exact shapes") shrinks the bucket-padded
    activations. A true byte ratio: each marked leaf contributes its full
    numel × dtype bytes with marked dims at the padded ceiling vs the
    failing call's exact extents (two marked dims of one leaf multiply;
    unmarked dims and dtype weight each leaf correctly — a tiny mask leaf
    cannot dilute a huge activation's shrinkage). ``arg_proxies`` are the
    trace's tensor args, aligned with the spec's leaf indices. None when
    the spec, extents, or shapes are unknown — the caller must treat that
    level as unprovable, never skippable."""
    mb = _marked_bytes(sym_spec, true_extents, arg_proxies)
    if mb is None:
        return None
    return _scale_of(*mb)


def _scale_of(padded_bytes: float, exact_bytes: float) -> float:
    """THE clamped byte-ratio formula — one copy, shared by
    :func:`exact_shape_scale` and the L3 pricing in
    :func:`predict_level_peaks`."""
    return max(min(exact_bytes / padded_bytes, 1.0), 1e-3)


def predict_level_peaks(
    trace: TraceCtx,
    *,
    sym_spec=None,
    donated: Sequence[str] = (),
    true_extents: Optional[dict] = None,
    device: Any = None,
    bucketing_unknown: bool = False,
) -> dict[int, Optional[int]]:
    """Predicted per-device peak bytes at each de-opt ladder level
    (``resilience/deopt.py``): L0 as compiled (donation on), L1 donation
    off, L2 = L1 (the ladder's aggressive-remat knob rewrites the module
    fw/bw split, which does not route through this ladder — on the
    functional pipeline L2 compiles the same program as L1). L3 ("exact
    shapes") shrinks BOTH the marked inputs (exact bytes replace padded)
    and the activation share (scaled by the exact/padded byte ratio), so
    the L3 prediction stays a lower bound — the skip logic's "predicted >=
    capacity proves unfit" premise. A ``None`` peak means "unknown — never
    skip this level". ``bucketing_unknown=True`` forces L3 unknown: the
    caller could not tell whether the trace is bucket-padded (e.g. a
    symbolic-cache function failing before its entry exists), so L3 must
    not be "proven" anything from a possibly-padded plan."""
    base = plan_liveness(trace, device=device, donated=donated, include_rows=False)
    # plan_liveness treats donated=() as "consult the trace tag", so the
    # donation-off plan must suppress the tag explicitly.
    no_don = _plan_without_donation(trace, device) if (
        donated or trace.tags.get("donated_inputs")
    ) else base
    peaks: dict[int, Optional[int]] = {
        0: base.peak_bytes,
        1: no_don.peak_bytes,
        2: no_don.peak_bytes,
        3: no_don.peak_bytes,
    }
    args = [a for a in tree_flatten((trace.args, trace.kwargs))[0]
            if isinstance(a, TensorProxy)]
    mb = _marked_bytes(sym_spec, true_extents, args)
    if bucketing_unknown:
        peaks[3] = None
    elif mb is not None:
        # Exact shapes shrink the marked inputs to their exact bytes AND the
        # activation share by the exact/padded byte ratio; unmarked inputs
        # (params) don't shrink. A ratio of exactly 1.0 (the call sits at
        # its bucket ceilings) is a KNOWN peak equal to L1's — provably
        # unfit when L1 is, so the ladder must not burn a compile "trying"
        # L3 on an unknown.
        padded_m, exact_m = mb
        scale = _scale_of(padded_m, exact_m)
        inputs_l3 = max(no_don.input_bytes - padded_m + exact_m, 0.0)
        act = max(no_don.peak_bytes - no_don.input_bytes, 0)
        peaks[3] = int(inputs_l3 + act * scale)
    elif sym_spec is None:
        peaks[3] = no_don.peak_bytes
    else:
        peaks[3] = None  # padded entry, extents unknown: can't prove either way
    return peaks


def _plan_without_donation(trace: TraceCtx, device) -> MemoryPlan:
    tag = trace.tags.pop("donated_inputs", None)
    try:
        return plan_liveness(trace, device=device, include_rows=False)
    finally:
        if tag is not None:
            trace.tags["donated_inputs"] = tag


# =============================================================================
# examine.memory_report
# =============================================================================


def memory_report(fn: Callable, *args, executors: Any = None, device: Any = None,
                  **kwargs) -> MemoryPlan:
    """Trace ``fn`` on the example inputs through the default pass pipeline
    (acquisition → DCE → CSE → claiming → del_last_used) and return the
    :class:`MemoryPlan` of the resulting execution trace — the static
    memory half of the planner suite (``examine.memory_report`` re-exports
    this; docs/performance.md).

    For an already-compiled ``thunder_tpu.jit`` function the underlying
    function is traced (mirroring ``examine.cost_report``); the exact plan
    of a compiled entry — donation and bucket padding included — is on the
    entry itself (``cache_info(jfn)`` → ``predicted_peak_bytes``)."""
    from thunder_tpu.api import trace_program
    from thunder_tpu.core.trace import debug_checks
    from thunder_tpu.executors.passes import del_last_used, transform_for_execution
    from thunder_tpu.extend import resolve_executors
    from thunder_tpu.transforms.common import cse, dce

    cd = getattr(fn, "_lc_cd", None)
    if cd is not None:
        fn = cd.fn
    with debug_checks(False):
        _, comp = trace_program(fn, args, kwargs)
        comp = cse(dce(comp))
        extrace = transform_for_execution(comp, resolve_executors(executors))
        extrace = del_last_used(extrace)
    return plan_liveness(extrace, device=device)


# =============================================================================
# Verifier rule: predicted OOM
# =============================================================================

# Traces smaller than this are guard/prologue plumbing — planning them would
# only add noise to every verify() call.
_MIN_RULE_BSYMS = 4


@register_rule(
    "mem.predicted-oom",
    "The trace's predicted peak HBM live-set fits the device's capacity",
)
def predicted_oom(ctx) -> None:
    """WARNING when the static live-set peak exceeds the detected device
    capacity: the compile is *predicted* to OOM before XLA spends ~20s
    discovering it (the de-opt ladder consults the same plan to jump
    levels). A warning, not an error — the plan is a lower bound and XLA
    may still fit via donation/aliasing the model can't see."""
    if len(ctx.bsyms) < _MIN_RULE_BSYMS:
        return
    try:
        # Capacity first: on capacity-unknown hosts (CPU spec, no
        # bytes_limit, no env override) the rule can never fire, so don't
        # pay the O(trace) planning walk per pass under checks.
        cap = device_capacity_bytes()
        if not cap:
            return
        plan = plan_liveness(ctx.trace, include_rows=False)
    except Exception:  # noqa: BLE001 — planning must never break verification
        return
    if cap and plan.peak_bytes > cap:
        ctx.report(
            "mem.predicted-oom",
            Severity.WARNING,
            f"predicted peak live-set {plan.peak_bytes / 1e9:.2f} GB exceeds the "
            f"{plan.device.name} device capacity {cap / 1e9:.2f} GB"
            + (f" (peak at L{plan.peak_index}.{plan.peak_sym})"
               if plan.peak_index is not None else ""),
            bsym_index=plan.peak_index,
            hint="expect RESOURCE_EXHAUSTED; shrink the bucket ceilings, enable "
            "donation, or let the de-opt ladder pick a remat level "
            "(resilience/deopt.py consults this same plan)",
        )
