"""Built-in verifier rules: SSA/def-use, meta consistency, alias hazards,
DCE safety, and name-registry hygiene.

Each rule consumes the precomputed :class:`VerifyContext` indexes — the trace
itself is walked exactly once, by the context. Severities: structural breaks
(use-before-def, redefinition, metadata drift, in-place hazards) are ERRORs —
a pass emitting them produced a program that cannot mean what the source
meant. Dead symbols are WARNINGs (legitimate pre-DCE, a bug post-DCE), and
orphaned registry names are INFO (``from_trace`` shares the name pool on
purpose, so stale names are expected after elimination passes).
"""

from __future__ import annotations

from thunder_tpu.analysis.context import VerifyContext, needs_definition
from thunder_tpu.analysis.diagnostics import Severity
from thunder_tpu.analysis.registry import register_rule
from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import TensorProxy
from thunder_tpu.core.pytree import tree_flatten


# =============================================================================
# (1) SSA / def-use
# =============================================================================


@register_rule("ssa.use-before-def", "Every consumed proxy is produced earlier or is a trace input")
def ssa_use_before_def(ctx: VerifyContext) -> None:
    for i, bsym in enumerate(ctx.bsyms):
        for p in bsym.flat_proxy_args:
            if not needs_definition(p):
                continue
            if not ctx.defined_before(p.name, i):
                where = "never defined" if p.name not in ctx.defs else f"defined later (bsym {ctx.defs[p.name][0]})"
                ctx.report(
                    "ssa.use-before-def",
                    Severity.ERROR,
                    f"{bsym.sym.qualname} consumes {p.name!r}, which is {where} and is not a trace input",
                    bsym_index=i,
                    hint="the producing symbol was dropped or reordered by the pass; "
                    "check its swap map / liveness set",
                )


@register_rule("ssa.redefinition", "No proxy name is produced twice")
def ssa_redefinition(ctx: VerifyContext) -> None:
    for i, name, prev in ctx.redefs:
        ctx.report(
            "ssa.redefinition",
            Severity.ERROR,
            f"{ctx.bsyms[i].sym.qualname} redefines {name!r}, already produced by bsym {prev}",
            bsym_index=i,
            hint="a rewriting pass must mint fresh proxies (trace.make_name) for new outputs",
        )


@register_rule("ssa.undefined-output", "Every trace output proxy has a producer (outputs are live)")
def ssa_undefined_output(ctx: VerifyContext) -> None:
    for p in ctx.output_proxies:
        if not needs_definition(p):
            continue
        if p.name not in ctx.input_names and p.name not in ctx.defs:
            ctx.report(
                "ssa.undefined-output",
                Severity.ERROR,
                f"trace output {p.name!r} is produced by no symbol and is not an input",
                hint="the pass rewired outputs without updating trace.output (or DCE'd the producer)",
            )


# =============================================================================
# (2) Metadata consistency (shape/dtype/device vs the prim's meta function)
# =============================================================================

# Prims whose metas are structural/guard plumbing over concrete caller data,
# or (synchronize) read trace-time proxy attributes a later pass may not
# preserve — re-running them is not a well-defined oracle.
_META_EXEMPT_IDS = {
    PrimIDs.UNPACK_TRIVIAL,
    PrimIDs.UNPACK_SEQUENCE,
    PrimIDs.UNPACK_KEY,
    PrimIDs.UNPACK_ATTR,
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA,
    PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    PrimIDs.CHECK_STRING_VALUE,
    PrimIDs.CHECK_LEN,
    PrimIDs.CHECK_KEYS,
    PrimIDs.CHECK_NONE,
    # Symbolic-values guards: structural plumbing over concrete caller data,
    # like the checks above (and unpack_dim's output is a NumberProxy, which
    # the meta rules do not model).
    PrimIDs.UNPACK_DIM,
    PrimIDs.CHECK_DIM_BUCKET,
}


def _meta_exempt(bsym) -> bool:
    if bsym.sym.id in _META_EXEMPT_IDS:
        return True
    from thunder_tpu.distributed.prims import DistOpIDs

    return bsym.sym.id is DistOpIDs.SYNCHRONIZE


def _meta_findings(ctx: VerifyContext) -> list[tuple]:
    """One shared meta-re-run walk per verify() call, cached on the context:
    both meta rules consume it, so disabling either rule id (per-call or
    process-wide) suppresses exactly its findings without a second walk."""
    cached = getattr(ctx, "_meta_findings_cache", None)
    if cached is not None:
        return cached
    findings: list[tuple] = []  # (kind, bsym_index, message, hint)
    for i, bsym in enumerate(ctx.bsyms):
        sym = bsym.sym
        if not sym.is_prim or sym.meta is None or _meta_exempt(bsym):
            continue
        got = [t for t in bsym.flat_outs if isinstance(t, TensorProxy)]
        if not got:
            continue
        try:
            expected = sym.meta(*bsym.args, **bsym.kwargs)
        except Exception as e:  # noqa: BLE001 — the meta rejecting its own recorded args IS the finding
            findings.append(
                (
                    "reject",
                    i,
                    f"{sym.qualname} meta rejects the recorded operands: {type(e).__name__}: {e}",
                    "a pass substituted operands the op cannot accept (shape/dtype drift upstream)",
                )
            )
            continue
        exp = [t for t in tree_flatten(expected)[0] if isinstance(t, TensorProxy)]
        if len(exp) != len(got):
            findings.append(
                (
                    "mismatch",
                    i,
                    f"{sym.qualname} records {len(got)} tensor output(s) but its meta produces {len(exp)}",
                    None,
                )
            )
            continue
        for e_t, g_t in zip(exp, got):
            drift = []
            if tuple(e_t.shape) != tuple(g_t.shape):
                drift.append(f"shape {tuple(g_t.shape)} != expected {tuple(e_t.shape)}")
            if e_t.dtype != g_t.dtype:
                drift.append(f"dtype {g_t.dtype} != expected {e_t.dtype}")
            if e_t.device != g_t.device:
                drift.append(f"device {g_t.device} != expected {e_t.device}")
            if drift:
                findings.append(
                    (
                        "mismatch",
                        i,
                        f"{sym.qualname} output {g_t.name!r}: " + "; ".join(drift),
                        "the pass rewrote operands without re-deriving the output proxy "
                        "(use the symbol call, not bind, when operand metadata changes)",
                    )
                )
    ctx._meta_findings_cache = findings
    return findings


@register_rule("meta.mismatch", "Recorded output metadata matches re-running the prim's meta function")
def meta_mismatch(ctx: VerifyContext) -> None:
    for kind, i, message, hint in _meta_findings(ctx):
        if kind == "mismatch":
            ctx.report("meta.mismatch", Severity.ERROR, message, bsym_index=i, hint=hint)


@register_rule("meta.reject", "The prim's meta function accepts its recorded operands")
def meta_reject(ctx: VerifyContext) -> None:
    for kind, i, message, hint in _meta_findings(ctx):
        if kind == "reject":
            ctx.report("meta.reject", Severity.ERROR, message, bsym_index=i, hint=hint)


# =============================================================================
# (3) Alias / in-place hazards
# =============================================================================

# For IN_PLACE-tagged prims: which positional arg is the mutated destination.
INPLACE_MUTATED_ARG: dict = {PrimIDs.COPY_: 1}


@register_rule("alias.inplace-hazard", "No in-place op's destination is consumed later in program order")
def inplace_hazard(ctx: VerifyContext) -> None:
    from thunder_tpu.core.proxies import Proxy

    for i, bsym in enumerate(ctx.bsyms):
        if not bsym.has_tag(OpTags.IN_PLACE):
            continue
        idx = INPLACE_MUTATED_ARG.get(bsym.sym.id, 0)
        if idx >= len(bsym.args) or not isinstance(bsym.args[idx], Proxy):
            continue
        dst = bsym.args[idx]
        later = ctx.consumed_after(dst.name, i)
        if later is not None:
            ctx.report(
                "alias.inplace-hazard",
                Severity.ERROR,
                f"{bsym.sym.qualname} mutates {dst.name!r} in place, but bsym {later} "
                f"({ctx.bsyms[later].sym.qualname}) still consumes the pre-mutation value",
                bsym_index=i,
                hint="functionalize: consume the op's output instead of the mutated operand, "
                "or reorder the consumer before the mutation",
            )


# =============================================================================
# (3b) Donation / entry-aliasing sanitizer (ISSUE 10)
#
# The compile pipeline stamps donation metadata on the claimed execution
# trace (api._compile_entry_impl → tags["donated_inputs"] naming the input
# proxies whose buffers XLA may reuse, tags["rerun_reads_inputs"] when the
# entry can re-run those same buffers unstaged: the on_nan
# "rerun-instrumented" guard and the SDC re-run both do). These rules turn
# the PR 6/9 by-convention invariants ("rerun paths never read donated
# buffers", "donate=False under sdc_guard") into statically checked ones.
# =============================================================================


@register_rule(
    "donation.use-after-donation",
    "No rerun-capable entry donates the input buffers its rerun would re-read",
)
def use_after_donation(ctx: VerifyContext) -> None:
    donated = ctx.trace.tags.get("donated_inputs") or ()
    if not donated or not ctx.trace.tags.get("rerun_reads_inputs"):
        return
    sample = ", ".join(list(donated)[:4]) + ("…" if len(donated) > 4 else "")
    ctx.report(
        "donation.use-after-donation",
        Severity.ERROR,
        f"entry re-runs its inputs unstaged (on_nan rerun / SDC re-run) but "
        f"donates {len(donated)} input buffer(s) ({sample}) — XLA deletes "
        "donated buffers after the staged run, so the re-run would read freed "
        "memory",
        hint="disable donation for rerun-capable entries "
        "(api._compile_entry_impl does; a pass re-enabling it must clear the "
        "rerun_reads_inputs tag)",
    )


def _alias_root_fn(ctx: VerifyContext):
    """name -> root-buffer name through the view chain — the SAME alias
    model the liveness planner uses (one shared helper), so a hazard hidden
    behind a view is still a hazard and the sanitizer can never disagree
    with the planner about what aliases what."""
    from thunder_tpu.analysis.liveness import alias_root_fn

    return alias_root_fn(ctx.bsyms)


@register_rule(
    "donation.donated-output",
    "No donated input buffer (or a view of one) is returned as a trace output",
)
def donated_output(ctx: VerifyContext) -> None:
    donated = set(ctx.trace.tags.get("donated_inputs") or ())
    if not donated:
        return
    root = _alias_root_fn(ctx)
    for out_name in sorted(ctx.output_names):
        r = root(out_name)
        if r in donated:
            via = "" if r == out_name else f" (via view {out_name!r})"
            ctx.report(
                "donation.donated-output",
                Severity.ERROR,
                f"input {r!r} is donated to XLA but its buffer is a trace "
                f"output{via} — the caller would receive a buffer the "
                "executable may already have reused",
                hint="drop the leaf from the donate set, or return a copy",
            )


@register_rule(
    "alias.entry-aliasing",
    "No in-place op mutates a trace input that is also (a view of) a trace output",
)
def entry_aliasing(ctx: VerifyContext) -> None:
    """The across-entry alias hazard: an input mutated in place AND returned
    (directly or through a view) means the caller's buffer and the entry's
    output alias — a later entry (or the caller) observes the mutation
    through a value it believes is functional."""
    from thunder_tpu.core.proxies import Proxy

    root = None
    for i, bsym in enumerate(ctx.bsyms):
        if not bsym.has_tag(OpTags.IN_PLACE):
            continue
        idx = INPLACE_MUTATED_ARG.get(bsym.sym.id, 0)
        if idx >= len(bsym.args) or not isinstance(bsym.args[idx], Proxy):
            continue
        dst = bsym.args[idx]
        if root is None:
            root = _alias_root_fn(ctx)
        # The mutated DESTINATION may itself be a view of an input — the
        # caller's buffer is what gets written either way.
        dst_root = root(dst.name)
        if dst_root not in ctx.input_names:
            continue
        escaping = next(
            (n for n in sorted(ctx.output_names) if root(n) == dst_root), None
        )
        if escaping is not None:
            via = "" if escaping == dst_root else f" (through view {escaping!r})"
            ctx.report(
                "alias.entry-aliasing",
                Severity.ERROR,
                f"{bsym.sym.qualname} mutates trace input {dst_root!r} in place "
                f"and that buffer is a trace output{via} — the mutation "
                "aliases across the entry boundary",
                bsym_index=i,
                hint="functionalize: return the op's output proxy instead of "
                "the mutated input",
            )


# =============================================================================
# (4) DCE safety & orphan detection
# =============================================================================


@register_rule("dce.dead-symbol", "No side-effect-free symbol's outputs are all unused")
def dead_symbol(ctx: VerifyContext) -> None:
    defs_by_bsym: dict[int, list[str]] = {}
    for n, (j, _) in ctx.defs.items():
        defs_by_bsym.setdefault(j, []).append(n)
    for i, bsym in enumerate(ctx.bsyms):
        if bsym.has_tag(OpTags.DONT_DCE) or bsym.has_tag(OpTags.SIDE_EFFECT):
            continue
        defined = defs_by_bsym.get(i)
        if not defined:
            continue
        live = any(
            ctx.is_live_output(n) or ctx.consumed_after(n, i) is not None for n in defined
        )
        if not live:
            ctx.report(
                "dce.dead-symbol",
                Severity.WARNING,
                f"{bsym.sym.qualname} produces {defined!r} but nothing consumes them and "
                "the op carries no side-effect tag",
                bsym_index=i,
                hint="expected before DCE; after DCE this is a liveness bug in the pass "
                "(or the op needs an OpTags.SIDE_EFFECT/DONT_DCE tag)",
            )


@register_rule("names.orphan", "Registered names refer to proxies that exist in the trace")
def orphan_names(ctx: VerifyContext) -> None:
    seen = set(ctx.input_names) | set(ctx.output_names) | set(ctx.defs) | set(ctx.uses)
    orphans = sorted(n for n in ctx.trace._names if n not in seen)
    if orphans:
        sample = ", ".join(orphans[:8]) + ("…" if len(orphans) > 8 else "")
        ctx.report(
            "names.orphan",
            Severity.INFO,
            f"{len(orphans)} registered name(s) have no referent in this trace ({sample})",
            hint="expected after DCE/from_trace name-pool sharing; a fresh trace with "
            "orphans indicates names registered but never materialized",
        )
