"""Event-log replay: validate and analyze an observability JSONL log.

The offline half of the event pipeline (``observability/events.py`` writes,
this module reads): ``scripts/lint_traces.py --events <path>`` replays a log
captured under ``THUNDER_TPU_EVENTS``/``jit(events=...)`` and flags

- schema violations (unparseable lines, unknown kinds, missing fields,
  wrong schema version) — the golden-schema contract tests and CI both key
  on this;
- **recompile storms**: one function compiling more than
  ``storm_threshold`` times (the PR 2 dispatch work exists precisely so
  steady-state traffic compiles once per shape bucket — more means guards
  are churning or bucketing is misconfigured);
- unbalanced compile brackets (a ``compile_start`` whose ``compile_end``
  never arrived: a crash or exception mid-compile).

Findings reuse :class:`~thunder_tpu.analysis.diagnostics.Diagnostic`
(severity-gated exactly like trace-verifier findings), so the lint driver
treats both uniformly.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from thunder_tpu.analysis.diagnostics import Diagnostic, Severity

# kind -> required fields. The writer guarantees these; the replayer checks
# them so downstream dashboards can rely on the shape of every record.
SCHEMA: dict[str, frozenset] = {
    "cache_miss": frozenset({"fn", "call"}),
    "compile_start": frozenset({"compile_id", "fn", "cache_option", "call"}),
    "compile_end": frozenset({"compile_id", "fn", "ms", "n_bsyms"}),
    "pass": frozenset({"compile_id", "name", "ms", "n_bsyms", "trace"}),
    "bucket_select": frozenset({"compile_id", "buckets", "marks"}),
    "sharp_edge": frozenset({"message", "policy"}),
    "nan_watch": frozenset({"value_kind", "symbol", "bsym_index", "line", "provenance"}),
    "profile_start": frozenset({"dir", "steps"}),
    "profile_stop": frozenset({"steps", "total_s", "avg_s", "profiler"}),
    # Distributed observatory (docs/observability.md "distributed telemetry").
    "compile_phase": frozenset({"compile_id", "phase", "s"}),
    "step_time": frozenset({"fn", "step", "s"}),
    "straggler_suspect": frozenset({"host", "mean_s", "ratio"}),
    # Resilience subsystem (thunder_tpu/resilience; docs/robustness.md).
    "fault_injected": frozenset({"seam", "target", "n"}),
    "executor_demoted": frozenset({"sym", "executor", "ttl_s", "reason"}),
    "compile_deopt": frozenset({"level", "action", "reason", "attempt"}),
    "nan_guard": frozenset({"action"}),
    "checkpoint_save": frozenset({"path", "step", "ok", "attempt"}),
    "checkpoint_restore": frozenset({"path", "step", "ok"}),
    "preemption": frozenset({"signal", "step"}),
    "cache_repair": frozenset({"action", "path", "reason"}),
    # Mesh-wide fault tolerance (ISSUE 9; docs/robustness.md "distributed
    # resilience").
    "collective_timeout": frozenset({"fn", "timeout_s", "lines", "suspected_host"}),
    "host_loss": frozenset({"step", "host"}),
    # Every elastic_resume names the restore tier it landed on (local RAM /
    # peer RAM / disk) — the ISSUE 14 acceptance invariant.
    "elastic_resume": frozenset({"step", "from_mesh", "to_mesh", "resharded",
                                 "tier"}),
    "sdc_suspect": frozenset({"step", "leaves"}),
    "sdc_rerun": frozenset({"step", "ok"}),
    # Tiered checkpointing (ISSUE 14; docs/robustness.md "tiered
    # checkpointing"): the step-boundary device→host snapshot (stall_ms is
    # the ONLY hot-path cost), the background writer's disk commit, and the
    # per-tier restore verdicts of the tier ladder.
    "snapshot": frozenset({"step", "stall_ms"}),
    "snapshot_flush": frozenset({"step", "ok"}),
    "restore": frozenset({"step", "tier", "ok"}),
    # Fleet autopilot (ISSUE 11; docs/robustness.md "fleet autopilot"): one
    # record per policy decision, carrying the triggering evidence; the
    # soak driver summarizes its run with one goodput record.
    "autopilot_decision": frozenset({"decision_id", "signal", "actuator"}),
    "goodput": frozenset({"goodput_tokens_per_sec", "useful_tokens", "wall_s"}),
    # Live ops plane (ISSUE 15; docs/observability.md "ops plane"): one
    # record per streaming-detector verdict (kind, severity, value vs
    # baseline, evidence window), and the trailer marker a flight-recorder
    # dump file ends with. The marker appears ONLY in flightrec-*.jsonl
    # dumps — its presence tells the correlation rules below that the log
    # is a fault-in-progress capture.
    "anomaly": frozenset({"anomaly", "severity", "value", "baseline"}),
    "flightrec_dump": frozenset({"reason", "records"}),
    # Slice-granular failure domains (ISSUE 18; docs/robustness.md "failure
    # domains"): one record per federation-ledger transition (the typed
    # slice membership state machine in resilience/federation.py), and the
    # restore-entry sweep of orphan checkpoint tmp dirs left by writers
    # that died mid-flush.
    "slice_state": frozenset({"slice", "from", "to", "reason"}),
    "ckpt_tmp_sweep": frozenset({"count"}),
    # Continuous roofline ledger (ISSUE 19; docs/observability.md
    # "roofline"): one record per duty-cycled probe (how many ledger ops
    # the join touched, what the probe cost), and the profiler bracket's
    # degradation marker — the plugin was missing, so the capture (and
    # every roofline probe behind it) is wall-clock only. Per-op drift
    # verdicts ride the existing `anomaly` kind (anomaly=cost_model_drift
    # | kernel_regression), not a new one.
    "roofline_probe": frozenset({"step", "ops", "probe_s"}),
    "profile_degraded": frozenset({"reason"}),
    # Fleet critical-path ledger (ISSUE 20; docs/observability.md "fleet
    # timeline"): one rendezvous record per collective completion (the
    # clock-alignment anchor; optional in_slice_s/cross_slice_s carry the
    # federation's per-tier wire legs), and one per-step critical-path
    # breakdown from the timeline recorder. bottleneck_shift verdicts ride
    # the existing `anomaly` kind, like the roofline's drift verdicts.
    "collective": frozenset({"fn", "cid", "s"}),
    "critpath_step": frozenset({"step", "total_s", "classes", "slowest_host"}),
}
_COMMON = frozenset({"v", "ts", "seq", "kind"})

# Chaos correlation contract (ISSUE 6 acceptance): every injected fault must
# be followed by its recovery/degradation event — seams mapped to the kinds
# that prove the runtime degraded instead of dying. Seams absent here
# (straggler) recover by simply completing.
FAULT_RECOVERY_KINDS: dict[str, frozenset] = {
    "kernel_raise": frozenset({"executor_demoted"}),
    "compile_fail": frozenset({"compile_deopt", "executor_demoted"}),
    "compile_timeout": frozenset({"compile_deopt"}),
    "oom": frozenset({"compile_deopt"}),
    "nan": frozenset({"nan_guard"}),
    "ckpt_io": frozenset({"checkpoint_save"}),
    "preempt": frozenset({"checkpoint_save"}),
    "cache_corrupt": frozenset({"cache_repair"}),
    # Mesh-wide seams (ISSUE 9): a hung collective is "recovered" by the
    # watchdog turning it into a typed, attributed timeout; a host loss by
    # the survivors' agreed checkpoint (the elastic resume happens in the
    # NEXT process, whose log carries elastic_resume); an SDC injection by
    # the guard's quarantine + re-run.
    "collective_hang": frozenset({"collective_timeout"}),
    "host_loss": frozenset({"checkpoint_save"}),
    # An elastic resume also recovers an SDC injection: the restore
    # discards the poisoned state wholesale, which is exactly what the
    # autopilot does when a fresher fault (host loss, hang) interrupts the
    # guard's re-run mid-flight (ISSUE 11 overlapping-fault scenarios).
    "sdc": frozenset({"sdc_rerun", "elastic_resume"}),
    # A corrupted comm-scheduler placement is recovered by the pass's own
    # interval validation rejecting the schedule and falling back to the
    # unscheduled trace (a sharp_edge record with
    # policy="comm_schedule_fallback" — only those count, see the replay's
    # sharp_edge handling below).
    "sched_bad": frozenset({"sharp_edge"}),
    # Tiered-checkpoint seams (ISSUE 14): a torn background flush is
    # recovered when the checkpoint pipeline demonstrably keeps working —
    # a later successful flush/save commit, or a restore that fell past the
    # incomplete step; a slow flush by its own eventual commit; a corrupted
    # RAM replica by the tier ladder's checksum gate landing a restore on a
    # clean tier (the seam fires at restore time, so the restore verdict
    # always follows).
    "snap_torn": frozenset({"snapshot_flush", "checkpoint_save", "restore"}),
    "snap_slow": frozenset({"snapshot_flush", "checkpoint_save"}),
    "snap_corrupt": frozenset({"restore"}),
    # Slice-granular seams (ISSUE 18): a whole-slice loss is recovered by
    # the survivors' elastic resume at the shrunk DP width (the cross-slice
    # buddy tier supplies the state, so its restore verdict precedes the
    # resume); a flapping slice by the federation ledger demonstrably
    # holding it in cooldown (a slice_state transition) instead of
    # thrashing the fleet. dcn_partition and slice_slow recover by simply
    # completing — replication resumes / the spread detector flags the
    # outlier — so, like straggler, they carry no entry here.
    "slice_loss": frozenset({"elastic_resume"}),
    "slice_flap": frozenset({"slice_state"}),
}

# Autopilot correlation contract (ISSUE 11): every autopilot_decision must
# be followed by its actuator's recovery event — a decision with no
# subsequent actuation means the control plane chose a recovery that never
# ran (or the actuator lost its event). checkpoint_halt and
# quarantine_rerun count only SUCCESSFUL saves/re-runs (ok=true), like the
# fault-correlation rule; an interrupted quarantine re-run may instead be
# superseded by an elastic restore, which discards the poisoned state.
DECISION_RECOVERY_KINDS: dict[str, frozenset] = {
    "elastic_resume": frozenset({"elastic_resume"}),
    "quarantine_rerun": frozenset({"sdc_rerun", "elastic_resume"}),
    "deopt_escalate": frozenset({"compile_deopt"}),
    "checkpoint_halt": frozenset({"checkpoint_save"}),
    # Fleet actuators (ISSUE 18): a shrink/regrow decision actuates as the
    # elastic resume that re-enters training at the new DP width.
    "shrink_dp": frozenset({"elastic_resume"}),
    "regrow_dp": frozenset({"elastic_resume"}),
}


def _parse_log_lines(path: str, diags: list[Diagnostic]) -> list[tuple[int, dict]]:
    """(lineno, record) pairs from one JSONL log; malformed lines become
    diagnostics (tagged with the path when several logs are merged)."""
    out: list[tuple[int, dict]] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                diags.append(Diagnostic(
                    rule="events.malformed-line", severity=Severity.ERROR,
                    message=f"{path}:{lineno}: not valid JSON ({e})",
                ))
                continue
            out.append((lineno, rec))
    return out


def merge_event_logs(
    paths: list[str],
    offsets: Optional[dict] = None,
) -> tuple[list[dict], list[Diagnostic]]:
    """Merge several per-host JSONL logs (multi-host jobs write one log per
    process; every record carries ``pid``/``host`` — observability/events.py)
    into one deterministically-ordered stream.

    Ordering is stable across re-runs of the merge: (ts, host, pid, seq) —
    wall-clock first so interleaved compiles read chronologically, then
    writer identity, then the writer's own monotonic ``seq`` to break
    same-timestamp ties. Returns (records, parse diagnostics).

    **Caveat — unaligned clocks.** Each host stamps ``ts`` from its own
    wall clock; without alignment, cross-host ordering under skew silently
    misorders causally-related records (host B's collective *completion*
    can sort before host A's *entry* into the same barrier). Pass
    ``offsets`` — ``{host: seconds the host's clock runs ahead of the
    fleet}``, e.g. from
    ``observability.timeline.estimate_skew``/``offsets_for_merge`` — to
    sort on skew-corrected time (``ts − offset``). Record contents are not
    rewritten, only the ordering; use ``timeline.apply_offsets`` to rewrite
    ``ts`` itself."""
    def num(v, cast) -> float:
        # A record with a non-numeric ts/host/pid/seq is still one record:
        # the schema validator downstream flags it; the merge must not die.
        try:
            return cast(v or 0)
        except (TypeError, ValueError):
            return cast(0)

    diags: list[Diagnostic] = []
    records: list[tuple[tuple, int, dict]] = []
    offsets = offsets or {}
    for path in paths:
        for lineno, rec in _parse_log_lines(path, diags):
            if isinstance(rec, dict):
                off = offsets.get(rec.get("host")) or 0.0
                key = (
                    num(rec.get("ts"), float) - num(off, float),
                    num(rec.get("host"), int),
                    num(rec.get("pid"), int),
                    num(rec.get("seq"), int),
                )
            else:
                key = (0.0, 0, 0, 0)
            records.append((key, lineno, rec))
    records.sort(key=lambda t: (t[0], t[1]))
    return [rec for _, _, rec in records], diags


def host_health(
    source,
    *,
    spread_threshold: float = 1.5,
) -> tuple[dict, list[Diagnostic]]:
    """Cross-host health summary over merged per-host event logs: per-host
    step-time statistics from ``step_time`` events, the fleet spread ratio
    (slowest host mean / fleet median), and straggler suspects.

    ``source``: a list of per-host log paths (merged via
    :func:`merge_event_logs`), or an already-merged record list. A host
    whose mean step time exceeds ``spread_threshold`` × the fleet median is
    flagged with an ``events.straggler-suspect`` diagnostic; the spread is
    surfaced as the ``thunder_tpu_host_step_time_spread_ratio`` gauge (per-
    host means as ``thunder_tpu_host_step_time_s{host=...}``) and each
    suspect emits a ``straggler_suspect`` event to the active log — so the
    coordinator that runs the merge republishes fleet health through the
    same metrics/events pipe everything else uses."""
    diags: list[Diagnostic] = []
    if isinstance(source, (list, tuple)) and source and isinstance(source[0], str):
        records, diags0 = merge_event_logs(list(source))
        diags.extend(diags0)
    else:
        records = list(source)

    # The incremental accumulator (observability/detect.py, ISSUE 15
    # satellite): one class owns the per-host stats + spread math for BOTH
    # this offline merged-log summary and the online streaming spread
    # detector. Running sums in record order reproduce the old from-scratch
    # recompute bit for bit (sum() was left-to-right too).
    from thunder_tpu.observability.detect import HostHealthAccumulator

    acc = HostHealthAccumulator()
    for rec in records:
        if not isinstance(rec, dict) or rec.get("kind") != "step_time":
            continue
        try:
            s = float(rec["s"])
        except (KeyError, TypeError, ValueError):
            continue
        acc.add(rec.get("host") or 0, s)

    hosts = acc.host_stats()
    summary: dict[str, Any] = {"hosts": hosts, "spread_ratio": None, "stragglers": []}
    if hosts:
        # True median (even fleets average the middle pair): taking the
        # upper-middle element would make the slow host of a 2-host fleet
        # its own baseline and hide the skew entirely (the accumulator
        # implements exactly that).
        median, spread = acc.spread()
        summary["spread_ratio"] = round(spread, 4)
        from thunder_tpu.observability import metrics as obsm
        from thunder_tpu.observability.events import emit_event

        if obsm.enabled():
            obsm.HOST_STEP_SPREAD.set(spread)
            for h, st in hosts.items():
                obsm.HOST_STEP_TIME_S.set(st["mean_s"], host=str(h))
        for h, st in sorted(hosts.items()):
            if median and st["mean_s"] > spread_threshold * median:
                ratio = st["mean_s"] / median
                summary["stragglers"].append(h)
                emit_event("straggler_suspect", host=h,
                           mean_s=round(st["mean_s"], 6), ratio=round(ratio, 4))
                diags.append(Diagnostic(
                    rule="events.straggler-suspect", severity=Severity.WARNING,
                    message=(
                        f"host {h} mean step time {st['mean_s'] * 1e3:.2f} ms is "
                        f"{ratio:.2f}x the fleet median ({median * 1e3:.2f} ms) "
                        f"over {st['steps']} steps — straggler suspect"
                    ),
                    hint="per-host step logs merge via merge_event_logs; the "
                         "spread gauge is thunder_tpu_host_step_time_spread_ratio",
                ))
    # Detection → action (ISSUE 9): the collective watchdog names this
    # summary's straggler as the suspected host when a collective later
    # times out. The installed autopilot (ISSUE 11) consumes the same
    # summary — a host flagged in consecutive summaries loses its gentle
    # same-mesh-retry rung on the next hang.
    from thunder_tpu.resilience import autopilot as _autopilot
    from thunder_tpu.resilience import watchdog as _watchdog

    _watchdog.note_host_health(summary)
    ap = _autopilot.current()
    if ap is not None:
        ap.note_host_health(summary)
    return summary, diags


def replay_events(
    path,
    *,
    storm_threshold: int = 4,
    strict_kinds: bool = False,
) -> tuple[dict, list[Diagnostic]]:
    """Parse + validate ``path`` (one log path, or a list of per-host log
    paths merged via :func:`merge_event_logs`); return
    ``(summary, diagnostics)``.

    ``summary``: event/kind counts, per-function compile counts, per-pass
    total milliseconds, bucket selections, sharp-edge messages.
    ``storm_threshold``: compiles per function above which a recompile-storm
    ERROR fires. ``strict_kinds`` upgrades unknown kinds to errors (default:
    warning, so log readers stay forward-compatible)."""
    diags: list[Diagnostic] = []
    kinds: dict[str, int] = {}
    compiles_by_fn: dict[str, int] = {}
    exact_compiles_by_fn: dict[str, int] = {}
    recompiles_by_fn: dict[str, int] = {}
    pass_ms: dict[str, float] = {}
    phase_s: dict[str, float] = {}
    seq_bucket_compiles_by_fn: dict[str, int] = {}
    open_compiles: dict[Any, str] = {}
    cache_option_by_cid: dict[Any, str] = {}
    bucket_by_cid: dict[Any, str] = {}
    bucket_compile_counts: dict[tuple, int] = {}  # (fn, bucket desc) -> compiles
    buckets: list[str] = []
    sharp_edges: list[str] = []
    fault_events: list[tuple[int, str, dict]] = []  # (lineno, seam, record)
    decision_events: list[tuple[int, str, dict]] = []  # (lineno, actuator, record)
    recovery_positions: dict[str, list[int]] = {}  # recovery kind -> linenos
    anomaly_counts: dict[str, int] = {}  # detector kind -> events (ISSUE 15)
    # flightrec_dump trailer positions: present ONLY in flight-recorder
    # dump files. A dump marker after a fault/decision satisfies the
    # correlation rules below — the dump is a capture of a fault whose
    # recovery is still in flight, not evidence the run lost it.
    dump_positions: list[int] = []
    restore_tiers: dict[str, int] = {}  # tier -> ok restores
    restore_fallthroughs = 0  # ok restores that skipped >=1 invalid candidate
    snapshot_stall_ms = 0.0
    n_snapshots = 0
    n_lines = 0

    merged = isinstance(path, (list, tuple)) and len(path) != 1
    if isinstance(path, (list, tuple)):
        src = ", ".join(path)
        records, parse_diags = merge_event_logs(list(path))
        diags.extend(parse_diags)
        labeled = list(enumerate(records, 1))
    else:
        src = path
        labeled = _parse_log_lines(path, diags)

    def _writer(rec: dict) -> tuple:
        # compile_id is a per-process counter: correlation must key on the
        # writer identity too once several hosts' logs are merged.
        return (rec.get("host") or 0, rec.get("pid") or 0)

    def _fn_key(rec: dict, fn: str) -> str:
        return f"h{rec.get('host') or 0}:{fn}" if merged else fn

    for lineno, rec in labeled:
            n_lines += 1
            if not isinstance(rec, dict) or "kind" not in rec:
                diags.append(Diagnostic(
                    rule="events.malformed-record", severity=Severity.ERROR,
                    message=f"line {lineno}: not an event object (no 'kind')",
                ))
                continue
            if rec.get("v") != 1:
                diags.append(Diagnostic(
                    rule="events.schema-version", severity=Severity.ERROR,
                    message=f"line {lineno}: unsupported schema version {rec.get('v')!r}",
                ))
                continue
            kind = rec["kind"]
            kinds[kind] = kinds.get(kind, 0) + 1
            required = SCHEMA.get(kind)
            if required is None:
                diags.append(Diagnostic(
                    rule="events.unknown-kind",
                    severity=Severity.ERROR if strict_kinds else Severity.WARNING,
                    message=f"line {lineno}: unknown event kind {kind!r}",
                ))
                continue
            missing = required - set(rec)
            if missing:
                diags.append(Diagnostic(
                    rule="events.missing-fields", severity=Severity.ERROR,
                    message=f"line {lineno}: {kind} event missing fields {sorted(missing)}",
                ))
                continue

            if kind == "compile_start":
                fn = _fn_key(rec, str(rec["fn"]))
                cid = (*_writer(rec), rec["compile_id"])
                compiles_by_fn[fn] = compiles_by_fn.get(fn, 0) + 1
                open_compiles[cid] = fn
                cache_option_by_cid[cid] = str(rec["cache_option"])
            elif kind == "compile_end":
                fn = _fn_key(rec, str(rec["fn"]))
                cid = (*_writer(rec), rec["compile_id"])
                open_compiles.pop(cid, None)
                if rec.get("recompile"):
                    recompiles_by_fn[fn] = recompiles_by_fn.get(fn, 0) + 1
                # Storm accounting distinguishes compile CLASSES: one compile
                # per shape bucket is the documented healthy steady state for
                # cache="symbolic values" (symbolic compiles count per
                # (fn, bucket) — repeats of the SAME bucket are the storm)
                # and for the module frontend's seq_bucket (bucket identity
                # is not in the log, so those get a higher threshold);
                # exact-shape compiles count per fn.
                if rec.get("symbolic"):
                    bkey = (fn, bucket_by_cid.get(cid, "?"))
                    bucket_compile_counts[bkey] = bucket_compile_counts.get(bkey, 0) + 1
                elif cache_option_by_cid.get(cid, "").endswith("+seq_bucket"):
                    seq_bucket_compiles_by_fn[fn] = seq_bucket_compiles_by_fn.get(fn, 0) + 1
                else:
                    exact_compiles_by_fn[fn] = exact_compiles_by_fn.get(fn, 0) + 1
            elif kind == "pass":
                if rec["ms"] is not None:
                    pass_ms[rec["name"]] = pass_ms.get(rec["name"], 0.0) + float(rec["ms"])
            elif kind == "compile_phase":
                if rec["s"] is not None:
                    key = str(rec["phase"])
                    if rec.get("cache"):
                        key = f"{key}[{rec['cache']}]"
                    phase_s[key] = phase_s.get(key, 0.0) + float(rec["s"])
            elif kind == "bucket_select":
                buckets.append(str(rec["buckets"]))
                bucket_by_cid[(*_writer(rec), rec["compile_id"])] = str(rec["buckets"])
            elif kind == "sharp_edge":
                sharp_edges.append(str(rec["message"]))
                # The comm scheduler's fallback record is the recovery event
                # of an injected sched_bad placement (FAULT_RECOVERY_KINDS);
                # ordinary sharp edges must not satisfy that correlation.
                if rec.get("policy") == "comm_schedule_fallback":
                    recovery_positions.setdefault("sharp_edge", []).append(lineno)
            elif kind == "fault_injected":
                fault_events.append((lineno, str(rec["seam"]), rec))
            elif kind == "autopilot_decision":
                decision_events.append((lineno, str(rec["actuator"]), rec))
            elif kind in ("executor_demoted", "compile_deopt", "nan_guard",
                          "cache_repair", "collective_timeout",
                          "elastic_resume", "slice_state"):
                recovery_positions.setdefault(kind, []).append(lineno)
            elif kind in ("checkpoint_save", "sdc_rerun", "snapshot_flush",
                          "restore"):
                # Only a SUCCESSFUL save/re-run/flush/restore proves
                # recovery: a failed attempt must not satisfy the
                # correlation rule.
                if rec.get("ok"):
                    recovery_positions.setdefault(kind, []).append(lineno)
                if kind == "restore" and rec.get("ok"):
                    tier = str(rec.get("tier"))
                    restore_tiers[tier] = restore_tiers.get(tier, 0) + 1
                    if rec.get("tried"):
                        restore_fallthroughs += 1
            elif kind == "snapshot":
                n_snapshots += 1
                try:
                    snapshot_stall_ms += float(rec.get("stall_ms") or 0.0)
                except (TypeError, ValueError):
                    pass
            elif kind == "anomaly":
                a = str(rec.get("anomaly"))
                anomaly_counts[a] = anomaly_counts.get(a, 0) + 1
            elif kind == "flightrec_dump":
                dump_positions.append(lineno)

    for fn, n in sorted(exact_compiles_by_fn.items()):
        if n > storm_threshold:
            diags.append(Diagnostic(
                rule="events.recompile-storm", severity=Severity.ERROR,
                message=(
                    f"{fn!r} compiled {n} times for exact shapes (threshold "
                    f"{storm_threshold}) — guards are churning; consider "
                    f"cache='symbolic values'"
                ),
                hint="thunder_tpu.cache_info(fn) shows per-entry guard fails",
            ))
    for fn, n in sorted(seq_bucket_compiles_by_fn.items()):
        # Bucket identity isn't in the module-frontend log, so distinct
        # buckets and same-bucket churn are indistinguishable here: flag only
        # well past any plausible bucket count, and as a WARNING.
        if n > storm_threshold * 4:
            diags.append(Diagnostic(
                rule="events.recompile-storm", severity=Severity.WARNING,
                message=(
                    f"{fn!r} (module, seq_bucket) compiled {n} times — more "
                    f"than {storm_threshold * 4} sequence buckets is unusual; "
                    f"check for value-guard churn"
                ),
                hint="the module warns in-process on repeated value-guard "
                     "misses; thunder_tpu.cache_info(tm) shows entry counts",
            ))
    for (fn, desc), n in sorted(bucket_compile_counts.items()):
        if n > 2:
            diags.append(Diagnostic(
                rule="events.recompile-storm", severity=Severity.ERROR,
                message=(
                    f"{fn!r} compiled shape bucket {desc} {n} times — one "
                    f"compile per bucket is steady state; repeats mean value "
                    f"guards or marks are churning"
                ),
                hint="check symbolic_dims/buckets configuration; "
                     "thunder_tpu.cache_info(fn) shows per-entry guard fails",
            ))
    for cid, fn in open_compiles.items():
        diags.append(Diagnostic(
            rule="events.unclosed-compile", severity=Severity.WARNING,
            message=f"compile {cid[-1]} of {fn!r} has no compile_end (crashed mid-compile?)",
        ))
    # Chaos correlation: every injected fault with a declared recovery
    # contract (FAULT_RECOVERY_KINDS) must be followed by its degradation/
    # recovery event — a fault_injected with no later recovery record means
    # the runtime died or the recovery path silently skipped its event.
    unrecovered: list[str] = []
    for lineno, seam, rec in fault_events:
        expected = FAULT_RECOVERY_KINDS.get(seam)
        if not expected:
            continue
        if any(pos > lineno for pos in dump_positions):
            # A flight-recorder dump landed after this injection: the log
            # is a black-box capture taken AT fault time (only dump files
            # carry the marker) — the recovery runs in the process that
            # continues, outside this snapshot.
            continue
        if not any(
            pos > lineno for k in expected for pos in recovery_positions.get(k, [])
        ):
            unrecovered.append(f"{seam}@{rec.get('target')}")
            diags.append(Diagnostic(
                rule="events.unrecovered-fault", severity=Severity.ERROR,
                message=(
                    f"line {lineno}: fault_injected seam={seam!r} "
                    f"target={rec.get('target')!r} has no subsequent "
                    f"{'/'.join(sorted(expected))} event — the fault was not "
                    f"recovered (or the recovery path lost its event)"
                ),
                hint="docs/robustness.md lists the expected recovery event "
                     "per seam",
            ))
    # Autopilot correlation (ISSUE 11): every decision must be followed by
    # its actuator's recovery event (DECISION_RECOVERY_KINDS) — the same
    # shape as the fault rule, one layer up: the control plane's choices
    # are falsifiable, not just the injections.
    unactuated: list[str] = []
    decisions_by_actuator: dict[str, int] = {}
    for lineno, actuator, rec in decision_events:
        decisions_by_actuator[actuator] = decisions_by_actuator.get(actuator, 0) + 1
        expected = DECISION_RECOVERY_KINDS.get(actuator)
        if not expected:
            continue
        if any(pos > lineno for pos in dump_positions):
            continue  # fault-in-progress capture (see the fault rule above)
        if not any(
            pos > lineno for k in expected for pos in recovery_positions.get(k, [])
        ):
            unactuated.append(f"{actuator}<-{rec.get('signal')}")
            diags.append(Diagnostic(
                rule="events.unactuated-decision", severity=Severity.ERROR,
                message=(
                    f"line {lineno}: autopilot_decision "
                    f"id={rec.get('decision_id')} actuator={actuator!r} "
                    f"(signal {rec.get('signal')!r}) has no subsequent "
                    f"{'/'.join(sorted(expected))} event — the chosen "
                    f"recovery never ran (or lost its event)"
                ),
                hint="docs/robustness.md 'fleet autopilot' lists the "
                     "recovery event per actuator",
            ))

    summary = {
        "path": src,
        "lines": n_lines,
        "kinds": kinds,
        "compiles_by_fn": compiles_by_fn,
        "exact_compiles_by_fn": exact_compiles_by_fn,
        "seq_bucket_compiles_by_fn": seq_bucket_compiles_by_fn,
        "bucket_compiles": {f"{fn}: {d}": n for (fn, d), n in sorted(bucket_compile_counts.items())},
        "recompiles_by_fn": recompiles_by_fn,
        "pass_ms_total": {k: round(v, 3) for k, v in sorted(pass_ms.items())},
        "compile_phase_s_total": {k: round(v, 4) for k, v in sorted(phase_s.items())},
        "bucket_selects": buckets,
        "sharp_edges": sharp_edges,
        "faults_injected": [f"{seam}@{rec.get('target')}" for _, seam, rec in fault_events],
        "unrecovered_faults": unrecovered,
        "autopilot_decisions": decisions_by_actuator,
        "unactuated_decisions": unactuated,
        # Tiered checkpointing (ISSUE 14): where restores landed, how many
        # fell through an invalid tier first, and the total/count of the
        # step-boundary snapshot stalls (the lint --soak smoke bounds
        # stall-per-step and requires RAM- and disk-tier restores from
        # exactly these numbers).
        "restore_tiers": restore_tiers,
        "restore_fallthroughs": restore_fallthroughs,
        "snapshots": n_snapshots,
        "snapshot_stall_ms_total": round(snapshot_stall_ms, 3),
        # Live ops plane (ISSUE 15): streaming-detector verdicts by kind,
        # and flight-recorder dump markers (non-zero only when replaying a
        # flightrec-*.jsonl capture).
        "anomalies": anomaly_counts,
        "flightrec_dumps": len(dump_positions),
    }
    return summary, diags


def format_replay(summary: dict, diags: list[Diagnostic]) -> str:
    """Human-readable replay report for the lint driver."""
    lines = [
        f"events: {summary['lines']} records from {summary['path']}",
        "  kinds: " + ", ".join(f"{k}={v}" for k, v in sorted(summary["kinds"].items())),
    ]
    if summary["compiles_by_fn"]:
        lines.append("  compiles: " + ", ".join(
            f"{fn}×{n}" for fn, n in sorted(summary["compiles_by_fn"].items())
        ))
    if summary["pass_ms_total"]:
        lines.append("  pass time (ms): " + ", ".join(
            f"{k}={v}" for k, v in summary["pass_ms_total"].items()
        ))
    if summary.get("compile_phase_s_total"):
        lines.append("  compile phases (s): " + ", ".join(
            f"{k}={v}" for k, v in summary["compile_phase_s_total"].items()
        ))
    if summary["bucket_selects"]:
        lines.append(f"  bucket selects: {len(summary['bucket_selects'])}")
    if summary["sharp_edges"]:
        lines.append(f"  sharp edges: {len(summary['sharp_edges'])}")
    if summary.get("faults_injected"):
        lines.append(
            f"  faults injected: {len(summary['faults_injected'])} "
            f"({', '.join(summary['faults_injected'])}); "
            f"unrecovered: {len(summary.get('unrecovered_faults') or [])}"
        )
    if summary.get("autopilot_decisions"):
        lines.append(
            "  autopilot decisions: " + ", ".join(
                f"{a}×{n}" for a, n in sorted(summary["autopilot_decisions"].items())
            ) + f"; unactuated: {len(summary.get('unactuated_decisions') or [])}"
        )
    if summary.get("restore_tiers"):
        lines.append(
            "  restores by tier: " + ", ".join(
                f"{t}×{n}" for t, n in sorted(summary["restore_tiers"].items())
            ) + f"; fall-throughs: {summary.get('restore_fallthroughs', 0)}"
        )
    if summary.get("snapshots"):
        lines.append(
            f"  snapshots: {summary['snapshots']} "
            f"(stall total {summary.get('snapshot_stall_ms_total', 0.0)} ms)"
        )
    if summary.get("anomalies"):
        lines.append(
            "  anomalies: " + ", ".join(
                f"{k}×{n}" for k, n in sorted(summary["anomalies"].items())
            )
        )
    for d in diags:
        lines.append("  " + d.format().replace("\n", "\n  "))
    return "\n".join(lines)
