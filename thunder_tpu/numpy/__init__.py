"""NumPy demo language layer.

Reference parity: thunder/numpy/__init__.py + thunder/numpy/langctx.py —
deliberately small, existing to prove the language-context machinery is
actually multi-language: a function written against numpy-style signatures
(ufunc ``where=`` kwarg, ``axis=`` reductions) traces through the SAME prim
vocabulary and executor pipeline as the torch mirror, and numpy-style
methods resolve on TensorProxy while the numpy context is active.
"""

from __future__ import annotations

from numbers import Number
from typing import Any, Callable, Optional

import thunder_tpu.clang as clang
from thunder_tpu.core.langctxs import (
    LanguageContext,
    Languages,
    langctx,
    register_langctx,
)
from thunder_tpu.core.symbol import Symbol

_numpy_ctx = LanguageContext(Languages.NUMPY)
register_langctx(Languages.NUMPY, _numpy_ctx)


def npsymbol(*, method_name: Optional[str] = None):
    """Decorator mirroring the reference's ``npsymbol`` (thunder/numpy/
    __init__.py:22): the body runs under the numpy language context and the
    op becomes a trace Symbol; ``method_name`` also exposes it as a proxy
    method while the numpy context is active."""

    def deco(fn: Callable) -> Symbol:
        wrapped = langctx(Languages.NUMPY)(fn)
        sym = Symbol(name=fn.__name__, meta=wrapped)
        if method_name is not None:
            _numpy_ctx.register_method(method_name, wrapped)
        return sym

    return deco


def _masked(result, a, where):
    """numpy ufunc ``where=`` semantics: unselected lanes keep ``a``."""
    if where is None:
        return result
    return clang.where(where, result, a)


@npsymbol(method_name="add")
def add(a, b, *, where=None):
    return _masked(clang.add(a, b), a, where)


@npsymbol(method_name="subtract")
def subtract(a, b, *, where=None):
    return _masked(clang.sub(a, b), a, where)


@npsymbol(method_name="multiply")
def multiply(a, b, *, where=None):
    return _masked(clang.mul(a, b), a, where)


@npsymbol(method_name="divide")
def divide(a, b, *, where=None):
    return _masked(clang.true_divide(a, b), a, where)


@npsymbol(method_name="exp")
def exp(a, *, where=None):
    return _masked(clang.exp(a), a, where)


@npsymbol(method_name="sum")
def sum(a, axis=None, keepdims: bool = False):  # noqa: A001 — numpy surface
    dims = (axis,) if isinstance(axis, int) else axis
    return clang.sum(a, dims, keepdims)


@npsymbol(method_name="mean")
def mean(a, axis=None, keepdims: bool = False):
    dims = (axis,) if isinstance(axis, int) else axis
    return clang.mean(a, dims, keepdims)


@npsymbol(method_name="matmul")
def matmul(a, b):
    return clang.matmul(a, b)


@npsymbol(method_name="transpose")
def transpose(a, axes=None):
    perm = tuple(axes) if axes is not None else tuple(reversed(range(a.ndim)))
    return clang.permute(a, perm)


@npsymbol(method_name="reshape")
def reshape(a, newshape):
    return clang.reshape(a, tuple(newshape))


def compute_len(a) -> int:
    return int(a.shape[0])


_numpy_ctx.register_method("len", compute_len)


def size(a) -> int:
    return int(a.numel)


_numpy_ctx.register_method("size", size)
