"""RNG functionalization: make randomness an explicit trace input.

TPU-first replacement for the reference's stateful Philox RNG
(thunder/core/prims.py `uniform_philox`, offset threading): any trace
containing RANDOM_OP prims is rewritten so a threefry key tensor becomes a
real trace input and each random op derives a unique subkey by folding in
its site index. The program stays pure — XLA caches one executable and the
host advances the seed between steps.
"""

from __future__ import annotations

import time

from thunder_tpu.core import dtypes, devices, prims
from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import TensorProxy, variableify
from thunder_tpu.core.pytree import tree_flatten, tree_unflatten
from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx, wrap_in_trace_provenance

RNG_TAG = "rng_functionalized"


def functionalize_rng_ops(trace: TraceCtx) -> TraceCtx:
    has_rng = any(OpTags.RANDOM_OP in b.sym.tags for b in trace.bound_symbols)
    if not has_rng:
        return trace

    start = time.perf_counter_ns()
    ntrace = from_trace(trace)
    key = TensorProxy(name=ntrace.make_name("rng_key"), shape=(2,), dtype=dtypes.uint32, device=devices.Device())
    swap_map = {}
    salt = 0

    with tracectx(ntrace):
        for bsym in trace.bound_symbols:
            bsym = bsym.from_bsym_swap_proxies(swap_map, skip_output=True)
            if OpTags.RANDOM_OP not in bsym.sym.tags:
                ntrace.bound_symbols.append(bsym)
                continue
            if bsym.sym.id == PrimIDs.UNIFORM:
                shape, minval, maxval = bsym.args
                new_out = prims.uniform_keyed(shape, minval, maxval, key, salt, **bsym.kwargs)
            elif bsym.sym.id == PrimIDs.RANDN:
                (shape,) = bsym.args
                new_out = prims.randn_keyed(shape, key, salt, **bsym.kwargs)
            else:
                raise NotImplementedError(f"RNG prim {bsym.sym.qualname} not functionalized")
            salt += 1
            swap_map[variableify(bsym.output)] = new_out

    ntrace.args = tuple(trace.args) + (key,)
    flat_out, spec = tree_flatten(ntrace.output)
    ntrace.output = tree_unflatten(spec, [swap_map.get(variableify(p), p) if hasattr(p, "name") else p for p in flat_out])
    ntrace.tags[RNG_TAG] = True
    return wrap_in_trace_provenance(ntrace, "Functionalize RNG", start)
