"""Min-cut solver for rematerialization: native C++ Dinic with Python fallback.

Reference parity: thunder/core/rematerialization.py:245 (igraph max-flow).
The native module (csrc/mincut.cpp) compiles lazily on first use with g++
into the user cache dir; the pure-Python Dinic below is the fallback when no
toolchain is available. Both implement the same interface:

    min_cut(n_nodes, edges=[(u, v, cap)], s, t) -> (flow, source_side_set)

Capacities ≥ INF_CAP are treated as uncuttable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from collections import deque
from typing import Optional, Sequence

INF_CAP = 1 << 60

_lib = None
_lib_tried = False


def _load_native():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    src = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc", "mincut.cpp")
    cache_dir = os.path.join(tempfile.gettempdir(), "thunder_tpu_native")
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, "libttmincut.so")
    try:
        if not os.path.exists(so_path) or os.path.getmtime(so_path) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", src, "-o", so_path],
                check=True, capture_output=True, timeout=120,
            )
        lib = ctypes.CDLL(so_path)
        lib.tt_mincut.restype = ctypes.c_int64
        lib.tt_mincut.argtypes = [
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8),
        ]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def _min_cut_py(n: int, edges: Sequence[tuple], s: int, t: int):
    """Pure-Python Dinic (fallback)."""
    graph: list[list[list]] = [[] for _ in range(n)]  # [to, cap, rev_idx]

    def add(u, v, cap):
        graph[u].append([v, cap, len(graph[v])])
        graph[v].append([u, 0, len(graph[u]) - 1])

    for u, v, c in edges:
        add(u, v, c)

    flow = 0
    while True:
        level = [-1] * n
        level[s] = 0
        q = deque([s])
        while q:
            u = q.popleft()
            for e in graph[u]:
                if e[1] > 0 and level[e[0]] < 0:
                    level[e[0]] = level[u] + 1
                    q.append(e[0])
        if level[t] < 0:
            break
        it = [0] * n

        def dfs(u, f):
            if u == t:
                return f
            while it[u] < len(graph[u]):
                e = graph[u][it[u]]
                v = e[0]
                if e[1] > 0 and level[u] < level[v]:
                    d = dfs(v, min(f, e[1]))
                    if d > 0:
                        e[1] -= d
                        graph[v][e[2]][1] += d
                        return d
                it[u] += 1
            return 0

        while True:
            f = dfs(s, INF_CAP)
            if f == 0:
                break
            flow += f

    side = set()
    q = deque([s])
    side.add(s)
    while q:
        u = q.popleft()
        for e in graph[u]:
            if e[1] > 0 and e[0] not in side:
                side.add(e[0])
                q.append(e[0])
    return flow, side


def min_cut(n: int, edges: Sequence[tuple], s: int, t: int):
    """(max_flow, source_side_node_set). Uses the C++ solver when available."""
    lib = _load_native()
    if lib is None:
        return _min_cut_py(n, edges, s, t)
    m = len(edges)
    eu = (ctypes.c_int32 * m)(*[e[0] for e in edges])
    ev = (ctypes.c_int32 * m)(*[e[1] for e in edges])
    ec = (ctypes.c_int64 * m)(*[min(int(e[2]), INF_CAP) for e in edges])
    side = (ctypes.c_uint8 * n)()
    flow = lib.tt_mincut(n, m, eu, ev, ec, s, t, side)
    return int(flow), {i for i in range(n) if side[i]}


def using_native() -> bool:
    return _load_native() is not None
