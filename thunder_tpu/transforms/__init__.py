"""Trace-to-trace transforms (reference: thunder/core/transforms.py,
transform_common.py, rematerialization.py)."""
