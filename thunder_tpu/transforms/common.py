"""Common trace passes: DCE, CSE, and the trace evaluator.

Reference parity: thunder/core/transform_common.py (`dce:41`, `cse:194`,
`cse_single_bsym:153`) and the evaluation machinery in
thunder/core/transforms.py (`eval_trace:1641`, `bsym_list_to_dag:117`,
`toposort_bsym_dag:214`, `visitor_transform:353`).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Sequence

from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import Proxy, Variable, variableify
from thunder_tpu.core.pytree import tree_flatten, tree_unflatten
from thunder_tpu.core.symbol import BoundSymbol
from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx, wrap_in_trace_provenance


def has_tag(bsym: BoundSymbol, tag: OpTags) -> bool:
    return bsym.has_tag(tag)


def dce(trace: TraceCtx, keep: Sequence[Proxy] = ()) -> TraceCtx:
    """Dead-code elimination via a backward liveness sweep
    (reference: transform_common.py `dce:41`)."""
    start = time.perf_counter_ns()
    needed: set[Variable] = {variableify(p) for p in keep}

    # The outputs of the trace are live.
    flat_out, _ = tree_flatten(trace.output)
    needed.update(variableify(p) for p in flat_out if isinstance(p, Proxy))

    new_bsyms: list[BoundSymbol] = []
    for bsym in reversed(trace.bound_symbols):
        # SIDE_EFFECT ops act beyond their outputs (I/O, in-place writes) and
        # must survive even when nothing consumes their result — the same tag
        # the verifier's dce.dead-symbol rule keys on (one source of truth).
        keep_bsym = has_tag(bsym, OpTags.DONT_DCE) or has_tag(bsym, OpTags.SIDE_EFFECT)
        if not keep_bsym:
            keep_bsym = any(variableify(o) in needed for o in bsym.flat_proxy_outs)
        if keep_bsym:
            needed.update(variableify(a) for a in bsym.flat_proxy_args)
            new_bsyms.append(bsym)
    new_bsyms.reverse()

    ntrace = from_trace(trace)
    ntrace.bound_symbols = new_bsyms
    return wrap_in_trace_provenance(ntrace, "Dead Code Elimination", start)


def cse(trace: TraceCtx) -> TraceCtx:
    """Common-subexpression elimination by RHS hashing
    (reference: transform_common.py `cse:194`)."""
    start = time.perf_counter_ns()
    seen: dict[Any, BoundSymbol] = {}
    swap_map: dict[Variable, Proxy] = {}
    new_bsyms: list[BoundSymbol] = []

    for bsym in trace.bound_symbols:
        bsym = bsym.from_bsym_swap_proxies(swap_map, skip_output=True)
        # Effectful ops (SIDE_EFFECT/IN_PLACE) must never be merged: two
        # identical copy_ calls are two observable writes, not one value —
        # same tag model as DCE and the verifier's dce.dead-symbol rule.
        if (
            has_tag(bsym, OpTags.RANDOM_OP)
            or has_tag(bsym, OpTags.DONT_DCE)
            or has_tag(bsym, OpTags.SIDE_EFFECT)
            or has_tag(bsym, OpTags.IN_PLACE)
            or not bsym.flat_proxy_outs
        ):
            new_bsyms.append(bsym)
            continue
        rhs = bsym.rhs
        prev = seen.get(rhs)
        if prev is not None:
            for old, new in zip(bsym.flat_proxy_outs, prev.flat_proxy_outs):
                swap_map[variableify(old)] = new
            continue
        seen[rhs] = bsym
        new_bsyms.append(bsym)

    ntrace = from_trace(trace)
    ntrace.bound_symbols = new_bsyms
    # Output proxies may have been replaced.
    flat_out, spec = tree_flatten(ntrace.output)
    ntrace.output = tree_unflatten(
        spec, [swap_map.get(variableify(p), p) if isinstance(p, Proxy) else p for p in flat_out]
    )
    return wrap_in_trace_provenance(ntrace, "Common Subexpression Elimination", start)


def eval_trace(trace: TraceCtx, *args, symbol_mapper: Optional[Callable] = None, **kwargs) -> Any:
    """Interpret a trace, binding ``args`` to the trace's signature proxies.

    The workhorse of transform construction (reference: transforms.py
    `eval_trace:1641`): called under an active trace context it re-records
    the program (possibly transformed per ``symbol_mapper``).
    """
    env: dict[str, Any] = {}

    def bind(proxies, values):
        flat_p, _ = tree_flatten(proxies)
        flat_v, _ = tree_flatten(values)
        for p, v in zip(flat_p, flat_v):
            if isinstance(p, Proxy):
                env[p.name] = v

    bind(trace.args, args)
    bind(trace.kwargs, kwargs)

    def read(x):
        if isinstance(x, Proxy):
            if x.name not in env:
                raise RuntimeError(f"eval_trace: undefined proxy {x.name}")
            return env[x.name]
        return x

    def read_tree(tree):
        flat, spec = tree_flatten(tree)
        return tree_unflatten(spec, [read(x) for x in flat])

    for bsym in trace.bound_symbols:
        if bsym.sym.id in (PrimIDs.RETURN,):
            break
        if bsym.sym.id in (PrimIDs.DEL, PrimIDs.COMMENT):
            continue
        fn = symbol_mapper(bsym) if symbol_mapper is not None else bsym.sym
        if fn is None:
            continue
        result = fn(*read_tree(bsym.args), **read_tree(bsym.kwargs))
        # Bind outputs
        flat_out, _ = tree_flatten(bsym.output)
        flat_res, _ = tree_flatten(result)
        for p, v in zip(flat_out, flat_res):
            if isinstance(p, Proxy):
                env[p.name] = v

    return read_tree(trace.output)


def visitor_transform(trace: TraceCtx, visit: Callable, provenance: str = "Visitor transform") -> TraceCtx:
    """Rebuild a trace by visiting each bound symbol under a recording scope.

    ``visit(bsym)`` returns one of: None (keep as-is), or records replacement
    ops into the active scope and returns a swap map for outputs.
    Reference parity: transforms.py `visitor_transform:353`.
    """
    start = time.perf_counter_ns()
    ntrace = from_trace(trace)
    swap_map: dict[Variable, Proxy] = {}

    with tracectx(ntrace):
        for bsym in trace.bound_symbols:
            bsym = bsym.from_bsym_swap_proxies(swap_map)
            result = visit(bsym)
            if result is None:
                ntrace.bound_symbols.append(bsym)
            elif isinstance(result, dict):
                swap_map.update(result)

    flat_out, spec = tree_flatten(ntrace.output)
    ntrace.output = tree_unflatten(
        spec, [swap_map.get(variableify(p), p) if isinstance(p, Proxy) else p for p in flat_out]
    )
    return wrap_in_trace_provenance(ntrace, provenance, start)


def replace_redundant_inputs(trace: TraceCtx) -> TraceCtx:
    """Deduplicate repeated proxy inputs (reference: transform_common.py:107)."""
    return trace


def order_proxies(bsyms: Sequence[BoundSymbol]) -> dict[str, int]:
    """Proxy name → index of producing bsym (definition order)."""
    order: dict[str, int] = {}
    for i, bsym in enumerate(bsyms):
        for o in bsym.flat_proxy_outs:
            order.setdefault(o.name, i)
    return order
