"""Attention-residual saving: kill the flash backward's forward recompute.

Reference parity: thunder/executors/cudnnex.py:375 — the cuDNN SDPA
executor's backward graph consumes the forward's saved softmax_stats
(logsumexp) and output instead of re-running the forward. Our trace-level
autodiff emits a ``torch.sdpa_bwd`` composite whose flash implementation
recomputes the forward kernel under ``jax.vjp`` (~24 ms/iter on the
open_llama_3b bench, r4 profile: splash_mha_fwd_residuals 26×0.94 ms).

This pass rewrites matched (sdpa fwd, sdpa_bwd) pairs into
``torch.sdpa_fwd_res`` (returns out + lse) / ``torch.sdpa_bwd_res``
(consumes q, k, v, out, lse) so the flash executor can claim the backward
without recompute. It only fires when the flash executor says both sides
are claimable (``flashex.residual_eligible``); otherwise the pair is left
on the recompute path.
"""

from __future__ import annotations

import time
from typing import Optional

from thunder_tpu.core import dtypes
from thunder_tpu.core.proxies import TensorProxy
from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx, wrap_in_trace_provenance


def _flash_active(executors) -> bool:
    return any(getattr(e, "name", None) == "flash" for e in (executors or ()))


def _bound_sdpa(args, kwargs) -> dict:
    names = ("query", "key", "value", "attn_mask", "dropout_p", "is_causal", "scale", "enable_gqa")
    defaults = {"attn_mask": None, "dropout_p": 0.0, "is_causal": False, "scale": None, "enable_gqa": False}
    b = dict(zip(names, args))
    b.update(kwargs)
    for k, v in defaults.items():
        b.setdefault(k, v)
    return b


def _bound_bwd(args, kwargs) -> dict:
    names = ("g", "query", "key", "value", "attn_mask", "is_causal", "scale", "enable_gqa")
    defaults = {"attn_mask": None, "is_causal": False, "scale": None, "enable_gqa": False}
    b = dict(zip(names, args))
    b.update(kwargs)
    for k, v in defaults.items():
        b.setdefault(k, v)
    return b


def _match_pairs(fw_bsyms, bw_bsyms):
    """(fw_index, bw_index, fwd_bound, bwd_bound) for claimable pairs."""
    from thunder_tpu.executors import flashex

    fwd_by_key = {}
    for i, bsym in enumerate(fw_bsyms):
        if bsym.sym.id == "torch.scaled_dot_product_attention":
            b = _bound_sdpa(bsym.args, bsym.kwargs)
            if b["attn_mask"] is not None:
                continue
            key = (b["query"].name, b["key"].name, b["value"].name)
            fwd_by_key[key] = (i, b)

    pairs = []
    for j, bsym in enumerate(bw_bsyms):
        if bsym.sym.id != "torch.sdpa_bwd":
            continue
        b = _bound_bwd(bsym.args, bsym.kwargs)
        if b["attn_mask"] is not None:
            continue
        key = (b["query"].name, b["key"].name, b["value"].name)
        hit = fwd_by_key.get(key)
        if hit is None:
            continue
        i, fb = hit
        if not flashex.residual_eligible(fb["query"], fb["key"], fb["value"]):
            continue
        pairs.append((i, j, fb, b))
    return pairs


def _rewrite(trc: TraceCtx, idx: int, bound: dict, out_proxy) -> TensorProxy:
    """Swap bsym #idx for sdpa_fwd_res with output (out, lse); returns lse."""
    import thunder_tpu.torch as ltorch

    q = bound["query"]
    B, H, Tq = q.shape[0], q.shape[-3], q.shape[-2]
    with tracectx(trc):
        lse = TensorProxy(shape=(B, H, Tq), dtype=dtypes.float32, device=q.device)
    new_bsym = ltorch.sdpa_fwd_res._symbol.bind(
        bound["query"], bound["key"], bound["value"], None,
        bound["is_causal"], bound["scale"], bound["enable_gqa"],
        output=(out_proxy, lse),
    )
    trc.bound_symbols[idx] = new_bsym
    return lse


def _rewrite_bwd(bw_bsyms, j: int, bound: dict, out_proxy, lse) -> None:
    import thunder_tpu.torch as ltorch

    old = bw_bsyms[j]
    new_bsym = ltorch.sdpa_bwd_res._symbol.bind(
        bound["g"], bound["query"], bound["key"], bound["value"], out_proxy, lse,
        None, bound["is_causal"], bound["scale"], bound["enable_gqa"],
        output=old.output,
    )
    bw_bsyms[j] = new_bsym


def save_sdpa_residuals_joint(trc: TraceCtx, executors) -> TraceCtx:
    """Joint-trace variant (grad/value_and_grad pipelines): forward and
    backward composites live in ONE trace, so no saved-for-backward
    bookkeeping is needed."""
    if not _flash_active(executors):
        return trc
    pairs = _match_pairs(trc.bound_symbols, trc.bound_symbols)
    if not pairs:
        return trc
    start = time.perf_counter_ns()
    out_of = {}
    for i, _, fb, _bb in pairs:
        out_of[i] = trc.bound_symbols[i].output
    for i, j, fb, bb in pairs:
        lse = _rewrite(trc, i, fb, out_of[i])
        _rewrite_bwd(trc.bound_symbols, j, bb, out_of[i], lse)
    return wrap_in_trace_provenance(trc, "Attention residual saving (joint)", start)


def save_sdpa_residuals(fw_trace: TraceCtx, bw_trace: TraceCtx, executors):
    """Split-pipeline variant: rewrites the pair across the fw/bw traces and
    extends the saved-for-backward set with (out, lse). Run BEFORE
    rematerialization so the remat cost model accounts for the new saved
    bytes."""
    if not _flash_active(executors):
        return fw_trace, bw_trace
    saved_names = list(fw_trace.tags.get("saved_for_backward", []))
    if not saved_names:
        return fw_trace, bw_trace
    pairs = _match_pairs(fw_trace.bound_symbols, bw_trace.bound_symbols)
    if not pairs:
        return fw_trace, bw_trace
    start = time.perf_counter_ns()

    new_saved_proxies = []
    for i, j, fb, bb in pairs:
        out_proxy = fw_trace.bound_symbols[i].output
        lse = _rewrite(fw_trace, i, fb, out_proxy)
        _rewrite_bwd(bw_trace.bound_symbols, j, bb, out_proxy, lse)
        for p in (out_proxy, lse):
            if p.name not in saved_names:
                saved_names.append(p.name)
                new_saved_proxies.append(p)

    if not new_saved_proxies:
        return fw_trace, bw_trace

    from thunder_tpu.core import prims

    # rebuild fw with the extended saved tuple
    primal_out, old_saved = fw_trace.output
    new_saved_tuple = tuple(old_saved) + tuple(new_saved_proxies)
    new_fw = from_trace(fw_trace)
    new_fw.bound_symbols.extend(
        b for b in fw_trace.bound_symbols if b.sym.id is not prims.PrimIDs.RETURN
    )
    new_out = (primal_out, new_saved_tuple)
    with tracectx(new_fw):
        prims.python_return(new_out)
    new_fw.output = new_out
    new_fw.tags["saved_for_backward"] = saved_names

    # rebuild bw with the extended arg list (saved... + cotangents...)
    n_old_saved = len(old_saved)
    cotangents = list(bw_trace.args[n_old_saved:])
    new_bw = from_trace(bw_trace)
    new_bw.args = tuple(old_saved) + tuple(new_saved_proxies) + tuple(cotangents)
    new_bw.bound_symbols.extend(bw_trace.bound_symbols)

    new_fw = wrap_in_trace_provenance(new_fw, "Attention residual saving (fw)", start)
    new_bw = wrap_in_trace_provenance(new_bw, "Attention residual saving (bw)", start)
    return new_fw, new_bw
