"""Autocast: mixed-precision trace transform.

Reference parity: thunder/core/transforms.py autocast rules + transform
(`:3998-4046`) — matmul-class ops run in the low-precision dtype; everything
else keeps its dtype (norms/softmax already compute in f32 inside their
ltorch decompositions).

TPU note: bf16 is the MXU-native dtype, so this transform is the single
biggest throughput lever for f32 models; no GradScaler is needed (bf16 has
f32's exponent range, unlike fp16 on CUDA).
"""

from __future__ import annotations

import time
from typing import Optional

import thunder_tpu.clang as clang
from thunder_tpu.core import dtypes
from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.core.proxies import TensorProxy, variableify
from thunder_tpu.core.pytree import tree_flatten, tree_unflatten
from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx, wrap_in_trace_provenance

# Ops whose *inputs* are downcast (reference: autocast_impls keyed on
# matmul/linear/convolution). Listed at both the composite (ltorch) and prim
# level so the transform works before or after flattening.
_AUTOCAST_IDS = {
    PrimIDs.MATMUL,
    PrimIDs.LINEAR,
    PrimIDs.CONVOLUTION,
    "torch.matmul",
    "torch.bmm",
    "torch.linear",
    "torch.conv1d",
    "torch.conv2d",
    "torch.conv3d",
    "torch.scaled_dot_product_attention",
}


def autocast(trace: TraceCtx, dtype=dtypes.bfloat16) -> TraceCtx:
    """Downcast matmul-class op inputs to ``dtype`` (default bf16)."""
    start = time.perf_counter_ns()
    dtype = dtypes.to_dtype(dtype)
    ntrace = from_trace(trace)
    swap: dict = {}

    def cast(x):
        if isinstance(x, TensorProxy) and dtypes.is_float_dtype(x.dtype) and x.dtype != dtype:
            return clang.maybe_convert_to_dtype(x, dtype)
        return x

    with tracectx(ntrace):
        for bsym in trace.bound_symbols:
            b = bsym.from_bsym_swap_proxies(swap)
            if b.sym.id in _AUTOCAST_IDS:
                flat_args, spec = tree_flatten((b.args, b.kwargs))
                new_flat = [cast(a) for a in flat_args]
                new_args, new_kwargs = tree_unflatten(spec, new_flat)
                out = b.sym(*new_args, **new_kwargs)
                old_outs = b.flat_proxy_outs
                new_outs, _ = tree_flatten(out)
                for o, n in zip(old_outs, [x for x in new_outs if isinstance(x, TensorProxy)]):
                    # Cast the low-precision result back to the op's original
                    # output dtype: consumers were recorded against that
                    # metadata, and swapping a bf16 proxy into them would make
                    # every downstream bsym's recorded dtype a lie (caught by
                    # the verifier's meta.mismatch rule). The matmul itself
                    # still runs on the MXU in ``dtype``; XLA fuses the
                    # widening convert into the epilogue.
                    if isinstance(o, TensorProxy) and n.dtype != o.dtype:
                        n = clang.maybe_convert_to_dtype(n, o.dtype)
                    swap[variableify(o)] = n
            else:
                ntrace.bound_symbols.append(b)

    flat_out, spec = tree_flatten(ntrace.output)
    ntrace.output = tree_unflatten(
        spec, [swap.get(variableify(p), p) if isinstance(p, TensorProxy) else p for p in flat_out]
    )
    return wrap_in_trace_provenance(ntrace, f"Autocast to {dtype}", start)
