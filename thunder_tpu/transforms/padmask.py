"""Pad-mask threading for symbolic-values caching.

Under ``cache="symbolic values"`` the trace is acquired on BUCKET-PADDED
inputs (core/bucketing.py): a marked dim's extent in the trace is the bucket
ceiling, and the dispatcher zero-pads real inputs up to it. Padding is exact
for row-independent compute (elementwise, matmul over non-padded contractions,
causal attention), but a REDUCTION over a padded dim would fold the pad rows
into the result. This pass makes those reductions exact for every extent in
the bucket:

1. **Dim provenance**: starting from the marked input dims, track which dims
   of every intermediate carry padding, through shape ops (broadcast,
   transpose, reshape-merge), elementwise ops, matmuls, gathers and
   reductions. A reshape that merges a padded dim keeps its factor structure
   so the mask can be rebuilt in the merged layout (``(B,T,V)->(B*T,V)``).

2. **Masked rewrites**: ``sum``/``prod``/``amax``/``amin``/``argmax``/
   ``argmin``/``topk`` over a padded dim are rewritten against a validity
   mask built from the RUNTIME true extent — a fresh 0-d int32 input appended
   to the trace (``iota(P) < n_true``) — so ONE executable serves the whole
   bucket with exact reduction semantics. A matmul whose contracted dim is
   padded gets the mask multiplied into its left operand (zeros contribute
   nothing to the contraction).

3. **Mean-count fix**: ``div(sum(x), k)`` / ``mul(sum(x), 1/k)`` where ``k``
   is the padded element count is re-pointed at the runtime true count, so
   means (cross-entropy losses included) match the unpadded computation.
   Known sharp edge: a USER literal that happens to equal the padded element
   count is indistinguishable from a shape-derived count and is re-pointed
   too (``sum(x, 0) / 4.0`` with a bucket ceiling of 4 divides by the true
   extent). Shape-derived counts (``x.shape[0]`` or ``mean``) are what this
   targets; keep literal divisors away from padded-dim sums or use exact
   caching for those dims (documented in docs/caching.md).

Ops the propagator does not model drop tracking for their outputs with a
one-time warning — downstream reductions over those values then see padded
rows (same behavior as no masking at all, but LOUD). The pass also returns a
crop plan: which output dims carry padding (and which bucket class), so the
dispatcher can slice outputs back to the true extents.
"""

from __future__ import annotations

import time
from numbers import Number
from typing import Any, Optional

from thunder_tpu.core import dtypes, prims
from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import NumberProxy, Proxy, TensorProxy, variableify
from thunder_tpu.core.pytree import tree_flatten, tree_unflatten
from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx, wrap_in_trace_provenance

# factors: tuple of (class_id | None, padded_extent) — a dim is "tracked" when
# at least one factor has a class id. Single-factor dims crop; multi-factor
# dims are reshape-merges (mask rebuilt, crop impossible).

_IDENTITY_IDS = {
    PrimIDs.CONVERT_ELEMENT_TYPE,
    PrimIDs.STOP_GRADIENT,
    PrimIDs.SHALLOW_COPY,
    PrimIDs.DEVICE_PUT,
    # Padding sits at the END of each dim, so prefix scans over real rows are
    # unaffected (zero/garbage only enters at padded positions, which crop).
    PrimIDs.CUMSUM,
    PrimIDs.CUMPROD,
}

_PASS_IDS = {PrimIDs.RETURN, PrimIDs.DEL, PrimIDs.COMMENT, PrimIDs.PRINT, PrimIDs.TENSOR_CONSTANT}

# Composites that are safe to keep whole (so kernel executors can still claim
# them) with known dim semantics. Keyed by symbol NAME.
_SAFE_COMPOSITES = {"apply_rope"}


def _is_tracked(factors: tuple) -> bool:
    return any(cid is not None for cid, _ in factors)


class _PadMasker:
    def __init__(self, trace: TraceCtx, spec, analyze_only: bool = False):
        self.trace = trace
        self.spec = spec
        # analyze_only: propagate provenance (for the crop plan) WITHOUT
        # rewriting — used on grad-transformed traces, whose reductions were
        # already masked before differentiation.
        self.analyze_only = analyze_only
        # from_trace gives an EMPTY trace whose scope stack aliases its
        # bound_symbols list — never reassign it, or Symbol.__call__ records
        # into a dead list.
        self.ntrace = from_trace(trace)
        self.swap_map: dict = {}
        # proxy name -> {dim: factors}
        self.tracked: dict[str, dict[int, tuple]] = {}
        self.ext_proxies: dict[int, TensorProxy] = {}  # class id -> 0-d int32 input
        self.ext_order: list[int] = []
        self.dim_mask_cache: dict = {}  # factors -> bool mask proxy (1-D, merged layout)
        self.sum_info: dict[str, tuple] = {}  # masked-sum name -> (padded_count, class ids, const count)
        # Scalar constants materialized as tensors (full / broadcast / convert
        # chains): clang's true_divide turns a Python count into a 0-d full,
        # so the mean-count fix must see through it.
        self.const_vals: dict[str, float] = {}
        self.warnings: list[str] = []
        self._warned: set[str] = set()

        for li, dims in spec.marks.items():
            p = trace.args[li]
            self.tracked[p.name] = {d: ((cid, hi),) for d, (lo, hi, cid) in dims.items()}

    # -- helpers --------------------------------------------------------------

    def warn(self, key: str, msg: str) -> None:
        if key not in self._warned:
            self._warned.add(key)
            self.warnings.append(msg)

    def t(self, p) -> dict:
        if isinstance(p, Proxy):
            return self.tracked.get(p.name, {})
        return {}

    def set_tracking(self, p, dims: dict) -> None:
        dims = {d: f for d, f in dims.items() if _is_tracked(f)}
        if dims and isinstance(p, TensorProxy):
            self.tracked[p.name] = dims

    def ext_proxy(self, cid: int, device) -> TensorProxy:
        p = self.ext_proxies.get(cid)
        if p is None:
            p = TensorProxy(shape=(), device=device, dtype=dtypes.int32, prefix="extent")
            self.ext_proxies[cid] = p
            self.ext_order.append(cid)
        return p

    def dim_mask(self, factors: tuple, device) -> TensorProxy:
        """Boolean validity mask of shape (prod(factor extents),) — True at
        positions whose coordinate along every tracked factor is < the
        runtime true extent."""
        hit = self.dim_mask_cache.get(factors)
        if hit is not None:
            return hit
        fshape = tuple(n for _, n in factors)
        mask = None
        for idx, (cid, n) in enumerate(factors):
            if cid is None:
                continue
            iv = prims.iota(n, start=0, step=1, device=device, dtype=dtypes.int32)
            ext = self.ext_proxy(cid, device)
            extb = prims.broadcast_in_dim(ext, (n,), ())
            mi = prims.lt(iv, extb)
            if len(factors) > 1:
                mi = prims.broadcast_in_dim(mi, fshape, (idx,))
            mask = mi if mask is None else prims.bitwise_and(mask, mi)
        if len(factors) > 1:
            total = 1
            for n in fshape:
                total *= n
            mask = prims.reshape(mask, (total,))
        self.dim_mask_cache[factors] = mask
        return mask

    def full_mask(self, a: TensorProxy, dims: list[int]) -> TensorProxy:
        """Boolean mask broadcast to a.shape, AND-ed over the given dims."""
        atrack = self.t(a)
        mask = None
        for d in dims:
            m = self.dim_mask(atrack[d], a.device)
            mb = prims.broadcast_in_dim(m, tuple(a.shape), (d,))
            mask = mb if mask is None else prims.bitwise_and(mask, mb)
        return mask

    def masked_value(self, a: TensorProxy, dims: list[int], neutral) -> TensorProxy:
        """a with padded positions along ``dims`` replaced by ``neutral``
        (0 via a multiply, anything else via where)."""
        mask = self.full_mask(a, dims)
        if neutral == 0:
            out = prims.mul(a, prims.convert_element_type(mask, a.dtype))
        else:
            fill = prims.full(tuple(a.shape), neutral, device=a.device, dtype=a.true_dtype)
            out = prims.where(mask, a, fill)
        # Masking replaces values, not layout: the result carries a's dims.
        self.set_tracking(out, dict(self.t(a)))
        return out

    # -- per-op handling ------------------------------------------------------

    def run(self):
        with tracectx(self.ntrace):
            self.walk(self.trace.bound_symbols)
        # Rewire the output through the swap map.
        flat_out, out_spec = tree_flatten(self.trace.output)
        flat_out = [
            self.swap_map.get(variableify(p), p) if isinstance(p, Proxy) else p for p in flat_out
        ]
        self.ntrace.output = tree_unflatten(out_spec, flat_out)
        self.ntrace.args = tuple(self.trace.args) + tuple(
            self.ext_proxies[cid] for cid in self.ext_order
        )
        crop_plan = self.crop_plan(flat_out)
        return self.ntrace, tuple(self.ext_order), crop_plan, self.warnings

    def crop_plan(self, flat_out) -> list:
        plan = []
        for i, p in enumerate(flat_out):
            if not isinstance(p, TensorProxy):
                continue
            dims = {}
            for d, factors in self.t(p).items():
                if len(factors) == 1 and factors[0][0] is not None:
                    dims[d] = factors[0][0]
                elif _is_tracked(factors):
                    self.warn(
                        f"crop-merged-{i}-{d}",
                        f"output {p.name} dim {d} interleaves padded data (a reshape "
                        "merged a padded dim); it cannot be cropped back — reshape "
                        "after the jit boundary or mark fewer dims symbolic",
                    )
            if dims:
                plan.append((i, dims))
        return plan

    def walk(self, bsyms) -> None:
        for bsym in bsyms:
            self.handle(bsym.from_bsym_swap_proxies(self.swap_map))

    def emit(self, bsym) -> None:
        self.ntrace.bound_symbols.append(bsym)

    def handle(self, bsym) -> None:
        sid = bsym.sym.id
        if sid in _PASS_IDS:
            self.emit(bsym)
            return
        if sid is PrimIDs.FULL and isinstance(bsym.args[1], Number):
            self.const_vals[bsym.output.name] = float(bsym.args[1])
        elif sid in (PrimIDs.BROADCAST_IN_DIM, PrimIDs.CONVERT_ELEMENT_TYPE):
            src = bsym.args[0]
            if isinstance(src, Proxy) and src.name in self.const_vals:
                self.const_vals[bsym.output.name] = self.const_vals[src.name]
        # Follow masked-sum outputs too: a FULL reduction's result carries no
        # tracked dims, but its consumers must still be expanded so the
        # div-by-count of a mean can be re-pointed at the true count.
        has_tracked_arg = any(
            a.name in self.tracked or a.name in self.sum_info for a in bsym.flat_proxy_args
        )
        if not has_tracked_arg:
            self.emit(bsym)
            return

        handler = _HANDLERS.get(sid)
        if handler is not None:
            handler(self, bsym)
            return
        name = getattr(bsym.sym, "name", "")
        if name == "scaled_dot_product_attention" and self._sdpa_causal(bsym):
            self._prop_sdpa(bsym)
            return
        if name in _SAFE_COMPOSITES:
            # Shape-preserving composite: output dims mirror the first arg.
            out = bsym.flat_proxy_outs
            a = next((x for x in bsym.flat_proxy_args if isinstance(x, TensorProxy)), None)
            self.emit(bsym)
            if a is not None:
                for o in out:
                    if isinstance(o, TensorProxy) and tuple(o.shape) == tuple(a.shape):
                        self.set_tracking(o, dict(self.t(a)))
            return
        if bsym.subsymbols:
            # Unknown composite consuming padded dims: expand so the prim
            # rules below see the reductions inside it.
            self.walk(bsym.subsymbols)
            return
        self.warn(
            f"op-{bsym.sym.qualname}",
            f"{bsym.sym.qualname} consumes a padded dim but has no provenance "
            "rule; padding is no longer tracked through its outputs (reductions "
            "downstream may include padded rows)",
        )
        self.emit(bsym)

    @staticmethod
    def _sdpa_causal(bsym) -> bool:
        if bsym.kwargs.get("is_causal"):
            return True
        # is_causal is the 5th positional arg of the torch signature.
        return len(bsym.args) > 5 and bool(bsym.args[5])

    def _prop_sdpa(self, bsym) -> None:
        # Causal SDPA is exactly tail-padding-safe: a real query position i
        # only attends keys <= i, and every padded key sits at a position
        # > i, already masked to -inf by the causal mask; padded query rows
        # produce garbage that the crop removes. Keep the composite whole so
        # the flash executor can still claim it.
        self.emit(bsym)
        q = bsym.args[0]
        out = bsym.flat_proxy_outs
        if isinstance(q, TensorProxy):
            for o in out:
                if isinstance(o, TensorProxy) and tuple(o.shape) == tuple(q.shape):
                    self.set_tracking(o, dict(self.t(q)))


# -- propagation rules ---------------------------------------------------------


def _carry_sum_info(pm: _PadMasker, src, out) -> None:
    """Value-preserving reshapes/casts/broadcasts of a masked sum keep the
    mean-count link alive (clang's keepdim path reshapes between the sum and
    its div; the dtype conversion of mean sits there too)."""
    if isinstance(src, TensorProxy) and isinstance(out, TensorProxy):
        info = pm.sum_info.get(src.name)
        if info is not None:
            pm.sum_info[out.name] = info


_VALUE_PRESERVING_IDS = {
    PrimIDs.CONVERT_ELEMENT_TYPE,
    PrimIDs.STOP_GRADIENT,
    PrimIDs.SHALLOW_COPY,
    PrimIDs.DEVICE_PUT,
}


def _prop_identity(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    a = next((x for x in bsym.flat_proxy_args if isinstance(x, TensorProxy)), None)
    if a is None:
        return
    if bsym.sym.id in _VALUE_PRESERVING_IDS:  # not the scans: they change values
        _carry_sum_info(pm, a, bsym.output)
    for o in bsym.flat_proxy_outs:
        if isinstance(o, TensorProxy) and tuple(o.shape) == tuple(a.shape):
            pm.set_tracking(o, dict(pm.t(a)))


def _prop_elementwise(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    outs = [o for o in bsym.flat_proxy_outs if isinstance(o, TensorProxy)]
    for o in outs:
        merged: dict[int, tuple] = {}
        for a in bsym.flat_proxy_args:
            if isinstance(a, TensorProxy) and tuple(a.shape) == tuple(o.shape):
                for d, f in pm.t(a).items():
                    merged.setdefault(d, f)
        pm.set_tracking(o, merged)


def _prop_broadcast(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    a, shape, bdims = bsym.args[0], bsym.args[1], bsym.args[2]
    o = bsym.output
    if not isinstance(o, TensorProxy) or not isinstance(a, TensorProxy):
        return
    _carry_sum_info(pm, a, o)
    out: dict[int, tuple] = {}
    for i, d in enumerate(tuple(bdims)):
        f = pm.t(a).get(i)
        if f is not None and int(a.shape[i]) == int(tuple(shape)[d]):
            out[d] = f
    pm.set_tracking(o, out)


def _prop_transpose(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    a, perm = bsym.args[0], tuple(bsym.args[1])
    o = bsym.output
    out = {j: pm.t(a)[perm[j]] for j in range(len(perm)) if perm[j] in pm.t(a)}
    pm.set_tracking(o, out)


def _prop_squeeze(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    a, dims = bsym.args[0], set(int(d) for d in bsym.args[1])
    o = bsym.output
    out: dict[int, tuple] = {}
    j = 0
    for i in range(a.ndim):
        if i in dims:
            continue
        if i in pm.t(a):
            out[j] = pm.t(a)[i]
        j += 1
    pm.set_tracking(o, out)


def _reshape_tracking(in_shape, in_track: dict, out_shape) -> Optional[dict]:
    """Greedy left-to-right alignment of a reshape: equal dims carry over,
    merges concatenate factor lists, splits of a TRACKED dim (and unaligned
    permuting reshapes) return None."""
    out: dict[int, tuple] = {}
    i = j = 0
    n_in, n_out = len(in_shape), len(out_shape)
    while i < n_in and j < n_out:
        if int(in_shape[i]) == int(out_shape[j]):
            if i in in_track:
                out[j] = in_track[i]
            i += 1
            j += 1
            continue
        if int(in_shape[i]) < int(out_shape[j]):
            # merge input dims i..k-1 into output dim j
            prod = int(in_shape[i])
            k = i + 1
            while prod < int(out_shape[j]) and k < n_in:
                prod *= int(in_shape[k])
                k += 1
            if prod != int(out_shape[j]):
                return None
            factors: list = []
            tracked = False
            for t_i in range(i, k):
                fs = in_track.get(t_i)
                if fs is not None:
                    factors.extend(fs)
                    tracked = True
                else:
                    factors.append((None, int(in_shape[t_i])))
            if tracked:
                out[j] = tuple(factors)
            i = k
            j += 1
            continue
        # split: input dim i covers output dims j..k2-1
        prod = int(out_shape[j])
        k2 = j + 1
        while prod < int(in_shape[i]) and k2 < n_out:
            prod *= int(out_shape[k2])
            k2 += 1
        if prod != int(in_shape[i]):
            return None
        if i in in_track:
            return None  # splitting a padded dim scatters the padding
        i += 1
        j = k2
    return out


def _prop_reshape(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    a, shape = bsym.args[0], tuple(bsym.args[1])
    o = bsym.output
    _carry_sum_info(pm, a, o)
    track = pm.t(a)
    if not track:
        return
    out = _reshape_tracking(tuple(a.shape), track, shape)
    if out is None:
        pm.warn(
            f"reshape-{o.name}",
            f"reshape {tuple(a.shape)} -> {shape} splits or reorders a padded "
            "dim; padding is no longer tracked through it",
        )
        return
    pm.set_tracking(o, out)


def _prop_matmul(pm: _PadMasker, bsym) -> None:
    a, b = bsym.args[0], bsym.args[1]
    o = bsym.output
    # A padded CONTRACTED dim must contract zeros (intermediates like exp(x)
    # are nonzero at padded positions): mask whichever operand carries the
    # tracking — one zeroed factor suffices.
    ka = a.ndim - 1
    kb = b.ndim - 2 if isinstance(b, TensorProxy) and b.ndim >= 2 else None
    if not pm.analyze_only:
        if ka in pm.t(a) and _is_tracked(pm.t(a)[ka]):
            a = pm.masked_value(a, [ka], 0)
            bsym = bsym.from_bsym(args=(a, b) + tuple(bsym.args[2:]))
        elif kb is not None and kb in pm.t(b) and _is_tracked(pm.t(b)[kb]):
            b = pm.masked_value(b, [kb], 0)
            bsym = bsym.from_bsym(args=(a, b) + tuple(bsym.args[2:]))
    pm.emit(bsym)
    if not isinstance(o, TensorProxy):
        return
    out: dict[int, tuple] = {}
    orig_a = bsym.args[0]
    for d in range(o.ndim - 2):  # batch dims, aligned from the left for equal ranks
        for operand in (orig_a, b):
            if (
                isinstance(operand, TensorProxy)
                and operand.ndim == o.ndim
                and d in pm.t(operand)
                and int(operand.shape[d]) == int(o.shape[d])
            ):
                out.setdefault(d, pm.t(operand)[d])
    if o.ndim >= 2:
        if isinstance(orig_a, TensorProxy) and (orig_a.ndim - 2) in pm.t(orig_a):
            out[o.ndim - 2] = pm.t(orig_a)[orig_a.ndim - 2]
        if isinstance(b, TensorProxy) and (b.ndim - 1) in pm.t(b):
            out[o.ndim - 1] = pm.t(b)[b.ndim - 1]
    pm.set_tracking(o, out)


def _prop_linear(pm: _PadMasker, bsym) -> None:
    a, w = bsym.args[0], bsym.args[1]
    o = bsym.output
    # linear contracts a's last dim with w's dim 1: zero whichever operand
    # carries the padded-contraction tracking.
    ka = a.ndim - 1
    if not pm.analyze_only:
        if ka in pm.t(a) and _is_tracked(pm.t(a)[ka]):
            a = pm.masked_value(a, [ka], 0)
            bsym = bsym.from_bsym(args=(a,) + tuple(bsym.args[1:]))
        elif isinstance(w, TensorProxy) and 1 in pm.t(w) and _is_tracked(pm.t(w)[1]):
            w = pm.masked_value(w, [1], 0)
            bsym = bsym.from_bsym(args=(bsym.args[0], w) + tuple(bsym.args[2:]))
    pm.emit(bsym)
    orig_a = bsym.args[0]
    out = {d: f for d, f in pm.t(orig_a).items() if d < orig_a.ndim - 1}
    if isinstance(w, TensorProxy) and 0 in pm.t(w):
        out[o.ndim - 1] = pm.t(w)[0]
    pm.set_tracking(o, out)


def _prop_embedding(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    idx = bsym.args[0]
    o = bsym.output
    pm.set_tracking(o, dict(pm.t(idx)))


def _prop_take(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    a, idx, dim = bsym.args[0], bsym.args[1], int(bsym.args[2])
    o = bsym.output
    out: dict[int, tuple] = {}
    for d, f in pm.t(a).items():
        if d < dim:
            out[d] = f
        elif d > dim:
            out[d + idx.ndim - 1] = f
    if isinstance(idx, TensorProxy):
        for d, f in pm.t(idx).items():
            out[dim + d] = f
    pm.set_tracking(o, out)


def _prop_gather(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    a, idx, dim = bsym.args[0], bsym.args[1], int(bsym.args[2])
    o = bsym.output
    # Same-rank gathers: non-gather output dims align positionally with BOTH
    # the source and the index tensor — take tracking from either (the source
    # contributes when e.g. a batch-padded h is gathered with a constant idx).
    out: dict[int, tuple] = {}
    for operand in (idx, a):
        if not isinstance(operand, TensorProxy) or operand.ndim != o.ndim:
            continue
        for d, f in pm.t(operand).items():
            if d != dim and int(operand.shape[d]) == int(o.shape[d]):
                out.setdefault(d, f)
    pm.set_tracking(o, out)


def _prop_cat(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    tensors, dim = bsym.args[0], int(bsym.args[1])
    o = bsym.output
    first = tensors[0]
    dim = dim if dim >= 0 else dim + first.ndim
    # Union the operands' tracked non-cat dims: every operand shares those
    # extents, so a dim tracked on ANY of them is padded in the result; a
    # factor disagreement (different class) keeps the first seen — extents
    # match, and interacting classes carry equal runtime extents by
    # construction (the unpadded program would be shape-invalid otherwise).
    out: dict[int, tuple] = {}
    for t_ in tensors:
        if not isinstance(t_, TensorProxy):
            continue
        for d, f in pm.t(t_).items():
            if d == dim:
                pm.warn(
                    f"cat-{o.name}",
                    f"cat along padded dim {dim} interleaves padding; the result "
                    "is no longer tracked along that dim",
                )
                continue
            out.setdefault(d, f)
    pm.set_tracking(o, out)


def _prop_slice(pm: _PadMasker, bsym) -> None:
    pm.emit(bsym)
    a = bsym.args[0]
    starts, ends = tuple(bsym.args[1]), tuple(bsym.args[2])
    strides = tuple(bsym.args[3]) if len(bsym.args) > 3 and bsym.args[3] else (1,) * a.ndim
    o = bsym.output
    out: dict[int, tuple] = {}
    for d, f in pm.t(a).items():
        full = (
            int(starts[d]) == 0
            and int(ends[d]) == int(a.shape[d])
            and int(strides[d]) == 1
        )
        if full:
            out[d] = f
    pm.set_tracking(o, out)


# -- reduction rewrites --------------------------------------------------------


def _tracked_reduced(pm: _PadMasker, a, dims) -> list[int]:
    return [int(d) for d in dims if int(d) in pm.t(a) and _is_tracked(pm.t(a)[int(d)])]


def _survivor_tracking(pm: _PadMasker, a, dims) -> dict:
    reduced = {int(d) for d in dims}
    out: dict[int, tuple] = {}
    j = 0
    for i in range(a.ndim):
        if i in reduced:
            continue
        if i in pm.t(a):
            out[j] = pm.t(a)[i]
        j += 1
    return out


def _rewrite_reduction(pm: _PadMasker, bsym) -> None:
    a, dims = bsym.args[0], tuple(int(d) for d in bsym.args[1])
    sid = bsym.sym.id
    tdims = _tracked_reduced(pm, a, dims)
    if not tdims or pm.analyze_only:
        pm.emit(bsym)
        pm.set_tracking(bsym.output, _survivor_tracking(pm, a, dims))
        return
    if sid in (PrimIDs.AMAX, PrimIDs.AMIN) and not dtypes.is_inexact_dtype(a.dtype):
        pm.warn(
            f"intred-{bsym.output.name}",
            f"{bsym.sym.name} over a padded dim of an integer tensor cannot be "
            "masked (no +-inf neutral); padded rows participate",
        )
        pm.emit(bsym)
        return
    if sid is PrimIDs.SUM:
        am = pm.masked_value(a, tdims, 0)
        new_out = prims.sum_prim(am, dims)
        padded = 1
        for d in dims:
            padded *= int(a.shape[d])
        cids: list[int] = []
        const = 1
        for d in dims:
            for cid, n in pm.t(a).get(d, ((None, int(a.shape[d])),)):
                if cid is None:
                    const *= int(n)
                else:
                    cids.append(cid)
        pm.sum_info[new_out.name] = (padded, tuple(cids), const)
    elif sid is PrimIDs.PROD:
        am = pm.masked_value(a, tdims, 1)
        new_out = prims.prod(am, dims)
    elif sid is PrimIDs.AMAX:
        am = pm.masked_value(a, tdims, float("-inf"))
        new_out = prims.amax(am, dims)
    else:  # AMIN
        am = pm.masked_value(a, tdims, float("inf"))
        new_out = prims.amin(am, dims)
    pm.swap_map[variableify(bsym.output)] = new_out
    pm.set_tracking(new_out, _survivor_tracking(pm, a, dims))


def _rewrite_argminmax(pm: _PadMasker, bsym) -> None:
    a, dim = bsym.args[0], bsym.args[1]
    if dim is None:
        if any(_is_tracked(f) for f in pm.t(a).values()):
            pm.warn(
                f"arg-flat-{bsym.output.name}",
                f"{bsym.sym.name}(dim=None) over a padded tensor returns indices "
                "in PADDED coordinates; pass an explicit dim or use exact caching",
            )
        pm.emit(bsym)
        return
    dim = int(dim)
    tdims = _tracked_reduced(pm, a, (dim,))
    if not tdims or pm.analyze_only or not dtypes.is_inexact_dtype(a.dtype):
        pm.emit(bsym)
        pm.set_tracking(bsym.output, _survivor_tracking(pm, a, (dim,)))
        return
    neutral = float("-inf") if bsym.sym.id is PrimIDs.ARGMAX else float("inf")
    am = pm.masked_value(a, tdims, neutral)
    new_out = (prims.argmax if bsym.sym.id is PrimIDs.ARGMAX else prims.argmin)(am, dim)
    pm.swap_map[variableify(bsym.output)] = new_out
    pm.set_tracking(new_out, _survivor_tracking(pm, a, (dim,)))


def _rewrite_topk(pm: _PadMasker, bsym) -> None:
    a, k, dim = bsym.args[0], bsym.args[1], int(bsym.args[2])
    largest = bool(bsym.args[3]) if len(bsym.args) > 3 else True
    tdims = _tracked_reduced(pm, a, (dim,))
    if not tdims or pm.analyze_only or not dtypes.is_inexact_dtype(a.dtype):
        pm.emit(bsym)
        return
    pm.warn(
        f"topk-{bsym.output.name if hasattr(bsym.output, 'name') else dim}",
        f"topk over a padded dim is masked with ∓inf filler: a call whose "
        f"runtime extent is smaller than k={k} returns filler values/padded "
        "indices for the excess slots (exact caching would raise instead)",
    )
    am = pm.masked_value(a, tdims, float("-inf") if largest else float("inf"))
    new_bsym = bsym.from_bsym(args=(am,) + tuple(bsym.args[1:]))
    # Mint fresh outputs to keep SSA: re-run via the symbol call.
    new_outs = bsym.sym(*new_bsym.args, **new_bsym.kwargs)
    flat_new, _ = tree_flatten(new_outs)
    for old, new in zip(bsym.flat_proxy_outs, [x for x in flat_new if isinstance(x, Proxy)]):
        pm.swap_map[variableify(old)] = new


def _rewrite_var(pm: _PadMasker, bsym) -> None:
    a, dims = bsym.args[0], tuple(int(d) for d in bsym.args[1])
    if _tracked_reduced(pm, a, dims):
        pm.warn(
            f"var-{bsym.sym.name}",
            f"{bsym.sym.name} over a padded dim is not masked (normalize over "
            "unpadded dims, or mark fewer dims symbolic); padded rows "
            "participate in the statistics",
        )
        pm.emit(bsym)
        return
    pm.emit(bsym)
    for o in bsym.flat_proxy_outs:
        pm.set_tracking(o, _survivor_tracking(pm, a, dims))


def _true_count(pm: _PadMasker, cids: tuple, const: int, device) -> TensorProxy:
    tc = None
    for cid in cids:
        e = pm.ext_proxy(cid, device)
        tc = e if tc is None else prims.mul(tc, e)
    if const != 1:
        c = prims.full((), const, device=device, dtype=dtypes.int32)
        tc = c if tc is None else prims.mul(tc, c)
    return tc


def _fix_mean_count(pm: _PadMasker, bsym) -> bool:
    """div(masked_sum, padded_count) / mul(masked_sum, 1/padded_count) →
    divide by the runtime true count instead. Returns True when rewritten."""
    if pm.analyze_only:
        return False
    s, c = bsym.args[0], bsym.args[1]
    if not isinstance(s, TensorProxy):
        return False
    info = pm.sum_info.get(s.name)
    if info is None:
        return False
    padded, cids, const = info
    if not cids:
        return False
    if isinstance(c, TensorProxy):
        cval = pm.const_vals.get(c.name)
    else:
        cval = c.value if isinstance(c, NumberProxy) else c
    if not isinstance(cval, Number):
        return False
    if bsym.sym.id is PrimIDs.DIV:
        if float(cval) != float(padded):
            return False
    else:  # MUL
        if float(cval) == 0 or abs(float(cval) * float(padded) - 1.0) > 1e-12:
            return False
    tc = _true_count(pm, cids, const, s.device)
    tcf = prims.convert_element_type(tc, s.dtype)
    if s.ndim > 0:
        tcf = prims.broadcast_in_dim(tcf, tuple(s.shape), ())
    new_out = prims.div(s, tcf)
    pm.swap_map[variableify(bsym.output)] = new_out
    pm.set_tracking(new_out, dict(pm.t(s)))
    return True


def _prop_div(pm: _PadMasker, bsym) -> None:
    if _fix_mean_count(pm, bsym):
        return
    _prop_elementwise(pm, bsym)


def _prop_mul(pm: _PadMasker, bsym) -> None:
    if _fix_mean_count(pm, bsym):
        return
    _prop_elementwise(pm, bsym)


def _drop_with_warning(pm: _PadMasker, bsym) -> None:
    pm.warn(
        f"op-{bsym.sym.qualname}",
        f"{bsym.sym.qualname} consumes a padded dim; padding is not tracked "
        "through it",
    )
    pm.emit(bsym)


_HANDLERS: dict = {
    PrimIDs.BROADCAST_IN_DIM: _prop_broadcast,
    PrimIDs.TRANSPOSE: _prop_transpose,
    PrimIDs.SQUEEZE: _prop_squeeze,
    PrimIDs.RESHAPE: _prop_reshape,
    PrimIDs.MATMUL: _prop_matmul,
    PrimIDs.LINEAR: _prop_linear,
    PrimIDs.EMBEDDING: _prop_embedding,
    PrimIDs.TAKE: _prop_take,
    PrimIDs.TAKE_ALONG_AXIS: _prop_gather,
    PrimIDs.GATHER: _prop_gather,
    PrimIDs.CAT: _prop_cat,
    PrimIDs.SLICE: _prop_slice,
    PrimIDs.SUM: _rewrite_reduction,
    PrimIDs.PROD: _rewrite_reduction,
    PrimIDs.AMAX: _rewrite_reduction,
    PrimIDs.AMIN: _rewrite_reduction,
    PrimIDs.ARGMAX: _rewrite_argminmax,
    PrimIDs.ARGMIN: _rewrite_argminmax,
    PrimIDs.TOPK: _rewrite_topk,
    PrimIDs.VAR: _rewrite_var,
    PrimIDs.VAR_MEAN: _rewrite_var,
    PrimIDs.DIV: _prop_div,
    PrimIDs.MUL: _prop_mul,
    PrimIDs.SORT: _drop_with_warning,
    PrimIDs.ARGSORT: _drop_with_warning,
    PrimIDs.FLIP: _drop_with_warning,
    PrimIDs.PAD: _drop_with_warning,
    PrimIDs.SETITEM: _drop_with_warning,
    PrimIDs.INDEX_PUT: _drop_with_warning,
    PrimIDs.SCATTER_ADD: _drop_with_warning,
}

for _pid in _IDENTITY_IDS:
    _HANDLERS[_pid] = _prop_identity


def _install_elementwise_handlers() -> None:
    for _sym in vars(prims).values():
        sym_tags = getattr(_sym, "tags", None)
        sym_id = getattr(_sym, "id", None)
        if not sym_tags or not isinstance(sym_id, PrimIDs) or sym_id in _HANDLERS:
            continue
        if OpTags.ELEMENTWISE_UNARY_OP in sym_tags or OpTags.ELEMENTWISE_BINARY_OP in sym_tags:
            _HANDLERS[sym_id] = _prop_elementwise


_install_elementwise_handlers()
_HANDLERS[PrimIDs.WHERE] = _prop_elementwise


def analyze_crop_plan(trace: TraceCtx, spec) -> list:
    """Provenance-only pass over an already-masked (and possibly
    grad-transformed) trace: which output dims carry padding, and which
    bucket class each belongs to. No rewrites, no trace mutation — backward
    programs are prims too, so the same propagation rules cover cotangent
    flow (the forward masks zero padded cotangents, making cropped grads
    exact)."""
    pm = _PadMasker(trace, spec, analyze_only=True)
    _ntrace, _classes, crop_plan, _warns = pm.run()
    return crop_plan


def thread_pad_masks(trace: TraceCtx, spec):
    """Apply pad-mask threading for symbolic-values caching.

    Returns ``(new_trace, mask_class_ids, crop_plan, warnings)``: the class
    ids name the extra 0-d int32 TRUE-EXTENT inputs appended to the trace's
    args (in order); the crop plan maps flat output leaf indices to
    ``{dim: class_id}`` for post-execution cropping.
    """
    start = time.perf_counter_ns()
    pm = _PadMasker(trace, spec)
    ntrace, mask_classes, crop_plan, warns = pm.run()
    ntrace = wrap_in_trace_provenance(ntrace, "Pad-mask threading (symbolic values)", start)
    return ntrace, mask_classes, crop_plan, warns
