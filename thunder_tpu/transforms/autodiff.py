"""Trace-level reverse-mode autodiff (VJP).

Reference parity: thunder/core/transforms.py — per-prim grad rules
(`augmented_forward_impls:2427` / `backward_impls:2460`), the `grad`
transform (`:1295`), `augmented_forward_pass:3460`, `backward_pass:3491`,
`forward_and_backward_from_trace:3815` — and the saved-for-backward
filtering at `:3930-3963`.

Design (TPU-first simplification): instead of a separate augmented-forward
interpreter, the primal trace is flattened to prim level and the backward is
built by a single reverse walk. Each prim's VJP rule references the primal
trace's *existing* proxies directly (inputs and outputs of the prim), so

- the **joint** grad trace is just primal-prims ++ backward-prims in one
  trace — ideal for staging whole under one ``jax.jit`` (grad-of-jit, the
  CUDA-graphs-style endgame the reference opts into late, as the default);
- the **split** fw/bw traces for the torch-autograd bridge fall out by
  cutting that program in two: saved-for-backward = exactly the primal
  proxies the backward half references.

Rules emit clang ops, so backward traces get the same broadcasting/promotion
treatment as forward ones and remain readable Python.
"""

from __future__ import annotations

import math
import time
from numbers import Number
from typing import Any, Callable, Optional, Sequence

import thunder_tpu.clang as clang
import thunder_tpu.core.prims as prims
from thunder_tpu.core import dtypes
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.core.proxies import NumberProxy, Proxy, TensorProxy, Variable, variableify
from thunder_tpu.core.pytree import tree_flatten, tree_map, tree_unflatten
from thunder_tpu.core.symbol import BoundSymbol
from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx, wrap_in_trace_provenance
from thunder_tpu.transforms.common import dce


# =============================================================================
# Rule registry
# =============================================================================

# prim/symbol id → rule(bsym, *cotangents) -> sequence of grads aligned with
# bsym.args (None for non-differentiable positions). Rules run under the
# backward trace's context and may reference any primal proxy.
_vjp_rules: dict[Any, Callable] = {}
# Optional applicability predicates: rule used only when checker(bsym) is
# truthy; otherwise autodiff descends into the op's decomposition. Lets a
# composite-level rule (e.g. flash-attention SDPA) scope itself to the cases
# a fast backward exists for.
_vjp_checkers: dict[Any, Callable] = {}

NONDIFF = object()  # registered marker: op treated as constant


def grads_by_name(bsym, names: Sequence[str], grad_map: dict):
    """Align a {param_name: grad} map with ``args + kwargs.values()``.

    Composite VJP rules receive operands that may arrive positionally OR as
    keywords depending on the call site; the reverse walk zips grads against
    ``tuple(bsym.args) + tuple(bsym.kwargs.values())``, so a rule must place
    each grad at its operand's actual slot. ``names`` is the composite's
    positional parameter order."""
    flat = [None] * (len(bsym.args) + len(bsym.kwargs))
    pos_of = {nm: i for i, nm in enumerate(names[: len(bsym.args)])}
    for i, nm in enumerate(bsym.kwargs):
        pos_of.setdefault(nm, len(bsym.args) + i)
    for nm, g in grad_map.items():
        if g is not None and nm in pos_of:
            flat[pos_of[nm]] = g
    return flat


def register_vjp(sym_id, checker: Optional[Callable] = None):
    def deco(fn):
        _vjp_rules[sym_id] = fn
        if checker is not None:
            _vjp_checkers[sym_id] = checker
        return fn

    return deco


def register_nondiff(*sym_ids) -> None:
    for sid in sym_ids:
        _vjp_rules[sid] = NONDIFF


def has_vjp(sym_id) -> bool:
    return sym_id in _vjp_rules


# =============================================================================
# Helpers
# =============================================================================


def _zeros_for(t: TensorProxy) -> TensorProxy:
    # Static full() — deliberately NOT zeros_like(t), so the backward half
    # does not hold a reference to (and thus save) the primal proxy.
    return clang.full(t.shape, 0, device=t.device, dtype=t.dtype)


def _unbroadcast(g, shape: tuple):
    """Reduce a cotangent back to ``shape`` after clang-level broadcasting."""
    if tuple(g.shape) == tuple(shape):
        return g
    extra = g.ndim - len(shape)
    if extra > 0:
        g = clang.sum(g, tuple(range(extra)))
    keep = tuple(i for i, s in enumerate(shape) if s == 1 and g.shape[i] != 1)
    if keep:
        g = clang.sum(g, keep, True)
    return g


def _is_float_tensor(x) -> bool:
    return isinstance(x, TensorProxy) and dtypes.is_inexact_dtype(x.dtype)


# =============================================================================
# Rules: data movement
# =============================================================================


@register_vjp(PrimIDs.CONVERT_ELEMENT_TYPE)
def _convert_vjp(bsym, g):
    a, _ = bsym.args
    if not isinstance(a, TensorProxy):
        return (None, None)
    if not dtypes.is_inexact_dtype(a.dtype):
        return (None, None)
    return (clang.maybe_convert_to_dtype(g, a.dtype), None)


@register_vjp(PrimIDs.SHALLOW_COPY)
def _identity_vjp(bsym, g):
    return (g,)


@register_vjp(PrimIDs.DEVICE_PUT)
def _device_put_vjp(bsym, g):
    return (g, None)


register_nondiff(
    PrimIDs.STOP_GRADIENT,
    PrimIDs.TENSOR_CONSTANT,
    PrimIDs.ITEM,
    PrimIDs.FULL,
    PrimIDs.IOTA,
    PrimIDs.UNIFORM,
    PrimIDs.RANDN,
    PrimIDs.UNIFORM_KEYED,
    PrimIDs.RANDN_KEYED,
    PrimIDs.TENSOR_FROM_SEQUENCE,
    PrimIDs.EQ,
    PrimIDs.NE,
    PrimIDs.GE,
    PrimIDs.GT,
    PrimIDs.LE,
    PrimIDs.LT,
    PrimIDs.ISFINITE,
    PrimIDs.ISINF,
    PrimIDs.ISNAN,
    PrimIDs.SIGNBIT,
    PrimIDs.SIGN,
    PrimIDs.FLOOR,
    PrimIDs.CEIL,
    PrimIDs.ROUND,
    PrimIDs.TRUNC,
    PrimIDs.ARGMAX,
    PrimIDs.ARGMIN,
    PrimIDs.ARGSORT,
    PrimIDs.BITWISE_AND,
    PrimIDs.BITWISE_OR,
    PrimIDs.BITWISE_XOR,
    PrimIDs.BITWISE_NOT,
    PrimIDs.BITWISE_LEFT_SHIFT,
    PrimIDs.BITWISE_RIGHT_SHIFT,
    PrimIDs.EMBEDDING_BACKWARD,
    PrimIDs.CONVOLUTION_BWD,
    PrimIDs.UNIFORM_PHILOX,
    PrimIDs.POOL_BWD,
    PrimIDs.IMAG,
)


@register_vjp(PrimIDs.POLYGAMMA)
def _polygamma_vjp(bsym, g):
    n, a = bsym.args
    return (None, clang.mul(g, prims.polygamma(int(n) + 1, a)))


@register_vjp(PrimIDs.POOL)
def _pool_vjp(bsym, g):
    a, kind, window, strides, padding = bsym.args
    return (prims.pool_bwd(g, a, kind, window, strides, padding), None, None, None, None)


# =============================================================================
# Rules: elementwise unary
# =============================================================================


def _unary_rule(fn):
    def rule(bsym, g):
        a = bsym.args[0]
        if not _is_float_tensor(a) and not isinstance(a, TensorProxy):
            return (None,)
        return (fn(a, bsym.output, g),)

    return rule


_SQRT_PI_INV_2 = 2.0 / math.sqrt(math.pi)

_unary_vjps = {
    PrimIDs.NEG: lambda a, out, g: clang.neg(g),
    PrimIDs.EXP: lambda a, out, g: clang.mul(g, out),
    PrimIDs.EXP2: lambda a, out, g: clang.mul(g, clang.mul(out, math.log(2.0))),
    PrimIDs.EXPM1: lambda a, out, g: clang.mul(g, clang.add(out, 1.0)),
    PrimIDs.LOG: lambda a, out, g: clang.true_divide(g, a),
    PrimIDs.LOG1P: lambda a, out, g: clang.true_divide(g, clang.add(a, 1.0)),
    PrimIDs.LOG2: lambda a, out, g: clang.true_divide(g, clang.mul(a, math.log(2.0))),
    PrimIDs.LOG10: lambda a, out, g: clang.true_divide(g, clang.mul(a, math.log(10.0))),
    PrimIDs.SQRT: lambda a, out, g: clang.true_divide(clang.mul(g, 0.5), out),
    PrimIDs.RSQRT: lambda a, out, g: clang.mul(clang.mul(g, -0.5), clang.mul(out, clang.mul(out, out))),
    PrimIDs.RECIPROCAL: lambda a, out, g: clang.neg(clang.mul(g, clang.mul(out, out))),
    PrimIDs.ABS: lambda a, out, g: clang.mul(g, clang.sign(a)),
    PrimIDs.SIN: lambda a, out, g: clang.mul(g, clang.cos(a)),
    PrimIDs.COS: lambda a, out, g: clang.neg(clang.mul(g, clang.sin(a))),
    PrimIDs.TAN: lambda a, out, g: clang.mul(g, clang.add(1.0, clang.mul(out, out))),
    PrimIDs.SINH: lambda a, out, g: clang.mul(g, clang.cosh(a)),
    PrimIDs.COSH: lambda a, out, g: clang.mul(g, clang.sinh(a)),
    PrimIDs.TANH: lambda a, out, g: clang.mul(g, clang.sub(1.0, clang.mul(out, out))),
    PrimIDs.ASIN: lambda a, out, g: clang.true_divide(g, clang.sqrt(clang.sub(1.0, clang.mul(a, a)))),
    PrimIDs.ACOS: lambda a, out, g: clang.neg(clang.true_divide(g, clang.sqrt(clang.sub(1.0, clang.mul(a, a))))),
    PrimIDs.ATAN: lambda a, out, g: clang.true_divide(g, clang.add(1.0, clang.mul(a, a))),
    PrimIDs.ASINH: lambda a, out, g: clang.true_divide(g, clang.sqrt(clang.add(clang.mul(a, a), 1.0))),
    PrimIDs.ACOSH: lambda a, out, g: clang.true_divide(g, clang.sqrt(clang.sub(clang.mul(a, a), 1.0))),
    PrimIDs.ATANH: lambda a, out, g: clang.true_divide(g, clang.sub(1.0, clang.mul(a, a))),
    PrimIDs.ERF: lambda a, out, g: clang.mul(g, clang.mul(_SQRT_PI_INV_2, clang.exp(clang.neg(clang.mul(a, a))))),
    PrimIDs.ERFC: lambda a, out, g: clang.neg(
        clang.mul(g, clang.mul(_SQRT_PI_INV_2, clang.exp(clang.neg(clang.mul(a, a)))))
    ),
    PrimIDs.LGAMMA: lambda a, out, g: clang.mul(g, clang.digamma(a)),
    # d/dx erfinv(x) = sqrt(pi)/2 * exp(erfinv(x)^2)
    PrimIDs.ERFINV: lambda a, out, g: clang.mul(
        g, clang.mul(math.sqrt(math.pi) / 2.0, clang.exp(clang.mul(out, out)))
    ),
    # d/dx digamma(x) = polygamma(1, x)
    PrimIDs.DIGAMMA: lambda a, out, g: clang.mul(g, prims.polygamma(1, a)),
    # real() on a float tensor is the identity (complex autodiff unsupported).
    PrimIDs.REAL: lambda a, out, g: g,
}

for _pid, _fn in _unary_vjps.items():
    _vjp_rules[_pid] = _unary_rule(_fn)


# =============================================================================
# Rules: elementwise binary / ternary
# =============================================================================


def _binary_rule(fa, fb):
    def rule(bsym, g):
        a, b = bsym.args
        ga = fa(a, b, bsym.output, g) if _is_float_tensor(a) else None
        gb = fb(a, b, bsym.output, g) if _is_float_tensor(b) else None
        return (ga, gb)

    return rule


_binary_vjps = {
    PrimIDs.ADD: (lambda a, b, out, g: g, lambda a, b, out, g: g),
    PrimIDs.SUB: (lambda a, b, out, g: g, lambda a, b, out, g: clang.neg(g)),
    PrimIDs.MUL: (lambda a, b, out, g: clang.mul(g, b), lambda a, b, out, g: clang.mul(g, a)),
    PrimIDs.DIV: (
        lambda a, b, out, g: clang.true_divide(g, b),
        lambda a, b, out, g: clang.neg(clang.true_divide(clang.mul(g, a), clang.mul(b, b))),
    ),
    PrimIDs.POW: (
        lambda a, b, out, g: clang.mul(g, clang.mul(b, clang.pow(a, clang.sub(b, 1.0)))),
        # Guard log at a<=0: the d/db branch only matters for a>0 anyway.
        lambda a, b, out, g: clang.mul(g, clang.mul(out, clang.log(clang.maximum(a, 1e-30)))),
    ),
    PrimIDs.MAXIMUM: (
        lambda a, b, out, g: clang.where(clang.ge(a, b), g, 0.0),
        lambda a, b, out, g: clang.where(clang.lt(a, b), g, 0.0),
    ),
    PrimIDs.MINIMUM: (
        lambda a, b, out, g: clang.where(clang.le(a, b), g, 0.0),
        lambda a, b, out, g: clang.where(clang.gt(a, b), g, 0.0),
    ),
    PrimIDs.ATAN2: (
        lambda a, b, out, g: clang.true_divide(clang.mul(g, b), clang.add(clang.mul(a, a), clang.mul(b, b))),
        lambda a, b, out, g: clang.neg(
            clang.true_divide(clang.mul(g, a), clang.add(clang.mul(a, a), clang.mul(b, b)))
        ),
    ),
    PrimIDs.FMOD: (
        lambda a, b, out, g: g,
        lambda a, b, out, g: clang.neg(clang.mul(g, clang.trunc(clang.true_divide(a, b)))),
    ),
    PrimIDs.REMAINDER: (
        lambda a, b, out, g: g,
        lambda a, b, out, g: clang.neg(clang.mul(g, clang.floor(clang.true_divide(a, b)))),
    ),
    PrimIDs.NEXTAFTER: (lambda a, b, out, g: g, lambda a, b, out, g: None),
    # copysign(a, b) = |a|*sgn(b): d/da = sign(a)*sgn(b); b only supplies sign.
    PrimIDs.COPYSIGN: (
        lambda a, b, out, g: clang.mul(
            g, clang.mul(clang.sign(a), clang.where(clang.signbit(b), -1.0, 1.0))
        ),
        lambda a, b, out, g: None,
    ),
    # d/dx zeta(s, x) = -s * zeta(s+1, x); grad wrt s undefined (torch parity).
    PrimIDs.ZETA: (
        lambda a, b, out, g: None,
        lambda a, b, out, g: clang.mul(g, clang.mul(clang.neg(a), prims.zeta(clang.add(a, 1.0), b))),
    ),
}

for _pid, (_fa, _fb) in _binary_vjps.items():
    _vjp_rules[_pid] = _binary_rule(_fa, _fb)


@register_vjp(PrimIDs.WHERE)
def _where_vjp(bsym, g):
    pred, a, b = bsym.args
    ga = clang.where(pred, g, 0.0) if _is_float_tensor(a) else None
    gb = clang.where(pred, 0.0, g) if _is_float_tensor(b) else None
    return (None, ga, gb)


# =============================================================================
# Rules: shape ops
# =============================================================================


@register_vjp(PrimIDs.BROADCAST_IN_DIM)
def _broadcast_in_dim_vjp(bsym, g):
    a, shape, bdims = bsym.args
    if not _is_float_tensor(a):
        return (None, None, None)
    reduce_dims = tuple(d for d in range(len(shape)) if d not in bdims)
    r = clang.sum(g, reduce_dims) if reduce_dims else g
    # r now has rank a.ndim, in bdims order (ascending). Handle size-1 dims.
    keep = tuple(i for i in range(a.ndim) if a.shape[i] == 1 and r.shape[i] != 1)
    if keep:
        r = clang.sum(r, keep, True)
    return (r, None, None)


@register_vjp(PrimIDs.RESHAPE)
def _reshape_vjp(bsym, g):
    a, _ = bsym.args
    return (clang.reshape(g, tuple(a.shape)), None) if _is_float_tensor(a) else (None, None)


@register_vjp(PrimIDs.TRANSPOSE)
def _transpose_vjp(bsym, g):
    a, perm = bsym.args
    if not _is_float_tensor(a):
        return (None, None)
    inv = [0] * len(perm)
    for i, p in enumerate(perm):
        inv[p] = i
    return (clang.permute(g, tuple(inv)), None)


@register_vjp(PrimIDs.SQUEEZE)
def _squeeze_vjp(bsym, g):
    a, _ = bsym.args
    return (clang.reshape(g, tuple(a.shape)), None) if _is_float_tensor(a) else (None, None)


@register_vjp(PrimIDs.FLIP)
def _flip_vjp(bsym, g):
    a, dims = bsym.args
    return (clang.flip(g, tuple(dims)), None) if _is_float_tensor(a) else (None, None)


@register_vjp(PrimIDs.CAT)
def _cat_vjp(bsym, g):
    tensors, dim = bsym.args
    grads = []
    offset = 0
    for t in tensors:
        grads.append(
            clang.slice_in_dim(g, offset, offset + t.shape[dim], dim=dim) if _is_float_tensor(t) else None
        )
        offset += t.shape[dim]
    return (grads, None)


@register_vjp(PrimIDs.SLICE)
def _slice_vjp(bsym, g):
    args = bsym.args
    a, starts, ends = args[0], args[1], args[2]
    strides = args[3] if len(args) > 3 and args[3] is not None else [1] * a.ndim
    if not _is_float_tensor(a):
        return (None,) * len(args)
    config = []
    for d in range(a.ndim):
        out_len = g.shape[d]
        covered = 0 if out_len == 0 else (out_len - 1) * strides[d] + 1
        config.append((starts[d], a.shape[d] - starts[d] - covered, strides[d] - 1))
    return (clang.pad(g, 0.0, config),) + (None,) * (len(args) - 1)


@register_vjp(PrimIDs.PAD)
def _pad_vjp(bsym, g):
    a, _, config = bsym.args
    if not _is_float_tensor(a):
        return (None, None, None)
    # Negative lo/hi crop the input — the cropped elements' grad is zero, so
    # zero-pad the cotangent back out before slicing (slice starts must be
    # non-negative).
    pre_pad = []
    starts, ends, strides = [], [], []
    needs_pre = False
    for gs, s, (lo, hi, dil) in zip(g.shape, a.shape, config):
        d1 = dil + 1
        p = max(0, -int(lo))
        end = int(lo) + (s - 1) * d1 + 1 if s > 0 else int(lo)
        q = max(0, end - int(gs))
        pre_pad.append((p, q, 0))
        needs_pre = needs_pre or p or q
        starts.append(p + int(lo))
        ends.append(p + end)
        strides.append(d1)
    if needs_pre:
        g = prims.pad(g, 0.0, tuple(pre_pad))
    return (prims.slice_prim(g, starts, ends, strides), None, None)


@register_vjp(PrimIDs.SETITEM)
def _setitem_vjp(bsym, g):
    a, key, value = bsym.args
    ga = prims.setitem(g, key, 0.0) if _is_float_tensor(a) else None
    gv = None
    if isinstance(value, TensorProxy) and _is_float_tensor(value):
        gv = clang.getitem(g, key)  # _unbroadcast handles value broadcasting
    return (ga, None, gv)


@register_vjp(PrimIDs.TAKE)
def _take_vjp(bsym, g):
    a, idx, dim = bsym.args
    if not _is_float_tensor(a):
        return (None, None, None)
    if idx.ndim == 0:
        g = clang.unsqueeze(g, dim)
        idx_1d = clang.reshape(idx, (1,))
    else:
        idx_1d = idx
    z = clang.full(tuple(a.shape), 0, device=a.device, dtype=a.dtype)
    if dim != 0:
        z = clang.movedim(z, dim, 0)
        g = clang.movedim(g, dim, 0)
    ga = clang.index_put(z, (idx_1d,), g, accumulate=True)
    if dim != 0:
        ga = clang.movedim(ga, 0, dim)
    return (ga, None, None)


def _scatter_back(a, idx, g, dim):
    z = clang.full(tuple(a.shape), 0, device=a.device, dtype=a.dtype)
    return prims.scatter_add(z, idx, g, dim)


@register_vjp(PrimIDs.TAKE_ALONG_AXIS)
def _take_along_axis_vjp(bsym, g):
    a, idx, dim = bsym.args
    if not _is_float_tensor(a):
        return (None, None, None)
    return (_scatter_back(a, idx, g, dim), None, None)


@register_vjp(PrimIDs.GATHER)
def _gather_vjp(bsym, g):
    a, idx, dim = bsym.args
    if not _is_float_tensor(a):
        return (None, None, None)
    return (_scatter_back(a, idx, g, dim), None, None)


@register_vjp(PrimIDs.TOPK)
def _topk_vjp(bsym, gv, gi=None):
    # (values, indices) outputs; indices are non-differentiable. The values
    # cotangent scatters back to the selected positions (MoE routers etc.).
    a, k, dim = bsym.args[0], bsym.args[1], bsym.args[2]
    if not _is_float_tensor(a) or gv is None:
        return (None, None, None, None, None)
    idx = bsym.output[1]
    z = clang.full(tuple(a.shape), 0, device=a.device, dtype=a.dtype)
    return (prims.scatter_add(z, idx, gv, dim), None, None, None, None)


@register_vjp(PrimIDs.SCATTER_ADD)
def _scatter_add_vjp(bsym, g):
    # Prim signature is (a, indices, value, dim) — grads must align.
    a, idx, val, dim = bsym.args
    ga = g if _is_float_tensor(a) else None
    gv = prims.gather(g, idx, dim) if _is_float_tensor(val) else None
    return (ga, None, gv, None)


@register_vjp(PrimIDs.CUMSUM)
def _cumsum_vjp(bsym, g):
    a, dim = bsym.args
    if not _is_float_tensor(a):
        return (None, None)
    return (clang.flip(prims.cumsum(clang.flip(g, (dim,)), dim), (dim,)), None)


@register_vjp(PrimIDs.CUMPROD)
def _cumprod_vjp(bsym, g):
    # Standard reverse-scan formula: dL/da_i = (sum_{j>=i} g_j * out_j) / a_i.
    # Matches torch autograd's fast path; like it, undefined where a == 0.
    a, dim = bsym.args
    if not _is_float_tensor(a):
        return (None, None)
    out = bsym.output
    w = clang.flip(prims.cumsum(clang.flip(clang.mul(g, out), (dim,)), dim), (dim,))
    return (clang.true_divide(w, a), None)


# =============================================================================
# Rules: reductions
# =============================================================================


def _broadcast_to_input(g, a: TensorProxy, dims: tuple):
    """Expand a reduced cotangent back over the reduced dims of ``a``."""
    shape = list(a.shape)
    for d in dims:
        shape[d] = 1
    g = clang.reshape(g, tuple(shape))
    return clang.expand_to(g, tuple(a.shape))


@register_vjp(PrimIDs.SUM)
def _sum_vjp(bsym, g):
    a, dims = bsym.args
    if not _is_float_tensor(a):
        return (None, None)
    return (_broadcast_to_input(g, a, tuple(dims)), None)


def _minmax_reduction_vjp(bsym, g):
    a, dims = bsym.args
    if not _is_float_tensor(a):
        return (None, None)
    dims = tuple(dims)
    out_b = _broadcast_to_input(bsym.output, a, dims)
    g_b = _broadcast_to_input(g, a, dims)
    mask = clang.maybe_convert_to_dtype(clang.eq(a, out_b), a.dtype)
    count = clang.sum(mask, dims, True)
    return (clang.true_divide(clang.mul(g_b, mask), clang.expand_to(count, tuple(a.shape))), None)


_vjp_rules[PrimIDs.AMAX] = _minmax_reduction_vjp
_vjp_rules[PrimIDs.AMIN] = _minmax_reduction_vjp


@register_vjp(PrimIDs.PROD)
def _prod_vjp(bsym, g):
    a, dims = bsym.args
    if not _is_float_tensor(a):
        return (None, None)
    dims = tuple(dims)
    out_b = _broadcast_to_input(bsym.output, a, dims)
    g_b = _broadcast_to_input(g, a, dims)
    return (clang.true_divide(clang.mul(g_b, out_b), a), None)


def _var_input_grad(a, dims, correction, gv):
    n = 1
    for d in dims:
        n *= a.shape[d]
    m = clang.true_divide(clang.sum(a, dims, True), float(n))
    centered = clang.sub(a, clang.expand_to(m, tuple(a.shape)))
    scale = 2.0 / builtins_max(n - int(correction), 1)
    return clang.mul(_broadcast_to_input(gv, a, dims), clang.mul(centered, scale))


def builtins_max(a, b):
    return a if a > b else b


@register_vjp(PrimIDs.VAR)
def _var_vjp(bsym, g):
    a, dims = bsym.args
    correction = bsym.kwargs.get("correction", 1)
    if not _is_float_tensor(a):
        return (None, None)
    return (_var_input_grad(a, tuple(dims), correction, g), None)


@register_vjp(PrimIDs.VAR_MEAN)
def _var_mean_vjp(bsym, gv, gm):
    a, dims = bsym.args
    correction = bsym.kwargs.get("correction", 1)
    if not _is_float_tensor(a):
        return (None, None)
    dims = tuple(dims)
    n = 1
    for d in dims:
        n *= a.shape[d]
    ga = None
    if gv is not None:
        ga = _var_input_grad(a, dims, correction, gv)
    if gm is not None:
        gmean = clang.mul(_broadcast_to_input(gm, a, dims), 1.0 / float(n))
        ga = gmean if ga is None else clang.add(ga, gmean)
    return (ga, None)


# =============================================================================
# Rules: linear algebra / NN
# =============================================================================


@register_vjp(PrimIDs.MATMUL)
def _matmul_vjp(bsym, g):
    a, b = bsym.args
    ga = gb = None
    if a.ndim == 1 and b.ndim == 1:
        if _is_float_tensor(a):
            ga = clang.mul(g, b)
        if _is_float_tensor(b):
            gb = clang.mul(g, a)
        return (ga, gb)
    # Promote vectors to matrices, compute the matrix rule, then strip.
    a2 = clang.unsqueeze(a, 0) if a.ndim == 1 else a
    b2 = clang.unsqueeze(b, 1) if b.ndim == 1 else b
    g2 = g
    if a.ndim == 1:
        g2 = clang.unsqueeze(g2, -2)
    if b.ndim == 1:
        g2 = clang.unsqueeze(g2, -1)
    if _is_float_tensor(a):
        ga = clang.matmul(g2, clang.transpose(b2, -2, -1))
        ga = _unbroadcast(ga, tuple(a2.shape))
        if a.ndim == 1:
            ga = clang.squeeze(ga, (ga.ndim - 2,))
    if _is_float_tensor(b):
        gb = clang.matmul(clang.transpose(a2, -2, -1), g2)
        gb = _unbroadcast(gb, tuple(b2.shape))
        if b.ndim == 1:
            gb = clang.squeeze(gb, (gb.ndim - 1,))
    return (ga, gb)


@register_vjp(PrimIDs.LINEAR)
def _linear_vjp(bsym, g):
    a, w, bias = bsym.args
    ga = gw = gbias = None
    out_features, in_features = w.shape
    if _is_float_tensor(a):
        ga = clang.matmul(g, w)  # (..., out) @ (out, in) -> (..., in)
    if _is_float_tensor(w):
        batch = 1
        for s in a.shape[:-1]:
            batch *= s
        a2 = clang.reshape(a, (batch, in_features))
        g2 = clang.reshape(g, (batch, out_features))
        gw = clang.matmul(clang.matrix_transpose(g2), a2)
    if bias is not None and _is_float_tensor(bias):
        gbias = clang.sum(g, tuple(range(g.ndim - 1)))
    return (ga, gw, gbias)


@register_vjp(PrimIDs.CONVOLUTION)
def _convolution_vjp(bsym, g):
    a, w, bias, stride, padding, dilation, groups = bsym.args
    da, dw = prims.convolution_bwd(g, a, w, stride, padding, dilation, groups)
    db = None
    if bias is not None and _is_float_tensor(bias):
        # bias broadcasts over (N, *spatial); channel dim is 1.
        db = clang.sum(g, (0,) + tuple(range(2, g.ndim)))
    return (
        da if _is_float_tensor(a) else None,
        dw if _is_float_tensor(w) else None,
        db,
        None, None, None, None,
    )


@register_vjp(PrimIDs.EMBEDDING)
def _embedding_vjp(bsym, g):
    idx, w = bsym.args
    if not _is_float_tensor(w):
        return (None, None)
    return (None, prims.embedding_backward(g, idx, w.shape[0], w.shape[1]))


# =============================================================================
# The reverse walk
# =============================================================================

_SKIP_IDS = {
    PrimIDs.RETURN,
    PrimIDs.DEL,
    PrimIDs.COMMENT,
    PrimIDs.PRINT,
    PrimIDs.UNPACK_TRIVIAL,
    PrimIDs.UNPACK_SEQUENCE,
    PrimIDs.UNPACK_KEY,
    PrimIDs.UNPACK_ATTR,
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA,
    PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    PrimIDs.CHECK_STRING_VALUE,
    PrimIDs.CHECK_LEN,
    PrimIDs.CHECK_NONE,
    PrimIDs.UNPACK_DIM,
    PrimIDs.CHECK_DIM_BUCKET,
}


def flatten_for_autodiff(bsyms: Sequence[BoundSymbol]) -> list[BoundSymbol]:
    """Expand composite bound symbols until each has a VJP rule or is a prim."""
    out: list[BoundSymbol] = []
    for b in bsyms:
        if b.sym.id in _SKIP_IDS:
            continue
        checker = _vjp_checkers.get(b.sym.id)
        rule_ok = b.sym.id in _vjp_rules and (checker is None or _checker_accepts(checker, b))
        if rule_ok or b.sym.is_prim:
            out.append(b)
        elif b.subsymbols:
            out.extend(flatten_for_autodiff(b.subsymbols))
        else:
            # Identity composite (e.g. full-slice getitem): outputs ARE input
            # proxies, nothing to record or differentiate through.
            arg_vars = {variableify(p) for p in b.flat_proxy_args}
            if all(variableify(o) in arg_vars for o in b.flat_proxy_outs):
                continue
            raise NotImplementedError(f"No VJP rule or decomposition for {b.sym.qualname}")
    return out


def _checker_accepts(checker: Callable, bsym: BoundSymbol) -> bool:
    try:
        return bool(checker(*bsym.args, **bsym.kwargs))
    except Exception:
        return False


class BackwardBuilder:
    """Reverse-walks a flattened primal program, emitting VJP ops into the
    active trace and accumulating cotangents per primal proxy."""

    def __init__(self):
        self.env: dict[Variable, Any] = {}

    def seed(self, proxy: TensorProxy, cotangent) -> None:
        self.accumulate(proxy, cotangent)

    def accumulate(self, proxy: Proxy, cotangent) -> None:
        if cotangent is None or not isinstance(proxy, TensorProxy):
            return
        v = variableify(proxy)
        prev = self.env.get(v)
        self.env[v] = cotangent if prev is None else clang.add(prev, cotangent)

    def cotangent_of(self, proxy: Proxy):
        return self.env.get(variableify(proxy))

    def run(self, flat_bsyms: Sequence[BoundSymbol]) -> None:
        for bsym in reversed(flat_bsyms):
            outs = bsym.flat_proxy_outs
            cts = [self.env.get(variableify(o)) for o in outs]
            if not any(c is not None for c in cts):
                continue
            rule = _vjp_rules.get(bsym.sym.id)
            if rule is NONDIFF:
                continue
            if rule is None:
                raise NotImplementedError(f"No VJP rule for prim {bsym.sym.qualname}")
            # Multi-output prims get a cotangent slot per output (None where
            # no gradient flows); single-output prims get exactly one.
            grads = rule(bsym, *cts)
            # Cotangents accumulate onto the FULL binding — positional args
            # first, then kwarg values in recorded order. A composite whose
            # differentiable operand arrived as a keyword (e.g. ltorch.
            # layer_norm(..., weight=w)) would otherwise silently drop its
            # grad (r5: zero LayerNorm grads through the module frontend).
            self._accumulate_grads(
                tuple(bsym.args) + tuple(bsym.kwargs.values()), grads
            )

    def _accumulate_grads(self, args, grads) -> None:
        for a, g in zip(args, grads):
            if g is None:
                continue
            if isinstance(a, (tuple, list)):
                for ai, gi in zip(a, g):
                    if gi is not None and isinstance(ai, TensorProxy):
                        self.accumulate(ai, _unbroadcast_if_needed(gi, ai))
            elif isinstance(a, TensorProxy):
                self.accumulate(a, _unbroadcast_if_needed(g, a))


def _unbroadcast_if_needed(g, a: TensorProxy):
    if isinstance(g, TensorProxy) and tuple(g.shape) != tuple(a.shape):
        return _unbroadcast(g, tuple(a.shape))
    return g


# =============================================================================
# Joint grad trace (thunder_tpu.grad / value_and_grad)
# =============================================================================


def grad_transform(
    trace: TraceCtx,
    *,
    return_value: bool = True,
    wrt: Optional[Sequence[TensorProxy]] = None,
) -> TraceCtx:
    """Primal trace → joint trace computing (value, grads).

    The primal output must be a scalar float tensor (a loss). ``wrt`` defaults
    to the trace's float tensor args marked requires_grad, else all float
    tensor args. Grads are returned in ``wrt`` order.

    Reference parity: the `grad` transform (thunder/core/transforms.py:1295),
    re-designed joint-trace-first for XLA: the whole (fw+bw) program stages
    under one ``jax.jit``, letting XLA schedule and fuse across the
    fw/bw boundary rather than crossing a host autograd engine.
    """
    start = time.perf_counter_ns()
    flat_out, _ = tree_flatten(trace.output)
    out_tensors = [o for o in flat_out if isinstance(o, TensorProxy)]
    check(len(out_tensors) == 1 and out_tensors[0].numel == 1,
          lambda: "grad requires a single scalar tensor output (the loss)")
    loss = out_tensors[0]

    if wrt is None:
        wrt = [a for a in trace.args if _is_float_tensor(a) and a.requires_grad]
        if not wrt:
            wrt = [a for a in trace.args if _is_float_tensor(a)]
    check(len(wrt) > 0, lambda: "grad: no differentiable inputs")

    flat = flatten_for_autodiff(trace.bound_symbols)

    gtrace = from_trace(trace)
    # Extend in place: _scopes[0] aliases bound_symbols, and the reverse walk
    # below records through the scope machinery.
    gtrace.bound_symbols.extend(flat)

    with tracectx(gtrace):
        seed = clang.full(tuple(loss.shape), 1.0, device=loss.device, dtype=loss.dtype)
        builder = BackwardBuilder()
        builder.seed(loss, seed)
        builder.run(flat)
        grads = tuple(
            builder.cotangent_of(p) if builder.cotangent_of(p) is not None else _zeros_for(p) for p in wrt
        )
        result = (trace.output, grads) if return_value else grads
        prims.python_return(result)

    gtrace.output = result
    gtrace = wrap_in_trace_provenance(gtrace, "Grad transform (joint fw+bw)", start)
    return dce(gtrace)


# =============================================================================
# Split fw/bw traces (torch-autograd bridge, remat, distributed passes)
# =============================================================================


def forward_and_backward_from_trace(trace: TraceCtx, *, wrt: Optional[Sequence[TensorProxy]] = None):
    """Primal trace → (fw_trace, bw_trace).

    fw returns (outputs, saved_for_backward); bw takes (saved...,
    cotangents...) and returns grads for ``wrt`` (default: requires_grad
    float args, else all float args).

    Reference parity: transforms.py `forward_and_backward_from_trace:3815` +
    the saved-for-backward filtering `:3930-3963`. Saved-for-backward is
    computed exactly: the primal proxies the emitted backward program
    references.
    """
    start = time.perf_counter_ns()
    flat_out, out_spec = tree_flatten(trace.output)
    out_tensors = [o for o in flat_out if isinstance(o, TensorProxy)]
    check(len(out_tensors) > 0, lambda: "No tensor outputs to differentiate")

    if wrt is None:
        wrt = [a for a in trace.args if _is_float_tensor(a) and a.requires_grad]
        if not wrt:
            wrt = [a for a in trace.args if _is_float_tensor(a)]

    flat = flatten_for_autodiff(trace.bound_symbols)

    # --- backward trace ------------------------------------------------------
    bw_trace = from_trace(trace)
    bw_trace.name = "backward"

    with tracectx(bw_trace):
        cotangents = [TensorProxy(like=o, requires_grad=False, prefix="ct") for o in out_tensors]
        builder = BackwardBuilder()
        for o, ct in zip(out_tensors, cotangents):
            builder.seed(o, ct)
        builder.run(flat)
        grads = tuple(
            builder.cotangent_of(p) if builder.cotangent_of(p) is not None else _zeros_for(p) for p in wrt
        )
        prims.python_return(grads)
    bw_trace.output = grads

    # --- saved-for-backward: primal proxies the backward references ----------
    defined_in_bw: set[str] = {ct.name for ct in cotangents}
    saved_names: list[str] = []
    saved_proxies: list[Proxy] = []
    primal_defined: dict[str, Proxy] = {}
    for a in trace.args:
        if isinstance(a, Proxy):
            primal_defined[a.name] = a
    for b in flat:
        for o in b.flat_proxy_outs:
            primal_defined[o.name] = o
    for b in bw_trace.bound_symbols:
        for o in b.flat_proxy_outs:
            defined_in_bw.add(o.name)
        for a in b.flat_proxy_args:
            if a.name not in defined_in_bw and a.name not in saved_names:
                check(a.name in primal_defined, lambda: f"backward references unknown proxy {a.name}")
                saved_names.append(a.name)
                saved_proxies.append(primal_defined[a.name])

    bw_trace.args = tuple(saved_proxies) + tuple(cotangents)

    # --- forward trace -------------------------------------------------------
    fw_trace = from_trace(trace)
    fw_trace.name = "augmented_forward"
    fw_trace.bound_symbols.extend(flat)
    fw_output = (trace.output, tuple(saved_proxies))
    with tracectx(fw_trace):
        prims.python_return(fw_output)
    fw_trace.output = fw_output

    fw_trace = dce(fw_trace)
    bw_trace = dce(bw_trace)
    fw_trace = wrap_in_trace_provenance(fw_trace, "Augmented forward", start)
    bw_trace = wrap_in_trace_provenance(bw_trace, "Backward from VJP", start)
    fw_trace.tags["saved_for_backward"] = saved_names
    return fw_trace, bw_trace
