"""Rematerialization: trade recompute for saved-for-backward memory.

Reference parity: thunder/core/rematerialization.py — the min-cut
recompute-vs-save decision between forward and backward
(`rematerialize_forward_and_backward:567`). The reference computes a
max-flow min-cut over producer/consumer fusion pairs (igraph, `:245`);
here the same decision is made by a recompute-closure analysis suited to
XLA's cost model: a saved tensor is recomputed in the backward when its
producer closure contains only cheap ops (elementwise / shape / creation /
cast — VPU work XLA fuses for free) and the closure's inputs cost fewer
saved bytes than the tensor itself. Matmul/reduction/random/collective
results are never recomputed (MXU work and nondeterminism stay saved),
which matches the reference's default executor-boundary behaviour.
"""

from __future__ import annotations

import time
from typing import Optional

from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import TensorProxy
from thunder_tpu.core.trace import TraceCtx, from_trace, wrap_in_trace_provenance
from thunder_tpu.transforms.common import dce

# Ops worth recomputing: one VPU pass, fused by XLA into whatever consumes
# them. Everything else (MXU ops, reductions, gathers, RNG, collectives)
# stays saved — except param-gather collectives under ZeRO-3, which
# `remat_collectives=True` marks recomputable (reference:
# rematerialization.py:389 `rematerialize_all_gather`).
_CHEAP_TAGS = {OpTags.ELEMENTWISE_UNARY_OP, OpTags.ELEMENTWISE_BINARY_OP, OpTags.SHAPE_OP}
_CHEAP_IDS = {
    PrimIDs.CONVERT_ELEMENT_TYPE,
    PrimIDs.FULL,
    PrimIDs.IOTA,
    PrimIDs.WHERE,
    PrimIDs.BROADCAST_IN_DIM,
    PrimIDs.SHALLOW_COPY,
}

_MAX_CHAIN = 64  # recompute-chain length bound

# De-opt ladder escalation (resilience/deopt.py, level ≥ 2): under memory
# pressure the ladder widens what counts as recomputable — reductions join
# the cheap set and chains may run 4× longer — trading recompute FLOPs for
# saved-for-backward bytes. RNG/collective/matmul results stay saved in
# both modes (nondeterminism and MXU cost don't become cheap under an OOM).
import contextlib
import contextvars

_aggressive = contextvars.ContextVar("thunder_tpu_remat_aggressive", default=False)
_AGGRESSIVE_EXTRA_TAGS = {OpTags.REDUCTION_OP}


@contextlib.contextmanager
def aggressive_remat():
    """Scope escalated rematerialization (the de-opt ladder's L2 knob)."""
    tok = _aggressive.set(True)
    try:
        yield
    finally:
        _aggressive.reset(tok)


def aggressiveness() -> str:
    return "aggressive" if _aggressive.get() else "normal"


def _max_chain() -> int:
    return _MAX_CHAIN * 4 if _aggressive.get() else _MAX_CHAIN


def _is_cheap(bsym) -> bool:
    if bsym.sym.id in _CHEAP_IDS:
        return True
    if any(t in _CHEAP_TAGS for t in bsym.sym.tags):
        return True
    if _aggressive.get():
        return any(t in _AGGRESSIVE_EXTRA_TAGS for t in bsym.sym.tags)
    return False


def rematerialize_forward_and_backward(
    fw_trace: TraceCtx, bw_trace: TraceCtx, *, remat_collectives: bool = False
):
    """Shrink saved-for-backward by recomputing cheap chains in backward.

    Returns (new_fw, new_bw). fw's output structure stays
    ``(outputs, saved_tuple)``; bw's args stay ``saved... + cotangents...``.

    ``remat_collectives=True`` is the ZeRO-3 seat (reference:
    rematerialization.py:389 + torch_autograd.py:224-228): a param-gathering
    collective (`synchronize`/`all_gather`) whose input is a trace arg (the
    dim-0 shard) counts as recomputable, so the backward re-gathers from the
    shard instead of saving the full parameter — the cut then saves shard
    bytes (free: the shard is already an input) instead of full-param bytes.
    """
    start = time.perf_counter_ns()

    saved_names: list[str] = list(fw_trace.tags.get("saved_for_backward", []))
    if not saved_names:
        return fw_trace, bw_trace

    producers: dict[str, object] = {}
    for bsym in fw_trace.bound_symbols:
        for o in bsym.flat_proxy_outs:
            producers.setdefault(o.name, bsym)

    arg_proxies = {a.name: a for a in fw_trace.args if isinstance(a, TensorProxy)}
    fw_out_flat, _ = _fw_primal_outputs(fw_trace)

    if remat_collectives:
        from thunder_tpu.distributed.prims import DistOpIDs

        _gather_ids = {DistOpIDs.SYNCHRONIZE, DistOpIDs.ALL_GATHER}

        def is_cheap(bsym) -> bool:
            if _is_cheap(bsym):
                return True
            if bsym.sym.id in _gather_ids:
                a = next(iter(bsym.flat_proxy_args), None)
                return a is not None and a.name in arg_proxies
            return False
    else:
        is_cheap = _is_cheap

    # Closure analysis: name → (chain bsyms in topo order, frontier names) or None.
    memo: dict[str, Optional[tuple]] = {}

    def closure(name: str):
        if name in memo:
            return memo[name]
        if name in arg_proxies:
            memo[name] = ([], {name})
            return memo[name]
        bsym = producers.get(name)
        if bsym is None or not is_cheap(bsym):
            memo[name] = None  # must be saved / is a frontier
            return None
        chain: list = []
        frontier: set[str] = set()
        for a in bsym.flat_proxy_args:
            sub = closure(a.name)
            if sub is None:
                frontier.add(a.name)
            else:
                sub_chain, sub_frontier = sub
                for b in sub_chain:
                    if b not in chain:
                        chain.append(b)
                frontier |= sub_frontier
        chain.append(bsym)
        if len(chain) > _max_chain():
            memo[name] = None
            return None
        memo[name] = (chain, frontier)
        return memo[name]

    def size_of(name: str) -> int:
        p = arg_proxies.get(name)
        if p is None:
            b = producers.get(name)
            p = next((o for o in b.flat_proxy_outs if o.name == name), None) if b else None
        return p.size_bytes if isinstance(p, TensorProxy) else 0

    def closure_until(name: str, stops: set[str]):
        """Recompute chain for ``name`` walking cheap producers, stopping at
        ``stops``/args. Returns (chain, frontier) or None if blocked."""
        chain: list = []
        frontier: set[str] = set()
        visiting: set[str] = set()

        def walk(n: str) -> bool:
            if n in stops or n in arg_proxies:
                frontier.add(n)
                return True
            if n in visiting:
                return True
            visiting.add(n)
            b = producers.get(n)
            if b is None or not is_cheap(b):
                return False
            for a in b.flat_proxy_args:
                if not walk(a.name):
                    return False
            if b not in chain:
                chain.append(b)
            return True

        return (chain, frontier) if walk(name) else None

    keep: list[str] = []
    recompute: dict[str, tuple] = {}
    cut_set = _min_cut_saved_set(saved_names, producers, arg_proxies, closure, size_of, is_cheap)

    if cut_set is not None:
        # Min-cut chose the optimal save boundary (possibly mid-chain).
        stops = set(cut_set)
        for name in saved_names:
            if name in cut_set or name in arg_proxies:
                if name not in keep:
                    keep.append(name)
                continue
            c = closure_until(name, stops)
            if c is None or not c[0]:
                keep.append(name)
            else:
                recompute[name] = c
        # Cut nodes that aren't original saved values become new saved values
        # via the recompute frontiers (handled below).
    else:
        for name in saved_names:
            c = closure(name)
            if c is None or not c[0]:
                keep.append(name)
                continue
            chain, frontier = c
            # Greedy fallback: frontier tensors not already saved/args become
            # extra saved values; recompute only if it's a net win in bytes.
            extra = [f for f in frontier if f not in saved_names and f not in arg_proxies and f not in keep]
            extra_bytes = sum(size_of(f) for f in extra)
            if extra_bytes >= size_of(name):
                keep.append(name)
                continue
            recompute[name] = (chain, frontier)

    if not recompute:
        return fw_trace, bw_trace

    # New saved set: kept names + all recompute frontiers not already
    # available. Frontiers are sets — iterate them SORTED so the saved-tuple
    # order (and therefore the staged program's HLO, and therefore the
    # persistent-compile-cache key) is identical across processes; unsorted
    # iteration varies with the per-process hash seed and made every fresh
    # run a cache miss.
    new_saved: list[str] = list(keep)
    for name, (chain, frontier) in recompute.items():
        for f in sorted(frontier):
            if f not in new_saved and f not in arg_proxies:
                new_saved.append(f)
    # Frontier values that are fw *args* must still be passed to bw.
    needed_args = sorted(
        {f for _, (c, fr) in recompute.items() for f in fr if f in arg_proxies}
    )
    for f in needed_args:
        if f not in new_saved:
            new_saved.append(f)

    def proxy_of(name: str) -> TensorProxy:
        if name in arg_proxies:
            return arg_proxies[name]
        b = producers[name]
        return next(o for o in b.flat_proxy_outs if o.name == name)

    # --- rebuild bw: recompute chains (deduped, fw order) + original body ---
    chain_bsyms: list = []
    seen = set()
    for name, (chain, _) in recompute.items():
        for b in chain:
            if id(b) not in seen:
                seen.add(id(b))
                chain_bsyms.append(b)
    fw_order = {id(b): i for i, b in enumerate(fw_trace.bound_symbols)}
    chain_bsyms.sort(key=lambda b: fw_order.get(id(b), 0))

    n_cots = len(bw_trace.args) - len(saved_names)
    cotangents = list(bw_trace.args[len(saved_names):])

    new_bw = from_trace(bw_trace)
    new_bw.args = tuple(proxy_of(n) for n in new_saved) + tuple(cotangents)
    new_bw.bound_symbols.extend(chain_bsyms)
    new_bw.bound_symbols.extend(bw_trace.bound_symbols)
    new_bw = dce(new_bw)

    # --- rebuild fw: same body, new saved tuple in the output -----------------
    new_fw = from_trace(fw_trace)
    primal_out = fw_trace.output[0]
    saved_tuple = tuple(proxy_of(n) for n in new_saved)
    new_fw.bound_symbols.extend(
        b for b in fw_trace.bound_symbols if b.sym.id is not PrimIDs.RETURN
    )
    from thunder_tpu.core import prims as _prims
    from thunder_tpu.core.trace import tracectx

    new_out = (primal_out, saved_tuple)
    with tracectx(new_fw):
        _prims.python_return(new_out)
    new_fw.output = new_out
    new_fw = dce(new_fw)
    new_fw.tags["saved_for_backward"] = list(new_saved)

    new_fw = wrap_in_trace_provenance(new_fw, "Rematerialization (fw)", start)
    new_bw = wrap_in_trace_provenance(new_bw, "Rematerialization (bw)", start)
    return new_fw, new_bw


def _min_cut_saved_set(saved_names, producers, arg_proxies, closure, size_of, is_cheap=_is_cheap):
    """Optimal save boundary via s-t min cut (reference:
    rematerialization.py:245 — igraph max-flow; here the in-repo C++ Dinic,
    thunder_tpu/csrc/mincut.cpp, with a Python fallback).

    Node-split graph over the cheap recompute region:
      S → seed_in (∞) for every available value (fw arg / expensive output),
      v_in → v_out (bytes(v)) for every region proxy — cutting = saving v,
      x_out → w_in (∞) along cheap dataflow,
      v_out → T (∞) for every currently-saved value.
    The min cut is the cheapest set of proxies that separates availability
    from the backward's needs; everything on the sink side recomputes.
    Returns the save set (names), or None when the region is trivial.
    """
    try:
        from thunder_tpu.transforms.mincut import INF_CAP, min_cut
    except Exception:
        return None

    # Region discovery: union of all saved values' cheap closures.
    region: set[str] = set()
    seeds: set[str] = set()
    targets: set[str] = set()
    for name in saved_names:
        c = closure(name)
        if c is None or not c[0]:
            seeds.add(name)
            targets.add(name)
            continue
        chain, frontier = c
        targets.add(name)
        seeds |= frontier
        region.add(name)
        for b in chain:
            for o in b.flat_proxy_outs:
                region.add(o.name)
            for a in b.flat_proxy_args:
                region.add(a.name)
    if not region or len(region) > 4096:
        return None

    all_nodes = sorted(region | seeds | targets)
    idx: dict[str, int] = {}
    n = 2  # 0 = S, 1 = T
    for name in all_nodes:
        idx[name] = n
        n += 2  # v_in = idx, v_out = idx + 1

    edges: list[tuple] = []
    for name in all_nodes:
        vi, vo = idx[name], idx[name] + 1
        cap = max(size_of(name), 1)
        edges.append((vi, vo, cap))
        if name in seeds or name in arg_proxies:
            edges.append((0, vi, INF_CAP))
        if name in targets:
            edges.append((vo, 1, INF_CAP))
        b = producers.get(name)
        if name not in seeds and name not in arg_proxies and b is not None and is_cheap(b):
            for a in b.flat_proxy_args:
                if a.name in idx:
                    edges.append((idx[a.name] + 1, vi, INF_CAP))

    try:
        _, source_side = min_cut(n, edges, 0, 1)
    except Exception:
        return None

    cut = {name for name in all_nodes if idx[name] in source_side and idx[name] + 1 not in source_side}
    if not cut:
        return None
    return cut


def _fw_primal_outputs(fw_trace: TraceCtx):
    from thunder_tpu.core.pytree import tree_flatten

    out = fw_trace.output
    primal = out[0] if isinstance(out, tuple) and len(out) == 2 else out
    return tree_flatten(primal)
