"""Certificate-driven static collective-overlap scheduler.

The pass that spends the trust layer on speed (ROADMAP open item 2): PR 10's
:class:`~thunder_tpu.analysis.schedule.ScheduleCertificate` computes, per
collective dispatch site, the legal placement interval ``[earliest, latest]``
under data deps, future/wait pairing, per-axis program order, and in-place
anti-dependencies. This pass consults those intervals, prices candidate
placements with the PR 5 cost model (ICI wire time vs the roofline compute
time of the bsyms a placement would overlap), and **moves each site to
maximize its predicted hidden wire time**:

- an fsdp ``synchronize`` (trace-level all-gather) hoists ahead of the
  compute that precedes its consuming GEMM — an async prefetch whose
  transfer is in flight while earlier layers compute;
- a grad ``reduce_scatter`` is consumed only by the return, so its window
  already spans the remaining backward GEMMs — it stays put (sinking it
  would shrink the window), and the predictor proves the hiding.

Moves are constrained by the static liveness planner
(``analysis/liveness.py``): hoisting a gather materializes the full tensor
earlier, so a move that pushes ``predicted_peak_bytes`` past the device
capacity is walked back toward its original position until the plan fits
(recorded as a back-off), never applied blind.

Every rewrite is re-stamped via ``schedule.recertify`` — the scheduler is
the *one* pass licensed to re-bless a collective order — and verified by
the PR 1 lint rules; the ``sched.exposed-collective`` advisory rule reports
the per-site predicted hidden/exposed µs the pass leaves behind (the
compile-time twin of the measured lane segmentation in
``observability/attribution.py``, which ``scripts/bench_multichip.py``
joins against this pass's report).

The pass is **advisory-safe**: any internal failure — including a chaos
``sched_bad`` seam corrupting a placement, which the interval validation
catches — falls back to the unscheduled trace with a ``sharp_edge`` event,
and the de-opt ladder disables the pass from L1 up (a bad schedule demotes
cleanly instead of wedging a compile). Kill switch:
``THUNDER_TPU_COMM_SCHEDULE=0``.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.core.trace import TraceCtx, from_trace, wrap_in_trace_provenance

ENV_KNOB = "THUNDER_TPU_COMM_SCHEDULE"

PASS_NAME = "Comm schedule"


def enabled(default: bool = True) -> bool:
    """Whether the scheduler runs (``THUNDER_TPU_COMM_SCHEDULE``; default
    on — the pass is a no-op on traces without collectives)."""
    v = os.environ.get(ENV_KNOB, "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "off", "no")


class PlacementError(ValueError):
    """A requested placement falls outside the site's certified
    ``[earliest, latest]`` interval — applying it could deadlock the mesh
    (cross-host order divergence) or read stale buffers."""


@dataclass
class SiteMove:
    """One site's scheduling outcome (JSON-able via ``to_dict``)."""

    key: str
    sym: str
    axis: Optional[str]
    index_before: int
    index_after: int
    earliest: int
    latest: int
    first_consumer: Optional[int]
    wire_us: float
    hidden_us_before: float
    hidden_us_after: float
    window_us_after: float
    backed_off: bool = False
    # True only when the SCHEDULER placed this site (a site can still drift
    # by an index when another site is hoisted across it — not a move).
    moved: bool = False

    @property
    def exposed_us_after(self) -> float:
        return max(0.0, self.wire_us - self.hidden_us_after)

    def to_dict(self) -> dict:
        return {
            "key": self.key, "sym": self.sym, "axis": self.axis,
            "from": self.index_before, "to": self.index_after,
            "earliest": self.earliest, "latest": self.latest,
            "first_consumer": self.first_consumer,
            "wire_us": round(self.wire_us, 3),
            "hidden_us_before": round(self.hidden_us_before, 3),
            "hidden_us_after": round(self.hidden_us_after, 3),
            "exposed_us_after": round(self.exposed_us_after, 3),
            "window_us_after": round(self.window_us_after, 3),
            "moved": self.moved, "backed_off": self.backed_off,
        }


@dataclass
class CommSchedule:
    """The pass's report: per-site moves + trace-level predicted overlap,
    stamped on the scheduled trace as ``tags["comm_schedule"]`` (a plain
    dict) for the bench/cache_info to read."""

    device: str
    sites: list = field(default_factory=list)   # SiteMove
    predicted_peak_bytes_before: Optional[int] = None
    predicted_peak_bytes_after: Optional[int] = None
    capacity_bytes: Optional[int] = None

    @property
    def moves(self) -> int:
        return sum(1 for s in self.sites if s.moved)

    @property
    def backoffs(self) -> int:
        return sum(1 for s in self.sites if s.backed_off)

    @property
    def wire_us(self) -> float:
        return sum(s.wire_us for s in self.sites)

    @property
    def hidden_us_before(self) -> float:
        return sum(s.hidden_us_before for s in self.sites)

    @property
    def hidden_us_after(self) -> float:
        return sum(s.hidden_us_after for s in self.sites)

    @property
    def exposed_pct_before(self) -> float:
        w = self.wire_us
        return (w - self.hidden_us_before) / w * 100.0 if w else 0.0

    @property
    def exposed_pct_after(self) -> float:
        w = self.wire_us
        return (w - self.hidden_us_after) / w * 100.0 if w else 0.0

    def to_tag(self) -> dict:
        return {
            "device": self.device,
            "moves": self.moves,
            "backoffs": self.backoffs,
            "wire_us": round(self.wire_us, 3),
            "hidden_us_before": round(self.hidden_us_before, 3),
            "hidden_us_after": round(self.hidden_us_after, 3),
            "exposed_pct_before": round(self.exposed_pct_before, 2),
            "exposed_pct_after": round(self.exposed_pct_after, 2),
            "predicted_peak_bytes_before": self.predicted_peak_bytes_before,
            "predicted_peak_bytes_after": self.predicted_peak_bytes_after,
            "capacity_bytes": self.capacity_bytes,
            "sites": [s.to_dict() for s in self.sites],
        }

    def format(self) -> str:
        lines = [
            f"comm schedule [{self.device}]: {self.moves} move(s), "
            f"{self.backoffs} back-off(s); predicted exposed "
            f"{self.exposed_pct_before:.1f}% -> {self.exposed_pct_after:.1f}% "
            f"of {self.wire_us:.1f}us wire",
        ]
        for s in self.sites:
            arrow = (f"L{s.index_before}->L{s.index_after}" if s.moved
                     else f"L{s.index_before} (pinned)" if s.earliest == s.latest
                     else f"L{s.index_before}")
            note = " BACKED-OFF" if s.backed_off else ""
            lines.append(
                f"  {s.sym:<16} [{s.axis or '-':<5}] {arrow:<12} "
                f"wire {s.wire_us:>8.2f}us hidden {s.hidden_us_before:>8.2f}"
                f"->{s.hidden_us_after:<8.2f}us{note}"
            )
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.format()


def _move(bsyms: list, i: int, p: int) -> list:
    """A new bsym list with the op at ``i`` re-placed at position ``p``."""
    out = list(bsyms)
    b = out.pop(i)
    out.insert(p, b)
    return out


def _validate_placement(site, position: int) -> None:
    """THE interval check — one copy, shared by :func:`apply_placement`
    and the scheduler's own move application, so the seeded-bad rejection
    (chaos ``sched_bad``) cannot drift between the two."""
    if not (site.earliest <= position <= site.latest):
        raise PlacementError(
            f"placement L{position} for {site.key} outside its certified "
            f"interval [L{site.earliest}, L{site.latest}] — refusing an "
            "unprovable reorder"
        )


def apply_placement(trace: TraceCtx, site_key: str, position: int) -> TraceCtx:
    """Move one collective site to ``position``, validating against a fresh
    certificate: a placement outside the site's ``[earliest, latest]``
    interval raises :class:`PlacementError` (the seeded-bad rejection the
    scheduler and its tests rely on). Returns a new re-certified trace."""
    from thunder_tpu.analysis import schedule as sched_mod

    cert = sched_mod.certify(trace)
    site = next((s for s in cert.sites if s.key == site_key), None)
    if site is None:
        raise PlacementError(f"no collective site with key {site_key!r}")
    _validate_placement(site, position)
    new = from_trace(trace)
    new.bound_symbols = _move(list(trace.bound_symbols), site.index, position)
    sched_mod.recertify(new)
    return new


def schedule_collectives(
    trace: TraceCtx,
    *,
    device: Any = None,
    capacity_bytes: Optional[int] = None,
    arg_divisors: Optional[dict] = None,
) -> tuple[TraceCtx, Optional[CommSchedule]]:
    """Schedule ``trace``'s collectives for compute/comm overlap.

    Returns ``(scheduled trace, report)``. The input trace is returned
    unchanged (report may still be attached) when there is nothing to move;
    on any internal failure the unscheduled trace comes back with a
    ``sharp_edge`` event — the pass is advisory and must never break a
    compile. Run it on the **claimed, pre-del** execution trace (explicit
    ``python_del``s would need re-derivation; ``del_last_used`` runs after).

    ``capacity_bytes`` overrides the detected device capacity for the
    liveness back-off; ``arg_divisors`` divides sharded input buffers
    (``analysis/liveness.arg_divisors_from_specs``) so the back-off prices
    per-device bytes on mesh traces."""
    start = time.perf_counter_ns()
    try:
        return _schedule(trace, device=device, capacity_bytes=capacity_bytes,
                         arg_divisors=arg_divisors, start_ns=start)
    except Exception as e:  # noqa: BLE001 — advisory: fall back, never wedge
        try:
            from thunder_tpu.observability import events as obs_events

            obs_events.emit_event(
                "sharp_edge",
                message=(
                    f"comm_schedule rejected for {trace.name}: "
                    f"{type(e).__name__}: {e} — compiling the unscheduled "
                    "certified order"
                ),
                policy="comm_schedule_fallback",
            )
        except Exception:  # noqa: BLE001
            pass
        return trace, None


def _schedule(trace: TraceCtx, *, device, capacity_bytes, arg_divisors,
              start_ns) -> tuple[TraceCtx, Optional[CommSchedule]]:
    from thunder_tpu.analysis import schedule as sched_mod
    from thunder_tpu.analysis.cost import resolve_device_spec
    from thunder_tpu.analysis.liveness import device_capacity_bytes, plan_liveness
    from thunder_tpu.distributed.prims import is_collective_bsym
    from thunder_tpu.resilience import chaos as chaos_mod

    bsyms = list(trace.bound_symbols)
    if not any(is_collective_bsym(b) for b in bsyms):
        return trace, None
    if any(b.sym.id is PrimIDs.DEL for b in bsyms):
        # Scheduling runs pre-del (the pipeline's del_last_used re-derives
        # dels afterwards); a del-carrying trace would need its dels moved
        # with the ops — refuse rather than risk a stale free.
        return trace, None

    dev = resolve_device_spec(device)
    capacity = capacity_bytes if capacity_bytes is not None else (
        device_capacity_bytes(dev)
    )

    def plan_peak(bs) -> Optional[int]:
        cand = from_trace(trace)
        cand.bound_symbols = bs
        return int(plan_liveness(
            cand, device=dev, arg_divisors=arg_divisors, include_rows=False
        ).peak_bytes)

    report = CommSchedule(device=dev.name)
    base_pred = sched_mod.predict_overlap(
        _as_trace(trace, bsyms), device=dev
    )
    try:
        base_peak = plan_peak(bsyms)
    except Exception:  # noqa: BLE001 — no liveness means no back-off, not no pass
        base_peak = None
    report.predicted_peak_bytes_before = base_peak
    report.capacity_bytes = int(capacity) if capacity else None

    # Sites by descending wire time: the biggest transfers claim the compute
    # budget (and the liveness headroom) first.
    order = [s.key for s in sorted(base_pred.sites, key=lambda s: -s.wire_us)]
    cur_peak = base_peak
    moves: dict[str, SiteMove] = {}

    # cert/pred only change when a move lands — recompute on demand, not
    # per site (a deep trace has dozens of sites; each recompute is a full
    # O(trace) analysis inside the timed static_analysis phase).
    cert = pred = None

    for key in order:
        if cert is None:
            cert = sched_mod.certify(_as_trace(trace, bsyms))
            pred = sched_mod.predict_overlap(
                _as_trace(trace, bsyms), device=dev, cert=cert
            )
        site = next((s for s in cert.sites if s.key == key), None)
        so = pred.by_key().get(key)
        if site is None or so is None:
            continue
        move = SiteMove(
            key=key, sym=site.sym, axis=site.axis,
            index_before=site.index, index_after=site.index,
            earliest=site.earliest, latest=site.latest,
            first_consumer=site.first_consumer,
            wire_us=so.wire_us, hidden_us_before=so.hidden_us,
            hidden_us_after=so.hidden_us, window_us_after=so.window_us,
        )
        moves[key] = move
        if so.wire_us <= 0.0 or site.first_consumer is None:
            continue
        if so.hidden_us >= so.wire_us or site.earliest >= site.index:
            continue  # already fully hidden, or nowhere to hoist

        # Hoist: latest position whose grown window fully hides the wire;
        # all the way to `earliest` when none does (maximal window). New
        # window rows are priced at the prediction's RESIDUAL budget, so a
        # GEMM an earlier (bigger-wire) site already claimed is not counted
        # toward this site's hiding.
        p = site.earliest
        gained = 0.0
        for q in range(site.index - 1, site.earliest - 1, -1):
            gained += pred.residual_budget.get(q, 0.0)
            if so.hidden_us + gained >= so.wire_us:
                p = q
                break
        p = chaos_mod.sched_seam(key, p, site.latest)
        _validate_placement(site, p)

        # Liveness back-off: retreat the hoist toward the original index
        # until the predicted per-device peak fits the capacity (a hoisted
        # gather materializes the full tensor earlier — the plan sees it).
        # The peak is non-increasing as the placement retreats, so binary
        # search finds the deepest fitting hoist in O(log distance) plans
        # instead of one O(trace) replan per index.
        def peak_at(pos):
            try:
                return plan_peak(_move(bsyms, site.index, pos))
            except Exception:  # noqa: BLE001
                return None

        def fits(pos) -> bool:
            if not capacity or cur_peak is None:
                return True
            peak = peak_at(pos)
            return peak is None or peak <= capacity or peak <= cur_peak

        wanted = p
        if not fits(p):
            lo, hi = p + 1, site.index  # fits(site.index) trivially: no move
            while lo < hi:
                mid = (lo + hi) // 2
                if fits(mid):
                    hi = mid
                else:
                    lo = mid + 1
            p = hi
        move.backed_off = p != wanted
        if p >= site.index:
            continue  # backed off all the way: no move survives the squeeze
        chosen = (_move(bsyms, site.index, p), peak_at(p))
        bsyms, cur_peak = chosen[0], (
            chosen[1] if chosen[1] is not None else cur_peak
        )
        move.index_after = p
        move.moved = True
        cert = pred = None  # positions shifted: re-derive before the next site

    if not any(m.moved for m in moves.values()):
        report.sites = [moves[k] for k in sorted(moves, key=lambda k: moves[k].index_before)]
        report.predicted_peak_bytes_after = base_peak
        trace.tags["comm_schedule"] = report.to_tag()
        return trace, report

    new = from_trace(trace)
    new.bound_symbols = bsyms
    # The scheduler is the pass licensed to re-bless the order it proved:
    # re-stamp via recertify so the sched.uncertified-reorder rule accepts
    # the new baseline (per-axis order is preserved by construction — same-
    # axis peers bound each other's intervals).
    final_cert = sched_mod.recertify(new)
    final_pred = sched_mod.predict_overlap(new, device=dev, cert=final_cert)
    by_key = final_pred.by_key()
    for m in moves.values():
        so = by_key.get(m.key)
        if so is not None:
            m.index_after = so.index
            m.hidden_us_after = so.hidden_us
            m.window_us_after = so.window_us
    report.sites = sorted(moves.values(), key=lambda m: m.index_after)
    report.predicted_peak_bytes_after = cur_peak
    new.tags["comm_schedule"] = report.to_tag()
    return wrap_in_trace_provenance(new, PASS_NAME, start_ns), report


def _as_trace(template: TraceCtx, bsyms: list) -> TraceCtx:
    t = from_trace(template)
    t.bound_symbols = bsyms
    return t
