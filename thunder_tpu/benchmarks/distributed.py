"""Distributed benchmark runner: sweep mesh configurations, one subprocess
per config.

Reference parity: thunder/benchmarks/distributed.py (`run_multiprocess_benchmark
:605` — spawns one process per rank over NCCL and aggregates). On TPU a mesh
is driven by a single controller, so "multiprocess per rank" becomes one
subprocess per *mesh configuration* (clean jax runtime each), either on the
real device set or on a virtual CPU mesh (``--virtual N``) — the same
no-hardware story the tests use.

Usage:
    python -m thunder_tpu.benchmarks.distributed --model pythia-160m \
        --configs dp8,fsdp8,fsdp4-tp2,dp2-fsdp2-tp2 --virtual 8 --iters 5

Each config line prints the litgpt CLI's JSON summary (tokens/sec,
TFLOP/s → MFU, memory, iteration time) tagged with the mesh.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def parse_config(spec: str) -> dict:
    """'dp2-fsdp2-tp2' → {'dp': 2, 'fsdp': 2, 'tp': 2}."""
    import re

    axes: dict[str, int] = {}
    for part in spec.split("-"):
        m = re.fullmatch(r"(dp|pp|fsdp|ep|sp|tp)(\d+)", part)
        if not m:
            raise ValueError(f"Bad mesh spec {spec!r} (part {part!r})")
        if m.group(1) in axes:
            raise ValueError(f"Duplicate axis {m.group(1)!r} in mesh spec {spec!r}")
        axes[m.group(1)] = int(m.group(2))
    return axes


def run_config(spec: str, *, model: str, micro_batch: int, seq: int, iters: int,
               virtual: int = 0) -> dict:
    try:
        axes = parse_config(spec)
    except ValueError as e:
        return {"mesh": spec, "error": str(e)}
    cmd = [
        sys.executable, "-m", "thunder_tpu.benchmarks.litgpt",
        "--model", model, "--micro-batch", str(micro_batch), "--seq", str(seq),
        "--iters", str(iters),
    ]
    for ax, n in axes.items():
        if ax in ("dp", "fsdp", "tp"):
            cmd += [f"--{ax}", str(n)]
        else:
            return {"mesh": spec, "error": f"axis {ax} not exposed by the litgpt CLI"}

    env = dict(os.environ)
    if virtual:
        # Clean CPU-mesh runtime: drop any site package that pins the real
        # accelerator and force N virtual devices.
        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env = {
            "PATH": env.get("PATH", "/usr/bin:/bin"),
            "HOME": env.get("HOME", "/root"),
            "PYTHONPATH": repo,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={virtual}",
        }

    try:
        r = subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=1800)
    except subprocess.TimeoutExpired:
        return {"mesh": spec, "error": "timed out after 1800 s"}
    if r.returncode != 0:
        return {"mesh": spec, "error": r.stderr[-500:]}
    try:
        out = json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"mesh": spec, "error": f"unparseable output: {r.stdout[-300:]}"}
    out["mesh"] = spec
    return out


def weak_scaling(*, model: str, micro_batch: int, seq: int, iters: int,
                 axis: str = "dp", max_devices: int = 8) -> list[dict]:
    """Weak-scaling sweep over the virtual mesh: device count doubles while
    the PER-DEVICE batch stays constant, so ideal scaling is flat iteration
    time and linear total tokens/sec (reference: distributed.py:605's
    multi-rank sweeps answer the same question over NCCL). Each point runs
    in its own subprocess with an N-virtual-CPU-device runtime."""
    points = []
    n = 1
    while n <= max_devices:
        spec = f"{axis}{n}" if n > 1 else "dp1"
        out = run_config(
            spec, model=model, micro_batch=micro_batch * n, seq=seq,
            iters=iters, virtual=max(n, 1),
        )
        out["devices"] = n
        out["global_batch"] = micro_batch * n
        base = points[0] if points else out
        if "tokens_per_sec" in out and "tokens_per_sec" in base:
            out["scaling_efficiency"] = round(
                out["tokens_per_sec"] / (base["tokens_per_sec"] * n), 3
            )
        points.append(out)
        n *= 2
    return points


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="pythia-160m")
    p.add_argument("--micro-batch", type=int, default=4)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--configs", default="dp8,fsdp8,fsdp4-tp2")
    p.add_argument("--virtual", type=int, default=0,
                   help="run each config on an N-virtual-CPU-device mesh")
    p.add_argument("--weak-scaling", default="",
                   help="axis to weak-scale over the virtual mesh (dp|fsdp): "
                        "1→N devices, constant per-device batch")
    args = p.parse_args()

    if args.weak_scaling:
        for point in weak_scaling(
            model=args.model, micro_batch=args.micro_batch, seq=args.seq,
            iters=args.iters, axis=args.weak_scaling,
        ):
            print(json.dumps(point), flush=True)
        return

    for spec in args.configs.split(","):
        spec = spec.strip()
        if not spec:
            continue
        summary = run_config(
            spec, model=args.model, micro_batch=args.micro_batch,
            seq=args.seq, iters=args.iters, virtual=args.virtual,
        )
        print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
