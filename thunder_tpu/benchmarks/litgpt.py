"""LitGPT-style end-to-end training benchmark CLI.

Reference parity: thunder/benchmarks/benchmark_litgpt.py:41 — model-name ×
batch × seq × distributed-config training benchmark reporting iteration
time, tokens/sec, TFLOP/s → MFU, and peak memory — plus the executor-matrix
comparison the reference publishes as its eager/inductor/thunder columns
(examples/lit-gpt/README.md): here the columns are executor stacks
(jax-only baseline → +flash → +pallas → +norm → +quant).

Usage:
    python -m thunder_tpu.benchmarks.litgpt --model pythia-160m \
        --micro-batch 4 --seq 1024 --iters 10 [--fsdp 8] [--tp 2] [--dp 2] \
        [--forward-only] [--dtype bfloat16]

    # executor-matrix comparison → markdown table (BENCHMARKS.md source):
    python -m thunder_tpu.benchmarks.litgpt --model pythia-410m --matrix \
        --micro-batch 4 --seq 2048 --iters 10 --markdown
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

# Executor stacks for --matrix, ordered baseline → full. Names resolve via
# thunder_tpu.extend; "pallas,flash,jax" is the registered default list.
# norm and quant are opt-in executors.
MATRIX_STACKS: tuple[tuple[str, str], ...] = (
    ("jax", "jax"),
    ("+flash", "flash,jax"),
    ("+pallas (default)", "pallas,flash,jax"),
    ("+norm", "norm,pallas,flash,jax"),
    ("+quant int8", "quant,pallas,flash,jax"),
)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="pythia-160m")
    p.add_argument("--micro-batch", type=int, default=4)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--forward-only", action="store_true")
    p.add_argument("--pipelined", action="store_true",
                   help="async-dispatch all iters, one final sync (amortizes "
                        "the axon tunnel's per-sync host round-trip)")
    p.add_argument("--optimizer", default="adamw", choices=("adamw", "sgd"))
    p.add_argument("--executors", default="",
                   help="comma list, e.g. quant,flash,pallas,jax (TE-seat "
                        "quantized-training evidence runs)")
    p.add_argument("--matrix", action="store_true",
                   help="run the executor-stack comparison matrix")
    p.add_argument("--markdown", action="store_true",
                   help="emit a markdown table (with --matrix)")
    return p.parse_args(argv)


def run_one(args, executors=None):
    """One benchmark configuration → summary dict."""
    from thunder_tpu.benchmarks import (
        count_params,
        forward_flops_per_token,
        run_benchmark,
        training_flops_per_token,
    )
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m

    cfg = m.name_to_config(args.model)
    seq = min(args.seq, cfg.block_size)
    params = m.init_params(cfg, dtype=dtypes.to_dtype(args.dtype), device_init=True, seed=0)
    n_params = count_params(params)

    rng = np.random.RandomState(0)
    idx = rng.randint(0, cfg.vocab_size, (args.micro_batch, seq)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)
    tokens = args.micro_batch * seq

    mesh = None
    if args.dp * args.fsdp * args.tp > 1:
        from thunder_tpu.parallel import make_mesh
        from thunder_tpu.parallel.sharding import gpt_param_specs, shard_pytree

        mesh = make_mesh(dp=args.dp, fsdp=args.fsdp, tp=args.tp)
        specs = gpt_param_specs(cfg, mesh)
        params = shard_pytree(params, mesh, specs)

    ex_list = [e for e in (executors or "").split(",") if e] or None

    if args.forward_only:
        import jax

        from thunder_tpu.api import trace_program
        from thunder_tpu.core.pytree import tree_flatten
        from thunder_tpu.executors.passes import transform_for_execution
        from thunder_tpu.extend import resolve_executors
        from thunder_tpu.transforms.common import dce

        fn = lambda p, i: m.forward(p, i, cfg)  # noqa: E731
        _, comp = trace_program(fn, (params, idx), {})
        ex = transform_for_execution(dce(comp), resolve_executors(ex_list))
        jfn = jax.jit(ex.python_callable())
        flat, _ = tree_flatten(((params, idx), {}))
        result = run_benchmark(
            f"{args.model}-fwd", lambda: jfn(*flat), warmup=args.warmup, iters=args.iters,
            tokens_per_iter=tokens, flops_per_iter=forward_flops_per_token(n_params) * tokens,
            pipelined=args.pipelined,
        )
        losses = None
    else:
        from thunder_tpu.parallel import build_train_step
        from thunder_tpu.parallel.sharding import gpt_param_specs

        specs = gpt_param_specs(cfg, mesh) if mesh is not None else None
        step, opt = build_train_step(
            cfg, params, idx, tgt, mesh=mesh, param_specs=specs, lr=args.lr,
            donate=(args.optimizer == "sgd"), grads_in_f32=(args.optimizer != "sgd"),
            executors=ex_list, optimizer=args.optimizer,
        )
        state = {"params": params, "opt": opt}
        losses = []

        def one_step():
            state["params"], state["opt"], loss = step(state["params"], state["opt"], idx, tgt)
            losses.append(loss)
            return loss

        result = run_benchmark(
            f"{args.model}-train", one_step, warmup=args.warmup, iters=args.iters,
            tokens_per_iter=tokens, flops_per_iter=training_flops_per_token(n_params) * tokens,
            pipelined=args.pipelined,
        )

    summary = result.summary()
    if losses is not None:
        summary["loss_first"] = round(float(np.asarray(losses[0])), 4)
        summary["loss_last"] = round(float(np.asarray(losses[-1])), 4)
    if executors:
        summary["executors"] = executors
    summary["n_params"] = n_params
    summary["mesh"] = {"dp": args.dp, "fsdp": args.fsdp, "tp": args.tp}
    return summary


def _matrix_markdown(args, rows) -> str:
    from thunder_tpu.benchmarks import tpu_generation

    mode = "fwd" if args.forward_only else "train"
    lines = [
        f"### {args.model} {mode} — B={args.micro_batch} T={args.seq} "
        f"dtype={args.dtype} iters={args.iters} ({tpu_generation()})",
        "",
        "| executors | avg iter (s) | median (s) | tokens/s | TFLOP/s | MFU | mem (GB) | loss (first→last) |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for label, s in rows:
        loss = (f"{s['loss_first']}→{s['loss_last']}" if "loss_first" in s else "—")
        lines.append(
            f"| {label} | {s.get('average_iter_time_s', '—')} "
            f"| {s.get('median_iter_time_s', '—')} "
            f"| {s.get('tokens_per_sec', '—')} | {s.get('model_tflop_per_sec', '—')} "
            f"| {s.get('mfu', '—')} | {s.get('memory_used_GB', '—')} | {loss} |"
        )
    return "\n".join(lines)


def main(argv=None) -> None:
    args = parse_args(argv)

    if not args.matrix:
        print(json.dumps(run_one(args, args.executors or None)))
        return

    rows = []
    for label, stack in MATRIX_STACKS:
        try:
            summary = run_one(args, stack)
        except Exception as e:  # a stack that can't run here (e.g. quant on CPU)
            print(f"# {label}: skipped ({type(e).__name__}: {e})", file=sys.stderr)
            continue
        rows.append((label, summary))
        print(f"# {label}: {json.dumps(summary)}", file=sys.stderr)

    if args.markdown:
        print(_matrix_markdown(args, rows))
    else:
        print(json.dumps({label: s for label, s in rows}))


if __name__ == "__main__":
    main()
