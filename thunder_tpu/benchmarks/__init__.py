"""Benchmark harness: timing, throughput, and MFU statistics.

Reference parity: thunder/benchmarks/__init__.py (`Benchmark:72`, timing
machinery `_benchmark:238`) and the LitGPT end-to-end metrics of
benchmark_litgpt.py:348-367 — `average_iter_time`, `tokens_per_sec`
(= global_batch × seq_len / iter_time), `model_flop_per_sec` (→ MFU against
chip peak), `memory_used_GB`.

TPU notes: timing forces completion with a scalar device→host read (async
dispatch otherwise returns immediately, see bench.py), and peak memory
comes from the device's allocator stats where exposed.
"""

from __future__ import annotations

import statistics
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

TPU_PEAK_BF16_TFLOPS = {"v5e": 197.0, "v5p": 459.0, "v4": 275.0, "v6e": 918.0}


def tpu_generation() -> str:
    import os

    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    if gen:
        return gen
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
        for g in ("v6e", "v5p", "v5e", "v4"):
            if g in kind.replace(" ", ""):
                return g
        if "v5 lite" in kind or "v5lite" in kind:
            return "v5e"
    except Exception:
        pass
    return "v5e"


def peak_tflops() -> float:
    return TPU_PEAK_BF16_TFLOPS.get(tpu_generation(), 197.0)


def device_memory_used_gb() -> Optional[float]:
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        return stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)) / 1e9
    except Exception:
        return None


def force_completion(out) -> float:
    """Force device completion via a scalar host read; returns the scalar."""
    import jax

    from thunder_tpu.core.pytree import tree_leaves

    for leaf in reversed(tree_leaves(out)):
        if isinstance(leaf, jax.Array):
            flat = leaf.reshape(-1) if leaf.ndim else leaf
            return float(np.asarray(flat[0] if leaf.ndim else flat))
    return 0.0


@dataclass
class BenchmarkResult:
    name: str
    iters: int
    times_s: list[float]
    tokens_per_iter: Optional[int] = None
    flops_per_iter: Optional[float] = None
    memory_gb: Optional[float] = None
    # True when the run was async-dispatched with one final sync: times_s
    # then holds the amortized average repeated, so per-iter variance was
    # NOT measured and summary() omits the synthetic stats.
    pipelined: bool = False

    @property
    def pruned_times_s(self) -> list[float]:
        """Outlier-pruned samples (reference: benchmarks/__init__.py:220-455
        prunes timing outliers before reporting): drop points beyond
        1.5×IQR of the quartiles. With <4 samples nothing is pruned."""
        ts = sorted(self.times_s)
        if len(ts) < 4:
            return ts
        q1 = float(np.percentile(ts, 25))
        q3 = float(np.percentile(ts, 75))
        lo, hi = q1 - 1.5 * (q3 - q1), q3 + 1.5 * (q3 - q1)
        pruned = [t for t in ts if lo <= t <= hi]
        return pruned or ts

    @property
    def outliers(self) -> int:
        return len(self.times_s) - len(self.pruned_times_s)

    @property
    def median_s(self) -> float:
        return statistics.median(self.pruned_times_s)

    @property
    def mean_s(self) -> float:
        return statistics.fmean(self.pruned_times_s)

    @property
    def stdev_s(self) -> float:
        ts = self.pruned_times_s
        return statistics.stdev(ts) if len(ts) > 1 else 0.0

    def percentile_s(self, q: float) -> float:
        return float(np.percentile(self.pruned_times_s, q))

    @property
    def tokens_per_sec(self) -> Optional[float]:
        return self.tokens_per_iter / self.median_s if self.tokens_per_iter else None

    @property
    def tflops_per_sec(self) -> Optional[float]:
        return self.flops_per_iter / self.median_s / 1e12 if self.flops_per_iter else None

    @property
    def mfu(self) -> Optional[float]:
        t = self.tflops_per_sec
        return t / peak_tflops() if t else None

    def summary(self) -> dict:
        d = {
            "name": self.name,
            "iters": self.iters,
            "average_iter_time_s": round(self.mean_s, 5),
        }
        if self.pipelined:
            d["pipelined"] = True  # one sync; per-iter variance not measured
        else:
            d["median_iter_time_s"] = round(self.median_s, 5)
            d["stdev_s"] = round(self.stdev_s, 6)
            d["p25_s"] = round(self.percentile_s(25), 5)
            d["p75_s"] = round(self.percentile_s(75), 5)
            if self.iters >= 10:
                d["p90_s"] = round(self.percentile_s(90), 5)
            if self.outliers:
                d["outliers_pruned"] = self.outliers
        if self.tokens_per_sec:
            d["tokens_per_sec"] = round(self.tokens_per_sec)
        if self.tflops_per_sec:
            d["model_tflop_per_sec"] = round(self.tflops_per_sec, 2)
            d["mfu"] = round(self.mfu, 4)
        if self.memory_gb is not None:
            d["memory_used_GB"] = round(self.memory_gb, 2)
        return d


def run_benchmark(
    name: str,
    fn: Callable[[], Any],
    *,
    warmup: int = 2,
    iters: int = 5,
    tokens_per_iter: Optional[int] = None,
    flops_per_iter: Optional[float] = None,
    pipelined: bool = False,
) -> BenchmarkResult:
    """``pipelined=True`` dispatches all iterations asynchronously and syncs
    once at the end (each per-iter host sync costs the axon tunnel's ~95 ms
    round-trip — launch overhead, not op throughput). Per-iter times then
    all equal the amortized average."""
    for _ in range(warmup):
        force_completion(fn())
    if pipelined:
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn()
        force_completion(out)
        avg = (time.perf_counter() - t0) / iters
        times = [avg] * iters
    else:
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            force_completion(fn())
            times.append(time.perf_counter() - t0)
    return BenchmarkResult(
        name=name,
        iters=iters,
        times_s=times,
        tokens_per_iter=tokens_per_iter,
        flops_per_iter=flops_per_iter,
        memory_gb=device_memory_used_gb(),
        pipelined=pipelined,
    )


def training_flops_per_token(n_params: float) -> float:
    """fwd+bwd ≈ 6·N FLOPs/token (fwd 2N, bwd 4N)."""
    return 6.0 * n_params


def forward_flops_per_token(n_params: float) -> float:
    return 2.0 * n_params


def count_params(params) -> int:
    from thunder_tpu.core.pytree import tree_leaves

    return sum(int(np.prod(p.shape)) for p in tree_leaves(params) if hasattr(p, "shape"))
