"""Microbenchmark targets: op units × executor matrix, pytest-runnable.

Reference parity: thunder/benchmarks/targets.py (pytest-benchmark targets)
+ the executor-matrix benchmark constructions in benchmarks/__init__.py:699-976
(GeLU/softmax/cross-entropy/SDPA units and LitGPT block benchmarks run per
executor). Here each target compiles the op through the full jit pipeline
under a named executor list and reports the standard harness metrics.

Run as pytest (opt-in — benchmarks are not correctness CI):
    THUNDER_BENCH=1 pytest thunder_tpu/benchmarks/targets.py -q -s
or as a CLI:
    python -m thunder_tpu.benchmarks.targets [--filter sdpa] [--iters 20]
"""

from __future__ import annotations

import json
import os
import sys
from functools import partial

import numpy as np

import pytest


def _enabled() -> bool:
    return bool(os.environ.get("THUNDER_BENCH"))


EXECUTOR_CONFIGS = {
    "jax": ["jax"],
    "kernels": ["flash", "pallas", "jax"],
    "quant": ["quant", "jax"],
}


def _rand(*shape, dtype=np.float32, seed=0):
    return (np.random.RandomState(seed + sum(shape)).randn(*shape) * 0.5).astype(dtype)


# -- unit definitions: name -> (fn builder over ltorch, example args) ---------


def _unit_gelu():
    import thunder_tpu.torch as ltorch

    x = _rand(4096, 4096)
    return lambda a: ltorch.gelu(a), (x,), 0


def _unit_softmax():
    import thunder_tpu.torch as ltorch

    x = _rand(256, 8192)
    return lambda a: ltorch.softmax(a, -1), (x,), 0


def _unit_layer_norm():
    import thunder_tpu.torch as ltorch

    x = _rand(4096, 4096)
    w, b = _rand(4096, seed=1), _rand(4096, seed=2)
    return lambda a, w, b: ltorch.layer_norm(a, (4096,), w, b), (x, w, b), 0


def _unit_cross_entropy():
    import thunder_tpu.torch as ltorch

    logits = _rand(4096, 32000)
    tgt = np.random.RandomState(3).randint(0, 32000, (4096,)).astype(np.int64)
    return lambda a, t: ltorch.cross_entropy(a, t), (logits, tgt), 0


def _unit_sdpa():
    import thunder_tpu.torch as ltorch

    B, H, S, D = 4, 16, 2048, 128
    q, k, v = (_rand(B, H, S, D, seed=i).astype(np.float32) for i in range(3))
    flops = 4.0 * B * H * S * S * D  # 2 matmuls fwd
    return (
        lambda q, k, v: ltorch.scaled_dot_product_attention(q, k, v, is_causal=True),
        (q, k, v),
        flops,
    )


def _unit_linear():
    import thunder_tpu.torch as ltorch

    x, w = _rand(4096, 4096), _rand(4096, 4096, seed=1)
    return lambda a, w: ltorch.linear(a, w), (x, w), 2.0 * 4096**3


def _unit_gpt_block_fwd():
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m

    cfg = m.name_to_config("pythia-160m")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    idx = np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 512)).astype(np.int32)
    n = sum(int(np.prod(p.shape)) for p in _leaves(params))
    return lambda p, i: m.forward(p, i, cfg), (params, idx), 2.0 * n * 4 * 512


def _leaves(tree):
    from thunder_tpu.core.pytree import tree_leaves

    return [p for p in tree_leaves(tree) if hasattr(p, "shape")]


UNITS = {
    "gelu": _unit_gelu,
    "softmax": _unit_softmax,
    "layer_norm": _unit_layer_norm,
    "cross_entropy": _unit_cross_entropy,
    "sdpa": _unit_sdpa,
    "linear": _unit_linear,
    "gpt_block_fwd": _unit_gpt_block_fwd,
}


def run_target(unit: str, executor: str, *, iters: int = 10, warmup: int = 2) -> dict:
    import jax

    import thunder_tpu
    from thunder_tpu.benchmarks import run_benchmark
    from thunder_tpu.core.pytree import tree_map

    fn, args, flops = UNITS[unit]()
    # Device-resident inputs: a numpy arg would re-upload through the axon
    # tunnel (~35 MB/s measured) every iteration and swamp the op time.
    args = tree_map(
        lambda x: jax.device_put(x) if isinstance(x, np.ndarray) else x, args
    )
    jfn = thunder_tpu.jit(fn, executors=EXECUTOR_CONFIGS[executor])
    result = run_benchmark(
        f"{unit}[{executor}]",
        partial(jfn, *args),
        warmup=warmup,
        iters=iters,
        flops_per_iter=flops or None,
        pipelined=True,
    )
    return result.summary()


# -- pytest targets (gated: benchmarks are not correctness CI) ----------------


@pytest.mark.parametrize("executor", list(EXECUTOR_CONFIGS))
@pytest.mark.parametrize("unit", list(UNITS))
def test_bench(unit, executor):
    if not _enabled():
        pytest.skip("set THUNDER_BENCH=1 to run benchmark targets")
    summary = run_target(unit, executor)
    print(json.dumps(summary))


def main() -> None:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--filter", default="")
    p.add_argument("--executors", default=",".join(EXECUTOR_CONFIGS))
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()

    for unit in UNITS:
        if args.filter and args.filter not in unit:
            continue
        for executor in args.executors.split(","):
            try:
                summary = run_target(unit, executor, iters=args.iters)
            except Exception as e:  # noqa: BLE001 — report and continue the matrix
                summary = {"name": f"{unit}[{executor}]", "error": f"{type(e).__name__}: {e}"}
            print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
