"""Microbenchmark targets: op units × executor matrix, pytest-runnable.

Reference parity: thunder/benchmarks/targets.py (pytest-benchmark targets)
+ the executor-matrix benchmark constructions in benchmarks/__init__.py:699-976
(GeLU/softmax/cross-entropy/SDPA units and LitGPT block benchmarks run per
executor). Here each target compiles the op through the full jit pipeline
under a named executor list and reports the standard harness metrics.

Run as pytest (opt-in — benchmarks are not correctness CI):
    THUNDER_BENCH=1 pytest thunder_tpu/benchmarks/targets.py -q -s
or as a CLI:
    python -m thunder_tpu.benchmarks.targets [--filter sdpa] [--iters 20]
"""

from __future__ import annotations

import json
import os
import sys
from functools import partial

import numpy as np

try:  # the CLI path must work without test dependencies (ADVICE r3)
    import pytest
except ImportError:  # pragma: no cover
    class _PytestStub:
        class mark:
            @staticmethod
            def parametrize(*a, **k):
                return lambda fn: fn

        @staticmethod
        def skip(msg):
            raise RuntimeError(msg)

    pytest = _PytestStub()


def _enabled() -> bool:
    return bool(os.environ.get("THUNDER_BENCH"))


EXECUTOR_CONFIGS = {
    "jax": ["jax"],
    "kernels": ["flash", "pallas", "jax"],
    "quant": ["quant", "jax"],
}


def _rand(*shape, dtype=np.float32, seed=0):
    return (np.random.RandomState(seed + sum(shape)).randn(*shape) * 0.5).astype(dtype)


# -- unit definitions: name -> (fn builder over ltorch, example args) ---------


def _unit_gelu():
    import thunder_tpu.torch as ltorch

    x = _rand(4096, 4096)
    return lambda a: ltorch.gelu(a), (x,), 0


def _unit_softmax():
    import thunder_tpu.torch as ltorch

    x = _rand(256, 8192)
    return lambda a: ltorch.softmax(a, -1), (x,), 0


def _unit_layer_norm():
    import thunder_tpu.torch as ltorch

    x = _rand(4096, 4096)
    w, b = _rand(4096, seed=1), _rand(4096, seed=2)
    return lambda a, w, b: ltorch.layer_norm(a, (4096,), w, b), (x, w, b), 0


def _unit_cross_entropy():
    import thunder_tpu.torch as ltorch

    logits = _rand(4096, 32000)
    tgt = np.random.RandomState(3).randint(0, 32000, (4096,)).astype(np.int64)
    return lambda a, t: ltorch.cross_entropy(a, t), (logits, tgt), 0


def _unit_sdpa():
    import jax.numpy as jnp

    import thunder_tpu.torch as ltorch

    B, H, S, D = 4, 16, 2048, 128
    # bf16: the flash executor (like the reference's cudnn/sdpa seats)
    # claims half precision only.
    q, k, v = (jnp.asarray(_rand(B, H, S, D, seed=i), dtype=jnp.bfloat16) for i in range(3))
    flops = 4.0 * B * H * S * S * D  # 2 matmuls fwd
    return (
        lambda q, k, v: ltorch.scaled_dot_product_attention(q, k, v, is_causal=True),
        (q, k, v),
        flops,
    )


def _unit_linear():
    import thunder_tpu.torch as ltorch

    x, w = _rand(4096, 4096), _rand(4096, 4096, seed=1)
    return lambda a, w: ltorch.linear(a, w), (x, w), 2.0 * 4096**3


def _unit_gpt_block_fwd():
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m

    cfg = m.name_to_config("pythia-160m")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    idx = np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 512)).astype(np.int32)
    n = sum(int(np.prod(p.shape)) for p in _leaves(params))
    return lambda p, i: m.forward(p, i, cfg), (params, idx), 2.0 * n * 4 * 512


def _unit_rms_norm():
    import thunder_tpu.torch as ltorch

    x, w = _rand(8192, 4096), _rand(4096, seed=1)
    return lambda a, w: ltorch.rms_norm(a, (4096,), w), (x, w), 0


def _block_unit(cfg_name: str, *, train: bool, B: int = 1, T: int = 512):
    """One transformer BLOCK of a model family (reference:
    benchmarks/__init__.py LitGPT/nanoGPT block benchmarks at :699-976 —
    per-block fwd or fwd+bwd with the model's real geometry)."""
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m
    from thunder_tpu.models.gpt import _block, _rope_cache

    import thunder_tpu.torch as ltorch

    cfg = m.name_to_config(cfg_name)
    full = m.init_params(cfg, dtype=dtypes.bfloat16, seed=0)
    p = full["blocks"][0]
    x = _rand(B, T, cfg.n_embd).astype(np.float32)

    def block_fwd(x, p):
        import thunder_tpu.clang as clang

        xb = clang.maybe_convert_to_dtype(x, dtypes.bfloat16)
        cos, sin = _rope_cache(T, cfg, device=xb.device, dtype=xb.dtype)
        out = _block(xb, p, cos, sin, cfg)
        return ltorch.sum(clang.maybe_convert_to_dtype(out, dtypes.float32) ** 2)

    n = sum(int(np.prod(q.shape)) for q in _leaves(p))
    fwd_flops = 2.0 * n * B * T + 4.0 * B * cfg.n_head * T * T * cfg.head_size
    if not train:
        return block_fwd, (x, p), fwd_flops

    def block_train(x, p):
        return block_fwd(x, p)

    return block_train, (x, p), 3.0 * fwd_flops


def _unit_llama_block_fwd():
    return _block_unit("llama-2-7b", train=False)


def _unit_llama_block_train():
    fn, args, flops = _block_unit("llama-2-7b", train=True)
    fn._needs_grad = True  # run_target stages it via value_and_grad
    return fn, args, flops


def _unit_nanogpt_block_fwd():
    # pythia-160m's block IS the nanoGPT geometry class: parallel-residual
    # GPT block with LayerNorm + GELU MLP.
    return _block_unit("pythia-160m", train=False)


def _unit_nanogpt_block_train():
    fn, args, flops = _block_unit("pythia-160m", train=True)
    fn._needs_grad = True
    return fn, args, flops


def _leaves(tree):
    from thunder_tpu.core.pytree import tree_leaves

    return [p for p in tree_leaves(tree) if hasattr(p, "shape")]


UNITS = {
    "gelu": _unit_gelu,
    "softmax": _unit_softmax,
    "layer_norm": _unit_layer_norm,
    "rms_norm": _unit_rms_norm,
    "cross_entropy": _unit_cross_entropy,
    "sdpa": _unit_sdpa,
    "linear": _unit_linear,
    "gpt_block_fwd": _unit_gpt_block_fwd,
    "nanogpt_block_fwd": _unit_nanogpt_block_fwd,
    "nanogpt_block_train": _unit_nanogpt_block_train,
    "llama_block_fwd": _unit_llama_block_fwd,
    "llama_block_train": _unit_llama_block_train,
}


def run_target(unit: str, executor: str, *, iters: int = 10, warmup: int = 2) -> dict:
    import jax

    import thunder_tpu
    from thunder_tpu.benchmarks import run_benchmark
    from thunder_tpu.core.pytree import tree_map

    fn, args, flops = UNITS[unit]()
    # Device-resident inputs: a numpy arg would re-upload through the axon
    # tunnel (~35 MB/s measured) every iteration and swamp the op time.
    args = tree_map(
        lambda x: jax.device_put(x) if isinstance(x, np.ndarray) else x, args
    )
    if getattr(fn, "_needs_grad", False):
        jfn = thunder_tpu.value_and_grad(fn, executors=EXECUTOR_CONFIGS[executor])
    else:
        jfn = thunder_tpu.jit(fn, executors=EXECUTOR_CONFIGS[executor])
    result = run_benchmark(
        f"{unit}[{executor}]",
        partial(jfn, *args),
        warmup=warmup,
        iters=iters,
        flops_per_iter=flops or None,
        pipelined=True,
    )
    return result.summary()


# -- pytest targets (gated: benchmarks are not correctness CI) ----------------


@pytest.mark.parametrize("executor", list(EXECUTOR_CONFIGS))
@pytest.mark.parametrize("unit", list(UNITS))
def test_bench(unit, executor):
    if not _enabled():
        pytest.skip("set THUNDER_BENCH=1 to run benchmark targets")
    summary = run_target(unit, executor)
    print(json.dumps(summary))


def main() -> None:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--filter", default="")
    p.add_argument("--executors", default=",".join(EXECUTOR_CONFIGS))
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--format", choices=("jsonl", "table"), default="table",
                   help="table: per-unit × per-executor comparison matrix "
                        "(reference: the executor-comparison benchmark specs, "
                        "benchmarks/__init__.py:699-976)")
    args = p.parse_args()

    executors = [e for e in args.executors.split(",") if e]
    rows = []
    for unit in UNITS:
        if args.filter and args.filter not in unit:
            continue
        row = {"unit": unit}
        for executor in executors:
            try:
                summary = run_target(unit, executor, iters=args.iters)
            except Exception as e:  # noqa: BLE001 — report and continue the matrix
                summary = {"name": f"{unit}[{executor}]", "error": f"{type(e).__name__}: {e}"}
            if args.format == "jsonl":
                print(json.dumps(summary), flush=True)
            row[executor] = summary
        rows.append(row)

    if args.format != "table":
        return
    # comparison table: median time per executor + speedup vs the jax column
    headers = ["unit"] + [f"{e} (s)" for e in executors] + [
        f"{e} vs jax" for e in executors if e != "jax"
    ]
    print("  ".join(f"{h:>20s}" for h in headers))
    for row in rows:
        def med(e):
            s = row.get(e, {})
            return s.get("median_iter_time_s", s.get("average_iter_time_s"))

        cells = [f"{row['unit']:>20s}"]
        base = med("jax")
        for e in executors:
            m = med(e)
            cells.append(f"{m:20.5f}" if m is not None else f"{'ERR':>20s}")
        for e in executors:
            if e == "jax":
                continue
            m = med(e)
            cells.append(
                f"{base / m:19.2f}x" if (m and base) else f"{'-':>20s}"
            )
        print("  ".join(cells), flush=True)


if __name__ == "__main__":
    main()
