"""Structured JSONL event log.

Every durable compilation-pipeline happening — compile start/end, per-pass
durations (from the PR 1 provenance hooks in ``core/trace.py``), cache
misses, bucket selection, sharp-edge observations, NaN-watch trips, profile
brackets — is one JSON object on one line, so logs stream, tail, and replay
(``scripts/lint_traces.py --events`` / ``thunder_tpu.analysis.events``).

Activation:

- process-wide: ``THUNDER_TPU_EVENTS=<path>`` (checked lazily, once);
- per-function: ``jit(fn, events="<path>")`` — that function's compiles and
  cache events go to its own log, overriding the global one.

Schema (stable; the replay tool validates it):

    {"v": 1, "ts": <unix seconds>, "seq": <per-log counter>, "kind": "...",
     "pid": <os pid>, "host": <jax.process_index() or 0>,
     ...kind-specific fields...}

``pid``/``host`` identify the writer so per-host logs of a multi-host job
merge deterministically (``scripts/lint_traces.py --events h0.jsonl h1.jsonl``).

Kind-specific required fields live in ``thunder_tpu.analysis.events.SCHEMA``.
Emission is a no-op costing one dict lookup when no log is active.

Ops plane (ISSUE 15): when ``observability/opsplane`` is enabled it
installs **taps** here — the flight-recorder ring and the streaming
detector bank see every emitted record, with or without a JSONL log
configured. With the plane off (the default) the taps tuple is empty and
every emit path pays exactly one module-global truth test; the dispatch
fast path emits nothing and pays nothing.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import sys
import threading
import time
from typing import Any, Optional

SCHEMA_VERSION = 1

# -- ops-plane taps (observability/opsplane installs; empty = plane off) -------
# A tuple of ``tap(kind, fields)`` callables that see every emitted record,
# independent of whether a JSONL sink is configured — the flight recorder's
# ring and the detector bank. One module-global truth test when empty.
_ops: dict[str, Any] = {"taps": (), "recorder": None}


def set_ops_taps(taps: tuple, *, recorder=None) -> None:
    """Install (or clear, with ``()``) the ops-plane event taps. ``recorder``
    is the flight recorder :func:`flight_dump` delegates to."""
    _ops["taps"] = tuple(taps)
    _ops["recorder"] = recorder


def ops_active() -> bool:
    return bool(_ops["taps"])


def ops_taps() -> tuple:
    """(taps, recorder) snapshot — for callers that need to restore the
    installed taps around an A/B measurement (bench.py) without tearing
    down a live ops plane's server."""
    return _ops["taps"], _ops["recorder"]


def _tap(kind: str, fields: dict) -> None:
    for tap in _ops["taps"]:
        try:
            tap(kind, fields)
        except Exception:
            # The ops plane observes the workload; it must never take it
            # down — a detector/recorder bug degrades to silence.
            pass


def tap_event(kind: str, fields: dict) -> None:
    """Feed the ops taps directly — for emit sites that write through a
    specific :class:`EventLog` handle (which taps on its own) but skip
    emitting entirely when no log is configured; the flight recorder must
    still see those records."""
    if _ops["taps"]:
        _tap(kind, fields)


def flight_dump(reason: str = "manual"):
    """Dump the installed flight recorder's ring (``flightrec-<ts>-
    <reason>.jsonl``); None when the ops plane is off. The spelling fault
    sites use (watchdog timeout, SDC exhaustion, autopilot halt, unhandled
    dispatch faults) — one global probe when off, never raises."""
    rec = _ops["recorder"]
    if rec is None:
        return None
    try:
        return rec.dump(reason)
    except Exception:
        return None


_identity: dict[str, Any] = {}


def host_identity() -> dict[str, Any]:
    """``{"pid", "host"}`` stamped into every event record so per-host JSONL
    logs from a multi-host job can be merged with stable ordering
    (``thunder_tpu.analysis.events.merge_event_logs``). ``host`` is
    ``jax.process_index()`` when the jax backend is already up at the FIRST
    emission, else 0 — and then FROZEN: merge ordering and compile-id
    correlation key on (host, pid), so one process's events must never flip
    identity mid-log (pid disambiguates processes even when several froze
    host=0). Observability must also never be the thing that initializes
    the backend, hence asking only an existing one."""
    pid = os.getpid()
    if _identity.get("pid") != pid:
        # Fork-safety: a forked worker is a new writer and re-resolves.
        _identity.clear()
        _identity["pid"] = pid
        host = 0
        jax_mod = sys.modules.get("jax")
        if jax_mod is not None:
            try:
                # Only ask an already-initialized backend; process_index()
                # on a cold jax would trigger backend init from inside an
                # emit() call.
                if jax_mod._src.xla_bridge._backends:  # type: ignore[attr-defined]
                    host = int(jax_mod.process_index())
            except Exception:
                pass
        _identity["host"] = host
    return {"pid": pid, "host": _identity["host"]}


class EventLog:
    """Append-only JSONL sink. Opens lazily, one line per event, flushed per
    write (a crashed process keeps everything emitted before the crash).

    Construct via :func:`log_for_path` — one shared instance per path, so
    two functions logging to the same file share one handle and one ``seq``
    counter (independent instances would interleave duplicate seq values)."""

    def __init__(self, path: str):
        self.path = path
        self._f = None
        self._seq = 0
        self._lock = threading.Lock()
        self._dead = False

    def emit(self, kind: str, **fields) -> None:
        # Ops-plane taps see the record whether or not the sink survives:
        # the flight recorder is most valuable exactly when the disk log is
        # dying underneath it.
        if _ops["taps"]:
            _tap(kind, fields)
        # Observability must never take the workload down: a sink I/O
        # failure (unwritable path, disk full) warns once and disables this
        # log instead of crashing the compile/training step it observes.
        if self._dead:
            return
        rec = {"v": SCHEMA_VERSION, "ts": time.time(), "kind": kind}
        rec.update(host_identity())
        rec.update(fields)
        try:
            with self._lock:
                if self._f is None:
                    d = os.path.dirname(os.path.abspath(self.path))
                    if d:
                        os.makedirs(d, exist_ok=True)
                    self._f = open(self.path, "a")
                rec["seq"] = self._seq
                self._f.write(json.dumps(rec, default=str))
                self._f.write("\n")
                self._f.flush()
                self._seq += 1
        except OSError as e:
            self._dead = True
            # Silent observability loss must itself be observable: the drop
            # counter increments past the metrics gate so monitor.report()
            # shows it even when metrics were never enabled (ISSUE 6).
            from thunder_tpu.observability import metrics as obsm

            obsm.EVENT_LOG_DROPPED.inc_always()
            import warnings

            warnings.warn(
                f"thunder_tpu event log {self.path!r} disabled after I/O "
                f"failure: {e}",
                stacklevel=3,
            )

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# -- active-log resolution ----------------------------------------------------

_active_log: contextvars.ContextVar[Optional[EventLog]] = contextvars.ContextVar(
    "thunder_tpu_event_log", default=None
)
_global = {"path": None, "log": None}
_logs_by_path: dict[str, EventLog] = {}


def log_for_path(path: str) -> EventLog:
    """The shared :class:`EventLog` for ``path`` (one instance per absolute
    path process-wide — keeps the per-log ``seq`` counter monotonic when
    several functions log to the same file)."""
    key = os.path.abspath(path)
    log = _logs_by_path.get(key)
    if log is None:
        log = _logs_by_path[key] = EventLog(path)
    return log


def set_global_path(path: Optional[str]) -> None:
    """Point the process-wide log somewhere (None disables). Mostly for
    tests; production uses THUNDER_TPU_EVENTS."""
    _global["path"] = path
    _global["log"] = log_for_path(path) if path else None
    _global["resolved"] = True


def _global_log() -> Optional[EventLog]:
    if not _global.get("resolved"):
        path = os.environ.get("THUNDER_TPU_EVENTS", "").strip()
        _global["path"] = path or None
        _global["log"] = log_for_path(path) if path else None
        _global["resolved"] = True
    return _global["log"]


def active_log() -> Optional[EventLog]:
    log = _active_log.get()
    if log is not None:
        return log
    return _global_log()


def emit_event(kind: str, **fields) -> None:
    """Emit to the active log (contextvar override, else the global
    THUNDER_TPU_EVENTS log); no-op when neither is configured — except the
    ops-plane taps, which see every record even with no log (the flight
    recorder keeps context without paying full event logging)."""
    log = active_log()
    if log is not None:
        log.emit(kind, **fields)  # taps fire inside emit
    elif _ops["taps"]:
        _tap(kind, fields)


def emit_compile_end(
    compile_id, fn_name: str, ms: float, trace=None, *,
    symbolic: bool = False, recompile: bool = False, staged: bool = True,
) -> None:
    """The one writer of ``compile_end`` records, shared by the functional
    pipeline (api._compile_entry_checked) and the module frontend
    (frontend/module.py) so the schema cannot diverge between producers.
    ``trace`` is the final execution trace; its ``claim_breakdown`` /
    ``collective_bytes`` tags (stamped by executors/passes.py) become the
    event's executor and collective payloads."""
    log = active_log()
    if log is None and not _ops["taps"]:
        return
    tags = getattr(trace, "tags", None) or {}
    fields = dict(
        compile_id=compile_id,
        fn=fn_name,
        ms=ms,
        n_bsyms=len(trace.bound_symbols) if trace is not None else None,
        claims=tags.get("claim_breakdown") or {},
        collective_bytes=int(tags.get("collective_bytes") or 0),
        symbolic=symbolic,
        recompile=recompile,
        staged=staged,
    )
    if log is not None:
        log.emit("compile_end", **fields)  # taps fire inside emit
    else:
        # No sink configured, ops plane on: the recompile-rate detector and
        # the flight ring still need the record.
        _tap("compile_end", fields)


@contextlib.contextmanager
def event_scope(log: Optional[EventLog]):
    """Route ``emit_event`` to ``log`` within the scope (None = no change)."""
    if log is None:
        yield
        return
    tok = _active_log.set(log)
    try:
        yield
    finally:
        _active_log.reset(tok)


# -- compile correlation ------------------------------------------------------
# Per-pass events fire deep inside core/trace.py with no compile handle in
# scope; a contextvar carries the compile id so one compile's pass events
# correlate in the log.

_compile_id: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "thunder_tpu_compile_id", default=None
)
_compile_seq = {"n": 0}


def current_compile_id() -> Optional[int]:
    return _compile_id.get()


@contextlib.contextmanager
def compile_scope(log: Optional[EventLog] = None):
    """Allocate a process-unique compile id, route events to ``log`` (when
    given), and yield the id. Used by ``api._compile_entry``."""
    _compile_seq["n"] += 1
    cid = _compile_seq["n"]
    tok = _compile_id.set(cid)
    try:
        with event_scope(log):
            yield cid
    finally:
        _compile_id.reset(tok)
