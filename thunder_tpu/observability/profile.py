"""Profiler bracketing: ``thunder_tpu.profile(fn, *args)``.

Runs a (compiled or plain) callable under ``jax.profiler.trace`` with one
``StepTraceAnnotation`` per step, producing an xprof-ready trace directory —
the consolidated home of the recipe that used to live only in
``scripts/profile_train.py``. Combined with annotated codegen
(``THUNDER_TPU_ANNOTATE_TRACES=1``; see ``core/trace.py``), every HLO row in
the profile carries the originating trace line + pass provenance, so
profiler time attributes back to BoundSymbols.

On backends without a profiler plugin the bracket degrades to wall-clock
timing (``trace_dir`` comes back None) instead of failing the run.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Optional

from thunder_tpu.observability.events import emit_event


def _count_capture(*, ok: bool) -> None:
    """Bump ``thunder_tpu_profile_captures_total{ok=}`` past the metrics
    gate (always-export; never fails the bracket)."""
    try:
        from thunder_tpu.observability import metrics as obsm

        obsm.PROFILE_CAPTURES.inc_always(ok="true" if ok else "false")
    except Exception:
        pass


def _block_on(out: Any) -> None:
    """Synchronize on every array leaf so the profiled region contains the
    device work, not just its async dispatch."""
    from thunder_tpu.core.pytree import tree_flatten

    flat, _ = tree_flatten(out)
    for x in flat:
        if hasattr(x, "block_until_ready"):
            x.block_until_ready()


def profile(
    fn: Callable,
    *args,
    trace_dir: Optional[str] = None,
    steps: int = 3,
    warmup: int = 1,
    step_name: str = "thunder_step",
    **kwargs,
) -> dict:
    """Bracket ``steps`` calls of ``fn(*args, **kwargs)`` with jax profiler
    markers and write an xprof-ready trace directory.

    Returns ``{"trace_dir", "steps", "avg_s", "total_s", "profiler",
    "attribution"}`` — ``profiler`` is False when the backend has no
    profiler plugin and only wall-clock numbers were collected.

    ``attribution`` closes the loop in-process: when the profiler ran and
    the trace-events carry annotated-codegen scopes (run under
    ``THUNDER_TPU_ANNOTATE_TRACES=1``), it is an
    :class:`~thunder_tpu.observability.attribution.Attribution` mapping
    measured device time back to trace lines (None otherwise). Join it with
    the static cost model via ``thunder_tpu.monitor.attribution_report`` or
    ``scripts/perf_report.py --trace-dir``; see docs/performance.md.
    """
    import jax

    if trace_dir is None:
        import tempfile

        trace_dir = tempfile.mkdtemp(prefix="thunder_tpu_prof_")
    else:
        os.makedirs(trace_dir, exist_ok=True)

    for _ in range(max(0, warmup)):
        _block_on(fn(*args, **kwargs))

    emit_event("profile_start", dir=trace_dir, steps=steps)
    # Only profiler SETUP failures degrade to wall-clock; an exception from
    # the profiled fn itself (a NaNWatchError, a consumed donated buffer)
    # must propagate — re-running the loop would misdiagnose it as a missing
    # profiler plugin and double-consume donated inputs.
    profiler_ctx = None
    profiler_ok = False
    try:
        profiler_ctx = jax.profiler.trace(trace_dir)
        profiler_ctx.__enter__()
        profiler_ok = True
    except Exception as e:  # profiler plugin unavailable: degrade, don't fail
        profiler_ctx = None
        import warnings

        warnings.warn(
            f"jax profiler unavailable ({type(e).__name__}: {e}); "
            "collecting wall-clock only",
            stacklevel=2,
        )
        # A degraded capture must be loud beyond the one-shot warning: the
        # roofline duty cycle (ISSUE 19) calls this bracket unattended, and
        # a plugin-less backend would silently produce wall-clock-only
        # probes forever. The always-export counter reaches /metrics and
        # degrades the /healthz `profile` component; the typed event lands
        # in the log/flight recorder next to the probes it explains.
        _count_capture(ok=False)
        emit_event(
            "profile_degraded", reason=f"{type(e).__name__}: {e}")

    out = None
    t0 = time.perf_counter()
    try:
        for i in range(steps):
            if profiler_ok:
                with jax.profiler.StepTraceAnnotation(step_name, step_num=i):
                    out = fn(*args, **kwargs)
            else:
                out = fn(*args, **kwargs)
        _block_on(out)
    finally:
        if profiler_ctx is not None:
            profiler_ctx.__exit__(None, None, None)
    total = time.perf_counter() - t0
    if profiler_ok:
        _count_capture(ok=True)
    result = {
        "trace_dir": trace_dir if profiler_ok else None,
        "steps": steps,
        "total_s": total,
        "avg_s": total / max(1, steps),
        "profiler": profiler_ok,
    }
    emit_event("profile_stop", **result)
    # Best-effort in-process attribution (never fails the profile): only
    # meaningful when annotated codegen stamped scopes into HLO metadata.
    result["attribution"] = None
    if profiler_ok:
        try:
            from thunder_tpu.observability.attribution import attribute

            attr = attribute(trace_dir)
            if attr.by_line:
                result["attribution"] = attr
        except Exception:
            pass
    return result
