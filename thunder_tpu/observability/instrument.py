"""Per-op instrumentation transform over execution traces.

The TPU analogue of the reference's ``debug_transform`` / NVTX-profile
transform (thunder/dev_utils): every value-producing BoundSymbol of a
claimed execution trace is bracketed with host pre/post callback prims, so
hooks observe the CONCRETE outputs of each op together with its
BoundSymbol name, generated trace line, and pass provenance.

Mechanics: the trace runs **unstaged** when instrumented (the hook prims
are host side effects XLA cannot stage — ``api._compile_entry_checked``
drops the ``jax.jit`` wrapper for these entries), so each claimed op
executes eagerly through jax and the hooks see real ``jax.Array`` values.
With instrumentation disabled nothing is inserted and the entry stages
whole under XLA as usual — zero overhead.

Built-in hooks:

- :class:`NaNWatcher` — ``jit(fn, debug_watch="nan")``: raises (or warns,
  ``action="warn"``) the moment any output turns NaN/Inf, attributed to the
  producing BoundSymbol + trace line + pass provenance.
- :class:`OpTimer` — per-op wall times (blocks on outputs; the measured
  time is dispatch+compute, i.e. profiler-truth for eager op latency).
- :class:`MemoryHighWater` — peak device ``bytes_in_use`` (falls back to a
  cumulative output-bytes estimate on backends without ``memory_stats``),
  attributed to the op active at the peak.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.symbol import BoundSymbol, Symbol
from thunder_tpu.core.trace import TraceCtx, from_trace, wrap_in_trace_provenance
from thunder_tpu.observability import metrics as obsm
from thunder_tpu.observability.events import emit_event


@dataclass(frozen=True)
class OpRecord:
    """What a hook learns about the op it brackets."""

    index: int  # bound-symbol index in the instrumented trace's source
    sym_name: str
    executor: Optional[str]
    line: str  # the generated trace line
    provenance: Optional[str]  # which pass produced the trace being run
    trace_name: str


class InstrumentationHook:
    """Base class: override either or both callbacks. ``outputs`` is the
    tuple of concrete flat proxy outputs (jax arrays / numbers)."""

    def on_op_start(self, rec: OpRecord) -> None:  # pragma: no cover - trivial
        pass

    def on_op_end(self, rec: OpRecord, outputs: tuple) -> None:  # pragma: no cover
        pass

    def report(self) -> dict:
        return {}


class CallbackHook(InstrumentationHook):
    """Wrap a bare ``fn(rec, outputs)`` callable as a post-op hook."""

    def __init__(self, fn: Callable[[OpRecord, tuple], None]):
        self._fn = fn

    def on_op_end(self, rec: OpRecord, outputs: tuple) -> None:
        self._fn(rec, outputs)


class NaNWatchError(RuntimeError):
    """A watched trace produced a NaN/Inf. Carries the attribution."""

    def __init__(self, kind: str, rec: OpRecord, out_index: int):
        self.kind = kind
        self.sym_name = rec.sym_name
        self.trace_line = rec.line
        self.provenance = rec.provenance
        self.bsym_index = rec.index
        super().__init__(
            f"{kind} detected in output {out_index} of BoundSymbol "
            f"{rec.sym_name!r} (bsym {rec.index} of trace {rec.trace_name!r})\n"
            f"    >> {rec.line}\n"
            f"    produced by pass: {rec.provenance or 'unknown'}"
        )


def _nonfinite_kind(x: Any, watch_nan: bool, watch_inf: bool) -> Optional[str]:
    if not hasattr(x, "dtype") or not hasattr(x, "shape"):
        return None
    import jax.numpy as jnp
    import numpy as np

    if not jnp.issubdtype(x.dtype, jnp.floating) and not jnp.issubdtype(
        x.dtype, jnp.complexfloating
    ):
        return None
    if watch_nan and bool(np.asarray(jnp.isnan(x).any())):
        return "NaN"
    if watch_inf and bool(np.asarray(jnp.isinf(x).any())):
        return "Inf"
    return None


class NaNWatcher(InstrumentationHook):
    """``mode``: "nan", "inf", or "nan+inf". ``action``: "raise" (default)
    or "warn" (log every trip, keep executing)."""

    def __init__(self, mode: str = "nan", action: str = "raise"):
        mode = mode.lower()
        if mode not in ("nan", "inf", "nan+inf", "inf+nan", "both"):
            raise ValueError(f"debug_watch: unknown mode {mode!r} (nan|inf|nan+inf)")
        self.watch_nan = "nan" in mode or mode == "both"
        self.watch_inf = "inf" in mode or mode == "both"
        if action not in ("raise", "warn"):
            raise ValueError(f"debug_watch action must be 'raise' or 'warn', got {action!r}")
        self.action = action
        self.trips: list[dict] = []

    def on_op_end(self, rec: OpRecord, outputs: tuple) -> None:
        for i, x in enumerate(outputs):
            kind = _nonfinite_kind(x, self.watch_nan, self.watch_inf)
            if kind is None:
                continue
            obsm.NAN_WATCH_TRIPS.inc(symbol=rec.sym_name)
            emit_event(
                "nan_watch", value_kind=kind, symbol=rec.sym_name,
                bsym_index=rec.index, line=rec.line, provenance=rec.provenance,
            )
            err = NaNWatchError(kind, rec, i)
            if self.action == "raise":
                raise err
            self.trips.append(
                {"kind": kind, "symbol": rec.sym_name, "bsym_index": rec.index,
                 "line": rec.line, "provenance": rec.provenance}
            )
            import warnings

            warnings.warn(str(err), RuntimeWarning, stacklevel=2)

    def report(self) -> dict:
        return {"trips": list(self.trips)}


class OpTimer(InstrumentationHook):
    """Wall time per op. Blocks on each op's outputs, so an op's time
    includes its dispatch + device compute (eager-latency truth; the staged
    pipeline's async overlap is intentionally defeated while timing)."""

    def __init__(self):
        self.times_s: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self._t0: float = 0.0

    def on_op_start(self, rec: OpRecord) -> None:
        self._t0 = time.perf_counter()

    def on_op_end(self, rec: OpRecord, outputs: tuple) -> None:
        for x in outputs:
            if hasattr(x, "block_until_ready"):
                x.block_until_ready()
        dt = time.perf_counter() - self._t0
        key = rec.sym_name
        self.times_s[key] = self.times_s.get(key, 0.0) + dt
        self.counts[key] = self.counts.get(key, 0) + 1
        obsm.INSTRUMENTED_OP_US.observe(dt * 1e6, symbol=key)

    def report(self) -> dict:
        total = sum(self.times_s.values()) or 1.0
        top = sorted(self.times_s.items(), key=lambda kv: -kv[1])
        return {
            "total_s": sum(self.times_s.values()),
            "ops": [
                {"symbol": k, "total_s": v, "calls": self.counts[k],
                 "pct": 100.0 * v / total}
                for k, v in top
            ],
        }


class MemoryHighWater(InstrumentationHook):
    """Peak device memory across the instrumented run, with the op active
    at the peak. Uses ``device.memory_stats()['bytes_in_use']`` where the
    backend provides it (TPU does); otherwise falls back to a cumulative
    produced-bytes estimate (an upper bound that ignores frees)."""

    def __init__(self):
        self.peak_bytes = 0
        self.peak_op: Optional[str] = None
        self._estimate = 0
        # Mode is resolved ONCE, on the first op: mixing absolute device
        # bytes with a from-zero cumulative estimate would corrupt the peak
        # comparison if memory_stats availability flickered mid-run.
        self.exact: Optional[bool] = None

    def _bytes_in_use(self) -> Optional[int]:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if stats and "bytes_in_use" in stats:
                return int(stats["bytes_in_use"])
        except Exception:
            pass
        return None

    def on_op_end(self, rec: OpRecord, outputs: tuple) -> None:
        used = self._bytes_in_use() if self.exact in (None, True) else None
        if self.exact is None:
            self.exact = used is not None
        if not self.exact or used is None:
            self._estimate += sum(
                int(getattr(x, "nbytes", 0) or 0) for x in outputs
            )
            if not self.exact:
                used = self._estimate
            else:
                return  # exact mode, reading momentarily unavailable: skip
        if used > self.peak_bytes:
            self.peak_bytes = used
            self.peak_op = rec.sym_name
            obsm.DEVICE_MEM_HIGH_WATER.set_max(used)

    def report(self) -> dict:
        return {"peak_bytes": self.peak_bytes, "peak_op": self.peak_op,
                "exact": bool(self.exact)}


# -- the transform ------------------------------------------------------------

# Plumbing prims that produce no device value worth observing.
_SKIP_IDS = {
    PrimIDs.DEL, PrimIDs.RETURN, PrimIDs.COMMENT,
    PrimIDs.UNPACK_TRIVIAL, PrimIDs.UNPACK_SEQUENCE, PrimIDs.UNPACK_KEY,
    PrimIDs.UNPACK_ATTR, PrimIDs.UNPACK_DIM,
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA, PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    PrimIDs.CHECK_STRING_VALUE, PrimIDs.CHECK_LEN, PrimIDs.CHECK_KEYS,
    PrimIDs.CHECK_NONE, PrimIDs.CHECK_DIM_BUCKET,
}


def instrument_for_execution(
    extrace: TraceCtx, hooks: Sequence[InstrumentationHook]
) -> TraceCtx:
    """Bracket every value-producing bound symbol of ``extrace`` with
    ``instrument_pre``/``instrument_post`` host prims that dispatch to
    ``hooks``. Returns a new trace (provenance: "Instrumentation")."""
    start = time.perf_counter_ns()
    hooks = tuple(hooks)
    records: dict[int, OpRecord] = {}
    provenance = extrace.pass_name()

    def pre_impl(idx: int) -> None:
        rec = records[idx]
        for h in hooks:
            h.on_op_start(rec)

    def post_impl(idx: int, *outs) -> None:
        rec = records[idx]
        for h in hooks:
            h.on_op_end(rec, outs)

    # SIDE_EFFECT keeps DCE/CSE and the verifier's dead-symbol rule from
    # touching the brackets; python_impl makes claiming pass them through.
    pre_sym = Symbol(
        "instrument_pre", meta=None, id="observability.instrument_pre",
        is_prim=True, python_impl=pre_impl, tags=(OpTags.SIDE_EFFECT, OpTags.DONT_DCE),
    )
    post_sym = Symbol(
        "instrument_post", meta=None, id="observability.instrument_post",
        is_prim=True, python_impl=post_impl, tags=(OpTags.SIDE_EFFECT, OpTags.DONT_DCE),
    )

    new_bsyms: list[BoundSymbol] = []
    for i, bsym in enumerate(extrace.bound_symbols):
        outs = bsym.flat_proxy_outs
        if bsym.sym.id in _SKIP_IDS or not outs:
            new_bsyms.append(bsym)
            continue
        ex = bsym.sym.executor
        records[i] = OpRecord(
            index=i,
            sym_name=bsym.sym.name,
            executor=ex.name if ex is not None else None,
            line=bsym.one_line(),
            provenance=provenance,
            trace_name=extrace.name,
        )
        new_bsyms.append(pre_sym.bind(i, output=None))
        new_bsyms.append(bsym)
        new_bsyms.append(post_sym.bind(i, *outs, output=None))

    ntrace = from_trace(extrace)
    ntrace.bound_symbols = new_bsyms
    return wrap_in_trace_provenance(ntrace, "Instrumentation", start)


def resolve_hooks(debug_watch: Optional[str], instrument: Any) -> tuple:
    """Normalize the ``jit(debug_watch=..., instrument=...)`` options into
    hook instances. ``instrument`` accepts a hook, a bare callable
    (post-op), the shorthands "time"/"memory", or a sequence of any."""
    hooks: list[InstrumentationHook] = []
    if debug_watch:
        hooks.append(NaNWatcher(mode=str(debug_watch)))
    items = instrument if isinstance(instrument, (list, tuple)) else (
        [instrument] if instrument is not None else []
    )
    for it in items:
        if isinstance(it, InstrumentationHook):
            hooks.append(it)
        elif it == "time":
            hooks.append(OpTimer())
        elif it == "memory":
            hooks.append(MemoryHighWater())
        elif callable(it):
            hooks.append(CallbackHook(it))
        else:
            raise ValueError(
                f"instrument: expected a hook, callable, 'time'/'memory', or a "
                f"sequence of those; got {it!r}"
            )
    return tuple(hooks)


def instrument_reports(jfn: Callable) -> list[dict]:
    """The hook reports of a compiled function's instrumentation (empty when
    not instrumented)."""
    cd = getattr(jfn, "_lc_cd", None)
    hooks = getattr(cd, "_instrument_hooks", ()) if cd is not None else ()
    return [
        {"hook": type(h).__name__, **h.report()} for h in hooks
    ]
