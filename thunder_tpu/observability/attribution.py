"""Device-time attribution: profiler trace-events → BoundSymbols.

The *measured* half of the performance-attribution observatory (the
*predicted* half is ``thunder_tpu/analysis/cost.py``). A profile captured
under ``thunder_tpu.profile()`` with ``THUNDER_TPU_ANNOTATE_TRACES=1``
carries the annotated-codegen scope ``L<idx>.<sym>#<pass>`` in every HLO
op's metadata; this module parses the xprof trace-events JSON the profiler
writes (``plugins/profile/<run>/<host>.trace.json.gz``), selects the
device-execution events, and aggregates measured device time back onto the
generated trace lines — closing the loop the PR 3 docstring left open
("parse per-HLO-op self times with xprof by hand").

Scope parsing accepts three spellings:

- ``L<idx>.<sym>#<pass>`` — current annotated codegen (core/trace.py);
- ``L<idx>.<sym>@<pass>`` — the PR 3 spelling, kept for old fixtures
  (JAX truncates ``@...`` before HLO metadata, so live profiles never
  contain it — but event logs and tests might);
- ``L<idx>.<sym>`` — the truncated form JAX produced for PR 3 profiles
  (provenance lost; attributed with ``pass_name=None``).

Backends whose trace events carry only raw HLO op names (the CPU plugin
emits ``{"args": {"hlo_op": "dot.3"}}`` with no scope path) are joined
through :func:`hlo_scope_map`, which recovers ``hlo_op → scope`` from the
compiled module's HLO text (``jax.jit(f).lower(...).compile().as_text()``).

Fused ops that cover several trace lines (one fusion whose metadata lists
multiple scopes) split their duration evenly across the matched scopes and
are additionally reported as fusion groups.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

# Scope with provenance: L<idx>.<sym>(#|@)<pass>. Symbol names may be dotted
# (executor ops like torch.sdpa_fwd_res).
_SCOPE_RE = re.compile(r"L(\d+)\.([A-Za-z_][\w.]*?)[#@]([\w]+)")
# Truncated legacy scope (JAX ate '@<pass>'): L<idx>.<sym> at a path-segment
# boundary.
_SCOPE_BARE_RE = re.compile(r"L(\d+)\.([A-Za-z_][\w.]*?)(?=/|$)")

# Event names that are device time but not attributable work.
_IDLE_NAMES = {"idle", "Idle", "IDLE"}

# HLO collective op families: what the SPMD partitioner (or shard_map
# lowering) names the wire ops in the compiled module. Instance names carry
# a ".N" suffix (all-gather.3); the class is the base family name.
_COLLECTIVE_HLO_RE = re.compile(
    r"\b(all-gather|all-reduce|reduce-scatter|collective-permute|all-to-all|"
    r"collective-broadcast|ragged-all-to-all)(-start|-done)?(\.\d+)?\b"
)

# Trace-level collective symbols (distributed/prims.py) → the HLO family
# their jax lowering produces. A scoped profiler row whose sym is one of
# these is a collective even when the event name itself is a fusion label.
COLLECTIVE_SYM_CLASS = {
    "all_gather": "all-gather",
    "all_reduce": "all-reduce",
    "reduce_scatter": "reduce-scatter",
    "broadcast": "all-reduce",  # lowered as masked psum (dist_prims)
    "all_to_all": "all-to-all",
    "ppermute": "collective-permute",
    "synchronize": "all-gather",  # fsdp gather; replicated sync is a no-op
}


def collective_class(name: str, hlo_op: str = "", refs: Sequence["ScopeRef"] = ()) -> Optional[str]:
    """The collective family of a profiler row ("all-gather", "all-reduce",
    ...), or None for compute rows. Classified by the trace-level symbol
    when the row carries a scope, else by the HLO op/event name."""
    for ref in refs:
        cls = COLLECTIVE_SYM_CLASS.get(ref.sym)
        if cls is not None:
            return cls
    m = _COLLECTIVE_HLO_RE.search(hlo_op) or _COLLECTIVE_HLO_RE.search(name)
    return m.group(1) if m else None


@dataclass(frozen=True)
class ScopeRef:
    """One parsed ``L<idx>.<sym>[#<pass>]`` scope."""

    line: int
    sym: str
    pass_name: Optional[str] = None

    @property
    def label(self) -> str:
        p = f"#{self.pass_name}" if self.pass_name else ""
        return f"L{self.line}.{self.sym}{p}"


def parse_scope(name: str) -> Optional[ScopeRef]:
    """First scope reference in ``name`` (a profiler event name or an HLO
    ``op_name`` path like ``jit_step/L3.linear#Transform_for_execution/dot``),
    or None."""
    refs = parse_scopes(name)
    return refs[0] if refs else None


def parse_scopes(name: str) -> list[ScopeRef]:
    """Every scope reference in ``name`` — a fused op's metadata may carry
    several. Provenance-bearing matches win over truncated ones covering the
    same span."""
    if not name:
        return []
    refs: list[ScopeRef] = []
    spans: list[tuple[int, int]] = []
    for m in _SCOPE_RE.finditer(name):
        refs.append(ScopeRef(int(m.group(1)), m.group(2), m.group(3)))
        spans.append(m.span())
    for m in _SCOPE_BARE_RE.finditer(name):
        if any(a <= m.start() < b for a, b in spans):
            continue
        refs.append(ScopeRef(int(m.group(1)), m.group(2), None))
    return refs


# =============================================================================
# Trace-events loading
# =============================================================================


def find_trace_files(path: str) -> list[str]:
    """The trace-events JSON file(s) under ``path`` — a profile dir from
    ``thunder_tpu.profile()`` (searched recursively for
    ``*.trace.json[.gz]``), or a single file."""
    if os.path.isfile(path):
        return [path]
    out: list[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        out.extend(glob.glob(os.path.join(path, pat), recursive=True))
    return sorted(out)


def load_trace_events(path: str) -> list[dict]:
    """Raw trace-event dicts from one Chrome-trace JSON file (gzipped or
    plain; top-level ``{"traceEvents": [...]}`` or a bare list)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return doc.get("traceEvents", [])
    return doc


# =============================================================================
# Attribution
# =============================================================================


@dataclass
class CollectiveRow:
    """Measured device time of one collective (one scoped trace line, or one
    HLO collective instance when the partitioner inserted it), with the
    portion of its wall interval hidden under concurrent compute on the same
    device vs. exposed on that device's critical path."""

    key: str  # scope label (L<i>.<sym>) or HLO instance name (all-gather.3)
    cls: str  # collective family: all-gather | all-reduce | ...
    us: float = 0.0  # total device time across calls
    hidden_us: float = 0.0  # overlapped with compute on another lane of the device
    count: int = 0

    @property
    def exposed_us(self) -> float:
        return max(0.0, self.us - self.hidden_us)

    @property
    def hidden_frac(self) -> float:
        return self.hidden_us / self.us if self.us else 0.0


@dataclass
class Attribution:
    """Measured device time aggregated per trace line / symbol / pass."""

    by_line: dict[ScopeRef, float] = field(default_factory=dict)  # scope -> us
    counts: dict[ScopeRef, int] = field(default_factory=dict)
    by_sym: dict[str, float] = field(default_factory=dict)
    by_pass: dict[str, float] = field(default_factory=dict)
    fusions: dict[str, tuple[float, tuple[ScopeRef, ...]]] = field(default_factory=dict)
    unattributed: dict[str, float] = field(default_factory=dict)  # op name -> us
    collectives: dict[str, CollectiveRow] = field(default_factory=dict)  # key -> row
    device_busy_us: float = 0.0  # non-idle device time
    idle_us: float = 0.0
    files: list[str] = field(default_factory=list)

    @property
    def attributed_us(self) -> float:
        return sum(self.by_line.values())

    @property
    def coverage(self) -> float:
        """Fraction of non-idle device time attributed to named trace lines."""
        return self.attributed_us / self.device_busy_us if self.device_busy_us else 0.0

    @property
    def with_provenance_us(self) -> float:
        return sum(us for ref, us in self.by_line.items() if ref.pass_name)

    @property
    def collective_us(self) -> float:
        """Total measured device time spent in collective rows."""
        return sum(r.us for r in self.collectives.values())

    @property
    def exposed_collective_us(self) -> float:
        return sum(r.exposed_us for r in self.collectives.values())

    def collective_summary(self) -> dict[str, CollectiveRow]:
        """Per-family rollup of the per-instance collective rows."""
        out: dict[str, CollectiveRow] = {}
        for row in self.collectives.values():
            agg = out.setdefault(row.cls, CollectiveRow(key=row.cls, cls=row.cls))
            agg.us += row.us
            agg.hidden_us += row.hidden_us
            agg.count += row.count
        return out

    def top(self, k: int = 10) -> list[tuple[ScopeRef, float]]:
        return sorted(self.by_line.items(), key=lambda kv: -kv[1])[:k]

    def format(self, top_k: int = 10) -> str:
        lines = [
            f"attribution: {self.device_busy_us / 1e3:.3f} ms device-busy over "
            f"{len(self.files)} trace file(s), {self.coverage * 100:.1f}% attributed "
            f"to {len(self.by_line)} trace lines"
            + (f", {self.idle_us / 1e3:.3f} ms idle" if self.idle_us else ""),
            f"  {'line':<34} {'calls':>6} {'us':>10} {'share':>7}",
        ]
        for ref, us in self.top(top_k):
            share = us / self.device_busy_us * 100 if self.device_busy_us else 0.0
            lines.append(
                f"  {ref.label:<34.34} {self.counts.get(ref, 0):>6} {us:>10.1f} {share:>6.1f}%"
            )
        if self.unattributed:
            worst = sorted(self.unattributed.items(), key=lambda kv: -kv[1])[:3]
            lines.append("  unattributed: " + ", ".join(f"{n}={us:.0f}us" for n, us in worst))
        if self.fusions:
            lines.append(f"  fusion groups spanning several lines: {len(self.fusions)}")
        if self.collectives:
            lines.append(
                f"  collectives: {self.collective_us:.1f}us on the wire, "
                f"{self.exposed_collective_us:.1f}us exposed ("
                + ", ".join(
                    f"{cls}={r.us:.0f}us/{r.hidden_frac * 100:.0f}%hidden"
                    for cls, r in sorted(self.collective_summary().items())
                )
                + ")"
            )
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.format()


def _is_device_pid(process_names: dict, pid: Any) -> bool:
    name = str(process_names.get(pid, ""))
    return "/device:" in name or name.startswith("/tpu") or "TPU" in name


def _self_times(device_ops: list[dict]) -> dict[int, float]:
    """Self time (dur minus nested children) per event, keyed by ``id(ev)``.

    Trace events nest: the CPU plugin emits an XLA ``call`` wrapper whose
    interval contains the ops it calls, and TPU timelines bracket kernels
    inside scope rows. Charging raw durations would double-count every
    nested microsecond, so each event is charged only the time not covered
    by a child on the same (pid, tid)."""
    out: dict[int, float] = {}
    by_tid: dict[tuple, list[dict]] = {}
    for ev in device_ops:
        by_tid.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)
    for evs in by_tid.values():
        # Parents sort before their children: earlier start first, longer
        # duration first on ties.
        evs.sort(key=lambda e: (float(e.get("ts", 0.0)), -float(e.get("dur", 0.0))))
        stack: list[tuple[float, int]] = []  # (end_ts, id) of open intervals
        for ev in evs:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
            eps = 1e-6  # float slack on interval ends
            while stack and stack[-1][0] <= ts + eps:
                stack.pop()
            out[id(ev)] = dur
            if stack:
                out[stack[-1][1]] -= dur  # direct parent loses this child's span
            stack.append((ts + dur, id(ev)))
    return out


def _lane_segments(evs: list[dict]) -> list[tuple[float, float, dict]]:
    """Leaf-level ``(start, end, event)`` segments of one serial timeline
    (one ``(pid, tid)`` lane): at any instant the deepest open event owns the
    moment, so a ``call`` wrapper's interval is split around its children
    instead of double-covering them — the interval analogue of
    :func:`_self_times`."""
    segs: list[tuple[float, float, dict]] = []
    stack: list[list] = []  # [end_ts, event, cursor]
    eps = 1e-6

    def close(upto: float) -> None:
        while stack and stack[-1][0] <= upto + eps:
            end, ev, cur = stack.pop()
            if end > cur:
                segs.append((cur, end, ev))

    for ev in sorted(evs, key=lambda e: (float(e.get("ts", 0.0)), -float(e.get("dur", 0.0)))):
        ts = float(ev.get("ts", 0.0))
        dur = float(ev.get("dur", 0.0))
        close(ts)
        if stack:
            parent = stack[-1]
            if ts > parent[2]:
                segs.append((parent[2], ts, parent[1]))
            parent[2] = ts + dur  # parent resumes after this child
        stack.append([ts + dur, ev, ts])
    close(float("inf"))
    return segs


def _merge_intervals(iv: list[tuple[float, float]]) -> list[tuple[float, float]]:
    if not iv:
        return []
    iv = sorted(iv)
    out = [list(iv[0])]
    for a, b in iv[1:]:
        if a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _overlap_us(start: float, end: float, merged: list[tuple[float, float]]) -> float:
    """Length of ``[start, end]`` covered by the merged interval union."""
    total = 0.0
    for a, b in merged:
        if b <= start:
            continue
        if a >= end:
            break
        total += min(b, end) - max(a, start)
    return total


def _collect_overlap(
    attr: Attribution,
    device_ops: list[dict],
    process_names: dict,
    op_refs: dict[int, list[ScopeRef]],
) -> None:
    """Per-collective hidden/exposed split for one trace file's device ops.

    Lanes (``(pid, tid)`` timelines) are grouped into devices: a pid whose
    process name is a device (``/device:TPU:N``) owns all its lanes (the
    TensorCore/DMA/stream lines xprof draws per core); host pids (the CPU
    plugin puts every emulated device's thread under one pid) count each
    lane as its own device. A collective's hidden time is the part of its
    wall interval covered by *compute* leaf segments on another lane of the
    same device — compute on a different device concurrently is parallelism,
    not overlap, and a lane is serial so same-lane overlap cannot exist.
    On backends with no async collective lanes (CPU) hidden is therefore
    structurally 0: every collective microsecond is exposed, which is the
    correct before-picture for overlap work."""
    by_lane: dict[tuple, list[dict]] = {}
    for ev in device_ops:
        by_lane.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)

    def device_of(lane: tuple) -> tuple:
        pid = lane[0]
        return (pid,) if _is_device_pid(process_names, pid) else lane

    compute_by_device: dict[tuple, list[tuple[float, float, tuple]]] = {}
    collective_evs: list[tuple[dict, str, tuple]] = []  # (ev, cls, lane)
    for lane, evs in by_lane.items():
        dev = device_of(lane)
        for start, end, ev in _lane_segments(evs):
            name = str(ev.get("name", ""))
            args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
            hlo_op = str(args.get("hlo_op", "")) if args else ""
            if name in _IDLE_NAMES or hlo_op in _IDLE_NAMES:
                continue
            if collective_class(name, hlo_op, op_refs.get(id(ev), ())) is None:
                compute_by_device.setdefault(dev, []).append((start, end, lane))
        for ev in evs:
            name = str(ev.get("name", ""))
            args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
            hlo_op = str(args.get("hlo_op", "")) if args else ""
            cls = collective_class(name, hlo_op, op_refs.get(id(ev), ()))
            if cls is not None and float(ev.get("dur", 0.0)) > 0.0:
                collective_evs.append((ev, cls, lane))

    # The merged other-lane compute union depends only on (device, lane):
    # build it once per lane, not once per collective event (a multi-step
    # trace has thousands of collective instances over a handful of lanes).
    merged_cache: dict[tuple, list[tuple[float, float]]] = {}

    def other_lane_compute(dev: tuple, lane: tuple) -> list[tuple[float, float]]:
        key = (dev, lane)
        got = merged_cache.get(key)
        if got is None:
            got = merged_cache[key] = _merge_intervals([
                (s, e) for s, e, seg_lane in compute_by_device.get(dev, ())
                if seg_lane != lane
            ])
        return got

    for ev, cls, lane in collective_evs:
        dev = device_of(lane)
        other = other_lane_compute(dev, lane)
        ts = float(ev.get("ts", 0.0))
        dur = float(ev.get("dur", 0.0))
        hidden = _overlap_us(ts, ts + dur, other)
        refs = op_refs.get(id(ev), ())
        args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
        key = refs[0].label if refs else (
            str(args.get("hlo_op")) if args and args.get("hlo_op") else str(ev.get("name", cls))
        )
        row = attr.collectives.setdefault(key, CollectiveRow(key=key, cls=cls))
        row.us += dur
        row.hidden_us += min(hidden, dur)
        row.count += 1


def _is_device_op(ev: dict, process_names: dict, thread_names: dict) -> bool:
    """Does this complete-event represent device execution of an HLO op?

    TPU xprof: op events live on pids named ``/device:TPU:N``. CPU plugin:
    there is no device pid — XLA execution runs on ``tf_XLAEigen`` threads
    and each op event carries ``args.hlo_op``/``hlo_module``."""
    if ev.get("ph") != "X" or not ev.get("dur"):
        return False
    args = ev.get("args")
    if isinstance(args, dict) and ("hlo_op" in args or "hlo_module" in args):
        return True
    if _is_device_pid(process_names, ev.get("pid")):
        # Step markers and scope brackets on device timelines have no args
        # and huge durations; HLO op rows always name an op. Keep everything
        # with a name that is not a step annotation.
        return bool(ev.get("name"))
    return False


def attribute(
    source: str,
    *,
    hlo_text: Optional[str] = None,
    extra_scope_map: Optional[dict[str, str]] = None,
) -> Attribution:
    """Aggregate measured device time per trace line from the profile at
    ``source`` (a ``thunder_tpu.profile()`` trace dir, or one trace-events
    JSON file).

    ``hlo_text``: optional compiled-HLO text (``lowered.compile().as_text()``)
    used to map raw HLO op names to scopes when the backend's trace events
    don't carry the scope path themselves (the CPU plugin).
    ``extra_scope_map``: pre-built ``hlo_op → scope-string`` entries merged
    over the ``hlo_text`` map."""
    files = find_trace_files(source)
    if not files:
        raise FileNotFoundError(f"no *.trace.json[.gz] under {source!r}")
    scope_map: dict[str, str] = {}
    if hlo_text:
        scope_map.update(hlo_scope_map(hlo_text))
    if extra_scope_map:
        scope_map.update(extra_scope_map)

    attr = Attribution(files=files)
    for path in files:
        events = load_trace_events(path)
        process_names: dict[Any, str] = {}
        thread_names: dict[tuple, str] = {}
        for ev in events:
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    process_names[ev.get("pid")] = ev.get("args", {}).get("name", "")
                elif ev.get("name") == "thread_name":
                    thread_names[(ev.get("pid"), ev.get("tid"))] = ev.get("args", {}).get("name", "")
        device_ops = [ev for ev in events if _is_device_op(ev, process_names, thread_names)]
        self_us = _self_times(device_ops)
        # Scope source, in order: the event name (TPU op rows carry the
        # full metadata path), then each arg value on its own (xprof
        # puts fused long names in args; parsing per-string keeps the
        # bare-scope regex's end-of-string anchor working for truncated
        # legacy names), then the HLO-text join on hlo_op/name. Resolved
        # once per event: the overlap pass classifies collectives by the
        # same refs the time attribution charges.
        op_refs: dict[int, list[ScopeRef]] = {}
        for ev in device_ops:
            name = str(ev.get("name", ""))
            args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
            hlo_op = str(args.get("hlo_op", "")) if args else ""
            refs = parse_scopes(name)
            if not refs and args:
                for v in args.values():
                    refs.extend(parse_scopes(str(v)))
            if not refs and scope_map:
                mapped = scope_map.get(hlo_op) or scope_map.get(name)
                if mapped:
                    refs = parse_scopes(mapped)
            op_refs[id(ev)] = refs
        for ev in device_ops:
            name = str(ev.get("name", ""))
            dur = self_us[id(ev)]
            if dur <= 0.0:
                continue
            args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
            hlo_op = str(args.get("hlo_op", "")) if args else ""
            if name in _IDLE_NAMES or hlo_op in _IDLE_NAMES:
                attr.idle_us += dur
                continue
            attr.device_busy_us += dur
            refs = op_refs[id(ev)]
            if not refs:
                key = hlo_op or name
                attr.unattributed[key] = attr.unattributed.get(key, 0.0) + dur
                continue
            share = dur / len(refs)
            for ref in refs:
                attr.by_line[ref] = attr.by_line.get(ref, 0.0) + share
                attr.counts[ref] = attr.counts.get(ref, 0) + 1
                attr.by_sym[ref.sym] = attr.by_sym.get(ref.sym, 0.0) + share
                if ref.pass_name:
                    attr.by_pass[ref.pass_name] = attr.by_pass.get(ref.pass_name, 0.0) + share
            if len(refs) > 1:
                prev = attr.fusions.get(name, (0.0, tuple(refs)))
                attr.fusions[name] = (prev[0] + dur, tuple(refs))
        # Compute–comm overlap: per collective, how much of its wall
        # interval was hidden under compute on another lane of its device.
        _collect_overlap(attr, device_ops, process_names, op_refs)
    return attr


def hlo_scope_map(hlo_text: str) -> dict[str, str]:
    """``hlo_op name → metadata op_name`` from compiled HLO text — the join
    table for backends whose trace events carry raw HLO op names instead of
    scope paths. Only entries whose op_name contains a scope are kept.

    The lexing lives in ``analysis/hlo_audit.iter_op_metadata`` — the HLO
    auditor's shared tokenizer, so this reader and the static auditor parse
    the same grammar and cannot drift (one tokenizer, two consumers)."""
    from thunder_tpu.analysis.hlo_audit import iter_op_metadata

    out: dict[str, str] = {}
    for op, op_name in iter_op_metadata(hlo_text):
        if parse_scope(op_name) is not None:
            out[op] = op_name
    return out


def scope_map_of(jfn: Any, *args, **kwargs) -> dict[str, str]:
    """Convenience: the :func:`hlo_scope_map` of an already-jitted callable
    (``jax.jit`` object or ``Compiled``), lowering on ``args`` if needed."""
    text = None
    if hasattr(jfn, "as_text"):
        text = jfn.as_text()
    elif hasattr(jfn, "lower"):
        text = jfn.lower(*args, **kwargs).compile().as_text()
    return hlo_scope_map(text) if text else {}


# =============================================================================
# Roofline/MFU join (predicted × measured)
# =============================================================================


@dataclass
class JoinedRow:
    """One trace line with both its measured device time and its static
    roofline bound."""

    label: str
    sym: str
    line: int
    pass_name: Optional[str]
    measured_us: float  # per profiled step
    share: float  # of device-busy time
    roofline_us: Optional[float] = None
    efficiency: Optional[float] = None  # roofline/measured, 1.0 = at the roof
    bound: Optional[str] = None  # compute|memory|comm|free
    flops: Optional[float] = None
    bytes_moved: Optional[float] = None


@dataclass
class CollectiveJoin:
    """One collective family (or scoped collective line) joined across the
    predicted and measured halves: ring-factor wire-time bound from the cost
    model vs. measured device time split into hidden (overlapped with
    compute) and exposed (on the device critical path)."""

    key: str
    cls: str
    count: int
    us: float  # measured, per step
    hidden_us: float
    exposed_us: float
    predicted_wire_us: Optional[float] = None  # cost-model bound, per step


@dataclass
class PerfJoin:
    """The joined report: top-k measured ops annotated with predicted
    cost, roofline ratio, and boundedness; plus trace-level rollups."""

    rows: list[JoinedRow]
    attribution: Attribution
    cost: Optional[Any] = None  # TraceCost
    steps: int = 1
    measured_step_us: float = 0.0
    mfu: Optional[float] = None
    padding_waste_elements: Optional[float] = None
    collectives: list[CollectiveJoin] = field(default_factory=list)

    def format(self, top_k: int = 10) -> str:
        a = self.attribution
        lines = [
            f"perf attribution: {self.measured_step_us / 1e3:.3f} ms device-busy/step "
            f"({self.steps} step(s) profiled), {a.coverage * 100:.1f}% attributed",
        ]
        if self.cost is not None:
            c = self.cost
            lines.append(
                f"  cost model [{c.device.name}]: {c.total_flops / 1e9:.2f} GFLOP/step, "
                f"roofline bound {c.roofline_s * 1e3:.3f} ms"
                + (f", MFU at measured time {self.mfu * 100:.1f}%" if self.mfu is not None else "")
            )
        if self.padding_waste_elements:
            lines.append(
                f"  bucket padding waste: {self.padding_waste_elements:.3g} elements "
                "dispatched beyond true extents (thunder_tpu_padding_waste_elements_total)"
            )
        lines.append(
            f"  {'line':<34} {'us/step':>9} {'share':>7} {'roofline':>9} {'eff':>6} {'bound':>8}"
        )
        for r in self.rows[:top_k]:
            roof = f"{r.roofline_us:.1f}" if r.roofline_us is not None else "-"
            eff = f"{r.efficiency * 100:.0f}%" if r.efficiency is not None else "-"
            lines.append(
                f"  {r.label:<34.34} {r.measured_us:>9.1f} {r.share * 100:>6.1f}% "
                f"{roof:>9} {eff:>6} {r.bound or '-':>8}"
            )
        if a.unattributed:
            worst = sorted(a.unattributed.items(), key=lambda kv: -kv[1])[:3]
            lines.append("  unattributed: " + ", ".join(
                f"{n}={us / self.steps:.0f}us" for n, us in worst))
        if self.collectives:
            lines.append("  compute-comm overlap (per collective, us/step):")
            lines.append(
                f"  {'collective':<28} {'n':>4} {'measured':>9} {'hidden':>8} "
                f"{'exposed':>8} {'predicted':>10}"
            )
            for c in self.collectives:
                pred = f"{c.predicted_wire_us:.1f}" if c.predicted_wire_us is not None else "-"
                lines.append(
                    f"  {c.key:<28.28} {c.count:>4} {c.us:>9.1f} {c.hidden_us:>8.1f} "
                    f"{c.exposed_us:>8.1f} {pred:>10}"
                )
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.format()


def _join_collectives(attr: Attribution, cost: Optional[Any], steps: int) -> list[CollectiveJoin]:
    """Measured collective rows (scaled to per-step) joined with the cost
    model's ring-factor wire-time bounds.

    Scoped rows (trace-level dist_prims collectives, ``L<i>.<sym>``) join
    their cost row by (line, sym); partitioner-inserted collectives carry no
    scope, so those join at the family level — measured family totals against
    the summed predicted wire time of the trace's collectives in that family
    (``COLLECTIVE_SYM_CLASS`` maps sym → HLO family)."""
    if not attr.collectives:
        return []
    cost_by_line: dict[tuple[int, str], float] = {}
    cost_by_cls: dict[str, float] = {}
    if cost is not None and getattr(cost.device, "ici_bw", 0.0):
        for r in cost.rows:
            if r.kind != "collective" or not r.comm_bytes:
                continue
            # Per-family effective bandwidth when the spec was calibrated
            # (analysis/cost.calibrate_ici); datasheet ici_bw otherwise.
            ici_bw = cost.device.ici_bw_for(COLLECTIVE_SYM_CLASS.get(r.sym))
            wire_us = r.comm_bytes / ici_bw * 1e6
            cost_by_line[(r.index, r.sym)] = cost_by_line.get((r.index, r.sym), 0.0) + wire_us
            cls = COLLECTIVE_SYM_CLASS.get(r.sym)
            if cls is not None:
                cost_by_cls[cls] = cost_by_cls.get(cls, 0.0) + wire_us

    out: list[CollectiveJoin] = []
    scoped = {k: v for k, v in attr.collectives.items() if parse_scope(k) is not None}
    unscoped = {k: v for k, v in attr.collectives.items() if k not in scoped}
    for key, row in sorted(scoped.items(), key=lambda kv: -kv[1].us):
        ref = parse_scope(key)
        out.append(CollectiveJoin(
            key=key, cls=row.cls, count=row.count, us=row.us / steps,
            hidden_us=row.hidden_us / steps, exposed_us=row.exposed_us / steps,
            predicted_wire_us=cost_by_line.get((ref.line, ref.sym)),
        ))
    # Family rollup of the unscoped (partitioner-inserted) instances.
    by_cls: dict[str, CollectiveRow] = {}
    for row in unscoped.values():
        agg = by_cls.setdefault(row.cls, CollectiveRow(key=row.cls, cls=row.cls))
        agg.us += row.us
        agg.hidden_us += row.hidden_us
        agg.count += row.count
    for cls, row in sorted(by_cls.items(), key=lambda kv: -kv[1].us):
        out.append(CollectiveJoin(
            key=cls, cls=cls, count=row.count, us=row.us / steps,
            hidden_us=row.hidden_us / steps, exposed_us=row.exposed_us / steps,
            predicted_wire_us=cost_by_cls.get(cls) if not scoped else None,
        ))
    return out


def join_cost_attribution(
    attr: Attribution,
    cost: Optional[Any] = None,
    *,
    steps: int = 1,
) -> PerfJoin:
    """Join measured per-line device time with the static cost model.

    Lines match on (index, symbol) against ``cost`` rows (both derive from
    the same execution trace when ``cost`` came from
    ``trace_cost(compile_stats(jfn).last_traces[-1])``); a line that moved
    between passes falls back to a symbol-name match. ``steps`` divides
    measured totals down to per-step numbers comparable with the per-call
    roofline bounds."""
    steps = max(1, steps)
    cost_by_line: dict[tuple[int, str], Any] = {}
    cost_by_sym: dict[str, list] = {}
    if cost is not None:
        for r in cost.rows:
            cost_by_line[(r.index, r.sym)] = r
            cost_by_sym.setdefault(r.sym, []).append(r)

    rows: list[JoinedRow] = []
    for ref, us in sorted(attr.by_line.items(), key=lambda kv: -kv[1]):
        measured = us / steps
        row = JoinedRow(
            label=ref.label, sym=ref.sym, line=ref.line, pass_name=ref.pass_name,
            measured_us=measured,
            share=us / attr.device_busy_us if attr.device_busy_us else 0.0,
        )
        crow = cost_by_line.get((ref.line, ref.sym))
        if crow is None and len(cost_by_sym.get(ref.sym, [])) == 1:
            crow = cost_by_sym[ref.sym][0]
        if crow is not None:
            row.roofline_us = crow.roofline_s * 1e6
            row.bound = crow.bound
            row.flops = crow.flops
            row.bytes_moved = crow.bytes_moved
            if measured > 0:
                row.efficiency = min(1.0, row.roofline_us / measured)
        rows.append(row)

    join = PerfJoin(
        rows=rows, attribution=attr, cost=cost, steps=steps,
        measured_step_us=attr.device_busy_us / steps,
    )
    join.collectives = _join_collectives(attr, cost, steps)
    if cost is not None and attr.device_busy_us:
        join.mfu = cost.mfu_at(attr.device_busy_us / steps / 1e6)
    try:
        from thunder_tpu.observability import metrics as obsm

        if obsm.enabled():
            waste = obsm.PADDING_WASTE_ELEMENTS.value()
            if waste:
                join.padding_waste_elements = float(waste)
    except Exception:
        pass
    return join
